"""Production soak mode: the schedule streamer's purity/carry-over
contract, the composed-shape soak with an injected mid-soak kill
(journal byte-identity + state-digest equality on resume), and the
drift invariants (flat compile cache, bounded RSS, zero violations)
including the trip wire on a deliberately-recompiling build.

The streamer pins mirror tests/test_chaos_fuzz.py's generate_scenario
pins: SOAK_SEED_STABILITY_PIN is the historical (seed, segment,
severity) → draw-order op-kind record — future tiers may APPEND draws
after the existing ones, never reshuffle them.  If this table breaks,
the fix is a new trailing rung, not a regenerated table.
"""

import dataclasses
import itertools
import json
import os

import pytest

from scalecube_cluster_tpu.chaos import scenarios as cs
from scalecube_cluster_tpu.resilience import harness as rharness
from scalecube_cluster_tpu.resilience import supervisor as rsup
from scalecube_cluster_tpu.soak import drift as sdrift
from scalecube_cluster_tpu.soak import driver as sdriver
from scalecube_cluster_tpu.soak import schedule as ss

pytestmark = pytest.mark.soak


# --------------------------------------------------------------------------
# The streamer: purity, boundary carry-over, node discipline
# --------------------------------------------------------------------------


def test_soak_segment_pure():
    for idx in (0, 2, 7):
        a = ss.soak_segment(7, idx, n=32, severity="moderate")
        b = ss.soak_segment(7, idx, n=32, severity="moderate")
        assert a == b   # frozen dataclass equality: kinds AND op fields


def test_soak_segment_computable_out_of_order():
    # Segment 5 without materializing 0..4 — the stream is pure in the
    # segment index, not an iterator.
    direct = ss.soak_segment(3, 5, n=32, severity="severe")
    after = [ss.soak_segment(3, i, n=32, severity="severe")
             for i in range(6)][5]
    assert direct == after


@pytest.mark.parametrize("severity", cs.SEVERITIES)
def test_every_segment_straddles_its_boundary(severity):
    for seed in (0, 7, 11):
        for idx in range(5):
            seg = ss.soak_segment(seed, idx, n=32, severity=severity)
            assert seg.spans_boundary, (seed, idx, severity)
            assert seg.kinds[0].startswith("edge_")
            # Recompute from the op itself — the straddler's window
            # really contains the segment's trailing edge.
            assert ss._spans(seg.ops[0], seg.round_end)


def test_node_schedule_ops_never_reuse_a_node():
    # One down window per node in the compiled world (with_crash
    # overwrites): across the whole stream every node-schedule op must
    # use fresh nodes.
    seen = set()
    for idx in range(8):
        seg = ss.soak_segment(7, idx, n=32, severity="severe")
        for op in seg.ops:
            if isinstance(op, cs.Crash):
                nodes = [op.node]
            elif isinstance(op, (cs.CrashBurst, cs.ChurnStorm)):
                nodes = list(op.nodes)
            else:
                continue
            for node in nodes:
                assert node not in seen, (idx, node)
                seen.add(node)


def test_quorum_reserve_never_faulted():
    pool = set(ss._fault_pool(7, 32, "severe"))
    assert len(pool) == 32 - 32 // 4
    for idx in range(12):
        seg = ss.soak_segment(7, idx, n=32, severity="severe")
        for op in seg.ops:
            if isinstance(op, cs.Crash):
                assert op.node in pool
            elif isinstance(op, (cs.CrashBurst, cs.ChurnStorm)):
                assert set(op.nodes) <= pool


def test_stream_degrades_to_link_weather_past_quota():
    # Segment 3 of a severe n=32 stream sits past the node quota
    # (3 * 8 = 24 = the whole faultable pool): only link-level ops.
    seg = ss.soak_segment(0, 3, n=32, severity="severe")
    for op in seg.ops:
        assert isinstance(op, (cs.LinkLoss, cs.FlappingLink,
                               cs.Brownout)), op


def test_soak_segment_validation():
    with pytest.raises(ValueError, match="severity"):
        ss.soak_segment(7, 0, n=32, severity="apocalyptic")
    with pytest.raises(ValueError, match="n >= 16"):
        ss.soak_segment(7, 0, n=8)
    with pytest.raises(ValueError, match="segment_index"):
        ss.soak_segment(7, -1, n=32)
    with pytest.raises(ValueError, match="multiple"):
        ss.soak_segment(7, 0, n=32, segment_rounds=100)
    with pytest.raises(ValueError, match="multiple"):
        ss.soak_segment(7, 0, n=32,
                        segment_rounds=ss.MIN_SEGMENT_ROUNDS // 2)


def test_soak_schedule_concatenates_the_stream():
    scen = ss.soak_schedule(7, 3, n=32, severity="moderate",
                            segment_rounds=128)
    segs = [ss.soak_segment(7, i, n=32, severity="moderate",
                            segment_rounds=128) for i in range(3)]
    assert scen.horizon == 3 * 128
    assert scen.n_members == 32
    assert scen.name == "soak-moderate-7-x3"
    assert scen.ops == tuple(op for s in segs for op in s.ops)
    assert scen.loss_probability == ss._STREAM_LOSS["moderate"]
    with pytest.raises(ValueError, match="n_segments"):
        ss.soak_schedule(7, 0)


# --------------------------------------------------------------------------
# Seed-stability pins (the trailing-draw contract, streamed)
# --------------------------------------------------------------------------

# (seed, segment_index, severity) -> "+".join(draw-order op kinds) at
# n=32, segment_rounds=256.  HISTORICAL RECORD — append new rungs after
# the existing draws; never edit an entry to make a refactor pass.
SOAK_SEED_STABILITY_PIN = {
    (0, 0, "mild"): "edge_flap+crash_revive",
    (0, 1, "mild"): "edge_loss+flap",
    (0, 3, "mild"): "edge_flap+crash_revive+config_push",
    (0, 0, "moderate"): "edge_loss+flap+loss_window",
    (0, 1, "moderate"): "edge_crash+loss_window+burst",
    (0, 3, "moderate"): "edge_crash+crash_revive+flap",
    (0, 0, "severe"): "edge_flap+flap+brownout+crash_revive+config_push",
    (0, 1, "severe"):
        "edge_loss+flap+brownout+crash_revive+join_storm+config_push",
    (0, 3, "severe"): "edge_loss+flap+loss_window+loss_window",
    (7, 0, "mild"): "edge_crash+flap",
    (7, 1, "mild"): "edge_flap+loss_window+config_push",
    (7, 3, "mild"): "edge_crash+crash_revive",
    (7, 0, "moderate"): "edge_loss+crash_revive+brownout+join_storm",
    (7, 1, "moderate"): "edge_loss+crash_revive+loss_window",
    (7, 3, "moderate"):
        "edge_loss+flap+brownout+join_storm+config_push",
    (7, 0, "severe"): "edge_crash+burst+crash_revive+brownout+config_push",
    (7, 1, "severe"): "edge_flap+churn+burst+crash_revive+config_push",
    (7, 3, "severe"): "edge_flap+loss_window+brownout+loss_window",
    (11, 0, "moderate"): "edge_crash+loss_window+burst",
    (11, 1, "moderate"): "edge_flap+crash_revive+brownout",
    (11, 3, "moderate"):
        "edge_loss+brownout+crash_revive+join_storm+config_push",
    (1234, 0, "severe"): "edge_loss+brownout+churn+burst",
    (1234, 1, "severe"):
        "edge_flap+crash_revive+brownout+flap+join_storm+config_push",
    (1234, 3, "severe"): "edge_flap+loss_window+loss_window+flap",
}


def test_soak_seed_stability_pin():
    for (seed, idx, severity), expect in \
            sorted(SOAK_SEED_STABILITY_PIN.items()):
        seg = ss.soak_segment(seed, idx, n=32, severity=severity)
        got = "+".join(seg.kinds)
        assert got == expect, (
            f"soak stream draw for (seed={seed}, segment={idx}, "
            f"{severity}) changed: {got!r} != {expect!r} — historical "
            f"streams must replay bit-identically; append new rungs "
            f"after the existing draws instead")


def test_soak_exact_op_pin():
    # One fully-field-pinned segment (the generate_scenario exact-op
    # pin, streamed): every field of every op, global round numbers.
    seg = ss.soak_segment(7, 1, n=32, severity="moderate")
    assert seg.round_start == 256 and seg.round_end == 512
    assert seg.spans_boundary
    assert seg.ops == (
        cs.LinkLoss(src=22, dst=15, loss=0.4, from_round=504,
                    until_round=520),
        cs.Crash(node=16, at_round=309, until_round=405),
        cs.LinkLoss(src=11, dst=1, loss=0.5, from_round=279,
                    until_round=304),
    )


def test_soak_exact_config_push_pin():
    # The trailing config rung, fully field-pinned: the owner comes
    # from the quorum-reserve ring (segment_index % ring length), the
    # value/round from the trailing draws — all global-round, all
    # replayable.
    seg = ss.soak_segment(7, 1, n=32, severity="mild")
    assert seg.kinds[-1] == "config_push"
    assert seg.ops[-1] == cs.ConfigPush(node=19, key=0, value=905,
                                        at_round=348)
    ring = ss._config_owner_ring(7, 32, "mild")
    assert ring[1 % len(ring)] == 19


def test_config_push_owners_roll_through_the_reserve():
    # Push owners are quorum-reserve members (never node-faulted) and
    # rotate with the segment index — the "rolling" in rolling config
    # pushes.
    pool = set(ss._fault_pool(7, 32, "severe"))
    ring = ss._config_owner_ring(7, 32, "severe")
    assert set(ring).isdisjoint(pool)
    assert set(ring) | pool == set(range(32))
    owners = []
    for idx in range(12):
        seg = ss.soak_segment(7, idx, n=32, severity="severe")
        for op in seg.ops:
            if isinstance(op, cs.ConfigPush):
                assert op.node == ring[idx % len(ring)]
                assert seg.round_start <= op.at_round < seg.round_end
                owners.append(op.node)
    assert len(set(owners)) > 1   # the ring actually rolls


# --------------------------------------------------------------------------
# Drift verdict (pure)
# --------------------------------------------------------------------------


def _samples(sizes, rss=None):
    rss = rss or [100_000] * len(sizes)
    return [{"round_end": (i + 1) * 128, "cache_size": s, "rss_kb": r}
            for i, (s, r) in enumerate(zip(sizes, rss))]


def test_drift_verdict_green():
    v = sdrift.drift_verdict(
        _samples([1, 1, 1]), 512.0,
        {"green": True, "total_violations": 0})
    assert v["ok"] and v["compile_flat"] and v["rss_bounded"]
    assert v["violations"] == 0 and v["monitor_green"]
    assert v["cache_sizes"] == [1, 1, 1]


def test_drift_verdict_trips_on_recompile():
    # The deliberately-recompiling build: cache grows mid-soak.
    v = sdrift.drift_verdict(
        _samples([1, 2, 3]), 512.0,
        {"green": True, "total_violations": 0})
    assert not v["compile_flat"] and not v["ok"]


def test_drift_verdict_trips_on_rss_growth():
    v = sdrift.drift_verdict(
        _samples([1, 1], rss=[100_000, 100_000 + 600 * 1024]), 512.0,
        {"green": True, "total_violations": 0})
    assert v["compile_flat"] and not v["rss_bounded"] and not v["ok"]


def test_drift_verdict_trips_on_violations_and_empty():
    v = sdrift.drift_verdict(
        _samples([1, 1]), 512.0, {"green": False,
                                  "total_violations": 3})
    assert v["violations"] == 3 and not v["ok"]
    # No monitor verdict at all (resumed-with-nothing-to-do) is NOT
    # silently green.
    assert sdrift.drift_verdict(_samples([1]), 512.0,
                                None)["violations"] == -1
    # A probe that can't see the cache (-1) must not count as flat.
    assert not sdrift.drift_verdict(
        _samples([-1, -1]), 512.0,
        {"green": True, "total_violations": 0})["compile_flat"]


def test_run_soak_trips_on_recompiling_probe(tmp_path, monkeypatch):
    # The wiring half of the trip test: run_soak samples through the
    # soak.drift module hook, so a growing cache size (a
    # deliberately-recompiling build) must flip drift.ok without any
    # real recompile happening.  The supervisor itself is stubbed —
    # the composed-shape integration runs in the soak fixture below.
    counter = itertools.count(1)
    monkeypatch.setattr(sdrift, "cache_size_probe",
                        lambda: next(counter))

    cfg = sdriver.SoakConfig(base_path=str(tmp_path / "soak.ckpt"),
                             n_members=16, severity="mild",
                             segment_rounds=128, n_segments=2)

    @dataclasses.dataclass
    class FakeResult:
        journal_path: str
        monitor_verdict: dict
        segments_run: int = 2
        segments_deduped: int = 0
        resumed_from: object = None

    def fake_run_resilient(shape, key, params, world, n_rounds, *,
                           on_segment=None, journal_path=None,
                           **kwargs):
        assert shape == rsup.RunShape.COMPOSED
        for end in (128, 256):
            on_segment({"round_end": end})
        with open(journal_path, "w"):
            pass
        return FakeResult(journal_path=journal_path,
                          monitor_verdict={"green": True,
                                           "total_violations": 0})

    monkeypatch.setattr(rsup, "run_resilient", fake_run_resilient)
    soak = sdriver.run_soak(cfg)
    assert soak.drift["cache_sizes"] == [1, 2]
    assert not soak.drift["compile_flat"]
    assert not soak.drift["ok"]


# --------------------------------------------------------------------------
# The soak itself: composed shape, injected kill, byte-identity
# --------------------------------------------------------------------------

GEOM = dict(n_members=16, severity="mild", segment_rounds=128,
            n_segments=2, seed=7)


@pytest.fixture(scope="module")
def soak_pair(tmp_path_factory):
    """One uninterrupted reference soak + one killed-and-resumed soak
    of the SAME config in its own lineage (in-process, mode='raise'),
    shared by the identity/drift tests below — a soak lifetime is too
    expensive to rerun per assertion."""
    root = tmp_path_factory.mktemp("soak")
    ref_cfg = sdriver.SoakConfig(
        base_path=str(root / "ref" / "soak.ckpt"), **GEOM)
    os.makedirs(os.path.dirname(ref_cfg.base_path))
    ref = sdriver.run_soak(ref_cfg)

    kcfg = sdriver.SoakConfig(
        base_path=str(root / "killed" / "soak.ckpt"), **GEOM)
    os.makedirs(os.path.dirname(kcfg.base_path))
    plan = rsup.KillPlan(round=128, stage="post_journal", mode="raise")
    with pytest.raises(rsup.SimulatedPreemption):
        sdriver.run_soak(kcfg, kill_plan=plan)
    resumed = sdriver.run_soak(kcfg)
    return ref_cfg, ref, kcfg, resumed


def test_soak_drift_invariants_green(soak_pair):
    _, ref, _, _ = soak_pair
    assert ref.drift["ok"], ref.drift
    assert ref.drift["violations"] == 0
    assert ref.drift["compile_flat"]
    # One program for the whole lifetime: every per-segment sample saw
    # the same compile count.
    assert len(set(ref.drift["cache_sizes"])) == 1
    assert ref.drift["segments_sampled"] == GEOM["n_segments"]
    assert ref.alarms["quiet"], ref.alarms


def test_soak_kill_resume_is_byte_identical(soak_pair):
    ref_cfg, ref, kcfg, resumed = soak_pair
    ref_rows = sdriver.content_rows(ref_cfg.journal_path)
    got_rows = sdriver.content_rows(kcfg.journal_path)
    assert got_rows == ref_rows          # raw byte lines, file order
    assert sdriver.result_digest(resumed) == sdriver.result_digest(ref)
    # The killed lineage re-ran the journaled-but-not-checkpointed
    # segment and deduped its record.
    assert resumed.result.segments_deduped >= 1


def test_soak_journal_tiles_the_lifetime(soak_pair):
    ref_cfg, ref, kcfg, _ = soak_pair
    for path in (ref_cfg.journal_path, kcfg.journal_path):
        cover = rharness.verify_journal(path, ref.rounds)
        assert cover["complete"], cover["problems"]
        assert cover["n_segments"] == GEOM["n_segments"]
    # Exactly one metrics_window row per segment rides the journal,
    # interleaved with its segment record (content kinds only).
    kinds = [json.loads(line).get("kind")
             for line in sdriver.content_rows(ref_cfg.journal_path)]
    assert kinds.count("segment") == GEOM["n_segments"]
    assert kinds.count("metrics_window") == GEOM["n_segments"]


@pytest.mark.slow
def test_soak_long_arm():
    """The >= 1e5-round soak (env-scalable: SCALECUBE_SOAK_ROUNDS)."""
    import tempfile

    rounds = int(os.environ.get("SCALECUBE_SOAK_ROUNDS", 100_000))
    segment_rounds = 256
    n_segments = max(1, -(-rounds // segment_rounds))
    with tempfile.TemporaryDirectory(prefix="soak-long-") as td:
        cfg = sdriver.SoakConfig(
            base_path=os.path.join(td, "soak.ckpt"), seed=7,
            n_members=32, severity="moderate",
            segment_rounds=segment_rounds, n_segments=n_segments)
        soak = sdriver.run_soak(cfg)
        assert soak.rounds == n_segments * segment_rounds >= rounds
        assert soak.drift["ok"], soak.drift
        assert soak.drift["violations"] == 0
        assert soak.alarms["quiet"], soak.alarms
