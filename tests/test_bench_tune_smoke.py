"""bench.py --tune --smoke: the autotuner JSON contract.

The smoke-pin pattern of tests/test_bench_fuzz_smoke.py: the bench is
the one entry point the tune measurement flows through, so this test
runs the real script in a subprocess (CPU) and pins the published
contract — one JSON line with the one-compile-per-shape-bucket witness
(``tune_compiles == tune_shape_buckets``, warm pass adds ZERO), the
compile-amortized ``batch_speedup_ratio`` >= 1.0, the Pareto frontier
over green rows, every shipped profile monitor-green + strictly better
than the reference on its target + fuzz-oracle green on the held-out
seed, an artifacts/tune_pareto.json-style artifact the query layer
loads as a real payload, and the regress gate walking the dedicated
tune checks.  The full grid runs under @slow with env-scaled size.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.tune

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run_tune_bench(tmp_path, extra_args=(), extra_env=None, timeout=540):
    artifact = tmp_path / "tune_pareto_smoke.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_TUNE_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--tune", *extra_args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    return json.loads(lines[0]), artifact


def _check_contract(result, artifact, smoke):
    assert "error" not in result, result
    assert result["smoke"] is smoke
    assert result["metric"] == "tune_pareto"
    # value stays None BY DESIGN (grid throughput is host-dependent and
    # the tune gates are absolute); the payload says so.
    assert result["value"] is None
    assert "value_note" in result

    # THE tentpole witness: one compile per scenario shape bucket for
    # the WHOLE grid (knobs are traced operands), zero on the warm pass.
    assert result["tune_shape_buckets"] >= 1
    assert result["tune_compiles"] == result["tune_shape_buckets"]
    assert result["tune_warm_recompiles"] == 0
    grid = result["grid"]
    assert grid["configs"] == len(result["rows"]) >= 5
    assert sum(grid["bucket_sizes"]) == result["scenarios"] > 0
    assert result["tune_grid_throughput"] > 0

    # The gated speedup: the one-compile dynamic-knob sweep vs the
    # recompile-per-config static sweep, measured on real cold configs.
    assert result["batch_speedup_ratio"] >= 1.0
    assert grid["static_configs_measured"] >= 1
    assert grid["seconds_static_per_config"] > 0
    if smoke:
        # the warm dispatch-parity control arm is full-mode only
        assert result["batch_dispatch_ratio"] is None
    else:
        assert result["batch_dispatch_ratio"] > 0

    # Rows: reference default first (the non-domination anchor), every
    # row scored on every objective, reference monitor-green.
    rows = result["rows"]
    assert rows[0]["name"] == "reference"
    assert rows[0]["overrides"] == {} and rows[0]["green"] is True
    objs = result["objectives"]
    assert set(objs) == {"false_positive_observer_rate",
                         "detection_latency_p99_rounds",
                         "removal_latency_p99_rounds",
                         "wire_bytes_per_member_round"}
    for row in rows:
        assert set(objs) <= set(row["slos"]), row["name"]
    assert result["reference_slos"] == rows[0]["slos"]

    # Frontier: non-empty, over known rows only.
    names = {r["name"] for r in rows}
    assert result["frontier"] and set(result["frontier"]) <= names

    # Shipped profiles: >= 2, each monitor-green, STRICTLY better than
    # the reference on its own target, non-dominated, and fuzz-oracle
    # green on the held-out seed.
    profiles = result["profiles"]
    assert len(profiles) >= 2
    for name, prof in profiles.items():
        assert name in names
        assert prof["target"] in objs
        assert prof["monitor_green"] is True, name
        assert prof["target_vs_reference"] < 0, (name, prof)
        assert prof["nondominated_vs_reference"] is True, name
        assert prof["fuzz_green"] is True, (name, prof["fuzz"])
        assert prof["fuzz"]["seed"] == result["held_out_seed"]
        assert prof["overrides"]

    # The artifact loads as a REAL (non-stub) payload and the regress
    # gate ran green with the dedicated tune checks.
    from scalecube_cluster_tpu.telemetry import query as tquery

    art = json.loads(artifact.read_text())
    assert art["metric"] == result["metric"]
    payload, skip_note = tquery.load_bench_payload(str(artifact))
    assert skip_note is None
    assert payload["batch_speedup_ratio"] == result["batch_speedup_ratio"]

    assert result["regress"]["ok"] is True, result["regress"]
    ok, checks = tquery.regress([str(artifact)])
    assert ok
    by_name = {r["check"]: r for r in checks}
    for check in ("slo/tune_batch_speedup", "slo/tune_profiles_shipped",
                  "slo/tune_profiles_nondominated",
                  "slo/tune_profiles_fuzz_green"):
        # the walk holds ONLY this round, so even a smoke sweep is
        # verdict-bearing (the sync-heal fallback rule)
        assert by_name[check]["ok"] is True, by_name[check]


@pytest.mark.slow
def test_bench_tune_smoke_contract(tmp_path):
    """@slow despite being the smoke pin: the sweep + held-out fuzz +
    static-counterfactual subprocess runs ~4.5 min on CPU, which blows
    the tier-1 budget (the bench-smoke convention caps around 2 min).
    ``test_tune_mode_is_exclusive`` keeps the CLI contract tier-1; the
    sweep/witness/profile machinery itself is pinned tier-1 in-process
    by tests/test_tune.py."""
    result, artifact = _run_tune_bench(tmp_path, extra_args=("--smoke",))
    _check_contract(result, artifact, smoke=True)


def test_tune_mode_is_exclusive():
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--tune", "--fuzz"],
        capture_output=True, text=True, timeout=60,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=str(REPO),
    )
    assert proc.returncode == 2
    assert "--tune" in proc.stderr


@pytest.mark.slow
def test_bench_tune_full_grid(tmp_path):
    """The full (non-smoke) grid path.  The design-target scale is the
    full scenario batch on an accelerator; under the CPU-forced test
    environment the same non-smoke code path runs at a CPU-feasible
    size (env overrides drop on real hardware) — full grid + solo
    arms, the dispatch-parity control arm, the static-counterfactual
    speedup, held-out profile validation, the regress gate."""
    result, artifact = _run_tune_bench(
        tmp_path,
        extra_env={
            "SCALECUBE_TUNE_N": os.environ.get("SCALECUBE_TUNE_N", "16"),
            "SCALECUBE_TUNE_SCENARIOS": os.environ.get(
                "SCALECUBE_TUNE_SCENARIOS", "8"),
            "SCALECUBE_TUNE_FUZZ_PER_TIER": os.environ.get(
                "SCALECUBE_TUNE_FUZZ_PER_TIER", "1"),
            "SCALECUBE_TUNE_STATIC_CONFIGS": os.environ.get(
                "SCALECUBE_TUNE_STATIC_CONFIGS", "1"),
        },
        timeout=3000,
    )
    _check_contract(result, artifact, smoke=False)
