"""bench.py --multichip --smoke: the multichip throughput JSON contract.

Like tests/test_bench_metrics_smoke.py for the health plane: the bench
is the one entry point the per-chip measurements flow through, so this
tier-1 test runs the real script in a subprocess (CPU, virtual 8-device
mesh) and pins the published contract — one JSON line with REAL
per-chip throughput fields (never a ``{"rc":0,"ok":true}`` stub), the
mesh shape, a finite pipelined-vs-serial ratio over both measured
rates, the bit-identity probe, a MULTICHIP_*-style artifact, and the
regress gate walking it.
"""

import json
import math
import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.multichip

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_bench_multichip_smoke_contract(tmp_path):
    artifact = tmp_path / "MULTICHIP_smoke.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SCALECUBE_TPU_TELEMETRY_DIR=str(tmp_path),
        SCALECUBE_MULTICHIP_ARTIFACT=str(artifact),
        SCALECUBE_XLA_CACHE_DIR="",           # no cache writes from tests
    )
    env.pop("SCALECUBE_TPU_PROFILE_DIR", None)
    # The subprocess must size its own virtual mesh (conftest's 8-device
    # XLA_FLAGS hack applies to THIS process, not children).
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--multichip", "--smoke"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout      # exactly ONE JSON line
    result = json.loads(lines[0])

    assert "error" not in result, result
    assert result["smoke"] is True
    assert result["metric"] == "swim_multichip_member_rounds_per_sec_per_chip"

    # A real mesh, never a silently-truncated one.
    assert result["n_devices"] >= 2
    assert result["mesh_shape"] == [result["n_devices"]]
    assert result["n_members"] % result["n_devices"] == 0
    assert result["delivery"] == "scatter"

    # Real throughput fields (the stub-replacement contract): both paths
    # measured, ratio consistent and finite.  No floor on the ratio here
    # (a loaded CI box can skew one smoke window); the committed
    # MULTICHIP_r06.json records the pinned >= 1.0 measurement and the
    # regress gate bounds future ones at 1 - band.
    pipelined = result["pipelined_member_rounds_per_sec_per_chip"]
    serial = result["serial_member_rounds_per_sec_per_chip"]
    ratio = result["pipelined_speedup_ratio"]
    assert pipelined > 0 and serial > 0
    assert math.isfinite(ratio) and ratio > 0
    assert ratio == pytest.approx(pipelined / serial, rel=1e-3)
    assert result["value"] == pipelined
    assert result["rounds_timed"] > 0
    assert result["ici_bytes_per_device_round"] > 0

    # The scheduling change is semantics-free: the in-bench parity probe
    # must agree with what tests/test_pipelined_delivery.py pins.
    assert result["bit_identical"] is True

    # The artifact round-trips and carries the same measurement —
    # loadable by the query layer as a real (non-stub) payload.
    art = json.loads(artifact.read_text())
    assert art["metric"] == result["metric"]
    assert art["pipelined_speedup_ratio"] == ratio
    assert art["value"] == pipelined

    from scalecube_cluster_tpu.telemetry import query as tquery

    payload, skip_note = tquery.load_bench_payload(str(artifact))
    assert skip_note is None
    assert payload["value"] == pipelined

    # The in-bench regress gate ran over the BENCH + MULTICHIP
    # trajectories (wired-in loud failure for future regressions) and
    # the fresh artifact's ratio check is present and green.
    assert result["regress"]["ok"] is True
    assert result["regress"]["artifacts"] >= 1
    ok, rows = tquery.regress([str(artifact)])
    ratio_rows = [r for r in rows
                  if r.get("check") == "slo/pipelined_speedup_ratio"]
    assert len(ratio_rows) == 1
