"""Batched composed runner (models/compose.composed_batch_scan):
bit-identity pins for the batch axis.

The contract under test (ISSUE 17 tentpole):

  - B=1 equals the unbatched ``composed_scan`` BIT-EXACTLY — protocol
    state, per-round metrics and every plane's finalized slice — across
    plane stacks, both carry layouts and under round fusion (the scan
    stays outside the vmap, so the per-round gates see the same
    predicates a single row would produce);
  - row i of any batch equals the sequential run of that row's
    (key, world, knobs) alone — including per-row VARIED knob data,
    the autotuner's whole premise (tune/search.py sweeps are only
    trustworthy if batching never leaks state across rows);
  - ``run_monitored_batch`` is a thin alias over the same runner
    (byte-for-byte monitor outputs);
  - sharding does not compose with the batch axis, and says so
    (``batch_shard_unsupported_reason`` — a declared reason, never a
    silent wrong answer).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.chaos import monitor as cmonitor
from scalecube_cluster_tpu.models import compose, swim
from scalecube_cluster_tpu.telemetry import metrics as tmetrics
from scalecube_cluster_tpu.telemetry import trace as ttrace

from tests.test_compose import (N, ROUNDS, chaos_params, chaos_world,
                                metrics_equal, states_equal)

pytestmark = pytest.mark.compose

CAPACITY = 128
TRACE_CAP = 64


def stack_rows(*rows):
    """Stack pytree rows on a new leading batch axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *rows)


def broadcast_spec(spec, batch):
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (batch,) + x.shape), spec)


def batch_planes(params, batch, trace=True, monitor=True, metr=False):
    planes = []
    if trace:
        planes.append(ttrace.TracePlane(capacity=TRACE_CAP))
    if monitor:
        planes.append(cmonitor.MonitorPlane(
            broadcast_spec(cmonitor.MonitorSpec.passive(params), batch),
            capacity=CAPACITY))
    if metr:
        planes.append(tmetrics.MetricsPlane(
            tmetrics.MetricsSpec.default(),
            chaos_from="monitor" if monitor else None))
    return tuple(planes)


def row_planes(params, trace=True, monitor=True, metr=False):
    planes = []
    if trace:
        planes.append(ttrace.TracePlane(capacity=TRACE_CAP))
    if monitor:
        planes.append(cmonitor.MonitorPlane(
            cmonitor.MonitorSpec.passive(params), capacity=CAPACITY))
    if metr:
        planes.append(tmetrics.MetricsPlane(
            tmetrics.MetricsSpec.default(),
            chaos_from="monitor" if monitor else None))
    return tuple(planes)


def tree_row(tree, i):
    return jax.tree.map(lambda x: x[i], tree)


def assert_trees_equal(a, b, label):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb), label
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=label)


# Plane-stack x layout x fusion grid for the B=1 pin: the bare scan,
# the tune stack (trace + passive monitor), the full observer stack,
# and in-tick planes armed (sync + lifeguard + open_world under the
# full stack) — each on the wide and compact carry layouts, plus one
# fused-with-tail cell.  Tier-1 samples the three distinct runner
# shapes (no planes / batched plane folds / fused body); the full grid
# runs @slow.
B1_CASES = [
    ("bare", dict(), dict(trace=False, monitor=False, metr=False)),
    ("tune-stack", dict(), dict(trace=True, monitor=True, metr=False)),
    # smallest fused shape with a non-divisible tail (33 = 16*2 + 1):
    # the unroll factor drives the compile cost, and tier-1 only needs
    # the fused-body runner SHAPE pinned — the full fused stack at
    # rounds_per_step=5 runs @slow below
    ("fused-tail", dict(rounds_per_step=2, rounds=33),
     dict(trace=True, monitor=False, metr=False)),
]
B1_SLOW_CASES = [
    ("fused-full", dict(rounds_per_step=5),  # 36 = 7*5 + 1
     dict(trace=True, monitor=True, metr=True)),
    ("full-stack", dict(sync=True, lifeguard=True),
     dict(trace=True, monitor=True, metr=True)),
    ("openworld-full", dict(sync=True, lifeguard=True, open_world=True),
     dict(trace=True, monitor=True, metr=True)),
    ("compact-carry", dict(compact_carry=True),
     dict(trace=True, monitor=True, metr=False)),
    ("compact-full", dict(compact_carry=True, sync=True, lifeguard=True),
     dict(trace=True, monitor=True, metr=True)),
]


def check_b1_bit_identity(name, pkw, stack):
    """Pinned B=1 == unbatched: every output of the batch runner at
    batch size one is byte-for-byte the ``composed_scan`` output on
    the same (key, world)."""
    ow = pkw.pop("open_world", False)
    rounds = pkw.pop("rounds", ROUNDS)
    params = chaos_params(open_world=ow, **pkw)
    world = chaos_world(params, open_world=ow)
    key = jax.random.key(31)

    f1, r1, m1 = compose.composed_scan(
        key, params, world, rounds, planes=row_planes(params, **stack))
    fb, rb, mb = compose.composed_batch_scan(
        stack_rows(key), params, stack_rows(world), rounds,
        planes=batch_planes(params, 1, **stack))

    states_equal(f1, tree_row(fb, 0))
    metrics_equal(m1, {k: v[0] for k, v in mb.items()})
    assert set(r1) == set(rb)
    for pname in r1:
        assert_trees_equal(r1[pname], tree_row(rb[pname], 0),
                           f"{name}: plane {pname!r} diverged at B=1")


@pytest.mark.parametrize("name,pkw,stack", B1_CASES,
                         ids=[c[0] for c in B1_CASES])
def test_b1_bit_identity_with_unbatched(name, pkw, stack):
    check_b1_bit_identity(name, pkw, stack)


@pytest.mark.slow
@pytest.mark.parametrize("name,pkw,stack", B1_SLOW_CASES,
                         ids=[c[0] for c in B1_SLOW_CASES])
def test_b1_bit_identity_full_grid(name, pkw, stack):
    check_b1_bit_identity(name, pkw, stack)


def test_rows_equal_sequential_with_varied_knobs():
    """Row i of a batch == the sequential run of row i alone, with the
    batch rows deliberately HETEROGENEOUS: three different chaos
    worlds under three different knob settings (the autotuner's
    config-grid shape).  Any cross-row leak in the batched scan would
    break at least one row's parity."""
    params = chaos_params(sync=True, lifeguard=True, lhm_max=4,
                          dead_suppress_rounds=6)
    # Batch rows must share the fault-rule arity (leaf shapes stack on
    # the batch axis), so every row carries exactly one link rule.
    worlds = [
        chaos_world(params),
        swim.SwimWorld.healthy(params).with_crash(2, at_round=6)
        .with_crash(11, at_round=18)
        .with_link_fault((0, 4), (4, 8), loss=0.2, from_round=5,
                         until_round=15),
        swim.SwimWorld.healthy(params)
        .with_link_fault((0, N // 2), (N // 2, N), loss=0.5,
                         from_round=2, until_round=30)
        .with_leave(9, at_round=10),
    ]
    knob_rows = [
        swim.Knobs.from_params(params),
        swim.Knobs.for_params(params, ping_every=1,
                              ping_timeout_ms=float(params.ping_timeout_ms)
                              / 2),
        swim.Knobs.for_params(params, ping_every=4, suspicion_rounds=9,
                              lhm_max=2, dead_suppress_rounds=3),
    ]
    keys = [jax.random.key(100 + i) for i in range(3)]

    fb, rb, mb = compose.composed_batch_scan(
        stack_rows(*keys), params, stack_rows(*worlds), ROUNDS,
        planes=batch_planes(params, 3),
        knobs=jax.tree.map(lambda *xs: jnp.stack(
            [jnp.asarray(x) for x in xs]), *knob_rows))

    for i in range(3):
        fi, ri, mi = compose.composed_scan(
            keys[i], params, worlds[i], ROUNDS,
            planes=row_planes(params), knobs=knob_rows[i])
        states_equal(fi, tree_row(fb, i))
        metrics_equal(mi, {k: v[i] for k, v in mb.items()})
        for pname in ri:
            assert_trees_equal(ri[pname], tree_row(rb[pname], i),
                               f"row {i}: plane {pname!r} diverged")


@pytest.mark.slow
def test_default_knobs_broadcast_matches_explicit():
    """``knobs=None`` broadcasts ``Knobs.from_params`` — same bits as
    passing the stacked default explicitly."""
    params = chaos_params()
    world = chaos_world(params)
    keys = stack_rows(jax.random.key(1), jax.random.key(2))
    worlds = stack_rows(world, world)
    kn = swim.Knobs.from_params(params)
    explicit = jax.tree.map(
        lambda x: jnp.broadcast_to(jnp.asarray(x)[None],
                                   (2,) + jnp.asarray(x).shape), kn)
    fa, _, ma = compose.composed_batch_scan(keys, params, worlds, ROUNDS)
    fb, _, mb = compose.composed_batch_scan(keys, params, worlds, ROUNDS,
                                            knobs=explicit)
    states_equal(fa, fb)
    metrics_equal(ma, mb)


@pytest.mark.slow
def test_run_monitored_batch_is_thin_alias():
    """The batched monitored sweep entry is a THIN alias over
    ``composed_batch_scan`` — byte-for-byte the same monitor slice and
    final states (the PR-12 private scan plumbing is gone; the fuzz
    suite pins the same parity campaign-wide, tests/test_chaos_fuzz)."""
    params = chaos_params(sync=True)
    worlds = stack_rows(chaos_world(params),
                        swim.SwimWorld.healthy(params)
                        .with_crash(4, at_round=7)
                        .with_link_fault((0, 4), (4, 8), loss=0.2,
                                         from_round=5, until_round=15))
    keys = stack_rows(jax.random.key(5), jax.random.key(6))
    spec = broadcast_spec(cmonitor.MonitorSpec.passive(params), 2)
    f_alias, mon_alias, m_alias = cmonitor.run_monitored_batch(
        keys, params, worlds, spec, ROUNDS, capacity=CAPACITY)
    fb, rb, mb = compose.composed_batch_scan(
        keys, params, worlds, ROUNDS,
        planes=(cmonitor.MonitorPlane(spec, capacity=CAPACITY),))
    states_equal(f_alias, fb)
    metrics_equal(m_alias, mb)
    assert_trees_equal(mon_alias, rb["monitor"], "monitor alias diverged")


@pytest.mark.slow
def test_batch_resume_matches_unbroken():
    """Chunked batched runs resume batch-stacked states bit-identically
    to one unbroken batched run (the checkpoint-segment shape on the
    batch axis)."""
    params = chaos_params()
    worlds = stack_rows(chaos_world(params),
                        swim.SwimWorld.healthy(params)
                        .with_crash(1, at_round=20)
                        .with_link_fault((0, 4), (4, 8), loss=0.2,
                                         from_round=5, until_round=15))
    keys = stack_rows(jax.random.key(8), jax.random.key(9))
    f_all, _, m_all = compose.composed_batch_scan(keys, params, worlds,
                                                  ROUNDS)
    half = ROUNDS // 2
    f1, _, _ = compose.composed_batch_scan(keys, params, worlds, half)
    f2, _, _ = compose.composed_batch_scan(keys, params, worlds,
                                           ROUNDS - half, states=f1,
                                           start_round=half)
    states_equal(f_all, f2)


def test_batch_shard_unsupported_reason_is_declared():
    """Batch x shard is declared unsupported with a real reason (the
    ``pipelined_delivery_unsupported`` pattern) — never a silent wrong
    answer."""
    params = chaos_params()
    reason = compose.batch_shard_unsupported_reason(params)
    assert isinstance(reason, str) and reason
    assert "shard" in reason and "batch" in reason
