"""Chaos verdicts vs the event-driven oracle at small N.

The acceptance criterion's second leg: under IDENTICAL fault schedules
the monitor's green verdict must agree with oracle cross-validation —
the model's on-device event trace and the oracle's listener stream
yield the same timing-free (observer, subject, type, incarnation) key
sets (telemetry/events.py), per victim, over continuously-live
observers.  ``chaos.campaign.cross_validate`` replays crash schedules
as the oracle's full link blockade and leaves as ``Cluster.shutdown``
(the proven mapping of tests/test_telemetry_trace.py).
"""

import dataclasses

import pytest

from scalecube_cluster_tpu.chaos import campaign as cc
from scalecube_cluster_tpu.chaos import scenarios as cs

pytestmark = pytest.mark.chaos

N = 16


def test_permanent_crash_verdict_agrees_with_oracle():
    scen = cs.Scenario(name="xval-crash", n_members=N, horizon=128,
                       ops=(cs.Crash(3, at_round=2),))
    v = cc.run_scenario(scen, seed=1)
    assert v.green, v.verdict["codes"]
    cv = cc.cross_validate(scen, seed=1)
    assert cv is not None
    assert cv["agree"], cv["victims"]
    assert cv["observers"] == N - 1
    assert cv["victims"]["3"] == {"only_model": [], "only_oracle": []}


def test_graceful_leave_verdict_agrees_with_oracle():
    scen = cs.Scenario(name="xval-leave", n_members=N, horizon=96,
                       ops=(cs.Leave(4, at_round=6),))
    v = cc.run_scenario(scen, seed=2)
    assert v.green, v.verdict["codes"]
    cv = cc.cross_validate(scen, seed=2)
    assert cv is not None and cv["agree"], cv["victims"]


def test_inexpressible_scenarios_return_none():
    """Scenarios the oracle can't replay faithfully are declined, not
    mis-compared: network ops, background loss, and short (non-quiescent)
    crash/revive windows."""
    for ops, loss in (
        ((cs.LinkLoss(0, 1, loss=0.5),), 0.0),
        ((cs.Crash(3, at_round=2),), 0.05),
        ((cs.Crash(3, at_round=2, until_round=12),), 0.0),  # too short
    ):
        scen = cs.Scenario(name="nope", n_members=N, horizon=96,
                           ops=ops, loss_probability=loss)
        assert cc.cross_validate(scen, seed=0) is None, ops


def _quiesced_partition_scenario(sync_interval=10):
    """One split/heal cycle long enough to quiesce (tombstones cold at
    the heal — the bounded-re-convergence precondition, models/sync.py),
    sized from the campaign preset's bounds."""
    p = cc.campaign_params(
        cs.Scenario(name="size-probe", n_members=N, horizon=8, ops=()),
        sync_interval=sync_interval,
    )
    return dataclasses.replace(
        cs.quiesced_heal_scenario(p, N), name="xval-partition-heal")


@pytest.mark.sync
def test_partition_heal_parity_with_oracle_sync_recovery():
    """The SYNC anti-entropy acceptance leg: under an identical
    partition/heal schedule, the model (anti-entropy plane ON) and the
    oracle (doSync/syncAck full-table exchange) emit the SAME timing-free
    event key sets per member over opposite-half observers — each half
    suspects, removes, and post-heal RE-ADDS every cross member, and the
    re-adds are exactly the SYNC-recovered members on both layers."""
    scen = _quiesced_partition_scenario()
    cv = cc.cross_validate_partition(scen, seed=3)
    assert cv is not None
    assert cv["agree"], {k: d for k, d in cv["victims"].items()
                         if d["only_model"] or d["only_oracle"]}
    assert cv["halves"] == [N // 2, N // 2]
    # Every member was re-added by every opposite-half observer through
    # the exchange: N/2 ADDED keys per victim, on both layers (the sets
    # are equal, so counting the model side counts the oracle too).
    for v, d in cv["victims"].items():
        assert d["sync_recovered_keys"] == N // 2, (v, d)

    # The same schedule's monitored green (incl. the armed
    # POST_HEAL_DIVERGENCE window) is pinned by tests/test_monitor.py;
    # here just check build() arms the promise for this scenario too.
    params = cc.campaign_params(scen, sync_interval=10)
    _, spec = scen.build(params)
    assert int(spec.agree_from) < scen.horizon  # the promise was armed


def test_partition_heal_inexpressible_variants_return_none():
    """Multi-cycle or composed partition scenarios are declined, not
    mis-compared."""
    two_cycle = cs.Scenario(
        name="nope", n_members=N, horizon=256,
        ops=(cs.RollingPartition(from_round=0, phase_rounds=32,
                                 n_cycles=2),))
    assert cc.cross_validate_partition(two_cycle, seed=0) is None
    composed = cs.Scenario(
        name="nope", n_members=N, horizon=256,
        ops=(cs.RollingPartition(from_round=0, phase_rounds=32,
                                 n_cycles=1),
             cs.Crash(3, at_round=2)))
    assert cc.cross_validate_partition(composed, seed=0) is None
    lossy = cs.Scenario(
        name="nope", n_members=N, horizon=256,
        ops=(cs.RollingPartition(from_round=0, phase_rounds=32,
                                 n_cycles=1),),
        loss_probability=0.05)
    assert cc.cross_validate_partition(lossy, seed=0) is None


def test_campaign_attaches_cross_validation(tmp_path):
    from scalecube_cluster_tpu.telemetry import sink as tsink

    scen = cs.Scenario(name="xval-crash", n_members=N, horizon=128,
                       ops=(cs.Crash(5, at_round=3),))
    with tsink.TelemetrySink(str(tmp_path), prefix="chaos") as sink:
        result = cc.run_campaign([scen], seed=3, sink=sink,
                                 cross_validate_small_n=True)
    assert result.green
    (row,) = tsink.read_records(result.manifest_path,
                                kind="chaos_scenario")
    assert row["cross_validation"]["agree"] is True


def test_config_push_kv_parity_with_oracle():
    """The metadata plane's ground truth: after a push schedule with an
    LWW overwrite, every observer on BOTH layers holds exactly the last
    written value per (owner, key) — the jit plane's versioned LWW and
    the oracle's incarnation-bump demand-fetch reach the same terminal
    table."""
    scen = cs.Scenario(
        name="xval-push", n_members=N, horizon=192,
        ops=(cs.ConfigPush(node=5, key=0, value=77, at_round=4),
             cs.ConfigPush(node=3, key=0, value=123, at_round=8),
             # LWW overwrite: node 3's second write must win everywhere.
             cs.ConfigPush(node=3, key=0, value=200, at_round=40)))
    cv = cc.cross_validate_metadata(scen, seed=5)
    assert cv is not None
    assert cv["agree"], cv["per_push"]
    assert cv["observers"] == N and cv["pushes"] == 3
    assert set(cv["per_push"]) == {"5:k0", "3:k0"}
    assert cv["per_push"]["3:k0"]["value"] == 200       # last write won
    for digest in cv["per_push"].values():
        assert digest["model_divergent"] == 0
        assert digest["oracle_divergent"] == 0


def test_staged_rollout_kv_parity_with_oracle():
    scen = cs.Scenario(
        name="xval-rollout", n_members=N, horizon=256,
        ops=(cs.StagedRollout(members=(1, 9, 4, 12), n_stages=2,
                              key=0, value=41, start_round=6,
                              stage_every=96),))
    cv = cc.cross_validate_metadata(scen, seed=6)
    assert cv is not None
    assert cv["agree"], cv["per_push"]
    assert cv["pushes"] == 4
    assert all(d["value"] == 41 for d in cv["per_push"].values())


def test_metadata_inexpressible_scenarios_return_none():
    """Mixed membership ops or background loss make terminal KV parity
    timing-dependent — declined, not mis-compared."""
    mixed = cs.Scenario(
        name="nope", n_members=N, horizon=128,
        ops=(cs.ConfigPush(node=2, key=0, value=9, at_round=4),
             cs.Crash(7, at_round=8)))
    assert cc.cross_validate_metadata(mixed, seed=0) is None
    lossy = cs.Scenario(
        name="nope", n_members=N, horizon=128,
        ops=(cs.ConfigPush(node=2, key=0, value=9, at_round=4),),
        loss_probability=0.05)
    assert cc.cross_validate_metadata(lossy, seed=0) is None
