"""Chaos verdicts vs the event-driven oracle at small N.

The acceptance criterion's second leg: under IDENTICAL fault schedules
the monitor's green verdict must agree with oracle cross-validation —
the model's on-device event trace and the oracle's listener stream
yield the same timing-free (observer, subject, type, incarnation) key
sets (telemetry/events.py), per victim, over continuously-live
observers.  ``chaos.campaign.cross_validate`` replays crash schedules
as the oracle's full link blockade and leaves as ``Cluster.shutdown``
(the proven mapping of tests/test_telemetry_trace.py).
"""

import pytest

from scalecube_cluster_tpu.chaos import campaign as cc
from scalecube_cluster_tpu.chaos import scenarios as cs

pytestmark = pytest.mark.chaos

N = 16


def test_permanent_crash_verdict_agrees_with_oracle():
    scen = cs.Scenario(name="xval-crash", n_members=N, horizon=128,
                       ops=(cs.Crash(3, at_round=2),))
    v = cc.run_scenario(scen, seed=1)
    assert v.green, v.verdict["codes"]
    cv = cc.cross_validate(scen, seed=1)
    assert cv is not None
    assert cv["agree"], cv["victims"]
    assert cv["observers"] == N - 1
    assert cv["victims"]["3"] == {"only_model": [], "only_oracle": []}


def test_graceful_leave_verdict_agrees_with_oracle():
    scen = cs.Scenario(name="xval-leave", n_members=N, horizon=96,
                       ops=(cs.Leave(4, at_round=6),))
    v = cc.run_scenario(scen, seed=2)
    assert v.green, v.verdict["codes"]
    cv = cc.cross_validate(scen, seed=2)
    assert cv is not None and cv["agree"], cv["victims"]


def test_inexpressible_scenarios_return_none():
    """Scenarios the oracle can't replay faithfully are declined, not
    mis-compared: network ops, background loss, and short (non-quiescent)
    crash/revive windows."""
    for ops, loss in (
        ((cs.LinkLoss(0, 1, loss=0.5),), 0.0),
        ((cs.Crash(3, at_round=2),), 0.05),
        ((cs.Crash(3, at_round=2, until_round=12),), 0.0),  # too short
    ):
        scen = cs.Scenario(name="nope", n_members=N, horizon=96,
                           ops=ops, loss_probability=loss)
        assert cc.cross_validate(scen, seed=0) is None, ops


def test_campaign_attaches_cross_validation(tmp_path):
    from scalecube_cluster_tpu.telemetry import sink as tsink

    scen = cs.Scenario(name="xval-crash", n_members=N, horizon=128,
                       ops=(cs.Crash(5, at_round=3),))
    with tsink.TelemetrySink(str(tmp_path), prefix="chaos") as sink:
        result = cc.run_campaign([scen], seed=3, sink=sink,
                                 cross_validate_small_n=True)
    assert result.green
    (row,) = tsink.read_records(result.manifest_path,
                                kind="chaos_scenario")
    assert row["cross_validation"]["agree"] is True
