"""Test configuration: force an 8-device virtual CPU mesh before jax import.

Mirrors the reference's test trick of simulating "multi-node" inside one
process (SURVEY.md §4): there, n in-JVM transports on loopback; here, a
virtual 8-device CPU mesh so sharding/collective code paths run without TPU
hardware.
"""

import os

# Force CPU even when a real TPU (e.g. JAX_PLATFORMS=axon) is attached:
# unit tests exercise the virtual 8-device mesh; the real chip is for
# bench.py only.  The axon image pins jax_platforms at jax-import time, so
# the env var alone is not enough — override the config after import too.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(autouse=True, scope="module")
def _bounded_jit_footprint():
    """Clear jax's compilation caches after every test module.

    One suite process compiles ~300 distinct XLA:CPU programs; past a
    cumulative threshold the in-process compiler segfaults
    deterministically (observed three runs in a row at the same compile
    in test_swim_model once the round-4 tests pushed the program count
    up — crash inside ``jax/_src/compiler.py backend_compile_and_load``,
    the test passing in isolation).  Dropping executables between
    modules keeps the JIT footprint bounded; cross-module recompiles
    are cheap relative to the suite.
    """
    yield
    jax.clear_caches()
