"""Test configuration: force an 8-device virtual CPU mesh before jax import.

Mirrors the reference's test trick of simulating "multi-node" inside one
process (SURVEY.md §4): there, n in-JVM transports on loopback; here, a
virtual 8-device CPU mesh so sharding/collective code paths run without TPU
hardware.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
