"""resilience/store.py: rotation, checksums, and corruption fallback.

The store's contract is that a run survives anything short of losing
EVERY generation: the latest checkpoint being truncated, bit-flipped or
deleted must fall back to the newest intact generation, and only full
exhaustion raises — with every candidate tried named in the error.
"""

import os

import numpy as np
import pytest

from scalecube_cluster_tpu.resilience import store as rstore

pytestmark = pytest.mark.resilience


def payload(seed=0, n=8):
    rng = np.random.default_rng(seed)
    return {
        "state/status": rng.integers(0, 4, (n, n)).astype(np.int8),
        "state/inc": rng.integers(0, 100, (n, n)).astype(np.int32),
        "telemetry/first_suspect": rng.integers(
            0, 1 << 30, (n, n)).astype(np.int32),
    }


def fill(store, gens, seed0=0):
    for i, g in enumerate(gens):
        store.save(payload(seed=seed0 + i), g, meta={"gen": g})


def test_roundtrip_with_key_and_meta(tmp_path):
    import jax

    store = rstore.CheckpointStore(str(tmp_path / "ck"), keep=2)
    arrays = payload(seed=3)
    key = jax.random.key(9)
    store.save(arrays, 40, key=key, meta={"run": "x", "n": 8})
    got, next_round, got_key, meta, info = store.load_latest()
    assert next_round == 40
    assert meta == {"run": "x", "n": 8}
    assert info["generation"] == 40 and info["fallbacks"] == []
    for name, a in arrays.items():
        np.testing.assert_array_equal(got[name], a)
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(key)),
        np.asarray(jax.random.key_data(got_key)),
    )


def test_rotation_keeps_last_g_and_gcs_older(tmp_path):
    store = rstore.CheckpointStore(str(tmp_path / "ck"), keep=3)
    fill(store, [10, 20, 30, 40, 50])
    assert store.generations_on_disk() == [30, 40, 50]
    # The GC'd files are really gone.
    assert not os.path.exists(store.gen_path(10))
    assert not os.path.exists(store.gen_path(20))
    _, next_round, _, _, info = store.load_latest()
    assert next_round == 50 and info["generation"] == 50


def test_empty_lineage_returns_none(tmp_path):
    store = rstore.CheckpointStore(str(tmp_path / "ck"))
    assert store.load_latest() is None


@pytest.mark.parametrize("corruption", ["truncate", "bitflip", "delete"])
def test_corrupt_latest_falls_back_to_previous(tmp_path, corruption):
    store = rstore.CheckpointStore(str(tmp_path / "ck"), keep=3)
    fill(store, [10, 20, 30])
    latest = store.gen_path(30)
    if corruption == "truncate":
        with open(latest, "rb+") as f:
            f.truncate(os.path.getsize(latest) // 3)
    elif corruption == "bitflip":
        with open(latest, "rb+") as f:
            f.seek(os.path.getsize(latest) // 2)
            b = f.read(1)
            f.seek(-1, 1)
            f.write(bytes([b[0] ^ 0xFF]))
    else:
        os.unlink(latest)

    got, next_round, _, meta, info = store.load_latest()
    assert next_round == 20
    assert meta == {"gen": 20}
    assert info["generation"] == 20
    np.testing.assert_array_equal(got["state/inc"],
                                  payload(seed=1)["state/inc"])
    if corruption == "delete":
        assert info["fallbacks"] == []     # nothing tried and rejected
    else:
        (path, why), = info["fallbacks"]
        assert path == latest and why      # the reason is named


def test_checksum_catches_content_swap(tmp_path):
    """A bit-flip the zip layer misses (CRC re-stamped — the 'clever
    corruption' case: an out-of-band rewrite of one member) still fails
    the payload checksum."""
    import zipfile

    store = rstore.CheckpointStore(str(tmp_path / "ck"), keep=2)
    fill(store, [10, 20])
    latest = store.gen_path(20)
    # Rewrite one member with valid-zip bytes of the wrong content.
    bogus = str(tmp_path / "bogus.npz")
    np.savez(bogus, **{"state/status": payload(seed=99)["state/status"]})
    with zipfile.ZipFile(bogus) as zin, \
            zipfile.ZipFile(latest, "a") as zout:
        zout.writestr("state/status.npy", zin.read("state/status.npy"))
    _, next_round, _, _, info = store.load_latest()
    assert next_round == 10
    (path, why), = info["fallbacks"]
    assert path == latest
    # Depending on the zipfile duplicate-name read path this surfaces
    # as a checksum mismatch or an unreadable member — either way it
    # must NOT load as round 20.
    assert "checksum" in why or "unreadable" in why


def test_gc_never_deletes_just_written_or_intact_fallback(tmp_path):
    """After load_latest falls back PAST corrupt newer generations, the
    resumed run re-checkpoints at a LOWER generation number than the
    corrupt stragglers.  GC must not prefer the stragglers (newest by
    number) over the just-written generation or the intact one the run
    resumed from — that would exhaust the lineage; instead the corrupt
    files age out once the cursor passes them again."""
    store = rstore.CheckpointStore(str(tmp_path / "ck"), keep=3)
    fill(store, [20, 30, 40])
    for g in (30, 40):
        with open(store.gen_path(g), "rb+") as f:
            f.truncate(os.path.getsize(store.gen_path(g)) // 3)
    _, next_round, _, _, info = store.load_latest()
    assert next_round == 20 and len(info["fallbacks"]) == 2

    store.save(payload(seed=9), 28, meta={"gen": 28})
    gens = store.generations_on_disk()
    assert 28 in gens                  # the write survives its own GC
    assert 20 in gens                  # so does the intact fallback
    _, next_round2, _, _, _ = store.load_latest()
    assert next_round2 == 28           # newest INTACT generation wins

    # The corrupt stragglers age out of the window as the cursor
    # advances, and a clean load needs no fallbacks again.
    for i, g in enumerate((36, 44, 52)):
        store.save(payload(seed=10 + i), g, meta={"gen": g})
    _, next_round3, _, _, info3 = store.load_latest()
    assert next_round3 == 52 and info3["fallbacks"] == []
    assert 30 not in store.generations_on_disk()


def test_exhausted_generations_raise_naming_every_candidate(tmp_path):
    store = rstore.CheckpointStore(str(tmp_path / "ck"), keep=3)
    fill(store, [10, 20, 30])
    for g in (10, 20, 30):
        with open(store.gen_path(g), "rb+") as f:
            f.truncate(10)
    with pytest.raises(rstore.CheckpointExhaustedError) as ei:
        store.load_latest()
    msg = str(ei.value)
    for g in (10, 20, 30):
        assert store.gen_path(g) in msg
    assert len(ei.value.candidates) == 3
    assert "start over" in msg


def test_legacy_single_file_checkpoint_still_loads(tmp_path):
    """Old unrotated, unchecksummed utils/checkpoint .npz files are the
    final fallback candidate (MIGRATING.md)."""
    import jax

    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.utils import checkpoint as ckpt

    from tests.test_swim_model import make

    params, world = make(8)
    state = swim.initial_state(params, world)
    base = str(tmp_path / "old.npz")
    ckpt.save(base, state, next_round=12, key=jax.random.key(1),
              meta={"legacy": True})

    store = rstore.CheckpointStore(base, keep=2)
    got, next_round, key, meta, info = store.load_latest()
    assert next_round == 12 and meta == {"legacy": True}
    assert info.get("legacy") is True and info["generation"] is None
    np.testing.assert_array_equal(got["state/status"],
                                  np.asarray(state.status))
    # Once a rotated generation exists it wins over the legacy file.
    store.save(got, 24, meta={"legacy": True})
    _, next_round2, _, _, info2 = store.load_latest()
    assert next_round2 == 24 and info2["generation"] == 24


def test_save_is_atomic_and_write_first_delete_second(tmp_path):
    """A failed save never removes existing generations: GC runs only
    after the new generation is durable."""
    store = rstore.CheckpointStore(str(tmp_path / "ck"), keep=2)
    fill(store, [10, 20])

    class Boom(RuntimeError):
        pass

    class Unsavable:
        def __array__(self):
            raise Boom("mid-serialization failure")

    with pytest.raises(Exception):
        store.save({"state/x": Unsavable()}, 30)
    # The lineage is untouched and still loads.
    assert store.generations_on_disk() == [10, 20]
    _, next_round, _, _, _ = store.load_latest()
    assert next_round == 20
    # No temp droppings either.
    leftovers = [f for f in os.listdir(tmp_path) if ".tmp" in f]
    assert leftovers == []


def test_keep_validation():
    with pytest.raises(ValueError, match="keep"):
        rstore.CheckpointStore("x", keep=0)
