"""Headline benchmark: SWIM member-rounds/sec/chip on real TPU.

Runs the full SWIM tick (FD + gossip + suspicion + SYNC) in focal mode at
1M members — the BASELINE.md north-star configuration (the reference never
ran above N=50, SURVEY.md §6, and publishes no absolute numbers) — using
the shift-delivery fast path (models/swim.py module docstring,
ops/shift.py) and reports throughput in member-rounds/sec/chip.

``vs_baseline`` is measured against the north-star requirement implied by
BASELINE.json: simulate 1M members × 10k rounds on a v5e-8 in one hour,
i.e. 1e6*1e4/(3600*8) ≈ 3.47e5 member-rounds/sec/chip.  (Round 1's bench
docstring wrote this constant as 3.47e8 — a 1000x typo; the arithmetic
below is and was 3.47e5.)  vs_baseline 1.0 means exactly that rate;
higher is better.

Robustness contract (this file must never ship an empty round):
  - backend init is retried, then falls back to CPU (clearly marked);
  - a small-N canary runs first so a failure is diagnosed cheaply;
  - every stage appends diagnostics to stderr;
  - exactly ONE JSON line is printed to stdout no matter what — on any
    failure it carries the best measurement achieved plus the error.

Telemetry: every invocation writes a JSONL run manifest (run id, config
digest, device info, counter rows, detection/removal latency histogram
buckets from a traced crash scenario, the event stream itself) under
``SCALECUBE_TPU_TELEMETRY_DIR`` (default ``artifacts/telemetry``) —
telemetry/sink.py; a TensorBoard export of the same data activates when
``SCALECUBE_TPU_PROFILE_DIR`` is set.

``--smoke``: a fast CPU-safe pass (small N, few rounds, no canary) that
exercises the full pipeline — timed run, dissemination probe, traced
telemetry scenario, JSONL manifest — so the wiring can't silently rot;
pinned by tests/test_bench_smoke.py.

Env overrides for debugging: SCALECUBE_BENCH_N, SCALECUBE_BENCH_ROUNDS,
SCALECUBE_BENCH_DELIVERY, SCALECUBE_BENCH_SKIP_CANARY,
SCALECUBE_BENCH_COMPACT (=1: the capacity-oriented compact carry layout,
SwimParams.compact_carry).
"""

import argparse
import json
import os
import sys
import time
import traceback

NORTH_STAR_RATE = 1e6 * 1e4 / (3600.0 * 8)  # member-rounds/sec/chip

SMOKE = False  # set by main() from --smoke; rescales the module knobs

N_MEMBERS = int(os.environ.get("SCALECUBE_BENCH_N", 1_000_000))
# "full" = full-view mode (K == N, exact reference semantics, O(N^2) state).
_subj = os.environ.get("SCALECUBE_BENCH_SUBJECTS", "16")
N_SUBJECTS = None if _subj == "full" else int(_subj)
# 1000-round timed window: each jit invocation pays ~0.1 s of dispatch
# through the tunnelled TPU link, which at 200 rounds depressed the
# measured rate ~12% below the device's steady state (~3.1e8 vs 3.54e8
# member-rounds/s at 1M).  The real workloads scan thousands of rounds
# per call, so the long window is the honest steady-state measure.
BENCH_ROUNDS = int(os.environ.get("SCALECUBE_BENCH_ROUNDS", 1000))
DELIVERY = os.environ.get("SCALECUBE_BENCH_DELIVERY", "shift")
COMPACT = os.environ.get("SCALECUBE_BENCH_COMPACT", "") == "1"
CANARY_N = 4096
# Traced telemetry scenario size cap (events scale ~2N; trace capacity is
# telemetry.trace.DEFAULT_CAPACITY = 65536, so 4096 leaves >8x headroom —
# the "zero drops at default capacity" contract).
TELEMETRY_N = 4096
TELEMETRY_CRASH_AT = 10


def apply_smoke_preset():
    """CPU-safe fast path: small N, short windows, no canary.  Explicit
    env overrides still win (same precedence as the full bench)."""
    global SMOKE, N_MEMBERS, BENCH_ROUNDS, TELEMETRY_N
    SMOKE = True
    N_MEMBERS = int(os.environ.get("SCALECUBE_BENCH_N", 256))
    BENCH_ROUNDS = int(os.environ.get("SCALECUBE_BENCH_ROUNDS", 40))
    TELEMETRY_N = min(TELEMETRY_N, 256)
    os.environ.setdefault("SCALECUBE_BENCH_SKIP_CANARY", "1")


def log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def init_backend():
    """jax.devices() with retries; fall back to CPU if TPU init fails."""
    import jax

    from scalecube_cluster_tpu.utils import runlog
    cache = runlog.enable_compilation_cache()
    if cache:
        log(f"xla compilation cache at {cache}")

    last_err = None
    for attempt in range(3):
        try:
            devs = jax.devices()
            log(f"backend ok (attempt {attempt + 1}): {devs}")
            return jax, jax.default_backend()
        except RuntimeError as e:  # backend init failure (e.g. tunnel down)
            last_err = e
            log(f"backend init failed (attempt {attempt + 1}): {e}")
            time.sleep(5.0 * (attempt + 1))
    log("falling back to CPU backend")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    devs = jax.devices("cpu")
    log(f"cpu fallback devices: {devs}")
    return jax, "cpu(fallback)"


def timed_run(jax, n_members, rounds, label):
    """Compile + steady-state-time a run; returns (member-rounds/sec,
    metrics traces of the timed window).

    The timed region is wrapped in ``runlog.profiled`` — a no-op unless
    ``SCALECUBE_TPU_PROFILE_DIR`` is set, in which case a ``jax.profiler``
    step trace lands there (the input to experiments/profile_roofline.py's
    kernel table), and the run's protocol counters are digested through
    ``runlog.log_metrics_summary`` (the reference-style per-period logs,
    SURVEY.md §5.1).
    """
    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.utils import runlog

    def force(state):
        return runlog.completion_barrier(state.status)

    rlog = runlog.get_logger("bench")
    params = swim.SwimParams.from_config(
        ClusterConfig.default(),
        n_members=n_members,
        n_subjects=N_SUBJECTS,
        loss_probability=0.02,
        per_subject_metrics=True,
        delivery=DELIVERY,
        compact_carry=COMPACT,
    )
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=50)
    key = jax.random.key(0)

    t0 = time.perf_counter()
    state = swim.initial_state(params, world)
    # Warm-up compiles the exact (params, n_rounds, state-provided)
    # signature the timed call uses, so the timed region is steady state.
    state, _ = swim.run(key, params, world, rounds, state=state,
                        start_round=0)
    force(state)
    log(f"{label}: compile+first-run took {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    with runlog.profiled(rlog):
        state, metrics = swim.run(
            key, params, world, rounds, state=state, start_round=rounds
        )
        force(state)
    elapsed = time.perf_counter() - t0
    rate = n_members * rounds / elapsed
    log(f"{label}: {rounds} rounds in {elapsed:.3f}s -> {rate:.3e} "
        f"member-rounds/sec")
    runlog.log_metrics_summary(rlog, metrics, round_offset=rounds)
    # Sanity: the crash at round 50 must eventually be noticed.
    dead_total = int(jax.numpy.asarray(metrics["dead"]).sum())
    log(f"{label}: dead-view observer-rounds in window: {dead_total}")
    return rate, metrics


def dissemination_at_scale(jax, n_members):
    """Rounds-to-full-dissemination at scale (BASELINE.json's 2nd metric).

    A graceful leave at round 10 emits one DEAD@inc+1 record whose
    infection-style spread to all N live observers is timed in rounds —
    pure dissemination, no suspicion-timeout wait.  Compare with the
    analytic window repeat_mult*ceil(log2(n+1)) (ClusterMath.java:111-113).
    """
    import numpy as np

    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.models import swim

    params = swim.SwimParams.from_config(
        ClusterConfig.default(),
        n_members=n_members,
        n_subjects=N_SUBJECTS,
        delivery=DELIVERY,
    )
    world = swim.SwimWorld.healthy(params).with_leave(3, at_round=10)
    _, metrics = swim.run(jax.random.key(1), params, world, 60)
    alive_view = np.asarray(metrics["alive"])[:, 3]
    gone = np.flatnonzero(alive_view == 0)
    rounds = int(gone[0]) - 10 if gone.size else -1
    log(f"dissemination@{n_members}: leave@10 fully known by round "
        f"{int(gone[0]) if gone.size else 'never'} -> {rounds} rounds")
    return rounds


def telemetry_scenario(jax):
    """The traced crash scenario: a crash at round k observed through the
    on-device event trace (models/swim.run_traced) and digested into
    detection/removal latency histograms — distribution-level
    observability where the bench prints could only report means.

    Runs at min(N_MEMBERS, TELEMETRY_N) so the ~2N SUSPECTED+REMOVED
    events sit far below the default trace capacity (zero drops is part
    of the contract, asserted in the manifest summary).
    """
    import numpy as np

    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.telemetry import trace as ttrace

    n = min(N_MEMBERS, TELEMETRY_N)
    # The sped-up protocol timing (the test preset): the suspicion
    # timeout resolves in tens of rounds, so the scenario stays cheap.
    cfg = ClusterConfig.default().replace(
        gossip_interval=100, ping_interval=200, ping_timeout=100,
        sync_interval=1_000, suspicion_mult=3,
    )
    params = swim.SwimParams.from_config(
        cfg, n_members=n, n_subjects=min(16, n), delivery=DELIVERY,
    )
    crash_node = 3
    world = swim.SwimWorld.healthy(params).with_crash(
        crash_node, at_round=TELEMETRY_CRASH_AT
    )
    rounds = params.suspicion_rounds + 80
    _, tel, metrics = swim.run_traced(
        jax.random.key(7), params, world, rounds
    )
    hists = ttrace.latency_histograms(tel, world)
    events = ttrace.decode_events(tel)
    log(f"telemetry@{n}: {int(tel.trace.count)} events recorded, "
        f"{int(tel.trace.dropped)} dropped "
        f"(capacity {tel.trace.capacity})")
    return {
        "params": params,
        "metrics": metrics,
        "events": events,
        "recorded": int(tel.trace.count),
        "dropped": int(tel.trace.dropped),
        "capacity": int(tel.trace.capacity),
        "edges": np.asarray(hists["edges"]).tolist(),
        "detection_buckets": np.asarray(hists["detection"])[crash_node].tolist(),
        "removal_buckets": np.asarray(hists["removal"])[crash_node].tolist(),
        "detection_undetected": int(
            np.asarray(hists["detection_undetected"])[crash_node]
        ),
        "crash_node": crash_node,
        "crash_at": TELEMETRY_CRASH_AT,
        "n_members": n,
        "rounds": rounds,
    }


def write_telemetry(scenario, main_metrics):
    """JSONL run manifest + (gated) TensorBoard export; returns the
    manifest path."""
    import numpy as np

    from scalecube_cluster_tpu.telemetry import sink as tsink

    out_dir = (os.environ.get(tsink.TELEMETRY_DIR_ENV)
               or os.path.join("artifacts", "telemetry"))
    sink = tsink.TelemetrySink(
        out_dir, prefix="bench-smoke" if SMOKE else "bench"
    )
    sink.write_manifest(
        params=scenario["params"],
        workload={
            "bench_n_members": N_MEMBERS,
            "bench_rounds": BENCH_ROUNDS,
            "delivery": DELIVERY,
            "compact_carry": COMPACT,
            "smoke": SMOKE,
        },
        scenario={
            "kind": "crash",
            "n_members": scenario["n_members"],
            "crash_node": scenario["crash_node"],
            "crash_round": scenario["crash_at"],
            "rounds": scenario["rounds"],
        },
    )
    if main_metrics is not None:
        sink.write_counters(main_metrics, round_offset=BENCH_ROUNDS,
                            label="main_timed_window")
    sink.write_counters(scenario["metrics"], label="telemetry_scenario")
    hist_meta = dict(subject=scenario["crash_node"],
                     fault_round=scenario["crash_at"])
    sink.write_histogram("detection_latency_rounds", scenario["edges"],
                         scenario["detection_buckets"],
                         undetected=scenario["detection_undetected"],
                         **hist_meta)
    sink.write_histogram("removal_latency_rounds", scenario["edges"],
                         scenario["removal_buckets"], **hist_meta)
    # Fraction-informed-by-round: the dissemination curve of the death
    # notice, from the scenario's per-subject dead counts.
    dead = np.asarray(scenario["metrics"]["dead"])[:, scenario["crash_node"]]
    sink.write_curve(
        "fraction_informed",
        tsink.fraction_informed_curve(dead, scenario["n_members"] - 1),
        subject=scenario["crash_node"],
    )
    sink.write_events(scenario["events"], dropped=scenario["dropped"])
    sink.write_summary(
        events_recorded=scenario["recorded"],
        event_drops=scenario["dropped"],
        trace_capacity=scenario["capacity"],
    )
    sink.close()
    tsink.maybe_export_tensorboard(
        sink.run_id,
        scalars={
            "telemetry/dead_views": scenario["metrics"]["dead"],
            "telemetry/messages_gossip":
                scenario["metrics"]["messages_gossip"],
            "telemetry/false_positives":
                scenario["metrics"]["false_positives"],
        },
        histograms={
            "telemetry/detection_latency_rounds":
                (scenario["edges"], scenario["detection_buckets"]),
            "telemetry/removal_latency_rounds":
                (scenario["edges"], scenario["removal_buckets"]),
        },
    )
    log(f"telemetry manifest written to {sink.path}")
    return sink.path


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--smoke", action="store_true",
        help="fast CPU-safe pass (small N, few rounds, no canary) that "
             "still exercises the full pipeline incl. telemetry",
    )
    try:
        args = parser.parse_args()
    except SystemExit as e:
        # The one-JSON-line contract holds even for a bad argv: argparse
        # already printed its usage message to stderr; ship the error
        # line before propagating its exit code (--help's clean exit
        # stays JSON-free — it is not a measurement attempt).
        if e.code not in (0, None):
            print(json.dumps({
                "metric": "swim_member_rounds_per_sec_per_chip",
                "value": None,
                "error": f"ArgumentError: bad argv {sys.argv[1:]}",
            }), flush=True)
        raise
    if args.smoke:
        apply_smoke_preset()

    result = {
        "metric": "swim_member_rounds_per_sec_per_chip",
        "value": None,
        "unit": "member-rounds/sec/chip",
        "vs_baseline": None,
        "smoke": SMOKE,
    }
    main_metrics = None
    try:
        jax, platform = init_backend()
        result["platform"] = platform

        if not os.environ.get("SCALECUBE_BENCH_SKIP_CANARY"):
            # 100 rounds at 4k members is ~0.13 s — nearly all per-call
            # dispatch overhead (~0.1 s/invocation through the tunnelled
            # TPU link), NOT throughput at 4k.  It exists to diagnose
            # failures cheaply before the 1M run; label it accordingly.
            canary_rate, _ = timed_run(jax, CANARY_N, 100,
                                       f"canary@{CANARY_N}")
            result["canary_smoke_member_rounds_per_sec"] = round(canary_rate, 1)
            result["canary_note"] = (
                "smoke check only — 100-round window is dispatch-dominated, "
                "do not read as throughput"
            )

        rate, main_metrics = timed_run(jax, N_MEMBERS, BENCH_ROUNDS,
                                       f"main@{N_MEMBERS}")
        result["value"] = round(rate, 1)
        result["vs_baseline"] = round(rate / NORTH_STAR_RATE, 3)
        result["n_members"] = N_MEMBERS
        result["rounds_timed"] = BENCH_ROUNDS
        result["delivery"] = DELIVERY
        result["dissemination_rounds"] = dissemination_at_scale(jax, N_MEMBERS)
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
        if (result["value"] is None
                and "canary_smoke_member_rounds_per_sec" in result):
            # Ship the canary as a lower-bound datum rather than nothing.
            result["value"] = result["canary_smoke_member_rounds_per_sec"]
            result["vs_baseline"] = round(result["value"] / NORTH_STAR_RATE, 3)
            result["n_members"] = CANARY_N

    # Telemetry stage: the traced scenario + JSONL manifest.  Same
    # never-ship-empty contract — a telemetry failure is recorded in the
    # result, it does not void the throughput measurement.
    try:
        import jax  # may already be initialized above; cheap re-import

        scenario = telemetry_scenario(jax)
        manifest = write_telemetry(scenario, main_metrics)
        result["telemetry"] = {
            "manifest": manifest,
            "events_recorded": scenario["recorded"],
            "event_drops": scenario["dropped"],
            "detection_latency_hist": {
                "edges": scenario["edges"],
                "counts": scenario["detection_buckets"],
                "undetected": scenario["detection_undetected"],
            },
        }
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["telemetry_error"] = f"{type(e).__name__}: {e}"

    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
