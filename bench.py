"""Headline benchmark: SWIM member-rounds/sec/chip on real TPU.

Runs the full SWIM tick (FD + gossip + suspicion + SYNC) in focal mode at
1M members — the BASELINE.md north-star configuration (the reference never
ran above N=50, SURVEY.md §6, and publishes no absolute numbers) — using
the shift-delivery fast path (models/swim.py module docstring,
ops/shift.py) and reports throughput in member-rounds/sec/chip.

``vs_baseline`` is measured against the north-star requirement implied by
BASELINE.json: simulate 1M members × 10k rounds on a v5e-8 in one hour,
i.e. 1e6*1e4/(3600*8) ≈ 3.47e5 member-rounds/sec/chip.  (Round 1's bench
docstring wrote this constant as 3.47e8 — a 1000x typo; the arithmetic
below is and was 3.47e5.)  vs_baseline 1.0 means exactly that rate;
higher is better.

Robustness contract (this file must never ship an empty round):
  - backend init is retried, then falls back to CPU (clearly marked);
  - a small-N canary runs first so a failure is diagnosed cheaply;
  - every stage appends diagnostics to stderr;
  - exactly ONE JSON line is printed to stdout no matter what — on any
    failure it carries the best measurement achieved plus the error.

Telemetry: every invocation writes a JSONL run manifest (run id, config
digest, device info, counter rows, detection/removal latency histogram
buckets from a traced crash scenario, the event stream itself) under
``SCALECUBE_TPU_TELEMETRY_DIR`` (default ``artifacts/telemetry``) —
telemetry/sink.py; a TensorBoard export of the same data activates when
``SCALECUBE_TPU_PROFILE_DIR`` is set.

Traced-vs-untraced: the timed window is measured BOTH ways by default —
the untraced ``swim.run`` hot path and the traced path through
``telemetry.sink.stream_traced_run`` (round-fused scan, donated carry,
device→host trace offload overlapped with the next segment).  The JSON
line carries ``untraced_member_rounds_per_sec``,
``traced_member_rounds_per_sec`` and ``traced_overhead_ratio``
(untraced/traced; 1.0 = telemetry is free).  ``--untraced``/``--traced``
restrict to one path for debugging; ``--gap-artifact [PATH]``
additionally writes a BENCH_*-style artifact pinning the measured gap.

``--smoke``: a fast CPU-safe pass (small N, few rounds, no canary) that
exercises the full pipeline — both timed paths (fused + traced +
overlapped offload included), dissemination probe, traced telemetry
scenario, JSONL manifest — so the wiring can't silently rot; pinned by
tests/test_bench_smoke.py.

``--chaos``: the robustness workload instead of the throughput one — a
seeded severity-tiered campaign of generated fault scenarios (churn
storms, flapping links, rolling partitions, crash bursts, brownouts;
chaos/scenarios.py) each run through the in-jit invariant monitor
(chaos/monitor.py), with verdict manifests through the same JSONL
pipeline.  One JSON line as always: green flag, per-invariant-code
violation totals, one-line repros for any red scenario.  ``--chaos
--smoke`` is the tier-1-safe mini campaign pinned by
tests/test_chaos_campaign.py.  Env overrides: SCALECUBE_CHAOS_N,
SCALECUBE_CHAOS_SCENARIOS, SCALECUBE_CHAOS_SEED.

``--metrics``: the observability-cost workload — the always-on health
registry (telemetry/metrics.py: in-jit counters/gauges/histograms
carried through the scan) measured against the bare hot path on the
same interleaved best-of window discipline as the traced/untraced
gap.  One JSON line out with ``metrics_overhead_ratio``
(unmetered/metered rate; 1.0 = the health plane is free), the window
registry digest, the health SLOs (telemetry/query.py), and a JSONL
manifest of ``metrics_window`` rows.  Writes a BENCH_*-style artifact
(default ``artifacts/metrics_smoke.json`` under --smoke,
``artifacts/metrics_bench.json`` otherwise; override with
SCALECUBE_METRICS_ARTIFACT).  ``--metrics --smoke`` is the tier-1-safe
pass pinned by tests/test_bench_metrics_smoke.py; the
``python -m scalecube_cluster_tpu.telemetry regress`` gate checks the
recorded ratio.

``--resilience``: the preemption-survival workload — the kill-injection
drill (resilience/harness.py) SIGKILLs a resilient run (rotated,
checksummed checkpoints + resumable JSONL journal;
resilience/supervisor.py) at seeded random rounds/write-stages in a
subprocess, relaunches it, and asserts the resumed final state is
bit-identical to an uninterrupted run with gap-free, duplicate-free
telemetry — for each of the plain/traced/monitored run shapes — plus
the corrupted-latest-generation fallback drill.  Runs on CPU by design
(a correctness harness, not a throughput one).  One JSON line as
always.  ``--resilience --smoke`` is the tier-1-safe mini drill.  Env
overrides: SCALECUBE_RESILIENCE_N, SCALECUBE_RESILIENCE_ROUNDS,
SCALECUBE_RESILIENCE_SEGMENT, SCALECUBE_RESILIENCE_KILLS,
SCALECUBE_RESILIENCE_SEED, SCALECUBE_RESILIENCE_SHAPES (comma list).

``--sync``: the partition-heal workload — the SYNC anti-entropy plane
(models/sync.py) measured for its headline robustness claim: after a
quiesced RollingPartition split, the plane re-converges every live
membership table within a bounded window (``sync_rounds_to_converge``),
while the gossip-only control demonstrably never does.  Two arms: a
monitored chaos-campaign-scale heal (POST_HEAL_DIVERGENCE must be 0)
and the focal-shift 1M-shape scale arm probed for the first
divergence-free table.  Writes an ``artifacts/sync_heal.json``-style
artifact the ``telemetry regress`` gate walks (absolute convergence
gates + banded convergence-time series).  ``--sync --smoke`` is the
tier-1-safe pass pinned by tests/test_bench_sync_smoke.py.  Env
overrides: SCALECUBE_SYNC_N, SCALECUBE_SYNC_SUBJECTS,
SCALECUBE_SYNC_INTERVAL, SCALECUBE_SYNC_PROBE_STEP,
SCALECUBE_SYNC_MONITOR_N, SCALECUBE_SYNC_SEED,
SCALECUBE_SYNC_ARTIFACT.

``--rollout``: the config-propagation workload — the metadata KV plane
(models/metadata.py) measured for its headline robustness claim: a
STAGED config rollout (chaos.StagedRollout — seeded owner waves, each
gated on cluster-wide convergence before the next fires) completes
under fire (a revive churn storm + a partition split/heal crossing the
stages) with every stage inside its convergence deadline
(chaos/scenarios.metadata_convergence_bound, partition-extended like
the monitor's completeness deadlines), while the gossip-only control
(metadata on, SYNC off) demonstrably never re-converges through the
heal.  Three arms: a monitored composite (zero violations required), a
gated segment-driven rollout probe (per-push convergence latencies →
``metadata_convergence_p99``; a deadline breach would roll the flipped
stages back via StagedRollout.rollback_ops and fail the gate), and the
control.  Writes an ``artifacts/config_rollout.json``-style artifact
the ``telemetry regress`` gate walks (absolute convergence/control/
monitor gates + banded p99 series).  ``--rollout --smoke`` is the
tier-1-safe pass pinned by tests/test_bench_rollout_smoke.py.  Env
overrides: SCALECUBE_ROLLOUT_N, SCALECUBE_ROLLOUT_STAGES,
SCALECUBE_ROLLOUT_STAGE_SIZE, SCALECUBE_ROLLOUT_SYNC_INTERVAL,
SCALECUBE_ROLLOUT_PROBE_STEP, SCALECUBE_ROLLOUT_SEED,
SCALECUBE_ROLLOUT_ARTIFACT.

``--lifeguard``: the adaptivity workload — the Lifeguard health plane
(models/lifeguard.py) measured A/B against its own control under the
seeded ``chaos.asymmetric_degradation`` scenario (Brownout loss+delay
on the inbound ranges of a degraded minority — an eighth of the ids,
``chaos.asymmetric_degraded_range`` — + FlappingLink): the plane must at
least HALVE the ``false_positive_observer_rate`` SLO while keeping
crash-detection latency P99 within +1 round — both gated absolutely by
``telemetry regress`` over the ``artifacts/lifeguard_fp.json``-style
artifact this mode writes.  ``--lifeguard --smoke`` is the tier-1-safe
single-scenario pass pinned by tests/test_bench_lifeguard_smoke.py.
Env overrides: SCALECUBE_LIFEGUARD_N, SCALECUBE_LIFEGUARD_LHM_MAX,
SCALECUBE_LIFEGUARD_SEED, SCALECUBE_LIFEGUARD_SCENARIOS,
SCALECUBE_LIFEGUARD_ARTIFACT.

``--alarms``: the live SLO alarm drill — the streaming breach detector
(telemetry/alarms.py) measured against a planted fault with a known
onset round.  The seeded ``chaos.alarm_drill_scenario`` square loss
pulse runs TWICE on the same world: a healthy arm (campaign-default
Knobs) that must ride the pulse out with ZERO alarm transitions, and a
weakened-knobs breach arm (``chaos.alarm_breach_knobs`` — probe every
round; dynamic Knobs data, so the rerun reuses the healthy arm's
compiled program, zero extra compiles) whose
``false_positive_observer_rate`` breach the alarm must catch within
ONE metrics window of the onset and RESOLVE after the heal — all gated
absolutely by ``telemetry regress`` over the
``artifacts/alarm_drill.json``-style artifact this mode writes.  Both
arms journal through live ``TelemetrySink`` sinks, so the drill
doubles as the end-to-end fixture for ``telemetry watch``.  ``--alarms
--smoke`` is the tier-1-safe pass pinned by
tests/test_bench_alarms_smoke.py.  Env overrides: SCALECUBE_ALARM_N,
SCALECUBE_ALARM_SEED, SCALECUBE_ALARM_WINDOW, SCALECUBE_ALARM_ONSET,
SCALECUBE_ALARM_PULSE, SCALECUBE_ALARM_COOL,
SCALECUBE_ALARM_PULSE_LOSS, SCALECUBE_ALARM_THRESHOLD,
SCALECUBE_ALARM_ARTIFACT.

``--blame``: the provenance blame drill — the per-belief channel
attribution plane (models/provenance.py) measured against a planted
fault with a KNOWN origin.  The seeded ``chaos.blame_drill_scenario``
plants ONE asymmetric faulty link (victim→observer acks drop, every
other link pristine) so exactly one member's direct probes fail; the
host-side blame engine (telemetry/query.blame_report), fed only the
recorded attributions, must name that observer as the origin with a
first-hand ``fd_direct`` sighting while the rest of the cluster heard
the rumor second-hand via gossip.  Rides along: the channel-mix
fractions must sum to 1.0 with zero provenance/trace drops, the
``provenance=False`` run must stay bit-identical (states + metrics),
the interleaved armed-vs-bare overhead ratio must stay <= 1.10, and a
``telemetry explain`` probe must resolve the seeded (observer,
subject) query from the journal with the correct channel and round —
all gated absolutely by ``telemetry regress`` over the
``artifacts/provenance_blame.json``-style artifact this mode writes.
``--blame --smoke`` is the tier-1-safe pass pinned by
tests/test_bench_blame_smoke.py.  Env overrides: SCALECUBE_BLAME_N,
SCALECUBE_BLAME_SEED, SCALECUBE_BLAME_ONSET, SCALECUBE_BLAME_PULSE,
SCALECUBE_BLAME_COOL, SCALECUBE_BLAME_VICTIM,
SCALECUBE_BLAME_OBSERVER, SCALECUBE_BLAME_REPS,
SCALECUBE_BLAME_ARTIFACT.

``--churn``: the open-world membership workload — mid-run JOIN admission
into recycled slots (models/swim.SwimParams.open_world) measured A/B
against naive slot reuse under the seeded
``chaos.churn_growth_scenario`` net-positive arrival storm: the epoch
guard must finish with ZERO NO_RESURRECTION / JOIN_COMPLETENESS
violations and a ``join_propagation_p99`` inside the dissemination
bound, while the naive control arm DEMONSTRATES the resurrection
failure (violations > 0) — all gated absolutely by ``telemetry
regress`` over the ``artifacts/churn_growth.json``-style artifact this
mode writes.  ``--churn --smoke`` is the tier-1-safe single-scenario
pass pinned by tests/test_bench_churn_smoke.py.  Env overrides:
SCALECUBE_CHURN_N, SCALECUBE_CHURN_SEED, SCALECUBE_CHURN_SCENARIOS,
SCALECUBE_CHURN_SUPPRESS, SCALECUBE_CHURN_ARTIFACT.

``--fuzz``: the vmapped chaos mega-campaign — scenario throughput as a
SPEED metric and violation coverage as a QUALITY metric.  Thousands of
seeded scenarios per severity tier (chaos/scenarios.
generate_fuzz_campaign) are bucketed by compiled shape signature and
each bucket is fuzzed by ONE device program (jax.vmap of the monitored
scan over the scenario batch axis — chaos/monitor.run_monitored_batch),
timed interleaved against the sequential one-dispatch-per-scenario
loop on the SAME batch: ``vmap_speedup_ratio`` must stay >= 1 (compile/
dispatch amortization has to pay on any host).  A COVERAGE arm reruns
the completeness-promising slice of the batch on a deliberately-
weakened build (suspicion timers stretched past the horizon —
chaos.campaign.weakened_knobs, a dynamic-knobs change that reuses the
healthy batch's compiled program) and requires the fuzzer to FIND the
planted violations while the healthy arm found none.  Writes an
``artifacts/fuzz_campaign.json``-style artifact (smoke runs get
``fuzz_campaign_smoke.json`` — provenance, the sync-heal convention)
walked by ``telemetry regress``.  ``--fuzz --smoke`` is the
tier-1-safe mini batch pinned by tests/test_bench_fuzz_smoke.py.  Env
overrides: SCALECUBE_FUZZ_N, SCALECUBE_FUZZ_SEEDS_PER_TIER,
SCALECUBE_FUZZ_SEED, SCALECUBE_FUZZ_REPS, SCALECUBE_FUZZ_CAPACITY,
SCALECUBE_FUZZ_ARTIFACT.

``--wire``: the fused single-buffer scatter wire A/B — the default
``SwimParams.fused_wire`` path (ALIVE flags riding the key word's
spare bits, ONE full-height collective per round) against the HEAD
two-buffer path (int32 key + int8 flag pair, two collectives), on both
the serial in-round combine and the pipelined sharded run, interleaved
best-of per pair.  Emits the fused/legacy speedup ratios (regress
floor: fused never slower), the compiled-HLO full-height collective
counts (1 vs 2), and the traffic model's 4-vs-5 B/slot + wire24
headroom numbers into an ``artifacts/wire_fused.json``-style artifact
(smoke runs get ``wire_fused_smoke.json`` — provenance, the sync-heal
convention) walked by ``telemetry regress``.  ``--wire --smoke`` is
the CPU-safe virtual-8-device pass pinned by
tests/test_bench_wire_smoke.py.  Env overrides: SCALECUBE_WIRE_DEVICES,
SCALECUBE_WIRE_N, SCALECUBE_WIRE_ROUNDS, SCALECUBE_WIRE_ARTIFACT.

``--compose``: the composed plane runner A/B — the full instrumented
stack (event trace ⊕ invariant monitor ⊕ health registry) through ONE
scan and ONE compiled program (models/compose.run_composed) against the
pre-compose alias-by-alias route (run_traced + run_metered +
run_monitored: three programs, three passes), interleaved best-of with
a bare-run anchor arm and a bit-identity parity probe, plus a
compile-cost arm counting programs compiled across the entry-point ×
layout matrix (head-style: 3/layout, composed: 1/layout — strictly
reduced).  Writes an ``artifacts/compose_perf.json``-style artifact
(smoke runs get ``compose_perf_smoke.json``) with
``compose_speedup_ratio`` (>= 1.0 floor), ``full_stack_overhead_ratio``
vs the head-style overhead, and the compile counts — all gated by
``telemetry regress``.  ``--compose --smoke`` is the tier-1-safe pass
pinned by tests/test_bench_compose_smoke.py.  Env overrides:
SCALECUBE_COMPOSE_ARTIFACT, SCALECUBE_BENCH_N, SCALECUBE_BENCH_ROUNDS.

``--soak``: production soak mode — one long-lived service lifetime
under the continuous seeded chaos stream (soak/schedule.py) through the
resilient supervisor's composed shape (soak/driver.py): the full plane
stack with live alarms, checkpointed segments, one JSONL journal, and
per-segment drift invariants (compile cache flat after segment 1, host
RSS bounded, zero monitor violations), plus a seeded mid-soak
SIGKILL/relaunch drill whose merged journal content rows must be
byte-identical to the uninterrupted run's with a bit-identical final
state digest.  Forces CPU (a correctness harness).  Writes an
``artifacts/soak_report.json``-style artifact (smoke runs get
``soak_report_smoke.json`` — provenance, the sync-heal convention) and
copies the soak journal next to it for ``telemetry watch`` replay.
``--soak --smoke`` is the tier-1-safe pass pinned by
tests/test_bench_soak_smoke.py.  Env overrides: SCALECUBE_SOAK_N,
SCALECUBE_SOAK_SEED, SCALECUBE_SOAK_SEVERITY, SCALECUBE_SOAK_SEGMENT,
SCALECUBE_SOAK_SEGMENTS, SCALECUBE_SOAK_ROUNDS (round target — rounded
UP to whole segments so the compile-flat invariant stays meaningful),
SCALECUBE_SOAK_TIMEOUT, SCALECUBE_SOAK_ARTIFACT.

Env overrides for debugging: SCALECUBE_BENCH_N, SCALECUBE_BENCH_ROUNDS,
SCALECUBE_BENCH_DELIVERY, SCALECUBE_BENCH_SKIP_CANARY,
SCALECUBE_BENCH_COMPACT (=1: the capacity-oriented compact carry layout,
SwimParams.compact_carry), SCALECUBE_BENCH_ROUNDS_PER_STEP (scan round
fusion, SwimParams.rounds_per_step; default resolves per backend — 4
off-CPU, 1 on XLA:CPU where unrolling measured slower),
SCALECUBE_TPU_TRACE_SEGMENT_ROUNDS (overlapped-offload segment length;
default: a quarter of the timed window).
"""

import argparse
import json
import os
import sys
import time
import traceback

NORTH_STAR_RATE = 1e6 * 1e4 / (3600.0 * 8)  # member-rounds/sec/chip

SMOKE = False  # set by main() from --smoke; rescales the module knobs

N_MEMBERS = int(os.environ.get("SCALECUBE_BENCH_N", 1_000_000))
# "full" = full-view mode (K == N, exact reference semantics, O(N^2) state).
_subj = os.environ.get("SCALECUBE_BENCH_SUBJECTS", "16")
N_SUBJECTS = None if _subj == "full" else int(_subj)
# 1000-round timed window: each jit invocation pays ~0.1 s of dispatch
# through the tunnelled TPU link, which at 200 rounds depressed the
# measured rate ~12% below the device's steady state (~3.1e8 vs 3.54e8
# member-rounds/s at 1M).  The real workloads scan thousands of rounds
# per call, so the long window is the honest steady-state measure.
BENCH_ROUNDS = int(os.environ.get("SCALECUBE_BENCH_ROUNDS", 1000))
DELIVERY = os.environ.get("SCALECUBE_BENCH_DELIVERY", "shift")
COMPACT = os.environ.get("SCALECUBE_BENCH_COMPACT", "") == "1"
# Scan round fusion (SwimParams.rounds_per_step): K ticks per scan step,
# bit-identical outputs — applied to BOTH timed paths.  Unset = chosen
# per backend by measurement: 4 off-CPU (amortizes per-step scan
# dispatch/carry fix-ups), 1 on XLA:CPU, where BOTH the native
# ``lax.scan(..., unroll=K)`` and the manual K-unrolled body measured
# SLOWER than the plain scan (untraced ~1.3x, traced ~3x at K=4,
# N=256..4096) — the same backend-priced-differently pattern as
# compact_carry/int16_wire.
_RPS_ENV = os.environ.get("SCALECUBE_BENCH_ROUNDS_PER_STEP")
ROUNDS_PER_STEP = int(_RPS_ENV) if _RPS_ENV else None


def resolve_rounds_per_step():
    """Backend-dependent default (module comment); call after init."""
    global ROUNDS_PER_STEP
    if ROUNDS_PER_STEP is None:
        import jax

        ROUNDS_PER_STEP = 1 if jax.default_backend() == "cpu" else 4
    return ROUNDS_PER_STEP


CANARY_N = 4096
# Traced telemetry scenario size cap (events scale ~2N; trace capacity is
# telemetry.trace.DEFAULT_CAPACITY = 65536, so 4096 leaves >8x headroom —
# the "zero drops at default capacity" contract).
TELEMETRY_N = 4096
TELEMETRY_CRASH_AT = 10

# The --alarms --smoke breach threshold.  The smoke drill geometry
# (n=24, an eighth of the ids pulsed = 3 members vs the full drill's 6)
# cycles false suspicions at lower per-observer rates than the full
# n=48 drill that calibrated telemetry.alarms.DEFAULT_FP_THRESHOLD, so
# the smoke preset rescales the threshold like every other smoke knob:
# at pulse_loss=0.8 under the smoke default seed 7 the healthy arm's
# pulse windows peak at 1.10 and the breach arm's first pulse window
# measures 1.26 — 1.18 splits that gap (seed-specific on purpose: the
# smoke pass is a fixed-seed determinism pin, not a sweep; changing
# SCALECUBE_ALARM_SEED means recalibrating SCALECUBE_ALARM_THRESHOLD).
SMOKE_ALARM_THRESHOLD = 1.18


def apply_smoke_preset():
    """CPU-safe fast path: small N, short windows, no canary.  Explicit
    env overrides still win (same precedence as the full bench)."""
    global SMOKE, N_MEMBERS, BENCH_ROUNDS, TELEMETRY_N
    SMOKE = True
    N_MEMBERS = int(os.environ.get("SCALECUBE_BENCH_N", 1024))
    BENCH_ROUNDS = int(os.environ.get("SCALECUBE_BENCH_ROUNDS", 80))
    TELEMETRY_N = min(TELEMETRY_N, 256)
    os.environ.setdefault("SCALECUBE_BENCH_SKIP_CANARY", "1")


def log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def init_backend():
    """jax.devices() with retries; fall back to CPU if TPU init fails."""
    import jax

    from scalecube_cluster_tpu.utils import runlog
    cache = runlog.enable_compilation_cache()
    if cache:
        log(f"xla compilation cache at {cache}")

    last_err = None
    for attempt in range(3):
        try:
            devs = jax.devices()
            log(f"backend ok (attempt {attempt + 1}): {devs}")
            return jax, jax.default_backend()
        except RuntimeError as e:  # backend init failure (e.g. tunnel down)
            last_err = e
            log(f"backend init failed (attempt {attempt + 1}): {e}")
            time.sleep(5.0 * (attempt + 1))
    log("falling back to CPU backend")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    devs = jax.devices("cpu")
    log(f"cpu fallback devices: {devs}")
    return jax, "cpu(fallback)"


def bench_workload(n_members):
    """The shared (params, world, key) of every timed path — traced and
    untraced must measure the SAME program modulo the trace, or the
    overhead ratio is meaningless."""
    import jax

    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.models import swim

    params = swim.SwimParams.from_config(
        ClusterConfig.default(),
        n_members=n_members,
        n_subjects=N_SUBJECTS,
        loss_probability=0.02,
        per_subject_metrics=True,
        delivery=DELIVERY,
        compact_carry=COMPACT,
        rounds_per_step=resolve_rounds_per_step(),
    )
    # Crash early enough that the SUSPECTED wave completes inside the
    # warmup window even on the 80-round smoke config: the timed window
    # then measures the representative telemetry-on steady state (the
    # wave itself is timed at full scale, where warmup spans it anyway).
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=10)
    return params, world, jax.random.key(0)


def timed_run(jax, n_members, rounds, label):
    """Compile + steady-state-time an untraced run; returns
    (member-rounds/sec, metrics traces of the timed window).

    The timed region is wrapped in ``runlog.profiled`` — a no-op unless
    ``SCALECUBE_TPU_PROFILE_DIR`` is set, in which case a ``jax.profiler``
    step trace lands there (the input to experiments/profile_roofline.py's
    kernel table), and the run's protocol counters are digested through
    ``runlog.log_metrics_summary`` (the reference-style per-period logs,
    SURVEY.md §5.1).
    """
    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.utils import runlog

    def force(state):
        return runlog.completion_barrier(state.status)

    rlog = runlog.get_logger("bench")
    params, world, key = bench_workload(n_members)

    t0 = time.perf_counter()
    state = swim.initial_state(params, world)
    # Warm-up compiles the exact (params, n_rounds, state-provided)
    # signature the timed call uses, so the timed region is steady state.
    state, _ = swim.run(key, params, world, rounds, state=state,
                        start_round=0)
    force(state)
    log(f"{label}: compile+first-run took {time.perf_counter() - t0:.1f}s")

    # Short smoke windows are host-noise-sensitive (±40% per-window
    # swings measured on a shared box): time several consecutive
    # steady-state windows and keep the best (the full bench's
    # 1000-round window stays a single measurement, comparable with the
    # round-1..5 artifacts).
    reps = 6 if SMOKE else 1
    elapsed, metrics = None, None
    for rep in range(reps):
        t0 = time.perf_counter()
        with runlog.profiled(rlog):
            state, metrics = swim.run(
                key, params, world, rounds, state=state,
                start_round=rounds * (1 + rep),
            )
            force(state)
        elapsed = (time.perf_counter() - t0 if elapsed is None
                   else min(elapsed, time.perf_counter() - t0))
    rate = n_members * rounds / elapsed
    log(f"{label}: {rounds} rounds in {elapsed:.3f}s (best of {reps}) -> "
        f"{rate:.3e} member-rounds/sec")
    # The logged/returned metrics are the LAST rep's window, which
    # started at rounds * reps.
    runlog.log_metrics_summary(rlog, metrics, round_offset=rounds * reps)
    # Sanity: the crash at round 10 must eventually be noticed (DEAD
    # views need the ~suspicion_rounds timeout, so expect 0 on short
    # smoke windows where only the SUSPECT wave fits).
    dead_total = int(jax.numpy.asarray(metrics["dead"]).sum())
    log(f"{label}: dead-view observer-rounds in window: {dead_total}")
    return rate, metrics


def traced_window_policy(n_members, rounds):
    """(segment_rounds, trace_capacity) of a timed traced window —
    shared by timed_traced_run and timed_both so --traced measures the
    SAME program as the default both-paths mode.  Segment default: a
    quarter of the window (>= 4 overlap segments even on smoke); env
    override wins.  Per-SEGMENT capacity scales with the workload: the
    scan carries (and functionally updates) the whole lane buffer every
    event round, so at small N an oversized buffer IS the traced
    overhead (65536 slots are ~20x the entire N=256 carry)."""
    from scalecube_cluster_tpu.telemetry import sink as tsink
    from scalecube_cluster_tpu.telemetry import trace as ttrace

    seg_env = os.environ.get(tsink.TRACE_SEGMENT_ENV)
    seg = int(seg_env) if seg_env else max(1, rounds // 4)
    cap = min(ttrace.DEFAULT_CAPACITY, max(4 * n_members, 4096))
    return seg, cap


def timed_traced_run(jax, n_members, rounds, label):
    """The SAME timed window with telemetry ON, through the segmented
    overlapped-offload driver (telemetry.sink.stream_traced_run).

    The measured time INCLUDES the device→host trace offload (that cost
    is the point of the overlap) but not host-side event decoding
    (``decode=False`` — python-object construction is a consumer cost,
    not a device-pipeline one).  Returns member-rounds/sec.
    """
    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.telemetry import sink as tsink
    from scalecube_cluster_tpu.utils import runlog

    def force(state):
        return runlog.completion_barrier(state.status)

    params, world, key = bench_workload(n_members)
    seg, cap = traced_window_policy(n_members, rounds)

    t0 = time.perf_counter()
    state = swim.initial_state(params, world)
    state, _ = tsink.stream_traced_run(
        key, params, world, rounds, state=state, segment_rounds=seg,
        trace_capacity=cap, decode=False,
    )
    force(state)
    log(f"{label}: compile+first-run took {time.perf_counter() - t0:.1f}s")

    reps = 6 if SMOKE else 1          # best-of policy mirrors timed_run
    elapsed, res = None, None
    for rep in range(reps):
        t0 = time.perf_counter()
        state, res = tsink.stream_traced_run(
            key, params, world, rounds, state=state,
            start_round=rounds * (1 + rep),
            segment_rounds=seg, trace_capacity=cap, decode=False,
        )
        force(state)
        elapsed = (time.perf_counter() - t0 if elapsed is None
                   else min(elapsed, time.perf_counter() - t0))
    rate = n_members * rounds / elapsed
    log(f"{label}: {rounds} rounds in {elapsed:.3f}s (best of {reps}) -> "
        f"{rate:.3e} member-rounds/sec traced ({res.n_segments} segments "
        f"of {seg}, {res.recorded} events, {res.dropped} dropped)")
    return rate


def interleaved_best_of(run_a, run_b, reps):
    """Best-of wall-times of two measurement callables, with their
    windows INTERLEAVED (a window, b window, repeat) and the order
    ALTERNATED each rep: host-speed drift — frequency scaling, a noisy
    neighbor calming down — then biases both rates equally instead of
    whichever path happened to run second (which a back-to-back
    measurement mis-reads as a negative overhead), and alternation
    cancels the residual whoever-runs-second-is-warmer bias within a
    rep pair.  ``run_a(rep)`` / ``run_b(rep)`` each execute one full
    timed window (including any completion barrier).  Returns
    ``(best_a_seconds, best_b_seconds)``.

    The one timing discipline every paired comparison shares:
    traced-vs-untraced (timed_both), metered-vs-unmetered
    (run_metrics_bench), pipelined-vs-serial (run_multichip_bench).
    """
    best = {"a": None, "b": None}
    for rep in range(reps):
        pair = ((("a", run_a), ("b", run_b)) if rep % 2 == 0
                else (("b", run_b), ("a", run_a)))
        for tag, fn in pair:
            t0 = time.perf_counter()
            fn(rep)
            dt = time.perf_counter() - t0
            best[tag] = dt if best[tag] is None else min(best[tag], dt)
    return best["a"], best["b"]


def timed_both(jax, n_members, rounds, label):
    """Both timed paths on the ``interleaved_best_of`` window
    discipline.  Returns (untraced_rate, untraced_metrics, traced_rate).
    """
    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.telemetry import sink as tsink
    from scalecube_cluster_tpu.telemetry import trace as ttrace
    from scalecube_cluster_tpu.utils import runlog

    def force(state):
        return runlog.completion_barrier(state.status)

    rlog = runlog.get_logger("bench")
    params, world, key = bench_workload(n_members)
    seg, cap = traced_window_policy(n_members, rounds)

    t0 = time.perf_counter()
    u_state = swim.initial_state(params, world)
    u_state, _ = swim.run(key, params, world, rounds, state=u_state,
                          start_round=0)
    force(u_state)
    t_state = swim.initial_state(params, world)
    t_state, _ = tsink.stream_traced_run(
        key, params, world, rounds, state=t_state, segment_rounds=seg,
        trace_capacity=cap, decode=False,
    )
    force(t_state)
    log(f"{label}: compile+first-run (both paths) took "
        f"{time.perf_counter() - t0:.1f}s")

    reps = 6 if SMOKE else 1
    u_metrics, res = None, None

    def run_untraced(rep):
        nonlocal u_state, u_metrics
        with runlog.profiled(rlog):
            u_state, u_metrics = swim.run(
                key, params, world, rounds, state=u_state,
                start_round=rounds * (1 + rep),
            )
            force(u_state)

    def run_traced_seg(rep):
        nonlocal t_state, res
        t_state, res = tsink.stream_traced_run(
            key, params, world, rounds, state=t_state,
            start_round=rounds * (1 + rep), segment_rounds=seg,
            trace_capacity=cap, decode=False,
        )
        force(t_state)

    u_best, t_best = interleaved_best_of(run_untraced, run_traced_seg, reps)
    u_rate = n_members * rounds / u_best
    t_rate = n_members * rounds / t_best
    log(f"{label}: untraced {u_best:.3f}s vs traced {t_best:.3f}s per "
        f"{rounds}-round window (best of {reps}, interleaved) -> "
        f"{u_rate:.3e} / {t_rate:.3e} member-rounds/sec "
        f"({res.n_segments} offload segments of {seg}, {res.recorded} "
        f"events, {res.dropped} dropped)")
    # The logged/returned metrics are the LAST rep's window, which
    # started at rounds * reps.
    runlog.log_metrics_summary(rlog, u_metrics, round_offset=rounds * reps)
    dead_total = int(jax.numpy.asarray(u_metrics["dead"]).sum())
    log(f"{label}: dead-view observer-rounds in window: {dead_total}")
    return u_rate, u_metrics, t_rate


def dissemination_at_scale(jax, n_members):
    """Rounds-to-full-dissemination at scale (BASELINE.json's 2nd metric).

    A graceful leave at round 10 emits one DEAD@inc+1 record whose
    infection-style spread to all N live observers is timed in rounds —
    pure dissemination, no suspicion-timeout wait.  Compare with the
    analytic window repeat_mult*ceil(log2(n+1)) (ClusterMath.java:111-113).
    """
    import numpy as np

    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.models import swim

    params = swim.SwimParams.from_config(
        ClusterConfig.default(),
        n_members=n_members,
        n_subjects=N_SUBJECTS,
        delivery=DELIVERY,
        rounds_per_step=resolve_rounds_per_step(),
    )
    world = swim.SwimWorld.healthy(params).with_leave(3, at_round=10)
    _, metrics = swim.run(jax.random.key(1), params, world, 60)
    alive_view = np.asarray(metrics["alive"])[:, 3]
    gone = np.flatnonzero(alive_view == 0)
    rounds = int(gone[0]) - 10 if gone.size else -1
    log(f"dissemination@{n_members}: leave@10 fully known by round "
        f"{int(gone[0]) if gone.size else 'never'} -> {rounds} rounds")
    return rounds


def telemetry_scenario(jax):
    """The traced crash scenario: a crash at round k observed through the
    on-device event trace (models/swim.run_traced) and digested into
    detection/removal latency histograms — distribution-level
    observability where the bench prints could only report means.

    Runs at min(N_MEMBERS, TELEMETRY_N) so the ~2N SUSPECTED+REMOVED
    events sit far below the default trace capacity (zero drops is part
    of the contract, asserted in the manifest summary).  Driven through
    the segmented overlapped-offload path (stream_traced_run) so every
    bench invocation — including --smoke on CPU — exercises the fused +
    traced + overlapped pipeline end to end.
    """
    import numpy as np

    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.telemetry import sink as tsink
    from scalecube_cluster_tpu.telemetry import trace as ttrace

    n = min(N_MEMBERS, TELEMETRY_N)
    # The sped-up protocol timing (the test preset): the suspicion
    # timeout resolves in tens of rounds, so the scenario stays cheap.
    cfg = ClusterConfig.default().replace(
        gossip_interval=100, ping_interval=200, ping_timeout=100,
        sync_interval=1_000, suspicion_mult=3,
    )
    params = swim.SwimParams.from_config(
        cfg, n_members=n, n_subjects=min(16, n), delivery=DELIVERY,
        rounds_per_step=resolve_rounds_per_step(),
    )
    crash_node = 3
    world = swim.SwimWorld.healthy(params).with_crash(
        crash_node, at_round=TELEMETRY_CRASH_AT
    )
    rounds = params.suspicion_rounds + 80
    # >= 3 segments so the dispatch-ahead/harvest-behind overlap really
    # cycles (env still overrides through stream_traced_run's default).
    _, res = tsink.stream_traced_run(
        jax.random.key(7), params, world, rounds,
        segment_rounds=max(1, rounds // 3),
    )
    hists = ttrace.latency_histograms(res.telemetry, world)
    events = res.events
    metrics = res.metrics
    log(f"telemetry@{n}: {res.recorded} events recorded, "
        f"{res.dropped} dropped (capacity {res.capacity}, "
        f"{res.n_segments} offload segments)")
    return {
        "params": params,
        "metrics": metrics,
        "events": events,
        "recorded": res.recorded,
        "dropped": res.dropped,
        "capacity": res.capacity,
        "edges": np.asarray(hists["edges"]).tolist(),
        "detection_buckets": np.asarray(hists["detection"])[crash_node].tolist(),
        "removal_buckets": np.asarray(hists["removal"])[crash_node].tolist(),
        "detection_undetected": int(
            np.asarray(hists["detection_undetected"])[crash_node]
        ),
        "crash_node": crash_node,
        "crash_at": TELEMETRY_CRASH_AT,
        "n_members": n,
        "rounds": rounds,
    }


def write_telemetry(scenario, main_metrics):
    """JSONL run manifest + (gated) TensorBoard export; returns the
    manifest path."""
    import numpy as np

    from scalecube_cluster_tpu.telemetry import sink as tsink

    out_dir = (os.environ.get(tsink.TELEMETRY_DIR_ENV)
               or os.path.join("artifacts", "telemetry"))
    sink = tsink.TelemetrySink(
        out_dir, prefix="bench-smoke" if SMOKE else "bench"
    )
    sink.write_manifest(
        params=scenario["params"],
        workload={
            "bench_n_members": N_MEMBERS,
            "bench_rounds": BENCH_ROUNDS,
            "delivery": DELIVERY,
            "compact_carry": COMPACT,
            "rounds_per_step": resolve_rounds_per_step(),
            "smoke": SMOKE,
        },
        scenario={
            "kind": "crash",
            "n_members": scenario["n_members"],
            "crash_node": scenario["crash_node"],
            "crash_round": scenario["crash_at"],
            "rounds": scenario["rounds"],
        },
    )
    if main_metrics is not None:
        # The metrics are the last best-of rep's window (timed_run /
        # timed_both): it started at BENCH_ROUNDS * reps.
        reps = 6 if SMOKE else 1
        sink.write_counters(main_metrics, round_offset=BENCH_ROUNDS * reps,
                            label="main_timed_window")
    sink.write_counters(scenario["metrics"], label="telemetry_scenario")
    hist_meta = dict(subject=scenario["crash_node"],
                     fault_round=scenario["crash_at"])
    sink.write_histogram("detection_latency_rounds", scenario["edges"],
                         scenario["detection_buckets"],
                         undetected=scenario["detection_undetected"],
                         **hist_meta)
    sink.write_histogram("removal_latency_rounds", scenario["edges"],
                         scenario["removal_buckets"], **hist_meta)
    # Fraction-informed-by-round: the dissemination curve of the death
    # notice, from the scenario's per-subject dead counts.
    dead = np.asarray(scenario["metrics"]["dead"])[:, scenario["crash_node"]]
    sink.write_curve(
        "fraction_informed",
        tsink.fraction_informed_curve(dead, scenario["n_members"] - 1),
        subject=scenario["crash_node"],
    )
    sink.write_events(scenario["events"], dropped=scenario["dropped"])
    sink.write_summary(
        events_recorded=scenario["recorded"],
        event_drops=scenario["dropped"],
        trace_capacity=scenario["capacity"],
    )
    sink.close()
    tsink.maybe_export_tensorboard(
        sink.run_id,
        scalars={
            "telemetry/dead_views": scenario["metrics"]["dead"],
            "telemetry/messages_gossip":
                scenario["metrics"]["messages_gossip"],
            "telemetry/false_positives":
                scenario["metrics"]["false_positives"],
        },
        histograms={
            "telemetry/detection_latency_rounds":
                (scenario["edges"], scenario["detection_buckets"]),
            "telemetry/removal_latency_rounds":
                (scenario["edges"], scenario["removal_buckets"]),
        },
    )
    log(f"telemetry manifest written to {sink.path}")
    return sink.path


def apply_regress_gate(result, patterns):
    """The in-bench cross-run regression gate, shared by --metrics /
    --multichip / --sync (the same check ``python -m
    scalecube_cluster_tpu.telemetry regress`` serves): walk the given
    artifact files/globs and report the verdict in
    ``result["regress"]`` — a regression is reported in the JSON line,
    it never voids the measurement (never-ship-empty)."""
    from scalecube_cluster_tpu.telemetry import query as tquery

    gate_paths = [p for p in tquery.expand_paths(patterns)
                  if os.path.exists(p)]
    ok, checks = tquery.regress(gate_paths)
    failed = [c for c in checks if c.get("ok") is False]
    log(f"regress gate over {len(gate_paths)} artifacts: "
        f"{'PASS' if ok else 'REGRESSION ' + json.dumps(failed)}")
    result["regress"] = {
        "ok": ok,
        "artifacts": len(gate_paths),
        "failed_checks": failed,
    }


def run_chaos_campaign():
    """The --chaos mode: a seeded generated-scenario campaign through
    the in-jit invariant monitor, one JSON line out (the same
    never-ship-empty contract as the throughput bench)."""
    result = {
        "metric": "chaos_campaign_green_scenarios",
        "value": None,
        "unit": "green scenarios",
        "smoke": SMOKE,
    }
    try:
        jax, platform = init_backend()  # noqa: F841 — backend retry/fallback
        result["platform"] = platform

        from scalecube_cluster_tpu import chaos
        from scalecube_cluster_tpu.telemetry import sink as tsink

        n = int(os.environ.get("SCALECUBE_CHAOS_N",
                               24 if SMOKE else 32))
        n_scen = int(os.environ.get("SCALECUBE_CHAOS_SCENARIOS",
                                    6 if SMOKE else 21))
        seed = int(os.environ.get("SCALECUBE_CHAOS_SEED", 100))
        scens = chaos.generate_campaign(seed=seed, n_scenarios=n_scen,
                                        n=n)
        t0 = time.time()
        with tsink.TelemetrySink.from_env(
                default_dir=os.path.join("artifacts", "telemetry"),
                prefix="chaos-smoke" if SMOKE else "chaos") as sink:
            campaign = chaos.run_campaign(scens, seed=seed, sink=sink)
        summary = campaign.summary()
        for v in campaign.verdicts:
            log(f"chaos {v.scenario.name}: "
                f"{'green' if v.green else 'RED ' + v.repro()}")
        log(f"chaos campaign: {summary['green_scenarios']}/"
            f"{summary['scenarios']} green in {time.time() - t0:.1f}s")
        result.update(
            value=summary["green_scenarios"],
            scenarios=summary["scenarios"],
            green=summary["green"],
            violations_by_code=summary["violations_by_code"],
            failing_repros=summary["failing_repros"],
            n_members=n,
            seed=seed,
            manifest=campaign.manifest_path,
        )
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_resilience_drill():
    """The --resilience mode: the subprocess kill-injection drill over
    all three run shapes + the corruption-fallback drill, one JSON line
    out (the never-ship-empty contract).  Forces CPU: this is a
    correctness harness — the children must not fight over an attached
    TPU, and the guarantees under test are backend-independent."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    result = {
        "metric": "resilience_drill_green_shapes",
        "value": None,
        "unit": "green shapes",
        "smoke": SMOKE,
        "platform": "cpu(forced)",
    }
    try:
        import tempfile

        from scalecube_cluster_tpu.resilience import harness as rh

        shapes = tuple(
            s for s in os.environ.get(
                "SCALECUBE_RESILIENCE_SHAPES",
                "plain,traced,monitored").split(",") if s
        )
        overrides = {
            "n_members": int(os.environ.get(
                "SCALECUBE_RESILIENCE_N", 16 if SMOKE else 32)),
            "n_rounds": int(os.environ.get(
                "SCALECUBE_RESILIENCE_ROUNDS", 30 if SMOKE else 96)),
            "segment_rounds": int(os.environ.get(
                "SCALECUBE_RESILIENCE_SEGMENT", 10 if SMOKE else 16)),
        }
        n_kills = int(os.environ.get("SCALECUBE_RESILIENCE_KILLS",
                                     1 if SMOKE else 3))
        seed = int(os.environ.get("SCALECUBE_RESILIENCE_SEED", 1234))
        t0 = time.time()
        with tempfile.TemporaryDirectory(
                prefix="resilience-drill-") as workdir:
            report = rh.run_drill(
                shapes, workdir, kill_seed=seed, n_kills=n_kills,
                cfg_overrides=overrides,
                extra_env={"JAX_PLATFORMS": "cpu"},
            )
        for shape, verdict in report["shapes"].items():
            log(f"resilience {shape}: "
                f"{'green' if verdict['ok'] else 'RED ' + json.dumps(verdict)}"
                f" (kills {verdict.get('kills')})")
        log(f"resilience corruption drill: "
            f"{'green' if report['corruption']['ok'] else 'RED'}")
        log(f"resilience drill: green={report['green']} in "
            f"{time.time() - t0:.1f}s")
        result.update(
            value=sum(1 for v in report["shapes"].values() if v["ok"]),
            shapes_run=list(report["shapes"]),
            green=report["green"],
            n_kills=n_kills,
            kill_seed=seed,
            workload=overrides,
            verdicts={
                s: {k: v[k] for k in ("ok", "bit_identical",
                                      "journal_complete", "events_match",
                                      "journal_segments", "kills")
                    if k in v}
                for s, v in report["shapes"].items()
            },
            corruption={k: report["corruption"][k]
                        for k in ("ok", "loaded_generation", "fallbacks")
                        if k in report["corruption"]},
        )
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_metrics_bench():
    """The --metrics mode: metrics-on vs metrics-off on the bench
    workload (interleaved best-of windows, the timed_both discipline)
    plus a windowed metered run flushed through the JSONL pipeline and
    digested into health SLOs.  One JSON line out, a BENCH_*-style
    artifact recording the overhead ratio (the never-ship-empty
    contract)."""
    result = {
        "metric": "swim_metrics_overhead_ratio",
        "value": None,
        "unit": "unmetered/metered rate ratio",
        "smoke": SMOKE,
    }
    artifact = os.environ.get("SCALECUBE_METRICS_ARTIFACT") or os.path.join(
        "artifacts", "metrics_smoke.json" if SMOKE else "metrics_bench.json"
    )
    try:
        jax, platform = init_backend()
        result["platform"] = platform

        from scalecube_cluster_tpu.models import swim
        from scalecube_cluster_tpu.telemetry import metrics as tmetrics
        from scalecube_cluster_tpu.telemetry import query as tquery
        from scalecube_cluster_tpu.telemetry import sink as tsink
        from scalecube_cluster_tpu.utils import runlog

        def force(state):
            return runlog.completion_barrier(state.status)

        params, world, key = bench_workload(N_MEMBERS)
        spec = tmetrics.MetricsSpec.default()
        rounds = BENCH_ROUNDS

        t0 = time.perf_counter()
        u_state = swim.initial_state(params, world)
        u_state, _ = swim.run(key, params, world, rounds, state=u_state,
                              start_round=0)
        force(u_state)
        m_state = swim.initial_state(params, world)
        m_state, ms, _ = swim.run_metered(key, params, world, rounds,
                                          spec=spec, state=m_state,
                                          start_round=0)
        force(m_state)
        log(f"metrics@{N_MEMBERS}: compile+first-run (both paths) took "
            f"{time.perf_counter() - t0:.1f}s")

        reps = 6 if SMOKE else 3

        def run_plain(rep):
            nonlocal u_state
            u_state, _ = swim.run(key, params, world, rounds,
                                  state=u_state,
                                  start_round=rounds * (1 + rep))
            force(u_state)

        def run_metered(rep):
            nonlocal m_state, ms
            m_state, ms, _ = swim.run_metered(
                key, params, world, rounds, spec=spec, state=m_state,
                start_round=rounds * (1 + rep), metrics_state=ms,
            )
            force(m_state)

        # The shared interleave + order-alternation window discipline
        # (interleaved_best_of), so the ratio measures the registry,
        # not whichever path ran on the warmer core.
        u_best, m_best = interleaved_best_of(run_plain, run_metered, reps)
        u_rate = N_MEMBERS * rounds / u_best
        m_rate = N_MEMBERS * rounds / m_best
        ratio = round(u_rate / m_rate, 4)
        log(f"metrics@{N_MEMBERS}: unmetered {u_best:.3f}s vs metered "
            f"{m_best:.3f}s per {rounds}-round window (best of {reps}, "
            f"interleaved) -> overhead ratio {ratio}")
        result.update(
            value=ratio,
            metrics_overhead_ratio=ratio,
            unmetered_member_rounds_per_sec=round(u_rate, 1),
            metered_member_rounds_per_sec=round(m_rate, 1),
            n_members=N_MEMBERS,
            rounds_timed=rounds,
            delivery=DELIVERY,
            rounds_per_step=resolve_rounds_per_step(),
        )

        # The windowed health run: registry flushes through the JSONL
        # pipeline, folded back into SLOs by the query layer.
        out_dir = (os.environ.get(tsink.TELEMETRY_DIR_ENV)
                   or os.path.join("artifacts", "telemetry"))
        sink = tsink.TelemetrySink(
            out_dir, prefix="metrics-smoke" if SMOKE else "metrics")
        sink.write_manifest(params=params, workload={
            "mode": "metrics",
            "bench_n_members": N_MEMBERS,
            "bench_rounds": rounds,
            "delivery": DELIVERY,
            "smoke": SMOKE,
        })
        _, windows = tmetrics.stream_metered_run(
            key, params, world, rounds, sink=sink,
            window_rounds=max(1, rounds // 4),
        )
        sink.write_summary(metrics_windows=len(windows))
        sink.close()
        report = tquery.load_report(sink.path)
        slos = tquery.compute_slos(report)
        log(f"metrics manifest written to {sink.path} "
            f"({len(windows)} windows)")
        result.update(
            manifest=sink.path,
            windows=len(windows),
            counters=report.counters,
            gauges=report.gauges,
            slos=slos,
        )

        art = {
            "metric": "metered_vs_unmetered_member_rounds_per_sec",
            "unmetered": result["unmetered_member_rounds_per_sec"],
            "metered": result["metered_member_rounds_per_sec"],
            "metrics_overhead_ratio": ratio,
            "n_members": N_MEMBERS,
            "rounds_timed": rounds,
            "rounds_per_step": resolve_rounds_per_step(),
            "delivery": DELIVERY,
            "smoke": SMOKE,
            "platform": platform,
            "counters": report.counters,
            "gauges": report.gauges,
            "slos": slos,
        }
        os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
        result["artifact"] = artifact
        log(f"metrics-overhead artifact written to {artifact}")

        apply_regress_gate(result, ["BENCH_*.json", artifact])
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_multichip_bench():
    """The --multichip mode: the sharded scatter run on a real device
    mesh, pipelined ICI delivery (parallel/mesh._pipelined_rounds)
    measured against the serial in-round combine on the
    ``interleaved_best_of`` window discipline, plus a bit-identity
    probe of the two paths.  One JSON line out with REAL per-chip
    throughput (member-rounds/sec/chip), the mesh shape and the
    pipelined-vs-serial ratio, and a MULTICHIP_*-style artifact
    (default ``MULTICHIP_r06.json``; override with
    SCALECUBE_MULTICHIP_ARTIFACT) — replacing the contentless
    ``{"rc":0,"ok":true}`` stubs of rounds 1-5.  The regress gate
    (telemetry/query.py) then walks the MULTICHIP trajectory like the
    BENCH one.

    ``--smoke`` forces CPU with a virtual 8-device mesh (the
    tests/conftest.py trick) so the full pipeline — both compiled
    paths, parity probe, artifact, regress gate — runs anywhere; env
    overrides: SCALECUBE_MULTICHIP_DEVICES, SCALECUBE_MULTICHIP_N,
    SCALECUBE_MULTICHIP_ROUNDS, SCALECUBE_MULTICHIP_ARTIFACT.
    """
    result = {
        "metric": "swim_multichip_member_rounds_per_sec_per_chip",
        "value": None,
        "unit": "member-rounds/sec/chip",
        "smoke": SMOKE,
    }
    artifact = (os.environ.get("SCALECUBE_MULTICHIP_ARTIFACT")
                or "MULTICHIP_r06.json")
    try:
        # Device-count resolution must happen BEFORE the first jax
        # import: a CPU backend only exposes multiple devices through
        # xla_force_host_platform_device_count.
        want_dev = int(os.environ.get("SCALECUBE_MULTICHIP_DEVICES",
                                      "8" if SMOKE else "0") or 0)
        if SMOKE:
            os.environ["JAX_PLATFORMS"] = "cpu"
        if want_dev and os.environ.get("JAX_PLATFORMS",
                                       "").startswith("cpu"):
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags
                    + f" --xla_force_host_platform_device_count={want_dev}"
                ).strip()
        jax, platform = init_backend()
        result["platform"] = platform

        import numpy as np

        from scalecube_cluster_tpu.config import ClusterConfig
        from scalecube_cluster_tpu.models import swim
        from scalecube_cluster_tpu.parallel import compat, traffic
        from scalecube_cluster_tpu.parallel import mesh as pmesh
        from scalecube_cluster_tpu.utils import runlog

        if not compat.HAS_SHARD_MAP:
            raise NotImplementedError(compat.SKIP_REASON)

        def force(state):
            return runlog.completion_barrier(state.status)

        n_dev = want_dev or len(jax.devices())
        mesh = pmesh.make_mesh(n_dev)
        n_members = int(os.environ.get(
            "SCALECUBE_MULTICHIP_N", 1024 if SMOKE else N_MEMBERS))
        # Rows must divide the mesh: round down to a multiple of it.
        n_members = max(n_dev, n_members - n_members % n_dev)
        rounds = int(os.environ.get(
            "SCALECUBE_MULTICHIP_ROUNDS", 48 if SMOKE else BENCH_ROUNDS))
        # Scatter delivery: the mode whose single inbox pmax the
        # pipeline double-buffers (sharded shift mode already overlaps
        # per-channel ppermutes; SwimParams docstring).
        params = swim.SwimParams.from_config(
            ClusterConfig.default(), n_members=n_members,
            n_subjects=N_SUBJECTS, loss_probability=0.02,
            delivery="scatter",
        )
        world = swim.SwimWorld.healthy(params).with_crash(3, at_round=10)
        key = jax.random.key(0)
        log(f"multichip: mesh {list(mesh.devices.shape)} on {platform}, "
            f"N={n_members}, {rounds}-round windows, "
            f"per-round ICI bytes/device ~ "
            f"{traffic.scatter_ici_bytes_per_device_round(params, n_dev)}")

        # Compile + first run of both paths doubles as the bit-identity
        # probe: the pipelined combine must be a pure scheduling change
        # (the test suite pins this exhaustively; the bench re-checks
        # its own exact config over the full timed window), and reusing
        # the first-run outputs as the probe inputs means two XLA
        # compilations instead of four.
        t0 = time.perf_counter()
        s_state, m_ser = pmesh.shard_run(key, params, world, rounds, mesh,
                                         pipelined=False)
        force(s_state)
        p_state, m_pip = pmesh.shard_run(key, params, world, rounds, mesh,
                                         pipelined=True)
        force(p_state)
        log(f"multichip: compile+first-run (both paths) took "
            f"{time.perf_counter() - t0:.1f}s")
        bit_identical = bool(
            all(np.array_equal(np.asarray(m_ser[k2]), np.asarray(m_pip[k2]))
                for k2 in m_ser)
            and np.array_equal(np.asarray(s_state.status),
                               np.asarray(p_state.status))
            and np.array_equal(np.asarray(s_state.inc),
                               np.asarray(p_state.inc))
        )
        log(f"multichip: pipelined-vs-serial parity probe "
            f"{'OK' if bit_identical else 'DIVERGED'}")

        reps = 6 if SMOKE else 3

        def run_serial(rep):
            nonlocal s_state
            s_state, _ = pmesh.shard_run(
                key, params, world, rounds, mesh, state=s_state,
                start_round=rounds * (1 + rep), pipelined=False)
            force(s_state)

        def run_pipelined(rep):
            nonlocal p_state
            p_state, _ = pmesh.shard_run(
                key, params, world, rounds, mesh, state=p_state,
                start_round=rounds * (1 + rep), pipelined=True)
            force(p_state)

        s_best, p_best = interleaved_best_of(run_serial, run_pipelined,
                                             reps)
        s_rate = n_members * rounds / s_best / n_dev
        p_rate = n_members * rounds / p_best / n_dev
        ratio = round(p_rate / s_rate, 4)
        log(f"multichip: serial {s_best:.3f}s vs pipelined {p_best:.3f}s "
            f"per {rounds}-round window (best of {reps}, interleaved) -> "
            f"{s_rate:.3e} / {p_rate:.3e} member-rounds/sec/chip "
            f"(pipelined speedup x{ratio})")
        result.update(
            value=round(p_rate, 1),
            pipelined_member_rounds_per_sec_per_chip=round(p_rate, 1),
            serial_member_rounds_per_sec_per_chip=round(s_rate, 1),
            pipelined_speedup_ratio=ratio,
            bit_identical=bit_identical,
            n_devices=n_dev,
            mesh_shape=list(mesh.devices.shape),
            n_members=n_members,
            rounds_timed=rounds,
            delivery="scatter",
            ici_bytes_per_device_round=(
                traffic.scatter_ici_bytes_per_device_round(params, n_dev)),
        )

        art = dict(result)
        os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
        result["artifact"] = artifact
        log(f"multichip artifact written to {artifact}")

        apply_regress_gate(
            result, ["BENCH_*.json", "MULTICHIP_*.json", artifact])
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_wire_bench():
    """The --wire mode: the FUSED single-buffer scatter wire
    (SwimParams.fused_wire, the default — ALIVE flags ride the key
    word's spare bits, ONE full-height collective per round) A/B'd
    against the HEAD two-buffer path (``fused_wire=False``: int32 key +
    int8 flag pair, two collectives) on BOTH the serial in-round
    combine and the pipelined sharded run, each pair on the
    ``interleaved_best_of`` window discipline.  One JSON line out with
    the fused per-chip rate, the fused/legacy speedup ratios (the
    regress floor: fused must never run slower), the compiled-HLO
    full-height collective counts (the 1-vs-2 pin, straight from the
    program text), and the traffic model's 4-vs-5 B/slot + wire24
    headroom numbers — into an ``artifacts/wire_fused.json`` artifact
    walked by ``telemetry regress``.

    ``--smoke`` forces CPU with the virtual 8-device mesh and writes
    ``artifacts/wire_fused_smoke.json`` (never the committed artifact —
    the sync-heal convention); env overrides: SCALECUBE_WIRE_DEVICES,
    SCALECUBE_WIRE_N, SCALECUBE_WIRE_ROUNDS, SCALECUBE_WIRE_ARTIFACT.
    """
    result = {
        "metric": "swim_wire_fused_member_rounds_per_sec_per_chip",
        "value": None,
        "unit": "member-rounds/sec/chip",
        "smoke": SMOKE,
    }
    artifact = (os.environ.get("SCALECUBE_WIRE_ARTIFACT")
                or os.path.join(
                    "artifacts",
                    "wire_fused_smoke.json" if SMOKE
                    else "wire_fused.json"))
    try:
        # Device-count resolution before the first jax import (the
        # multichip rule: CPU only exposes multiple devices through
        # xla_force_host_platform_device_count).
        want_dev = int(os.environ.get("SCALECUBE_WIRE_DEVICES",
                                      "8" if SMOKE else "0") or 0)
        if SMOKE:
            os.environ["JAX_PLATFORMS"] = "cpu"
        if want_dev and os.environ.get("JAX_PLATFORMS",
                                       "").startswith("cpu"):
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags
                    + f" --xla_force_host_platform_device_count={want_dev}"
                ).strip()
        jax, platform = init_backend()
        result["platform"] = platform

        import dataclasses

        import numpy as np

        from scalecube_cluster_tpu.config import ClusterConfig
        from scalecube_cluster_tpu.models import swim
        from scalecube_cluster_tpu.parallel import compat, traffic
        from scalecube_cluster_tpu.parallel import mesh as pmesh
        from scalecube_cluster_tpu.utils import runlog

        if not compat.HAS_SHARD_MAP:
            raise NotImplementedError(compat.SKIP_REASON)

        def force(state):
            return runlog.completion_barrier(state.status)

        n_dev = want_dev or len(jax.devices())
        mesh = pmesh.make_mesh(n_dev)
        n_members = int(os.environ.get(
            "SCALECUBE_WIRE_N", 1024 if SMOKE else 4096))
        n_members = max(n_dev, n_members - n_members % n_dev)
        rounds = int(os.environ.get(
            "SCALECUBE_WIRE_ROUNDS", 48 if SMOKE else 128))

        def make_params(fused):
            return swim.SwimParams.from_config(
                ClusterConfig.default(), n_members=n_members,
                n_subjects=N_SUBJECTS, loss_probability=0.02,
                delivery="scatter", fused_wire=fused,
            )

        p_fused, p_legacy = make_params(True), make_params(False)
        world = swim.SwimWorld.healthy(p_fused).with_crash(3, at_round=10)
        key = jax.random.key(0)
        log(f"wire: mesh {list(mesh.devices.shape)} on {platform}, "
            f"N={n_members}, {rounds}-round windows; modeled "
            f"{traffic.scatter_wire_bytes_per_slot(p_fused)} B/slot "
            f"fused vs {traffic.scatter_wire_bytes_per_slot(p_legacy)} "
            f"legacy, {traffic.scatter_collectives_per_round(p_fused)} "
            f"vs {traffic.scatter_collectives_per_round(p_legacy)} "
            f"collectives/round")

        # Compile + first run of all four paths; within each wire the
        # pipelined-vs-serial pair doubles as the bit-identity probe
        # (the fused-vs-legacy pair is NOT claimed identical here: the
        # bench world has loss, where the documented merge-gate corner
        # may transiently differ — tests/test_wire_fused.py pins the
        # deterministic-schedule identity).
        t0 = time.perf_counter()
        states, metrics = {}, {}
        for wire, params in (("fused", p_fused), ("legacy", p_legacy)):
            for pipe in (False, True):
                s, m = pmesh.shard_run(key, params, world, rounds, mesh,
                                       pipelined=pipe)
                force(s)
                states[(wire, pipe)] = s
                metrics[(wire, pipe)] = m
        log(f"wire: compile+first-run (4 paths) took "
            f"{time.perf_counter() - t0:.1f}s")
        parity = {}
        for wire in ("fused", "legacy"):
            s_ser, s_pip = states[(wire, False)], states[(wire, True)]
            parity[wire] = bool(
                all(np.array_equal(np.asarray(metrics[(wire, False)][k2]),
                                   np.asarray(metrics[(wire, True)][k2]))
                    for k2 in metrics[(wire, False)])
                and all(np.array_equal(
                    np.asarray(getattr(s_ser, f.name)),
                    np.asarray(getattr(s_pip, f.name)))
                    for f in dataclasses.fields(s_ser))
            )
        log(f"wire: pipelined==serial parity probe "
            f"{'OK' if all(parity.values()) else 'DIVERGED ' + repr(parity)}")

        # The compiled-program pin: full-height [N, K] all-reduce
        # instructions in the SERIAL program text — 1 fused vs 2
        # legacy.  Counting only the [N, K]-shaped combines keeps the
        # pin lowering-neutral (metric psums are [K]/scalar shaped;
        # tests/test_traffic.py
        # test_pipelined_combine_count_doubles_lowering_neutral); an
        # exotic lowering that defeats the text parse records null —
        # provenance, never a voided measurement.
        try:
            import re

            def full_height_combines(params):
                txt = pmesh.shard_run.lower(
                    key, params, world, 4, mesh,
                    state=swim.initial_state(params, world),
                    start_round=0, pipelined=False,
                ).compile().as_text()
                k_cols = params.n_subjects
                return len(re.findall(
                    r"= \w+\[" + f"{n_members},{k_cols}"
                    + r"\]\S* all-reduce\(", txt))

            hlo_counts = {"fused": full_height_combines(p_fused),
                          "legacy": full_height_combines(p_legacy)}
            log(f"wire: HLO full-height collectives/round {hlo_counts}")
        except Exception as e:  # noqa: BLE001
            hlo_counts = None
            log(f"wire: HLO collective count unavailable "
                f"({type(e).__name__}: {e})")

        reps = 6 if SMOKE else 4
        rates = {}
        for pipe, pipe_name in ((False, "serial"), (True, "pipelined")):
            def run_wire(wire, rep, pipe=pipe):
                params = p_fused if wire == "fused" else p_legacy
                s, _ = pmesh.shard_run(
                    key, params, world, rounds, mesh,
                    state=states[(wire, pipe)],
                    start_round=rounds * (1 + rep), pipelined=pipe)
                force(s)
                states[(wire, pipe)] = s

            f_best, l_best = interleaved_best_of(
                lambda rep: run_wire("fused", rep),
                lambda rep: run_wire("legacy", rep), reps)
            rates[(pipe_name, "fused")] = n_members * rounds / f_best / n_dev
            rates[(pipe_name, "legacy")] = n_members * rounds / l_best / n_dev
            log(f"wire/{pipe_name}: fused {f_best:.3f}s vs legacy "
                f"{l_best:.3f}s per {rounds}-round window (best of "
                f"{reps}, interleaved) -> speedup "
                f"x{f_best and l_best / f_best:.4f}")

        serial_ratio = round(
            rates[("serial", "fused")] / rates[("serial", "legacy")], 4)
        pipelined_ratio = round(
            rates[("pipelined", "fused")] / rates[("pipelined", "legacy")],
            4)
        result.update(
            value=round(rates[("pipelined", "fused")], 1),
            fused_serial_member_rounds_per_sec_per_chip=round(
                rates[("serial", "fused")], 1),
            legacy_serial_member_rounds_per_sec_per_chip=round(
                rates[("serial", "legacy")], 1),
            fused_pipelined_member_rounds_per_sec_per_chip=round(
                rates[("pipelined", "fused")], 1),
            legacy_pipelined_member_rounds_per_sec_per_chip=round(
                rates[("pipelined", "legacy")], 1),
            fused_serial_speedup_ratio=serial_ratio,
            fused_pipelined_speedup_ratio=pipelined_ratio,
            pipelined_serial_parity=parity,
            hlo_full_height_collectives=hlo_counts,
            wire_collectives_per_round={
                "fused": traffic.scatter_collectives_per_round(p_fused),
                "legacy": traffic.scatter_collectives_per_round(p_legacy),
            },
            wire_bytes_per_slot={
                "fused": traffic.scatter_wire_bytes_per_slot(p_fused),
                "legacy": traffic.scatter_wire_bytes_per_slot(p_legacy),
            },
            # The wire24 rung's headroom at zero extra wire bytes, and
            # the shift-mode accounting untouched by the flag fold —
            # straight from the model (the HLO versions live in
            # tests/test_traffic.py).
            wire24_bytes_per_slot=traffic.scatter_wire_bytes_per_slot(
                swim.SwimParams.from_config(
                    ClusterConfig.default(), n_members=n_members,
                    n_subjects=N_SUBJECTS, delivery="scatter",
                    compact_carry=True, wire24=True)),
            wire_inc_sat={
                name: swim._wire_inc_sat(swim.SwimParams.from_config(
                    ClusterConfig.default(), n_members=n_members,
                    n_subjects=N_SUBJECTS, delivery="scatter",
                    open_world=True, **kw))
                for name, kw in (
                    ("wide", {}),
                    ("wire16", {"compact_carry": True}),
                    ("wire24", {"compact_carry": True, "wire24": True}),
                )},
            shift_accounting_unchanged=bool(
                traffic.shift_ici_bytes_per_device_round(
                    dataclasses.replace(p_fused, delivery="shift"), n_dev)
                == traffic.shift_ici_bytes_per_device_round(
                    dataclasses.replace(p_legacy, delivery="shift"),
                    n_dev)),
            n_devices=n_dev,
            mesh_shape=list(mesh.devices.shape),
            n_members=n_members,
            rounds_timed=rounds,
            delivery="scatter",
        )

        art = dict(result)
        os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
        result["artifact"] = artifact
        log(f"wire artifact written to {artifact}")

        apply_regress_gate(
            result, ["BENCH_*.json", "MULTICHIP_*.json",
                     os.path.join("artifacts", "wire_fused*.json"),
                     artifact])
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_sync_bench():
    """The --sync mode: partition-heal convergence of the SYNC
    anti-entropy plane (models/sync.py) against the gossip-only
    control, one JSON line out (never-ship-empty).

    Two arms, both on the chaos-campaign timing preset (this is a
    robustness workload, like --chaos):

      1. *monitored* — a quiesced RollingPartition heal at the
         chaos-campaign scale through ``chaos.run_monitored`` with the
         plane ON and the POST_HEAL_DIVERGENCE agreement window armed
         (green required), plus a divergence probe of the gossip-only
         control at the same horizon (non-zero required — the control
         demonstrably does not converge);
      2. *scale* — the focal shift workload (the 1M bench shape) healed
         after a quiesced split, probed every few rounds for the first
         divergence-free table: ``sync_rounds_to_converge``.

    Results land in an ``artifacts/sync_heal.json``-style artifact
    (override SCALECUBE_SYNC_ARTIFACT) gated by ``telemetry regress``
    (absolute convergence gates + the banded convergence-time series),
    and a JSONL manifest summary row feeds the
    ``sync_rounds_to_converge`` SLO (telemetry/query.compute_slos).
    ``--sync --smoke`` is the tier-1-safe pass
    (tests/test_bench_sync_smoke.py pins the contract).  Env overrides:
    SCALECUBE_SYNC_N, SCALECUBE_SYNC_SUBJECTS, SCALECUBE_SYNC_INTERVAL,
    SCALECUBE_SYNC_PROBE_STEP, SCALECUBE_SYNC_MONITOR_N,
    SCALECUBE_SYNC_ARTIFACT.

    ``value`` stays None by design: rounds-to-converge is
    smaller-is-better, so it must not enter the generic
    higher-is-better throughput walk — regress gates the dedicated
    ``sync_rounds_to_converge`` series instead.
    """
    result = {
        "metric": "sync_heal_rounds_to_converge",
        "value": None,
        "unit": "rounds",
        "smoke": SMOKE,
    }
    # Smoke runs get their own default artifact (the metrics-mode
    # convention): `--sync --smoke` must never overwrite the committed
    # full-scale measurement, and the regress walk treats smoke heal
    # artifacts as provenance, not trajectory data.
    artifact = (os.environ.get("SCALECUBE_SYNC_ARTIFACT")
                or os.path.join("artifacts",
                                "sync_heal_smoke.json" if SMOKE
                                else "sync_heal.json"))
    try:
        jax, platform = init_backend()
        result["platform"] = platform

        import dataclasses

        from scalecube_cluster_tpu.chaos import campaign as ccampaign
        from scalecube_cluster_tpu.chaos import monitor as cmonitor
        from scalecube_cluster_tpu.chaos import scenarios as cscenarios
        from scalecube_cluster_tpu.models import swim
        from scalecube_cluster_tpu.models import sync as sync_plane
        from scalecube_cluster_tpu.parallel import traffic
        from scalecube_cluster_tpu.telemetry import sink as tsink
        from scalecube_cluster_tpu.utils import runlog

        def force(state):
            return runlog.completion_barrier(state.status)

        cfg = ccampaign.campaign_config()
        sync_interval = int(os.environ.get("SCALECUBE_SYNC_INTERVAL", 32))
        seed = int(os.environ.get("SCALECUBE_SYNC_SEED", 7))

        # ---- Arm 1: chaos-campaign-scale monitored heal -----------------
        n_mon = int(os.environ.get("SCALECUBE_SYNC_MONITOR_N",
                                   24 if SMOKE else 32))
        si_mon = 8
        p_mon = swim.SwimParams.from_config(
            cfg, n_members=n_mon, delivery="shift", sync_every=0,
            sync_interval=si_mon,
        )
        scen = cscenarios.quiesced_heal_scenario(
            p_mon, n_mon, name=f"sync-heal-{n_mon}")
        phase_mon, horizon = scen.ops[0].phase_rounds, scen.horizon
        world_mon, spec_mon = scen.build(p_mon)
        t0 = time.time()
        _, mon, _ = cmonitor.run_monitored(
            jax.random.key(seed), p_mon, world_mon, spec_mon, horizon)
        verdict = cmonitor.verdict(mon)
        # Gossip-only control at the same schedule: divergence persists.
        p_mon_off = dataclasses.replace(p_mon, sync_interval=0)
        world_off, _ = scen.build(p_mon_off)
        st_off, _ = swim.run(jax.random.key(seed), p_mon_off, world_off,
                             horizon)
        mon_control_div = int(sync_plane.divergence_probe(
            st_off, p_mon_off, world_off, horizon))
        log(f"sync monitored arm (n={n_mon}, split {phase_mon}, horizon "
            f"{horizon}): {'green' if verdict['green'] else 'RED'}; "
            f"gossip-only control divergent columns: {mon_control_div} "
            f"({time.time() - t0:.1f}s)")
        phd = verdict["codes"]["POST_HEAL_DIVERGENCE"]["violations"]

        # ---- Arm 2: scale arm (the focal shift 1M shape) ----------------
        n_scale = int(os.environ.get("SCALECUBE_SYNC_N",
                                     2048 if SMOKE else 1_000_000))
        k = int(os.environ.get("SCALECUBE_SYNC_SUBJECTS", 16))
        probe_step = int(os.environ.get("SCALECUBE_SYNC_PROBE_STEP",
                                        1 if SMOKE else 2))
        params = swim.SwimParams.from_config(
            cfg, n_members=n_scale, n_subjects=k, delivery="shift",
            sync_every=0, sync_interval=sync_interval,
            rounds_per_step=resolve_rounds_per_step(),
        )
        # Same canonical quiesced split/heal schedule as the monitored
        # arm (ONE place for the bound arithmetic —
        # cscenarios.quiesced_heal_scenario), applied to a FOCAL world:
        # subjects spread over the id range so the split divides them
        # (Scenario.build compiles full-view worlds only, so the op is
        # applied to the focal world directly).
        scen_scale = cscenarios.quiesced_heal_scenario(params, n_scale)
        phase = scen_scale.ops[0].phase_rounds
        window = scen_scale.horizon - 2 * phase
        subject_ids = jax.numpy.arange(k, dtype=jax.numpy.int32) * (
            n_scale // k)
        world = swim.SwimWorld.healthy(params, subject_ids=subject_ids)
        world = scen_scale.ops[0].apply(world, n_scale,
                                        scen_scale.horizon)

        key = jax.random.key(seed)
        t0 = time.time()
        state = swim.initial_state(params, world)
        state, _ = swim.run(key, params, world, phase, state=state)
        force(state)
        split_div = int(sync_plane.divergence_probe(
            state, params, world, phase))
        log(f"sync scale arm: N={n_scale} K={k} split {phase} rounds "
            f"(divergent columns at heal: {split_div}), probing every "
            f"{probe_step} rounds over a {window}-round window "
            f"(compile+split took {time.time() - t0:.1f}s)")

        t0 = time.time()
        converge_at = None
        r = phase
        while r < phase + window:
            state, _ = swim.run(key, params, world, probe_step,
                                state=state, start_round=r)
            r += probe_step
            if int(sync_plane.divergence_probe(state, params, world,
                                               r)) == 0:
                converge_at = r - phase
                break
        if converge_at is None:
            log(f"sync scale arm: DID NOT converge within the "
                f"{window}-round window ({time.time() - t0:.1f}s)")
        else:
            log(f"sync scale arm: converged at heal+{converge_at} "
                f"rounds ({time.time() - t0:.1f}s)")

        # Gossip-only control over the same window, probed at its end.
        p_off = dataclasses.replace(params, sync_interval=0)
        t0 = time.time()
        st_off, _ = swim.run(key, p_off, world, phase + window)
        gossip_only_div = int(sync_plane.divergence_probe(
            st_off, p_off, world, phase + window))
        log(f"sync scale control (gossip-only): divergent columns at "
            f"heal+{window}: {gossip_only_div} ({time.time() - t0:.1f}s)")

        result.update(
            sync_rounds_to_converge=converge_at,
            converged=converge_at is not None,
            post_heal_divergence=int(phd),
            monitored_green=bool(verdict["green"]),
            monitored_n_members=n_mon,
            monitored_control_divergence=mon_control_div,
            gossip_only_divergence=gossip_only_div,
            gossip_only_converged=bool(gossip_only_div == 0),
            divergence_at_heal=split_div,
            n_members=n_scale,
            n_subjects=k,
            delivery="shift",
            sync_interval=sync_interval,
            split_rounds=phase,
            window_rounds=window,
            probe_step=probe_step,
            seed=seed,
            sync_exchange_bytes_per_member=(
                traffic.sync_exchange_bytes_per_member(params)),
            piggyback_bytes_per_member_round=(
                traffic.piggyback_bytes_per_member_round(params)),
            value_note=("value stays null by design: rounds-to-converge "
                        "is smaller-is-better and must not enter the "
                        "throughput walk — regress gates "
                        "sync_rounds_to_converge instead"),
        )

        # SLO surface: one manifest summary row the query layer folds
        # into the sync_rounds_to_converge SLO.
        with tsink.TelemetrySink.from_env(
                default_dir=os.path.join("artifacts", "telemetry"),
                prefix="sync-heal-smoke" if SMOKE else "sync-heal") as sink:
            sink.write_manifest(
                params=cfg,
                workload={"kind": "sync_heal", "n_members": n_scale,
                          "sync_interval": sync_interval,
                          "split_rounds": phase,
                          "window_rounds": window, "seed": seed},
            )
            sink.write_record("summary", {
                "sync_rounds_to_converge": converge_at,
                "post_heal_divergence": int(phd),
                "gossip_only_divergence": gossip_only_div,
            })
            result["manifest"] = sink.path

        art = dict(result)
        os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
        result["artifact"] = artifact
        log(f"sync artifact written to {artifact}")

        apply_regress_gate(
            result, ["BENCH_*.json", "MULTICHIP_*.json",
                     os.path.join("artifacts", "sync_heal*.json"),
                     artifact])
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_rollout_bench():
    """The --rollout mode: staged config rollout through the metadata
    KV plane (models/metadata.py) under fire, one JSON line out
    (never-ship-empty).

    One composite scenario on the chaos-campaign timing preset — a
    revive churn storm, a quiesced partition split/heal, and a seeded
    :class:`chaos.StagedRollout` whose stages CROSS the split — run
    three ways:

      1. *monitored* — the full composite through ``chaos.run_monitored``
         with the agreement window armed (zero violations required: the
         KV plane must not perturb membership convergence);
      2. *gated rollout* — the same program segment-by-segment, probing
         every few rounds for per-push convergence (every live table
         holds the pushed word).  Each push's deadline is
         ``max(push round, heal round) + metadata_convergence_bound``
         (the monitor's completeness convention: no promise under an
         active disruption).  A breach would roll the flipped stages
         back (``StagedRollout.rollback_ops``) and fail the in-bench
         gate; the happy path records per-push latencies from the
         deadline clock start → ``metadata_convergence_p99``;
      3. *control* — gossip-only dissemination (metadata ON,
         ``sync_interval=0``): the hot piggyback window expires inside
         the split, so the control stays DIVERGENT through the heal —
         the A/B that shows the full-table anti-entropy lane is what
         makes config propagation survive partitions.

    Results land in an ``artifacts/config_rollout.json``-style artifact
    (override SCALECUBE_ROLLOUT_ARTIFACT) gated by ``telemetry
    regress`` (absolute convergence/control/monitor gates + the banded
    p99 series), and a JSONL manifest summary row feeds the
    ``metadata_convergence_p99`` SLO (telemetry/query.compute_slos).
    ``--rollout --smoke`` is the tier-1-safe pass
    (tests/test_bench_rollout_smoke.py pins the contract).  Env
    overrides: SCALECUBE_ROLLOUT_N, SCALECUBE_ROLLOUT_STAGES,
    SCALECUBE_ROLLOUT_STAGE_SIZE, SCALECUBE_ROLLOUT_SYNC_INTERVAL,
    SCALECUBE_ROLLOUT_PROBE_STEP, SCALECUBE_ROLLOUT_SEED,
    SCALECUBE_ROLLOUT_ARTIFACT.

    ``value`` stays None by design: convergence latency is
    smaller-is-better, so it must not enter the generic
    higher-is-better throughput walk — regress gates the dedicated
    ``metadata_convergence_p99`` series instead.
    """
    result = {
        "metric": "config_rollout_convergence",
        "value": None,
        "unit": "rounds",
        "smoke": SMOKE,
    }
    artifact = (os.environ.get("SCALECUBE_ROLLOUT_ARTIFACT")
                or os.path.join("artifacts",
                                "config_rollout_smoke.json" if SMOKE
                                else "config_rollout.json"))
    try:
        jax, platform = init_backend()
        result["platform"] = platform

        import numpy as np

        from scalecube_cluster_tpu.chaos import campaign as ccampaign
        from scalecube_cluster_tpu.chaos import monitor as cmonitor
        from scalecube_cluster_tpu.chaos import scenarios as cscenarios
        from scalecube_cluster_tpu.models import metadata as md_plane
        from scalecube_cluster_tpu.models import swim
        from scalecube_cluster_tpu.telemetry import sink as tsink

        cfg = ccampaign.campaign_config()
        seed = int(os.environ.get("SCALECUBE_ROLLOUT_SEED", 11))
        n = int(os.environ.get("SCALECUBE_ROLLOUT_N",
                               24 if SMOKE else 48))
        sync_interval = int(os.environ.get(
            "SCALECUBE_ROLLOUT_SYNC_INTERVAL", 8))
        n_stages = int(os.environ.get("SCALECUBE_ROLLOUT_STAGES",
                                      2 if SMOKE else 3))
        stage_size = int(os.environ.get("SCALECUBE_ROLLOUT_STAGE_SIZE",
                                        2 if SMOKE else 4))
        probe_step = int(os.environ.get("SCALECUBE_ROLLOUT_PROBE_STEP", 2))
        new_value, rollback_value = 641, 7

        # Geometry: one quiesced split/heal (the sync bench's bound
        # arithmetic), the storm before it, the rollout stages crossing
        # it.  stage_every covers the convergence bound by construction
        # (StagedRollout.validate_gate re-checks).
        p0 = swim.SwimParams.from_config(
            cfg, n_members=n, delivery="shift", sync_every=0,
            sync_interval=sync_interval, metadata_keys=1)
        phase = -(-cscenarios.quiesce_bound(p0, n) // 16) * 16
        bound = cscenarios.metadata_convergence_bound(p0, n)
        stage_every = -(-bound // 16) * 16
        split_at, heal_at = phase, 2 * phase
        start = phase + phase // 2            # stage 0 fires mid-split

        rng = np.random.default_rng(np.random.SeedSequence([seed, 0x19C0]))
        perm = [int(x) for x in rng.permutation(n)]
        storm_nodes = tuple(perm[:4])
        owners = tuple(perm[4:4 + n_stages * stage_size])
        revive_down = cscenarios.quiesce_bound(p0, n)
        storm = cscenarios.ChurnStorm(
            nodes=storm_nodes, wave_size=2, start_round=8,
            wave_every=24, down_rounds=revive_down)
        rollout = cscenarios.StagedRollout(
            members=owners, n_stages=n_stages, key=0, value=new_value,
            start_round=start, stage_every=stage_every,
            rollback_value=rollback_value)
        rollout.validate_gate(p0, n)
        last_stage = rollout.stage_round(n_stages - 1)
        horizon = -(-(max(last_stage, heal_at) + bound + 32) // 64) * 64
        scen = cscenarios.Scenario(
            name=f"config-rollout-n{n}", n_members=n, horizon=horizon,
            ops=(storm,
                 cscenarios.RollingPartition(from_round=phase,
                                             phase_rounds=phase,
                                             n_cycles=1),
                 rollout),
            seed=seed)

        params = ccampaign.campaign_params(
            scen, delivery="shift", sync_every=0,
            sync_interval=sync_interval)
        world, spec = scen.build(params)
        key = jax.random.key(seed)

        # ---- Arm 1: monitored composite ---------------------------------
        t0 = time.time()
        _, mon, _ = cmonitor.run_monitored(key, params, world, spec,
                                           horizon)
        verdict = cmonitor.verdict(mon)
        violations = sum(d["violations"]
                         for d in verdict["codes"].values())
        log(f"rollout monitored arm (n={n}, split [{split_at},{heal_at}), "
            f"horizon {horizon}): "
            f"{'green' if verdict['green'] else 'RED'} "
            f"({violations} violation(s), {time.time() - t0:.1f}s)")

        # ---- Arm 2: gated segment-driven rollout ------------------------
        # Per-push deadline clock starts at max(push, heal) — the
        # completeness convention: no convergence promise while the
        # split still partitions the readers.
        pushes = []
        for node, k_, value, at in rollout.push_schedule():
            eff = heal_at if split_at <= at < heal_at else at
            pushes.append({"owner": node, "key": k_, "value": value,
                           "push_round": at, "clock_from": eff,
                           "deadline": eff + bound, "converged_at": None})
        df = np.asarray(world.down_from)
        du = np.asarray(world.down_until)

        t0 = time.time()
        state = swim.initial_state(params, world)
        r, rolled_back, breaches = 0, False, []
        while r < horizon:
            step = min(probe_step, horizon - r)
            state, _ = swim.run(key, params, world, step, state=state,
                                start_round=r)
            r += step
            open_pushes = [p for p in pushes
                           if p["converged_at"] is None
                           and p["push_round"] < r]
            if open_pushes:
                md = np.asarray(state.md)
                alive = ~((df <= r - 1) & (r - 1 < du))
                obs = np.flatnonzero(alive)
                for p in open_pushes:
                    vals = (md[obs, p["owner"], p["key"]]
                            & md_plane.MD_VALUE_MAX)
                    if bool((vals == p["value"]).all()):
                        p["converged_at"] = r
            for p in pushes:
                if p["converged_at"] is None and r >= p["deadline"]:
                    breaches.append(p)
            if breaches and not rolled_back:
                # Convergence-deadline breach: roll the flipped stages
                # back — rebuild the remaining schedule with the
                # rollback pushes and drive it to the horizon (the
                # drill keeps the run honest; the gate below fails).
                rolled_back = True
                failed_stage = max(
                    s for s in range(n_stages)
                    if rollout.stage_round(s) <= breaches[0]["push_round"])
                rb_world = world
                for op in rollout.rollback_ops(failed_stage, r + 1):
                    rb_world = op.apply(rb_world, n, horizon)
                state, _ = swim.run(key, params, rb_world, horizon - r,
                                    state=state, start_round=r)
                r = horizon
            if all(p["converged_at"] is not None for p in pushes):
                break
        lats = [p["converged_at"] - p["clock_from"] for p in pushes
                if p["converged_at"] is not None]
        converged = (not rolled_back
                     and all(p["converged_at"] is not None
                             and p["converged_at"] <= p["deadline"]
                             for p in pushes))
        p99 = float(np.percentile(lats, 99)) if lats and converged else None
        # Drive the survivors to the horizon and take the global probe:
        # every table (including the revived storm nodes) must agree.
        if r < horizon and not rolled_back:
            state, _ = swim.run(key, params, world, horizon - r,
                                state=state, start_round=r)
        final_div = int(md_plane.divergence_probe(state, params, world,
                                                  horizon))
        log(f"rollout gated arm: {len(pushes)} push(es) over "
            f"{n_stages} stage(s), converged={converged} "
            f"(p99 {p99} rounds from clock start, bound {bound}; "
            f"final divergent cells {final_div}; "
            f"rolled_back={rolled_back}, {time.time() - t0:.1f}s)")

        # ---- Arm 3: gossip-only control ---------------------------------
        params_off = ccampaign.campaign_params(
            scen, delivery="shift", sync_every=0, sync_interval=0)
        world_off, _ = scen.build(params_off)
        t0 = time.time()
        st_off, _ = swim.run(key, params_off, world_off, horizon)
        control_div = int(md_plane.divergence_probe(
            st_off, params_off, world_off, horizon))
        log(f"rollout control (gossip-only): divergent cells at horizon: "
            f"{control_div} ({time.time() - t0:.1f}s)")

        result.update(
            metadata_convergence_p99=p99,
            rollout_converged=bool(converged and final_div == 0),
            rolled_back=rolled_back,
            convergence_deadline_rounds=bound,
            stage_converge_rounds=[p["converged_at"] for p in pushes],
            stage_rounds=[rollout.stage_round(s)
                          for s in range(n_stages)],
            final_divergent_cells=final_div,
            control_divergent_cells=control_div,
            control_converged=bool(control_div == 0),
            monitored_green=bool(verdict["green"]),
            monitor_violations=int(violations),
            n_members=n,
            metadata_keys=int(params.metadata_keys),
            n_stages=n_stages,
            stage_size=stage_size,
            owners=list(owners),
            delivery="shift",
            sync_interval=sync_interval,
            split_rounds=phase,
            horizon_rounds=horizon,
            probe_step=probe_step,
            seed=seed,
            value_note=("value stays null by design: convergence latency "
                        "is smaller-is-better and must not enter the "
                        "throughput walk — regress gates "
                        "metadata_convergence_p99 instead"),
        )

        # SLO surface: one manifest summary row the query layer folds
        # into the metadata_convergence_p99 SLO.
        with tsink.TelemetrySink.from_env(
                default_dir=os.path.join("artifacts", "telemetry"),
                prefix=("config-rollout-smoke" if SMOKE
                        else "config-rollout")) as sink:
            sink.write_manifest(
                params=cfg,
                workload={"kind": "config_rollout", "n_members": n,
                          "sync_interval": sync_interval,
                          "stages": n_stages, "stage_size": stage_size,
                          "split_rounds": phase, "horizon": horizon,
                          "seed": seed},
            )
            sink.write_record("summary", {
                "metadata_convergence_p99": p99,
                "rollout_converged": bool(converged and final_div == 0),
                "control_divergent_cells": control_div,
            })
            result["manifest"] = sink.path

        art = dict(result)
        os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
        result["artifact"] = artifact
        log(f"rollout artifact written to {artifact}")

        apply_regress_gate(
            result, ["BENCH_*.json", "MULTICHIP_*.json",
                     os.path.join("artifacts", "config_rollout*.json"),
                     artifact])
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_lifeguard_bench():
    """The --lifeguard mode: the Lifeguard health plane's headline
    robustness claim, measured A/B (never asserted) — one JSON line out
    (never-ship-empty).

    Workload: the seeded ``chaos.asymmetric_degradation`` composite
    (Brownout loss+delay on the degraded minority's inbound ranges +
    FlappingLink — the observer-side degradation Lifeguard targets)
    with the DEGRADED RACK itself crashing permanently mid-hold for
    detection-latency parity (the in-loop comment explains why healthy
    crash targets would corrupt the comparison).  Each scenario seed
    runs TWICE through ``swim.run_metered``
    on the same key: the control (``lhm_max=0``, the plane compiled
    out) and the plane (``lhm_max`` from SCALECUBE_LIFEGUARD_LHM_MAX,
    default 8).  Aggregated over scenarios:

      - ``false_positive_observer_rate`` per arm, from the registry's
        false_suspicion_onsets / live_observer_rounds counters (the
        PR-5 SLO definition);
      - ``fp_ratio`` = on/off — the headline, gated ABSOLUTELY at
        <= 0.5 by ``telemetry regress``;
      - crash-detection latency P99 per arm (first round any live
        observer holds SUSPECT/DEAD about a crashed node, from the
        per-subject metric traces) and their delta, gated at <= +1
        round — adaptivity must not buy its FP win with detection
        latency.

    Writes an ``artifacts/lifeguard_fp.json``-style artifact (smoke
    runs get ``lifeguard_fp_smoke.json`` — provenance, not trajectory
    data, the sync-heal convention).  ``--lifeguard --smoke`` is the
    tier-1-safe single-scenario pass pinned by
    tests/test_bench_lifeguard_smoke.py.  Env overrides:
    SCALECUBE_LIFEGUARD_N, SCALECUBE_LIFEGUARD_LHM_MAX,
    SCALECUBE_LIFEGUARD_SEED, SCALECUBE_LIFEGUARD_SCENARIOS,
    SCALECUBE_LIFEGUARD_ARTIFACT.

    ``value`` stays None by design: the headline is a smaller-is-better
    ratio and must not enter the higher-is-better throughput walk —
    regress gates the dedicated absolute checks instead.
    """
    result = {
        "metric": "lifeguard_fp_observer_rate",
        "value": None,
        "unit": "ratio",
        "smoke": SMOKE,
    }
    artifact = (os.environ.get("SCALECUBE_LIFEGUARD_ARTIFACT")
                or os.path.join("artifacts",
                                "lifeguard_fp_smoke.json" if SMOKE
                                else "lifeguard_fp.json"))
    try:
        jax, platform = init_backend()
        result["platform"] = platform

        import dataclasses

        import numpy as np

        from scalecube_cluster_tpu.chaos import scenarios as cscenarios
        from scalecube_cluster_tpu.chaos.campaign import campaign_config
        from scalecube_cluster_tpu.models import swim
        from scalecube_cluster_tpu.telemetry import metrics as tmetrics

        # The campaign timing preset at its finest probe cadence
        # (ping_every = 1 round): detection latencies quantize to
        # single rounds, which is what makes a +-1-round parity gate
        # meaningful.
        cfg = campaign_config().replace(ping_interval=100,
                                        ping_timeout=50)
        n = int(os.environ.get("SCALECUBE_LIFEGUARD_N",
                               24 if SMOKE else 48))
        lhm_max = int(os.environ.get("SCALECUBE_LIFEGUARD_LHM_MAX", 8))
        seed = int(os.environ.get("SCALECUBE_LIFEGUARD_SEED", 11))
        n_scen = int(os.environ.get("SCALECUBE_LIFEGUARD_SCENARIOS",
                                    1 if SMOKE else 3))
        spec = tmetrics.MetricsSpec.default()
        # ping_known_only=False draws probe targets uniformly over the
        # cluster (the focal-mode probe discipline, documented in
        # models/swim.py) instead of from each observer's table: the
        # target DRAWS are then shared between the two arms — common
        # random numbers for the detection race — instead of being
        # reshuffled by every table divergence (choose_eligible re-maps
        # the whole draw when one cell's eligibility differs).  The
        # arms still differ where the plane actually acts: suppressed
        # degraded probers, and healthy observers whose own multiplier
        # drifts above 1 from probing INTO the degraded rack — that
        # residual adaptivity cost is precisely what the +-1-round
        # parity gate measures.
        p_off = swim.SwimParams.from_config(
            cfg, n_members=n, delivery="scatter", ping_known_only=False)
        p_on = dataclasses.replace(p_off, lhm_max=lhm_max)

        totals = {"off": [0, 0], "on": [0, 0]}   # [onsets, observer-rounds]
        latencies = {"off": [], "on": []}
        scenario_rows = []
        for s_i in range(n_scen):
            scen = cscenarios.asymmetric_degradation(seed + s_i, n)
            world, _mspec = scen.build(p_off)
            # The degraded rack DIES mid-hold (the operationally real
            # crash: browning-out members are the ones that fail).
            # Detection of these crashes is the fair parity probe:
            # pre-crash false suspicions about the hard-to-reach rack
            # come from healthy observers under near-identical
            # conditions in both arms (the plane's big lever — quieting
            # the degraded observers' own verdicts — doesn't apply to
            # suspicions OF the rack), and after the crash no degraded
            # prober remains to suppress.  The residual asymmetry —
            # healthy observers' multipliers drift above 1 from probing
            # into the rack, thinning their probe rate and pre-crash
            # suspicions in the on-arm — is a real adaptivity cost and
            # is exactly what the +-1-round gate bounds.  Crashing
            # healthy members instead would let the control arm "win"
            # via its own false-alarm storm pre-suspecting every
            # subject.
            crash_nodes = list(range(
                cscenarios.asymmetric_degraded_range(n)))
            crash_at = 120
            world = world.with_crash(crash_nodes, crash_at)
            row = {"scenario": scen.name, "repro":
                   f"chaos.asymmetric_degradation(seed={seed + s_i}, "
                   f"n={n})", "horizon": scen.horizon}
            for arm, p in (("off", p_off), ("on", p_on)):
                t0 = time.time()
                _, ms, metrics = swim.run_metered(
                    jax.random.key(seed + s_i), p, world, scen.horizon)
                digest = tmetrics.to_json(jax.device_get(ms), spec)
                onsets = digest["counters"]["false_suspicion_onsets"]
                obs_rounds = digest["counters"]["live_observer_rounds"]
                totals[arm][0] += onsets
                totals[arm][1] += obs_rounds
                sus = np.asarray(metrics["suspect"])
                dead = np.asarray(metrics["dead"])
                lat = []
                for c in crash_nodes:
                    seen = np.nonzero(
                        (sus[crash_at:, c] + dead[crash_at:, c]) > 0)[0]
                    lat.append(int(seen[0]) if len(seen)
                               else scen.horizon - crash_at)
                latencies[arm].extend(lat)
                row[f"fp_onsets_{arm}"] = int(onsets)
                row[f"detection_latency_{arm}"] = sorted(lat)
                if arm == "on":
                    row["lhm_gauge"] = digest["gauges"].get("lhm")
                log(f"lifeguard {scen.name} arm={arm}: onsets={onsets} "
                    f"observer-rounds={obs_rounds} detection={sorted(lat)}"
                    f" ({time.time() - t0:.1f}s)")
            scenario_rows.append(row)

        fp_off = totals["off"][0] / max(totals["off"][1], 1)
        fp_on = totals["on"][0] / max(totals["on"][1], 1)
        fp_ratio = (fp_on / fp_off) if fp_off > 0 else None
        p99_off = float(np.percentile(latencies["off"], 99))
        p99_on = float(np.percentile(latencies["on"], 99))
        log(f"lifeguard headline: fp_rate off={fp_off:.6f} "
            f"on={fp_on:.6f} ratio={fp_ratio} detection_p99 "
            f"off={p99_off:.2f} on={p99_on:.2f}")
        result.update(
            false_positive_observer_rate_off=round(fp_off, 8),
            false_positive_observer_rate_on=round(fp_on, 8),
            fp_ratio=(round(fp_ratio, 6) if fp_ratio is not None
                      else None),
            detection_p99_off_rounds=p99_off,
            detection_p99_on_rounds=p99_on,
            detection_p99_delta_rounds=round(p99_on - p99_off, 2),
            fp_onsets_off=int(totals["off"][0]),
            fp_onsets_on=int(totals["on"][0]),
            live_observer_rounds=int(totals["off"][1]),
            n_members=n,
            lhm_max=lhm_max,
            seed=seed,
            n_scenarios=n_scen,
            delivery="scatter",
            scenarios=scenario_rows,
            value_note=("value stays null by design: fp_ratio is "
                        "smaller-is-better and must not enter the "
                        "throughput walk — regress gates the absolute "
                        "lifeguard checks instead"),
        )

        art = dict(result)
        os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
        result["artifact"] = artifact
        log(f"lifeguard artifact written to {artifact}")

        apply_regress_gate(
            result, ["BENCH_*.json", "MULTICHIP_*.json",
                     os.path.join("artifacts", "lifeguard_fp*.json"),
                     artifact])
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_alarm_bench():
    """The --alarms mode: the live SLO alarm engine's measured drill —
    one JSON line out (never-ship-empty).

    Workload: the seeded ``chaos.alarm_drill_scenario`` square loss
    pulse (sharp edges — the drill measures DETECTION LAG against a
    known onset round) run TWICE on the same world through live
    ``TelemetrySink`` journals with ``stream_metered_run(...,
    alarm_specs=default_specs(threshold))``:

      - the HEALTHY arm (campaign-default Knobs): must ride the pulse
        out with ZERO ``alarm_transition`` rows — the committed
        quiet-under-stress half of the claim;
      - the BREACH arm (``chaos.alarm_breach_knobs``: probe every
        round — dynamic Knobs data, so this rerun REUSES the healthy
        arm's compiled program, zero extra compiles): the planted
        ``false_positive_observer_rate`` breach must reach FIRING
        within ONE metrics window of the pulse onset
        (``alarm_detection_lag_windows`` <= 1, the headline) and
        RESOLVE after the heal.

    Writes an ``artifacts/alarm_drill.json``-style artifact (smoke runs
    get ``alarm_drill_smoke.json`` — provenance, the sync-heal
    convention) and runs the regress gate in-bench.  The two journals
    stay on disk next to the artifact, so ``python -m
    scalecube_cluster_tpu.telemetry watch <journal>`` replays the drill
    live.  ``--alarms --smoke`` is the tier-1-safe pass pinned by
    tests/test_bench_alarms_smoke.py.  Env overrides: SCALECUBE_ALARM_N,
    SCALECUBE_ALARM_SEED, SCALECUBE_ALARM_WINDOW, SCALECUBE_ALARM_ONSET,
    SCALECUBE_ALARM_PULSE, SCALECUBE_ALARM_COOL,
    SCALECUBE_ALARM_PULSE_LOSS, SCALECUBE_ALARM_THRESHOLD,
    SCALECUBE_ALARM_ARTIFACT.

    ``value`` stays None by design: detection lag is smaller-is-better
    and must not enter the higher-is-better throughput walk — regress
    gates the absolute alarm checks instead.
    """
    result = {
        "metric": "alarm_detection_lag_windows",
        "value": None,
        "unit": "metrics windows",
        "smoke": SMOKE,
    }
    artifact = (os.environ.get("SCALECUBE_ALARM_ARTIFACT")
                or os.path.join("artifacts",
                                "alarm_drill_smoke.json" if SMOKE
                                else "alarm_drill.json"))
    try:
        jax, platform = init_backend()
        result["platform"] = platform

        from scalecube_cluster_tpu.chaos import scenarios as cscenarios
        from scalecube_cluster_tpu.chaos.campaign import (
            alarm_breach_knobs, campaign_config)
        from scalecube_cluster_tpu.models import swim
        from scalecube_cluster_tpu.telemetry import alarms as talarms
        from scalecube_cluster_tpu.telemetry import metrics as tmetrics
        from scalecube_cluster_tpu.telemetry import sink as tsink

        n = int(os.environ.get("SCALECUBE_ALARM_N", 24 if SMOKE else 48))
        seed = int(os.environ.get("SCALECUBE_ALARM_SEED", 7))
        window_rounds = int(os.environ.get("SCALECUBE_ALARM_WINDOW",
                                           16 if SMOKE else 32))
        onset = int(os.environ.get("SCALECUBE_ALARM_ONSET",
                                   48 if SMOKE else 128))
        pulse = int(os.environ.get("SCALECUBE_ALARM_PULSE",
                                   48 if SMOKE else 128))
        cool = int(os.environ.get("SCALECUBE_ALARM_COOL",
                                  64 if SMOKE else 128))
        pulse_loss = float(os.environ.get("SCALECUBE_ALARM_PULSE_LOSS",
                                          0.8 if SMOKE else 0.6))
        threshold = float(os.environ.get(
            "SCALECUBE_ALARM_THRESHOLD",
            SMOKE_ALARM_THRESHOLD if SMOKE
            else talarms.DEFAULT_FP_THRESHOLD))
        heal = onset + pulse

        scen = cscenarios.alarm_drill_scenario(
            seed, n=n, pulse_loss=pulse_loss, onset_round=onset,
            pulse_rounds=pulse, cool_rounds=cool)
        p = swim.SwimParams.from_config(
            campaign_config(), n_members=n, delivery="scatter",
            ping_known_only=False)
        world, _mspec = scen.build(p)
        specs = talarms.default_specs(threshold=threshold)
        journal_dir = (os.environ.get(tsink.TELEMETRY_DIR_ENV)
                       or os.path.dirname(artifact) or ".")
        arms = {}
        for arm, knobs in (("healthy", swim.Knobs.from_params(p)),
                           ("breach", alarm_breach_knobs(scen, p))):
            t0 = time.time()
            journal = os.path.join(journal_dir,
                                   f"alarm_drill_{arm}.jsonl")
            # append=False: the drill is a fresh measurement, not a
            # resumed run — a stale journal would replay into the
            # engine and dedup this run's transitions away.
            sink = tsink.TelemetrySink(path=journal)
            _, rows = tmetrics.stream_metered_run(
                jax.random.key(seed), p, world, scen.horizon,
                sink=sink, window_rounds=window_rounds,
                alarm_specs=specs, knobs=knobs)
            sink.write_summary(metric="alarm_drill", arm=arm,
                               windows=len(rows))
            sink.close()
            transitions = tsink.read_records(
                journal, kind=talarms.TRANSITION_KIND)
            rates = [
                r["counters"].get("false_suspicion_onsets", 0)
                / max(r["counters"].get("live_observer_rounds", 0), 1)
                for r in rows]
            arms[arm] = {
                "journal": journal,
                # The zero-extra-compiles witness: the breach arm's
                # wall time is pure execution — its dynamic-Knobs rerun
                # reuses the healthy arm's compiled program.
                "seconds": round(time.time() - t0, 2),
                "window_rates": [round(x, 6) for x in rates],
                "peak_rate": round(max(rates), 6) if rates else None,
                "transitions": transitions,
            }
            log(f"alarm drill arm={arm}: {len(rows)} windows, "
                f"{len(transitions)} transition(s), peak rate "
                f"{max(rates):.4f} ({time.time() - t0:.1f}s)")

        firing = [t for t in arms["breach"]["transitions"]
                  if t.get("to") == "firing"]
        resolved = [t for t in arms["breach"]["transitions"]
                    if t.get("to") == "resolved"]
        lag = ((firing[0]["round_end"] - onset) / window_rounds
               if firing else None)
        breach_resolved = any(t["round_end"] >= heal for t in resolved)
        healthy_peak = arms["healthy"]["peak_rate"]
        first_fire_rate = firing[0]["value"] if firing else None
        log(f"alarm drill headline: breach fired {len(firing)}x, "
            f"detection lag {lag} window(s), resolved after heal: "
            f"{breach_resolved}; healthy transitions "
            f"{len(arms['healthy']['transitions'])}")
        result.update(
            alarm_detection_lag_windows=lag,
            breach_fired=len(firing),
            breach_resolved=breach_resolved,
            healthy_transitions=len(arms["healthy"]["transitions"]),
            healthy_peak_rate=healthy_peak,
            breach_first_fire_rate=first_fire_rate,
            threshold=threshold,
            # The committed calibration evidence: how much seed/platform
            # jitter each side of the threshold can absorb before the
            # drill flips (alarms.DEFAULT_FP_THRESHOLD docstring).
            margin_healthy=(round(threshold / healthy_peak - 1, 4)
                            if healthy_peak else None),
            margin_breach=(round(first_fire_rate / threshold - 1, 4)
                           if first_fire_rate else None),
            onset_round=onset,
            heal_round=heal,
            window_rounds=window_rounds,
            pulse_loss=pulse_loss,
            horizon=scen.horizon,
            n_members=n,
            seed=seed,
            delivery="scatter",
            scenario=scen.name,
            arms=arms,
            repro=(f"chaos.alarm_drill_scenario(seed={seed}, n={n}, "
                   f"pulse_loss={pulse_loss}, onset_round={onset}, "
                   f"pulse_rounds={pulse}, cool_rounds={cool})"),
            value_note=("value stays null by design: detection lag is "
                        "smaller-is-better and must not enter the "
                        "throughput walk — regress gates the absolute "
                        "alarm checks instead"),
        )

        art = dict(result)
        os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
        result["artifact"] = artifact
        log(f"alarm artifact written to {artifact}")

        apply_regress_gate(
            result, ["BENCH_*.json", "MULTICHIP_*.json",
                     os.path.join("artifacts", "alarm_drill*.json"),
                     artifact])
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_blame_bench():
    """The --blame mode: the provenance plane's measured blame drill —
    one JSON line out (never-ship-empty).

    Workload: the seeded ``chaos.blame_drill_scenario`` — ONE
    asymmetric faulty link (victim→observer acks drop at loss=1.0,
    every other link pristine) run through the composed stack with the
    provenance plane armed (``ping_req_members=0`` so the first-hand
    sighting is unambiguously fd_direct — the scenario docstring).
    Four claims measured:

      - BLAME: the host-side blame engine, fed only the recorded
        (observer, subject, transition, channel, round) attributions,
        must name the planted link's observer as ``origin_observer``
        with a first-hand ``fd_direct`` sighting — even though almost
        every other member heard the false suspicion second-hand via
        gossip;
      - ATTRIBUTION: every recorded transition carries exactly one
        channel (the channel-mix fractions sum to 1.0) with ZERO
        provenance-buffer drops and ZERO trace drops;
      - OFF-SWITCH: the same composed run with ``provenance=False`` is
        bit-identical in protocol states AND stacked metrics (the
        plane compiles out);
      - OVERHEAD: interleaved best-of wall-times, plane-armed vs the
        same composed stack without it — ``provenance_overhead_ratio``
        must stay <= query.PROVENANCE_OVERHEAD_LIMIT.

    The journal next to the artifact carries the full record set
    (manifest + counters + events + the new ``provenance`` record
    kind), so ``python -m scalecube_cluster_tpu.telemetry explain
    <journal> --observer I --subject J`` replays any belief — the
    in-bench ``explain_check`` probes the committed journal for the
    origin observer's first sighting and pins its channel and round.
    Writes an ``artifacts/provenance_blame.json``-style artifact
    (smoke runs get ``provenance_blame_smoke.json`` — provenance, the
    sync-heal convention) and runs the regress gate in-bench.
    ``--blame --smoke`` is the tier-1-safe pass pinned by
    tests/test_bench_blame_smoke.py.  Env overrides: SCALECUBE_BLAME_N,
    SCALECUBE_BLAME_SEED, SCALECUBE_BLAME_ONSET, SCALECUBE_BLAME_PULSE,
    SCALECUBE_BLAME_COOL, SCALECUBE_BLAME_VICTIM,
    SCALECUBE_BLAME_OBSERVER, SCALECUBE_BLAME_REPS,
    SCALECUBE_BLAME_CAPACITY, SCALECUBE_BLAME_ARTIFACT.

    ``value`` stays None by design: attribution correctness is a
    verdict, not a rate — regress gates the absolute blame checks
    instead.
    """
    result = {
        "metric": "provenance_blame_drill",
        "value": None,
        "unit": None,
        "smoke": SMOKE,
    }
    artifact = (os.environ.get("SCALECUBE_BLAME_ARTIFACT")
                or os.path.join("artifacts",
                                "provenance_blame_smoke.json" if SMOKE
                                else "provenance_blame.json"))
    try:
        import numpy as np

        jax, platform = init_backend()
        result["platform"] = platform

        from scalecube_cluster_tpu.chaos import scenarios as cscenarios
        from scalecube_cluster_tpu.chaos.campaign import campaign_config
        from scalecube_cluster_tpu.models import compose, swim
        from scalecube_cluster_tpu.models import provenance as mprov
        from scalecube_cluster_tpu.telemetry import query as tquery
        from scalecube_cluster_tpu.telemetry import sink as tsink
        from scalecube_cluster_tpu.telemetry import trace as ttrace

        n = int(os.environ.get("SCALECUBE_BLAME_N", 16 if SMOKE else 48))
        seed = int(os.environ.get("SCALECUBE_BLAME_SEED", 7))
        onset = int(os.environ.get("SCALECUBE_BLAME_ONSET",
                                   16 if SMOKE else 32))
        pulse = int(os.environ.get("SCALECUBE_BLAME_PULSE",
                                   64 if SMOKE else 160))
        cool = int(os.environ.get("SCALECUBE_BLAME_COOL",
                                  48 if SMOKE else 96))
        victim = int(os.environ.get("SCALECUBE_BLAME_VICTIM", 3))
        observer = int(os.environ.get("SCALECUBE_BLAME_OBSERVER", 11))
        reps = int(os.environ.get("SCALECUBE_BLAME_REPS", 40))

        scen = cscenarios.blame_drill_scenario(
            seed, n=n, victim=victim, observer=observer,
            onset_round=onset, pulse_rounds=pulse, cool_rounds=cool)
        # ping_every=1 keeps the observer's probe cadence high enough
        # that the pulse window sees several direct probes of the
        # victim; sync_interval arms the SYNC channel so the committed
        # channel mix exercises the full attribution cascade.
        overrides = dict(delivery="scatter", ping_known_only=False,
                         ping_req_members=0, ping_every=1,
                         sync_interval=8)
        p_on = swim.SwimParams.from_config(
            campaign_config(), n_members=n, provenance=True, **overrides)
        p_off = swim.SwimParams.from_config(
            campaign_config(), n_members=n, provenance=False, **overrides)
        world, _mspec = scen.build(p_on)
        key = jax.random.key(seed)

        # Capacity sized to the drill (one faulty link -> hundreds of
        # transitions, not tens of thousands): a right-sized buffer
        # keeps the scan carry cheap; overflow still counts exactly and
        # gates at zero either way.
        prov_capacity = int(os.environ.get("SCALECUBE_BLAME_CAPACITY",
                                           2048))

        def run_stack(params, armed):
            return compose.run_composed(
                key, params, world, scen.horizon, with_trace=True,
                with_metrics=True, with_monitor=False,
                with_provenance=armed,
                provenance_capacity=prov_capacity if armed else None)

        t0 = time.time()
        final_on, res_on, metrics_on = run_stack(p_on, True)
        pv = res_on["provenance"]
        tel = res_on["trace"]
        rows = mprov.decode_attributions(pv)
        log(f"blame drill: {int(pv.count)} attributions recorded "
            f"({int(pv.dropped)} dropped), {int(tel.trace.count)} trace "
            f"events ({int(tel.trace.dropped)} dropped) over "
            f"{scen.horizon} rounds ({time.time() - t0:.1f}s)")

        # ---- BLAME: the engine must name the planted origin ----------
        br = tquery.blame_report(rows, victim)
        blame_origin_correct = (
            br.get("origin_observer") == observer
            and br.get("origin_channel") == "fd_direct"
            and br.get("origin_first_hand") is True)
        log(f"blame report: verdict={br.get('verdict')} origin="
            f"{br.get('origin_observer')} via {br.get('origin_channel')} "
            f"(planted observer {observer}) -> "
            f"{'CORRECT' if blame_origin_correct else 'WRONG'}")

        # ---- ATTRIBUTION: exactly one channel per transition ---------
        mix = tquery.channel_mix(rows)
        slos = tquery.provenance_slos(rows)
        attribution = {
            "total_fraction": float(sum(mix.values())) if rows else None,
            "recorded": int(pv.count),
            "dropped": int(pv.dropped),
            "capacity": int(pv.capacity),
        }

        # ---- OFF-SWITCH: armed vs unarmed bit-identity ---------------
        final_off, res_off, metrics_off = run_stack(p_off, False)
        state_same = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree_util.tree_leaves(final_on),
                            jax.tree_util.tree_leaves(final_off)))
        metrics_same = (
            set(metrics_on) == set(metrics_off)
            and all(np.array_equal(np.asarray(metrics_on[k]),
                                   np.asarray(metrics_off[k]))
                    for k in metrics_on))
        off_switch_identical = bool(state_same and metrics_same)
        log(f"off-switch identity: states {'==' if state_same else '!='} "
            f"metrics {'==' if metrics_same else '!='}")

        # ---- JOURNAL: the full record set, explain's fixture ---------
        journal_dir = (os.environ.get(tsink.TELEMETRY_DIR_ENV)
                       or os.path.dirname(artifact) or ".")
        journal = os.path.join(
            journal_dir, "provenance_blame_journal_smoke.jsonl" if SMOKE
            else "provenance_blame_journal.jsonl")
        with tsink.TelemetrySink(path=journal) as sink:
            sink.write_manifest(p_on, scenario=scen.name,
                                repro=scen.repro())
            sink.write_counters(metrics_on)
            sink.write_events(ttrace.decode_events(tel),
                              dropped=int(tel.trace.dropped))
            sink.write_provenance(mprov.attributions_payload(pv))
            sink.write_summary(metric="provenance_blame_drill",
                               victim=victim, observer=observer,
                               onset_round=onset)
        report = tquery.load_report(journal)
        trace_dropped_total = report.counters.get("trace_dropped_total")

        # ---- EXPLAIN: the committed journal resolves the seeded query
        # (the origin observer's first sighting must be its own direct
        # probe timeout, at the blame report's onset round).
        ex = tquery.explain_belief(report.provenance, observer, victim,
                                   round_idx=br.get("onset_round"))
        ans = ex.get("answer") or {}
        explain_check = {
            "observer": observer,
            "subject": victim,
            "round": br.get("onset_round"),
            "resolved": bool(ans),
            "channel_correct": ans.get("channel") == "fd_direct",
            "round_correct": ans.get("round") == br.get("onset_round"),
            "answer": ans or None,
        }
        log(f"explain probe: observer {observer} x subject {victim} @ "
            f"round {br.get('onset_round')} -> {ans or 'UNRESOLVED'}")

        # ---- OVERHEAD: armed vs unarmed interleaved best-of ----------
        def force(out):
            jax.block_until_ready(out[0].status)

        force(run_stack(p_on, True))     # both programs warm
        force(run_stack(p_off, False))

        # One run per window, MANY interleaved windows: a composed run
        # is tens of milliseconds on this geometry and host load
        # oscillates on a similar timescale, so the stable estimator is
        # the per-arm floor over many alternated samples (each arm's
        # best window catches the host unloaded), not a handful of
        # multi-run windows that average the load spikes in.
        runs_per_window = int(os.environ.get("SCALECUBE_BLAME_WINDOW_RUNS",
                                             1))

        def run_armed(rep):
            for _ in range(runs_per_window):
                force(run_stack(p_on, True))

        def run_bare(rep):
            for _ in range(runs_per_window):
                force(run_stack(p_off, False))

        armed_best, bare_best = interleaved_best_of(
            run_armed, run_bare, reps)
        overhead = armed_best / bare_best
        log(f"provenance overhead: armed {armed_best:.3f}s vs bare "
            f"{bare_best:.3f}s per {scen.horizon}-round window (best of "
            f"{reps}, interleaved) -> ratio {overhead:.4f} (limit "
            f"{tquery.PROVENANCE_OVERHEAD_LIMIT})")

        result.update(
            blame_origin_correct=bool(blame_origin_correct),
            blame_report=br,
            channel_mix={k: round(v, 6) for k, v in mix.items()},
            removal_via_sync_fraction=slos.get(
                "removal_via_sync_fraction"),
            dissemination_hops_p99=slos.get("dissemination_hops_p99"),
            attribution=attribution,
            trace_dropped_total=trace_dropped_total,
            off_switch_identical=off_switch_identical,
            provenance_overhead_ratio=round(overhead, 4),
            provenance_armed_seconds=round(armed_best, 4),
            provenance_bare_seconds=round(bare_best, 4),
            explain_check=explain_check,
            journal=journal,
            n_members=n,
            seed=seed,
            horizon=scen.horizon,
            onset_round=onset,
            heal_round=onset + pulse,
            victim=victim,
            observer=observer,
            delivery="scatter",
            scenario=scen.name,
            repro=(f"chaos.blame_drill_scenario(seed={seed}, n={n}, "
                   f"victim={victim}, observer={observer}, "
                   f"onset_round={onset}, pulse_rounds={pulse}, "
                   f"cool_rounds={cool})"),
            value_note=("value stays null by design: attribution "
                        "correctness is a verdict, not a rate — regress "
                        "gates the absolute blame checks instead"),
        )

        art = dict(result)
        os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
        result["artifact"] = artifact
        log(f"blame artifact written to {artifact}")

        apply_regress_gate(
            result, ["BENCH_*.json", "MULTICHIP_*.json",
                     os.path.join("artifacts", "provenance_blame*.json"),
                     artifact])
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_soak_bench():
    """The --soak mode: one long-lived service lifetime under the
    seeded chaos stream, with kill/resume and drift invariants — one
    JSON line out (never-ship-empty).  Forces CPU: a correctness
    harness — the drill children must not fight over an attached TPU,
    and the guarantees under test are backend-independent.

    Three acts, one artifact:

      - the MAIN soak: ``soak.driver.run_soak`` in-process — the
        composed shape (trace ⊕ metrics ⊕ monitor ⊕ sync ⊕ lifeguard ⊕
        open-world) over ``soak.schedule.soak_schedule``'s stream, live
        alarms armed, drift sampled per segment (flat compile cache,
        bounded RSS, zero monitor violations);
      - the KILL DRILL: a sibling lineage of the SAME config is
        SIGKILLed mid-soak in a subprocess at a seeded write-stage,
        relaunched, and its merged journal's content rows
        (segment/metrics_window/alarm_transition) must be
        BYTE-identical to the main soak's with a bit-identical final
        state digest;
      - the journal is copied next to the artifact so ``python -m
        scalecube_cluster_tpu.telemetry watch`` replays the whole
        lifetime (segment boundaries included).

    ``value`` stays None by design: rounds survived is configured, not
    measured — the headline is the absolute invariant gates
    (``telemetry regress`` walks artifacts/soak_report*.json), not a
    throughput number.  ``--soak --smoke`` is the tier-1-safe pass
    pinned by tests/test_bench_soak_smoke.py.  Env overrides: module
    docstring.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    result = {
        "metric": "soak_rounds_survived",
        "value": None,
        "unit": "rounds",
        "smoke": SMOKE,
        "platform": "cpu(forced)",
    }
    artifact = (os.environ.get("SCALECUBE_SOAK_ARTIFACT")
                or os.path.join("artifacts",
                                "soak_report_smoke.json" if SMOKE
                                else "soak_report.json"))
    try:
        import logging
        import shutil
        import signal
        import tempfile

        import numpy as np

        from scalecube_cluster_tpu.resilience import (
            supervisor as rsup)
        from scalecube_cluster_tpu.soak import driver as sdrv

        n = int(os.environ.get("SCALECUBE_SOAK_N", 16 if SMOKE else 32))
        seed = int(os.environ.get("SCALECUBE_SOAK_SEED", 7))
        severity = os.environ.get("SCALECUBE_SOAK_SEVERITY", "moderate")
        segment_rounds = int(os.environ.get(
            "SCALECUBE_SOAK_SEGMENT", 128 if SMOKE else 256))
        n_segments = int(os.environ.get(
            "SCALECUBE_SOAK_SEGMENTS", 2 if SMOKE else 8))
        # The slow-arm scaling lever: a round TARGET, rounded UP to
        # whole segments (a partial tail segment would compile a second
        # program and void the compile-flat invariant by construction).
        rounds_env = os.environ.get("SCALECUBE_SOAK_ROUNDS")
        if rounds_env:
            n_segments = max(
                1, -(-int(rounds_env) // segment_rounds))
        timeout = float(os.environ.get("SCALECUBE_SOAK_TIMEOUT",
                                       600.0 if SMOKE else 3600.0))

        t0 = time.time()
        with tempfile.TemporaryDirectory(prefix="soak-") as workdir:
            cfg = sdrv.SoakConfig(
                base_path=os.path.join(workdir, "main", "soak.ckpt"),
                seed=seed, n_members=n, severity=severity,
                segment_rounds=segment_rounds, n_segments=n_segments)
            os.makedirs(os.path.dirname(cfg.base_path))
            # The supervisor logs through the logging API; adapt the
            # bench's stderr print to it.
            slog = logging.getLogger("bench.soak")
            if not slog.handlers:
                handler = logging.StreamHandler(sys.stderr)
                handler.setFormatter(
                    logging.Formatter("[bench] %(message)s"))
                slog.addHandler(handler)
                slog.setLevel(logging.INFO)
                slog.propagate = False
            soak = sdrv.run_soak(cfg, log=slog)
            main_digest = sdrv.result_digest(soak)
            main_rows = sdrv.content_rows(cfg.journal_path)
            log(f"soak main: {cfg.n_rounds} rounds / "
                f"{n_segments} segments, drift ok={soak.drift['ok']}, "
                f"{soak.alarms['transitions']} alarm transition(s) "
                f"({time.time() - t0:.1f}s)")

            # The seeded mid-soak kill: same config, own lineage; the
            # MAIN soak is the uninterrupted reference (same process
            # env, both on forced CPU — no backend seam to cross).
            rng = np.random.default_rng(
                np.random.SeedSequence([seed, 0x50AC]))
            kill_round = int(rng.integers(
                1, n_segments) * segment_rounds) if n_segments > 1 \
                else segment_rounds
            stage = rsup.KILL_STAGES[
                int(rng.integers(len(rsup.KILL_STAGES)))]
            kcfg = sdrv.SoakConfig(
                base_path=os.path.join(workdir, "killed", "soak.ckpt"),
                seed=seed, n_members=n, severity=severity,
                segment_rounds=segment_rounds, n_segments=n_segments)
            os.makedirs(os.path.dirname(kcfg.base_path))
            cfg_path = os.path.join(workdir, "killed_config.json")
            plan = rsup.KillPlan(round=kill_round, stage=stage)
            t1 = time.time()
            killed = sdrv.launch_child(
                kcfg, cfg_path, kill_plan=plan, timeout=timeout,
                extra_env={"JAX_PLATFORMS": "cpu"})
            drill = {"kill": plan.encode(), "ok": False}
            if killed.returncode != -signal.SIGKILL:
                drill["error"] = (f"kill did not land "
                                  f"(rc={killed.returncode})")
                drill["stderr_tail"] = killed.stderr[-2000:]
            else:
                relaunch = sdrv.launch_child(
                    kcfg, cfg_path, timeout=timeout,
                    extra_env={"JAX_PLATFORMS": "cpu"})
                if relaunch.returncode != 0:
                    drill["error"] = "relaunch failed"
                    drill["stderr_tail"] = relaunch.stderr[-2000:]
                else:
                    summary = json.loads(
                        [ln for ln in
                         relaunch.stdout.strip().splitlines()
                         if ln][-1])
                    got_rows = sdrv.content_rows(kcfg.journal_path)
                    drill.update(
                        ok=bool(got_rows == main_rows
                                and summary["state_digest"]
                                == main_digest),
                        journal_match=got_rows == main_rows,
                        state_match=(summary["state_digest"]
                                     == main_digest),
                        content_rows=len(got_rows),
                        resumed_segments=summary["segments_run"],
                        seconds=round(time.time() - t1, 2),
                    )
            log(f"soak kill drill at {plan.encode()}: "
                f"{'green' if drill['ok'] else 'RED ' + json.dumps(drill)}")

            journal_copy = os.path.join(
                os.path.dirname(artifact) or ".",
                "soak_journal_smoke.jsonl" if SMOKE
                else "soak_journal.jsonl")
            os.makedirs(os.path.dirname(artifact) or ".",
                        exist_ok=True)
            shutil.copyfile(cfg.journal_path, journal_copy)

        result.update(
            rounds_survived=cfg.n_rounds,
            segments=n_segments,
            segment_rounds=segment_rounds,
            violations=soak.drift["violations"],
            drift=soak.drift,
            alarms=soak.alarms,
            kill_drill=drill,
            state_digest=main_digest,
            journal=journal_copy,
            n_members=n,
            seed=seed,
            severity=severity,
            scenario=soak.scenario_name,
            seconds=round(time.time() - t0, 2),
            repro=(f"soak.driver.run_soak(SoakConfig(base_path=..., "
                   f"seed={seed}, n_members={n}, "
                   f"severity={severity!r}, "
                   f"segment_rounds={segment_rounds}, "
                   f"n_segments={n_segments}))"),
            value_note=("value stays null by design: rounds survived "
                        "is configured, not measured — regress gates "
                        "the absolute drift/drill invariants instead"),
        )
        log(f"soak headline: {cfg.n_rounds} rounds survived, "
            f"violations={soak.drift['violations']}, compile flat="
            f"{soak.drift['compile_flat']}, drill ok={drill['ok']}")

        art = dict(result)
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
        result["artifact"] = artifact
        log(f"soak artifact written to {artifact}")

        apply_regress_gate(
            result, ["BENCH_*.json",
                     os.path.join("artifacts", "soak_report*.json"),
                     artifact])
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_churn_bench():
    """The --churn mode: the open-world membership plane's headline
    robustness claim, measured A/B (never asserted) — one JSON line out
    (never-ship-empty).

    Workload: the seeded ``chaos.churn_growth_scenario`` NET-POSITIVE
    arrival storm — permanent crash waves recycled by mid-run JOINs
    (plus a pre-dead arrivals pool, so the cluster GROWS), with each
    join landing mid-suspicion of the previous occupant and the
    occupants dying at incarnation >= 1 (the pre-death scare) — the
    adversarial slot-recycling window.  Each scenario seed runs the
    monitored scan TWICE on the same key:

      - the PLANE (``open_world=True`` with the identity-epoch guard):
        the committed claim is ZERO NO_RESURRECTION and ZERO
        JOIN_COMPLETENESS violations, with every join globally known
        within the dissemination bound — ``join_propagation_p99``
        (rounds from the join to each observer's JOINED admission,
        from the traced run's event stream) gated absolutely against
        the scenario's join deadline offset;
      - the NAIVE-reuse control (``epoch_guard=False`` — the
        reference's epoch-blind wire): the monitor's incarnation
        forensics count the resurrection failures
        (NO_RESURRECTION > 0 required — the control arm must
        DEMONSTRATE the hazard the guard kills) and the
        identity-confusion refutation burn rides along.

    Writes an ``artifacts/churn_growth.json``-style artifact (smoke
    runs get ``churn_growth_smoke.json`` — provenance, the sync-heal
    convention) and runs the regress gate in-bench.  ``--churn
    --smoke`` is the tier-1-safe single-scenario pass pinned by
    tests/test_bench_churn_smoke.py.  Env overrides: SCALECUBE_CHURN_N,
    SCALECUBE_CHURN_SEED, SCALECUBE_CHURN_SCENARIOS,
    SCALECUBE_CHURN_SUPPRESS (dead_suppress_rounds on both arms),
    SCALECUBE_CHURN_ARTIFACT.

    ``value`` stays None by design: the headline is a pair of absolute
    zero/non-zero violation gates plus a latency SLO, none of which
    belong in the higher-is-better throughput walk.
    """
    result = {
        "metric": "churn_growth",
        "value": None,
        "unit": "violations/rounds",
        "smoke": SMOKE,
    }
    artifact = (os.environ.get("SCALECUBE_CHURN_ARTIFACT")
                or os.path.join("artifacts",
                                "churn_growth_smoke.json" if SMOKE
                                else "churn_growth.json"))
    try:
        jax, platform = init_backend()
        result["platform"] = platform

        import dataclasses

        import numpy as np

        from scalecube_cluster_tpu.chaos import monitor as cmonitor
        from scalecube_cluster_tpu.chaos import scenarios as cscenarios
        from scalecube_cluster_tpu.chaos.campaign import campaign_params
        from scalecube_cluster_tpu.models import swim
        from scalecube_cluster_tpu.telemetry import trace as ttrace
        from scalecube_cluster_tpu.telemetry.events import TraceEventType

        n = int(os.environ.get("SCALECUBE_CHURN_N", 24 if SMOKE else 48))
        seed = int(os.environ.get("SCALECUBE_CHURN_SEED", 3))
        n_scen = int(os.environ.get("SCALECUBE_CHURN_SCENARIOS",
                                    1 if SMOKE else 3))
        suppress = int(os.environ.get("SCALECUBE_CHURN_SUPPRESS", 0))

        guard_counts = {"NO_RESURRECTION": 0, "JOIN_COMPLETENESS": 0}
        naive_counts = {"NO_RESURRECTION": 0, "JOIN_COMPLETENESS": 0}
        guard_green = True
        latencies = []
        refutes = {"guard": 0, "naive": 0}
        joins_total = 0
        growth_total = 0
        bound = None
        scenario_rows = []
        for s_i in range(n_scen):
            scen = cscenarios.churn_growth_scenario(seed + s_i, n)
            p_guard = campaign_params(
                scen, delivery="shift", dead_suppress_rounds=suppress)
            p_naive = dataclasses.replace(p_guard, epoch_guard=False)
            world, spec = scen.build(p_guard)
            join_at = np.asarray(world.join_at)
            known_by = np.asarray(spec.join_known_by)
            joined = join_at < np.iinfo(np.int32).max
            joins_total += int(joined.sum())
            bound = int((known_by[joined] - join_at[joined]).max())
            growth_total += int(
                np.asarray(world.alive_at(scen.horizon - 1)).sum()
                - np.asarray(world.alive_at(0)).sum())
            row = {"scenario": scen.name, "horizon": scen.horizon,
                   "repro": f"chaos.churn_growth_scenario("
                            f"seed={seed + s_i}, n={n})"}
            for arm, p in (("guard", p_guard), ("naive", p_naive)):
                t0 = time.time()
                w_arm, spec_arm = scen.build(p)
                _, mon, metrics = cmonitor.run_monitored(
                    jax.random.key(seed + s_i), p, w_arm, spec_arm,
                    scen.horizon)
                v = cmonitor.verdict(mon)
                counts = {c: v["codes"][c]["violations"]
                          for c in ("NO_RESURRECTION",
                                    "JOIN_COMPLETENESS")}
                target = guard_counts if arm == "guard" else naive_counts
                for c, x in counts.items():
                    target[c] += x
                if arm == "guard":
                    guard_green = guard_green and v["green"]
                refutes[arm] += int(
                    np.asarray(metrics["refutations"]).sum())
                row[f"violations_{arm}"] = {
                    c: d["violations"]
                    for c, d in v["codes"].items() if d["violations"]}
                log(f"churn {scen.name} arm={arm}: "
                    f"green={v['green']} join_codes={counts} "
                    f"({time.time() - t0:.1f}s)")
            # Join-propagation latency from the GUARD arm's traced run
            # (same key: bit-identical protocol trajectory — the trace
            # plane only observes).
            _, tel, _ = swim.run_traced(
                jax.random.key(seed + s_i), p_guard, world, scen.horizon)
            ev = [e for e in ttrace.decode_events(tel)
                  if e.event_type == TraceEventType.JOINED]
            lat = [int(e.round - join_at[e.subject]) for e in ev]
            latencies.extend(lat)
            row["joined_events"] = len(ev)
            scenario_rows.append(row)

        p99 = (float(np.percentile(latencies, 99)) if latencies
               else None)
        log(f"churn headline: guard {guard_counts} (green={guard_green})"
            f" naive {naive_counts} join_p99={p99} bound={bound} "
            f"refutes={refutes}")
        result.update(
            no_resurrection_violations=guard_counts["NO_RESURRECTION"],
            join_completeness_violations=guard_counts[
                "JOIN_COMPLETENESS"],
            guard_green=guard_green,
            naive_no_resurrection_violations=naive_counts[
                "NO_RESURRECTION"],
            naive_join_completeness_violations=naive_counts[
                "JOIN_COMPLETENESS"],
            join_propagation_p99_rounds=p99,
            join_propagation_bound_rounds=bound,
            joined_events=len(latencies),
            joins_admitted=joins_total,
            net_growth_members=growth_total,
            refutations_guard=refutes["guard"],
            refutations_naive=refutes["naive"],
            n_members=n,
            seed=seed,
            n_scenarios=n_scen,
            dead_suppress_rounds=suppress,
            delivery="shift",
            scenarios=scenario_rows,
            value_note=("value stays null by design: the headline is "
                        "absolute violation/latency gates, not a "
                        "throughput — regress gates the dedicated "
                        "churn checks instead"),
        )

        art = dict(result)
        os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
        result["artifact"] = artifact
        log(f"churn artifact written to {artifact}")

        apply_regress_gate(
            result, ["BENCH_*.json", "MULTICHIP_*.json",
                     os.path.join("artifacts", "churn_growth*.json"),
                     artifact])
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_fuzz_bench():
    """The --fuzz mode: the vmapped chaos mega-campaign (module
    docstring) — one JSON line out (never-ship-empty).

    Three stages, all over the SAME generated scenario batch:

      1. *verdict pass* — the batch bucketed by compiled shape and run
         through ``chaos.run_campaign_vmapped`` (this also warms the
         vmapped compiles and writes the JSONL manifest with its
         ``chaos_bucket`` rows — bucket sizes are never silent);
      2. *speed* — sequential one-``run_monitored``-per-scenario sweep
         vs the per-bucket vmapped sweep, interleaved best-of windows
         (the ``interleaved_best_of`` discipline): scenarios/sec,
         aggregate member-rounds/sec, and ``vmap_speedup_ratio``;
      3. *coverage* — the completeness-promising slice rerun on the
         deliberately-weakened build (``chaos.weakened_knobs``: a
         dynamic-knobs change, so the rerun reuses the healthy
         compiled programs): the fuzzer must FIND the planted
         violations (> 0) while the healthy arm found none.

    ``value`` stays None by design: scenarios/sec is host-dependent and
    the quality gates are absolute — regress walks the dedicated fuzz
    checks instead (telemetry/query.py).
    """
    result = {
        "metric": "fuzz_campaign",
        "value": None,
        "unit": "scenarios/sec",
        "smoke": SMOKE,
    }
    artifact = (os.environ.get("SCALECUBE_FUZZ_ARTIFACT")
                or os.path.join("artifacts",
                                "fuzz_campaign_smoke.json" if SMOKE
                                else "fuzz_campaign.json"))
    try:
        jax, platform = init_backend()
        result["platform"] = platform

        from scalecube_cluster_tpu.chaos import campaign as ccampaign
        from scalecube_cluster_tpu.chaos import monitor as cmonitor
        from scalecube_cluster_tpu.chaos import scenarios as cscenarios
        from scalecube_cluster_tpu.telemetry import sink as tsink
        from scalecube_cluster_tpu.utils import runlog

        n = int(os.environ.get("SCALECUBE_FUZZ_N", 16 if SMOKE else 32))
        per_tier = int(os.environ.get("SCALECUBE_FUZZ_SEEDS_PER_TIER",
                                      1 if SMOKE else 334))
        seed = int(os.environ.get("SCALECUBE_FUZZ_SEED", 100))
        reps = int(os.environ.get("SCALECUBE_FUZZ_REPS", 2))
        # Evidence-lane capacity: the monitor carries [B, capacity, 5]
        # through the batched scan, so the fuzz path trims the buffer
        # (green runs need none; exact per-code totals are uncapped
        # either way) — applied to BOTH timed arms for a fair ratio.
        capacity = int(os.environ.get("SCALECUBE_FUZZ_CAPACITY", 256))

        scens = cscenarios.generate_fuzz_campaign(seed, per_tier, n=n)
        member_rounds = sum(s.n_members * s.horizon for s in scens)
        rlog = runlog.get_logger("bench")
        buckets = ccampaign.build_buckets(scens, seed=seed,
                                          delivery="shift", log=rlog)
        log(f"fuzz: {len(scens)} scenarios ({per_tier}/tier) at n={n} -> "
            f"{len(buckets)} compile buckets "
            f"(sizes {[b.size for b in buckets]}), "
            f"{member_rounds} member-rounds per sweep")

        def force(mon):
            runlog.completion_barrier(mon.code_counts)

        # ---- stage 1: verdicts + manifest (vmapped compile warm-up) ----
        t0 = time.time()
        with tsink.TelemetrySink.from_env(
                default_dir=os.path.join("artifacts", "telemetry"),
                prefix="fuzz-smoke" if SMOKE else "fuzz") as sink:
            campaign_res = ccampaign.run_campaign_vmapped(
                scens, seed=seed, delivery="shift", capacity=capacity,
                sink=sink, log=rlog, buckets=buckets)
        summary = campaign_res.summary()
        log(f"fuzz verdict pass: {summary['green_scenarios']}/"
            f"{summary['scenarios']} green in {time.time() - t0:.1f}s "
            f"(vmapped compiles included)")

        # ---- stage 2: interleaved sequential-vs-vmapped timing ---------
        def seq_sweep(rep=0):
            mon = None
            for b in buckets:
                for i, (world, spec) in zip(b.indices, b.members):
                    _, mon, _ = cmonitor.run_monitored(
                        jax.random.key(seed + i), b.params, world, spec,
                        b.horizon, capacity=capacity)
            force(mon)

        def vmap_sweep(rep=0):
            mon = None
            for b in buckets:
                mon, _ = ccampaign.run_bucket(b, capacity=capacity)
            force(mon)

        t0 = time.perf_counter()
        seq_sweep()
        log(f"fuzz: sequential compile+first sweep took "
            f"{time.perf_counter() - t0:.1f}s")
        s_best, v_best = interleaved_best_of(seq_sweep, vmap_sweep, reps)
        ratio = round(s_best / v_best, 4)
        seq_rate = len(scens) / s_best
        vmap_rate = len(scens) / v_best
        log(f"fuzz: sequential {s_best:.3f}s vs vmapped {v_best:.3f}s "
            f"per sweep (best of {reps}, interleaved) -> "
            f"{seq_rate:.2f} / {vmap_rate:.2f} scenarios/sec "
            f"(vmap speedup x{ratio})")

        # ---- stage 3: weakened-build coverage arm ----------------------
        t0 = time.time()
        cov, weak_counts, first_red = ccampaign.run_weakened_slice(
            buckets, capacity=capacity)
        healthy_on_slice = sum(
            campaign_res.verdicts[i].verdict["total_violations"]
            for i in cov)
        first_repro = None
        if first_red is not None:
            first_repro = (
                f"chaos.run_scenario({scens[first_red].repro()}, "
                f"seed={seed + first_red}, delivery='shift', "
                f"knobs=lambda p: chaos.weakened_knobs(None, p))")
        weak_by_code = {
            cmonitor.InvariantCode(c).name: int(weak_counts[c])
            for c in range(cmonitor.N_CODES) if weak_counts[c]
        }
        coverage = {
            "scenarios": len(cov),
            "weakened_violations": int(weak_counts.sum()),
            "weakened_by_code": weak_by_code,
            "healthy_violations": int(healthy_on_slice),
            "planted": ("suspicion timers stretched past the horizon "
                        "(chaos.weakened_knobs): permanent crashes are "
                        "never removed, so every completeness-promising "
                        "scenario must trip COMPLETENESS"),
            "first_repro": first_repro,
        }
        log(f"fuzz coverage arm: {len(cov)} completeness-promising "
            f"scenarios, weakened violations "
            f"{coverage['weakened_violations']} {weak_by_code}, healthy "
            f"violations {healthy_on_slice} ({time.time() - t0:.1f}s, "
            f"compiled programs reused)")

        result.update(
            scenario_throughput=round(vmap_rate, 3),
            scenario_throughput_sequential=round(seq_rate, 3),
            member_rounds_per_sec=round(member_rounds / v_best, 1),
            vmap_speedup_ratio=ratio,
            scenarios=len(scens),
            seeds_per_tier=per_tier,
            green=summary["green"],
            green_scenarios=summary["green_scenarios"],
            violations_by_code=summary["violations_by_code"],
            failing_repros=summary["failing_repros"][:8],
            buckets=campaign_res.buckets,
            coverage=coverage,
            n_members=n,
            seed=seed,
            capacity=capacity,
            delivery="shift",
            manifest=campaign_res.manifest_path,
            value_note=("value stays null by design: scenarios/sec is "
                        "host-dependent and the coverage gates are "
                        "absolute — regress walks the dedicated fuzz "
                        "checks instead"),
        )

        art = dict(result)
        os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
        result["artifact"] = artifact
        log(f"fuzz artifact written to {artifact}")

        apply_regress_gate(
            result, ["BENCH_*.json",
                     os.path.join("artifacts", "fuzz_campaign*.json"),
                     artifact])
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_compose_bench():
    """The --compose mode: the FULL instrumented stack (event trace ⊕
    invariant monitor ⊕ health registry) through the composed plane
    runner's ONE scan (models/compose.run_composed) A/B'd against the
    pre-compose alias-by-alias route — run_traced + run_metered +
    run_monitored sequentially, which is what obtaining all three
    instrumented outputs cost before compose() existed: three XLA
    programs, three passes over the rounds, each re-deriving the
    per-round live masks / status-change gates / wide decodes the
    composed body computes once.  A bare ``swim.run`` arm anchors the
    overhead ratios, all three arms on one rotated-order interleaved
    best-of discipline; a PARITY probe asserts the composed outputs are
    bit-identical to the alias outputs before anything is timed.

    A separate COMPILE-COST arm counts programs compiled (jit cache
    misses) and compile+first-run wall seconds across an entry-point ×
    layout matrix at a small fresh N: head-style full instrumentation
    compiles three programs per layout, the composed stack ONE — the
    strictly-reduced compile count the regress gate pins.

    Writes an ``artifacts/compose_perf.json``-style artifact (smoke
    runs get ``compose_perf_smoke.json`` — provenance, the sync-heal
    convention) with ``compose_speedup_ratio`` (head-style seconds /
    composed seconds, >= 1.0 floor), ``full_stack_overhead_ratio``
    (composed vs bare — must be no worse than the head-style overhead)
    and the compile counts, walked by ``telemetry regress``.  Env
    overrides: SCALECUBE_COMPOSE_ARTIFACT, SCALECUBE_BENCH_N,
    SCALECUBE_BENCH_ROUNDS.
    """
    result = {
        "metric": "swim_compose_full_stack_member_rounds_per_sec",
        "value": None,
        "unit": "member-rounds/sec (composed full stack)",
        "smoke": SMOKE,
    }
    artifact = os.environ.get("SCALECUBE_COMPOSE_ARTIFACT") or os.path.join(
        "artifacts",
        "compose_perf_smoke.json" if SMOKE else "compose_perf.json",
    )
    try:
        jax, platform = init_backend()
        result["platform"] = platform
        import numpy as np

        from scalecube_cluster_tpu.chaos import monitor as cmonitor
        from scalecube_cluster_tpu.config import ClusterConfig
        from scalecube_cluster_tpu.models import compose, swim
        from scalecube_cluster_tpu.telemetry import metrics as tmetrics
        from scalecube_cluster_tpu.utils import runlog

        def force(state):
            return runlog.completion_barrier(state.status)

        params, world, key = bench_workload(N_MEMBERS)
        rounds = BENCH_ROUNDS
        spec = cmonitor.MonitorSpec.passive(params)
        mspec = tmetrics.MetricsSpec.default()
        _, cap = traced_window_policy(N_MEMBERS, rounds)

        def head_style(state3, start):
            """The pre-compose route to full instrumentation: three
            aliases, three scans, three sets of outputs."""
            ts, es, os_ = state3
            ts, tel, _ = swim.run_traced(key, params, world, rounds,
                                         trace_capacity=cap, state=ts,
                                         start_round=start)
            es, ms, _ = swim.run_metered(key, params, world, rounds,
                                         spec=mspec, state=es,
                                         start_round=start)
            os_, mon, _ = cmonitor.run_monitored(key, params, world, spec,
                                                 rounds, state=os_,
                                                 start_round=start)
            return (ts, es, os_), tel, ms, mon

        def composed(state, start):
            return compose.run_composed(
                key, params, world, rounds, monitor_spec=spec,
                trace_capacity=cap, metrics_spec=mspec, state=state,
                start_round=start)

        def force_head(state3):
            # The head arm runs THREE separate programs: block on each
            # one's output, or async dispatch leaks the metered/
            # monitored work into the next arm's timing window.
            for st in state3:
                force(st)

        # Warm-up compiles + the PARITY probe: the composed stack's
        # outputs must be bit-identical to the alias outputs on the
        # same inputs before any timing means anything.
        t0 = time.perf_counter()
        h_states = tuple(swim.initial_state(params, world)
                         for _ in range(3))
        h_states, tel, ms, mon = head_style(h_states, 0)
        force_head(h_states)
        c_state, c_res, _ = composed(swim.initial_state(params, world), 0)
        force(c_state)
        b_state, _ = swim.run(key, params, world, rounds,
                              state=swim.initial_state(params, world),
                              start_round=0)
        force(b_state)
        log(f"compose@{N_MEMBERS}: compile+first-run (all arms) took "
            f"{time.perf_counter() - t0:.1f}s")

        def eq(a, b):
            return bool(np.array_equal(np.asarray(a), np.asarray(b)))

        parity = {
            "final_status": eq(h_states[0].status, c_state.status),
            "trace_lanes": eq(tel.trace.lanes, c_res["trace"].trace.lanes),
            "trace_count": eq(tel.trace.count, c_res["trace"].trace.count),
            "monitor_code_counts": eq(mon.code_counts,
                                      c_res["monitor"].code_counts),
            # chaos_violations rides only the monitored-metered /
            # composed registry, so compare every OTHER counter lane.
            "metrics_counters": all(
                eq(ms.counters[i], c_res["metrics"].counters[i])
                for i, name in enumerate(mspec.counters)
                if name != "chaos_violations"),
        }
        result["parity"] = parity
        if not all(parity.values()):
            raise AssertionError(f"composed != alias outputs: {parity}")

        # Rotated-order three-arm interleave (the interleaved_best_of
        # discipline generalized): host drift biases every arm equally.
        reps = 6 if SMOKE else 3
        best = {"bare": None, "head": None, "composed": None}
        states = {"bare": b_state, "head": h_states, "composed": c_state}
        order = ("bare", "head", "composed")
        for rep in range(reps):
            start = rounds * (1 + rep)
            for tag in order[rep % 3:] + order[:rep % 3]:
                t0 = time.perf_counter()
                if tag == "bare":
                    states[tag], _ = swim.run(key, params, world, rounds,
                                              state=states[tag],
                                              start_round=start)
                    force(states[tag])
                elif tag == "head":
                    states[tag], _, _, _ = head_style(states[tag], start)
                    force_head(states[tag])
                else:
                    states[tag], _, _ = composed(states[tag], start)
                    force(states[tag])
                dt = time.perf_counter() - t0
                best[tag] = dt if best[tag] is None else min(best[tag], dt)

        c_rate = N_MEMBERS * rounds / best["composed"]
        h_rate = N_MEMBERS * rounds / best["head"]
        b_rate = N_MEMBERS * rounds / best["bare"]
        speedup = round(best["head"] / best["composed"], 4)
        log(f"compose@{N_MEMBERS}: bare {best['bare']:.3f}s / composed "
            f"{best['composed']:.3f}s / head-style {best['head']:.3f}s "
            f"per {rounds}-round window (best of {reps}, interleaved) -> "
            f"compose_speedup_ratio {speedup}")
        result.update(
            value=round(c_rate, 1),
            composed_member_rounds_per_sec=round(c_rate, 1),
            head_style_member_rounds_per_sec=round(h_rate, 1),
            bare_member_rounds_per_sec=round(b_rate, 1),
            compose_speedup_ratio=speedup,
            full_stack_overhead_ratio=round(best["composed"]
                                            / best["bare"], 4),
            head_style_overhead_ratio=round(best["head"]
                                            / best["bare"], 4),
            n_members=N_MEMBERS,
            rounds_timed=rounds,
            delivery=DELIVERY,
            rounds_per_step=resolve_rounds_per_step(),
            trace_capacity=cap,
        )

        # COMPILE-COST arm: fresh tiny-N signatures per layout, jit
        # cache misses counted per entry — full instrumentation costs
        # head-style THREE programs per layout, composed ONE.
        layouts = [
            ("focal-scatter", dict(delivery="scatter")),
            ("focal-shift", dict(delivery="shift")),
        ]
        if not SMOKE:
            layouts += [
                ("compact-scatter", dict(delivery="scatter",
                                         compact_carry=True)),
                ("wire24-fused", dict(delivery="scatter",
                                      compact_carry=True, wire24=True)),
            ]
        compile_n, compile_rounds = 64, 4
        rows = []
        total_head = total_comp = 0
        sec_head = sec_comp = 0.0
        for lname, overrides in layouts:
            lp = swim.SwimParams.from_config(
                ClusterConfig.default(), n_members=compile_n,
                n_subjects=16, **overrides)
            lw = swim.SwimWorld.healthy(lp)
            lspec = cmonitor.MonitorSpec.passive(lp)

            def misses(fn, thunk):
                before = fn._cache_size()
                t0 = time.perf_counter()
                jax.block_until_ready(thunk()[0].status)
                return fn._cache_size() - before, time.perf_counter() - t0

            mh = sh = 0
            for fn, thunk in (
                (swim.run_traced,
                 lambda: swim.run_traced(key, lp, lw, compile_rounds)),
                (swim.run_metered,
                 lambda: swim.run_metered(key, lp, lw, compile_rounds)),
                (cmonitor.run_monitored,
                 lambda: cmonitor.run_monitored(key, lp, lw, lspec,
                                                compile_rounds)),
            ):
                m, s = misses(fn, thunk)
                mh += m
                sh += s
            mc, sc = misses(
                compose.run_composed,
                lambda: compose.run_composed(key, lp, lw, compile_rounds,
                                             monitor_spec=lspec))
            rows.append({"layout": lname, "programs_head_style": mh,
                         "programs_composed": mc,
                         "seconds_head_style": round(sh, 2),
                         "seconds_composed": round(sc, 2)})
            total_head += mh
            total_comp += mc
            sec_head += sh
            sec_comp += sc
            log(f"compose compile[{lname}]: head-style {mh} programs "
                f"{sh:.1f}s vs composed {mc} programs {sc:.1f}s")
        result["compile"] = {
            "layouts": rows,
            "programs_head_style": total_head,
            "programs_composed": total_comp,
            "seconds_head_style": round(sec_head, 2),
            "seconds_composed": round(sec_comp, 2),
        }

        art = dict(result)
        os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
        result["artifact"] = artifact
        log(f"compose artifact written to {artifact}")

        apply_regress_gate(
            result, ["BENCH_*.json",
                     os.path.join("artifacts", "compose_perf*.json"),
                     artifact])
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def run_tune_bench():
    """The --tune mode: device-parallel protocol autotuning
    (tune/search.py) — one JSON line out (never-ship-empty).

    Four stages over one seeded tune workload (generated campaign
    scenarios, join storms excluded, health planes enabled at the knob
    ceilings):

      1. *sweep* — every knob-grid config and every shipped profile
         over every scenario shape bucket through the scored plane
         stack (trace ⊕ passive monitor on the batched composed scan).
         Knob data is traced, so the whole grid reuses ONE compiled
         program per shape bucket — the jit-cache miss counts are the
         witness (``tune_compiles == tune_shape_buckets``, and the
         timed warm pass adds ZERO);
      2. *throughput* — a second full grid pass over the warm
         programs: ``tune_grid_throughput`` = configs x member-rounds
         per wall second, scoring included;
      3. *speedup* — what the traced-knob batching actually buys: the
         grid swept with dynamic knobs (ONE compile per shape bucket,
         every config a warm rerun) vs the same grid swept the static
         way (each config baked into ``SwimParams`` -> a FRESH compile
         per config x bucket, measured on real cold configs and
         extrapolated to the grid): ``batch_speedup_ratio`` with a
         >= 1.0 regress floor.  The warm-path control — one
         ``composed_batch_scan`` call per bucket vs one
         ``composed_scan`` call per scenario, interleaved best-of
         windows — ships alongside as ``batch_dispatch_ratio``
         (informational: on CPU at small widths the two warm paths
         are within noise of parity; the compile amortization is the
         win);
      4. *profiles* — the Pareto frontier over green rows, and every
         shipped profile (``SwimParams.tuned``) checked non-dominated
         vs the reference row and revalidated by the FULL fuzz oracle
         (completeness deadlines rebuilt under the profile's static
         schedule) on held-out seeds.

    ``value`` stays None by design: grid throughput is host-dependent
    and the quality gates are absolute — regress walks the dedicated
    tune checks instead (telemetry/query.py).
    """
    result = {
        "metric": "tune_pareto",
        "value": None,
        "unit": "config-member-rounds/sec",
        "smoke": SMOKE,
    }
    artifact = (os.environ.get("SCALECUBE_TUNE_ARTIFACT")
                or os.path.join("artifacts",
                                "tune_pareto_smoke.json" if SMOKE
                                else "tune_pareto.json"))
    try:
        jax, platform = init_backend()
        result["platform"] = platform

        import dataclasses

        import jax.numpy as jnp

        from scalecube_cluster_tpu.chaos import campaign as ccampaign
        from scalecube_cluster_tpu.chaos import monitor as cmonitor
        from scalecube_cluster_tpu.models import swim
        from scalecube_cluster_tpu.tune import profiles as tprofiles
        from scalecube_cluster_tpu.tune import search as tsearch
        from scalecube_cluster_tpu.utils import runlog

        n = int(os.environ.get("SCALECUBE_TUNE_N", 16 if SMOKE else 32))
        n_scen = int(os.environ.get("SCALECUBE_TUNE_SCENARIOS",
                                    6 if SMOKE else 12))
        seed = int(os.environ.get("SCALECUBE_TUNE_SEED", 500))
        held_out = int(os.environ.get("SCALECUBE_TUNE_HELDOUT_SEED",
                                      7001))
        per_tier = int(os.environ.get("SCALECUBE_TUNE_FUZZ_PER_TIER",
                                      1 if SMOKE else 2))
        reps = int(os.environ.get("SCALECUBE_TUNE_REPS", 2))
        capacity = int(os.environ.get("SCALECUBE_TUNE_CAPACITY", 256))
        trace_cap = tsearch.DEFAULT_TRACE_CAPACITY

        scens = tsearch.tune_scenarios(seed, n_scen, n=n, log=log)
        result.update(scenarios=len(scens), n_members=n, seed=seed,
                      delivery="shift", capacity=capacity)

        # ---- stage 1: the sweep (compiles included) -------------------
        t0 = time.time()
        rows, info = tsearch.sweep(scens, seed=seed, smoke=SMOKE,
                                   capacity=capacity, log=log)
        sweep_s = time.time() - t0
        log(f"tune sweep: {info['configs']} configs x "
            f"{info['scenarios']} scenarios in {sweep_s:.1f}s "
            f"— {info['calls']} device calls, {info['compiles']} "
            f"compiles ({info['shape_buckets']} shape buckets)")

        # ---- stage 2: timed warm grid pass ----------------------------
        configs = ([{"name": r["name"], "overrides": r["overrides"],
                     "profile": r["profile"]} for r in rows])
        t0 = time.perf_counter()
        _, warm_info = tsearch.sweep(scens, configs=configs, seed=seed,
                                     capacity=capacity)
        grid_s = time.perf_counter() - t0
        throughput = warm_info["member_rounds"] * warm_info["configs"] / grid_s
        log(f"tune warm grid pass: {grid_s:.2f}s -> "
            f"{throughput:,.0f} config-member-rounds/sec "
            f"({warm_info['compiles']} recompiles)")

        # ---- stage 3: batched-vs-sequential speedup -------------------
        buckets = ccampaign.build_buckets(
            scens, seed=seed, delivery="shift",
            **tsearch.TUNE_PARAM_OVERRIDES)
        specs = [tsearch.passive_specs(b.params, b.size)
                 for b in buckets]
        row_specs = [cmonitor.MonitorSpec.passive(b.params)
                     for b in buckets]
        batch_kn = [tsearch.config_knobs(b.params, {}, b.size)
                    for b in buckets]
        row_kn = [jax.tree.map(jnp.asarray, swim.Knobs.from_params(b.params))
                  for b in buckets]

        def force(mon):
            runlog.completion_barrier(mon.code_counts)

        def batch_sweep(rep=0):
            mon = None
            for b, sp, kn in zip(buckets, specs, batch_kn):
                _, mon, _ = tsearch._sweep_bucket(
                    b.keys, b.params, b.worlds, sp, b.horizon, kn,
                    capacity, trace_cap)
            force(mon)

        def seq_sweep(rep=0):
            mon = None
            for b, sp, kn in zip(buckets, row_specs, row_kn):
                for i, (world, _spec) in zip(b.indices, b.members):
                    _, mon, _ = tsearch._row_run(
                        jax.random.key(seed + i), b.params, world, sp,
                        b.horizon, kn, capacity, trace_cap)
            force(mon)

        # The warm-path control arm is full-mode only: it exists to
        # show the vmap costs nothing once compiled (parity), and the
        # per-bucket _row_run compiles it needs are the wrong place to
        # spend the smoke budget.
        dispatch_ratio = None
        if not SMOKE:
            t0 = time.perf_counter()
            seq_sweep()
            log(f"tune: sequential compile+first sweep "
                f"{time.perf_counter() - t0:.1f}s")
            s_best, b_best = interleaved_best_of(seq_sweep, batch_sweep,
                                                 reps)
            dispatch_ratio = round(s_best / b_best, 4)
            log(f"tune: warm sequential {s_best:.3f}s vs warm batched "
                f"{b_best:.3f}s per reference sweep (best of {reps}, "
                f"interleaved) -> dispatch ratio x{dispatch_ratio}")

        # The gated headline: the static counterfactual.  Without
        # traced knobs the ONLY way to sweep a schedule config is to
        # bake it into SwimParams — a fresh XLA program per config x
        # shape bucket.  Measure that cost on k real cold configs
        # (overrides applied via dataclasses.replace -> guaranteed
        # jit-cache misses), extrapolate to the grid, and compare
        # against the measured stage-1 dynamic sweep (its own compiles
        # AND host scoring included — the conservative side).
        k_static = int(os.environ.get("SCALECUBE_TUNE_STATIC_CONFIGS",
                                      1 if SMOKE else 2))
        static_cfgs = [c for c in tsearch.default_grid(
            buckets[0].params, smoke=SMOKE) if c["overrides"]][:k_static]
        t0 = time.perf_counter()
        for cfg in static_cfgs:
            mon = None
            for b in buckets:
                sparams = dataclasses.replace(b.params, **{
                    k: type(getattr(b.params, k))(v)
                    for k, v in cfg["overrides"].items()})
                _, mon, _ = tsearch._sweep_bucket(
                    b.keys, sparams, b.worlds,
                    tsearch.passive_specs(sparams, b.size), b.horizon,
                    tsearch.config_knobs(sparams, {}, b.size),
                    capacity, trace_cap)
            force(mon)
        static_s = (time.perf_counter() - t0) / len(static_cfgs)
        static_grid_s = static_s * info["configs"]
        ratio = round(static_grid_s / sweep_s, 4)
        log(f"tune: static sweep {static_s:.1f}s/config cold "
            f"({len(static_cfgs)} config(s) measured, compile per "
            f"config x bucket) -> {static_grid_s:.0f}s for the "
            f"{info['configs']}-config grid vs {sweep_s:.1f}s dynamic "
            f"-> batch speedup x{ratio}")

        # ---- stage 4: frontier + shipped profiles ---------------------
        ref = rows[0]
        assert ref["name"] == "reference"
        green_idx = [i for i, r in enumerate(rows) if r["green"]]
        front = [green_idx[i] for i in tsearch.pareto_front(
            [rows[i]["slos"] for i in green_idx])]
        profiles = {}
        for name in sorted(tprofiles.PROFILES):
            prow = next(r for r in rows if r["name"] == name)
            target = tprofiles.PROFILES[name]["target"]
            val = tsearch.validate_profile(
                name, seed=held_out, seeds_per_tier=per_tier, n=n,
                capacity=capacity, log=log)
            profiles[name] = {
                "target": target,
                "overrides": prow["overrides"],
                "slos": prow["slos"],
                "monitor_green": prow["green"],
                "nondominated_vs_reference":
                    not tsearch.dominates(ref["slos"], prow["slos"]),
                "target_vs_reference": round(
                    prow["slos"][target] - ref["slos"][target], 6),
                "fuzz_green": val["green"],
                "fuzz": val,
            }

        result.update(
            tune_grid_throughput=round(throughput, 1),
            batch_speedup_ratio=ratio,
            batch_dispatch_ratio=dispatch_ratio,
            tune_compiles=info["compiles"],
            tune_warm_recompiles=warm_info["compiles"],
            tune_shape_buckets=info["shape_buckets"],
            grid={"configs": info["configs"],
                  "scenarios": info["scenarios"],
                  "bucket_sizes": info["bucket_sizes"],
                  "member_rounds": info["member_rounds"],
                  "param_overrides": info["param_overrides"],
                  "seconds_dynamic_sweep": round(sweep_s, 3),
                  "seconds_static_per_config": round(static_s, 3),
                  "static_configs_measured": len(static_cfgs),
                  "seconds_warm_pass": round(grid_s, 3)},
            objectives=list(tsearch.OBJECTIVES),
            reference_slos=ref["slos"],
            rows=[{"name": r["name"], "overrides": r["overrides"],
                   "green": r["green"], "profile": r["profile"],
                   "slos": r["slos"]} for r in rows],
            frontier=[rows[i]["name"] for i in front],
            profiles=profiles,
            held_out_seed=held_out,
            value_note=("value stays null by design: grid throughput "
                        "is host-dependent and the tune gates are "
                        "absolute — regress walks the dedicated tune "
                        "checks instead"),
        )

        art = dict(result)
        os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
        result["artifact"] = artifact
        log(f"tune artifact written to {artifact}")

        apply_regress_gate(
            result, ["BENCH_*.json",
                     os.path.join("artifacts", "tune_pareto*.json"),
                     artifact])
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result), flush=True)


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--smoke", action="store_true",
        help="fast CPU-safe pass (small N, few rounds, no canary) that "
             "still exercises the full pipeline incl. telemetry",
    )
    parser.add_argument(
        "--chaos", action="store_true",
        help="run the chaos campaign (generated fault scenarios through "
             "the in-jit invariant monitor) instead of the throughput "
             "bench; combine with --smoke for the tier-1-safe mini "
             "campaign",
    )
    parser.add_argument(
        "--metrics", action="store_true",
        help="measure the always-on health-metrics registry against "
             "the bare hot path (metrics_overhead_ratio) and emit the "
             "windowed health manifest + SLO digest; combine with "
             "--smoke for the tier-1-safe pass",
    )
    parser.add_argument(
        "--resilience", action="store_true",
        help="run the kill-injection resilience drill (subprocess "
             "SIGKILL + relaunch over rotated checksummed checkpoints, "
             "all three run shapes) instead of the throughput bench; "
             "combine with --smoke for the tier-1-safe mini drill",
    )
    parser.add_argument(
        "--multichip", action="store_true",
        help="measure the sharded scatter run on the device mesh: "
             "pipelined ICI delivery vs the serial in-round combine, "
             "real member-rounds/sec/chip + mesh shape + speedup ratio "
             "into a MULTICHIP_* artifact; combine with --smoke for "
             "the CPU-safe virtual-8-device pass",
    )
    parser.add_argument(
        "--sync", action="store_true",
        help="measure SYNC anti-entropy partition-heal convergence "
             "(rounds-to-converge after a quiesced split, plane vs "
             "gossip-only control, monitored chaos-scale arm) into an "
             "artifacts/sync_heal.json-style artifact; combine with "
             "--smoke for the tier-1-safe pass",
    )
    parser.add_argument(
        "--churn", action="store_true",
        help="run the open-world membership A/B instead (seeded "
             "net-positive arrival storm: epoch guard vs naive slot "
             "reuse, NO_RESURRECTION/JOIN_COMPLETENESS verdicts + "
             "join-propagation P99) into an artifacts/churn_growth"
             ".json-style artifact; combine with --smoke for the "
             "tier-1-safe single-scenario pass",
    )
    parser.add_argument(
        "--rollout", action="store_true",
        help="measure staged config rollout through the metadata KV "
             "plane under fire (revive churn storm + partition "
             "split/heal crossing the stages; gated per-push "
             "convergence deadlines + metadata_convergence_p99, "
             "gossip-only control stays divergent) into an "
             "artifacts/config_rollout.json-style artifact; combine "
             "with --smoke for the tier-1-safe pass",
    )
    parser.add_argument(
        "--lifeguard", action="store_true",
        help="measure the Lifeguard health plane A/B under the seeded "
             "asymmetric-degradation scenario (false-positive observer "
             "rate plane-on vs control + crash-detection latency "
             "parity) into an artifacts/lifeguard_fp.json-style "
             "artifact; combine with --smoke for the tier-1-safe "
             "single-scenario pass",
    )
    parser.add_argument(
        "--fuzz", action="store_true",
        help="run the vmapped chaos mega-campaign instead: thousands of "
             "seeded scenarios bucketed by compiled shape and fuzzed by "
             "one device program per bucket, sequential-vs-vmapped "
             "timing + a weakened-build coverage arm into an "
             "artifacts/fuzz_campaign.json-style artifact; combine "
             "with --smoke for the tier-1-safe mini batch",
    )
    parser.add_argument(
        "--wire", action="store_true",
        help="measure the fused single-buffer scatter wire against the "
             "two-buffer HEAD path (serial AND pipelined sharded runs, "
             "fused/legacy speedup ratios + compiled-HLO collective "
             "counts + traffic-model bytes/slot) into an "
             "artifacts/wire_fused.json-style artifact; combine with "
             "--smoke for the CPU-safe virtual-8-device pass",
    )
    parser.add_argument(
        "--compose", action="store_true",
        help="measure the composed plane runner: the full instrumented "
             "stack (trace+metrics+monitor) in ONE scan via "
             "models/compose.run_composed vs the pre-compose "
             "alias-by-alias route (three programs, three scans), plus "
             "a compile-count arm over the entry-point x layout "
             "matrix, into an artifacts/compose_perf.json-style "
             "artifact; combine with --smoke for the tier-1-safe pass",
    )
    parser.add_argument(
        "--alarms", action="store_true",
        help="run the live SLO alarm drill instead: the seeded square "
             "loss pulse measured twice (healthy Knobs vs the "
             "weakened-knobs breach arm on the same compiled program), "
             "alarm detection lag + resolve-after-heal + "
             "healthy-arm-quiet into an artifacts/alarm_drill.json-"
             "style artifact; combine with --smoke for the tier-1-safe "
             "pass",
    )
    parser.add_argument(
        "--blame", action="store_true",
        help="run the provenance blame drill instead: the seeded "
             "single-faulty-link scenario through the provenance-armed "
             "composed stack — blame-engine origin attribution, "
             "channel-mix completeness, off-switch bit-identity and "
             "the interleaved armed-vs-bare overhead ratio into an "
             "artifacts/provenance_blame.json-style artifact; combine "
             "with --smoke for the tier-1-safe pass",
    )
    parser.add_argument(
        "--tune", action="store_true",
        help="run the protocol autotuner instead: the knob-grid x "
             "scenario-batch sweep through one compiled program per "
             "shape bucket (knob data never recompiles), PR-5 SLO "
             "scoring, the Pareto frontier + shipped tuned profiles "
             "(fuzz-oracle-validated) and the batched-vs-sequential "
             "speedup ratio into an artifacts/tune_pareto.json-style "
             "artifact; combine with --smoke for the tier-1-safe "
             "mini grid",
    )
    parser.add_argument(
        "--soak", action="store_true",
        help="run production soak mode instead: one long-lived service "
             "lifetime under the seeded chaos stream through the "
             "supervisor's composed shape — live alarms, per-segment "
             "drift invariants (flat compile cache, bounded RSS, zero "
             "monitor violations) and a seeded mid-soak SIGKILL/"
             "relaunch drill with byte-identical journals, into an "
             "artifacts/soak_report.json-style artifact; combine with "
             "--smoke for the tier-1-safe pass",
    )
    mode = parser.add_mutually_exclusive_group()
    mode.add_argument(
        "--untraced", action="store_true",
        help="time only the untraced hot path (default: both, plus the "
             "traced_overhead_ratio)",
    )
    mode.add_argument(
        "--traced", action="store_true",
        help="time only the traced path (overlapped trace offload)",
    )
    parser.add_argument(
        "--gap-artifact", nargs="?", const="BENCH_traced_overhead.json",
        default=None, metavar="PATH",
        help="also write a BENCH_*-style JSON artifact pinning the "
             "traced-vs-untraced gap (default path when bare: "
             "BENCH_traced_overhead.json)",
    )
    try:
        args = parser.parse_args()
        if args.gap_artifact and (args.traced or args.untraced):
            parser.error(
                "--gap-artifact pins the traced-vs-untraced gap and needs "
                "BOTH paths measured; drop --traced/--untraced")
        if args.chaos and (args.traced or args.untraced
                           or args.gap_artifact):
            parser.error(
                "--chaos is the robustness workload; it measures no "
                "throughput paths — drop --traced/--untraced/"
                "--gap-artifact")
        if args.resilience and (args.chaos or args.traced
                                or args.untraced or args.gap_artifact):
            parser.error(
                "--resilience is the preemption-survival workload; it "
                "measures no throughput paths and is not --chaos — "
                "drop the other mode flags")
        if args.metrics and (args.chaos or args.resilience or args.traced
                             or args.untraced or args.gap_artifact):
            parser.error(
                "--metrics measures the metered-vs-unmetered gap on its "
                "own interleaved windows — drop the other mode flags")
        if args.multichip and (args.chaos or args.resilience or args.metrics
                               or args.traced or args.untraced
                               or args.gap_artifact):
            parser.error(
                "--multichip measures the pipelined-vs-serial sharded gap "
                "on its own interleaved windows — drop the other mode "
                "flags")
        if args.sync and (args.chaos or args.resilience or args.metrics
                          or args.multichip or args.traced
                          or args.untraced or args.gap_artifact):
            parser.error(
                "--sync measures partition-heal convergence on its own "
                "workload — drop the other mode flags")
        if args.rollout and (args.chaos or args.resilience or args.metrics
                             or args.multichip or args.sync
                             or args.lifeguard or args.churn or args.fuzz
                             or args.wire or args.compose or args.alarms
                             or args.tune or args.soak or args.traced
                             or args.untraced or args.gap_artifact):
            parser.error(
                "--rollout measures staged config propagation on its "
                "own workload — drop the other mode flags")
        if args.lifeguard and (args.chaos or args.resilience
                               or args.metrics or args.multichip
                               or args.sync or args.traced
                               or args.untraced or args.gap_artifact):
            parser.error(
                "--lifeguard measures the health-plane A/B on its own "
                "workload — drop the other mode flags")
        if args.churn and (args.chaos or args.resilience or args.metrics
                           or args.multichip or args.sync
                           or args.lifeguard or args.traced
                           or args.untraced or args.gap_artifact):
            parser.error(
                "--churn measures the open-world membership A/B on its "
                "own workload — drop the other mode flags")
        if args.fuzz and (args.chaos or args.resilience or args.metrics
                          or args.multichip or args.sync
                          or args.lifeguard or args.churn
                          or args.traced or args.untraced
                          or args.gap_artifact):
            parser.error(
                "--fuzz runs the vmapped chaos mega-campaign on its own "
                "workload — drop the other mode flags")
        if args.wire and (args.chaos or args.resilience or args.metrics
                          or args.multichip or args.sync
                          or args.lifeguard or args.churn or args.fuzz
                          or args.traced or args.untraced
                          or args.gap_artifact):
            parser.error(
                "--wire measures the fused-vs-two-buffer wire gap on "
                "its own interleaved windows — drop the other mode "
                "flags")
        if args.compose and (args.chaos or args.resilience or args.metrics
                             or args.multichip or args.sync
                             or args.lifeguard or args.churn or args.fuzz
                             or args.wire or args.traced or args.untraced
                             or args.gap_artifact):
            parser.error(
                "--compose measures the composed-vs-alias full-stack "
                "gap on its own interleaved windows — drop the other "
                "mode flags")
        if args.alarms and (args.chaos or args.resilience or args.metrics
                            or args.multichip or args.sync
                            or args.lifeguard or args.churn or args.fuzz
                            or args.wire or args.compose or args.traced
                            or args.untraced or args.gap_artifact):
            parser.error(
                "--alarms runs the live SLO alarm drill on its own "
                "workload — drop the other mode flags")
        if args.blame and (args.chaos or args.resilience or args.metrics
                           or args.multichip or args.sync
                           or args.lifeguard or args.churn or args.fuzz
                           or args.wire or args.compose or args.alarms
                           or args.traced or args.untraced
                           or args.gap_artifact):
            parser.error(
                "--blame runs the provenance blame drill on its own "
                "workload — drop the other mode flags")
        if args.tune and (args.chaos or args.resilience or args.metrics
                          or args.multichip or args.sync
                          or args.lifeguard or args.churn or args.fuzz
                          or args.wire or args.compose or args.alarms
                          or args.blame or args.traced or args.untraced
                          or args.gap_artifact):
            parser.error(
                "--tune runs the protocol autotuner on its own "
                "workload — drop the other mode flags")
        if args.soak and (args.chaos or args.resilience or args.metrics
                          or args.multichip or args.sync
                          or args.lifeguard or args.churn or args.fuzz
                          or args.wire or args.compose or args.alarms
                          or args.blame or args.tune or args.traced
                          or args.untraced or args.gap_artifact):
            parser.error(
                "--soak runs production soak mode on its own "
                "workload — drop the other mode flags")
    except SystemExit as e:
        # The one-JSON-line contract holds even for a bad argv: argparse
        # already printed its usage message to stderr; ship the error
        # line before propagating its exit code (--help's clean exit
        # stays JSON-free — it is not a measurement attempt).
        if e.code not in (0, None):
            print(json.dumps({
                "metric": "swim_member_rounds_per_sec_per_chip",
                "value": None,
                "error": f"ArgumentError: bad argv {sys.argv[1:]}",
            }), flush=True)
        raise
    if args.smoke:
        apply_smoke_preset()
    if args.resilience:
        return run_resilience_drill()
    if args.chaos:
        return run_chaos_campaign()
    if args.metrics:
        return run_metrics_bench()
    if args.multichip:
        return run_multichip_bench()
    if args.sync:
        return run_sync_bench()
    if args.rollout:
        return run_rollout_bench()
    if args.lifeguard:
        return run_lifeguard_bench()
    if args.churn:
        return run_churn_bench()
    if args.fuzz:
        return run_fuzz_bench()
    if args.wire:
        return run_wire_bench()
    if args.compose:
        return run_compose_bench()
    if args.alarms:
        return run_alarm_bench()
    if args.blame:
        return run_blame_bench()
    if args.tune:
        return run_tune_bench()
    if args.soak:
        return run_soak_bench()

    result = {
        "metric": "swim_member_rounds_per_sec_per_chip",
        "value": None,
        "unit": "member-rounds/sec/chip",
        "vs_baseline": None,
        "smoke": SMOKE,
    }
    main_metrics = None
    try:
        jax, platform = init_backend()
        result["platform"] = platform

        if not os.environ.get("SCALECUBE_BENCH_SKIP_CANARY"):
            # 100 rounds at 4k members is ~0.13 s — nearly all per-call
            # dispatch overhead (~0.1 s/invocation through the tunnelled
            # TPU link), NOT throughput at 4k.  It exists to diagnose
            # failures cheaply before the 1M run; label it accordingly.
            canary_rate, _ = timed_run(jax, CANARY_N, 100,
                                       f"canary@{CANARY_N}")
            result["canary_smoke_member_rounds_per_sec"] = round(canary_rate, 1)
            result["canary_note"] = (
                "smoke check only — 100-round window is dispatch-dominated, "
                "do not read as throughput"
            )

        rate = None
        if args.untraced:
            rate, main_metrics = timed_run(jax, N_MEMBERS, BENCH_ROUNDS,
                                           f"main@{N_MEMBERS}")
            result["untraced_member_rounds_per_sec"] = round(rate, 1)
        elif args.traced:
            rate = timed_traced_run(jax, N_MEMBERS, BENCH_ROUNDS,
                                    f"traced@{N_MEMBERS}")
            result["traced_member_rounds_per_sec"] = round(rate, 1)
        else:
            rate, main_metrics, traced_rate = timed_both(
                jax, N_MEMBERS, BENCH_ROUNDS, f"main@{N_MEMBERS}"
            )
            result["untraced_member_rounds_per_sec"] = round(rate, 1)
            result["traced_member_rounds_per_sec"] = round(traced_rate, 1)
        if ("untraced_member_rounds_per_sec" in result
                and "traced_member_rounds_per_sec" in result):
            # > 1.0 = telemetry still costs device time; 1.0 = free.
            result["traced_overhead_ratio"] = round(
                result["untraced_member_rounds_per_sec"]
                / result["traced_member_rounds_per_sec"], 4)
        # The headline ``value`` stays the untraced hot-path rate (the
        # round-1..5 artifact series); --traced makes it the traced rate.
        result["value"] = round(rate, 1)
        result["vs_baseline"] = round(rate / NORTH_STAR_RATE, 3)
        result["n_members"] = N_MEMBERS
        result["rounds_timed"] = BENCH_ROUNDS
        result["delivery"] = DELIVERY
        result["rounds_per_step"] = resolve_rounds_per_step()
        if args.gap_artifact and "traced_overhead_ratio" in result:
            gap = {
                "metric": "traced_vs_untraced_member_rounds_per_sec",
                "untraced": result["untraced_member_rounds_per_sec"],
                "traced": result["traced_member_rounds_per_sec"],
                "traced_overhead_ratio": result["traced_overhead_ratio"],
                "n_members": N_MEMBERS,
                "rounds_timed": BENCH_ROUNDS,
                "rounds_per_step": resolve_rounds_per_step(),
                "delivery": DELIVERY,
                "smoke": SMOKE,
                "platform": platform,
            }
            with open(args.gap_artifact, "w") as f:
                json.dump(gap, f, indent=1)
                f.write("\n")
            log(f"traced-overhead artifact written to {args.gap_artifact}")
        result["dissemination_rounds"] = dissemination_at_scale(jax, N_MEMBERS)
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["error"] = f"{type(e).__name__}: {e}"
        if (result["value"] is None
                and "canary_smoke_member_rounds_per_sec" in result):
            # Ship the canary as a lower-bound datum rather than nothing.
            result["value"] = result["canary_smoke_member_rounds_per_sec"]
            result["vs_baseline"] = round(result["value"] / NORTH_STAR_RATE, 3)
            result["n_members"] = CANARY_N

    # Telemetry stage: the traced scenario + JSONL manifest.  Same
    # never-ship-empty contract — a telemetry failure is recorded in the
    # result, it does not void the throughput measurement.
    try:
        import jax  # may already be initialized above; cheap re-import

        scenario = telemetry_scenario(jax)
        manifest = write_telemetry(scenario, main_metrics)
        result["telemetry"] = {
            "manifest": manifest,
            "events_recorded": scenario["recorded"],
            "event_drops": scenario["dropped"],
            "detection_latency_hist": {
                "edges": scenario["edges"],
                "counts": scenario["detection_buckets"],
                "undetected": scenario["detection_undetected"],
            },
        }
    except BaseException as e:  # noqa: BLE001 — partial result by contract
        log(traceback.format_exc())
        result["telemetry_error"] = f"{type(e).__name__}: {e}"

    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
