"""Headline benchmark: SWIM member-rounds/sec/chip on real TPU.

Runs the full SWIM tick (FD + gossip + suspicion + SYNC,
models/swim.swim_tick) in focal mode at 1M members — the BASELINE.md
north-star configuration (1M members on a v5e; the reference never ran
above N=50, SURVEY.md §6, and publishes no absolute numbers) — and reports
throughput in member-rounds/sec/chip.

``vs_baseline`` is measured against the north-star requirement implied by
BASELINE.json: simulate 1M members × 10k rounds on a v5e-8 in one hour,
i.e. 1e6*1e4/(3600*8) ≈ 3.47e8 member-rounds/sec/chip.  vs_baseline 1.0
means exactly that rate; higher is better.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

N_MEMBERS = 1_000_000
N_SUBJECTS = 16
BENCH_ROUNDS = 200
NORTH_STAR_RATE = 1e6 * 1e4 / (3600.0 * 8)  # member-rounds/sec/chip


def main():
    import jax

    from scalecube_cluster_tpu.config import ClusterConfig
    from scalecube_cluster_tpu.models import swim

    params = swim.SwimParams.from_config(
        ClusterConfig.default(),
        n_members=N_MEMBERS,
        n_subjects=N_SUBJECTS,
        loss_probability=0.02,
        per_subject_metrics=True,
    )
    world = swim.SwimWorld.healthy(params).with_crash(3, at_round=50)
    key = jax.random.key(0)

    # Compile + warm up with the SAME static args and pytree structure as
    # the timed call (params, n_rounds, state-provided), so the timed
    # region hits the jit cache and measures steady state only.
    state = swim.initial_state(params, world)
    state, _ = swim.run(key, params, world, BENCH_ROUNDS, state=state,
                        start_round=0)
    jax.block_until_ready(state.status)

    t0 = time.perf_counter()
    state, metrics = swim.run(
        key, params, world, BENCH_ROUNDS, state=state, start_round=BENCH_ROUNDS
    )
    jax.block_until_ready(state.status)
    elapsed = time.perf_counter() - t0

    member_rounds_per_sec = N_MEMBERS * BENCH_ROUNDS / elapsed
    print(json.dumps({
        "metric": "swim_member_rounds_per_sec_per_chip",
        "value": round(member_rounds_per_sec, 1),
        "unit": "member-rounds/sec/chip",
        "vs_baseline": round(member_rounds_per_sec / NORTH_STAR_RATE, 3),
    }))


if __name__ == "__main__":
    main()
