"""Host-side metadata for the dense tick: (id, incarnation)-keyed table.

The reference never gossips metadata content — only the owner's
incarnation bump travels (through membership gossip), and receivers then
PULL the metadata from the owner, emitting an UPDATED event
(metadata/MetadataStoreImpl.java:106-146 updateMetadata -> incarnation
bump; :149-186 remote fetch; MembershipProtocolImpl.java:572-584 the
higher-incarnation -> fetchMetadata -> UPDATED path).  SURVEY.md §2.2
scoped metadata content out of tensor scope for exactly this reason: the
wire protocol only ever carries (id, incarnation), which the tick already
disseminates exactly.

This module is the host-side half: a table keyed by (node_id,
incarnation) plus the three protocol operations —

  - :meth:`TickMetadataStore.update`: the owner's updateMetadata — bumps
    the node's incarnation in the carry and opens its gossip window so
    the bump disseminates through the NORMAL membership machinery, and
    registers the new metadata version under the bumped incarnation;
  - :meth:`TickMetadataStore.view`: what an observer's fetch would
    return — resolved against the incarnation THE OBSERVER HAS SEEN
    (a refutation bump without a metadata change resolves to the prior
    version, like the reference's fetch returning unchanged content);
  - :meth:`updated_events`: the UPDATED-event stream — (observer,
    subject, old_inc, new_inc) tuples diffed between two carries, the
    batch analog of MembershipProtocolImpl's per-record UPDATED emission.

Scale: all operations are O(rows touched) host-side; the 1M-member
propagation demo is examples/metadata_at_scale.py.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from scalecube_cluster_tpu import records
from scalecube_cluster_tpu.models import swim


class TickMetadataStore:
    """(node_id, incarnation) -> metadata dict, resolved like the
    reference's pull-on-bump protocol."""

    def __init__(self):
        # node_id -> sorted list of (incarnation, metadata) versions.
        self._versions: Dict[int, list] = {}

    # -- owner-side ------------------------------------------------------

    def put(self, node_id: int, incarnation: int, metadata: dict) -> None:
        """Register ``metadata`` as ``node_id``'s content at
        ``incarnation`` (initial metadata at incarnation 0 = the
        reference's config.metadata at join)."""
        versions = self._versions.setdefault(int(node_id), [])
        versions.append((int(incarnation), dict(metadata)))
        versions.sort(key=lambda iv: iv[0])

    def update(self, state: swim.SwimState, params: swim.SwimParams,
               world: swim.SwimWorld, node_id: int, metadata: dict,
               current_round: int) -> swim.SwimState:
        """The owner's ``updateMetadata``: bump incarnation + re-announce.

        Mirrors MetadataStoreImpl.updateMetadata (:106-146) + the
        membership re-gossip of the bumped record: ``self_inc[node] += 1``
        (the tick re-pins the node's own record from self_inc every
        round) and the node's own spread window reopens so the bump
        disseminates.  The new metadata registers under the bumped
        incarnation; observers "fetch" it via :meth:`view` once their
        table shows the new incarnation.

        Returns the updated carry (host-side, between scan chunks — the
        same seam checkpoint/resume uses).
        """
        node_id = int(node_id)
        slot = int(np.asarray(world.slot_of_node)[node_id])
        if slot < 0:
            raise ValueError(
                f"node {node_id} is not a tracked subject — its record "
                f"(and so its incarnation bump) is not simulated"
            )
        new_inc = int(np.asarray(state.self_inc)[node_id]) + 1
        self.put(node_id, new_inc, metadata)
        spread = params.periods_to_spread + 1
        if params.compact_carry:
            spread_val = np.int8(min(spread, 127))
        else:
            spread_val = np.int32(current_round + spread)
        return dataclasses.replace(
            state,
            self_inc=state.self_inc.at[node_id].add(1),
            spread_until=state.spread_until.at[node_id, slot].set(
                jnp.asarray(spread_val, dtype=state.spread_until.dtype)
            ),
        )

    # -- observer-side ---------------------------------------------------

    def resolve(self, node_id: int, seen_incarnation: int) -> Optional[dict]:
        """Metadata at the newest registered version <= what the observer
        has seen — a refutation bump (no metadata change) resolves to the
        prior content, exactly like the reference's fetch."""
        versions = self._versions.get(int(node_id), [])
        best = None
        for inc, md in versions:
            if inc <= seen_incarnation:
                best = md
            else:
                break
        return best

    def view(self, state: swim.SwimState, params: swim.SwimParams,
             world: swim.SwimWorld, observer_id: int,
             subject_id: int, round_idx: Optional[int] = None
             ) -> Optional[dict]:
        """What ``observer_id``'s metadata fetch for ``subject_id`` would
        return right now: None if the observer does not hold a live
        record of the subject (the reference only fetches for members in
        its table)."""
        slot = int(np.asarray(world.slot_of_node)[subject_id])
        if slot < 0:
            raise ValueError(f"node {subject_id} is not a tracked subject")
        snap = swim.node_snapshot(state, params, world, observer_id,
                                  round_idx=round_idx)
        if subject_id in snap["alive_members"] + snap["suspected_members"]:
            seen = snap["record_incarnations"][subject_id]
        elif subject_id == observer_id:
            seen = snap["incarnation"]
        else:
            return None
        return self.resolve(subject_id, seen)


def updated_events(prev_state: swim.SwimState, state: swim.SwimState,
                   world: swim.SwimWorld,
                   max_events: int = 10_000) -> list:
    """The UPDATED-event stream between two carries.

    (observer_id, subject_id, old_inc, new_inc) wherever an observer's
    live record of a subject moved to a higher incarnation — the batch
    analog of the reference's per-record UPDATED emission
    (MembershipProtocolImpl.java:572-584); each event is the trigger the
    reference uses to re-fetch metadata.  Capped at ``max_events`` (the
    [N, K] diff can be huge at scale; the CURVE of bump dissemination is
    cheaper via the inc matrix directly — see examples/metadata_at_scale).
    """
    old_inc = np.asarray(prev_state.inc, dtype=np.int64)
    new_inc = np.asarray(state.inc, dtype=np.int64)
    new_status = np.asarray(state.status)
    old_status = np.asarray(prev_state.status)
    live = (new_status == records.ALIVE) | (new_status == records.SUSPECT)
    # A record the observer just LEARNED is the reference's ADDED, not
    # UPDATED (MembershipProtocolImpl.java:558-570 vs :572-584) — require
    # the prior record to have been live too.
    was_live = ((old_status == records.ALIVE)
                | (old_status == records.SUSPECT))
    bumped = (new_inc > old_inc) & live & was_live
    # A node's record about ITSELF emits no UPDATED — the reference's
    # about-self path refutes instead of emitting
    # (MembershipProtocolImpl.java:488-509).
    subj = np.asarray(world.subject_ids)
    for sl, s_id in enumerate(subj):
        bumped[int(s_id), sl] = False
    obs, slot = np.nonzero(bumped)
    subjects = np.asarray(world.subject_ids)[slot]
    events = []
    for o, s, sl in zip(obs[:max_events], subjects[:max_events],
                        slot[:max_events]):
        events.append((int(o), int(s), int(old_inc[o, sl]),
                       int(new_inc[o, sl])))
    return events
