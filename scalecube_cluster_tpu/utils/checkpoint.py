"""On-disk checkpoint/resume of the scan carry for long runs.

The reference keeps no persistent state (SURVEY.md §5.4: membership is
ephemeral, a restarted node rejoins from seeds) — but a 1M-member ×
10k-round TPU sweep needs to survive preemption.  The scan carry
(models/swim.SwimState) plus the (key, params-hash, next round) cursor is
everything required to re-enter ``swim.run`` at round r; the resume
contract is bit-exact (tests/test_swim_model.py TestDeterminism and
tests/test_checkpoint.py) because every draw is a pure function of
(key, round) — ops/prng.py.

Format: a single ``.npz`` (host offload — no orbax dependency needed for
flat int arrays; np.savez is the natural host-offload container for a
pytree of small-dtype leaves).  Writes are atomic (tmp file + rename) so
a kill mid-write never corrupts the latest checkpoint.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Optional, Tuple

import jax
import numpy as np

from scalecube_cluster_tpu.models.swim import SwimState


def save(path: str, state: SwimState, next_round: int,
         key=None, meta: Optional[dict] = None) -> None:
    """Atomically write ``state`` + cursor to ``path`` (.npz).

    ``meta`` is an arbitrary JSON-able dict (config snapshot, world hash)
    stored alongside for validation at load time.
    """
    arrays = {
        f"state/{f.name}": np.asarray(getattr(state, f.name))
        for f in dataclasses.fields(state)
    }
    arrays["next_round"] = np.int64(next_round)
    if key is not None:
        arrays["key_data"] = np.asarray(jax.random.key_data(key))
    arrays["meta_json"] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8
    )
    directory = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load(path: str) -> Tuple[SwimState, int, Optional[jax.Array], dict]:
    """Load (state, next_round, key-or-None, meta) written by :func:`save`."""
    with np.load(path) as z:
        fields = {
            name[len("state/"):]: jax.numpy.asarray(z[name])
            for name in z.files if name.startswith("state/")
        }
        state = SwimState(**fields)
        next_round = int(z["next_round"])
        key = None
        if "key_data" in z.files:
            key = jax.random.wrap_key_data(jax.numpy.asarray(z["key_data"]))
        meta = json.loads(bytes(z["meta_json"].tobytes()).decode() or "{}")
    return state, next_round, key, meta


def _metrics_path(path: str, upto_round: int) -> str:
    return f"{path}.metrics-{upto_round:08d}.npz"


def run_checkpointed(run_fn, key, params, world, n_rounds: int, path: str,
                     chunk: int = 1000, state=None, start_round: int = 0,
                     meta: Optional[dict] = None, log=None):
    """Drive ``run_fn`` (swim.run-shaped) in chunks, checkpointing each.

    Resumes from ``path`` if it exists (``start_round``/``state`` args are
    then ignored).  On resume the stored ``meta`` must equal the caller's
    ``meta`` — a mismatch (different config/world than the interrupted run)
    raises instead of silently continuing a different experiment.

    Each chunk's metric traces are persisted next to the checkpoint
    (``<path>.metrics-<round>.npz``) and reloaded on resume, so the
    returned list always covers rounds [0, n_rounds) even across
    preemptions.  Returns (final_state, list of per-chunk metrics dicts).
    """
    metrics_chunks = []
    if os.path.exists(path):
        state, start_round, saved_key, saved_meta = load(path)
        if saved_key is not None:
            key = saved_key
        if meta is not None and saved_meta != meta:
            raise ValueError(
                f"checkpoint meta mismatch: saved {saved_meta!r} != "
                f"current {meta!r} — refusing to resume a different run"
            )
        meta = saved_meta
        # Reload the already-produced metric chunks.
        r0, upto = 0, start_round
        while r0 < upto:
            mpath = _metrics_path(path, min(r0 + chunk, upto))
            if not os.path.exists(mpath):
                break  # older run used a different chunking; traces partial
            with np.load(mpath) as z:
                metrics_chunks.append({k: z[k] for k in z.files})
            r0 += chunk
        if log is not None:
            log.info("resumed from %s at round %d (%d metric chunks)",
                     path, start_round, len(metrics_chunks))
    r = start_round
    while r < n_rounds:
        step = min(chunk, n_rounds - r)
        state, metrics = run_fn(key, params, world, step,
                                state=state, start_round=r)
        jax.block_until_ready(state.status)
        r += step
        save(path, state, r, key=key, meta=meta)
        np.savez(_metrics_path(path, r),
                 **{k: np.asarray(v) for k, v in metrics.items()})
        metrics_chunks.append(metrics)
        if log is not None:
            log.info("checkpointed round %d/%d", r, n_rounds)
    return state, metrics_chunks
