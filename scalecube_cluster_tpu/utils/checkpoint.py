"""On-disk checkpoint/resume of the scan carry for long runs.

The reference keeps no persistent state (SURVEY.md §5.4: membership is
ephemeral, a restarted node rejoins from seeds) — but a 1M-member ×
10k-round TPU sweep needs to survive preemption.  The scan carry
(models/swim.SwimState) plus the (key, params-hash, next round) cursor is
everything required to re-enter ``swim.run`` at round r; the resume
contract is bit-exact (tests/test_swim_model.py TestDeterminism and
tests/test_checkpoint.py) because every draw is a pure function of
(key, round) — ops/prng.py.

Format: a single ``.npz`` (host offload — no orbax dependency needed for
flat int arrays; np.savez is the natural host-offload container for a
pytree of small-dtype leaves).  Writes are atomic (tmp file + rename) so
a kill mid-write never corrupts the latest checkpoint.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Optional, Tuple

import jax
import numpy as np

from scalecube_cluster_tpu.models.swim import SwimState


def state_to_arrays(state: SwimState) -> dict:
    """SwimState -> flat ``{"state/<field>": np.ndarray}`` dict — the
    checkpoint payload naming shared with resilience/store.py."""
    return {
        f"state/{f.name}": np.asarray(getattr(state, f.name))
        for f in dataclasses.fields(state)
    }


def state_from_arrays(fields: dict, origin: str = "checkpoint",
                      params=None) -> SwimState:
    """Inverse of :func:`state_to_arrays` (keys WITHOUT the ``state/``
    prefix).  Checkpoints written before the user-gossip fields existed
    load as G=0 (zero-width arrays), ones written before the Lifeguard
    health lane existed load with the plane-off zero-size ``lhm``,
    ones written before the open-world identity lane existed load with
    the plane-off zero-size ``epoch``, and ones written before the
    metadata KV lanes existed load with the plane-off zero-size
    ``md``/``md_spread`` — the layouts params.n_user_gossips=0 /
    params.lhm_max=0 / params.open_world=False / params.metadata_keys=0
    produce, so resume validation stays meaningful.

    ``params`` (optional SwimParams): when given and the checkpoint
    predates the epoch lane while the run expects it
    (``params.open_world``), the lane defaults to ZERO-EPOCH — a full
    [N, K] zeros lane in the params' carry dtype (every record
    attributed to the original occupants, exactly the pre-open-world
    semantics), so an open-world run can resume a legacy checkpoint
    instead of refusing on shape mismatch."""
    fields = {k: jax.numpy.asarray(v) for k, v in fields.items()}
    missing = ({f.name for f in dataclasses.fields(SwimState)}
               - set(fields))
    if missing:
        n = fields["status"].shape[0]
        if params is not None and getattr(params, "epoch_bits", 0):
            from scalecube_cluster_tpu.models import swim as _swim
            epoch_default = _swim.initial_epoch(params)
        else:
            epoch_default = jax.numpy.zeros(
                (n, 0), dtype=jax.numpy.int32)
        g_defaults = {
            "g_infected": jax.numpy.zeros((n, 0), dtype=bool),
            "g_spread_until": jax.numpy.zeros(
                (n, 0), dtype=jax.numpy.int32),
            "g_ring": jax.numpy.zeros((0, n, 0), dtype=bool),
            "lhm": jax.numpy.zeros((0,), dtype=jax.numpy.int32),
            "epoch": epoch_default,
            # Pre-metadata-plane checkpoints (PR-19) load the plane-off
            # zero-size lanes — the PR-9/10 back-compat rule.
            "md": jax.numpy.zeros((n, 0, 0), dtype=jax.numpy.int32),
            "md_spread": jax.numpy.zeros(
                (n, 0), dtype=jax.numpy.int32),
        }
        unknown = missing - set(g_defaults)
        if unknown:
            raise KeyError(
                f"{origin} lacks state fields {sorted(unknown)}"
            )
        for name in missing:
            fields[name] = g_defaults[name]
    return SwimState(**fields)


def save(path: str, state: SwimState, next_round: int,
         key=None, meta: Optional[dict] = None) -> None:
    """Atomically write ``state`` + cursor to ``path`` (.npz).

    ``meta`` is an arbitrary JSON-able dict (config snapshot, world hash)
    stored alongside for validation at load time.
    """
    arrays = state_to_arrays(state)
    arrays["next_round"] = np.int64(next_round)
    if key is not None:
        arrays["key_data"] = np.asarray(jax.random.key_data(key))
    arrays["meta_json"] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8
    )
    _atomic_savez(path, arrays)


def _atomic_savez(path: str, arrays: dict) -> None:
    """np.savez to ``path`` via tmp-file + rename — a kill mid-write never
    leaves a truncated .npz at the final name."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load(path: str, params=None
         ) -> Tuple[SwimState, int, Optional[jax.Array], dict]:
    """Load (state, next_round, key-or-None, meta) written by :func:`save`.

    ``params`` (optional SwimParams) forwards to
    :func:`state_from_arrays`: pass the run's params when resuming a
    legacy checkpoint into an OPEN-WORLD run, so a missing epoch lane
    defaults to zero-epoch instead of the plane-off zero-size shape."""
    with np.load(path) as z:
        fields = {
            name[len("state/"):]: z[name]
            for name in z.files if name.startswith("state/")
        }
        state = state_from_arrays(fields, origin=f"checkpoint {path}",
                                  params=params)
        next_round = int(z["next_round"])
        key = None
        if "key_data" in z.files:
            key = jax.random.wrap_key_data(jax.numpy.asarray(z["key_data"]))
        meta = json.loads(bytes(z["meta_json"].tobytes()).decode() or "{}")
    return state, next_round, key, meta


def _metrics_path(path: str, upto_round: int) -> str:
    return f"{path}.metrics-{upto_round:08d}.npz"


def _metric_rounds_on_disk(path: str) -> list:
    """Sorted end-rounds of the metric-trace files written next to ``path``.

    The boundaries are discovered from disk rather than assumed to sit on a
    fixed chunk grid: a run whose ``n_rounds`` is not a multiple of
    ``chunk`` writes a short final chunk, so a later extension's boundaries
    are offset from the grid.
    """
    import re

    directory = os.path.dirname(os.path.abspath(path)) or "."
    if not os.path.isdir(directory):
        return []  # fresh run into a directory _atomic_savez will create
    # {:08d} zero-pads to 8 digits but grows wider past 99,999,999 rounds;
    # accept any width >= 8 so such traces stay visible on resume.
    pat = re.compile(
        re.escape(os.path.basename(path)) + r"\.metrics-(\d{8,})\.npz$"
    )
    rounds = []
    for fn in os.listdir(directory):
        m = pat.match(fn)
        if m:
            rounds.append(int(m.group(1)))
    return sorted(rounds)


def _delete_traces_above(path: str, above_round: int) -> list:
    """Delete trace files past ``above_round`` — stale leftovers of an
    earlier run lineage (e.g. the checkpoint was deleted to re-chunk, or a
    preemption landed between the trace write and the checkpoint write).
    Keeps the on-disk invariant: traces always cover a prefix of
    [0, next_round).  Returns the deleted paths."""
    deleted = []
    for upto in _metric_rounds_on_disk(path):
        if upto > above_round:
            fn = _metrics_path(path, upto)
            os.unlink(fn)
            deleted.append(fn)
    return deleted


def run_checkpointed(run_fn, key, params, world, n_rounds: int, path: str,
                     chunk: int = 1000, state=None, start_round: int = 0,
                     meta: Optional[dict] = None, log=None):
    """Drive ``run_fn`` (swim.run-shaped) in chunks, checkpointing each.

    Resumes from ``path`` if it exists (``start_round``/``state`` args are
    then ignored).  On resume the stored ``meta`` must equal the caller's
    ``meta`` — a mismatch (different config/world than the interrupted run)
    raises instead of silently continuing a different experiment.  ``meta``
    is JSON-normalized on both sides before comparing (tuples become lists,
    int keys become strings), so JSON-lossy values don't spuriously refuse a
    legitimate resume.  Resuming with a different ``chunk`` is fine: trace
    boundaries are discovered from the files on disk, not assumed to sit on
    a chunk grid.

    Each chunk's metric traces are persisted next to the checkpoint
    (``<path>.metrics-<round>.npz``) and reloaded on resume (boundaries
    discovered from the files on disk), so the returned list always covers
    rounds [0, n_rounds) even across preemptions.  If a trace file was
    deleted out-of-band, resume raises rather than return a list with a
    silent interior gap.  Returns (final_state, list of per-chunk metrics
    dicts).
    """
    # JSON round-trip so the resume equality check compares what was stored.
    meta = json.loads(json.dumps(meta)) if meta is not None else None
    metrics_chunks = []
    if os.path.exists(path):
        state, start_round, saved_key, saved_meta = load(path,
                                                        params=params)
        if saved_key is not None:
            key = saved_key
        if meta is not None and saved_meta != meta:
            raise ValueError(
                f"checkpoint meta mismatch: saved {saved_meta!r} != "
                f"current {meta!r} — refusing to resume a different run"
            )
        meta = saved_meta
        # Reload the already-produced metric chunks, discovering their
        # boundaries from the files on disk (chunk ends need not sit on a
        # fixed grid — a previous run's final chunk may have been short).
        covered = 0
        for upto in _metric_rounds_on_disk(path):
            if upto > start_round:
                break
            with np.load(_metrics_path(path, upto)) as z:
                mchunk = {k: z[k] for k in z.files}
            n_in_chunk = len(next(iter(mchunk.values())))
            if covered + n_in_chunk != upto:
                # Trace files are written contiguously, so an interior hole
                # can only come from an out-of-band deletion.  Returning a
                # list with a silent gap would misalign every round-indexed
                # consumer — refuse instead.
                raise ValueError(
                    f"metric traces covering rounds [{covered}, "
                    f"{upto - n_in_chunk}) are missing next to {path!r} — "
                    f"a trace file was deleted out-of-band; restore it or "
                    f"delete the checkpoint to start over"
                )
            metrics_chunks.append(mchunk)
            covered = upto
        if covered != start_round:
            # Same contract for a missing suffix: the trace ending at the
            # checkpoint cursor is gone (out-of-band deletion, or a
            # checkpoint from the pre-round-3 write order interrupted
            # between its checkpoint and trace writes).
            raise ValueError(
                f"metric traces covering rounds [{covered}, {start_round}) "
                f"are missing next to {path!r} — a trace file was deleted "
                f"out-of-band; restore it or delete the checkpoint to "
                f"start over"
            )
        if log is not None:
            log.info("resumed from %s at round %d (%d metric chunks)",
                     path, start_round, len(metrics_chunks))
        # Traces past the checkpoint cursor are stale (a preemption landed
        # between the trace write and the checkpoint write, or leftovers of
        # a deleted checkpoint) — the rounds they claim will re-run below.
        _delete_traces_above(path, start_round)
    else:
        # Fresh run (no checkpoint at ``path``): any metric traces sitting
        # next to it are leftovers of a deleted run lineage and would
        # corrupt this run's coverage invariant — but the user may have
        # kept them on purpose, so say what is being removed.
        deleted = _delete_traces_above(path, -1)
        if deleted:
            import warnings
            msg = (f"fresh run at {path!r}: removing {len(deleted)} "
                   f"pre-existing metric trace file(s) from an earlier "
                   f"run lineage: {deleted}")
            (log.warning if log is not None else
             lambda m: warnings.warn(m, stacklevel=2))(msg)
    r = start_round
    while r < n_rounds:
        step = min(chunk, n_rounds - r)
        state, metrics = run_fn(key, params, world, step,
                                state=state, start_round=r)
        jax.block_until_ready(state.status)
        r += step
        # Trace first, checkpoint second: a preemption between the two
        # re-runs this chunk on resume and deterministically overwrites the
        # orphaned trace (runs are bit-reproducible), so resumed traces
        # never have a hole.  Both writes are atomic.
        _atomic_savez(_metrics_path(path, r),
                      {k: np.asarray(v) for k, v in metrics.items()})
        save(path, state, r, key=key, meta=meta)
        metrics_chunks.append(metrics)
        if log is not None:
            log.info("checkpointed round %d/%d", r, n_rounds)
    return state, metrics_chunks
