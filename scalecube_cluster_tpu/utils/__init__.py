"""utils subpackage of scalecube_cluster_tpu."""
