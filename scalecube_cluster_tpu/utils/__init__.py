"""Host-side utilities for long runs: checkpointing and logging.

  - ``checkpoint``  atomic on-disk save/resume of the scan carry
    (SURVEY.md §5.4 — the subsystem the reference lacks but 10k-round
    TPU sweeps need)
  - ``runlog``      stdlib logging + metric digests + jax.profiler hook
    (the SLF4J/JMX observability analog, SURVEY.md §5.1)
"""

from scalecube_cluster_tpu.utils import checkpoint, runlog
from scalecube_cluster_tpu.utils.runlog import get_logger

__all__ = ["checkpoint", "runlog", "get_logger"]
