"""Run logging + profiling hooks for long scans.

The reference logs every protocol action through SLF4J (SURVEY.md §5.1:
per-period counters in FailureDetectorImpl.java:148,156-164, gossip sweep
logs at GossipProtocolImpl.java:300).  A dense 10k-round scan can't log
per-action from inside jit; the equivalent observability is:

  - a stdlib logger (:func:`get_logger`) for host-side progress — chunk
    boundaries, checkpoint writes, compile times, device info;
  - :func:`log_metrics_summary` to digest the per-round metric tensors the
    scan carries (models/swim.py metrics) into the protocol-level counters
    the reference logs;
  - :func:`profiled` to wrap a run with a ``jax.profiler`` step trace when
    ``SCALECUBE_TPU_PROFILE_DIR`` is set (inspect with TensorBoard).
"""

from __future__ import annotations

import contextlib
import logging
import os
import sys
import time

import numpy as np

_FORMAT = "%(asctime)s %(name)s %(levelname)s %(message)s"


def get_logger(name: str = "scalecube_tpu", level=None) -> logging.Logger:
    """Package logger; level from SCALECUBE_TPU_LOGLEVEL (default INFO).

    The resolved level is applied on EVERY call (an explicit ``level``
    argument wins over the env var), so repeat calls with a new level
    take effect regardless of whether the handler already exists.
    ``level`` may be a logging constant (including 0 == NOTSET) or a
    name like ``"DEBUG"``.
    """
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(handler)
        logger.propagate = False
    if level is None:
        level = os.environ.get("SCALECUBE_TPU_LOGLEVEL", "INFO")
    logger.setLevel(level)
    return logger


def log_metrics_summary(log: logging.Logger, metrics: dict,
                        round_offset: int = 0) -> None:
    """Digest a run's metric traces into the reference-style counters.

    ``metrics`` is the dict of [n_rounds, ...] traces returned by
    models/swim.run: status counts, false_positives, messages_*,
    refutations.  An empty dict logs a "no metrics" line instead of
    crashing (a zero-round chunk or a filtered-out trace is a valid
    input at a chunk boundary).
    """
    if not metrics:
        log.info("rounds starting at %d: no metrics to summarize",
                 round_offset)
        return
    n_rounds = len(np.asarray(next(iter(metrics.values()))))
    last = round_offset + n_rounds - 1

    def total(name):
        return int(np.asarray(metrics[name]).sum()) if name in metrics else 0

    log.info(
        "rounds [%d, %d]: pings sent %d (+%d ping-req fan-outs), "
        "tracked-subject probe verdicts %d, gossip msgs %d, "
        "refutations %d, false-positive observer-rounds %d",
        round_offset, last, total("messages_ping_sent"),
        total("messages_ping_req_sent"), total("messages_ping"),
        total("messages_gossip"), total("refutations"),
        total("false_positives"),
    )


def completion_barrier(x) -> float:
    """Force device execution to completion; returns the scalar fetched.

    On the axon TPU platform ``jax.block_until_ready`` has been observed
    returning before execution finishes for some compiled programs
    (e.g. the compact int16-carry [16k, 16k] scan "completed" in 0.000 s
    while the equivalent wide program blocked correctly).  Fetching a
    scalar reduction to the host is the reliable barrier — use this, not
    ``block_until_ready``, around any timed region on this platform.
    """
    import jax.numpy as jnp

    # dtype=int32 reduces without materializing an int32 copy of the
    # input — the barrier runs right at the OOM boundary in the
    # full-view capacity experiments, where a transient 4x-status-bytes
    # convert would perturb the measured ceiling.
    return float(jnp.sum(jnp.asarray(x), dtype=jnp.int32))


def enable_compilation_cache(log: logging.Logger = None) -> str:
    """Point jax at an on-disk compilation cache and return its path.

    A 1M-member scan compiles in ~45 s; the persistent cache turns every
    later same-shape compile (bench reruns, northstar chunks across
    invocations, CI) into a ~5 s load — measured 56.5 s -> 6.7 s across
    processes on the attached TPU.  Directory from
    ``SCALECUBE_XLA_CACHE_DIR`` (default ``~/.cache/scalecube_tpu_xla``);
    set it to the empty string to disable.
    """
    cache_dir = os.environ.get(
        "SCALECUBE_XLA_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "scalecube_tpu_xla"),
    )
    if not cache_dir:
        return ""
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    if log is not None:
        log.info("xla compilation cache at %s", cache_dir)
    return cache_dir


@contextlib.contextmanager
def profiled(log: logging.Logger = None):
    """jax.profiler trace when SCALECUBE_TPU_PROFILE_DIR is set, else no-op."""
    trace_dir = os.environ.get("SCALECUBE_TPU_PROFILE_DIR")
    t0 = time.perf_counter()
    if not trace_dir:
        yield
    else:
        import jax
        with jax.profiler.trace(trace_dir):
            yield
        if log is not None:
            log.info("profiler trace written to %s", trace_dir)
    if log is not None:
        log.info("profiled section took %.2fs", time.perf_counter() - t0)
