"""Lifeguard health plane: local health-aware probing and suspicion.

SWIM's false-positive rate is dominated by *observer-side* degradation:
a slow or browning-out member times out healthy peers, and the plain
protocol gives it no way to notice its own unreliability.  Lifeguard
(Dadgar, Hashemi & Currie, arXiv:1707.00788) fixes this with three
local mechanisms, all driven by one per-member integer — the Local
Health Multiplier (LHM):

  - **LHA Probe** (Local Health Aware Probe): a member's effective
    probe interval and probe timeout scale with its own LHM, so a
    member that keeps failing probes slows down and stops seeding
    false suspicions at full rate (``probe_gate`` /
    ``models/fd.effective_probe_budgets``);
  - **LHA Suspicion**: the suspicion deadline a member arms scales
    with its LHM and with ``log(n_live)`` (``suspicion_deadline_rounds``
    — the reference's ``suspicionMult * ceilLog2(n)`` schedule made
    live-count- and health-aware), giving falsely suspected peers more
    time to refute when the *observer* is the unhealthy party;
  - **Buddy System**: a probed member that is currently suspected by
    its prober learns this in the probe's ack path — the refute push in
    ``models/swim`` rides the FD ack channel whenever the plane is on,
    independent of the membership SYNC channel — and its
    self-refutation bump re-enters dissemination immediately.  (The
    dense wire model has no piggyback budget: every hot record already
    transmits on every gossip send, so Lifeguard's "refutations jump
    the piggyback queue" priority is the default here; the ack-path
    delivery is the part that needs mechanism.)

The LHM lane
------------
``SwimState.lhm`` [N] int32, clamped to ``[1, SwimParams.lhm_max]``
(1 = healthy).  Per round, for each live member that issued a probe:

  - clean ACK (direct ping answered within the scaled timeout): **-1**
    — the only decay path, mirroring Lifeguard's successful-probe
    decrement;
  - probe timeout (no ack at all) **or** a proxy-rescued probe whose
    direct ping timed out: **+1**.  (The collapsed probe chains of the
    dense tick — ``models/swim._chain_ok`` — don't expose individual
    missed nacks; a failed direct ping inside a rescued probe is this
    model's observable for Lifeguard's missed-nack event and carries
    the same self-degradation signal.)
  - refuting its own suspicion (the self-refutation incarnation bump):
    **+1**.

``SwimParams.lhm_max = 0`` (the default) compiles the whole plane out:
the lane is a zero-size array, no extra PRNG stream is drawn, and every
run shape is bit-identical to the plane-less tick (the
``sync_interval`` off-switch contract; tests/test_lifeguard.py).  With
the plane ON but every member healthy (lhm pinned at 1) the scaled
budgets and deadlines equal their base values and the probe gate always
passes, so warm no-fault runs are table- and metrics-identical too —
enabling the plane perturbs nothing until degradation actually occurs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from scalecube_cluster_tpu import swim_math

# Fold constant for the LHA probe gate's uniform draw — disjoint from
# every existing fold (0x5317 shift channels, 0x53CA anti-entropy
# offset, 41 anti-entropy drop, 29 seed anti-entropy, 7/11/13 delay
# bins, 11+c gossip bins), so enabling the plane never perturbs the
# base tick's draws (the lhm_max=0 bit-identity contract).
_PROBE_GATE_FOLD = 0x11F6

# This module's row in the composed-runner plane inventory
# (models/compose.plane_registry): an IN-TICK plane gated by lhm_max,
# carrying the [N] LHM lane inside SwimState.  A plain dict (no
# compose import: swim imports this module, compose imports swim).
PLANE = dict(
    name="lifeguard", kind="in-tick",
    knobs=("lhm_max", "dead_suppress_rounds"), lanes=("lhm",),
    doc="Local Health Multiplier lane driving LHA Probe/Suspicion and "
        "the buddy refute path (+ the dead-member suppression window)",
)


def initial_lhm(params) -> jnp.ndarray:
    """The carry lane: all-healthy (1) when the plane is on, a
    zero-size array when ``lhm_max == 0`` (costs nothing, keeps the
    pytree structure uniform)."""
    n = params.n_members if params.lhm_max > 0 else 0
    return jnp.ones((n,), dtype=jnp.int32)


def probe_gate(k_ping_net, lhm, n_local: int) -> jnp.ndarray:
    """[n_local] bool: does each member issue its probe this fd round?

    LHA Probe's interval scaling: a member at multiplier ``m`` probes
    with probability ``1/m`` per fd round — its *effective* probe
    interval is ``ping_every * m`` in expectation, without the
    per-member modular bookkeeping a deterministic stagger would need.
    At ``m == 1`` the gate always passes (``u < 1`` for u in [0, 1)),
    so healthy members probe exactly on the base schedule.

    The draw comes from a dedicated fold of the round's ping-chain key,
    so the probe chains' own draws are untouched.
    """
    u = jax.random.uniform(
        jax.random.fold_in(k_ping_net, _PROBE_GATE_FOLD), (n_local,)
    )
    return u * lhm.astype(jnp.float32) < 1.0


def lha_probe_setup(params, lhm, k_ping_net, n_local: int,
                    ping_timeout_ms=None):
    """The LHA Probe ingredients of one tick's FD phase:
    ``(ping_budget_ms, ping_req_budget_ms, probe_gate)`` — health-scaled
    chain budgets (models/fd.effective_probe_budgets) plus the 1/lhm
    probe gate, or ``(None, None, None)`` when the plane is compiled
    out.  ONE place for the block all three tick bodies (scatter,
    shift, blocked) share, so the budgets/gate cannot drift apart and
    break the pinned shift==blocked bit-identity.

    ``ping_timeout_ms`` overrides the static base budget (the
    ``Knobs.ping_timeout_ms`` sweep axis, pre-clamped by
    ``swim.knob_ping_timeout``); None = ``params.ping_timeout_ms``.
    """
    if params.lhm_max == 0:
        return None, None, None
    from scalecube_cluster_tpu.models import fd as fd_model

    ping_budget, ping_req_budget = fd_model.effective_probe_budgets(
        params, lhm, ping_timeout_ms=ping_timeout_ms)
    return ping_budget, ping_req_budget, probe_gate(k_ping_net, lhm,
                                                    n_local)


def suspicion_deadline_rounds(kn_suspicion_rounds, lhm, n_live,
                              n_members: int):
    """LHA Suspicion: the rounds-until-DEAD a member arms for a new
    SUSPECT entry, scaled by its own health and the live count.

    ``base + base * (lhm - 1) * ceil_log2(n_live) / ceil_log2(N)``
    (integer arithmetic, static denominator): the reference's
    ``suspicionMult * ceilLog2(n) * pingInterval`` schedule
    (ClusterMath.java:123-125) already folded ``ceil_log2(N)`` into
    ``base``; the health-scaled extension re-shapes that term with the
    CURRENT live count and multiplies it by the observer's excess
    multiplier.  Properties (pinned by tests/test_lifeguard.py):

      - never below ``base`` (lhm >= 1 makes the extra term >= 0) —
        a healthy observer's deadline is exactly the reference's;
      - monotone in ``lhm`` and in ``n_live``;
      - at most ``base * lhm_max`` (n_live <= N), the bound the
        TIMER_BOUND invariant enforces (chaos/monitor.py).

    ``n_live`` is the GROUND-TRUTH live count (one [N] reduction per
    round) — the reference uses each member's local list size; in the
    warm regime the two track each other, and using the shared truth
    keeps the schedule identical across focal mode (where an observer
    tracks only K subjects and has no local estimate of N_live).
    """
    base = jnp.asarray(kn_suspicion_rounds, jnp.int32)
    log_live = swim_math.ceil_log2_jnp(n_live)
    log_n = max(swim_math.ceil_log2(n_members), 1)
    extra = (base * (jnp.asarray(lhm, jnp.int32) - 1) * log_live) // log_n
    return base + extra


def update(lhm, probe_fail, probe_clean, refuted, alive_here,
           lhm_max: int):
    """One round's LHM transition (module docstring): +1 per failed /
    proxy-rescued probe, +1 per self-refutation, -1 per clean ACK,
    clamped to [1, lhm_max].  Frozen (crashed/left) members keep their
    multiplier — a stopped JVM updates nothing; on revival the stale
    health decays through its own probes.
    """
    delta = (probe_fail.astype(jnp.int32)
             - probe_clean.astype(jnp.int32)
             + refuted.astype(jnp.int32))
    bumped = jnp.clip(lhm + delta, 1, lhm_max)
    return jnp.where(alive_here, bumped, lhm)
