"""Gossip-only dissemination model: the TPU analog of GossipProtocolImpl.

Simulates infection-style dissemination of G tracked gossips over N members
as a ``jax.lax.scan`` over gossip periods — the batched equivalent of the
reference's gossip component in isolation (the setup of its statistical
experiment matrix, GossipProtocolTest.java:50-66: {N, loss%, meanDelay}).

Reference behaviors modeled (gossip/GossipProtocolImpl.java):
  - per-period fanout selection over remote members (:252-273) ->
    ``prng.targets_excluding_self``;
  - a member spreads each live gossip for ``periodsToSpread =
    repeatMult * ceilLog2(n+1)`` periods after first receiving it
    (:239-250, ClusterMath.java:111-113) -> per-(member, gossip)
    ``spread_until`` round;
  - delivery dedup by gossip id (:176-180) -> the infection bit itself;
  - NetworkEmulator per-message loss (NetworkEmulator.java:132-192) ->
    Bernoulli ``drop`` mask per (sender, fanout-slot).

Deviations, documented:
  - the per-gossip "infected" set (don't re-send to the member you got it
    from, GossipState.java:17-38) is not tracked: we re-send and rely on
    delivery dedup, which the protocol tolerates (SURVEY.md §7 hard parts);
    message *counts* therefore track the ClusterMath worst-case bound
    (max_messages_per_gossip_per_node) rather than the slightly lower
    typical count.
  - mean link delay quantizes to the period grid via a delayed-delivery
    ring (``max_delay_rounds`` slots): a message's exponential delay draw
    (NetworkLinkSettings.java:64-74) becomes a round offset
    floor(delay/period), saturating at the ring depth.  With
    ``max_delay_rounds=0`` delays below one period (the reference's
    2ms-100ms sweep vs 200ms periods) round to same-period delivery.

State is O(N·G) bits, not O(N²), so this model scales to millions of
members on one chip; rows shard over devices via parallel/mesh.py.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from scalecube_cluster_tpu import swim_math
from scalecube_cluster_tpu.ops import delivery, prng, ring as ring_ops


@dataclasses.dataclass(frozen=True)
class GossipSimParams:
    """Static (compile-time) knobs of the gossip tick.

    Derived from ClusterConfig gossip settings (config.GossipConfig fields;
    reference gossip/GossipConfig.java:3-10) for a given cluster size.
    """

    n_members: int
    n_gossips: int
    fanout: int
    periods_to_spread: int
    loss_probability: float = 0.0
    mean_delay_ms: float = 0.0
    round_ms: float = 200.0
    max_delay_rounds: int = 0

    @staticmethod
    def from_config(config, n_members: int, n_gossips: int = 1,
                    loss_probability: float = 0.0,
                    mean_delay_ms: float = 0.0,
                    max_delay_rounds: int = 0) -> "GossipSimParams":
        return GossipSimParams(
            n_members=n_members,
            n_gossips=n_gossips,
            fanout=config.gossip_fanout,
            periods_to_spread=swim_math.gossip_periods_to_spread(
                config.gossip_repeat_mult, n_members
            ),
            loss_probability=loss_probability,
            mean_delay_ms=mean_delay_ms,
            round_ms=float(config.gossip_interval),
            max_delay_rounds=max_delay_rounds,
        )


@dataclasses.dataclass
class GossipState:
    """Scan carry: per-(member, gossip) infection state.

    ``infected``     [N, G] bool — member has the gossip (delivery-dedup bit,
                     GossipProtocolImpl.java:176-180).
    ``spread_until`` [N, G] int32 — first period this member no longer
                     retransmits it (GossipState.infectionPeriod analog,
                     gossip/GossipState.java:8-38).
    ``ring``         [D, N, G] bool — infection bits due in future rounds
                     (delayed-delivery ring; D = max_delay_rounds + 1 or 0).
    """

    infected: jnp.ndarray
    spread_until: jnp.ndarray
    ring: jnp.ndarray


jax.tree_util.register_dataclass(
    GossipState, data_fields=["infected", "spread_until", "ring"],
    meta_fields=[]
)


def initial_state(params: GossipSimParams,
                  origin: Optional[jnp.ndarray] = None) -> GossipState:
    """Each gossip g starts at member ``origin[g]`` (default member g).

    Mirrors ``spread()`` enqueueing at the originating member
    (GossipProtocolImpl.java:163-169) at period 0.
    """
    n, g = params.n_members, params.n_gossips
    if origin is None:
        origin = jnp.arange(g, dtype=jnp.int32) % n
    infected = jnp.zeros((n, g), dtype=jnp.bool_).at[origin, jnp.arange(g)].set(True)
    spread_until = jnp.where(infected, params.periods_to_spread, 0).astype(jnp.int32)
    d = params.max_delay_rounds + 1 if params.max_delay_rounds > 0 else 0
    return GossipState(infected=infected, spread_until=spread_until,
                       ring=jnp.zeros((d, n, g), dtype=jnp.bool_))


def gossip_tick(state: GossipState, round_idx, base_key,
                params: GossipSimParams) -> tuple:
    """One gossip period (the body of doSpreadGossip, :139-157).

    Returns (new_state, metrics) where metrics is a dict of per-round
    observables (the TPU analog of the NetworkEmulator counters the
    reference tests measure with, GossipProtocolTest.java:212-228).
    """
    key = prng.round_key(base_key, round_idx)
    k_targets, k_drop, k_delay = jax.random.split(key, 3)

    # selectGossipsToSend (:239-250): alive == still within spread window.
    hot = state.infected & (round_idx < state.spread_until)

    targets = prng.targets_excluding_self(
        k_targets, params.n_members, params.n_members, params.fanout
    )
    drop = prng.bernoulli_mask(
        k_drop, params.loss_probability, (params.n_members, params.fanout)
    )

    ring = state.ring
    if params.max_delay_rounds == 0:
        inbox = delivery.scatter_or(hot, targets, drop, params.n_members)
    else:
        # Quantized per-message delay (ops/ring.py): offset-0 messages land
        # now, later offsets go to the ring slots.
        d = params.max_delay_rounds + 1
        slot0 = round_idx % d
        q = ring_ops.delay_bins(
            k_delay, params.mean_delay_ms, params.round_ms,
            params.max_delay_rounds, (params.n_members, params.fanout),
        )
        due_now, ring = ring_ops.open_slot(ring, slot0, False)
        inbox = delivery.scatter_or(hot, targets, drop | (q != 0),
                                    params.n_members) | due_now
        for j in range(1, d):
            contribution = delivery.scatter_or(
                hot, targets, drop | (q != j), params.n_members
            )
            ring = ring_ops.push_or(ring, (slot0 + j) % d, contribution)

    newly = inbox & ~state.infected
    infected = state.infected | inbox
    spread_until = jnp.where(
        newly, round_idx + 1 + params.periods_to_spread, state.spread_until
    )

    # Transmissions this period, per gossip (ClusterMath bound substrate).
    sent = jnp.sum(hot, axis=0, dtype=jnp.int32) * params.fanout
    metrics = {
        "infected_count": jnp.sum(infected, axis=0, dtype=jnp.int32),
        "messages_sent": sent,
        "newly_infected": jnp.sum(newly, axis=0, dtype=jnp.int32),
    }
    return GossipState(infected=infected, spread_until=spread_until,
                       ring=ring), metrics


@partial(jax.jit, static_argnames=("params", "n_rounds"))
def run(base_key, params: GossipSimParams, n_rounds: int,
        state: Optional[GossipState] = None):
    """Scan the gossip tick over ``n_rounds`` periods.

    Returns (final_state, metrics) with metrics arrays of leading dim
    ``n_rounds`` — the full dissemination trace (infected-count curve =
    the measured analog of ClusterMath.gossipConvergencePercent).
    """
    if state is None:
        state = initial_state(params)

    def body(carry, round_idx):
        new_state, metrics = gossip_tick(carry, round_idx, base_key, params)
        return new_state, metrics

    final_state, metrics = jax.lax.scan(
        body, state, jnp.arange(n_rounds, dtype=jnp.int32)
    )
    return final_state, metrics


def piggyback_occupancy(hot_count, capacity):
    """Gossip piggyback occupancy: fraction of live tracked records
    currently inside their retransmission window (the health-registry
    gauge, telemetry/metrics.py).

    ``hot_count`` = records matching the ``selectGossipsToSend`` window
    (GossipProtocolImpl.java:239-250 — the same ``hot`` mask the send
    path transmits); ``capacity`` = live members x tracked subjects.
    Near 0 in the steady state (nothing to piggyback), near 1 when the
    membership churns faster than the spread windows drain — sustained
    high occupancy is the wire-amplification early warning.
    """
    cap = jnp.maximum(jnp.asarray(capacity, jnp.float32), 1.0)
    return jnp.asarray(hot_count, jnp.float32) / cap


def dissemination_rounds(metrics, n_members: int):
    """First round at which each gossip reached all N members (-1 if never).

    The measured counterpart of ClusterMath.gossipDisseminationTime
    (ClusterMath.java:77-79) in period units.
    """
    full = metrics["infected_count"] >= n_members
    ever = jnp.any(full, axis=0)
    first = jnp.argmax(full, axis=0)
    return jnp.where(ever, first, -1)
