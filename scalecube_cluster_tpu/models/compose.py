"""Composable plane runner: ONE scan, shared round reductions, any
plane stack.

The reference layers its protocol as independent peer components driven
by one scheduler thread (membership ⊕ fdetector ⊕ gossip ⊕ metadata —
PAPER.md §1 L3); this module is the dense-tick analog of that layering.
A **plane** is a small object declaring three hooks over the shared
protocol scan:

  ``init(params, world)``      -> its carry slice (one pytree, carried
                                  through the scan next to ``SwimState``;
                                  resume state threads through here)
  ``on_round(rc, slice)``      -> the per-round observation fold,
                                  reading the shared :class:`RoundCtx`
  ``finalize(fc, slice)``      -> the end-of-run sample (gauges, etc.)
                                  over the shared :class:`FinalCtx`

plus, for planes that batch work across a fused scan step (the event
trace's one-scatter-per-step record), the optional fused pair
``on_round_fused(rc, slice) -> (slice, out)`` / ``on_step(rounds_k,
slice, stacked_outs, world) -> slice`` with ``fused = True``.

:func:`composed_scan` drives the protocol tick once per round and hands
every plane the SAME :class:`RoundCtx` — live masks, the status-change
matrix and its emptiness predicate, the wide carry decodes and the wide
deadline lane are each computed ONCE per round and memoized, where the
pre-compose run shapes re-derived them per subsystem
(telemetry/trace.py, telemetry/metrics.py and chaos/monitor.py each
recomputed ``world.alive_at``, the ``prev != new`` gate and the compact
decode independently).  :func:`composed_shard_scan` is the row-sharded
twin (serial or software-pipelined delivery — ``_pipelined_rounds``
lives here too, so every scan driver is in one module).

All eight run entry points are thin aliases over these three drivers:

  ``models/swim.run``                    -> composed_scan, no planes
  ``models/swim.run_traced``             -> + TracePlane
  ``models/swim.run_metered``            -> + MetricsPlane
  ``chaos/monitor.run_monitored``        -> + MonitorPlane
  ``chaos/monitor.run_monitored_metered``-> + MonitorPlane ⊕ MetricsPlane
  ``chaos/monitor.run_monitored_batch``  -> composed_batch_scan + MonitorPlane
  ``parallel/mesh.shard_run``            -> composed_shard_scan
  ``parallel/mesh.shard_run_metered``    -> + MetricsPlane (sharded)

each bit-identical to its pre-compose hand-threaded body (the per-plane
math is byte-for-byte the same calls on the same values — pinned by
tests/test_compose.py and the per-subsystem suites), and the NEXT plane
lands by writing one plane module instead of editing ~28 files
(ROADMAP item 1's acceptance bar).  :func:`run_composed` is the new
capability the aliases cannot express: the FULL instrumented stack
(trace ⊕ metrics ⊕ monitor) in one program and one pass over the
rounds, where the alias-by-alias route pays three compiles and three
scans (``bench.py --compose`` measures the gap;
artifacts/compose_perf.json).

The in-tick planes (SYNC anti-entropy, Lifeguard health, the
open-world identity epoch, delay rings, user gossip) are compiled into
``swim_tick`` by their ``SwimParams`` knobs and carried inside
``SwimState`` lanes; :func:`plane_registry` lists them next to the
observer planes with their knob gates and carry lanes, so swimlint's
plane matrix and a human reader see one inventory
(tests/test_compose.py pins the registry against the real dataclasses).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from scalecube_cluster_tpu.models import swim


def wide_view(params: "swim.SwimParams", st: "swim.SwimState", cursor):
    """Any carry layout -> the WIDE form observer planes read (lossless
    below the caps the layouts already validate).  The one decode site
    the monitor and metrics planes share through :class:`RoundCtx`."""
    if params.compact_carry:
        return swim._carry_decode(st, cursor)
    if params.int16_wire:
        return dataclasses.replace(st, inc=st.inc.astype(jnp.int32))
    return st


class RoundCtx:
    """Shared per-round context: everything more than one plane might
    derive from one tick's (prev, new) pair, computed ONCE and memoized.

    ``prev``/``new`` are the scan carry BEFORE/AFTER the tick in their
    STORED layout; ``metrics`` the tick's per-round metrics dict
    (already psum-global under sharding).  Planes read the raw fields
    for stored-layout math and the lazy properties for the shared
    derivations; a derivation is traced the first time any plane asks
    and handed to every later plane from the cache — which is exactly
    the "computed once per round" contract the composed full stack
    buys over three independent run shapes.
    """

    __slots__ = ("params", "world", "kn", "round_idx", "prev", "new",
                 "metrics", "offset", "axis_name", "lead", "provenance",
                 "_cache", "_plane_prev", "_plane_new")

    def __init__(self, params, world, kn, round_idx, prev, new, metrics,
                 offset=0, axis_name=None, lead=None, provenance=None):
        self.params = params
        self.world = world
        self.kn = kn
        self.round_idx = round_idx
        self.prev = prev
        self.new = new
        self.metrics = metrics
        self.offset = offset
        self.axis_name = axis_name
        self.lead = lead
        # The tick's per-channel folded maxima (SwimParams.provenance:
        # dict(fd=, gossip=, sync=, ping_req=) of local-row arrays),
        # popped out of the metrics dict by the scan drivers BEFORE the
        # scan stacks metrics — None when the knob is off.
        self.provenance = provenance
        self._cache = {}
        self._plane_prev = {}
        self._plane_new = {}

    def _memo(self, key, fn):
        if key not in self._cache:
            self._cache[key] = fn()
        return self._cache[key]

    # -- live masks --------------------------------------------------------

    @property
    def alive_now(self):
        """[N] ground-truth liveness at this round (world.alive_at) —
        consulted by the monitor's eligibility masks AND the metrics
        plane's live_observer_rounds counter."""
        return self._memo("alive_now",
                          lambda: self.world.alive_at(self.round_idx))

    # -- the shared emptiness gate -----------------------------------------

    @property
    def status_changed(self):
        """[N, K] bool: cells whose status changed this tick — the one
        compare matrix behind the trace event derivation, the metrics
        suspicion-transition gate and the trace emptiness predicate."""
        return self._memo(
            "status_changed",
            lambda: self.prev.status != self.new.status)

    @property
    def any_status_change(self):
        """Scalar emptiness predicate over :attr:`status_changed` —
        the trace/metrics gates share this ONE reduction."""
        return self._memo("any_status_change",
                          lambda: jnp.any(self.status_changed))

    # -- wide decodes ------------------------------------------------------

    @property
    def prev_wide(self):
        """``prev`` decoded wide at this round's cursor (the monitor's
        check input; under compact carries this is the per-round decode
        the pre-compose monitored scan paid on its own)."""
        return self._memo(
            "prev_wide",
            lambda: wide_view(self.params, self.prev, self.round_idx))

    @property
    def new_wide(self):
        """``new`` decoded wide at the NEXT round's cursor."""
        return self._memo(
            "new_wide",
            lambda: wide_view(self.params, self.new, self.round_idx + 1))

    @property
    def prev_deadline_wide(self):
        """``prev.suspect_deadline`` in absolute wide rounds — the lane
        the metrics plane's suspicion-lifetime recovery reads.  Served
        from :attr:`prev_wide` when a plane already paid the full
        decode (the monitored-metered stack), else from the two-lane
        ``swim._wide_timer_fields`` fast path (the metrics-only
        stack)."""
        def derive():
            if "prev_wide" in self._cache:
                return self._cache["prev_wide"].suspect_deadline
            return swim._wide_timer_fields(self.prev, self.params,
                                           self.round_idx)[0]
        return self._memo("prev_deadline_wide", derive)

    # -- cross-plane reads -------------------------------------------------

    def plane_before(self, name: str):
        """Another plane's carry slice BEFORE its on_round this round
        (planes run in stack order; later planes may read earlier
        ones — the metered monitor's chaos_violations delta)."""
        return self._plane_prev[name]

    def plane_after(self, name: str):
        """Another plane's carry slice AFTER its on_round this round."""
        return self._plane_new[name]


class FinalCtx:
    """Shared end-of-run context for plane finalizers: the final carry
    at cursor ``end_round`` plus the stacked per-round metrics, with
    the wide decodes and liveness slices memoized like
    :class:`RoundCtx`."""

    __slots__ = ("params", "world", "kn", "end_round", "final_state",
                 "metrics", "offset", "axis_name", "n_local", "_cache")

    def __init__(self, params, world, kn, end_round, final_state, metrics,
                 offset=0, axis_name=None, n_local=None):
        self.params = params
        self.world = world
        self.kn = kn
        self.end_round = end_round
        self.final_state = final_state
        self.metrics = metrics
        self.offset = offset
        self.axis_name = axis_name
        self.n_local = n_local
        self._cache = {}

    def _memo(self, key, fn):
        if key not in self._cache:
            self._cache[key] = fn()
        return self._cache[key]

    @property
    def spread_until_wide(self):
        """Final ``spread_until`` decoded wide at the end cursor (the
        piggyback-occupancy gauge input)."""
        return self._memo(
            "spread_until_wide",
            lambda: swim._wide_timer_fields(self.final_state, self.params,
                                            self.end_round)[1])

    @property
    def alive_here(self):
        """Ground-truth liveness rows matching the (possibly local)
        final carry: the full [N] vector single-device, this shard's
        contiguous slice under sharding."""
        def derive():
            alive = self.world.alive_at(self.end_round)
            if self.n_local is not None \
                    and self.n_local != self.params.n_members:
                return jax.lax.dynamic_slice_in_dim(alive, self.offset,
                                                    self.n_local)
            return alive
        return self._memo("alive_here", derive)

    @property
    def last_tick_metrics(self):
        """The final round's row of the wire-gauge inputs."""
        return self._memo(
            "last_tick_metrics",
            lambda: {k: self.metrics[k][-1]
                     for k in ("messages_gossip",) if k in self.metrics})


# --------------------------------------------------------------------------
# The scan drivers
# --------------------------------------------------------------------------


def _pop_provenance(m):
    """Detach the tick's in-band provenance evidence from the metrics
    dict (the ``swim._round_metrics`` passthrough) BEFORE the scan
    stacks it: the per-channel [n_local, K] maxima ride the
    :class:`RoundCtx` for the provenance plane's attribution and must
    never reach a ``[rounds, N, K]`` stacked metrics trace.  Returns
    the popped dict, or None when the knob is off."""
    return m.pop("_provenance", None)


def _apply_planes(planes, rc: RoundCtx, slices) -> Tuple:
    """One round's plane folds, in stack order, publishing each plane's
    before/after slice into the ctx for cross-plane reads."""
    out = []
    for plane, sl in zip(planes, slices):
        rc._plane_prev[plane.name] = sl
        new_sl = plane.on_round(rc, sl)
        rc._plane_new[plane.name] = new_sl
        out.append(new_sl)
    return tuple(out)


def _finalize_planes(planes, fc: FinalCtx, slices) -> dict:
    return {plane.name: plane.finalize(fc, sl)
            for plane, sl in zip(planes, slices)}


def composed_scan(base_key, params: "swim.SwimParams",
                  world: "swim.SwimWorld", n_rounds: int, planes=(),
                  state: Optional["swim.SwimState"] = None,
                  start_round: int = 0,
                  knobs: Optional["swim.Knobs"] = None, shift_key=None):
    """Scan the SWIM tick over ``n_rounds`` with ``planes`` riding the
    carry — the ONE single-device scan body behind run / run_traced /
    run_metered / run_monitored / run_monitored_metered and
    :func:`run_composed`.

    Round fusion (``params.rounds_per_step``) is honored exactly like
    the pre-compose entries: planes without a fused hook fold once per
    tick inside the fused body; a ``fused`` plane's per-round outputs
    are stacked and handed to its ``on_step`` once per scan step (the
    trace plane's single batched event scatter) — bit-identical to the
    per-round path for any K (``swim._fused_scan`` docstring).

    Returns ``(final_state, {plane name: finalized slice}, metrics)``.
    """
    kn = knobs if knobs is not None else swim.Knobs.from_params(params)
    if state is None:
        state = swim.initial_state(params, world)
    slices = tuple(p.init(params, world) for p in planes)

    def tick(carry, round_idx):
        st, pcs = carry
        new_st, m = swim.swim_tick(st, round_idx, base_key, params, world,
                                   knobs=kn, shift_key=shift_key)
        prov = _pop_provenance(m)
        rc = RoundCtx(params, world, kn, round_idx, st, new_st, m,
                      provenance=prov)
        return (new_st, _apply_planes(planes, rc, pcs)), m

    k = params.rounds_per_step
    fused_body = None
    if k > 1 and any(getattr(p, "fused", False) for p in planes):
        def fused_body(carry, rounds_k):
            # K ticks with per-round plane folds, but each fused
            # plane's record half batched ONCE per step — flattened
            # round-major, bit-identical to K sequential folds
            # (telemetry/trace.record_events_batch docstring).
            st, pcs = carry
            pcs = list(pcs)
            ms = []
            step_outs = {i: [] for i, p in enumerate(planes)
                         if getattr(p, "fused", False)}
            for j in range(k):
                prev = st
                st, m = swim.swim_tick(prev, rounds_k[j], base_key,
                                       params, world, knobs=kn,
                                       shift_key=shift_key)
                prov = _pop_provenance(m)
                rc = RoundCtx(params, world, kn, rounds_k[j], prev, st, m,
                              provenance=prov)
                for i, plane in enumerate(planes):
                    rc._plane_prev[plane.name] = pcs[i]
                    if i in step_outs:
                        pcs[i], out = plane.on_round_fused(rc, pcs[i])
                        step_outs[i].append(out)
                    else:
                        pcs[i] = plane.on_round(rc, pcs[i])
                    rc._plane_new[plane.name] = pcs[i]
                ms.append(m)
            for i, outs in step_outs.items():
                stacked = jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *outs)
                pcs[i] = planes[i].on_step(rounds_k, pcs[i], stacked,
                                           world)
            return (st, tuple(pcs)), jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *ms)

    (final_state, slices), metrics = swim._fused_scan(
        tick, (state, slices), n_rounds, start_round, k,
        fused_body=fused_body,
    )
    fc = FinalCtx(params, world, kn, start_round + n_rounds, final_state,
                  metrics)
    return final_state, _finalize_planes(planes, fc, slices), metrics


def _pipelined_rounds(base_key, params: "swim.SwimParams",
                      world: "swim.SwimWorld", state: "swim.SwimState",
                      n_rounds: int, start_round, offset, axis: str,
                      n_dev: int, on_round=None, carry0=None):
    """Software-pipelined scatter round loop (runs INSIDE shard_map).

    Round structure: scan body j combines + merges round j-1's carried
    contribution (swim.swim_tick_recv) and then computes round j's
    sends (swim.swim_tick_send); the first send runs as a prologue and
    the last combine+merge as an epilogue.  The cross-device pmax of a
    round therefore sits in the SAME program body as the next round's
    state-independent draw compute (targets, drop masks, FD chains),
    which is what lets XLA's latency-hiding scheduler run the ICI
    transfer under it — in the serial body the pmax's only in-body
    consumers follow it immediately, and an async collective pair
    cannot span the scan iteration boundary.

    Because delivery is already "send this round, listen next round"
    (the merge is the tick's last phase), this is a scheduling change
    only: outputs are BIT-IDENTICAL to the serial scan
    (tests/test_pipelined_delivery.py), at the cost of double-buffering
    one [N, K] contribution in the carry — a SINGLE packed-key buffer
    under the fused wire (SwimParams.fused_wire, the default: the
    ALIVE flags ride the key bits), the legacy key + int8 flag pair
    under ``fused_wire=False``.

    ``on_round(extra, prev_state, round_idx, new_state, metrics)`` is
    the per-round observation hook (the composed plane folds), applied
    after each round's merge with the round's OWN index and pre-merge
    state — exactly the serial ordering; ``carry0`` is its initial
    value.  Returns (final_state, extra, stacked metrics).
    """
    if n_rounds < 1:
        raise ValueError("pipelined delivery needs n_rounds >= 1")

    def send(st, r):
        return swim.swim_tick_send(st, r, base_key, params, world,
                                   offset=offset, axis_name=axis,
                                   n_devices=n_dev)

    def recv(st, pend, aux, r):
        return swim.swim_tick_recv(st, pend, aux, r, base_key, params,
                                   world, offset=offset, axis_name=axis,
                                   n_devices=n_dev)

    start = jnp.asarray(start_round, jnp.int32)
    pending, send_aux = send(state, start)

    def body(carry, round_idx):
        st, pend, aux, extra = carry
        new_st, metrics = recv(st, pend, aux, round_idx - 1)
        if on_round is not None:
            extra = on_round(extra, st, round_idx - 1, new_st, metrics)
        # on_round pops the provenance evidence into its RoundCtx; this
        # defensive pop keeps the STACKED metrics clean when no planes
        # ride (provenance on, plane off — still a valid config).
        _pop_provenance(metrics)
        new_pend, new_aux = send(new_st, round_idx)
        return (new_st, new_pend, new_aux, extra), metrics

    rounds = jnp.arange(1, n_rounds, dtype=jnp.int32) + start
    (st, pend, aux, extra), ms = jax.lax.scan(
        body, (state, pending, send_aux, carry0), rounds
    )
    last = start + jnp.int32(n_rounds - 1)
    final_state, last_metrics = recv(st, pend, aux, last)
    if on_round is not None:
        extra = on_round(extra, st, last, final_state, last_metrics)
    _pop_provenance(last_metrics)
    metrics = jax.tree.map(
        lambda rows, tail: jnp.concatenate([rows, tail[None]], axis=0),
        ms, last_metrics,
    )
    return final_state, extra, metrics


def composed_shard_scan(base_key, params: "swim.SwimParams",
                        world: "swim.SwimWorld",
                        state: "swim.SwimState", n_rounds: int,
                        start_round, offset, axis: str, n_dev: int,
                        n_local: int, planes=(),
                        use_pipeline: bool = False, lead=None):
    """The row-sharded twin of :func:`composed_scan` — runs INSIDE
    shard_map with this device's ``offset``/``n_local`` row slice,
    driving either the serial fused scan or the software-pipelined
    delivery loop (:func:`_pipelined_rounds`), with the plane folds
    observing each round after its (possibly deferred) merge with the
    SAME pre-merge state and round index the serial body sees — so
    plane slices stay bit-identical across ``pipelined`` too.

    ``lead`` is the sharded-dedup weight for psum-global tick counters
    (telemetry/metrics.observe_tick) — the ctx carries it to every
    plane.  Returns ``(final_state, {name: finalized}, metrics)``.
    """
    kn = swim.Knobs.from_params(params)
    slices = tuple(p.init(params, world) for p in planes)

    if use_pipeline:
        def on_round(pcs, prev_st, round_idx, new_st, m):
            prov = _pop_provenance(m)
            rc = RoundCtx(params, world, kn, round_idx, prev_st, new_st,
                          m, offset=offset, axis_name=axis, lead=lead,
                          provenance=prov)
            return _apply_planes(planes, rc, pcs)

        final_state, slices, metrics = _pipelined_rounds(
            base_key, params, world, state, n_rounds, start_round,
            offset, axis, n_dev,
            on_round=on_round if planes else None, carry0=slices,
        )
    else:
        def body(carry, round_idx):
            st, pcs = carry
            new_st, m = swim.swim_tick(
                st, round_idx, base_key, params, world,
                offset=offset, axis_name=axis, n_devices=n_dev,
            )
            prov = _pop_provenance(m)
            rc = RoundCtx(params, world, kn, round_idx, st, new_st, m,
                          offset=offset, axis_name=axis, lead=lead,
                          provenance=prov)
            return (new_st, _apply_planes(planes, rc, pcs)), m

        # _fused_scan honors params.rounds_per_step (bit-identical for
        # any K; k == 1 is the classic per-round scan) — the pipelined
        # path declares fusion unsupported instead
        # (swim.pipelined_delivery_unsupported_reason), so auto-select
        # falls back to this body when both knobs are on.
        (final_state, slices), metrics = swim._fused_scan(
            body, (state, slices), n_rounds, start_round,
            params.rounds_per_step,
        )

    fc = FinalCtx(params, world, kn, start_round + n_rounds, final_state,
                  metrics, offset=offset, axis_name=axis, n_local=n_local)
    return final_state, _finalize_planes(planes, fc, slices), metrics


# --------------------------------------------------------------------------
# The batched scan driver — (scenarios × knobs) on one device program
# --------------------------------------------------------------------------


#: RoundCtx memo keys whose batched values vmap row-wise into a per-row
#: fold's cache (leading batch axis maps off).  ``any_status_change`` is
#: deliberately ABSENT: the batched value is the GLOBAL reduce over all
#: rows (the batch-level cond predicate), not any row's own scalar — a
#: per-row fold must recompute its own from the seeded status_changed.
_ROW_CACHE_KEYS = ("alive_now", "status_changed", "prev_wide", "new_wide",
                   "prev_deadline_wide")


class BatchRoundCtx(RoundCtx):
    """The batched :class:`RoundCtx`: ``world``/``kn``/``prev``/``new``/
    ``metrics`` carry a leading batch axis; the shared derivations are
    computed ONCE over the whole batch (vmapped) and memoized exactly
    like the unbatched ctx, so every plane in the stack reads the same
    batched matrices.

    :attr:`any_status_change` (inherited — ``jnp.any`` over the
    [B, N, K] compare matrix) is the BATCH-LEVEL emptiness predicate:
    a ``lax.cond`` gated on it sits OUTSIDE the row vmap and fires iff
    ANY row has fresh evidence — the PR-12 trick that keeps per-round
    gates as real branches instead of vmap-lowered select-both-branches
    (which made naive vmap-of-scan 4-5x slower).  Planes whose silent
    branch is an exact identity per row (trace's drop-scatter, the
    monitor's zero-total record) stay bit-identical per row under it.
    """

    __slots__ = ()

    @property
    def alive_now(self):
        """[B, N] ground-truth liveness at this round, per row."""
        return self._memo(
            "alive_now",
            lambda: jax.vmap(lambda w: w.alive_at(self.round_idx))(
                self.world))

    @property
    def prev_wide(self):
        return self._memo(
            "prev_wide",
            lambda: jax.vmap(
                lambda st: wide_view(self.params, st, self.round_idx))(
                    self.prev))

    @property
    def new_wide(self):
        return self._memo(
            "new_wide",
            lambda: jax.vmap(
                lambda st: wide_view(self.params, st, self.round_idx + 1))(
                    self.new))

    @property
    def prev_deadline_wide(self):
        def derive():
            if "prev_wide" in self._cache:
                return self._cache["prev_wide"].suspect_deadline
            return jax.vmap(
                lambda st: swim._wide_timer_fields(st, self.params,
                                                   self.round_idx)[0])(
                    self.prev)
        return self._memo("prev_deadline_wide", derive)

    def per_row_fold(self, plane, sl):
        """Run a plane's plain (unbatched) ``on_round`` vmapped over the
        rows — the fallback for planes without an ``on_round_batch``.

        Each row sees a plain :class:`RoundCtx` seeded with the row
        slice of every batch-level memo already paid
        (:data:`_ROW_CACHE_KEYS`) and of every already-published plane
        slice, so cross-plane reads and the computed-once contract
        survive the vmap boundary.  Inside the vmap, per-row
        ``lax.cond`` gates lower to select-both-branches — values are
        bit-identical to the sequential per-row fold (both branches are
        pure), only the skip-when-empty economics change, which is
        exactly what ``on_round_batch`` exists to recover.
        """
        cache_keys = [k for k in _ROW_CACHE_KEYS if k in self._cache]
        cache_vals = tuple(self._cache[k] for k in cache_keys)
        prev_names = list(self._plane_prev)
        prev_vals = tuple(self._plane_prev[n] for n in prev_names)
        new_names = list(self._plane_new)
        new_vals = tuple(self._plane_new[n] for n in new_names)

        def row(world, kn, prev, new, metrics, sl_row, cvals, pvals,
                nvals, prov):
            rc = RoundCtx(self.params, world, kn, self.round_idx, prev,
                          new, metrics, provenance=prov)
            rc._cache.update(zip(cache_keys, cvals))
            rc._plane_prev.update(zip(prev_names, pvals))
            rc._plane_new.update(zip(new_names, nvals))
            return plane.on_round(rc, sl_row)

        return jax.vmap(row)(self.world, self.kn, self.prev, self.new,
                             self.metrics, sl, cache_vals, prev_vals,
                             new_vals, self.provenance)


def _apply_planes_batch(planes, rc: BatchRoundCtx, slices) -> Tuple:
    """One round's plane folds over the batched ctx: a plane that
    declares ``on_round_batch`` gets the whole batch (and can gate its
    evidence recording on the batch-level predicates); any other plane
    folds per row via :meth:`BatchRoundCtx.per_row_fold`."""
    out = []
    for plane, sl in zip(planes, slices):
        rc._plane_prev[plane.name] = sl
        fold = getattr(plane, "on_round_batch", None)
        new_sl = (fold(rc, sl) if fold is not None
                  else rc.per_row_fold(plane, sl))
        rc._plane_new[plane.name] = new_sl
        out.append(new_sl)
    return tuple(out)


def composed_batch_scan(base_keys, params: "swim.SwimParams", worlds,
                        n_rounds: int, planes=(), states=None,
                        start_round: int = 0,
                        knobs: Optional["swim.Knobs"] = None):
    """The batched analogue of :func:`composed_scan`: ``base_keys`` /
    ``worlds`` / ``knobs`` (and optional resume ``states``) stacked on
    a leading batch axis, ONE scan over the rounds with the protocol
    tick vmapped inside it — so B independent scenarios (or one
    scenario under B knob settings, or any product of both: stack the
    product) advance in lockstep through one compiled program.

    Structure, and why it is this way and not vmap-of-scan:

      - the scan is OUTSIDE the vmap: per-round ``lax.cond`` gates in
        plane folds stay real branches, fired on BATCH-LEVEL predicates
        (:class:`BatchRoundCtx`), where vmapping the whole scan would
        lower every cond to select-both-branches per row (measured
        4-5x slower on the fuzz campaign, PR 12);
      - ``knobs`` are traced DATA: sweeping a knob grid reuses one
        compile for the whole grid (zero recompiles per config — the
        tune/search.py contract, pinned via ``_cache_size`` deltas);
      - planes ride batched: ``on_round_batch`` where a plane defines
        it, vmapped plain ``on_round`` otherwise, one memoized
        :class:`BatchRoundCtx` either way.

    Round fusion (``params.rounds_per_step``) unrolls K vmapped ticks
    per scan step exactly like the unbatched driver; the fused
    ``on_round_fused``/``on_step`` pair is NOT used here — the
    batch-level evidence cond already amortizes the per-round scatter
    the fused pair exists to batch, and the pair's step-stacked layout
    does not commute with the row vmap.  Sharding does not compose with
    the batch axis either (:func:`batch_shard_unsupported_reason`).

    ``knobs=None`` broadcasts :meth:`swim.Knobs.from_params` over the
    batch; resume ``states`` must already be batch-stacked.  Pinned
    contracts (tests/test_compose_batch.py): B=1 equals the unbatched
    :func:`composed_scan` bit-exactly, and row i of any batch equals
    the sequential run of that row's (key, world, knobs) alone.

    Returns ``(final_states, {plane name: finalized slice}, metrics)``
    with every output batch-leading (metrics ``[B, n_rounds, ...]``).
    """
    batch = jax.tree_util.tree_leaves(base_keys)[0].shape[0]
    kn = knobs
    if kn is None:
        kn = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (batch,) + x.shape),
            swim.Knobs.from_params(params))
    if states is None:
        states = jax.vmap(lambda w: swim.initial_state(params, w))(worlds)
    slices = tuple(jax.vmap(lambda w, p=p: p.init(params, w))(worlds)
                   for p in planes)

    def tick(carry, round_idx):
        sts, pcs = carry
        new_sts, ms = jax.vmap(
            lambda st, key, w, k: swim.swim_tick(st, round_idx, key,
                                                 params, w, knobs=k)
        )(sts, base_keys, worlds, kn)
        prov = _pop_provenance(ms)
        rc = BatchRoundCtx(params, worlds, kn, round_idx, sts, new_sts,
                           ms, provenance=prov)
        return (new_sts, _apply_planes_batch(planes, rc, pcs)), ms

    (final_states, slices), metrics = swim._fused_scan(
        tick, (states, slices), n_rounds, start_round,
        params.rounds_per_step,
    )
    # Scan stacks rounds on axis 0 with the batch axis inside; every
    # public output is batch-leading.
    metrics = {k: jnp.moveaxis(v, 0, 1) for k, v in metrics.items()}

    results = {}
    if planes:
        end_round = start_round + n_rounds

        def fin(world, k, st, ms, sls):
            fc = FinalCtx(params, world, k, end_round, st, ms)
            return tuple(p.finalize(fc, s) for p, s in zip(planes, sls))

        finalized = jax.vmap(fin)(worlds, kn, final_states, metrics,
                                  slices)
        results = {p.name: r for p, r in zip(planes, finalized)}
    return final_states, results, metrics


def batch_shard_unsupported_reason(params: "swim.SwimParams") -> str:
    """Why :func:`composed_batch_scan` does not compose with the row
    mesh — a declared reason (the ``pipelined_delivery_unsupported``
    pattern), never a silent wrong answer.

    The batch axis vmaps INDEPENDENT worlds on one device; the sharded
    driver's shard_map collectives (the delivery pmax / metrics psum
    over the row mesh) reduce over rows of ONE world split across
    devices.  Vmapping those collectives over a scenario batch would
    need a second mesh axis per batch row — shard the members or batch
    the scenarios, not both in one program.  For batch throughput on a
    multi-chip host, run one :func:`composed_batch_scan` per device
    over disjoint scenario sub-batches instead (no cross-talk to
    reduce)."""
    return ("batch axis is single-device: composed_shard_scan's "
            "shard_map collectives reduce over member rows of one "
            "world and cannot be vmapped over independent batched "
            "worlds")


# --------------------------------------------------------------------------
# The full instrumented stack in ONE program
# --------------------------------------------------------------------------


def build_stack(with_trace: bool, with_metrics: bool, with_monitor: bool,
                monitor_spec=None, trace_capacity=None, metrics_spec=None,
                monitor_capacity=None, telemetry=None, metrics_state=None,
                monitor=None, with_provenance: bool = False,
                provenance_capacity=None):
    """The observer-plane stack of :func:`run_composed`, in canonical
    order (trace, then provenance, then monitor before metrics, so the
    metered chaos_violations counter can read the monitor's per-round
    count delta)."""
    planes = []
    if with_trace:
        from scalecube_cluster_tpu.telemetry import trace as ttrace

        planes.append(ttrace.TracePlane(
            capacity=(ttrace.DEFAULT_CAPACITY if trace_capacity is None
                      else trace_capacity),
            telemetry=telemetry,
        ))
    if with_provenance:
        from scalecube_cluster_tpu.models import provenance as mprov

        planes.append(mprov.ProvenancePlane(
            capacity=(mprov.DEFAULT_CAPACITY if provenance_capacity is None
                      else provenance_capacity),
        ))
    if with_monitor:
        from scalecube_cluster_tpu.chaos import monitor as cmonitor

        if monitor_spec is None:
            raise ValueError(
                "run_composed(with_monitor=True) needs monitor_spec (use "
                "chaos.monitor.MonitorSpec.passive(params) for the "
                "safety-only checks)")
        planes.append(cmonitor.MonitorPlane(
            monitor_spec,
            capacity=(cmonitor.DEFAULT_CAPACITY if monitor_capacity is None
                      else monitor_capacity),
            monitor=monitor,
        ))
    if with_metrics:
        from scalecube_cluster_tpu.telemetry import metrics as tmetrics

        planes.append(tmetrics.MetricsPlane(
            (tmetrics.MetricsSpec.default() if metrics_spec is None
             else metrics_spec),
            metrics_state=metrics_state,
            chaos_from="monitor" if with_monitor else None,
        ))
    return tuple(planes)


@partial(jax.jit,
         static_argnames=("params", "n_rounds", "with_trace",
                          "with_metrics", "with_monitor", "trace_capacity",
                          "metrics_spec", "monitor_capacity",
                          "with_provenance", "provenance_capacity"),
         donate_argnames=("state",))
def run_composed(base_key, params: "swim.SwimParams",
                 world: "swim.SwimWorld", n_rounds: int,
                 monitor_spec=None, with_trace: bool = True,
                 with_metrics: bool = True, with_monitor: bool = True,
                 trace_capacity: Optional[int] = None,
                 metrics_spec=None, monitor_capacity: Optional[int] = None,
                 state: Optional["swim.SwimState"] = None,
                 start_round: int = 0,
                 knobs: Optional["swim.Knobs"] = None, shift_key=None,
                 telemetry=None, metrics_state=None, monitor=None,
                 with_provenance: bool = False,
                 provenance_capacity: Optional[int] = None):
    """The FULL instrumented stack in one compiled program and one scan:
    event trace ⊕ invariant monitor ⊕ health-metrics registry riding
    the protocol scan together, sharing one :class:`RoundCtx` per
    round.

    Pre-compose, this took THREE separate entry points — run_traced +
    run_metered + run_monitored — i.e. three XLA programs and three
    full passes over the rounds, each re-deriving the per-round live
    masks, status-change gates and wide decodes (``bench.py --compose``
    measures the gap; the protocol state and each plane's output are
    bit-identical to the corresponding single-plane alias, pinned by
    tests/test_compose.py).

    ``with_*`` (static) toggle planes; resume slices thread through
    ``telemetry``/``metrics_state``/``monitor`` exactly like the
    aliases' arguments.  ``state`` is DONATED (the swim.run contract);
    plane slices are not.  Returns ``(final_state, results, metrics)``
    where ``results`` maps each enabled plane's name to its finalized
    slice (``results["trace"]`` etc.).
    """
    stack = build_stack(
        with_trace, with_metrics, with_monitor,
        monitor_spec=monitor_spec, trace_capacity=trace_capacity,
        metrics_spec=metrics_spec, monitor_capacity=monitor_capacity,
        telemetry=telemetry, metrics_state=metrics_state, monitor=monitor,
        with_provenance=with_provenance,
        provenance_capacity=provenance_capacity,
    )
    return composed_scan(base_key, params, world, n_rounds, planes=stack,
                         state=state, start_round=start_round, knobs=knobs,
                         shift_key=shift_key)


# --------------------------------------------------------------------------
# The plane inventory (observer planes + the in-tick planes)
# --------------------------------------------------------------------------

# The protocol core and the knob-gated in-tick planes, declared here so
# one registry lists EVERY plane with its knob gate and carry lanes
# (tests/test_compose.py pins knob/lane names against the real
# dataclasses; models/sync.py and models/lifeguard.py declare their own
# rows as plain PLANE dicts, collected below).
_CORE_PLANES = (
    dict(name="protocol", kind="core", knobs=(), lanes=(
        "status", "inc", "spread_until", "suspect_deadline", "self_inc"),
        doc="the SWIM tick itself (models/swim.swim_tick)"),
    dict(name="delay", kind="in-tick", knobs=("max_delay_rounds",),
         lanes=("inbox_ring", "flag_ring"),
         doc="delayed-delivery rings (0 = same-round-or-lost)"),
    dict(name="user_gossip", kind="in-tick", knobs=("n_user_gossips",),
         lanes=("g_infected", "g_spread_until", "g_ring"),
         doc="user-payload gossip riding the membership channels"),
    dict(name="open_world", kind="in-tick",
         knobs=("open_world", "epoch_guard"), lanes=("epoch",),
         doc="JOIN admission into recycled slots, identity-epoch lane"),
)

_OBSERVER_PLANES = (
    dict(name="trace", kind="observer", knobs=(), lanes=(),
         doc="membership event trace (telemetry/trace.TracePlane)"),
    dict(name="provenance", kind="observer", knobs=("provenance",),
         lanes=(),
         doc="per-belief channel attribution "
             "(models/provenance.ProvenancePlane); the knob arms the "
             "tick bodies' per-channel exposure the plane reads"),
    dict(name="monitor", kind="observer", knobs=(), lanes=(),
         doc="in-jit invariant monitor (chaos/monitor.MonitorPlane)"),
    dict(name="metrics", kind="observer", knobs=(), lanes=(),
         doc="health-metrics registry (telemetry/metrics.MetricsPlane)"),
)


def plane_registry() -> Tuple[dict, ...]:
    """Every plane the composed runner knows: the protocol core, the
    knob-gated in-tick planes (incl. the rows models/sync.py,
    models/lifeguard.py and models/metadata.py declare for themselves)
    and the observer planes — name, kind, gating knobs, SwimState
    carry lanes."""
    from scalecube_cluster_tpu.models import lifeguard, metadata, sync

    return _CORE_PLANES[:1] + (dict(sync.PLANE), dict(lifeguard.PLANE),
                               dict(metadata.PLANE)) \
        + _CORE_PLANES[1:] + _OBSERVER_PLANES
