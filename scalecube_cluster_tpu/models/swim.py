"""The full SWIM tick: failure detection + gossip + suspicion + SYNC on TPU.

This is the flagship model: the reference's three protocol components —
FailureDetectorImpl (random probe + ping-req), GossipProtocolImpl
(infection-style dissemination) and MembershipProtocolImpl (merge rule,
suspicion timeouts, incarnation self-refutation, SYNC anti-entropy) — lifted
into ONE pure state-transition function over dense arrays, scanned over
protocol rounds with ``jax.lax.scan``.  The lift is faithful because the
reference already runs each node's whole stack single-threaded on one
scheduler (SURVEY.md §1): a node's behavior in a period IS a pure function
of (state, inbound messages, RNG).

State layout — the subject-view matrix
--------------------------------------
``[N, K]`` arrays where row i = observer node, column k = *tracked subject*
(``subject_ids[k]`` is the subject's node index):

  - **full-view mode** (K == N, subjects = everyone): exact dense SWIM,
    every node tracks every node — the reference semantics, O(N²) state,
    practical to ~16k members/chip.
  - **focal mode** (K << N): only K focal subjects' records are tracked
    through the full protocol machinery; the other N-K members are alive
    background that probes, relays gossip and syncs.  State is O(N·K), so
    1M members × 10k rounds fits one chip — this is what produces the
    dissemination / first-false-positive curves at the BASELINE.md scale
    (the reference itself never ran above N=50, SURVEY.md §6).

Delivery modes (``SwimParams.delivery``)
----------------------------------------
  - ``"scatter"``: exact per-node uniform target draws, delivered with
    XLA scatter-max (ops/delivery.py).  Reference-faithful sampling; the
    validation mode.
  - ``"shift"``: cyclic-shift mixing (ops/shift.py) — every send channel
    uses one fresh random shift per round shared by all nodes, so the
    whole exchange is contiguous vector ops.  This is the fast path the
    1M-member benchmark runs; its statistics are validated against
    scatter mode and the oracle (tests/test_shift_mode.py).

Network faults — the NetworkEmulator analog
-------------------------------------------
Per-link loss/delay/block lives in :class:`LinkFaults`: an ordered list of
override rules (sender-id range × receiver-id range × round window →
loss probability, mean delay), the vectorization of the reference's
per-destination link-settings map (transport/NetworkEmulator.java:132-192,
NetworkLinkSettings.java:15-80; block == loss 1.0).  Rules evaluate
elementwise against any (src, dst) id arrays — O(N·R) with no [N,N]
materialization, so the same mechanism works at N=50 and N=1M.  Process
faults (crash, revive, graceful leave) and rolling partitions are separate
schedules on :class:`SwimWorld`.

Time quantization: the gossip period is the base round
(config.ClusterConfig.to_sim); pings fire every ``ping_every`` rounds,
SYNC every ``sync_every``.  Sub-round timing (pingTimeout vs pingInterval,
exponential link delays) is resolved in closed form inside the FD phase by
sampling per-hop delays and comparing sums against the millisecond budgets
— the phased collapse of the 3-hop ping-req flow (SURVEY.md §7 hard parts).

Documented deviations from the reference (all statistical-regime-neutral):
  - scatter mode draws fanout targets with replacement (ops/prng.py
    docstring); shift mode shares per-round target offsets across nodes
    (ops/shift.py docstring);
  - FD probe targets are drawn uniformly per period instead of round-robin
    over a shuffled pass (FailureDetectorImpl.java:338-347); detection-time
    distributions at large N are indistinguishable, and the SWIM paper
    itself analyzes the uniform variant;
  - shift-mode FD probing draws ONE shared target offset per fd round: a
    node probes only when that offset lands on an entry it knows
    ALIVE/SUSPECT, so its per-round probe probability equals its
    fraction-known instead of re-drawing uniformly among known members.
    In the warm steady state (everyone known) this is statistically
    neutral; during cold-start joins or heavy churn, partially-joined
    nodes probe proportionally less often than the reference would —
    use scatter mode to validate cold-start FD behavior;
  - the SYNC exchange is push-only per round (the syncAck pull is replaced
    by the partner's own future random pushes — symmetric in distribution
    in the warm steady state); during COLD START, where push-only is far
    too slow, the reference's join protocol is restored exactly: members
    holding ABSENT entries run a joiner ⇄ seed SYNC round trip each sync
    round (``_seed_anti_entropy`` — doSync's seeds ∪ live candidate rule
    + the syncAck reply, MembershipProtocolImpl.java:298-331,346-367),
    active in FULL-VIEW mode with seeds configured (join semantics are a
    full-view concern; focal mode's cold start remains statistical) and
    inert once views are full;
    an FD ALIVE-verdict on a suspected member pushes the suspect record to
    the member itself (MembershipProtocolImpl.java:379-391's SYNC), whose
    self-refutation then travels back by gossip;
  - gossip per-gossip "infected" sets are not tracked (models/gossip.py);
  - link delay affects FD hop budgets exactly; for gossip/SYNC it
    quantizes to round offsets through the delayed-delivery ring
    (``SwimParams.max_delay_rounds``; offsets beyond the ring saturate at
    its last slot rather than dropping).  With max_delay_rounds=0 those
    channels are same-round-or-lost — exact for the reference's default
    regime where mean delay << gossip interval.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from scalecube_cluster_tpu import records
from scalecube_cluster_tpu.models import lifeguard
from scalecube_cluster_tpu.models import metadata
from scalecube_cluster_tpu.models import sync as sync_plane
from scalecube_cluster_tpu.ops import delivery, prng, ring as ring_ops, \
    shift as shift_ops
from scalecube_cluster_tpu.telemetry import trace as telemetry_trace

INT32_MAX = jnp.iinfo(jnp.int32).max


# --------------------------------------------------------------------------
# Static parameters
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SwimParams:
    """Compile-time shape/schedule knobs of the SWIM tick.

    Round-quantized from ClusterConfig via :meth:`from_config`
    (config.ClusterConfig.to_sim describes the quantization rule).
    Millisecond knobs that resolve *within* a round (ping_timeout_ms,
    mean_delay_ms) stay in ms and are compared against sampled hop delays.
    """

    n_members: int
    n_subjects: int
    fanout: int
    periods_to_spread: int
    ping_every: int
    sync_every: int
    suspicion_rounds: int
    ping_req_members: int
    # Sub-round timing (ms), resolved in closed form in the FD phase.
    ping_timeout_ms: float = 500.0
    ping_interval_ms: float = 1000.0
    mean_delay_ms: float = 0.0
    loss_probability: float = 0.0
    # True: FD probes uniformly among *known* subjects (exact reference
    # behavior, full-view mode); False: uniformly over the whole cluster
    # (focal mode, where most members aren't tracked subjects).
    ping_known_only: bool = True
    # Per-subject metric columns (disable for K too large to trace).
    per_subject_metrics: bool = True
    # Delivery collective: "scatter" (exact uniform draws, XLA scatter) or
    # "shift" (cyclic-shift mixing, the fast path — module docstring).
    delivery: str = "scatter"
    # Base round length (= gossip interval) in ms, used to quantize link
    # delays to round offsets for gossip/SYNC delivery.
    round_ms: float = 200.0
    # Max gossip/SYNC delivery delay in rounds (0 = same-round-or-lost).
    # When > 0 the scan carry gains a (max_delay_rounds+1)-slot inbox ring
    # and each message's sampled exponential delay quantizes to
    # floor(delay / round_ms), clamped to this bound.  The reference's
    # NetworkEmulator delays every message this way
    # (NetworkLinkSettings.java:64-74); its test matrix sweeps mean delay
    # to half a gossip period (GossipProtocolTest.java:50-66), where
    # ~13% of messages cross into the next round.
    max_delay_rounds: int = 0
    # Capacity-oriented carry/wire layout for full-view scale runs: the
    # carry stores int16 incarnation (saturating at 8191, like the wire),
    # int8 remaining-spread-rounds and int16 remaining-suspicion-rounds
    # (re-relativized to the round cursor every tick — lossless while the
    # deadline is < 32767 rounds ahead), and every key buffer (payloads,
    # inbox, scatter contributions) uses the int16 records.merge_key16
    # wire format: 6 B/cell of carry + 2 B/cell of inbox vs 13 + 4 wide.
    # Protocol-trace-identical to the wide layout while incarnations stay
    # below the 8191 saturation point (tests/test_compact_carry.py).
    # The round-3 narrow-int experiment measured this layout ~12% SLOWER
    # at 1M focal (narrow lanes cost more in the merge fusion than the
    # saved bandwidth) — it exists to raise the [N, N] single-chip
    # CEILING, where the regime is capacity-, not compute-bound.
    compact_carry: bool = False
    # int16 WIRE keys with the WIDE carry — the hybrid the round-3
    # narrow-int negative did not cover: that experiment narrowed the
    # carry lanes (which made the merge fusion slower than the saved
    # bandwidth); this knob narrows only the wire-format buffers
    # (payloads, channel delivers, inbox, delay-ring slots) to the int16
    # records.merge_key16 format while every carry field stays at the
    # wide dtypes.  The merge upcasts the inbox on load (i16 load + i32
    # compute).  Trace-identical to the wide wire while incarnations stay
    # below merge_key16's 8191 saturation (same cap as compact_carry;
    # tests/test_wire16.py).  Implied by compact_carry (whose wire is
    # already int16); see ``compact_wire`` for the derived predicate.
    # MEASURED NEGATIVE for speed (round 5): 3.76 vs 3.05 ms/round at
    # 1M x 16 and 5.76 vs 4.99 at 131k x 256 — the halved key bytes do
    # not pay for the narrow-lane loads/compares inside the merge
    # fusion, even at a 256-wide minor dim.  The knob stays as the
    # wire-format seam (and because the sharded traffic model's ICI
    # bytes DO halve — parallel/traffic._key_bytes — a multi-chip,
    # ICI-bound regime may price it differently than single-chip HBM).
    int16_wire: bool = False
    # wire24: the compact-carry HEADROOM rung of the wire-format ladder
    # (ops/delivery.WIRE24).  The STORED table stays int16 (requires
    # ``compact_carry`` — that pairing is the point: wire width is
    # decoupled from carry width) but the WIRE key widens from the int16
    # merge_key16 layout to a 24-bit field inside an int32 word — epoch
    # 2 -> 4 bits, and the incarnation ceiling rises from the wire16
    # cap (8191, or 2^11-1 = 2047 with epoch bits) to the int16
    # stored-incarnation ceiling 32767 (_wire_inc_sat: the wire field
    # itself carries 2^22-1 / 2^18-1, so the carry dtype binds first).
    # Wire buffers (payloads, inbox, delay-ring slots, scatter
    # contributions) are int32 — under the FUSED single-buffer scatter
    # wire this costs zero extra collectives and, per slot, the same
    # 4 B the pre-ladder wide wire paid for its key alone
    # (parallel/traffic.scatter_wire_bytes_per_slot).
    wire24: bool = False
    # FUSED single-buffer wire (scatter delivery): the per-slot
    # ALIVE/transmit flag is NOT shipped as a parallel [N, K] int8
    # buffer — it already lives in the key word's spare bits (an ALIVE
    # record is exactly a key with the dead and suspect bits clear,
    # ops/delivery.is_alive_key), so the merge gate derives it from the
    # round's folded winner key.  The scatter tick then moves ONE
    # full-height [N, K] buffer per round instead of the key + flag
    # pair: one cross-device collective instead of two (each delay bin
    # likewise halved), 4 B/slot instead of 5 on the wide wire, and the
    # pipelined double-buffer (parallel/mesh._pipelined_rounds) carries
    # a single buffer.  Documented gate deviation: the separate flag
    # buffer OR-folded aliveness over ALL of a round's arrivals, so an
    # ABSENT-gated cell could open on a losing ALIVE arrival and store
    # a non-ALIVE winner; the fused gate opens only when the WINNER
    # itself is ALIVE — the reference's per-message null-gate
    # (MembershipRecord.java:67-69) applied to the round's folded
    # message.  The two differ only when an ALIVE and a strictly
    # higher non-ALIVE record about the same subject land at the same
    # ABSENT-gated cell in the same round (tests/test_wire_fused.py
    # pins both the scenario-level identity and the corner).
    # False = the pre-ladder two-buffer wire, kept as the bench.py
    # --wire comparison baseline and equivalence-pin arm.
    fused_wire: bool = True
    # Single-device shift delivery: replace the persistent doubled
    # [2N, K] payload buffers with a jnp.roll per channel (transient
    # two-slice concats) — value-identical (ops/shift.ShiftEngine
    # docstring), measured ~equal speed at full-view scale, and a
    # negative result for capacity: the ceiling boundary turned out to
    # be compile-stage, not HBM (RESULTS.md round-4 optimization log).
    # No effect on sharded runs (sharded payloads never double) or
    # scatter mode.
    shift_roll_payloads: bool = False
    # Per-sender wire counters — the NetworkEmulator measurement substrate
    # (transport/NetworkEmulator.java:200-222 totalMessageSent/LostCount;
    # the reference's gossip experiments read exactly these counters,
    # GossipProtocolTest.java:212-228).  When on, each round's metrics
    # gain ``sent_by_node``/``lost_by_node`` [N] int32: wire messages
    # each sender issued, and the subset dropped in flight by the network
    # model (per-link loss/block rules, default loss, partition walls).
    # "Lost" counts network drops only — a message toward a crashed
    # receiver still counts as sent (the reference increments sent before
    # the connect; a refused connect is an error, not an emulator loss).
    # FD probe chains are collapsed to one closed-form draw (_chain_ok),
    # so their in-flight losses are not attributable per hop: pings and
    # ping-req fan-outs count as sent, and probe-chain loss surfaces in
    # verdicts rather than lost_by_node (documented deviation; the
    # reference substrate's tests measure the gossip channel, where this
    # accounting is exact).  Single-device only (the counters are a
    # small/medium-N measurement substrate, not a 1M perf path).
    link_counters: bool = False
    # K-tiled round body for full-view capacity runs (0 = off).  The
    # standard shift tick materializes one [N, K] payload temp per send
    # channel (deliver_channel's masked keys); at the [N, N] single-chip
    # ceiling those temps — not the carry — bind HBM (measured at
    # N=28,160: 11.8G of HLO temps, six 1.48G s16[N, N] buffers, vs the
    # 4.4G donated carry; experiments/ceiling_probe.py).  With
    # ``k_block = Kb`` the tick runs a fori_loop over K/Kb column blocks:
    # each block's payloads/inbox/merge are [N, Kb] transients and the
    # block's new state is written straight into the carry accumulator,
    # so peak HBM ~= one carry + O(N·Kb) — the per-node O(cluster) table
    # (MembershipProtocolImpl.java:82) at near carry-bound N.
    # Bit-identical to the unblocked shift tick (same shifts, same draws
    # — delivery rotates rows, so column blocks are independent;
    # tests/test_blocked_tick.py).  Constraints: shift delivery,
    # full-view, single device, max_delay_rounds=0, no link_counters, no
    # seed-gated contacts.
    k_block: int = 0
    # User-payload gossip co-running with membership in ONE gossip
    # machinery — the reference's GossipProtocol carries arbitrary user
    # gossips AND membership piggyback through the same component
    # (GossipProtocolImpl.java:124-128 spread(), 139-157 doSpreadGossip;
    # membership piggybacks via spreadMembershipGossip,
    # MembershipProtocolImpl.java:620-635).  G > 0 adds [N, G] infection
    # state to the carry: ``SwimWorld.with_spread`` schedules spread()
    # calls (origin, round), and the bits ride the SAME gossip channels,
    # loss draws, and delay bins as the membership records — one
    # GOSSIP_REQ per (sender, target) carries both, exactly the
    # reference's one-wire-message batching (GossipProtocolImpl.java:
    # 211-237 sends all selected gossips in one message).  Spread window
    # = periods_to_spread, the ClusterMath schedule shared with
    # membership records.  Metrics gain ``user_gossip_infected`` [G].
    n_user_gossips: int = 0
    # Round fusion: ``run``/``run_traced`` scan a body that unrolls this
    # many protocol ticks per scan step, amortising the scan's per-step
    # carry layout fix-ups and dispatch over K rounds (an explicit
    # K-unrolled body rather than ``lax.scan(..., unroll=K)``, so the
    # stacked per-round metric rows stay inside one fused step instead
    # of round-tripping the scan output buffers each round).  Outputs
    # are BIT-IDENTICAL to the unfused path for any K: each tick's PRNG
    # stream is a pure function of (base_key, round_idx) — not of scan
    # position — and per-round counter rows / trace lanes are stacked
    # [steps, K, ...] then reshaped back to [rounds, ...] in round
    # order (tests/test_round_fusion.py).  A trailing n_rounds % K
    # remainder runs through an unfused tail scan, so any (n_rounds, K)
    # pair is legal.  1 = the classic one-tick-per-step scan.
    rounds_per_step: int = 1
    # SYNC anti-entropy plane (models/sync.py): every ``sync_interval``
    # rounds each live member exchanges its FULL syncable table — status
    # + incarnation lanes — with a shared-offset partner pair
    # ((i ± s) mod N; the doSync/syncAck round trip realized as two
    # dense channels, models/sync.py module docstring for the deviation
    # argument).  This is the partition-heal repair loop: stale
    # divergence that aged out of the piggyback window re-enters the
    # table merge and re-disseminates, so healed partitions re-converge
    # within ~(sync_interval + dissemination bound) rounds.  0 (the
    # default) compiles the plane OUT entirely — every run shape is
    # bit-identical to the plane-less tick (tests/test_sync_plane.py).
    # Distinct from ``sync_every``, the reference-faithful push-only
    # per-round SYNC channel: the plane runs much less often and is
    # bidirectional.  Enabled runs grow a ``messages_anti_entropy``
    # per-round counter in the metrics dict.
    sync_interval: int = 0
    # Lifeguard health plane (models/lifeguard.py): per-member Local
    # Health Multiplier lane, clamped to [1, lhm_max] — incremented on
    # probe timeout / proxy-rescued probe / refuting own suspicion,
    # decayed on clean ACK.  Scales the member's effective probe
    # interval + timeout (LHA Probe, models/fd.effective_probe_budgets)
    # and the suspicion deadlines it arms (LHA Suspicion,
    # lifeguard.suspicion_deadline_rounds), and routes the buddy-system
    # refute push over the FD ack path independent of ``sync_every``.
    # 0 (the default) compiles the plane OUT entirely — zero-size lane,
    # no extra draws, every run shape bit-identical to the plane-less
    # tick (the sync_interval off-switch contract;
    # tests/test_lifeguard.py).
    lhm_max: int = 0
    # Dead-member suppression window (the PR-7 mid-suspicion-heal debt,
    # models/sync.py "quiesced-heal precondition"): for this many rounds
    # after a tombstone is stored, the cell does NOT reopen for an
    # arriving ALIVE — it gates by its true DEAD key instead of the
    # reference's delete-like ABSENT gate — which breaks the DEAD/ALIVE
    # reinfection ping-pong a mid-suspicion heal otherwise sustains
    # (each reopen re-hots the death notice and burns another
    # incarnation; tests/test_dead_suppression.py pins termination).
    # The window expiry is tracked in the cell's ``suspect_deadline``
    # lane (unused for DEAD cells otherwise); size it past the
    # tombstone's gossip expiry (periods_to_spread + 1) so the notice
    # goes cold before the cell can reopen.  0 (the default) keeps the
    # reference's immediate-reopen behavior, bit-identical.
    dead_suppress_rounds: int = 0
    # Open-world membership plane: JOIN admission into recycled DEAD
    # slots mid-run (``SwimWorld.with_join``).  When on, every slot
    # carries a per-record IDENTITY EPOCH lane (``SwimState.epoch``
    # [N, K]; int16 under compact_carry, the lhm-lane pattern) and
    # every wire key carries (slot, epoch, incarnation) — the epoch
    # field sits directly under the dead bit (ops/delivery.py layout
    # comment), so the inbox fold keeps the reference's DEAD-absorbs
    # order while the merge gate resolves identities.  A join resets
    # the slot's row (fresh cold table, self_inc 0, lhm 1) and bumps
    # its ground-truth epoch (``SwimWorld.epoch_at``); the joiner
    # announces itself hot and observers ADMIT the new identity through
    # the epoch gate — the reference's Cluster.join / seed-sync arrival
    # path (MembershipProtocolImpl.start0) for a recycled slot.  False
    # (the default) compiles the plane out entirely: zero-size lane,
    # the exact pre-open-world wire layout, every run shape
    # bit-identical (tests/test_open_world.py).
    open_world: bool = False
    # Identity-epoch merge guard (meaningful only with ``open_world``):
    # True (default) = the epoch lane + wire field exist and cross-epoch
    # records DROP at the merge gate, with a new identity admitted only
    # through its own ALIVE announcement (ops/delivery.merge_inbox
    # docstring) — including through the SYNC anti-entropy exchange and
    # OVER the dead_suppress_rounds window (a suppressed tombstone must
    # not block a higher-epoch JOIN).  False = the NAIVE-reuse control
    # arm (bench.py --churn): joins still recycle slots, but the wire
    # and merge are the reference's EPOCH-BLIND legacy layout — the old
    # occupant's hot tombstone kills the new member and its stale
    # higher-incarnation ALIVE notices shadow/resurrect the dead
    # identity, which the invariant monitor proves attribution-free by
    # incarnation forensics (a live record with inc above the subject's
    # own self_inc cannot be about the current occupant —
    # chaos/monitor.NO_RESURRECTION / JOIN_COMPLETENESS).
    epoch_guard: bool = True
    # Metadata KV plane (models/metadata.py): M fixed-shape per-member
    # config cells, LWW-versioned per the (slot, epoch) identity, hot
    # rows piggybacking the gossip channels and the full table riding
    # the anti-entropy exchange (sync_interval > 0) — the reference's
    # MetadataStoreImpl as infection-style payload dissemination.
    # 0 (the default) compiles the plane OUT entirely: zero-size
    # ``md``/``md_spread`` lanes, no extra draws (the plane reuses the
    # round's existing targets and drop masks), every layout and run
    # shape bit-identical to the plane-less tick
    # (tests/test_metadata_plane.py).  Requires full view (column j IS
    # node j — the owner-row authority rule) and excludes k_block (an
    # [N, N, M] table has no place on the >10M capacity path).
    metadata_keys: int = 0
    # Provenance plane (models/provenance.py): per-(observer, subject)
    # CHANNEL ATTRIBUTION of every status transition — which channel's
    # folded key won the round (FD direct ack/timeout, ping-req proxy,
    # piggyback gossip, SYNC exchange, self-refutation, join-rebirth).
    # True arms the tick bodies to expose per-channel folded maxima
    # into ``aux["_provenance"]`` (picked up by the composed runner's
    # shared RoundCtx); the attribution itself lives in the plane.
    # False (the default) compiles the exposure OUT entirely — no
    # extra folds, no extra metrics keys, every layout and run shape
    # bit-identical to the plane-less tick (tests/test_provenance.py).
    # Requires max_delay_rounds == 0: the delay ring folds all
    # channels into shared bins before delivery, so per-channel
    # identity is unrecoverable there.
    provenance: bool = False

    def __post_init__(self):
        if self.delivery not in ("scatter", "shift"):
            raise ValueError(f"unknown delivery mode {self.delivery!r}")
        if self.sync_interval < 0:
            raise ValueError(
                f"sync_interval must be >= 0 (0 = anti-entropy plane off; "
                f"got {self.sync_interval})"
            )
        if self.sync_interval > 0 and self.n_members < 2:
            raise ValueError(
                "the anti-entropy exchange needs n_members >= 2 "
                "(a single member has no partner to pair with)"
            )
        if self.rounds_per_step < 1:
            raise ValueError(
                f"rounds_per_step must be >= 1 (got {self.rounds_per_step})"
            )
        if self.lhm_max < 0:
            raise ValueError(
                f"lhm_max must be >= 0 (0 = Lifeguard plane off; got "
                f"{self.lhm_max})"
            )
        if self.dead_suppress_rounds < 0:
            raise ValueError(
                f"dead_suppress_rounds must be >= 0 (0 = immediate "
                f"tombstone reopen; got {self.dead_suppress_rounds})"
            )
        if self.delivery == "shift" and self.ping_known_only != self.full_view:
            # Shift mode has no known-only probe path at K < N (its FD
            # target is the shared offset; eligibility is evaluated at the
            # slot) — the two flags must agree so wire-probe counters and
            # FD targeting mean the same thing in both delivery modes.
            # from_config derives ping_known_only = (K == N); direct
            # constructions must do the same.
            raise ValueError(
                f"shift delivery requires ping_known_only == full_view "
                f"(got ping_known_only={self.ping_known_only}, "
                f"n_subjects={self.n_subjects}, n_members={self.n_members})"
            )
        if self.k_block:
            if self.delivery != "shift" or not self.full_view:
                raise ValueError(
                    "k_block is the full-view shift-mode capacity path "
                    f"(got delivery={self.delivery!r}, "
                    f"n_subjects={self.n_subjects}, "
                    f"n_members={self.n_members})"
                )
            if self.n_subjects % self.k_block != 0:
                raise ValueError(
                    f"k_block ({self.k_block}) must divide n_subjects "
                    f"({self.n_subjects})"
                )
            if self.max_delay_rounds != 0 or self.link_counters:
                raise ValueError(
                    "k_block supports max_delay_rounds=0 and "
                    "link_counters=False only (capacity path)"
                )
        if self.wire24 and not self.compact_carry:
            raise ValueError(
                "wire24 is the compact-carry headroom rung — it widens "
                "the WIRE key while the STORED table stays int16; with a "
                "wide carry the wide wire already has more headroom (set "
                "compact_carry=True, or drop wire24)"
            )
        if self.wire24 and self.int16_wire:
            raise ValueError(
                "wire24 and int16_wire are distinct rungs of the wire-"
                "format ladder (24-bit vs 16-bit wire keys) — pick one"
            )
        if self.metadata_keys < 0:
            raise ValueError(
                f"metadata_keys must be >= 0 (0 = metadata plane off; "
                f"got {self.metadata_keys})"
            )
        if self.provenance and self.max_delay_rounds > 0:
            raise ValueError(
                "provenance requires max_delay_rounds == 0: the delay "
                "ring folds every channel into shared per-round bins "
                "before delivery, so the winning record's channel is "
                "unrecoverable once it has been through the ring"
            )
        if self.metadata_keys > 0:
            if not self.full_view:
                raise ValueError(
                    "the metadata plane requires full view (n_subjects == "
                    "n_members): column j is node j, which is what makes "
                    "the owner's own row the table authority "
                    f"(got n_subjects={self.n_subjects}, "
                    f"n_members={self.n_members})"
                )
            if self.k_block:
                raise ValueError(
                    "metadata_keys > 0 excludes k_block: the blocked "
                    "capacity path targets table sizes where an "
                    "[N, N, M] metadata lane is itself infeasible "
                    "(models/metadata.py docstring)"
                )
        if self.compact_carry:
            if self.periods_to_spread + 1 > 127:
                raise ValueError(
                    f"compact_carry stores remaining spread rounds as int8; "
                    f"periods_to_spread={self.periods_to_spread} exceeds 126"
                )
            if self.suspicion_rounds >= 32766:
                raise ValueError(
                    f"compact_carry stores remaining suspicion rounds as "
                    f"int16; suspicion_rounds={self.suspicion_rounds} "
                    f"exceeds 32765 (also applies to Knobs overrides)"
                )
            if (self.lhm_max > 0
                    and self.suspicion_rounds * self.lhm_max >= 32766):
                raise ValueError(
                    f"compact_carry stores remaining suspicion rounds as "
                    f"int16 and the Lifeguard plane arms deadlines up to "
                    f"suspicion_rounds * lhm_max = "
                    f"{self.suspicion_rounds * self.lhm_max} rounds out "
                    f"(exceeds 32765)"
                )
            if self.dead_suppress_rounds >= 32766:
                raise ValueError(
                    f"compact_carry stores the dead-suppression expiry in "
                    f"the int16 deadline lane; dead_suppress_rounds="
                    f"{self.dead_suppress_rounds} exceeds 32765"
                )

    @property
    def compact_wire(self) -> bool:
        """True when the wire format is int16 (records.merge_key16):
        chosen directly by ``int16_wire`` or implied by ``compact_carry``
        — unless ``wire24`` widens the wire back to an int32 word.
        Gates every wire-WIDTH decision (ring-slot dtype, traffic-model
        key bytes); format-layout decisions go through ``wire_format``;
        carry-layout decisions gate on ``compact_carry`` alone."""
        return (self.compact_carry or self.int16_wire) and not self.wire24

    @property
    def wire_format(self) -> "delivery.WireFormat":
        """The active rung of the wire-format bitfield ladder
        (ops/delivery.WIRE_FORMATS) — the one object every pack/unpack/
        merge/no-message call site threads, and the single source of
        the saturation and epoch-width constants
        (tests/test_wire_constants.py grep-proofs that no clamp site
        hard-codes them)."""
        if self.wire24:
            return delivery.WIRE24
        return delivery.WIRE16 if self.compact_wire else delivery.WIDE

    @property
    def epoch_bits(self) -> int:
        """Identity-epoch field width of the active wire key: 0 when the
        open-world plane is off OR the epoch guard is disabled (the
        exact legacy key layouts — the naive-reuse arm runs the
        reference's epoch-blind wire, which is the point of the
        control), else the active format's fixed width
        (ops/delivery.WireFormat.epoch_bits: 6 wide / 4 wire24 /
        2 wire16).  Gates every epoch decision — lane allocation,
        pack/unpack, the merge gate — so one predicate compiles the
        whole identity plane in or out."""
        if not (self.open_world and self.epoch_guard):
            return 0
        return self.wire_format.epoch_bits

    @staticmethod
    def from_config(config, n_members: int, n_subjects: Optional[int] = None,
                    loss_probability: float = 0.0, mean_delay_ms: float = 0.0,
                    **overrides) -> "SwimParams":
        sim = config.to_sim(n_members)
        k = n_members if n_subjects is None else n_subjects
        kwargs = dict(
            n_members=n_members,
            n_subjects=k,
            fanout=sim.gossip_fanout,
            periods_to_spread=sim.periods_to_spread,
            ping_every=sim.ping_every,
            sync_every=sim.sync_every,
            suspicion_rounds=sim.suspicion_rounds,
            ping_req_members=sim.ping_req_members,
            ping_timeout_ms=float(config.ping_timeout),
            ping_interval_ms=float(config.ping_interval),
            mean_delay_ms=mean_delay_ms,
            loss_probability=loss_probability,
            ping_known_only=(k == n_members),
            round_ms=float(config.gossip_interval),
        )
        kwargs.update(overrides)
        return SwimParams(**kwargs)

    @property
    def full_view(self) -> bool:
        return self.n_subjects == self.n_members

    @classmethod
    def tuned(cls, profile: str, base: Optional["SwimParams"] = None,
              **overrides) -> "SwimParams":
        """Named tuned-default constructor: the autotuner's shipped
        Pareto picks ("fast-detect", "low-traffic", "churn-hardened" —
        tune/profiles.py) baked into static params.  ``base`` defaults
        to the chaos-campaign timing preset (``n_members=32``; pass
        ``n_members=...`` through ``overrides`` to rescale); explicit
        ``overrides`` win over the profile's.  Every shipped profile
        is fuzz-oracle-validated and Pareto-gated by ``telemetry
        regress`` over artifacts/tune_pareto.json."""
        from scalecube_cluster_tpu.tune import profiles as _profiles
        n_members = overrides.pop("n_members", 32)
        return _profiles.tuned_params(profile, base=base,
                                      n_members=n_members, **overrides)


# --------------------------------------------------------------------------
# Sweepable knobs (dynamic overrides of SwimParams schedule fields)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class Knobs:
    """Traced overrides of the protocol schedule — the sweep axes.

    ``SwimParams`` is a static jit argument (it fixes shapes and unrolled
    channel counts); these knobs are the subset that can vary as *data*,
    which is what lets one compiled program sweep a whole hyperparameter
    grid with ``jax.vmap`` (BASELINE config 5: fanout × ping-interval ×
    suspicion-mult; sweep.py) or rerun a scenario batch across a knob
    grid with ZERO recompiles (tune/search.py — knob values are traced
    operands, so the compiled program is knob-oblivious).

    Static-vs-dynamic, all 33 ``SwimParams`` fields (why each side):

    ==================== === =====================================
    field                dyn one-line reason
    ==================== === =====================================
    n_members            no  array shapes ([N, K] carries)
    n_subjects           no  array shapes (the K axis)
    fanout               YES data mask over the params.fanout
                             pre-built channels (ceiling)
    periods_to_spread    no  int8 remaining-spread lane ceiling,
                             validated at construction
    ping_every           YES probe-round modulus — pure data in
                             the round gate
    sync_every           YES push-SYNC modulus — pure data in the
                             round gate (and the buddy fallback)
    suspicion_rounds     YES timer length — data in the deadline
                             arithmetic (the weakened coverage arm
                             sweeps it far ABOVE the params value)
    ping_req_members     no  unrolled proxy-chain count (program
                             structure)
    ping_timeout_ms      YES direct-ping sub-round budget — data
                             in the closed-form chain compares;
                             ceiling params.ping_interval_ms (the
                             indirect budget is the complement)
    ping_interval_ms     no  the round's total FD budget — it IS
                             the ping_timeout_ms ceiling
    mean_delay_ms        no  paired with the max_delay_rounds ring
                             sizing/quantization thresholds
    loss_probability     YES per-message drop chance — pure data
                             in the drop draws
    ping_known_only      no  FD-targeting branch structure
    per_subject_metrics  no  metrics output shapes
    delivery             no  tick-body dispatch
    round_ms             no  delay→round quantization constant
    max_delay_rounds     no  inbox-ring buffer shape
    compact_carry        no  carry dtype/layout
    int16_wire           no  wire dtype/layout
    wire24               no  wire dtype/layout
    fused_wire           no  wire buffer structure
    shift_roll_payloads  no  delivery graph structure
    link_counters        no  metrics output shapes
    k_block              no  loop structure / block shapes
    n_user_gossips       no  gossip lane shape
    rounds_per_step      no  scan unroll factor
    sync_interval        no  0-vs-on compiles the anti-entropy
                             plane in/out; keeping the cadence
                             static keeps that off-switch
                             bit-identity contract compile-time
    lhm_max              YES dynamic CLAMP CAP of the LHM lane
                             (lifeguard.update's clip) — the
                             static field stays the lane-shape
                             gate ([N] vs [0]) and the
                             TIMER_BOUND / int16 ceiling
    dead_suppress_rounds YES tombstone reopen-window length —
                             data in the expiry arithmetic; the
                             static >0 gate (suppression in/out)
                             and the int16 deadline-lane ceiling
                             stay compile-time
    open_world           no  identity-epoch lane/wire layout
    epoch_guard          no  wire-key layout (epoch field width)
    metadata_keys        no  md lane shape ([N, K, M]) and the
                             0-vs-on plane off-switch (the
                             sync_interval bit-identity rationale)
    provenance           no  off-vs-on plane off-switch: the
                             per-channel exposure compiles in/out
                             (sync_interval bit-identity rationale)
    ==================== === =====================================

    Each dynamic knob with a static ceiling is masked/clamped at its
    use site against the params value (the ``fanout <= params.fanout``
    pattern: ``knob_dead_suppress`` / ``knob_lhm_cap`` /
    ``knob_ping_timeout`` below), so an out-of-range traced value can
    never overflow a lane the params validated; :meth:`for_params`
    additionally REJECTS concrete out-of-range overrides at
    construction (tests/test_tune.py pins the raises).

    The three newer knobs default to ``None`` = "use the params value"
    — pre-existing five-field constructions (sweep.knob_grid,
    experiments/northstar.py) behave exactly as before.
    """

    loss_probability: jnp.ndarray
    suspicion_rounds: jnp.ndarray
    ping_every: jnp.ndarray
    sync_every: jnp.ndarray
    fanout: jnp.ndarray
    dead_suppress_rounds: Optional[jnp.ndarray] = None
    lhm_max: Optional[jnp.ndarray] = None
    ping_timeout_ms: Optional[jnp.ndarray] = None

    @staticmethod
    def from_params(params: "SwimParams") -> "Knobs":
        return Knobs(
            loss_probability=jnp.float32(params.loss_probability),
            suspicion_rounds=jnp.int32(params.suspicion_rounds),
            ping_every=jnp.int32(params.ping_every),
            sync_every=jnp.int32(params.sync_every),
            fanout=jnp.int32(params.fanout),
            dead_suppress_rounds=jnp.int32(params.dead_suppress_rounds),
            lhm_max=jnp.int32(params.lhm_max),
            ping_timeout_ms=jnp.float32(params.ping_timeout_ms),
        )

    @staticmethod
    def for_params(params: "SwimParams", **overrides) -> "Knobs":
        """:meth:`from_params` plus validated overrides — the checked
        construction path the autotuner's grid goes through.

        Concrete (non-traced) override values are range-checked against
        their static ceilings and raise ``ValueError`` when invalid;
        traced values skip the host-side check (the use-site clamps
        still bound them).  Unknown knob names always raise.
        """
        field_names = {f.name for f in dataclasses.fields(Knobs)}
        unknown = sorted(set(overrides) - field_names)
        if unknown:
            raise ValueError(f"unknown Knobs field(s) {unknown}; "
                             f"sweepable knobs are {sorted(field_names)}")
        # (low, high, why) ceilings for the knobs that have one; None
        # bounds are unchecked.  suspicion_rounds deliberately has NO
        # ceiling — the weakened coverage arm sweeps it above params.
        ceilings = {
            "fanout": (0, params.fanout,
                       "the static channel count params.fanout"),
            "ping_every": (0, None, "probe cadence must be >= 0"),
            "sync_every": (0, None, "SYNC cadence must be >= 0"),
            "loss_probability": (0.0, 1.0, "a probability"),
            "dead_suppress_rounds": (
                0, params.dead_suppress_rounds,
                "the params window (the int16 deadline-lane ceiling "
                "was validated against the params value; size the "
                "params field as the grid maximum and sweep below)"),
            "lhm_max": (
                1, params.lhm_max,
                "the static LHM cap (lane shape + TIMER_BOUND ceiling)"),
            "ping_timeout_ms": (
                0.0, params.ping_interval_ms,
                "params.ping_interval_ms (the indirect probe budget "
                "is the complement and must stay >= 0)"),
        }
        for name, val in overrides.items():
            if isinstance(val, jax.core.Tracer) or name not in ceilings:
                continue
            lo, hi = ceilings[name][0], ceilings[name][1]
            why = ceilings[name][2]
            v = float(jnp.asarray(val))
            if (lo is not None and v < lo) or (hi is not None and v > hi):
                raise ValueError(
                    f"Knobs.{name}={v:g} outside [{lo}, {hi}] — "
                    f"ceiling: {why}")
        base = Knobs.from_params(params)
        # Normalize concrete overrides to the from_params dtypes so a
        # knob-grid sweep never splits the jit cache on weak types —
        # every config must rerun the SAME compiled program.
        coerced = {
            name: (val if isinstance(val, jax.core.Tracer)
                   else jnp.asarray(val, getattr(base, name).dtype))
            for name, val in overrides.items()
        }
        return dataclasses.replace(base, **coerced)


jax.tree_util.register_dataclass(
    Knobs,
    data_fields=["loss_probability", "suspicion_rounds", "ping_every",
                 "sync_every", "fanout", "dead_suppress_rounds",
                 "lhm_max", "ping_timeout_ms"],
    meta_fields=[],
)


def knob_dead_suppress(kn: "Knobs", params: "SwimParams"):
    """Effective dead-suppression window: the dynamic knob masked by
    its static ceiling (the ``fanout <= params.fanout`` pattern — the
    int16 deadline lane was validated against the PARAMS value, so the
    knob sweeps at-or-below it).  ``None`` (a pre-knob Knobs
    construction) falls back to the params value, bit-identically."""
    if kn.dead_suppress_rounds is None:
        return params.dead_suppress_rounds
    return jnp.minimum(jnp.asarray(kn.dead_suppress_rounds, jnp.int32),
                       params.dead_suppress_rounds)


def knob_lhm_cap(kn: "Knobs", params: "SwimParams"):
    """Effective LHM clamp cap: the dynamic knob clipped into
    [1, params.lhm_max] — the static field keeps the lane shape and
    the TIMER_BOUND/int16 ceilings; the knob only lowers the cap.
    Consulted exclusively under the static ``params.lhm_max > 0``
    plane gate."""
    if kn.lhm_max is None:
        return params.lhm_max
    return jnp.clip(jnp.asarray(kn.lhm_max, jnp.int32), 1, params.lhm_max)


def knob_ping_timeout(kn: "Knobs", params: "SwimParams"):
    """Effective direct-ping budget (ms): the dynamic knob clipped into
    [0, params.ping_interval_ms] so the complementary indirect budget
    (interval - timeout) can never go negative."""
    if kn.ping_timeout_ms is None:
        return params.ping_timeout_ms
    return jnp.clip(jnp.asarray(kn.ping_timeout_ms, jnp.float32),
                    jnp.float32(0.0), jnp.float32(params.ping_interval_ms))


# --------------------------------------------------------------------------
# Link faults: the per-link NetworkEmulator rules
# --------------------------------------------------------------------------


@dataclasses.dataclass
class LinkFaults:
    """Ordered per-link override rules — vectorized NetworkEmulator state.

    Rule r matches messages with sender id in [src_lo[r], src_hi[r]),
    receiver id in [dst_lo[r], dst_hi[r]), during rounds
    [from_round[r], until_round[r]); the *last* matching rule wins,
    mirroring the reference's setLink-overwrites-the-map semantics
    (transport/NetworkEmulator.java:99-130).  ``loss == 1.0`` is a blocked
    link (NetworkEmulator.block, :132-192); ``delay_ms`` is the mean of the
    exponential per-hop delay (NetworkLinkSettings.java:64-74).

    All arrays are [R]; R is static (part of the traced shapes), so rule
    evaluation unrolls to R elementwise select passes — no [N, N] tensors,
    which is what lets the same fault model run at N=1M.
    """

    src_lo: jnp.ndarray
    src_hi: jnp.ndarray
    dst_lo: jnp.ndarray
    dst_hi: jnp.ndarray
    from_round: jnp.ndarray
    until_round: jnp.ndarray
    loss: jnp.ndarray
    delay_ms: jnp.ndarray

    @staticmethod
    def none() -> "LinkFaults":
        z = jnp.zeros((0,), dtype=jnp.int32)
        f = jnp.zeros((0,), dtype=jnp.float32)
        return LinkFaults(z, z, z, z, z, z, f, f)

    @property
    def n_rules(self) -> int:
        return self.src_lo.shape[0]

    def add(self, src, dst, loss: float, delay_ms: float = 0.0,
            from_round: int = 0, until_round: int = INT32_MAX) -> "LinkFaults":
        """Append one rule.  ``src``/``dst`` are a node id or an (lo, hi)
        half-open id range.

        Host-side schedule builder: arguments are validated eagerly —
        a loss outside [0, 1], an empty id range (``lo >= hi``) or an
        inverted round window (``from_round >= until_round``) raises
        instead of appending a rule that silently matches nothing (or,
        for a bad loss, everything the sampler compares against).
        """
        def rng(x):
            if isinstance(x, (tuple, list)):
                return int(x[0]), int(x[1])
            return int(x), int(x) + 1
        s_lo, s_hi = rng(src)
        d_lo, d_hi = rng(dst)
        if not 0.0 <= float(loss) <= 1.0:
            raise ValueError(
                f"loss must be a probability in [0, 1] (got {loss!r})")
        if float(delay_ms) < 0.0:
            raise ValueError(
                f"delay_ms must be non-negative (got {delay_ms!r})")
        if s_lo >= s_hi or d_lo >= d_hi:
            raise ValueError(
                f"empty id range: src=[{s_lo}, {s_hi}), dst=[{d_lo}, "
                f"{d_hi}) — a half-open range needs lo < hi, and a rule "
                f"over an empty range would silently match nothing")
        if int(from_round) >= int(until_round):
            raise ValueError(
                f"inverted round window [{from_round}, {until_round}) — "
                f"the rule would silently never apply")

        def cat(a, v, dtype):
            return jnp.concatenate([a, jnp.asarray([v], dtype=dtype)])

        return LinkFaults(
            src_lo=cat(self.src_lo, s_lo, jnp.int32),
            src_hi=cat(self.src_hi, s_hi, jnp.int32),
            dst_lo=cat(self.dst_lo, d_lo, jnp.int32),
            dst_hi=cat(self.dst_hi, d_hi, jnp.int32),
            from_round=cat(self.from_round, from_round, jnp.int32),
            until_round=cat(self.until_round, until_round, jnp.int32),
            loss=cat(self.loss, loss, jnp.float32),
            delay_ms=cat(self.delay_ms, delay_ms, jnp.float32),
        )


jax.tree_util.register_dataclass(
    LinkFaults,
    data_fields=["src_lo", "src_hi", "dst_lo", "dst_hi", "from_round",
                 "until_round", "loss", "delay_ms"],
    meta_fields=[],
)


def link_eval(faults: LinkFaults, round_idx, src_ids, dst_ids,
              default_loss, default_delay_ms) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(loss probability, mean delay ms) per (src, dst) message this round.

    ``src_ids``/``dst_ids`` broadcast against each other; the result has the
    broadcast shape.  Vectorizes NetworkEmulator.resolveLinkSettings +
    NetworkLinkSettings.evaluate{Loss,Delay}
    (transport/NetworkEmulator.java:60-97, NetworkLinkSettings.java:54-74).

    When every delay is STATICALLY zero (no fault rules and a zero default,
    both compile-time facts), the delay result is ``None``: downstream
    consumers (_chain_ok, _route_delayed) then skip the exponential
    delay sampling entirely.  XLA cannot fold ``-log1p(-u) * 0`` to zero
    itself (0·x is unsafe for non-finite x), and at 1M members the dead
    sampling is tens of millions of transcendentals per FD round.
    """
    src_ids = jnp.asarray(src_ids, jnp.int32)
    dst_ids = jnp.asarray(dst_ids, jnp.int32)
    shape = jnp.broadcast_shapes(src_ids.shape, dst_ids.shape)
    loss = jnp.full(shape, default_loss, dtype=jnp.float32)
    static_zero_delay = (
        faults.n_rules == 0
        and isinstance(default_delay_ms, (int, float))
        and float(default_delay_ms) == 0.0
    )
    delay = (None if static_zero_delay
             else jnp.full(shape, default_delay_ms, dtype=jnp.float32))
    for r in range(faults.n_rules):  # static unroll; last match wins
        match = (
            (src_ids >= faults.src_lo[r]) & (src_ids < faults.src_hi[r])
            & (dst_ids >= faults.dst_lo[r]) & (dst_ids < faults.dst_hi[r])
            & (round_idx >= faults.from_round[r])
            & (round_idx < faults.until_round[r])
        )
        loss = jnp.where(match, faults.loss[r], loss)
        delay = jnp.where(match, faults.delay_ms[r], delay)
    return loss, delay


# --------------------------------------------------------------------------
# World model: ground truth + fault injection (the NetworkEmulator analog)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class SwimWorld:
    """Ground-truth node liveness + network fault schedule (dynamic arrays).

    The vectorization of the reference's NetworkEmulator
    (transport/NetworkEmulator.java:21-273) plus process-level faults the
    reference injects by stopping transports (MembershipProtocolTest
    partition/restart scenarios, SURVEY.md §4):

      - ``down_from``/``down_until`` [N] int32: node i is crashed during
        rounds [down_from, down_until) — it neither sends, receives, nor
        updates state (frozen, like a stopped JVM); on revival it resumes
        with its old identity and refutes its own death via gossip.
      - ``leave_at`` [N] int32: node i *gracefully leaves* at that round —
        it gossips its own DEAD record at incarnation+1 in its final round
        and is down afterwards (MembershipProtocolImpl.leaveCluster,
        :197-206); INT32_MAX = never.
      - ``partition_of`` [P, N] int8: rolling-partition schedule; at round
        r, phase (r // partition_phase_rounds) % P is active, and messages
        cross partition boundaries only if ids match.  A single all-zeros
        phase means no partition (the default).
      - ``faults``: per-link loss/delay/block rules (:class:`LinkFaults`).
      - ``seed_ids`` [S] int32: configured seed members.  When non-empty,
        full-view senders only contact members they *know* (their table
        entry is live) or seeds — the reference's join/contact rule
        (MembershipProtocolImpl doSync picks from seeds ∪ live members,
        :298-314).  When empty (the default), every member is implicitly a
        seed, matching tests that pre-populate full views.
      - ``subject_ids`` [K] int32 / ``slot_of_node`` [N] int32: the focal
        subject mapping (slot -1 = node is not a tracked subject).
      - ``gossip_origin``/``gossip_spread_at`` [G] int32: the spread()
        schedule for user gossips (SwimParams.n_user_gossips): gossip g
        is injected at its origin node in round gossip_spread_at[g]
        (INT32_MAX = never) — the batched analog of
        Cluster.spreadGossip(msg) (GossipProtocolImpl.java:124-128).
      - ``join_at`` [N] int32: slot i admits a NEW member (a fresh
        identity at epoch 1, incarnation 0, cold table) at that round —
        the open-world JOIN schedule (``SwimParams.open_world``;
        INT32_MAX = never).  The slot must be scheduled dead strictly
        before the join (``with_join`` validates); one join per slot
        per run, so ``epoch_at`` is a single threshold per slot.
      - ``md_push_at``/``md_push_node``/``md_push_key``/``md_push_value``
        [P] int32: the metadata-plane config-push schedule
        (``SwimParams.metadata_keys``; ``with_metadata_push`` appends):
        at round ``md_push_at[p]`` node ``md_push_node[p]`` writes
        ``md_push_value[p]`` into its own metadata cell
        ``md_push_key[p]`` at the next version — the batched analog of
        ``Cluster.updateMetadata`` (MetadataStoreImpl).  Empty (the
        default) means no pushes; ignored when the plane is off.
    """

    down_from: jnp.ndarray
    down_until: jnp.ndarray
    leave_at: jnp.ndarray
    partition_of: jnp.ndarray
    partition_phase_rounds: jnp.ndarray  # int32 scalar
    faults: LinkFaults
    seed_ids: jnp.ndarray
    subject_ids: jnp.ndarray
    slot_of_node: jnp.ndarray
    gossip_origin: jnp.ndarray
    gossip_spread_at: jnp.ndarray
    join_at: jnp.ndarray = None
    md_push_at: jnp.ndarray = None
    md_push_node: jnp.ndarray = None
    md_push_key: jnp.ndarray = None
    md_push_value: jnp.ndarray = None

    @staticmethod
    def healthy(params: SwimParams,
                subject_ids: Optional[jnp.ndarray] = None) -> "SwimWorld":
        n, k = params.n_members, params.n_subjects
        g = params.n_user_gossips
        if subject_ids is None:
            subject_ids = jnp.arange(k, dtype=jnp.int32)
        slot_of_node = (
            jnp.full((n,), -1, dtype=jnp.int32)
            .at[subject_ids]
            .set(jnp.arange(k, dtype=jnp.int32))
        )
        return SwimWorld(
            down_from=jnp.full((n,), INT32_MAX, dtype=jnp.int32),
            down_until=jnp.full((n,), INT32_MAX, dtype=jnp.int32),
            leave_at=jnp.full((n,), INT32_MAX, dtype=jnp.int32),
            partition_of=jnp.zeros((1, n), dtype=jnp.int8),
            partition_phase_rounds=jnp.int32(1),
            faults=LinkFaults.none(),
            seed_ids=jnp.zeros((0,), dtype=jnp.int32),
            subject_ids=subject_ids,
            slot_of_node=slot_of_node,
            gossip_origin=jnp.arange(g, dtype=jnp.int32) % max(n, 1),
            gossip_spread_at=jnp.full((g,), INT32_MAX, dtype=jnp.int32),
            join_at=jnp.full((n,), INT32_MAX, dtype=jnp.int32),
            md_push_at=jnp.zeros((0,), dtype=jnp.int32),
            md_push_node=jnp.zeros((0,), dtype=jnp.int32),
            md_push_key=jnp.zeros((0,), dtype=jnp.int32),
            md_push_value=jnp.zeros((0,), dtype=jnp.int32),
        )

    def with_spread(self, gossip_idx: int, origin, at_round: int) -> "SwimWorld":
        """Schedule ``spread()`` of user gossip ``gossip_idx`` at ``origin``
        in round ``at_round`` (Cluster.spreadGossip ->
        GossipProtocolImpl.spread, :124-128).  The origin must be alive in
        that round for the injection to happen (a crashed JVM can't call
        spread)."""
        if not 0 <= gossip_idx < self.gossip_origin.shape[0]:
            raise ValueError(
                f"gossip_idx {gossip_idx} out of range for n_user_gossips="
                f"{self.gossip_origin.shape[0]} (jnp would silently drop the"
                f" out-of-bounds update)")
        return dataclasses.replace(
            self,
            gossip_origin=self.gossip_origin.at[gossip_idx].set(
                jnp.int32(origin)),
            gossip_spread_at=self.gossip_spread_at.at[gossip_idx].set(
                jnp.int32(at_round)),
        )

    def _checked_node_ids(self, node, method: str) -> jnp.ndarray:
        """[ids] int32, validated in range [0, N) when concrete.

        ``jnp .at[].set`` silently DROPS out-of-bounds updates, so a
        typo'd node id would produce a healthy world and a vacuously
        green scenario — the same guard ``with_spread`` already has for
        gossip indices.  Traced ids (inside jit) can't be inspected and
        pass through unchecked.
        """
        import numpy as np

        n = self.down_from.shape[0]
        ids = jnp.atleast_1d(jnp.asarray(node, dtype=jnp.int32))
        try:
            concrete = np.asarray(ids)
        except Exception:  # noqa: BLE001 — tracer: defer to runtime semantics
            return ids
        if concrete.size and (concrete.min() < 0 or concrete.max() >= n):
            bad = concrete[(concrete < 0) | (concrete >= n)]
            raise ValueError(
                f"{method}: node id(s) {bad.tolist()} out of range for "
                f"n_members={n} (jnp would silently drop the "
                f"out-of-bounds update)")
        return ids

    def with_crash(self, node, at_round: int, until_round: int = INT32_MAX):
        """Crash ``node`` (scalar or array) during [at_round, until_round).

        ``until_round <= at_round`` is an EMPTY down window: the node is
        never down (``alive_at`` tests ``down_from <= r < down_until``)
        — the revive-before-crash composition edge, pinned by
        tests/test_swim_world_validation.py."""
        node = self._checked_node_ids(node, "with_crash")
        return dataclasses.replace(
            self,
            down_from=self.down_from.at[node].set(at_round),
            down_until=self.down_until.at[node].set(until_round),
        )

    def with_leave(self, node, at_round: int):
        """Graceful leave: gossip own DEAD@inc+1 at ``at_round``, then down
        (MembershipProtocolImpl.leaveCluster, :197-206).

        Overwrites any prior crash window for the same node (one down
        schedule per node — the leave clobbers the crash; composition
        edge pinned by tests/test_swim_world_validation.py)."""
        node = self._checked_node_ids(node, "with_leave")
        return dataclasses.replace(
            self,
            leave_at=self.leave_at.at[node].set(at_round),
            down_from=self.down_from.at[node].set(at_round + 1),
            down_until=self.down_until.at[node].set(INT32_MAX),
        )

    def with_join(self, slot, at_round: int):
        """Admit a NEW member (fresh identity: epoch 1, incarnation 0,
        cold table) into the recycled DEAD ``slot`` at ``at_round`` —
        the open-world arrival schedule (``SwimParams.open_world``
        executes it; a plane-off run treats the slot as an ordinary
        revival of the OLD identity, which is exactly the naive-reuse
        hazard, so schedule joins only on open-world runs).

        Validation mirrors the ``with_crash``/``with_leave`` guards
        (concrete ids only; traced values defer to runtime semantics):

          - slot ids are range-checked like every other schedule;
          - the slot must be scheduled DEAD strictly before the join:
            joining a live slot would overwrite a living member's
            identity, and a join at-or-before the scheduled death
            (``at_round <= down_from`` / ``<= leave_at``) would admit
            the new identity while the old one still runs — both raise
            (tests/test_swim_world_validation.py pins the edges);
          - the slot must still be down AT the join round: a crash
            window that revives the old identity before ``at_round``
            (``down_until <= at_round``) composes crash→revive→join,
            i.e. two identities alive in sequence with no death between
            the revival and the join — raise rather than guess.

        One join per slot per run (a second ``with_join`` on the same
        slot overwrites the first, like every other schedule write);
        the slot's ground-truth epoch is therefore the single threshold
        ``epoch_at`` evaluates.  Sets ``down_until = at_round`` — from
        the join round on, the slot's occupant is the new identity.
        """
        import numpy as np

        slot_ids = self._checked_node_ids(slot, "with_join")
        at_round = int(at_round)
        try:
            concrete = np.asarray(slot_ids)
            df = np.asarray(self.down_from)[concrete]
            du = np.asarray(self.down_until)[concrete]
            la = np.asarray(self.leave_at)[concrete]
        except Exception:  # noqa: BLE001 — tracer: defer to runtime
            pass
        else:
            fault = np.minimum(df, la)
            live = fault >= INT32_MAX
            if live.any():
                raise ValueError(
                    f"with_join: slot(s) "
                    f"{concrete[live].tolist()} have no scheduled "
                    f"death before round {at_round} — joining a LIVE "
                    f"slot would overwrite a living member's identity; "
                    f"schedule with_crash/with_leave first")
            early = fault >= at_round
            if (~live & early).any():
                bad = concrete[~live & early]
                raise ValueError(
                    f"with_join: join at round {at_round} is not "
                    f"strictly after slot(s) {bad.tolist()}'s scheduled "
                    f"death (down_from/leave_at "
                    f"{np.minimum(df, la)[~live & early].tolist()}) — "
                    f"the old identity must die before the new one "
                    f"joins")
            revived = du <= at_round
            if revived.any():
                raise ValueError(
                    f"with_join: slot(s) {concrete[revived].tolist()} "
                    f"revive the OLD identity at "
                    f"{du[revived].tolist()} before the join at "
                    f"{at_round} — a revived member cannot be joined "
                    f"over; crash it permanently (or until the join "
                    f"round) first")
        return dataclasses.replace(
            self,
            down_until=self.down_until.at[slot_ids].set(at_round),
            join_at=self.join_at.at[slot_ids].set(at_round),
        )

    def epoch_at(self, round_idx):
        """[N] int32 ground-truth identity epoch per slot at a round:
        0 = the original occupant, 1 = the joined identity (one join
        per slot per run — ``with_join``)."""
        return (self.join_at <= round_idx).astype(jnp.int32)

    def joining_at(self, round_idx):
        """[N] bool: slots whose JOIN fires exactly this round."""
        return self.join_at == round_idx

    def with_metadata_push(self, node, key: int, value: int,
                           at_round: int) -> "SwimWorld":
        """Schedule a config push: ``node`` writes ``value`` into its own
        metadata cell ``key`` at ``at_round`` (Cluster.updateMetadata;
        ``SwimParams.metadata_keys`` must cover ``key`` for the push to
        take effect — models/metadata.inject_pushes).  APPENDS to the
        schedule (multiple pushes compose; the schedule length is a
        static program shape, so vary it sparingly).  Ids are
        range-checked like every other schedule; ``value`` must fit the
        10-bit payload field and ``key``/``at_round`` be non-negative
        (the packed-word layout, models/metadata.py docstring)."""
        node_ids = self._checked_node_ids(node, "with_metadata_push")
        if node_ids.shape[0] != 1:
            raise ValueError(
                "with_metadata_push schedules ONE push per call (a push "
                "is one owner-local write; compose calls for fleets)")
        key, value, at_round = int(key), int(value), int(at_round)
        if key < 0:
            raise ValueError(f"with_metadata_push: key {key} must be >= 0")
        if not 0 <= value <= metadata.MD_VALUE_MAX:
            raise ValueError(
                f"with_metadata_push: value {value} outside the "
                f"{metadata.MD_VALUE_BITS}-bit payload field "
                f"[0, {metadata.MD_VALUE_MAX}]")
        if at_round < 0:
            raise ValueError(
                f"with_metadata_push: at_round {at_round} must be >= 0")

        def app(arr, v):
            base = (jnp.zeros((0,), dtype=jnp.int32) if arr is None else arr)
            return jnp.concatenate(
                [base, jnp.asarray([v], dtype=jnp.int32)])

        return dataclasses.replace(
            self,
            md_push_at=app(self.md_push_at, at_round),
            md_push_node=jnp.concatenate([
                (jnp.zeros((0,), dtype=jnp.int32)
                 if self.md_push_node is None else self.md_push_node),
                node_ids,
            ]),
            md_push_key=app(self.md_push_key, key),
            md_push_value=app(self.md_push_value, value),
        )

    def with_partition_schedule(self, partition_of, phase_rounds: int):
        partition_of = jnp.asarray(partition_of, dtype=jnp.int8)
        if partition_of.ndim == 1:
            partition_of = partition_of[None, :]
        return dataclasses.replace(
            self,
            partition_of=partition_of,
            partition_phase_rounds=jnp.int32(phase_rounds),
        )

    def with_link_fault(self, src, dst, loss: float, delay_ms: float = 0.0,
                        from_round: int = 0,
                        until_round: int = INT32_MAX) -> "SwimWorld":
        """Per-link loss/delay override (NetworkEmulator.setLink analog).

        ``src``/``dst``: node id or (lo, hi) half-open range.  Applies to
        messages src → dst only (asymmetric, like the reference's
        per-destination settings)."""
        return dataclasses.replace(
            self, faults=self.faults.add(src, dst, loss, delay_ms,
                                         from_round, until_round)
        )

    def with_block(self, src, dst, from_round: int = 0,
                   until_round: int = INT32_MAX) -> "SwimWorld":
        """Block the src → dst link (100% loss — NetworkEmulator.block,
        transport/NetworkEmulator.java:132-192).  Unblock = until_round."""
        return self.with_link_fault(src, dst, loss=1.0,
                                    from_round=from_round,
                                    until_round=until_round)

    def with_seeds(self, seed_ids) -> "SwimWorld":
        """Configure seed members (enables the known-or-seed contact gate
        in full-view mode — see class docstring).  Ids are range-checked
        like the crash/leave schedules: an out-of-range seed id would
        otherwise gate every contact on a member that doesn't exist."""
        return dataclasses.replace(
            self, seed_ids=self._checked_node_ids(seed_ids, "with_seeds")
        )

    def alive_at(self, round_idx):
        """[N] bool ground-truth liveness at a round."""
        return ~((self.down_from <= round_idx) & (round_idx < self.down_until))

    def partition_at(self, round_idx):
        """[N] partition id at a round (rolling schedule)."""
        phase = (round_idx // self.partition_phase_rounds) % self.partition_of.shape[0]
        return jax.lax.dynamic_index_in_dim(
            self.partition_of, phase, axis=0, keepdims=False
        )


jax.tree_util.register_dataclass(
    SwimWorld,
    data_fields=[
        "down_from", "down_until", "leave_at", "partition_of",
        "partition_phase_rounds", "faults", "seed_ids",
        "subject_ids", "slot_of_node", "gossip_origin", "gossip_spread_at",
        "join_at", "md_push_at", "md_push_node", "md_push_key",
        "md_push_value",
    ],
    meta_fields=[],
)


# --------------------------------------------------------------------------
# Scan carry
# --------------------------------------------------------------------------


@dataclasses.dataclass
class SwimState:
    """Scan carry: the distributed membership state, one row per observer.

    ``status``/``inc`` [N, K]: observer's record of each subject — the dense
    form of ``Map<id, MembershipRecord>`` (MembershipProtocolImpl.java:82).
    A stored DEAD is the deleted-record tombstone that keeps spreading its
    death notice (ops/delivery.merge_inbox docstring).

    ``spread_until``    [N, K] int32: gossip retransmission window for the
                        current record (GossipState.infectionPeriod analog).
                        (A remaining-rounds int8 form was tried and measured
                        SLOWER at 1M — narrow-int carry lanes cost more in
                        the merge fusion than the saved bandwidth.)
    ``suspect_deadline`` [N, K] int32: round at which a SUSPECT entry is
                        declared DEAD (suspicionTimeoutTasks analog,
                        MembershipProtocolImpl.java:96,597-606); INT32_MAX
                        when no timer is pending.
    ``self_inc``        [N] int32: own incarnation (bumped by refutation,
                        MembershipProtocolImpl.java:488-509).
    ``inbox_ring``/``flag_ring`` [D, N, K]: delayed-delivery buffers for
                        gossip/SYNC messages quantized to future rounds
                        (params.max_delay_rounds; D = max_delay_rounds + 1,
                        or 0 when delay modeling is off — zero-size arrays
                        cost nothing).  Slot (round % D) holds the messages
                        due in that round.
    ``g_infected``      [N, G] bool: user-gossip possession bits
                        (params.n_user_gossips; the delivery-dedup bit,
                        GossipProtocolImpl.java:176-180).
    ``g_spread_until``  [N, G] int32: per-(member, gossip) retransmission
                        window (GossipState.infectionPeriod analog).  Kept
                        int32 absolute in BOTH carry layouts — [N, G] is
                        small next to [N, K], so compact_carry doesn't
                        narrow it.
    ``g_ring``          [D, N, G] bool: delayed user-gossip bits, sharing
                        the membership payload's delay bins (one wire
                        message carries both).
    ``lhm``             [N] int32: Lifeguard Local Health Multiplier,
                        clamped to [1, params.lhm_max]
                        (models/lifeguard.py); zero-size when
                        ``lhm_max == 0`` (the plane compiled out).
                        Always int32 absolute — [N] is small next to
                        [N, K], so compact_carry doesn't narrow it.
    ``epoch``           [N, K]: the IDENTITY EPOCH of the record each
                        cell holds (params.open_world — the slot-
                        recycling lane; 0 = the original occupant).
                        int16 under compact_carry (the lhm-lane dtype
                        pattern), int32 otherwise; zero-size
                        ([N, 0] int32) when the plane is compiled out.
    ``md``              [N, K, M] int32: metadata KV lane — observer's
                        packed (epoch, version, value) word per subject
                        cell (params.metadata_keys; models/metadata.py).
                        Always int32 absolute in BOTH carry layouts (the
                        packed word IS the stored form); zero-size
                        ([N, 0, 0]) when the plane is compiled out.
    ``md_spread``       [N, K] int32: per-(observer, subject) metadata
                        gossip window (the ``spread_until`` rule applied
                        to metadata rows); int32 absolute in both
                        layouts, zero-size ([N, 0]) when off.
    """

    status: jnp.ndarray
    inc: jnp.ndarray
    spread_until: jnp.ndarray
    suspect_deadline: jnp.ndarray
    self_inc: jnp.ndarray
    inbox_ring: jnp.ndarray
    flag_ring: jnp.ndarray
    g_infected: jnp.ndarray
    g_spread_until: jnp.ndarray
    g_ring: jnp.ndarray
    lhm: jnp.ndarray
    epoch: jnp.ndarray
    md: jnp.ndarray
    md_spread: jnp.ndarray


jax.tree_util.register_dataclass(
    SwimState,
    data_fields=["status", "inc", "spread_until", "suspect_deadline",
                 "self_inc", "inbox_ring", "flag_ring",
                 "g_infected", "g_spread_until", "g_ring", "lhm", "epoch",
                 "md", "md_spread"],
    meta_fields=[],
)


def initial_epoch(params: SwimParams) -> jnp.ndarray:
    """The identity-epoch carry lane: all-zero (original occupants) when
    the open-world plane is on, a zero-size [N, 0] int32 array when off
    (the lifeguard.initial_lhm pattern — costs nothing, keeps the
    pytree structure uniform)."""
    n = params.n_members
    if params.epoch_bits == 0:
        return jnp.zeros((n, 0), dtype=jnp.int32)
    dtype = jnp.int16 if params.compact_carry else jnp.int32
    return jnp.zeros((n, params.n_subjects), dtype=dtype)


def initial_state(params: SwimParams, world: SwimWorld,
                  warm: bool = True) -> SwimState:
    """Initial membership tables.

    ``warm=True``: everyone knows every subject ALIVE at incarnation 0 (the
    post-join steady state).  ``warm=False``: cold start — rows are ABSENT
    except each node's own record and the configured seeds
    (``world.seed_ids``), which every node knows a priori
    (MembershipProtocolImpl.start0 syncs to seeds, :216-251); the cluster
    then grows by gossip/SYNC through the ABSENT→ALIVE gate.
    """
    n, k = params.n_members, params.n_subjects
    fill = records.ALIVE if warm else records.ABSENT
    status = jnp.full((n, k), fill, dtype=jnp.int8)
    is_self = world.subject_ids[None, :] == jnp.arange(n, dtype=jnp.int32)[:, None]
    if not warm and world.seed_ids.shape[0] > 0:
        seed_slot = world.slot_of_node[world.seed_ids]      # [S] (-1 untracked)
        is_seed_col = jnp.any(
            (jnp.arange(k, dtype=jnp.int32)[None, :] == seed_slot[:, None])
            & (seed_slot >= 0)[:, None],
            axis=0,
        )
        status = jnp.where(is_seed_col[None, :], records.ALIVE, status)
    status = jnp.where(is_self, records.ALIVE, status)
    spread0 = jnp.zeros((n, k), dtype=jnp.int32)
    if not warm:
        # A joining node's own record is hot: it announces itself for a
        # full spread window, the ADDED-dissemination path
        # (MembershipProtocolTest seed-chain join, :432-462).
        spread0 = jnp.where(is_self, params.periods_to_spread + 1, spread0)
    d_slots = params.max_delay_rounds + 1 if params.max_delay_rounds > 0 else 0
    g = params.n_user_gossips
    gd_slots = d_slots if g > 0 else 0
    g_fields = dict(
        g_infected=jnp.zeros((n, g), dtype=jnp.bool_),
        g_spread_until=jnp.zeros((n, g), dtype=jnp.int32),
        g_ring=jnp.zeros((gd_slots, n, g), dtype=jnp.bool_),
        lhm=lifeguard.initial_lhm(params),
        epoch=initial_epoch(params),
        **metadata.initial_lanes(params, n),
    )
    # The ring stores wire-format keys; the int16 wire (compact_carry or
    # int16_wire) makes its delayed slots int16 (records.merge_key16).
    ring_dtype = params.wire_format.dtype
    if params.compact_carry:
        # Relative encodings (the carry is re-relativized every tick by
        # _carry_encode): spread_until / suspect_deadline as remaining
        # rounds from round 0.
        return SwimState(
            status=status,
            inc=jnp.zeros((n, k), dtype=jnp.int16),
            spread_until=spread0.astype(jnp.int8),
            suspect_deadline=jnp.full((n, k), _DEADLINE_NONE16,
                                      dtype=jnp.int16),
            self_inc=jnp.zeros((n,), dtype=jnp.int32),
            inbox_ring=jnp.full((d_slots, n, k), -1, dtype=ring_dtype),
            flag_ring=jnp.zeros((d_slots, n, k), dtype=jnp.int8),
            **g_fields,
        )
    return SwimState(
        status=status,
        inc=jnp.zeros((n, k), dtype=jnp.int32),
        spread_until=spread0,
        suspect_deadline=jnp.full((n, k), INT32_MAX, dtype=jnp.int32),
        self_inc=jnp.zeros((n,), dtype=jnp.int32),
        inbox_ring=jnp.full((d_slots, n, k), -1, dtype=ring_dtype),
        flag_ring=jnp.zeros((d_slots, n, k), dtype=jnp.int8),
        **g_fields,
    )


# --------------------------------------------------------------------------
# The tick
# --------------------------------------------------------------------------

# compact_carry sentinel: "no suspicion timer" in the int16
# remaining-rounds encoding (decodes to INT32_MAX).
_DEADLINE_NONE16 = 32767
# int16 stored-incarnation ceiling (the COMPACT CARRY's dtype bound —
# a carry-layout constant, distinct from the per-format WIRE saturation
# points that live in ops/delivery.WIRE_FORMATS).
_CARRY16_INC_SAT = (1 << 15) - 1


def _wire_inc_sat(params: "SwimParams") -> int:
    """Largest incarnation the active wire format AND carry layout hold
    exactly — min of the wire key's incarnation-field saturation
    (ops/delivery.WireFormat.inc_sat, the one format table) and, under
    ``compact_carry``, the int16 stored-incarnation ceiling.

    The carry must never hold an incarnation ABOVE this cap: past it the
    packed keys of distinct incarnations collide, so the merge gate
    (ops/delivery.merge_inbox's ``inbox_key > entry_key``) stops
    distinguishing records the carry still could — wire and table would
    silently disagree.  Incarnations only grow at the self-refutation
    bump, which is clamped to this cap (_merge_and_timers); at the cap a
    node can no longer refute (ALIVE@cap does not override SUSPECT@cap)
    — a loud, pinned degradation (tests/test_wire16.py boundary tests)
    instead of a silent wire/table divergence.

    The open-world plane's epoch field is carved out of the TOP of the
    incarnation field (ops/delivery.py layout comment), so the wire cap
    drops by ``2^epoch_bits`` — 2^23-1 wide / 2^11-1 wire16.  The
    wire24 rung exists exactly to lift the compact-carry pairing off
    that 2^11-1 floor: its 24-bit key field carries 2^18-1 with the
    4-bit epoch field, so the int16 CARRY ceiling (32767) becomes the
    binding cap — 16x the wire16+epoch headroom at identical wire
    bytes per slot under the fused single-buffer wire.
    """
    sat = params.wire_format.inc_sat(params.epoch_bits)
    if params.compact_carry:
        sat = min(sat, _CARRY16_INC_SAT)
    return sat


def _carry_decode(state: SwimState, round_idx) -> SwimState:
    """compact -> wide: absolute rounds + int32, at the current cursor.

    The tick body then runs unchanged on the wide form; _carry_encode
    narrows the result back.  Lossless both ways while deadlines are
    < 32767 rounds ahead and incarnations <= 8191 (validated statically
    for params; Knobs overrides share the caps — SwimParams docstring).
    """
    dl = state.suspect_deadline.astype(jnp.int32)
    return dataclasses.replace(
        state,
        inc=state.inc.astype(jnp.int32),
        spread_until=round_idx + state.spread_until.astype(jnp.int32),
        suspect_deadline=jnp.where(
            dl == _DEADLINE_NONE16, INT32_MAX, round_idx + dl
        ),
        # Identity-epoch lane (open-world plane): plain int16 -> int32
        # upcast, no re-relativization (epochs are absolute counters).
        # A zero-size lane (plane off) passes through untouched so its
        # int32 dtype stays carry-stable.
        epoch=(state.epoch if state.epoch.size == 0
               else state.epoch.astype(jnp.int32)),
    )


def _carry_encode(state: SwimState, round_idx, inc_sat: int) -> SwimState:
    """wide -> compact, relative to the NEXT round's cursor.

    ``inc_sat`` (required — a defaulted carry-ceiling clamp would
    silently under-clamp a wire16 run): the incarnation clamp — callers
    pass the active format's ``_wire_inc_sat(params)`` (8191 under
    wire16, 32767 under wire24; a well-formed carry is already at or
    below it, since the refutation bump clamps there — this is the
    encode-side safety for hand-seeded states).

    A ``suspect_deadline`` in the past encodes as a NEGATIVE remaining
    count — a frozen (crashed/left) row's pending timer goes stale
    while the rest of the world moves on, and clipping it to 0 would
    decode it to the current cursor instead of the round it actually
    pointed at (the leave + ring-shift divergence
    tests/test_compact_carry.py pins).  Behavior is unchanged either
    way (any past deadline fires on the next live evaluation, i.e. on
    revival), but the decoded DEADLINE table must match the wide layout
    bit for bit.  Staleness saturates at -(32766) remaining — beyond
    that (impossible inside the <32k-round compact contract) the
    decoded round drifts but the fires-immediately semantics still
    hold.

    ``spread_until`` keeps its clip-to-0 for stale rows: its only
    consumer is the ``round_idx < spread_until`` spread gate, which a
    stale absolute round and the cursor both fail identically, and
    nothing compares the decoded spread table across layouts — so the
    int8 stays narrow instead of spending a sign bit on an
    unobservable distinction.

    A deadline MORE than 32765 rounds out (possible only through a
    traced ``Knobs.suspicion_rounds`` override — static params are
    validated in ``SwimParams.__post_init__``) cannot be represented;
    it encodes as "no timer" rather than clipping, so a
    beyond-the-horizon suspicion never matures instead of silently
    firing ~32766 rounds in (the FD-isolation pattern that sets
    suspicion past the run length gets exactly its intent; a >32k-round
    run genuinely needing such timers must use the wide layout).
    """
    nxt = round_idx + 1
    dl = state.suspect_deadline
    remaining = dl - nxt
    return dataclasses.replace(
        state,
        inc=jnp.minimum(state.inc, inc_sat).astype(jnp.int16),
        epoch=(state.epoch if state.epoch.size == 0
               else state.epoch.astype(jnp.int16)),
        spread_until=jnp.clip(
            state.spread_until - nxt, 0, 127
        ).astype(jnp.int8),
        suspect_deadline=jnp.where(
            (dl == INT32_MAX) | (remaining > _DEADLINE_NONE16 - 1),
            _DEADLINE_NONE16,
            jnp.clip(remaining, -(_DEADLINE_NONE16 - 1),
                     _DEADLINE_NONE16 - 1),
        ).astype(jnp.int16),
    )


def _chain_ok(key, hop_losses: Sequence[jnp.ndarray],
              hop_delay_means: Sequence[jnp.ndarray], budget_ms, shape):
    """P2P multi-hop success: every hop delivered AND total delay <= budget.

    Vectorizes NetworkLinkSettings.evaluateLoss/evaluateDelay
    (transport/NetworkLinkSettings.java:54-74) over chained hops with
    per-hop (possibly per-link, from link_eval) loss/delay and a shared
    millisecond budget (the reference's Reactor ``.timeout(duration)``,
    FailureDetectorImpl.java:152).

    A hop's delay mean may be ``None`` (statically zero — link_eval
    docstring): that hop contributes no delay and no exponential sample.
    With every hop static-zero the whole chain collapses to ONE Bernoulli
    draw against the product of per-hop success probabilities — exact,
    because the per-hop losses are independent (each message's loss is an
    independent event in the reference emulator too,
    NetworkEmulator.java:60-97), and the all-hops-succeed probability of
    independent events is their product.  This cuts the FD probe's
    per-round PRNG volume ~7x at 1M members (threefry bits are the
    dominant probe cost on TPU, not the comparisons).
    """
    n_hops = len(hop_losses)
    delayed = [h for h in range(n_hops) if hop_delay_means[h] is not None]
    if not delayed:
        # The delayed path still compares total_delay (= 0 here) against the
        # budget, which fails every chain for a negative budget (e.g. a
        # misconfigured ping_timeout >= ping_interval).  Keep the collapse
        # exactly equivalent; budget_ms is static, so this folds away.
        if isinstance(budget_ms, (int, float)) and not 0.0 <= float(budget_ms):
            return jnp.zeros(shape, dtype=jnp.bool_)
        p_chain = jnp.ones(shape, dtype=jnp.float32)
        for h in range(n_hops):
            p_chain = p_chain * (1.0 - hop_losses[h])
        ok = jax.random.uniform(key, shape) < p_chain
        if not isinstance(budget_ms, (int, float)):
            ok &= jnp.float32(0.0) <= budget_ms
        return ok
    u = jax.random.uniform(key, (*shape, n_hops + len(delayed)))
    ok = jnp.ones(shape, dtype=jnp.bool_)
    for h in range(n_hops):
        ok &= u[..., h] >= hop_losses[h]
    total_delay = jnp.zeros(shape, dtype=jnp.float32)
    for j, h in enumerate(delayed):
        total_delay += (
            -jnp.log1p(-u[..., n_hops + j]) * hop_delay_means[h]
        )
    return ok & (total_delay <= budget_ms)


def _ring_open(state: SwimState, params: SwimParams, round_idx,
               with_flags: bool = True):
    """Read this round's due slot and clear it for reuse (ops/ring.py).

    Returns (inbox_now, flags_now, g_now, ring, fring, gring, slot0) —
    the rings already have slot0 reset, ready to accumulate future
    arrivals.  With delay modeling off (max_delay_rounds == 0) returns
    Nones; the user-gossip pair is None when n_user_gossips == 0.

    ``with_flags=False`` (the fused scatter wire): the flag ring is
    never written or read — the merge gate derives ALIVE flags from the
    ring's folded KEYS at open time — so skip the per-round full-height
    reset store and pass ``state.flag_ring`` through untouched
    (all-zeros forever).  The lane itself stays allocated: zero-sizing
    it under the DEFAULT config would change checkpoint shapes for
    delay configs, and the wire change promises legacy checkpoints
    load as-is (MIGRATING.md).  Shift-mode delay genuinely uses the
    ring (its channels push transmit flags), so it keeps the default.
    """
    if params.max_delay_rounds == 0:
        return None, None, None, None, None, None, None
    slot0 = round_idx % (params.max_delay_rounds + 1)
    inbox_now, ring = ring_ops.open_slot(
        state.inbox_ring, slot0, delivery.no_message(fmt=params.wire_format)
    )
    if with_flags:
        flags_now, fring = ring_ops.open_slot(
            state.flag_ring, slot0, jnp.int8(0)
        )
        flags_now = flags_now.astype(jnp.bool_)
    else:
        flags_now, fring = None, state.flag_ring
    g_now, gring = (None, None)
    if params.n_user_gossips > 0:
        g_now, gring = ring_ops.open_slot(state.g_ring, slot0, False)
    return inbox_now, flags_now, g_now, ring, fring, gring, slot0


def _ring_push(ring, fring, slot, keys, flags):
    """Max/or-merge a future (keys, flags) contribution into one slot."""
    return (ring_ops.push_max(ring, slot, keys),
            ring_ops.push_or(fring, slot, flags.astype(jnp.int8)))


def _route_delayed(ok, delivered, delivered_flags, delay_mean, key, params,
                   ring, fring, slot0, g_bits=None, g_ring=None):
    """Split one channel's delivery into now vs future ring slots.

    Returns (ok_now, ring, fring, g_ring): ``ok_now`` masks the messages
    arriving this round; later quantized offsets are max/or-merged into
    the ring.  Shared by the gossip, SYNC, and refute channels so the
    binning and slot arithmetic exist once.  ``delay_mean is None``
    (statically zero, link_eval docstring) means everything arrives this
    round.  ``g_bits`` [n, G]: user-gossip bits riding the SAME wire
    message — they share the channel's delay bins exactly (one message,
    one delay draw); their future slots go to ``g_ring``.
    """
    if params.max_delay_rounds == 0 or delay_mean is None:
        return ok, ring, fring, g_ring
    no_msg = delivery.no_message(fmt=params.wire_format)
    q = ring_ops.delay_bins(key, delay_mean, params.round_ms,
                            params.max_delay_rounds, ok.shape)
    d = params.max_delay_rounds + 1
    for j in range(1, d):
        m = (ok & (q == j))[:, None]
        ring, fring = _ring_push(
            ring, fring, (slot0 + j) % d,
            jnp.where(m, delivered, no_msg),
            delivered_flags & m,
        )
        if g_bits is not None:
            g_ring = ring_ops.push_or(g_ring, (slot0 + j) % d, g_bits & m)
    return ok & (q == 0), ring, fring, g_ring


def _entry_at_slot(mat, slot, k):
    """mat[i, slot[i]] via a one-hot reduce over K (elementwise, no gather).

    Standalone, a ``take_along_axis`` row-local gather micro-benchmarks
    2x faster — but inside the scanned tick it de-optimizes the whole
    round (measured 4.3 -> 10+ ms/round at 1M): the gather forces layout
    changes on the [N, K] operands that cascade into every neighboring
    fusion.  Keep the branch-free one-hot form."""
    onehot = jnp.arange(k, dtype=jnp.int32)[None, :] == slot[:, None]
    return jnp.max(jnp.where(onehot, mat, mat.dtype.type(0)), axis=1)


def _apply_joins(state: SwimState, round_idx, params: SwimParams,
                 world: SwimWorld, node_ids, is_self) -> SwimState:
    """Reset the rows of slots whose JOIN fires this round to the fresh-
    identity cold-start shape, in the state's STORED layout.

    Elementwise masked selects on the carry (compiled out entirely when
    ``params.open_world`` is False — the caller gates).  The reset
    mirrors ``initial_state(warm=False)`` for exactly the joining rows:
    ABSENT except self (pinned ALIVE) and the configured seeds (the
    joiner knows seeds a priori — MembershipProtocolImpl.start0's
    contact list), incarnation 0, a hot self-announcement window, no
    timers, cleared delay-ring rows and user-gossip bits, lhm back to
    healthy, and zeroed epoch BELIEFS (the row learns current epochs
    from the wire; its own cell is pinned to the world's ground-truth
    epoch by the round context / merge tail).

    Layout rule: the non-blocked compact path decodes the carry BEFORE
    this runs (``_round_context`` order), so the reset writes wide
    encodings there; only the k_block path sees the stored compact
    form, where the relative encodings of a fresh row are written
    directly (remaining-rounds spread, the int16 no-timer sentinel).
    """
    compact_layout = params.compact_carry and bool(params.k_block)
    jvec = world.join_at[node_ids] == round_idx          # [n_local]
    jrow = jvec[:, None]

    reset_status = jnp.where(is_self, records.ALIVE, records.ABSENT)
    if world.seed_ids.shape[0] > 0:
        k = state.status.shape[1]
        seed_slot = world.slot_of_node[world.seed_ids]   # [S] (-1 untracked)
        is_seed_col = jnp.any(
            (jnp.arange(k, dtype=jnp.int32)[None, :] == seed_slot[:, None])
            & (seed_slot >= 0)[:, None],
            axis=0,
        )
        reset_status = jnp.where(is_seed_col[None, :] & ~is_self,
                                 records.ALIVE, reset_status)
    status = jnp.where(jrow, reset_status, state.status).astype(jnp.int8)
    inc = jnp.where(jrow, 0, state.inc).astype(state.inc.dtype)
    if compact_layout:
        spread_fresh = jnp.where(is_self, params.periods_to_spread + 1, 0)
        deadline_fresh = _DEADLINE_NONE16
    else:
        spread_fresh = jnp.where(is_self,
                                 round_idx + 1 + params.periods_to_spread, 0)
        deadline_fresh = INT32_MAX
    spread = jnp.where(jrow, spread_fresh, state.spread_until) \
        .astype(state.spread_until.dtype)
    deadline = jnp.where(jrow, deadline_fresh, state.suspect_deadline) \
        .astype(state.suspect_deadline.dtype)
    self_inc = jnp.where(jvec, 0, state.self_inc)
    epoch = state.epoch
    if params.epoch_bits:
        epoch = jnp.where(jrow, 0, state.epoch).astype(state.epoch.dtype)
    lhm = state.lhm
    if params.lhm_max > 0:
        lhm = jnp.where(jvec, 1, state.lhm)
    inbox_ring, flag_ring = state.inbox_ring, state.flag_ring
    if params.max_delay_rounds > 0:
        # In-flight messages addressed to the OLD occupant die with it.
        inbox_ring = jnp.where(
            jrow[None], delivery.no_message(fmt=params.wire_format),
            state.inbox_ring,
        )
        flag_ring = jnp.where(jrow[None], jnp.int8(0), state.flag_ring)
    g_infected, g_spread_until, g_ring = (state.g_infected,
                                          state.g_spread_until,
                                          state.g_ring)
    if params.n_user_gossips > 0:
        g_infected = jnp.where(jrow[:, :1], False, state.g_infected)
        g_spread_until = jnp.where(jrow[:, :1], 0, state.g_spread_until)
        if state.g_ring.shape[0] > 0:
            g_ring = jnp.where(jrow[None, :, :1], False, state.g_ring)
    md, md_spread = state.md, state.md_spread
    if params.metadata_keys > 0:
        # A reborn slot starts with an EMPTY metadata table (the reference
        # seeds a fresh MetadataStore per member): its own words are re-
        # published by the next ConfigPush under the new epoch, and stale
        # words about it die at receivers via the epoch gate in
        # metadata.merge.
        md = jnp.where(jrow[:, :1, None], 0, state.md)
        md_spread = jnp.where(jrow[:, :1], 0, state.md_spread)
    return SwimState(
        status=status, inc=inc, spread_until=spread,
        suspect_deadline=deadline, self_inc=self_inc,
        inbox_ring=inbox_ring, flag_ring=flag_ring,
        g_infected=g_infected, g_spread_until=g_spread_until,
        g_ring=g_ring, lhm=lhm, epoch=epoch,
        md=md, md_spread=md_spread,
    )


def _round_context(state: SwimState, round_idx, base_key,
                   params: SwimParams, world: SwimWorld, offset=0,
                   knobs: Optional[Knobs] = None, shift_key=None):
    """Shared per-round preamble of ``swim_tick`` and its pipelined
    halves (``swim_tick_send`` / ``swim_tick_recv``): carry decode,
    per-round PRNG keys, world liveness/partition slices, the
    self-record pin, user-gossip injection, and the phase gates.

    Both halves of a pipelined round derive the SAME context from the
    same (state, round_idx) — recomputing it is a handful of elementwise
    ops, and it is what makes the send/recv split bit-identical to the
    monolithic tick without carrying pinned temporaries between rounds.
    """
    kn = knobs if knobs is not None else Knobs.from_params(params)
    n = params.n_members
    n_local = state.status.shape[0]
    # k_block keeps the carry in its stored layout end-to-end: a global
    # decode would materialize three wide int32 [N, N] temps (measured
    # 6x 4G at 32,768 — the decode can't fuse through a fori_loop's
    # operand boundary); the blocked body decodes/encodes per block.
    if params.compact_carry and not params.k_block:
        state = _carry_decode(state, round_idx)
    # Fold both the round and the shard offset so draws are independent
    # across rounds AND across devices (ops/prng.py module docstring).
    # The shift channel draws come from the UN-folded round key: every
    # device must agree on the round's global shifts.
    key_global = prng.round_key(base_key, round_idx)
    key = prng.round_key(key_global, offset)
    keys = tuple(jax.random.split(key, 8))
    # ``shift_key`` (default: the base key) sources ONLY the per-round
    # channel shifts.  Under a vmapped knob sweep, passing one UNBATCHED
    # shift key makes the round's shifts batch-invariant, so the payload
    # dynamic-slices stay slices instead of lowering to gathers — the
    # shared-shift batching that makes 1M-member vmap sweeps run at the
    # shift path's contiguous rate (sweep.sweep_run).  Within an
    # instance the draws are identical in distribution; across instances
    # the shared offsets act as common random numbers for the channel
    # topology while drop/chain draws stay per-instance.
    k_shifts = jax.random.fold_in(
        prng.round_key(base_key if shift_key is None else shift_key,
                       round_idx),
        0x5317,
    )

    alive = world.alive_at(round_idx)                       # [N] ground truth
    part = world.partition_at(round_idx)                    # [N]
    node_ids = jnp.arange(n_local, dtype=jnp.int32) + offset    # global ids
    if n_local != n:  # contiguous local row slice of the replicated vectors
        alive_here = jax.lax.dynamic_slice_in_dim(alive, offset, n_local)
        part_here = jax.lax.dynamic_slice_in_dim(part, offset, n_local)
    else:
        alive_here, part_here = alive, part
    is_self = world.subject_ids[None, :] == node_ids[:, None]   # [n_local, K]

    # Open-world JOIN execution (SwimParams.open_world): a slot whose
    # join fires this round is REBORN as a fresh identity — its row
    # resets to the cold-start shape (ABSENT except self + configured
    # seeds, incarnation 0, no timers, hot self-announcement, healthy
    # lhm, epoch beliefs 0) before the tick's phases read it.  Shared
    # by all three tick bodies and both pipelined halves through this
    # one preamble, so the reset cannot drift between them; the
    # joiner's own ground-truth epoch comes from the world schedule
    # (``epoch_at``), never from the carry.
    own_epoch = None
    if params.open_world:
        state = _apply_joins(state, round_idx, params, world, node_ids,
                             is_self)
        own_epoch = world.epoch_at(round_idx)[node_ids]     # [n_local]

    # Row i's record about itself is pinned (a node always believes itself
    # ALIVE at self_inc — MembershipProtocolImpl drops self-updates and
    # refutes instead, :488-509).  The blocked body pins per block — the
    # global int32 pin would materialize a wide temp; a well-formed carry
    # already holds the pinned values (the merge re-asserts them), so the
    # raw fields the blocked FD pre-pass reads are identical.
    if params.k_block:
        status, inc = state.status, state.inc
        epoch = state.epoch if params.epoch_bits else None
    else:
        status = jnp.where(is_self, records.ALIVE, state.status)
        inc = jnp.where(is_self, state.self_inc[:, None], state.inc)
        epoch = None
        if params.epoch_bits:
            epoch = jnp.where(is_self, own_epoch[:, None],
                              state.epoch.astype(jnp.int32))

    # User-gossip spread() injections (GossipProtocolImpl.createAndPutGossip,
    # :163-169): gossip g appears at its origin in its scheduled round and
    # starts spreading the SAME round (doSpreadGossip sends just-created
    # gossips too, :139-157).  A crashed origin can't call spread().
    if params.n_user_gossips > 0:
        inject = (
            (world.gossip_spread_at[None, :] == round_idx)
            & (world.gossip_origin[None, :] == node_ids[:, None])
            & alive_here[:, None]
        )
        state = dataclasses.replace(
            state,
            g_infected=state.g_infected | inject,
            g_spread_until=jnp.where(
                inject & ~state.g_infected,
                round_idx + 1 + params.periods_to_spread,
                state.g_spread_until,
            ),
        )

    # Scheduled config pushes (SwimWorld.with_metadata_push): owner-
    # local writes applied in this shared preamble, so the pipelined
    # halves re-derive the identical injection from the same carried
    # state — the same argument as the self-record pin above
    # (metadata.inject_pushes is pure in (md, md_spread, round_idx)).
    if (params.metadata_keys > 0 and world.md_push_at is not None
            and world.md_push_at.shape[0] > 0):
        md, md_spread = metadata.inject_pushes(
            state.md, state.md_spread, round_idx, params, world,
            node_ids, own_epoch, alive_here,
        )
        state = dataclasses.replace(state, md=md, md_spread=md_spread)

    # ping_every/sync_every <= 0 disable the phase entirely (a plain
    # modulo sentinel like INT32_MAX would still fire at round 0).
    fd_round = (kn.ping_every > 0) & (
        (round_idx % jnp.maximum(kn.ping_every, 1)) == 0
    )
    sync_round = (kn.sync_every > 0) & (
        (round_idx % jnp.maximum(kn.sync_every, 1)) == 0
    )

    # Contact gating (full-view only, active when seeds are configured):
    # a sender only gossips/syncs at members it knows live, or at seeds —
    # the reference's peer-list rule (class docstring of SwimWorld).
    gate_contacts = params.full_view and world.seed_ids.shape[0] > 0

    def known_live(target_ids):
        """[...]: sender's table holds ALIVE/SUSPECT for these targets
        (full-view: slot == node id)."""
        ts = jnp.take_along_axis(
            status, target_ids.reshape(n_local, -1), axis=1
        ).reshape(target_ids.shape)
        return (ts == records.ALIVE) | (ts == records.SUSPECT)

    def is_seed(target_ids):
        return jnp.any(
            target_ids[..., None] == world.seed_ids[None, :], axis=-1
        )

    return dict(
        kn=kn, state=state, status=status, inc=inc, keys=keys,
        k_shifts=k_shifts, alive=alive, part=part, node_ids=node_ids,
        alive_here=alive_here, part_here=part_here, is_self=is_self,
        fd_round=fd_round, sync_round=sync_round,
        gate_contacts=gate_contacts, known_live=known_live,
        is_seed=is_seed, epoch=epoch, own_epoch=own_epoch,
    )


def swim_tick(state: SwimState, round_idx, base_key, params: SwimParams,
              world: SwimWorld, offset=0, axis_name: Optional[str] = None,
              knobs: Optional[Knobs] = None, n_devices: int = 1,
              shift_key=None):
    """One protocol round.  Pure: (state, r, key) -> (state', metrics).

    Phases (matching the reference's periodic loops, SURVEY.md §3.2-3.4):
      1. FD probe (every ping_every rounds): pick target, direct ping with
         ping_timeout, else ping-req via k proxies — collapsed in closed
         form over the loss/delay model; SUSPECT verdicts merge locally,
         ALIVE-on-suspected pushes the record to the subject (SYNC analog).
      2. Gossip send: every node pushes its hot records to fanout targets.
      3. SYNC (every sync_every rounds): push the full row to one random
         member (anti-entropy, MembershipProtocolImpl.java:439-454).
      4. Merge all inboxes through the is_overrides lattice; self-records
         refute (incarnation bump); suspicion timers set/cancel/fire.

    Delivery is either exact-uniform scatter or cyclic-shift mixing
    (module docstring); per-link faults apply in both via link_eval.

    Sharding (scatter mode): ``state`` rows may be a contiguous slice of
    the global member axis (``offset`` = first global row).  Senders
    scatter into a global-height inbox contribution; under ``shard_map``
    the contributions combine with one ``lax.pmax`` over ``axis_name`` —
    the ICI collective that replaces the reference's point-to-point TCP
    (SURVEY.md §5.8) — and each device keeps its own row slice.  With
    ``axis_name=None`` and ``offset=0`` this is the single-device path
    unchanged.  Sharded shift mode exchanges payload blocks with
    block-rotation ppermutes instead (ops/shift.ShiftEngine); its
    per-round traffic is O(n_local*K) per channel vs the pmax's O(N*K).
    ``n_devices`` must be the static mesh size when ``axis_name`` is set.
    """
    if params.link_counters and axis_name is not None:
        raise NotImplementedError(
            "link_counters is a single-device measurement substrate "
            "(per-sender [N] rows don't cross shard_map metric combining)"
        )
    ctx = _round_context(state, round_idx, base_key, params, world,
                         offset=offset, knobs=knobs, shift_key=shift_key)
    kn, state, status, inc = ctx["kn"], ctx["state"], ctx["status"], ctx["inc"]
    (k_ping_t, k_ping_net, k_proxy, k_proxy_net, k_gossip_t, k_gossip_drop,
     k_sync_t, k_sync_drop) = ctx["keys"]
    k_shifts = ctx["k_shifts"]
    alive, part, node_ids = ctx["alive"], ctx["part"], ctx["node_ids"]
    alive_here, part_here = ctx["alive_here"], ctx["part_here"]
    is_self = ctx["is_self"]
    fd_round, sync_round = ctx["fd_round"], ctx["sync_round"]
    gate_contacts = ctx["gate_contacts"]
    known_live, is_seed = ctx["known_live"], ctx["is_seed"]

    if params.k_block:
        if axis_name is not None:
            raise NotImplementedError(
                "k_block is the single-chip capacity path (shard the rows "
                "instead for multi-chip full view — parallel/mesh.py)"
            )
        if gate_contacts:
            raise NotImplementedError(
                "k_block does not support seed-gated contacts (the gate "
                "reads a full-status column per channel)"
            )
        new_state, aux = _tick_shift_blocked(
            state, status, inc, round_idx, params, kn, world,
            alive, part, node_ids, alive_here, part_here, is_self,
            fd_round, sync_round,
            (k_shifts, k_ping_net, k_proxy, k_proxy_net, k_gossip_t,
             k_gossip_drop, k_sync_t, k_sync_drop),
            own_epoch=ctx["own_epoch"],
        )
    elif params.delivery == "shift":
        new_state, aux = _tick_shift(
            state, status, inc, round_idx, params, kn, world,
            alive, part, node_ids, alive_here, part_here, is_self,
            fd_round, sync_round, gate_contacts, known_live, is_seed,
            (k_shifts, k_ping_net, k_proxy, k_proxy_net, k_gossip_t,
             k_gossip_drop, k_sync_t, k_sync_drop),
            offset=offset, axis_name=axis_name, n_devices=n_devices,
            epoch=ctx["epoch"], own_epoch=ctx["own_epoch"],
        )
    else:
        new_state, aux = _tick_scatter(
            state, status, inc, round_idx, params, kn, world,
            alive, part, node_ids, alive_here, part_here, is_self,
            fd_round, sync_round, gate_contacts, known_live, is_seed,
            (k_ping_t, k_ping_net, k_proxy, k_proxy_net, k_gossip_t,
             k_gossip_drop, k_sync_t, k_sync_drop),
            offset, axis_name, k_channel=k_shifts,
            epoch=ctx["epoch"], own_epoch=ctx["own_epoch"],
        )

    metrics = _round_metrics(new_state, status, aux, params, world,
                             alive, alive_here, axis_name)
    if params.compact_carry and not params.k_block:
        new_state = _carry_encode(new_state, round_idx,
                                  inc_sat=_wire_inc_sat(params))
    return new_state, metrics


def _round_metrics(new_state: SwimState, status, aux, params: SwimParams,
                   world: SwimWorld, alive, alive_here,
                   axis_name: Optional[str]):
    """The per-round observability tensors (SURVEY.md §5.1), from the
    post-merge state + the tick's send-side counters (``aux``).  Shared
    by the monolithic tick and the pipelined recv half — under
    pipelining a round's metrics are emitted one scan body later, from
    identical inputs, so the stacked traces stay bit-identical.

    ``status`` is the PRE-merge pinned status (for the suspicion-onset
    delta); ``alive``/``alive_here`` are the round's ground-truth
    liveness.
    """
    k = params.n_subjects

    def global_sum(x):
        return jax.lax.psum(x, axis_name) if axis_name is not None else x

    # Restructured in round 4 from seven [N, K] pred masks (each ANDing in
    # the per-column subject-liveness and the one-hot self mask) to FOUR
    # row-space reductions plus per-column post-processing:
    #   - the self cell is pinned ALIVE, so it contributes exactly
    #     alive[subject_k] to column k's ALIVE histogram and nothing to
    #     any other metric — subtract it after the reduce instead of
    #     materializing ~is_self into every mask;
    #   - subject liveness is a per-column [K] factor — multiply after
    #     the reduce instead of broadcasting it into the [N, K] masks;
    #   - "absent" follows from the histogram identity: each live
    #     observer row contributes exactly one status code per column,
    #     so sum_code hist[code] == live observer count.
    if "blocked_metrics" in aux:
        # Blocked tick: histograms AND the per-column products (the FP
        # families don't commute with aggregation) were accumulated per
        # column block inside the fori_loop — same reductions, summed
        # blockwise, numerically exact.
        bm = aux.pop("blocked_metrics")
        hist_alive, hist_suspect, hist_dead = (
            bm["hist_alive"], bm["hist_suspect"], bm["hist_dead"])
        still_suspect = bm["still_suspect"]
        subject_alive_i = bm["subject_alive_i"]
        live_observers = jnp.sum(alive_here, dtype=jnp.int32)
        if not params.per_subject_metrics:
            # Aggregate "absent" is sum_k (live_observers - hists[k]):
            # the per-column live_observers term appears K times.
            live_observers = live_observers * k
        false_suspect_rounds = bm["false_suspect_rounds"]
        stale_view_rounds = bm["stale_view_rounds"]
        onsets = bm["onsets"]
        products_precomputed = True
    else:
        products_precomputed = False
        new_status = new_state.status
        observer_alive = alive_here[:, None]
        subject_alive_i = alive[world.subject_ids].astype(jnp.int32)  # [K]

        def col_sum(mask):
            return jnp.sum(mask, axis=0, dtype=jnp.int32)             # [K]

        hist_alive = global_sum(col_sum(
            (new_status == records.ALIVE) & observer_alive))
        hist_suspect = global_sum(col_sum(
            (new_status == records.SUSPECT) & observer_alive))
        hist_dead = global_sum(col_sum(
            (new_status == records.DEAD) & observer_alive))
        # SUSPECT now AND at tick start — subtracted from hist_suspect to
        # count NEW suspicions (onsets).
        still_suspect = global_sum(col_sum(
            (new_status == records.SUSPECT) & (status == records.SUSPECT)
            & observer_alive))
        live_observers = global_sum(jnp.sum(alive_here, dtype=jnp.int32))

    counts = {
        "alive": hist_alive - subject_alive_i,
        "suspect": hist_suspect,
        "dead": hist_dead,
        "absent": live_observers - hist_alive - hist_suspect - hist_dead,
    }
    # False positive: a live observer holds SUSPECT/DEAD about a live
    # subject.  The aggregate partitions EXACTLY by the held status
    # (false_positives == false_suspect_rounds + stale_view_rounds):
    #   - ``false_suspect_rounds``: observer-ROUNDS holding SUSPECT about a
    #     live subject — active false-suspicion episodes, plus genuine
    #     suspicions begun while the subject was down that outlived a quick
    #     revival without maturing to DEAD;
    #   - ``stale_view_rounds``: observer-ROUNDS holding a DEAD tombstone
    #     about a live subject — dominated by the window after a revival
    #     until the refuted record re-disseminates (the reference has the
    #     same window between restart and ADDED re-emission,
    #     MembershipProtocolImpl.java:512-516 deletes then re-adds).
    # ``false_suspicion_onsets`` counts EVENTS, not rounds — a live
    # observer newly turning SUSPECT about a live subject this round (a
    # genuine FD false alarm beginning, the thing the SWIM paper's FP
    # curves count).  ``false_positives`` (observer-rounds) is kept for
    # continuity with round-1/2 artifacts.
    if not products_precomputed:
        false_suspect_rounds = hist_suspect * subject_alive_i
        stale_view_rounds = hist_dead * subject_alive_i
        onsets = (hist_suspect - still_suspect) * subject_alive_i
    if not params.per_subject_metrics:
        counts = {name: jnp.sum(v) for name, v in counts.items()}
        false_suspect_rounds = jnp.sum(false_suspect_rounds)
        stale_view_rounds = jnp.sum(stale_view_rounds)
        onsets = jnp.sum(onsets)
    metrics = dict(
        counts,
        # The aggregate is the partition sum by construction (the two
        # terms gate disjoint statuses: SUSPECT xor DEAD).
        false_positives=false_suspect_rounds + stale_view_rounds,
        false_suspicion_onsets=onsets,
        false_suspect_rounds=false_suspect_rounds,
        stale_view_rounds=stale_view_rounds,
        messages_gossip=global_sum(aux["messages_gossip"]),
        # Two probe-counter families (both per round):
        #   ``messages_ping``      — probes whose verdict lands on a
        #     *tracked subject* (drives suspicion state; in focal mode
        #     ~N·K/N² of real traffic, so at 1M×16 it reads "3 pings" a
        #     round while the cluster issues ~1M);
        #   ``messages_ping_sent`` — PINGs actually issued by live
        #     members this round, the reference's per-period probe count
        #     (FailureDetectorImpl.java:148,156-164); plus
        #   ``messages_ping_req_sent`` — PING_REQ fan-out messages for
        #     probes whose direct ping failed (k proxies each).
        messages_ping=global_sum(aux["messages_ping"]),
        messages_ping_sent=global_sum(aux["messages_ping_sent"]),
        messages_ping_req_sent=global_sum(aux["messages_ping_req_sent"]),
        refutations=global_sum(aux["refutations"]),
    )
    if params.sync_interval > 0:
        # Anti-entropy exchange messages issued by live members this
        # round (2 per member on exchange rounds — models/sync.sent_count).
        metrics["messages_anti_entropy"] = global_sum(
            aux["messages_anti_entropy"]
        )
    if params.link_counters:
        # Per-sender NetworkEmulator counters (single-device; validated
        # above) — [N] rows, stacked by the scan into [rounds, N] traces.
        metrics["sent_by_node"] = aux["sent_by_node"]
        metrics["lost_by_node"] = aux["lost_by_node"]
    if params.n_user_gossips > 0:
        # Per-gossip infection curve — the measured analog of
        # ClusterMath.gossipConvergencePercent co-running with the full
        # protocol (GossipProtocolTest.java:178-205's substrate).
        metrics["user_gossip_infected"] = global_sum(
            jnp.sum(new_state.g_infected, axis=0, dtype=jnp.int32)
        )
    if params.metadata_keys > 0:
        # Metadata convergence observable (models/metadata.py): the
        # count of (live observer, live owner, key) cells disagreeing
        # with the owner's own word — computed in the tick bodies where
        # the shard offset lives, ALREADY globally reduced (one psum
        # inside divergent_count), so no global_sum here.
        metrics["metadata_divergent"] = aux["metadata_divergent"]
    if params.provenance:
        # Per-channel folded maxima for the provenance plane — LOCAL
        # per-cell evidence (already cross-device combined where a
        # combine exists), passed through un-reduced.  The composed
        # runner pops this key into the shared RoundCtx before the
        # scan stacks metrics (models/compose.py) — it never reaches
        # a stacked trace.
        metrics["_provenance"] = aux["_provenance"]
    return metrics


# --------------------------------------------------------------------------
# Shared phase 4: merge + refutation + timers + process faults
# --------------------------------------------------------------------------


def _merge_and_timers(state, status, inc, inbox, inbox_alive, round_idx,
                      params, kn, world, node_ids, alive_here, is_self,
                      inbox_ring=None, flag_ring=None,
                      g_delivered=None, g_ring=None, lhm_signals=None,
                      epoch=None, own_epoch=None, md_delivered=None):
    """Inbox merge, self-refutation, suspicion timers, crash/leave freeze.

    Shared tail of both delivery modes; all elementwise on [n_local, K].
    ``g_delivered`` [n_local, G] bool: user-gossip bits arriving this
    round (OR-merged; newly infected rows open a fresh spread window —
    onGossipReq, GossipProtocolImpl.java:171-183).
    ``lhm_signals``: ``(probe_fail, probe_clean)`` [n_local] bool from
    the round's FD phase (Lifeguard plane on) — None leaves the lhm
    lane untouched (the blocked tick updates it once outside its block
    loop; the plane-off path has a zero-size lane either way).
    ``epoch``/``own_epoch`` (open-world plane): the pinned identity-
    epoch matrix and each row's own ground-truth epoch
    (``_round_context``) — the merge gate resolves identities with
    them and the updated lane lands in the carry; None (plane off)
    leaves the zero-size lane untouched.
    ``md_delivered`` [n_local, K*M] int32 (metadata plane on): the
    round's max-folded metadata arrivals, LWW-merged against the
    receiver's POST-merge identity beliefs (metadata.merge); None
    leaves the md lanes untouched.
    Returns (new_state, refuted[n_local] bool).
    """
    # Dead-member suppression window (SwimParams.dead_suppress_rounds):
    # a freshly stored tombstone gates by its TRUE DEAD key — it does
    # not reopen for an arriving ALIVE — until its expiry (tracked in
    # the cell's deadline lane) passes.  Static 0 compiles this out.
    suppress = None
    if params.dead_suppress_rounds > 0:
        suppress = ((status == records.DEAD)
                    & (state.suspect_deadline != INT32_MAX)
                    & (round_idx < state.suspect_deadline))
    eb = params.epoch_bits
    if eb:
        new_status, new_inc, new_epoch, changed = delivery.merge_inbox(
            status, inc, inbox, inbox_alive, fmt=params.wire_format,
            suppress=suppress, entry_epoch=epoch, epoch_bits=eb,
            epoch_guard=params.epoch_guard,
        )
    else:
        new_epoch = None
        new_status, new_inc, changed = delivery.merge_inbox(
            status, inc, inbox, inbox_alive, fmt=params.wire_format,
            suppress=suppress,
        )

    # Self-refutation (updateMembership about-self branch, :488-509): if the
    # inbound winner about ME overrides my ALIVE@self_inc record, bump to
    # max(inc)+1 and gossip the refutation (spread reset via `changed`).
    win_status, win_inc = delivery.unpack_record(
        inbox, fmt=params.wire_format, epoch_bits=eb
    )
    self_overridden = is_self & records.is_overrides_array(
        win_status, win_inc, records.ALIVE, state.self_inc[:, None]
    )
    if eb and params.epoch_guard:
        # Identity check: a record about MY SLOT at another epoch is not
        # about ME — a new member must not burn incarnations refuting
        # the PREVIOUS occupant's death notice (the naive-reuse arm
        # deliberately omits this, measuring exactly that burn).
        win_ep = delivery.unpack_epoch(inbox, fmt=params.wire_format,
                                       epoch_bits=eb)
        self_overridden = self_overridden & (
            win_ep == jnp.asarray(own_epoch, jnp.int32)[:, None]
        )
    refuted = jnp.any(self_overridden, axis=1)
    # The bump saturates at the wire key's incarnation cap (8191 on the
    # int16 wire): the carry must never hold an incarnation the wire
    # cannot express, or table and wire silently diverge at the merge
    # gate (_wire_inc_sat docstring; the advisor finding at
    # ops/delivery.py:189).
    bumped_inc = jnp.minimum(
        jnp.maximum(
            state.self_inc,
            jnp.max(jnp.where(self_overridden, win_inc, 0), axis=1),
        ) + 1,
        _wire_inc_sat(params),
    )
    new_self_inc = jnp.where(refuted & alive_here, bumped_inc, state.self_inc)
    new_status = jnp.where(is_self, records.ALIVE, new_status)
    new_inc = jnp.where(is_self, new_self_inc[:, None], new_inc)
    changed = jnp.where(is_self, self_overridden & alive_here[:, None], changed)
    if new_epoch is not None:
        # Own cell pinned at the slot's ground-truth epoch (a member
        # always knows its own identity; the world schedule is the
        # authority, never the wire).
        new_epoch = jnp.where(
            is_self, jnp.asarray(own_epoch, jnp.int32)[:, None], new_epoch
        )

    # Suspicion timers (scheduleSuspicionTimeoutTask / cancel,
    # MembershipProtocolImpl.java:518-523,590-606).  ``computeIfAbsent``
    # semantics: an accepted SUSPECT update does NOT reset a pending timer;
    # any accepted non-SUSPECT update cancels it.
    no_timer = state.suspect_deadline == INT32_MAX
    if suppress is not None:
        # With suppression on, a DEAD cell's deadline lane holds the
        # suppression expiry, not a suspicion timer — a reopened cell
        # going straight to SUSPECT must still arm a fresh timer.
        no_timer = no_timer | (status == records.DEAD)
    # Lifeguard LHA Suspicion (models/lifeguard.py): the deadline an
    # observer arms scales with its own health multiplier and the
    # current live count; lhm=1 arms exactly the base schedule.
    if params.lhm_max > 0:
        n_live = jnp.sum(world.alive_at(round_idx), dtype=jnp.int32)
        armed_rounds = lifeguard.suspicion_deadline_rounds(
            kn.suspicion_rounds, state.lhm, n_live, params.n_members
        )[:, None]
    else:
        armed_rounds = kn.suspicion_rounds
    start_timer = changed & (new_status == records.SUSPECT) & no_timer
    cancel_timer = changed & (new_status != records.SUSPECT)
    if suppress is not None:
        # An accepted DEAD record must not clear the cell's suppression
        # expiry (set below); only live-record acceptance cancels.
        cancel_timer = cancel_timer & (new_status != records.DEAD)
    deadline = jnp.where(
        start_timer,
        round_idx + armed_rounds,
        jnp.where(cancel_timer, INT32_MAX, state.suspect_deadline),
    )
    # Timer fires -> DEAD at the same incarnation (onSuspicionTimeout,
    # :608-618); the tombstone spreads its death notice.
    fired = (new_status == records.SUSPECT) & (round_idx >= deadline)
    new_status = jnp.where(fired, records.DEAD, new_status)
    deadline = jnp.where(fired, INT32_MAX, deadline)
    changed = changed | fired
    if suppress is not None:
        # Arm/refresh the suppression expiry on every newly stored (or
        # re-armed) tombstone: accepted DEAD records and fired timers
        # (``changed`` already includes ``fired`` by this point).
        became_dead = (new_status == records.DEAD) & changed
        deadline = jnp.where(
            became_dead, round_idx + knob_dead_suppress(kn, params), deadline
        )

    # Crashed/left nodes are frozen (a stopped JVM): no state updates.
    frozen = ~alive_here[:, None]
    new_status = jnp.where(frozen, status, new_status)
    new_inc = jnp.where(frozen, inc, new_inc)
    deadline = jnp.where(frozen, state.suspect_deadline, deadline)
    changed = changed & ~frozen
    if new_epoch is not None:
        new_epoch = jnp.where(frozen, epoch, new_epoch)

    spread_until = jnp.where(
        changed, round_idx + 1 + params.periods_to_spread, state.spread_until
    )

    g_infected, g_spread_until = state.g_infected, state.g_spread_until
    if g_delivered is not None:
        newly_g = g_delivered & ~g_infected
        g_infected = g_infected | g_delivered
        g_spread_until = jnp.where(
            newly_g, round_idx + 1 + params.periods_to_spread, g_spread_until
        )
        # Crashed rows are frozen like the rest of the carry.
        g_infected = jnp.where(frozen[:, :1], state.g_infected, g_infected)
        g_spread_until = jnp.where(frozen[:, :1], state.g_spread_until,
                                   g_spread_until)

    # Lifeguard LHM transition (models/lifeguard.update): the refuted
    # bump plus the FD phase's probe evidence, clamped; frozen members
    # keep their multiplier (handled inside update via alive_here).
    new_lhm = state.lhm
    if params.lhm_max > 0 and lhm_signals is not None:
        probe_fail, probe_clean = lhm_signals
        new_lhm = lifeguard.update(
            state.lhm, probe_fail, probe_clean, refuted & alive_here,
            alive_here, knob_lhm_cap(kn, params),
        )

    # Metadata LWW merge (models/metadata.py): gated on the receiver's
    # POST-merge identity beliefs, so a round that both learns a slot's
    # new epoch and delivers its fresh config accepts the config (and
    # zeroes the stale words) in the same round.
    new_md, new_md_spread = state.md, state.md_spread
    if params.metadata_keys > 0 and md_delivered is not None:
        new_md, new_md_spread = metadata.merge(
            state.md, state.md_spread, md_delivered, round_idx, params,
            is_self,
            (new_epoch if new_epoch is not None else None),
            ~alive_here,
        )

    new_state = SwimState(
        status=new_status.astype(jnp.int8),
        inc=new_inc.astype(jnp.int32),
        spread_until=spread_until.astype(jnp.int32),
        suspect_deadline=deadline.astype(jnp.int32),
        self_inc=new_self_inc.astype(jnp.int32),
        inbox_ring=state.inbox_ring if inbox_ring is None else inbox_ring,
        flag_ring=state.flag_ring if flag_ring is None else flag_ring,
        g_infected=g_infected,
        g_spread_until=g_spread_until,
        g_ring=state.g_ring if g_ring is None else g_ring,
        lhm=new_lhm,
        epoch=(state.epoch if new_epoch is None
               else new_epoch.astype(jnp.int32)),
        md=new_md, md_spread=new_md_spread,
    )
    return new_state, refuted


def _send_components(state, status, inc, round_idx, params, world,
                     node_ids, is_self, epoch=None):
    """(record_keys, hot, syncable) — one payload, two transmit masks.

    Gossip carries hot records (changed within the spread window; DEAD
    tombstones transmit their death notice, GossipProtocolImpl.java:239-250).
    A gracefully leaving node's final-round gossip carries its own DEAD
    record at incarnation+1 (leaveCluster, MembershipProtocolImpl.java:197-206).
    SYNC pushes the full row minus tombstones (the reference table holds no
    DEAD records, so SYNC never carries them) — masked on the sender's
    TABLE status, not the key's DEAD bit: a leaver's key carries DEAD@inc+1
    while its table row is pinned ALIVE, and that record must still sync.

    ``epoch`` (open-world plane): the PINNED identity-epoch matrix
    (``_round_context``'s ``epoch``) — every transmitted key carries the
    epoch of the record it describes, including the leaver's own DEAD
    notice (the leaver dies at its own current epoch).
    """
    leaving_now = (world.leave_at[node_ids] == round_idx)[:, None] & is_self
    hot = (status != records.ABSENT) & (round_idx < state.spread_until)
    hot = hot | leaving_now
    wf = params.wire_format
    eb = params.epoch_bits
    record_keys = delivery.pack_record(status, inc, fmt=wf,
                                       epoch=epoch, epoch_bits=eb)
    leave_key = delivery.pack_record(
        jnp.int8(records.DEAD), state.self_inc[:, None] + 1, fmt=wf,
        epoch=epoch, epoch_bits=eb,
    )
    record_keys = jnp.where(leaving_now, leave_key, record_keys)
    syncable = status != records.DEAD
    return record_keys, hot, syncable


def _seed_anti_entropy(status, sync_keys, inbox, inbox_alive, sync_round,
                       round_idx, params, kn, world, node_ids, alive_here,
                       alive, part, key, axis_name=None):
    """Joiner ⇄ seed SYNC round trip — the reference's join protocol.

    The reference's doSync picks its target from seeds ∪ live members and
    the receiver REPLIES with its full table
    (MembershipProtocolImpl.java:298-314 candidate rule, :320-331,346-367
    onSync -> merge -> SYNC_ACK; start0's initial sync is the same
    exchange, :216-251).  The tick's regular SYNC channel is push-only at
    a uniform target — distribution-symmetric in steady state but far too
    slow during cold start, where a joiner's uniform draw almost never
    lands on a known member.  This channel restores the reference's
    behavior exactly where it differs: on sync rounds, every live member
    that still has ABSENT entries pushes its row to one random configured
    seed and receives the seed's row back in the same round (the
    reference's request/reply both complete well within one gossip
    period).  Runs in FULL-VIEW mode with seeds configured (the same
    gate as every contact rule — join semantics are a full-view concern;
    focal cold starts stay on the statistical push-only path); inert in
    steady state (no ABSENT entries -> no traffic), so warm-state traces
    are unchanged.

    Deviations, documented: the ack carries the seed's PRE-merge row
    (one round staler than the reference's post-merge reply — the pusher
    already holds everything it pushed); delivery is same-round even
    under max_delay_rounds (sync_timeout >> link delays in the reference
    regime).  Sharded: the seed's row and its inbox contribution combine
    with one [K]-vector pmax per seed over ``axis_name``.

    Returns (inbox, inbox_alive, sent_by_node, lost_by_node) — the
    counter vectors feed SwimParams.link_counters accounting (pushes at
    the pushers, acks at the seed).
    """
    n_seeds = world.seed_ids.shape[0]
    wf = params.wire_format
    no_msg = delivery.no_message(fmt=wf)
    has_absent = jnp.any(status == records.ABSENT, axis=1)
    pusher = sync_round & alive_here & has_absent
    k_sel, k_push, k_ack = jax.random.split(key, 3)
    sel = jax.random.randint(k_sel, (node_ids.shape[0],), 0, n_seeds)
    sent_vec = jnp.zeros(node_ids.shape, dtype=jnp.int32)
    lost_vec = jnp.zeros(node_ids.shape, dtype=jnp.int32)

    def pmax(x):
        return jax.lax.pmax(x, axis_name) if axis_name is not None else x

    for si in range(n_seeds):                       # S is static and small
        sid = world.seed_ids[si]
        mask_i = pusher & (sel == si) & (node_ids != sid)
        loss_push, _ = link_eval(world.faults, round_idx, node_ids, sid,
                                 kn.loss_probability, params.mean_delay_ms)
        part_ok_p = part[node_ids] == part[sid]
        wire_drop_push = prng.bernoulli_mask(
            jax.random.fold_in(k_push, si), loss_push, node_ids.shape
        )
        ok_push = mask_i & alive[sid] & part_ok_p & ~wire_drop_push
        # Seed-side merge of all arriving pushes: a one-hot row write of
        # the columnwise max over pushers (no scatter, no gather).
        is_seed_row = (node_ids == sid)[:, None]
        contribution = pmax(jnp.max(
            jnp.where(ok_push[:, None], sync_keys, no_msg), axis=0
        ))
        inbox = jnp.maximum(
            inbox, jnp.where(is_seed_row, contribution[None, :], no_msg)
        )
        if inbox_alive is not None:
            inbox_alive |= is_seed_row & delivery.is_alive_key(
                contribution, fmt=wf)[None, :]
        # The ack: the seed's syncable row back to every successful
        # pusher, over the reverse link.
        seed_row = pmax(jnp.max(
            jnp.where(is_seed_row, sync_keys, no_msg), axis=0
        ))
        loss_ack, _ = link_eval(world.faults, round_idx, sid, node_ids,
                                kn.loss_probability, params.mean_delay_ms)
        wire_drop_ack = prng.bernoulli_mask(
            jax.random.fold_in(k_ack, si), loss_ack, node_ids.shape
        )
        ok_ack = ok_push & ~wire_drop_ack
        inbox = jnp.maximum(
            inbox, jnp.where(ok_ack[:, None], seed_row[None, :], no_msg)
        )
        if inbox_alive is not None:
            inbox_alive |= ok_ack[:, None] & delivery.is_alive_key(
                seed_row, fmt=wf)[None, :]
        # Wire accounting (SwimParams.link_counters): pushes at the
        # pushers, acks at the seed.
        at_seed = node_ids == sid
        sent_vec += mask_i.astype(jnp.int32) + jnp.where(
            at_seed, jnp.sum(ok_push, dtype=jnp.int32), 0
        )
        lost_vec += (mask_i & (wire_drop_push | ~part_ok_p)
                     ).astype(jnp.int32) + jnp.where(
            at_seed, jnp.sum(ok_push & wire_drop_ack, dtype=jnp.int32), 0
        )
    return inbox, inbox_alive, sent_vec, lost_vec


def _send_payloads(state, status, inc, round_idx, params, world,
                   node_ids, is_self, epoch=None):
    """(gossip_keys, sync_keys) — the masked per-channel payload matrices
    (scatter mode materializes both; shift mode ships the shared key buffer
    plus the int8 masks instead — see _tick_shift)."""
    record_keys, hot, syncable = _send_components(
        state, status, inc, round_idx, params, world, node_ids, is_self,
        epoch=epoch,
    )
    no_msg = delivery.no_message(fmt=params.wire_format)
    gossip_keys = jnp.where(hot, record_keys, no_msg)
    sync_keys = jnp.where(syncable, record_keys, no_msg)
    return gossip_keys, sync_keys


# --------------------------------------------------------------------------
# Scatter-mode tick body (exact uniform target draws)
# --------------------------------------------------------------------------


def _scatter_send_phase(state, status, inc, round_idx, params, kn, world,
                        alive, part, node_ids, alive_here, part_here,
                        is_self, fd_round, sync_round, gate_contacts,
                        known_live, is_seed, keys, offset, k_channel=None,
                        epoch=None):
    """Phases 1-3 of the scatter tick: FD probe verdicts + gossip/SYNC
    sends — everything up to (but excluding) the cross-device inbox
    combine.  Returns a dict of per-channel payloads/targets/drop masks
    plus the send-side signals, consumed either serially (combine in
    the same round body — ``_tick_scatter``) or double-buffered (the
    combine deferred to the NEXT round body — ``swim_tick_send`` /
    ``swim_tick_recv``, the pipelined ICI path of parallel/mesh.py).

    ``k_channel`` is the round's UN-device-folded channel key
    (``_round_context``'s ``k_shifts``) — the anti-entropy plane's
    shared partner offset must agree across shards; required when
    ``params.sync_interval > 0``.
    """
    n, k = params.n_members, params.n_subjects
    n_local = status.shape[0]
    (k_ping_t, k_ping_net, k_proxy, k_proxy_net, k_gossip_t, k_gossip_drop,
     k_sync_t, k_sync_drop) = keys

    def same_partition(a_ids, b_ids):
        return part[a_ids] == part[b_ids]

    # ---- Phase 1: failure detector probe --------------------------------
    if params.ping_known_only:
        # Uniform among known live-record subjects (FailureDetectorImpl
        # pingMembers list, :48-49) — exact in full-view mode.
        eligible = (~is_self) & (
            (status == records.ALIVE) | (status == records.SUSPECT)
        )
        slot, has_target = prng.choose_eligible(k_ping_t, eligible)
        ping_target = world.subject_ids[slot]               # [n_local] node ids
    else:
        # Focal mode: probe the whole cluster uniformly; only probes that
        # land on tracked subjects affect tracked state.
        ping_target = prng.targets_excluding_self(
            k_ping_t, n_local, n, 1, sender_offset=offset
        )[:, 0]
        slot = world.slot_of_node[ping_target]              # -1 = untracked
        has_target = slot >= 0
        eligible_t = (
            jnp.take_along_axis(status, jnp.maximum(slot, 0)[:, None], 1)[:, 0]
        )
        has_target &= (eligible_t == records.ALIVE) | (eligible_t == records.SUSPECT)

    t = ping_target
    # Lifeguard LHA Probe (models/lifeguard.py): a member's effective
    # probe interval and timeout scale with its own health multiplier —
    # the probe gate suppresses the send (1/lhm probability per fd
    # round) and the chain budgets stretch.  Compiled out entirely at
    # lhm_max=0; at lhm=1 the gate always passes and the budgets equal
    # the base values, so healthy runs stay bit-identical.
    ping_budget, ping_req_budget, lhm_gate = lifeguard.lha_probe_setup(
        params, state.lhm, k_ping_net, n_local,
        ping_timeout_ms=knob_ping_timeout(kn, params))
    if lhm_gate is None:
        ping_budget = knob_ping_timeout(kn, params)
        ping_req_budget = params.ping_interval_ms - ping_budget
    # Direct ping: 2 hops within ping_timeout (FailureDetectorImpl.java:128-176).
    loss_it, delay_it = link_eval(world.faults, round_idx, node_ids, t,
                                  kn.loss_probability, params.mean_delay_ms)
    loss_ti, delay_ti = link_eval(world.faults, round_idx, t, node_ids,
                                  kn.loss_probability, params.mean_delay_ms)
    direct_ok = (
        _chain_ok(k_ping_net, [loss_it, loss_ti], [delay_it, delay_ti],
                  ping_budget, (n_local,))
        & alive[t] & same_partition(node_ids, t)
    )
    # Ping-req through R proxies: 4 hops within (ping_interval - ping_timeout)
    # (:178-213; transit relay :258-315).
    r_proxies = params.ping_req_members
    proxies = prng.targets_excluding_self(
        k_proxy, n_local, n, r_proxies, sender_offset=offset
    )
    hop_pairs = [
        (node_ids[:, None], proxies),       # issuer -> proxy
        (proxies, t[:, None]),              # proxy  -> target (transit ping)
        (t[:, None], proxies),              # target -> proxy (ack)
        (proxies, node_ids[:, None]),       # proxy  -> issuer (transit ack)
    ]
    hop_losses, hop_delays = [], []
    for src, dst in hop_pairs:
        lo, de = link_eval(world.faults, round_idx, src, dst,
                           kn.loss_probability, params.mean_delay_ms)
        hop_losses.append(lo)
        hop_delays.append(de)
    proxy_ok = (
        _chain_ok(k_proxy_net, hop_losses, hop_delays,
                  (ping_req_budget[:, None] if lhm_gate is not None
                   else ping_req_budget),
                  (n_local, r_proxies))
        & alive[proxies] & alive[t][:, None]
        & same_partition(node_ids[:, None], proxies)
        & same_partition(proxies, t[:, None])
        & (proxies != t[:, None])
    )
    ack_ok = direct_ok | jnp.any(proxy_ok, axis=1)
    probe_active = fd_round & has_target & alive_here       # [n_local]
    if lhm_gate is not None:
        probe_active = probe_active & lhm_gate
    verdict_suspect = probe_active & ~ack_ok
    verdict_alive = probe_active & ack_ok
    # True wire-message accounting (the reference logs per-period probe
    # counts, FailureDetectorImpl.java:148,156-164): every live member
    # issues one PING per fd round — in focal mode regardless of whether
    # the target is a *tracked* subject (``probe_active`` gates only the
    # verdict bookkeeping, not the send).  Full-view senders probe only
    # members they know live (the reference's pingMembers list).
    probes_sent = (probe_active if params.ping_known_only
                   else fd_round & alive_here)
    if lhm_gate is not None and not params.ping_known_only:
        probes_sent = probes_sent & lhm_gate
    ping_req_launches = probes_sent & ~direct_ok

    # SUSPECT verdict -> local record (SUSPECT, entry inc) for the target
    # slot (onFailureDetectorEvent, MembershipProtocolImpl.java:392-397).
    slot_safe = jnp.maximum(slot, 0)
    fd_slot_onehot = (
        jnp.arange(k, dtype=jnp.int32)[None, :] == slot_safe[:, None]
    )
    wf = params.wire_format
    no_msg = delivery.no_message(fmt=wf)
    # The FD verdict is about the record the observer HOLDS — same
    # incarnation, same identity epoch (a stale-epoch SUSPECT verdict
    # then drops at every guarded merge gate, including the observer's
    # own, exactly like any other stale-identity record).
    fd_entry_epoch = None
    if params.epoch_bits:
        fd_entry_epoch = jnp.take_along_axis(
            epoch, slot_safe[:, None], 1)[:, 0]
    fd_suspect_key = delivery.pack_record(
        jnp.int8(records.SUSPECT),
        jnp.take_along_axis(inc, slot_safe[:, None], 1)[:, 0],
        fmt=wf, epoch=fd_entry_epoch,
        epoch_bits=params.epoch_bits,
    )
    fd_inbox = jnp.where(
        fd_slot_onehot & verdict_suspect[:, None],
        fd_suspect_key[:, None],
        no_msg,
    )

    # ALIVE verdict on a suspected entry -> push the suspect record to the
    # member itself so it can refute (the reference sends SYNC there,
    # :379-391; the refutation travels back via gossip).
    entry_t_status = jnp.take_along_axis(status, slot_safe[:, None], 1)[:, 0]
    push_refute = verdict_alive & (entry_t_status == records.SUSPECT)

    # ---- Phase 2 + 3: gossip and SYNC sends ------------------------------
    gossip_keys, sync_keys = _send_payloads(
        state, status, inc, round_idx, params, world, node_ids, is_self,
        epoch=epoch,
    )

    gossip_targets = prng.targets_excluding_self(
        k_gossip_t, n_local, n, params.fanout, sender_offset=offset
    )
    # Named components (vs one fused mask): the link_counters substrate
    # attributes in-flight drops (wire loss, partition walls) separately
    # from never-sent (dead sender, contact gate) and not-delivered
    # (crashed receiver) — the reference's sent/lost split.
    part_ok_g = same_partition(node_ids[:, None], gossip_targets)
    contact_ok_g = (known_live(gossip_targets) | is_seed(gossip_targets)
                    if gate_contacts
                    else jnp.ones((n_local, params.fanout), dtype=jnp.bool_))
    send_ok = (alive_here[:, None] & alive[gossip_targets] & part_ok_g
               & contact_ok_g)
    loss_g, delay_g = link_eval(world.faults, round_idx, node_ids[:, None],
                                gossip_targets, kn.loss_probability,
                                params.mean_delay_ms)
    wire_drop_g = prng.bernoulli_mask(
        k_gossip_drop, loss_g, (n_local, params.fanout)
    )
    chan_off = (
        jnp.arange(params.fanout, dtype=jnp.int32)[None, :] >= kn.fanout
    )
    gossip_drop = wire_drop_g | ~send_ok | chan_off

    # SYNC: full-row push to one random member (doSync,
    # MembershipProtocolImpl.java:298-314).
    sync_target = prng.targets_excluding_self(
        k_sync_t, n_local, n, 1, sender_offset=offset
    )
    # FD's alive-on-suspected push reuses the sync channel, aimed at the
    # suspected member itself.
    # The refute push rides the sync channel (it IS a SYNC to the
    # suspected member, MembershipProtocolImpl.java:379-391), so disabling
    # the channel (sync_every <= 0) disables it too — UNLESS the
    # Lifeguard buddy system is on (static lhm_max > 0): there the
    # suspected member learns of its suspicion in the probe's ACK path
    # itself (models/lifeguard.py), independent of the membership SYNC.
    if params.lhm_max == 0:
        push_refute = push_refute & (kn.sync_every > 0)
    sync_target = jnp.where(push_refute[:, None], t[:, None], sync_target)
    do_sync = (sync_round & alive_here) | push_refute
    if gate_contacts:
        do_sync &= (
            known_live(sync_target)[:, 0] | is_seed(sync_target)[:, 0]
            | push_refute
        )
    loss_s, delay_s = link_eval(world.faults, round_idx, node_ids,
                                sync_target[:, 0], kn.loss_probability,
                                params.mean_delay_ms)
    part_ok_s = same_partition(node_ids, sync_target[:, 0])
    wire_drop_s = prng.bernoulli_mask(k_sync_drop, loss_s, (n_local,))
    sync_ok = alive[sync_target[:, 0]] & part_ok_s & ~wire_drop_s
    sync_drop = (~(do_sync & sync_ok))[:, None]

    alive_flags = delivery.is_alive_key(gossip_keys, fmt=wf)
    sync_alive_flags = delivery.is_alive_key(sync_keys, fmt=wf)
    hot_any = jnp.any(gossip_keys >= 0, axis=1)
    hot_g = None
    if params.n_user_gossips > 0:
        hot_g = (state.g_infected & alive_here[:, None]
                 & (round_idx < state.g_spread_until))
        # A wire gossip message exists when EITHER family has content.
        hot_any = hot_any | jnp.any(hot_g, axis=1)

    # ---- Anti-entropy plane: the paired full-table exchange --------------
    # (models/sync.py module docstring).  Two extra scatter channels with
    # deterministic shared-offset targets delivering the SAME sync_keys
    # payload; they fold into the same contribution buffer as the regular
    # channels (_scatter_channel_bufs), so the pipelined path carries
    # them for free and the sharded combine stays one pmax per buffer.
    ae = {}
    if params.sync_interval > 0:
        ae_due = sync_plane.due(round_idx, params.sync_interval)
        s_off = sync_plane.partner_offset(k_channel, n)
        ae_targets = sync_plane.exchange_targets(node_ids, s_off, n)
        ae_do = ae_due & alive_here
        ae_contact_ok = (known_live(ae_targets) | is_seed(ae_targets)
                         if gate_contacts
                         else jnp.ones((n_local, 2), dtype=jnp.bool_))
        loss_ae, _ = link_eval(world.faults, round_idx, node_ids[:, None],
                               ae_targets, kn.loss_probability,
                               params.mean_delay_ms)
        ae_wire_drop = prng.bernoulli_mask(
            sync_plane.drop_key(k_sync_drop), loss_ae, (n_local, 2)
        )
        ae_part_ok = same_partition(node_ids[:, None], ae_targets)
        ae_ok = (alive[ae_targets] & ae_part_ok & ae_contact_ok
                 & ~ae_wire_drop)
        ae = dict(
            ae_targets=ae_targets,
            ae_drop=~(ae_do[:, None] & ae_ok),
            ae_attempt=ae_do[:, None] & ae_contact_ok,
            ae_wire_drop=ae_wire_drop, ae_part_ok=ae_part_ok,
            messages_anti_entropy=sync_plane.sent_count(ae_due, alive_here),
        )
    # Lifeguard LHM transition evidence (models/lifeguard.update): a
    # clean direct ACK decays, a timed-out or proxy-rescued probe bumps.
    lg = {}
    if params.lhm_max > 0:
        lg = dict(lhm_fail=probes_sent & ~direct_ok,
                  lhm_clean=probes_sent & direct_ok)
    # Metadata plane payloads (models/metadata.py): hot rows piggyback
    # the gossip + sync channels, the full table rides the anti-entropy
    # exchange — same targets, same drop masks, no new draws (the
    # structural metadata_keys=0 bit-identity argument).
    mdp = {}
    if params.metadata_keys > 0:
        mdp = dict(
            md_hot=metadata.hot_payload(state.md, state.md_spread,
                                        round_idx),
            md_full=metadata.full_payload(state.md),
        )
    return dict(
        **ae,
        **lg,
        **mdp,
        gossip_keys=gossip_keys, sync_keys=sync_keys,
        gossip_targets=gossip_targets, gossip_drop=gossip_drop,
        sync_target=sync_target, sync_drop=sync_drop,
        alive_flags=alive_flags, sync_alive_flags=sync_alive_flags,
        fd_inbox=fd_inbox, hot_any=hot_any, hot_g=hot_g,
        delay_g=delay_g, delay_s=delay_s,
        probe_active=probe_active, probes_sent=probes_sent,
        ping_req_launches=ping_req_launches,
        # link_counters attribution components (single-device serial path).
        contact_ok_g=contact_ok_g, chan_off=chan_off,
        wire_drop_g=wire_drop_g, part_ok_g=part_ok_g,
        wire_drop_s=wire_drop_s, part_ok_s=part_ok_s, do_sync=do_sync,
        k_gossip_drop=k_gossip_drop, k_sync_drop=k_sync_drop,
    )


def _scatter_channel_bufs(s, params, gossip_extra_drop, sync_extra_drop,
                          ae_suppress=False, channel_split=False):
    """The UNCOMBINED global-height inbox contribution of one scatter
    round: the max-folded packed-key buffer (``[N, K]``), plus — on the
    legacy two-buffer wire (``params.fused_wire`` False) — the int8
    ALIVE-flag buffer.  The serial tick pmax-combines these in the same
    round body; the pipelined path carries them to the next one.

    Under the FUSED wire (the default) the flag buffer is None: the
    ALIVE flag lives in the key word's own bits and the merge gate
    derives it from the folded winner (delivery.is_alive_key), so the
    round moves ONE buffer — half the scatter folds, half the
    cross-device collectives (SwimParams.fused_wire docstring).

    The anti-entropy plane's paired exchange (``sync_interval > 0``)
    folds its two channels into the SAME buffers — same payload as the
    sync channel, deterministic shared-offset targets — so it adds no
    collectives and rides the pipelined double-buffer unchanged.  Its
    delivery is same-round only (models/sync.py docstring), so the
    delay path passes ``ae_suppress=True`` for every bin after 0.

    Returns ``(buf, fbuf, md_buf)``.  ``md_buf`` [N, K*M] int32 (fill
    -1) is the metadata plane's contribution (``metadata_keys > 0``,
    else None): hot rows through the gossip + sync channels, the full
    table through the anti-entropy channels — the identical targets and
    drop masks, folded with the same associative max.  Metadata is
    same-round only like the anti-entropy plane, so only bin 0 reads it.

    ``channel_split=True`` (the provenance plane's exposure,
    SwimParams.provenance) appends a fourth element: the
    ``(gossip_buf, sync_family_buf)`` per-channel components the
    combined ``buf`` is the max of — the SAME scatters, kept apart so
    the attribution cascade can name the winning channel at zero extra
    fold cost (int max is associative, so building ``buf`` from the
    split components is value-identical to the unsplit fold).
    """
    n = params.n_members
    g_drop = s["gossip_drop"] | gossip_extra_drop
    s_drop = s["sync_drop"] | sync_extra_drop
    if channel_split:
        g_buf = delivery.scatter_max(s["gossip_keys"], s["gossip_targets"],
                                     g_drop, n)
        s_fam = delivery.scatter_max(s["sync_keys"], s["sync_target"],
                                     s_drop, n)
        if params.sync_interval > 0 and not ae_suppress:
            s_fam = jnp.maximum(
                s_fam,
                delivery.scatter_max(s["sync_keys"], s["ae_targets"],
                                     s["ae_drop"], n),
            )
        buf = jnp.maximum(g_buf, s_fam)
    else:
        buf = jnp.maximum(
            delivery.scatter_max(s["gossip_keys"], s["gossip_targets"],
                                 g_drop, n),
            delivery.scatter_max(s["sync_keys"], s["sync_target"],
                                 s_drop, n),
        )
        if params.sync_interval > 0 and not ae_suppress:
            buf = jnp.maximum(
                buf,
                delivery.scatter_max(s["sync_keys"], s["ae_targets"],
                                     s["ae_drop"], n),
            )
    md_buf = None
    if params.metadata_keys > 0:
        md_buf = jnp.maximum(
            delivery.scatter_max(s["md_hot"], s["gossip_targets"],
                                 g_drop, n),
            delivery.scatter_max(s["md_hot"], s["sync_target"], s_drop, n),
        )
        if params.sync_interval > 0 and not ae_suppress:
            md_buf = jnp.maximum(
                md_buf,
                delivery.scatter_max(s["md_full"], s["ae_targets"],
                                     s["ae_drop"], n),
            )
    if params.fused_wire:
        if channel_split:
            return buf, None, md_buf, (g_buf, s_fam)
        return buf, None, md_buf
    fbuf = (
        delivery.scatter_or(s["alive_flags"], s["gossip_targets"],
                            g_drop, n)
        | delivery.scatter_or(s["sync_alive_flags"], s["sync_target"],
                              s_drop, n)
    )
    if params.sync_interval > 0 and not ae_suppress:
        fbuf = fbuf | delivery.scatter_or(
            s["sync_alive_flags"], s["ae_targets"], s["ae_drop"], n
        )
    if channel_split:
        return buf, fbuf.astype(jnp.int8), md_buf, (g_buf, s_fam)
    return buf, fbuf.astype(jnp.int8), md_buf


def _scatter_send_aux(s, params):
    """Send-side counters of one scatter round — merge-independent, so
    the pipelined path can carry them across the round boundary and
    psum them together with the round's metrics one body later."""
    aux = dict(
        messages_gossip=jnp.sum(
            s["hot_any"][:, None] & ~s["gossip_drop"], dtype=jnp.int32
        ),
        messages_ping=jnp.sum(s["probe_active"], dtype=jnp.int32),
        messages_ping_sent=jnp.sum(s["probes_sent"], dtype=jnp.int32),
        messages_ping_req_sent=(
            jnp.sum(s["ping_req_launches"], dtype=jnp.int32)
            * params.ping_req_members
        ),
    )
    if params.sync_interval > 0:
        aux["messages_anti_entropy"] = s["messages_anti_entropy"]
    return aux


def _tick_scatter(state, status, inc, round_idx, params, kn, world,
                  alive, part, node_ids, alive_here, part_here, is_self,
                  fd_round, sync_round, gate_contacts, known_live, is_seed,
                  keys, offset, axis_name, k_channel=None, epoch=None,
                  own_epoch=None):
    n, k = params.n_members, params.n_subjects
    n_local = status.shape[0]
    s = _scatter_send_phase(state, status, inc, round_idx, params, kn,
                            world, alive, part, node_ids, alive_here,
                            part_here, is_self, fd_round, sync_round,
                            gate_contacts, known_live, is_seed, keys,
                            offset, k_channel=k_channel, epoch=epoch)
    delay_g, delay_s = s["delay_g"], s["delay_s"]

    def combine_max(buf):
        """Cross-device inbox combine + own-row slice."""
        if axis_name is not None:
            buf = jax.lax.pmax(buf, axis_name)
        if n_local == n and axis_name is None:
            return buf
        return jax.lax.dynamic_slice_in_dim(buf, offset, n_local, axis=0)

    # Accumulate all send channels into one global-height contribution,
    # then one cross-device combine per delay bin (a single pmax per round
    # in the default max_delay_rounds=0 configuration; the delay path is a
    # small-N validation mode, so its extra per-bin combines are
    # acceptable — the 1M shift path bins receiver-side instead).
    inbox_now, flags_now, g_now, ring, fring, gring, slot0 = _ring_open(
        state, params, round_idx, with_flags=not params.fused_wire
    )

    def channel_bufs(gossip_extra_drop, sync_extra_drop, ae_suppress=False):
        buf, fbuf, md_buf = _scatter_channel_bufs(s, params,
                                                  gossip_extra_drop,
                                                  sync_extra_drop,
                                                  ae_suppress=ae_suppress)
        # Fused wire: ONE combined buffer per bin (fbuf is None — the
        # merge gate derives the ALIVE flag from the winner key).
        return (combine_max(buf),
                None if fbuf is None else combine_max(fbuf),
                None if md_buf is None else combine_max(md_buf))

    prov_g = prov_s = None
    if params.max_delay_rounds == 0:
        if params.provenance:
            # channel_split: the combined inbox is rebuilt as
            # max(g_buf, s_fam) from per-channel components (int max is
            # associative, so the folded values are bit-identical to the
            # single-fold path), and the components double as the
            # provenance plane's per-channel evidence — zero extra
            # scatters for attribution.
            buf, fbuf, md_buf, (g_split, s_split) = _scatter_channel_bufs(
                s, params, False, False, channel_split=True)
            inbox = combine_max(buf)
            inbox_alive8 = None if fbuf is None else combine_max(fbuf)
            md_delivered = None if md_buf is None else combine_max(md_buf)
            prov_g = combine_max(g_split)
            prov_s = combine_max(s_split)
        else:
            inbox, inbox_alive8, md_delivered = channel_bufs(False, False)
        inbox_alive = (None if inbox_alive8 is None
                       else inbox_alive8.astype(jnp.bool_))
    else:
        # delay None = statically zero (link_eval docstring): bin 0 always.
        q_g = (jnp.zeros((n_local, params.fanout), jnp.int32)
               if delay_g is None else ring_ops.delay_bins(
                   jax.random.fold_in(s["k_gossip_drop"], 7), delay_g,
                   params.round_ms, params.max_delay_rounds,
                   (n_local, params.fanout)))
        q_s = (jnp.zeros((n_local,), jnp.int32)
               if delay_s is None else ring_ops.delay_bins(
                   jax.random.fold_in(s["k_sync_drop"], 7), delay_s,
                   params.round_ms, params.max_delay_rounds,
                   (n_local,)))[:, None]
        # Metadata is same-round only like the anti-entropy exchange:
        # the bin-0 call below is its one delivery (a delayed message
        # carries membership but not the md piggyback — module
        # docstring deviation; convergence is measured in rounds).
        inbox, inbox_alive8, md_delivered = channel_bufs(q_g != 0,
                                                         q_s != 0)
        inbox = jnp.maximum(inbox, inbox_now)
        inbox_alive = (None if inbox_alive8 is None
                       else inbox_alive8.astype(jnp.bool_) | flags_now)
        d = params.max_delay_rounds + 1
        for j in range(1, d):
            # The anti-entropy exchange is same-round only (bin 0).
            buf_j, fbuf_j, _ = channel_bufs(q_g != j, q_s != j,
                                            ae_suppress=True)
            if fbuf_j is None:
                # Fused wire: the flag ring is dead weight — future
                # flags rederive from the ring's key slots at open time
                # (is_alive_key of the folded winner), so only the key
                # contribution is pushed.
                ring = ring_ops.push_max(ring, (slot0 + j) % d, buf_j)
            else:
                ring, fring = _ring_push(ring, fring, (slot0 + j) % d,
                                         buf_j, fbuf_j.astype(jnp.bool_))

    # FD local verdicts fold into the same inbox (observer-local, no comm).
    inbox = jnp.maximum(inbox, s["fd_inbox"])

    # Joiner <-> seed SYNC round trip (the reference's join protocol;
    # inert once no row holds ABSENT entries).
    ss_sent = ss_lost = jnp.int32(0)
    if gate_contacts:
        inbox, inbox_alive, ss_sent, ss_lost = _seed_anti_entropy(
            status, s["sync_keys"], inbox, inbox_alive, sync_round,
            round_idx, params, kn, world, node_ids, alive_here, alive,
            part, jax.random.fold_in(s["k_sync_drop"], 29),
            axis_name=axis_name,
        )

    if params.fused_wire:
        # The FUSED merge gate: the ALIVE flag of the round's folded
        # winner, derived from the key bits themselves after every fold
        # (channels, delay ring, FD verdicts, seed round trip) — the
        # reference's per-message null-gate applied to the round's one
        # folded message (SwimParams.fused_wire docstring).
        inbox_alive = delivery.is_alive_key(inbox, fmt=params.wire_format)

    # User-gossip bits ride the same gossip channels, targets, and drop
    # masks — one GOSSIP_REQ carries membership records AND user gossips
    # (GossipProtocolImpl.java:211-237).
    g_delivered, g_ring_new = None, None
    if params.n_user_gossips > 0:

        def g_buf(extra_drop):
            gb = delivery.scatter_or(
                s["hot_g"], s["gossip_targets"],
                s["gossip_drop"] | extra_drop, n
            )
            return combine_max(gb.astype(jnp.int8)).astype(jnp.bool_)

        if params.max_delay_rounds == 0:
            g_delivered = g_buf(False)
        else:
            # Same per-message bins as the membership payload (q_g).
            g_delivered = g_buf(q_g != 0) | g_now
            g_ring_new = gring
            d = params.max_delay_rounds + 1
            for j in range(1, d):
                g_ring_new = ring_ops.push_or(
                    g_ring_new, (slot0 + j) % d, g_buf(q_g != j)
                )

    new_state, refuted = _merge_and_timers(
        state, status, inc, inbox, inbox_alive, round_idx, params, kn, world,
        node_ids, alive_here, is_self, inbox_ring=ring, flag_ring=fring,
        g_delivered=g_delivered, g_ring=g_ring_new,
        lhm_signals=((s["lhm_fail"], s["lhm_clean"])
                     if params.lhm_max > 0 else None),
        epoch=epoch, own_epoch=own_epoch, md_delivered=md_delivered,
    )
    aux = dict(
        _scatter_send_aux(s, params),
        refutations=jnp.sum(refuted & alive_here, dtype=jnp.int32),
    )
    if params.metadata_keys > 0:
        # Already globally reduced (one psum inside when sharded) —
        # _round_metrics passes it through without re-summing.
        aux["metadata_divergent"] = metadata.divergent_count(
            new_state.md, node_ids, alive, alive_here, n,
            offset=offset, axis_name=axis_name,
        )
    if params.provenance:
        # Per-channel folded maxima, receiver-side (the provenance
        # plane's evidence — SwimParams.provenance): the SAME scatter
        # components the combined inbox above was built from
        # (channel_split), kept apart per channel so the plane can name
        # the winning one.  No extra scatters: attribution reuses the
        # folds the protocol already paid for, and int-max associativity
        # keeps the combined inbox bit-identical to the single-fold
        # off-switch path.  max_delay_rounds == 0 is validated at
        # construction, so the single-bin folds are the round's
        # complete deliveries.
        g_chan = prov_g
        s_chan = prov_s
        if gate_contacts:
            # Same folded key as the real round trip above -> the same
            # draws -> identical contributions, folded into the SYNC
            # family (the join path IS a SYNC exchange).
            s_chan, _, _, _ = _seed_anti_entropy(
                status, s["sync_keys"], s_chan, None, sync_round,
                round_idx, params, kn, world, node_ids, alive_here,
                alive, part, jax.random.fold_in(s["k_sync_drop"], 29),
                axis_name=axis_name,
            )
        aux["_provenance"] = dict(
            fd=s["fd_inbox"], gossip=g_chan, sync=s_chan,
            ping_req=s["ping_req_launches"],
        )
    if params.link_counters:
        # Per-sender wire accounting (SwimParams.link_counters docstring).
        # A gossip message exists per active channel when the sender is
        # live, has hot records, and its peer-list gate admits the target.
        g_attempt = ((alive_here & s["hot_any"])[:, None]
                     & s["contact_ok_g"] & ~s["chan_off"])
        g_lost = g_attempt & (s["wire_drop_g"] | ~s["part_ok_g"])
        s_lost = s["do_sync"] & (s["wire_drop_s"] | ~s["part_ok_s"])
        aux["sent_by_node"] = (
            jnp.sum(g_attempt, axis=1, dtype=jnp.int32)
            + s["do_sync"].astype(jnp.int32)
            + s["probes_sent"].astype(jnp.int32)
            + s["ping_req_launches"].astype(jnp.int32)
            * params.ping_req_members
            + ss_sent
        )
        aux["lost_by_node"] = (
            jnp.sum(g_lost, axis=1, dtype=jnp.int32)
            + s_lost.astype(jnp.int32) + ss_lost
        )
        if params.sync_interval > 0:
            # Anti-entropy exchange accounting: both directions count as
            # sends at the sender; in-flight drops (wire loss, partition
            # walls) count as lost, matching the gossip/sync attribution.
            ae_lost = s["ae_attempt"] & (s["ae_wire_drop"]
                                         | ~s["ae_part_ok"])
            aux["sent_by_node"] += jnp.sum(s["ae_attempt"], axis=1,
                                           dtype=jnp.int32)
            aux["lost_by_node"] += jnp.sum(ae_lost, axis=1,
                                           dtype=jnp.int32)
    return new_state, aux


# --------------------------------------------------------------------------
# Pipelined delivery: the scatter tick split across the round boundary
# --------------------------------------------------------------------------


def pipelined_delivery_unsupported_reason(params: SwimParams,
                                          world: SwimWorld) -> Optional[str]:
    """Why this config cannot run the double-buffered (pipelined) inbox
    combine, or None when it can.

    The pipeline defers the cross-device pmax of round r's contribution
    into round r+1's scan body, so any feature that must read a COMBINED
    inbox within its own round body is incompatible.  Every predicate
    here is a static trace-time fact (params fields / world array
    shapes), so the check costs nothing inside jit.
    """
    if params.delivery != "scatter":
        return ("pipelined delivery targets the scatter-mode inbox pmax; "
                "sharded shift mode already exchanges payload blocks with "
                "per-channel ppermutes (ops/shift.ShiftEngine)")
    if params.max_delay_rounds != 0:
        return ("delay modeling combines one buffer per delay bin and "
                "pushes future bins into the carried ring within the "
                "round body (small-N validation mode)")
    if params.link_counters:
        return ("link_counters is the single-device measurement "
                "substrate; pipelining is a cross-device scheduling "
                "optimisation")
    if params.full_view and world.seed_ids.shape[0] > 0:
        return ("the joiner<->seed anti-entropy round trip (push + ack) "
                "completes within one round, so its combines cannot be "
                "deferred")
    if params.rounds_per_step != 1:
        return ("round fusion (rounds_per_step > 1) unrolls K ticks per "
                "scan step through _fused_scan; the pipelined loop "
                "carries exactly one round of pending contribution and "
                "has no fused body — the serial sharded scan fuses "
                "instead")
    return None


def swim_tick_send(state: SwimState, round_idx, base_key,
                   params: SwimParams, world: SwimWorld, offset=0,
                   axis_name: Optional[str] = None,
                   knobs: Optional[Knobs] = None, n_devices: int = 1):
    """First half of the PIPELINED scatter round: phases 1-3 only.

    Returns ``(pending, send_aux)``: ``pending`` is the device's
    UNCOMBINED global-height inbox contribution — under the FUSED wire
    (the default) a SINGLE packed-key buffer whose spare bits carry the
    ALIVE flags, else the legacy key + int8 flag pair — plus optional
    user-gossip bits, with the FD verdicts max-folded into the owner's
    local row block; ``send_aux`` is the send-side counters.  Both are
    consumed by
    :func:`swim_tick_recv` — in the NEXT scan body under the pipelined
    runner (parallel/mesh.shard_run) — which is where the cross-device
    ``pmax`` actually runs.

    Deferring the combine is a pure SCHEDULING change: the merge is the
    tick's last phase, so the combined inbox of round r is first read
    by round r+1's sends either way.  Folding the FD verdicts before
    the pmax instead of after it is bit-identical too — max is
    associative and only the owning device contributes FD values to its
    own rows.  Pinned by tests/test_pipelined_delivery.py.
    """
    reason = pipelined_delivery_unsupported_reason(params, world)
    if reason is not None:
        raise NotImplementedError(f"pipelined delivery: {reason}")
    ctx = _round_context(state, round_idx, base_key, params, world,
                         offset=offset, knobs=knobs)
    n_local = ctx["status"].shape[0]
    s = _scatter_send_phase(ctx["state"], ctx["status"], ctx["inc"],
                            round_idx, params, ctx["kn"], world,
                            ctx["alive"], ctx["part"], ctx["node_ids"],
                            ctx["alive_here"], ctx["part_here"],
                            ctx["is_self"], ctx["fd_round"],
                            ctx["sync_round"], ctx["gate_contacts"],
                            ctx["known_live"], ctx["is_seed"],
                            ctx["keys"], offset,
                            k_channel=ctx["k_shifts"], epoch=ctx["epoch"])
    if params.provenance:
        # channel_split: per-channel components double as the provenance
        # evidence below — zero extra scatters, and int-max associativity
        # keeps the combined buffer value-identical to the single fold.
        buf, fbuf, md_buf, (prov_g_buf, prov_s_buf) = _scatter_channel_bufs(
            s, params, False, False, channel_split=True)
    else:
        buf, fbuf, md_buf = _scatter_channel_bufs(s, params, False, False)
    # FD verdicts are observer-local: fold them into the owner's row
    # block of the pending buffer (serial folds after the combine; max
    # commutes with the pmax because no other device writes fd values
    # into these rows).
    local = jax.lax.dynamic_slice(buf, (offset, 0), (n_local, buf.shape[1]))
    buf = jax.lax.dynamic_update_slice(
        buf, jnp.maximum(local, s["fd_inbox"]), (offset, 0)
    )
    # Fused wire: the pipelined carry is a SINGLE buffer — the ALIVE
    # flag rides the key word's own bits (SwimParams.fused_wire).
    pending = dict(keys=buf)
    if fbuf is not None:
        pending["flags"] = fbuf
    if md_buf is not None:
        # Metadata contribution crosses the round boundary uncombined,
        # exactly like the key buffer (max is associative; the deferred
        # pmax runs in the recv half).
        pending["md"] = md_buf
    if params.n_user_gossips > 0:
        pending["g_bits"] = delivery.scatter_or(
            s["hot_g"], s["gossip_targets"], s["gossip_drop"],
            params.n_members,
        ).astype(jnp.int8)
    if params.lhm_max > 0:
        # Lifeguard probe evidence crosses the round boundary with the
        # contribution: the deferred recv half applies the SAME lhm
        # transition the serial tick would (local rows, no combine).
        pending["lhm_fail"] = s["lhm_fail"]
        pending["lhm_clean"] = s["lhm_clean"]
    if params.provenance:
        # Per-channel folded maxima cross the round boundary UNCOMBINED
        # exactly like the fused key buffer (max is associative; the
        # deferred pmax runs in the recv half).  The components come
        # straight from the channel_split fold above — no re-scatter.
        # The pipeline's static exclusions (no delay ring, no seed
        # contacts) already rule out every channel the serial exposure
        # folds beyond these.
        pending["prov_gossip"] = prov_g_buf
        pending["prov_sync"] = prov_s_buf
        pending["prov_fd"] = s["fd_inbox"]
        pending["prov_ping_req"] = s["ping_req_launches"]
    return pending, _scatter_send_aux(s, params)


def swim_tick_recv(state: SwimState, pending, send_aux, round_idx,
                   base_key, params: SwimParams, world: SwimWorld,
                   offset=0, axis_name: Optional[str] = None,
                   knobs: Optional[Knobs] = None, n_devices: int = 1):
    """Second half of the PIPELINED scatter round: combine the pending
    contribution from :func:`swim_tick_send` (the one cross-device
    ``pmax`` per buffer), merge, run the suspicion timers, and emit the
    round's metrics.

    MUST be called with the SAME ``(state, round_idx)`` the send half
    saw — it rederives the pinned/injected round context from them, so
    the pair composes to exactly :func:`swim_tick`.  Under the
    pipelined scan the call happens one body later, which puts the
    combine's collective next to the FOLLOWING round's state-independent
    draw compute in one program — the overlap window XLA's latency-
    hiding scheduler needs (a collective start/done pair cannot span a
    scan iteration boundary).
    """
    ctx = _round_context(state, round_idx, base_key, params, world,
                         offset=offset, knobs=knobs)
    n = params.n_members
    n_local = ctx["status"].shape[0]

    def combine_max(buf):
        if axis_name is not None:
            buf = jax.lax.pmax(buf, axis_name)
        if n_local == n and axis_name is None:
            return buf
        return jax.lax.dynamic_slice_in_dim(buf, offset, n_local, axis=0)

    inbox = combine_max(pending["keys"])
    if params.fused_wire:
        # The fused merge gate: the folded winner's own ALIVE flag,
        # derived from the combined key buffer (ONE pmax per round).
        inbox_alive = delivery.is_alive_key(inbox, fmt=params.wire_format)
    else:
        inbox_alive = combine_max(pending["flags"]).astype(jnp.bool_)
    g_delivered = None
    if params.n_user_gossips > 0:
        g_delivered = combine_max(pending["g_bits"]).astype(jnp.bool_)
    md_delivered = None
    if params.metadata_keys > 0:
        md_delivered = combine_max(pending["md"])

    new_state, refuted = _merge_and_timers(
        ctx["state"], ctx["status"], ctx["inc"], inbox, inbox_alive,
        round_idx, params, ctx["kn"], world, ctx["node_ids"],
        ctx["alive_here"], ctx["is_self"], g_delivered=g_delivered,
        lhm_signals=((pending["lhm_fail"], pending["lhm_clean"])
                     if params.lhm_max > 0 else None),
        epoch=ctx["epoch"], own_epoch=ctx["own_epoch"],
        md_delivered=md_delivered,
    )
    aux = dict(
        send_aux,
        refutations=jnp.sum(refuted & ctx["alive_here"], dtype=jnp.int32),
    )
    if params.metadata_keys > 0:
        # Globally reduced inside (psum) — _round_metrics passes through.
        aux["metadata_divergent"] = metadata.divergent_count(
            new_state.md, ctx["node_ids"], ctx["alive"],
            ctx["alive_here"], params.n_members,
            offset=offset, axis_name=axis_name,
        )
    if params.provenance:
        # Combine the per-channel pending maxima the send half exposed —
        # the same deferred pmax the key buffer gets, per channel.
        aux["_provenance"] = dict(
            fd=pending["prov_fd"],
            gossip=combine_max(pending["prov_gossip"]),
            sync=combine_max(pending["prov_sync"]),
            ping_req=pending["prov_ping_req"],
        )
    metrics = _round_metrics(new_state, ctx["status"], aux, params, world,
                             ctx["alive"], ctx["alive_here"], axis_name)
    if params.compact_carry:
        new_state = _carry_encode(new_state, round_idx,
                                  inc_sat=_wire_inc_sat(params))
    return new_state, metrics


# --------------------------------------------------------------------------
# Shift-mode tick body (cyclic-shift mixing — the fast path)
# --------------------------------------------------------------------------


def _shift_fd_chains(eng, d_ids, d_alive, d_part, fd_shift, proxy_shifts,
                     k_ping_net, k_proxy_net, params, kn, world, round_idx,
                     node_ids, part_here, out_shape,
                     ping_budget=None, ping_req_budget=None):
    """Shift-mode FD network outcomes as [n_local] vectors: the direct
    ping round trip and the ping-req proxy chains
    (FailureDetectorImpl.java:128-213), collapsed per _chain_ok.

    Shared by ``_tick_shift.fd_phase`` and ``_tick_shift_blocked`` so a
    protocol fix lands in one place; both callers pass the same keys in
    the same order, which is what keeps the blocked tick bit-identical.

    ``ping_budget``/``ping_req_budget`` override the static millisecond
    budgets (scalars or [n] vectors — the Lifeguard LHA Probe scaling,
    models/fd.effective_probe_budgets); None = the params base values.

    Returns ``(t, alive_t, part_t, direct_ok, ack_ok)`` where ``t`` is
    each prober's target id and ``ack_ok`` includes the proxy rescues.
    """
    if ping_budget is None:
        ping_budget = knob_ping_timeout(kn, params)
    if ping_req_budget is None:
        ping_req_budget = params.ping_interval_ms - knob_ping_timeout(kn, params)
    t = eng.look_replicated(d_ids, fd_shift)
    alive_t = eng.look_replicated(d_alive, fd_shift)
    part_t = eng.look_replicated(d_part, fd_shift)
    loss_it, delay_it = link_eval(world.faults, round_idx, node_ids, t,
                                  kn.loss_probability, params.mean_delay_ms)
    loss_ti, delay_ti = link_eval(world.faults, round_idx, t, node_ids,
                                  kn.loss_probability, params.mean_delay_ms)
    direct_ok = (
        _chain_ok(k_ping_net, [loss_it, loss_ti], [delay_it, delay_ti],
                  ping_budget, out_shape)
        & alive_t & (part_here == part_t)
    )
    # Ping-req via proxy shifts; proxy r for node i is (i + ps_r) % n.
    ack_ok = direct_ok
    for r in range(params.ping_req_members):
        ps = proxy_shifts[r]
        p_ids = eng.look_replicated(d_ids, ps)
        p_alive = eng.look_replicated(d_alive, ps)
        p_part = eng.look_replicated(d_part, ps)
        hop_pairs = [(node_ids, p_ids), (p_ids, t), (t, p_ids),
                     (p_ids, node_ids)]
        hop_losses, hop_delays = [], []
        for src, dst in hop_pairs:
            lo, de = link_eval(world.faults, round_idx, src, dst,
                               kn.loss_probability, params.mean_delay_ms)
            hop_losses.append(lo)
            hop_delays.append(de)
        ok_pr = (
            _chain_ok(jax.random.fold_in(k_proxy_net, r),
                      hop_losses, hop_delays, ping_req_budget,
                      out_shape)
            & p_alive & alive_t
            & (part_here == p_part) & (p_part == part_t)
            & (ps != fd_shift)                           # proxy != target
        )
        ack_ok = ack_ok | ok_pr
    return t, alive_t, part_t, direct_ok, ack_ok


def _shift_sender_gate(eng, d_ids, d_alive, d_part, s, world, round_idx,
                       node_ids, kn, params):
    """Receiver-evaluated ingredients of a shift channel's sender-side
    gate: the sender's id/alive/partition views through shift ``s`` plus
    the per-link loss/delay of the sender->receiver hop.  Shared by both
    shift tick bodies; callers compose the channel-specific gate (wire
    drop draw, fanout cap, sync round, refute suppression) from these.

    Returns ``(sender, sender_alive, sender_part, loss, delay)``.
    """
    sender = eng.deliver_replicated(d_ids, s)
    sender_alive = eng.deliver_replicated(d_alive, s)
    sender_part = eng.deliver_replicated(d_part, s)
    loss, delay = link_eval(world.faults, round_idx, sender, node_ids,
                            kn.loss_probability, params.mean_delay_ms)
    return sender, sender_alive, sender_part, loss, delay


def _tick_shift(state, status, inc, round_idx, params, kn, world,
                alive, part, node_ids, alive_here, part_here, is_self,
                fd_round, sync_round, gate_contacts, known_live, is_seed,
                keys, offset=0, axis_name=None, n_devices=1, epoch=None,
                own_epoch=None):
    n, k = params.n_members, params.n_subjects
    n_local = status.shape[0]
    (k_shifts, k_ping_net, k_proxy, k_proxy_net, k_gossip_t, k_gossip_drop,
     k_sync_t, k_sync_drop) = keys
    r_proxies = params.ping_req_members
    f = params.fanout
    eng = shift_ops.ShiftEngine(n, offset=offset, axis_name=axis_name,
                                n_devices=n_devices, n_local=n_local,
                                roll_payloads=params.shift_roll_payloads)

    # One shift per send channel: [fd, proxies..., gossip..., sync].
    # Drawn from the UN-offset-folded key: all devices must agree on the
    # round's shifts (the per-node draws below use the folded keys).
    n_shifts = 1 + r_proxies + f + 1
    shifts = jax.random.randint(
        k_shifts, (n_shifts,), 1, n, dtype=jnp.int32
    )
    fd_shift = shifts[0]
    proxy_shifts = shifts[1:1 + r_proxies]
    gossip_shifts = shifts[1 + r_proxies:1 + r_proxies + f]
    sync_shift = shifts[-1]

    # Replicated world vectors: shifted views are plain doubled-slices.
    d_alive = eng.prep_replicated(alive)
    d_part = eng.prep_replicated(part)
    d_ids = eng.prep_replicated(jnp.arange(n, dtype=jnp.int32))

    # ---- Phase 1: failure detector probe --------------------------------
    # The probe runs every round and its verdicts are masked by fd_round.
    # A lax.cond gate looks cheaper but measures WORSE at 1M members: the
    # conditional's operand/result tupling costs ~1 ms/round on TPU even
    # when the branch never fires, while the probe body itself (uniform
    # draws + [N]-vector chains) is ~0.3 ms — and under vmap sweeps a cond
    # lowers to select-both-branches anyway.
    # Lifeguard LHA Probe (the scatter tick's block, shared semantics):
    # health-scaled budgets + the 1/lhm probe gate; compiled out at
    # lhm_max=0 (None budgets = _shift_fd_chains' base defaults).
    lhm_ping_budget, lhm_pr_budget, lhm_gate = lifeguard.lha_probe_setup(
        params, state.lhm, k_ping_net, n_local,
        ping_timeout_ms=knob_ping_timeout(kn, params))

    def fd_phase(_):
        t, _alive_t, _part_t, direct_ok, ack_ok = _shift_fd_chains(
            eng, d_ids, d_alive, d_part, fd_shift, proxy_shifts,
            k_ping_net, k_proxy_net, params, kn, world, round_idx,
            node_ids, part_here, (n_local,),
            ping_budget=lhm_ping_budget, ping_req_budget=lhm_pr_budget,
        )
        if params.full_view:
            slot = t
            entry_t_status = jnp.take_along_axis(status, t[:, None], 1)[:, 0]
            entry_t_inc = jnp.take_along_axis(inc, t[:, None], 1)[:, 0]
            entry_t_ep = (jnp.take_along_axis(epoch, t[:, None], 1)[:, 0]
                          if params.epoch_bits else None)
            has_target = (
                (entry_t_status == records.ALIVE)
                | (entry_t_status == records.SUSPECT)
            )
        else:
            d_slot = eng.prep_replicated(world.slot_of_node)
            slot = eng.look_replicated(d_slot, fd_shift)     # -1 = untracked
            slot_sf = jnp.maximum(slot, 0)
            entry_t_status = _entry_at_slot(status, slot_sf, k)
            entry_t_inc = _entry_at_slot(inc, slot_sf, k)
            entry_t_ep = (_entry_at_slot(epoch, slot_sf, k)
                          if params.epoch_bits else None)
            has_target = (slot >= 0) & (
                (entry_t_status == records.ALIVE)
                | (entry_t_status == records.SUSPECT)
            )
        active = fd_round & has_target & alive_here
        if lhm_gate is not None:
            active = active & lhm_gate
        suspect_v = active & ~ack_ok
        refute_v = active & ack_ok & (entry_t_status == records.SUSPECT)
        # True wire-message accounting — see _tick_scatter's probes_sent
        # comment: every live member probes its offset target each fd
        # round; ``active`` gates only the tracked-subject bookkeeping.
        # Same predicate as scatter mode (ping_known_only == full_view is
        # validated for shift delivery in SwimParams.__post_init__).
        probes_sent = (active if params.ping_known_only
                       else fd_round & alive_here)
        if lhm_gate is not None and not params.ping_known_only:
            probes_sent = probes_sent & lhm_gate
        ping_req_launches = probes_sent & ~direct_ok
        return (suspect_v, refute_v, active,
                jnp.maximum(slot, 0), entry_t_inc, entry_t_ep, probes_sent,
                ping_req_launches, probes_sent & direct_ok)

    (verdict_suspect, push_refute, probe_active, slot_safe,
     entry_t_inc, entry_t_ep, probes_sent, ping_req_launches,
     lhm_clean) = fd_phase(0)
    ping_req_n = jnp.sum(ping_req_launches, dtype=jnp.int32) * r_proxies

    wf = params.wire_format
    no_msg = delivery.no_message(fmt=wf)
    fd_slot_onehot = (
        jnp.arange(k, dtype=jnp.int32)[None, :] == slot_safe[:, None]
    )
    fd_suspect_key = delivery.pack_record(
        jnp.int8(records.SUSPECT), entry_t_inc, fmt=wf,
        epoch=entry_t_ep, epoch_bits=params.epoch_bits,
    )
    fd_inbox = jnp.where(
        fd_slot_onehot & verdict_suspect[:, None],
        fd_suspect_key[:, None],
        no_msg,
    )

    # ---- Phase 2 + 3: gossip and SYNC sends ------------------------------
    record_keys, hot, syncable = _send_components(
        state, status, inc, round_idx, params, world, node_ids, is_self,
        epoch=epoch,
    )

    # Delivery: receiver j's channel-c message comes from sender
    # (j - shift_c) % n; sender-side gates (alive, partition, contact gate,
    # per-link loss) evaluate at the receiver via shifted views, which is
    # distribution-identical and keeps everything contiguous.  Sharded
    # payloads travel by block-rotation ppermutes (ops/shift.ShiftEngine).
    #
    # HBM economy: every channel ships the SAME packed-key buffer; gossip
    # and SYNC differ only by their sender-side transmit masks (hot window
    # / not-table-DEAD), which travel as int8 — 4x narrower than a second
    # masked int32 copy of the keys.  The per-message ALIVE gate needs no
    # buffer at all: it is a pure function of the delivered key bits
    # (delivery.is_alive_key), and in shift mode each channel's delivered
    # key IS the individual message (unlike scatter mode, where the
    # scatter-max folds messages and the gate must be scattered
    # separately).
    h_keys = eng.prep(record_keys)                        # [2N, K] or local
    # Both transmit masks ride one int8 buffer (bit 0 = hot, bit 1 =
    # syncable): halves the doubled-mask writes and lets a channel fetch
    # its mask with one slice.
    h_tx = eng.prep(hot.astype(jnp.int8) | (syncable.astype(jnp.int8) << 1))
    hot_any_local = jnp.any(hot, axis=1)
    hot_g, h_g = None, None
    if params.n_user_gossips > 0:
        # User gossips ride the same channels; a wire message exists when
        # either family has content (GossipProtocolImpl.java:211-237).
        hot_g = (state.g_infected & alive_here[:, None]
                 & (round_idx < state.g_spread_until))
        h_g = eng.prep(hot_g)
        hot_any_local = hot_any_local | jnp.any(hot_g, axis=1)
    h_hot_any = eng.prep(hot_any_local)
    h_status = eng.prep(status) if gate_contacts else None
    # Metadata plane payloads (models/metadata.py): hot rows on the
    # gossip + sync/refute channels, the full table on the anti-entropy
    # exchange — the same channels, shifts, and gates, no new draws.
    # Same-round delivery only (the anti-entropy precedent): the
    # per-channel ok_*_now masks below exclude delayed messages.
    h_md_hot = h_md_full = None
    md_delivered = None
    if params.metadata_keys > 0:
        h_md_hot = eng.prep(
            metadata.hot_payload(state.md, state.md_spread, round_idx))
        h_md_full = eng.prep(metadata.full_payload(state.md))
        md_delivered = jnp.zeros(
            (n_local, k * params.metadata_keys), dtype=jnp.int32)

    def deliver_channel(s, tx_bit):
        """(payload, alive-flags) of the channel at shift ``s`` whose
        transmit mask is ``tx_bit`` of the packed mask buffer."""
        keys = eng.deliver(h_keys, s)
        tx = (eng.deliver(h_tx, s) & tx_bit) != 0
        payload = jnp.where(tx, keys, no_msg)
        return payload, delivery.is_alive_key(payload, fmt=wf)

    def deliver_gossip(s):
        return deliver_channel(s, 1)

    def deliver_sync(s):
        return deliver_channel(s, 2)

    drop_u = jax.random.uniform(k_gossip_drop, (n_local, f + 1))

    # Per-sender wire accounting (SwimParams.link_counters docstring):
    # channel gates evaluate at the receiver in shift mode, so the masks
    # unshift back to the sender — sender i's channel-s message rides to
    # receiver (i + s) % n, one doubled-slice per mask.
    counters_on = params.link_counters
    sent_acc = jnp.zeros((n_local,), jnp.int32) if counters_on else None
    lost_acc = jnp.zeros((n_local,), jnp.int32) if counters_on else None

    def unshift(x_local, s):
        return eng.look_replicated(eng.prep_replicated(x_local), s)

    inbox_now, flags_now, g_now, ring, fring, gring, slot0 = _ring_open(
        state, params, round_idx
    )
    inbox = fd_inbox
    inbox_alive = jnp.zeros((n_local, k), dtype=jnp.bool_)
    # Provenance accumulators (SwimParams.provenance): the same channel
    # contributions folded a second time, kept apart per channel family
    # so the plane can name the winner — strictly additive next to the
    # combined inbox (XLA CSEs the shared delivery work).
    prov_gossip = prov_sync = None
    if params.provenance:
        prov_gossip = jnp.full((n_local, k), no_msg, dtype=inbox.dtype)
        prov_sync = jnp.full((n_local, k), no_msg, dtype=inbox.dtype)
    g_delivered, g_ring_acc = None, None
    if params.n_user_gossips > 0:
        g_delivered = jnp.zeros((n_local, params.n_user_gossips),
                                dtype=jnp.bool_)
    if params.max_delay_rounds > 0:
        inbox = jnp.maximum(inbox, inbox_now)
        inbox_alive |= flags_now
        if params.n_user_gossips > 0:
            g_delivered = g_delivered | g_now
            g_ring_acc = gring
    n_gossip_sent = jnp.int32(0)
    for c in range(f):
        s = gossip_shifts[c]
        _, sender_alive, sender_part, loss_c, delay_c = _shift_sender_gate(
            eng, d_ids, d_alive, d_part, s, world, round_idx, node_ids,
            kn, params,
        )
        ok_c = (
            sender_alive & alive_here & (sender_part == part_here)
            & (drop_u[:, c] >= loss_c)
            & (jnp.int32(c) < kn.fanout)
        )
        contact_ok_c = None
        if gate_contacts:
            # Sender-side knowledge of the receiver, evaluated at the
            # receiver: sender's record of me (full-view: my id column).
            sender_knows = jnp.take_along_axis(
                eng.deliver(h_status, s),
                node_ids[:, None], axis=1,
            )[:, 0]
            contact_ok_c = (
                (sender_knows == records.ALIVE)
                | (sender_knows == records.SUSPECT)
                | is_seed(node_ids)
            )
            ok_c &= contact_ok_c
        if counters_on:
            attempt_c = (sender_alive & eng.deliver(h_hot_any, s)
                         & (jnp.int32(c) < kn.fanout))
            if contact_ok_c is not None:
                attempt_c &= contact_ok_c
            lost_c = attempt_c & ((drop_u[:, c] < loss_c)
                                  | (sender_part != part_here))
            sent_acc += unshift(attempt_c, s).astype(jnp.int32)
            lost_acc += unshift(lost_c, s).astype(jnp.int32)
        delivered, delivered_flags = deliver_gossip(s)    # [n_local, K]
        g_bits_c = eng.deliver(h_g, s) if h_g is not None else None
        ok_now, ring, fring, g_ring_acc = _route_delayed(
            ok_c, delivered, delivered_flags, delay_c,
            jax.random.fold_in(k_gossip_drop, 11 + c), params,
            ring, fring, slot0, g_bits=g_bits_c, g_ring=g_ring_acc,
        )
        inbox = jnp.maximum(
            inbox, jnp.where(ok_now[:, None], delivered, no_msg)
        )
        inbox_alive |= delivered_flags & ok_now[:, None]
        if prov_gossip is not None:
            prov_gossip = jnp.maximum(
                prov_gossip, jnp.where(ok_now[:, None], delivered, no_msg)
            )
        if g_bits_c is not None:
            g_delivered = g_delivered | (g_bits_c & ok_now[:, None])
        if h_md_hot is not None:
            md_delivered = jnp.maximum(
                md_delivered,
                jnp.where(ok_now[:, None], eng.deliver(h_md_hot, s), 0),
            )
        n_gossip_sent += jnp.sum(
            ok_c & eng.deliver(h_hot_any, s), dtype=jnp.int32,
        )

    # Refute push: issuer i sends a SYNC (its full row minus tombstones,
    # matching MembershipProtocolImpl.java:379-391 and the scatter path) to
    # the suspected member t = (i + fd_shift); at the receiver that is the
    # sender (j - fd_shift).  Only fd rounds with the sync channel enabled
    # can produce push_refute (masked below), so on other rounds the
    # delivery contributes nothing — it still executes (same no-cond
    # rationale as the probe above).  It also reports which senders are
    # refuting as seen through the sync shift, so the regular sync channel
    # below can suppress them — in scatter mode the refute push REPLACES
    # the sender's regular sync target (do_sync override), and without the
    # suppression shift mode would emit one extra message per refuting
    # sender.  With the Lifeguard buddy system on (static lhm_max > 0)
    # the push rides the FD ack path regardless of the SYNC channel —
    # the scatter tick's gate, kept in lockstep.
    if params.lhm_max == 0:
        push_refute = push_refute & (kn.sync_every > 0)

    def refute_deliver(rf):
        ring_, fring_ = rf
        h_pushers = eng.prep(push_refute)
        # Loss/delay for the refute push (issuer -> target hop); it rides
        # the same delayed-delivery ring as the other channels so both
        # delivery modes agree under max_delay_rounds > 0.
        _, sender_alive_r, sender_part_r, loss_r, delay_r = \
            _shift_sender_gate(eng, d_ids, d_alive, d_part, fd_shift,
                               world, round_idx, node_ids, kn, params)
        part_ok_r = sender_part_r == part_here
        wire_drop_r = jax.random.uniform(k_sync_drop, (n_local,)) < loss_r
        pushing_r = eng.deliver(h_pushers, fd_shift)
        ok_r = (sender_alive_r & alive_here & part_ok_r & ~wire_drop_r
                & pushing_r)
        delivered_r, flags_r = deliver_sync(fd_shift)
        ok_r_now, ring_, fring_, _ = _route_delayed(
            ok_r, delivered_r, flags_r, delay_r,
            jax.random.fold_in(k_sync_drop, 13), params, ring_, fring_,
            slot0,
        )
        contrib = jnp.where(ok_r_now[:, None], delivered_r, no_msg)
        fcontrib = flags_r & ok_r_now[:, None]
        md_contrib = None
        if h_md_hot is not None:
            # The refute push is a SYNC to the suspected member; the md
            # hot rows ride it like any other sync payload.
            md_contrib = jnp.where(ok_r_now[:, None],
                                   eng.deliver(h_md_hot, fd_shift), 0)
        lost_r_mask = pushing_r & (wire_drop_r | ~part_ok_r)
        return contrib, fcontrib, ring_, fring_, \
            eng.deliver(h_pushers, sync_shift), lost_r_mask, md_contrib

    (refute_contrib, refute_flags, ring, fring, sender_refuting,
     refute_lost_r, refute_md) = refute_deliver((ring, fring))
    inbox = jnp.maximum(inbox, refute_contrib)
    inbox_alive |= refute_flags
    if prov_sync is not None:
        # The refute push is a SYNC payload (scatter mode's do_sync
        # override) — it folds into the SYNC family.
        prov_sync = jnp.maximum(prov_sync, refute_contrib)
    if refute_md is not None:
        md_delivered = jnp.maximum(md_delivered, refute_md)
    if counters_on:
        # The refute push is sender-local (the pusher mask IS per sender);
        # only its in-flight loss needs unshifting back from the receiver.
        sent_acc += push_refute.astype(jnp.int32)
        lost_acc += unshift(refute_lost_r, fd_shift).astype(jnp.int32)

    # SYNC channel: the periodic anti-entropy push, plus the FD
    # alive-on-suspected refute push (aimed at the probed member = the
    # fd_shift channel, delivered above).
    s = sync_shift
    _, sender_alive, sender_part, loss_sy, delay_sy = _shift_sender_gate(
        eng, d_ids, d_alive, d_part, s, world, round_idx, node_ids,
        kn, params,
    )
    part_ok_sy = sender_part == part_here
    wire_drop_sy = drop_u[:, f] < loss_sy
    ok_s = (
        sync_round & sender_alive & alive_here & ~sender_refuting
        & part_ok_sy & ~wire_drop_sy
    )
    contact_ok_sy = None
    if gate_contacts:
        sender_knows = jnp.take_along_axis(
            eng.deliver(h_status, s),
            node_ids[:, None], axis=1,
        )[:, 0]
        contact_ok_sy = (
            (sender_knows == records.ALIVE)
            | (sender_knows == records.SUSPECT)
            | is_seed(node_ids)
        )
        ok_s &= contact_ok_sy
    if counters_on:
        attempt_sy = sync_round & sender_alive & ~sender_refuting
        if contact_ok_sy is not None:
            attempt_sy &= contact_ok_sy
        lost_sy = attempt_sy & (wire_drop_sy | ~part_ok_sy)
        sent_acc += unshift(attempt_sy, s).astype(jnp.int32)
        lost_acc += unshift(lost_sy, s).astype(jnp.int32)
    delivered, delivered_flags = deliver_sync(s)
    ok_s_now, ring, fring, _ = _route_delayed(
        ok_s, delivered, delivered_flags, delay_sy,
        jax.random.fold_in(k_sync_drop, 11), params, ring, fring, slot0,
    )
    inbox = jnp.maximum(
        inbox, jnp.where(ok_s_now[:, None], delivered, no_msg)
    )
    inbox_alive |= delivered_flags & ok_s_now[:, None]
    if prov_sync is not None:
        prov_sync = jnp.maximum(
            prov_sync, jnp.where(ok_s_now[:, None], delivered, no_msg)
        )
    if h_md_hot is not None:
        md_delivered = jnp.maximum(
            md_delivered,
            jnp.where(ok_s_now[:, None], eng.deliver(h_md_hot, s), 0),
        )

    # Anti-entropy plane: the paired full-table exchange (models/sync.py)
    # as two extra syncable-payload channels at the shared offset ±s —
    # receiver j hears partner (j - s) on the forward channel and
    # (j + s) on the reverse one, so each unordered pair {i, i + s}
    # swaps tables in full duplex.  Same-round delivery only (no delay
    # ring — the _seed_anti_entropy precedent).
    ae_sent_local = None
    if params.sync_interval > 0:
        ae_due = sync_plane.due(round_idx, params.sync_interval)
        s_ae = sync_plane.partner_offset(k_shifts, n)
        k_ae = sync_plane.drop_key(k_sync_drop)
        ae_sent_local = sync_plane.sent_count(ae_due, alive_here)
        for d_i, sft in enumerate((s_ae, jnp.int32(n) - s_ae)):
            _, sa_ae, sp_ae, loss_ae, _ = _shift_sender_gate(
                eng, d_ids, d_alive, d_part, sft, world, round_idx,
                node_ids, kn, params,
            )
            part_ok_ae = sp_ae == part_here
            wire_drop_ae = jax.random.uniform(
                jax.random.fold_in(k_ae, d_i), (n_local,)) < loss_ae
            ok_ae = (ae_due & sa_ae & alive_here & part_ok_ae
                     & ~wire_drop_ae)
            contact_ok_ae = None
            if gate_contacts:
                sender_knows = jnp.take_along_axis(
                    eng.deliver(h_status, sft),
                    node_ids[:, None], axis=1,
                )[:, 0]
                contact_ok_ae = (
                    (sender_knows == records.ALIVE)
                    | (sender_knows == records.SUSPECT)
                    | is_seed(node_ids)
                )
                ok_ae &= contact_ok_ae
            delivered_ae, flags_ae = deliver_sync(sft)
            inbox = jnp.maximum(
                inbox, jnp.where(ok_ae[:, None], delivered_ae, no_msg)
            )
            inbox_alive |= flags_ae & ok_ae[:, None]
            if prov_sync is not None:
                # Anti-entropy is a SYNC-family exchange.
                prov_sync = jnp.maximum(
                    prov_sync,
                    jnp.where(ok_ae[:, None], delivered_ae, no_msg),
                )
            if h_md_full is not None:
                # The FULL metadata table rides the exchange — the
                # convergence-through-heal guarantee (module docstring).
                md_delivered = jnp.maximum(
                    md_delivered,
                    jnp.where(ok_ae[:, None],
                              eng.deliver(h_md_full, sft), 0),
                )
            if counters_on:
                attempt_ae = ae_due & sa_ae
                if contact_ok_ae is not None:
                    attempt_ae &= contact_ok_ae
                lost_ae = attempt_ae & (wire_drop_ae | ~part_ok_ae)
                sent_acc += unshift(attempt_ae, sft).astype(jnp.int32)
                lost_acc += unshift(lost_ae, sft).astype(jnp.int32)

    # Joiner <-> seed SYNC round trip (the reference's join protocol;
    # inert once no row holds ABSENT entries — the masked key copy only
    # materializes in seed-configured cold-start scenarios).
    ss_sent = ss_lost = jnp.int32(0)
    if gate_contacts:
        sync_keys_local = jnp.where(syncable, record_keys, no_msg)
        inbox, inbox_alive, ss_sent, ss_lost = _seed_anti_entropy(
            status, sync_keys_local, inbox, inbox_alive, sync_round,
            round_idx, params, kn, world, node_ids, alive_here, alive, part,
            jax.random.fold_in(k_sync_drop, 29), axis_name=axis_name,
        )
        if prov_sync is not None:
            # Same folded key -> same draws -> identical contributions,
            # folded into the SYNC family (the join path IS a SYNC
            # exchange) — mirrors the scatter tick's provenance fold.
            prov_sync, _, _, _ = _seed_anti_entropy(
                status, sync_keys_local, prov_sync, None, sync_round,
                round_idx, params, kn, world, node_ids, alive_here,
                alive, part, jax.random.fold_in(k_sync_drop, 29),
                axis_name=axis_name,
            )

    new_state, refuted = _merge_and_timers(
        state, status, inc, inbox, inbox_alive, round_idx, params, kn, world,
        node_ids, alive_here, is_self, inbox_ring=ring, flag_ring=fring,
        g_delivered=g_delivered, g_ring=g_ring_acc,
        lhm_signals=((ping_req_launches, lhm_clean)
                     if params.lhm_max > 0 else None),
        epoch=epoch, own_epoch=own_epoch, md_delivered=md_delivered,
    )
    aux = dict(
        messages_gossip=n_gossip_sent,
        messages_ping=jnp.sum(probe_active, dtype=jnp.int32),
        messages_ping_sent=jnp.sum(probes_sent, dtype=jnp.int32),
        messages_ping_req_sent=ping_req_n,
        refutations=jnp.sum(refuted & alive_here, dtype=jnp.int32),
    )
    if params.metadata_keys > 0:
        # Globally reduced inside (psum) — _round_metrics passes through.
        aux["metadata_divergent"] = metadata.divergent_count(
            new_state.md, node_ids, alive, alive_here, n,
            offset=offset, axis_name=axis_name,
        )
    if ae_sent_local is not None:
        aux["messages_anti_entropy"] = ae_sent_local
    if params.provenance:
        aux["_provenance"] = dict(
            fd=fd_inbox, gossip=prov_gossip, sync=prov_sync,
            ping_req=ping_req_launches,
        )
    if counters_on:
        aux["sent_by_node"] = (
            sent_acc + probes_sent.astype(jnp.int32)
            + ping_req_launches.astype(jnp.int32) * r_proxies + ss_sent
        )
        aux["lost_by_node"] = lost_acc + ss_lost
    return new_state, aux


# --------------------------------------------------------------------------
# K-tiled shift-mode tick body (full-view capacity path)
# --------------------------------------------------------------------------


def _tick_shift_blocked(state, status, inc, round_idx, params, kn, world,
                        alive, part, node_ids, alive_here, part_here,
                        is_self, fd_round, sync_round, keys,
                        own_epoch=None):
    """The shift tick restructured as a fori_loop over K column blocks.

    Bit-identical to ``_tick_shift`` (single device, full view, no delay
    ring): the channel shifts rotate ROWS, so each column block's
    delivery + merge is independent of the others, and every PRNG draw
    (shifts, drop uniforms, FD chains) is K-independent — same keys,
    same values, same order as the unblocked body.  What changes is
    materialization: payload/inbox/merge temps are [N, Kb] transients
    and each block's new state is written into the carry accumulator by
    ``dynamic_update_slice``, so peak HBM ~= one carry instead of carry
    + six [N, K] channel temps (SwimParams.k_block docstring; the OOM
    anatomy is in experiments/ceiling_probe.py).
    """
    n = params.n_members
    k = params.n_subjects                           # == n (full view)
    kb = params.k_block
    n_blocks = k // kb
    (k_shifts, k_ping_net, k_proxy, k_proxy_net, k_gossip_t, k_gossip_drop,
     k_sync_t, k_sync_drop) = keys
    r_proxies = params.ping_req_members
    f = params.fanout
    eng = shift_ops.ShiftEngine(n, roll_payloads=params.shift_roll_payloads)
    compact = params.compact_carry          # carry layout
    wf = params.wire_format                 # wire-key format
    no_msg = delivery.no_message(fmt=wf)

    # ---- Round draws: identical keys/shapes to _tick_shift --------------
    n_shifts = 1 + r_proxies + f + 1
    shifts = jax.random.randint(k_shifts, (n_shifts,), 1, n, dtype=jnp.int32)
    fd_shift = shifts[0]
    proxy_shifts = shifts[1:1 + r_proxies]
    gossip_shifts = shifts[1 + r_proxies:1 + r_proxies + f]
    sync_shift = shifts[-1]

    d_alive = eng.prep_replicated(alive)
    d_part = eng.prep_replicated(part)
    d_ids = eng.prep_replicated(jnp.arange(n, dtype=jnp.int32))

    # ---- FD phase (full-view take_along on the whole carry; [N] vectors,
    # no [N, K] temps) — the chain math is _shift_fd_chains, shared with
    # _tick_shift.fd_phase.  ``status``/``inc`` are the RAW carry fields
    # (a well-formed carry is already diagonal-pinned, and t != i for
    # every shift) — in compact layout the per-entry decode is just the
    # int32 upcast.
    # Lifeguard LHA Probe — the same shared setup as _tick_shift, drawn
    # from the same keys so the blocked tick stays bit-identical.
    lhm_ping_budget, lhm_pr_budget, lhm_gate = lifeguard.lha_probe_setup(
        params, state.lhm, k_ping_net, n,
        ping_timeout_ms=knob_ping_timeout(kn, params))
    t, _alive_t, _part_t, direct_ok, ack_ok = _shift_fd_chains(
        eng, d_ids, d_alive, d_part, fd_shift, proxy_shifts,
        k_ping_net, k_proxy_net, params, kn, world, round_idx,
        node_ids, part_here, (n,),
        ping_budget=lhm_ping_budget, ping_req_budget=lhm_pr_budget,
    )
    entry_t_status = jnp.take_along_axis(status, t[:, None], 1)[:, 0]
    entry_t_inc = jnp.take_along_axis(inc, t[:, None], 1)[:, 0] \
        .astype(jnp.int32)
    entry_t_ep = None
    if params.epoch_bits:
        # The raw carry's epoch lane (t != i, so the unpinned diagonal
        # is never read — the _round_context k_block contract).
        entry_t_ep = jnp.take_along_axis(
            state.epoch, t[:, None], 1)[:, 0].astype(jnp.int32)
    has_target = ((entry_t_status == records.ALIVE)
                  | (entry_t_status == records.SUSPECT))
    probe_active = fd_round & has_target & alive_here
    if lhm_gate is not None:
        probe_active = probe_active & lhm_gate
    verdict_suspect = probe_active & ~ack_ok
    push_refute = (probe_active & ack_ok
                   & (entry_t_status == records.SUSPECT))
    probes_sent = probe_active                      # full view: same gate
    ping_req_launches = probes_sent & ~direct_ok
    ping_req_n = jnp.sum(ping_req_launches, dtype=jnp.int32) * r_proxies
    slot_safe = t                                    # full view: slot == id
    fd_suspect_key = delivery.pack_record(
        jnp.int8(records.SUSPECT), entry_t_inc, fmt=wf,
        epoch=entry_t_ep, epoch_bits=params.epoch_bits,
    )

    # ---- Channel sender gates (receiver-indexed [N] vectors) ------------
    drop_u = jax.random.uniform(k_gossip_drop, (n, f + 1))
    ok_gossip = []
    for c in range(f):
        _, sender_alive, sender_part, loss_c, _ = _shift_sender_gate(
            eng, d_ids, d_alive, d_part, gossip_shifts[c], world,
            round_idx, node_ids, kn, params,
        )
        ok_gossip.append(
            sender_alive & alive_here & (sender_part == part_here)
            & (drop_u[:, c] >= loss_c) & (jnp.int32(c) < kn.fanout)
        )
    if params.lhm_max == 0:            # buddy: ack-path push (see _tick_shift)
        push_refute = push_refute & (kn.sync_every > 0)
    h_pushers = eng.prep(push_refute)
    _, sender_alive_r, sender_part_r, loss_r, _ = _shift_sender_gate(
        eng, d_ids, d_alive, d_part, fd_shift, world, round_idx,
        node_ids, kn, params,
    )
    wire_drop_r = jax.random.uniform(k_sync_drop, (n,)) < loss_r
    ok_refute = (sender_alive_r & alive_here & (sender_part_r == part_here)
                 & ~wire_drop_r & eng.deliver(h_pushers, fd_shift))
    sender_refuting = eng.deliver(h_pushers, sync_shift)
    _, sender_alive_s, sender_part_s, loss_sy, _ = _shift_sender_gate(
        eng, d_ids, d_alive, d_part, sync_shift, world, round_idx,
        node_ids, kn, params,
    )
    ok_sync = (
        sync_round & sender_alive_s & alive_here & ~sender_refuting
        & (sender_part_s == part_here) & (drop_u[:, f] >= loss_sy)
    )
    # Anti-entropy plane channel gates (K-independent [N] vectors; the
    # per-block loop below delivers the payload) — same draws, same
    # order as _tick_shift's exchange block, which is what keeps the
    # blocked tick bit-identical with the plane on.
    ae_shifts, ok_ae, ae_sent_local = (), [], None
    if params.sync_interval > 0:
        ae_due = sync_plane.due(round_idx, params.sync_interval)
        s_ae = sync_plane.partner_offset(k_shifts, n)
        k_ae = sync_plane.drop_key(k_sync_drop)
        ae_sent_local = sync_plane.sent_count(ae_due, alive_here)
        ae_shifts = (s_ae, jnp.int32(n) - s_ae)
        for d_i, sft in enumerate(ae_shifts):
            _, sa_ae, sp_ae, loss_ae, _ = _shift_sender_gate(
                eng, d_ids, d_alive, d_part, sft, world, round_idx,
                node_ids, kn, params,
            )
            wire_drop_ae = jax.random.uniform(
                jax.random.fold_in(k_ae, d_i), (n,)) < loss_ae
            ok_ae.append(
                ae_due & sa_ae & alive_here & (sp_ae == part_here)
                & ~wire_drop_ae
            )

    # ---- K-independent extras: message counts, user gossip --------------
    leaving = world.leave_at[node_ids] == round_idx          # [N]
    # hot_any: streamed reduce over the carry (no [N, K] temp survives).
    # Compact layout stores spread as remaining rounds: r < r + rel
    # iff rel > 0, so the condition reads the int8 field directly.
    in_window = (state.spread_until > 0 if compact
                 else round_idx < state.spread_until)
    hot_any = jnp.any(
        (status != records.ABSENT) & in_window, axis=1,
    ) | leaving
    hot_g, g_delivered = None, None
    if params.n_user_gossips > 0:
        hot_g = (state.g_infected & alive_here[:, None]
                 & (round_idx < state.g_spread_until))
        h_g = eng.prep(hot_g)
        hot_any = hot_any | jnp.any(hot_g, axis=1)
        g_delivered = jnp.zeros((n, params.n_user_gossips), dtype=jnp.bool_)
        for c in range(f):
            g_delivered = g_delivered | (
                eng.deliver(h_g, gossip_shifts[c]) & ok_gossip[c][:, None]
            )
    h_hot_any = eng.prep(hot_any)
    n_gossip_sent = jnp.int32(0)
    for c in range(f):
        n_gossip_sent += jnp.sum(
            ok_gossip[c] & eng.deliver(h_hot_any, gossip_shifts[c]),
            dtype=jnp.int32,
        )

    # ---- Block loop ------------------------------------------------------
    per_subject = params.per_subject_metrics
    hist_shape = (k,) if per_subject else ()

    def hist_init():
        return jnp.zeros(hist_shape, dtype=jnp.int32)

    zero_g = dict(
        g_infected=jnp.zeros((n, 0), dtype=jnp.bool_),
        g_spread_until=jnp.zeros((n, 0), dtype=jnp.int32),
        g_ring=jnp.zeros((0, n, 0), dtype=jnp.bool_),
        # metadata_keys > 0 excludes k_block (SwimParams.__post_init__),
        # so the block view only carries the zero-size lanes.
        md=jnp.zeros((n, 0, 0), dtype=jnp.int32),
        md_spread=jnp.zeros((n, 0), dtype=jnp.int32),
    )

    def body(b, acc):
        (st_acc, inc_acc, ep_acc, spr_acc, dl_acc, self_inc_acc,
         refuted_acc, h_alive, h_suspect, h_dead, h_still, fsr, svr,
         ons, prov_g_acc, prov_s_acc) = acc
        c0 = b * kb
        cols = c0 + jnp.arange(kb, dtype=jnp.int32)          # global ids

        def blk_of(x):
            return jax.lax.dynamic_slice_in_dim(x, c0, kb, 1)

        # Raw (stored-layout) block -> decoded block, pinned diagonal.
        blk_raw = SwimState(
            status=blk_of(state.status), inc=blk_of(state.inc),
            spread_until=blk_of(state.spread_until),
            suspect_deadline=blk_of(state.suspect_deadline),
            self_inc=state.self_inc,
            inbox_ring=state.inbox_ring, flag_ring=state.flag_ring,
            # K-independent [N] lane: the real values ride into every
            # block (the LHS deadline arming reads them); the update
            # itself happens ONCE outside the loop (lhm_signals=None).
            lhm=state.lhm,
            epoch=(blk_of(state.epoch) if params.epoch_bits
                   else state.epoch),
            **zero_g,
        )
        blk = _carry_decode(blk_raw, round_idx) if compact else blk_raw
        is_self_b = cols[None, :] == node_ids[:, None]
        st_b = jnp.where(is_self_b, records.ALIVE, blk.status)
        inc_b = jnp.where(is_self_b, state.self_inc[:, None], blk.inc)
        ep_b = None
        if params.epoch_bits:
            ep_b = jnp.where(is_self_b, own_epoch[:, None],
                             blk.epoch.astype(jnp.int32))

        record_keys_b, hot_b, syncable_b = _send_components(
            blk, st_b, inc_b, round_idx, params, world, node_ids,
            is_self_b, epoch=ep_b,
        )

        h_keys_b = eng.prep(record_keys_b)
        h_tx_b = eng.prep(
            hot_b.astype(jnp.int8) | (syncable_b.astype(jnp.int8) << 1)
        )

        def deliver_channel_b(sft, tx_bit):
            keys_c = eng.deliver(h_keys_b, sft)
            tx = (eng.deliver(h_tx_b, sft) & tx_bit) != 0
            payload = jnp.where(tx, keys_c, no_msg)
            return payload, delivery.is_alive_key(payload, fmt=wf)

        # FD verdict lands on column slot_safe (one cell per row).
        inbox_b = jnp.where(
            (cols[None, :] == slot_safe[:, None])
            & verdict_suspect[:, None],
            fd_suspect_key[:, None], no_msg,
        )
        inbox_alive_b = jnp.zeros((n, kb), dtype=jnp.bool_)
        # Per-channel block maxima for the provenance plane — the same
        # contributions folded a second time, kept apart per channel
        # family (SwimParams.provenance; XLA CSEs the shared delivery).
        prov_g_b = prov_s_b = None
        if params.provenance:
            prov_g_b = jnp.full((n, kb), no_msg, dtype=inbox_b.dtype)
            prov_s_b = jnp.full((n, kb), no_msg, dtype=inbox_b.dtype)
        for c in range(f):
            payload, aflags = deliver_channel_b(gossip_shifts[c], 1)
            okc = ok_gossip[c][:, None]
            inbox_b = jnp.maximum(inbox_b, jnp.where(okc, payload, no_msg))
            inbox_alive_b |= aflags & okc
            if prov_g_b is not None:
                prov_g_b = jnp.maximum(prov_g_b,
                                       jnp.where(okc, payload, no_msg))
        payload, aflags = deliver_channel_b(fd_shift, 2)     # refute push
        okr = ok_refute[:, None]
        inbox_b = jnp.maximum(inbox_b, jnp.where(okr, payload, no_msg))
        inbox_alive_b |= aflags & okr
        if prov_s_b is not None:
            # Refute push is a SYNC payload — the SYNC family.
            prov_s_b = jnp.maximum(prov_s_b,
                                   jnp.where(okr, payload, no_msg))
        payload, aflags = deliver_channel_b(sync_shift, 2)   # SYNC
        oks = ok_sync[:, None]
        inbox_b = jnp.maximum(inbox_b, jnp.where(oks, payload, no_msg))
        inbox_alive_b |= aflags & oks
        if prov_s_b is not None:
            prov_s_b = jnp.maximum(prov_s_b,
                                   jnp.where(oks, payload, no_msg))
        for d_i, sft in enumerate(ae_shifts):        # anti-entropy pair
            payload, aflags = deliver_channel_b(sft, 2)
            oka = ok_ae[d_i][:, None]
            inbox_b = jnp.maximum(inbox_b,
                                  jnp.where(oka, payload, no_msg))
            inbox_alive_b |= aflags & oka
            if prov_s_b is not None:
                prov_s_b = jnp.maximum(prov_s_b,
                                       jnp.where(oka, payload, no_msg))

        new_blk, refuted_b = _merge_and_timers(
            blk, st_b, inc_b, inbox_b, inbox_alive_b, round_idx,
            params, kn, world, node_ids, alive_here, is_self_b,
            epoch=ep_b, own_epoch=own_epoch,
        )
        out_blk = (_carry_encode(new_blk, round_idx,
                                 inc_sat=_wire_inc_sat(params))
                   if compact else new_blk)

        st_acc = jax.lax.dynamic_update_slice_in_dim(
            st_acc, out_blk.status, c0, 1)
        inc_acc = jax.lax.dynamic_update_slice_in_dim(
            inc_acc, out_blk.inc, c0, 1)
        if params.epoch_bits:
            ep_acc = jax.lax.dynamic_update_slice_in_dim(
                ep_acc, out_blk.epoch, c0, 1)
        spr_acc = jax.lax.dynamic_update_slice_in_dim(
            spr_acc, out_blk.spread_until, c0, 1)
        dl_acc = jax.lax.dynamic_update_slice_in_dim(
            dl_acc, out_blk.suspect_deadline, c0, 1)
        # Refutation bumps only happen in the diagonal block of each row;
        # bumps strictly increase, so max-accumulate is exact.
        self_inc_acc = jnp.maximum(self_inc_acc, new_blk.self_inc)
        refuted_acc = refuted_acc | refuted_b

        # Metrics, accumulated blockwise (same reductions as swim_tick).
        observer_alive = alive_here[:, None]
        sa_b = alive[cols].astype(jnp.int32)                 # [Kb]
        ha_b = jnp.sum((new_blk.status == records.ALIVE) & observer_alive,
                       axis=0, dtype=jnp.int32)
        hs_b = jnp.sum((new_blk.status == records.SUSPECT) & observer_alive,
                       axis=0, dtype=jnp.int32)
        hd_b = jnp.sum((new_blk.status == records.DEAD) & observer_alive,
                       axis=0, dtype=jnp.int32)
        hst_b = jnp.sum(
            (new_blk.status == records.SUSPECT)
            & (st_b == records.SUSPECT) & observer_alive,
            axis=0, dtype=jnp.int32)
        fsr_b = hs_b * sa_b
        svr_b = hd_b * sa_b
        ons_b = (hs_b - hst_b) * sa_b
        if per_subject:
            upd = partial(jax.lax.dynamic_update_slice_in_dim,
                          start_index=c0, axis=0)
            h_alive = upd(h_alive, update=ha_b)
            h_suspect = upd(h_suspect, update=hs_b)
            h_dead = upd(h_dead, update=hd_b)
            h_still = upd(h_still, update=hst_b)
            fsr = upd(fsr, update=fsr_b)
            svr = upd(svr, update=svr_b)
            ons = upd(ons, update=ons_b)
        else:
            h_alive += jnp.sum(ha_b)
            h_suspect += jnp.sum(hs_b)
            h_dead += jnp.sum(hd_b)
            h_still += jnp.sum(hst_b)
            fsr += jnp.sum(fsr_b)
            svr += jnp.sum(svr_b)
            ons += jnp.sum(ons_b)
        if params.provenance:
            prov_g_acc = jax.lax.dynamic_update_slice_in_dim(
                prov_g_acc, prov_g_b, c0, 1)
            prov_s_acc = jax.lax.dynamic_update_slice_in_dim(
                prov_s_acc, prov_s_b, c0, 1)
        return (st_acc, inc_acc, ep_acc, spr_acc, dl_acc, self_inc_acc,
                refuted_acc, h_alive, h_suspect, h_dead, h_still, fsr,
                svr, ons, prov_g_acc, prov_s_acc)

    # Accumulators stay in the STORED layout (compact dtypes included):
    # blocks are decoded on read and re-encoded on write, so no wide
    # [N, K] int32 copy of the carry ever exists.
    # Provenance accumulators: [N, K] wire-dtype channel maxima when the
    # plane is armed, zero-column placeholders (never touched) when off —
    # the acc tuple keeps one static shape either way.
    prov_cols = k if params.provenance else 0
    prov_init = jnp.full((n, prov_cols), no_msg,
                         dtype=fd_suspect_key.dtype)
    acc0 = (
        state.status, state.inc, state.epoch,
        state.spread_until, state.suspect_deadline,
        state.self_inc, jnp.zeros((n,), dtype=jnp.bool_),
        hist_init(), hist_init(), hist_init(), hist_init(),
        hist_init(), hist_init(), hist_init(),
        prov_init, prov_init,
    )
    (st_acc, inc_acc, ep_acc, spr_acc, dl_acc, self_inc_acc, refuted,
     h_alive, h_suspect, h_dead, h_still, fsr, svr, ons,
     prov_g_acc, prov_s_acc) = \
        jax.lax.fori_loop(0, n_blocks, body, acc0)

    # User-gossip merge (K-independent; mirrors _merge_and_timers's tail).
    g_infected, g_spread_until = state.g_infected, state.g_spread_until
    if g_delivered is not None:
        newly_g = g_delivered & ~g_infected
        g_infected2 = g_infected | g_delivered
        g_spread2 = jnp.where(
            newly_g, round_idx + 1 + params.periods_to_spread,
            g_spread_until)
        frozen1 = ~alive_here[:, None]
        g_infected = jnp.where(frozen1, g_infected, g_infected2)
        g_spread_until = jnp.where(frozen1, g_spread_until, g_spread2)

    # Lifeguard LHM transition, once for the whole round (K-independent;
    # mirrors _merge_and_timers' tail with the accumulated refutations).
    new_lhm = state.lhm
    if params.lhm_max > 0:
        new_lhm = lifeguard.update(
            state.lhm, ping_req_launches, probes_sent & direct_ok,
            refuted & alive_here, alive_here, knob_lhm_cap(kn, params),
        )

    new_state = SwimState(
        status=st_acc, inc=inc_acc, spread_until=spr_acc,
        suspect_deadline=dl_acc, self_inc=self_inc_acc,
        inbox_ring=state.inbox_ring, flag_ring=state.flag_ring,
        g_infected=g_infected, g_spread_until=g_spread_until,
        g_ring=state.g_ring,
        lhm=new_lhm,
        epoch=ep_acc,
        md=state.md, md_spread=state.md_spread,
    )
    subject_alive_i = (alive[world.subject_ids].astype(jnp.int32)
                       if per_subject
                       else jnp.sum(alive[world.subject_ids],
                                    dtype=jnp.int32))
    aux = dict(
        messages_gossip=n_gossip_sent,
        messages_ping=jnp.sum(probe_active, dtype=jnp.int32),
        messages_ping_sent=jnp.sum(probes_sent, dtype=jnp.int32),
        messages_ping_req_sent=ping_req_n,
        refutations=jnp.sum(refuted & alive_here, dtype=jnp.int32),
        **({"messages_anti_entropy": ae_sent_local}
           if ae_sent_local is not None else {}),
        blocked_metrics=dict(
            hist_alive=h_alive, hist_suspect=h_suspect, hist_dead=h_dead,
            still_suspect=h_still, subject_alive_i=subject_alive_i,
            false_suspect_rounds=fsr, stale_view_rounds=svr, onsets=ons,
        ),
    )
    if params.provenance:
        # FD verdicts are one cell per row — built whole outside the
        # block loop (an [N, K] wire-dtype temp is acceptable in an
        # observability mode; the capacity path runs with the plane off).
        prov_fd = jnp.where(
            (jnp.arange(k, dtype=jnp.int32)[None, :] == slot_safe[:, None])
            & verdict_suspect[:, None],
            fd_suspect_key[:, None], no_msg,
        )
        aux["_provenance"] = dict(
            fd=prov_fd, gossip=prov_g_acc, sync=prov_s_acc,
            ping_req=ping_req_launches,
        )
    return new_state, aux


def node_snapshot(state: SwimState, params: SwimParams, world: SwimWorld,
                  node_id: int, round_idx: Optional[int] = None) -> dict:
    """Queryable per-node state dump — the JMX MBean analog for the tick.

    Host-side digest of one observer row, mirroring the reference's
    ``MembershipProtocolImpl.JmxMonitorMBean`` surface
    (MembershipProtocolImpl.java:693-749: incarnation, alive/suspected
    lists, removals) for any of the N simulated nodes; the oracle facade's
    counterpart is ``oracle.Cluster.monitor``.

    ``round_idx``: the round cursor the state is encoded against — pass
    the next round the state would run (e.g. the number of rounds
    executed so far) so a ``compact_carry`` state's relative
    remaining-rounds encodings decode to the same absolute rounds the
    wide layout reports.  REQUIRED for ``compact_carry`` states (no
    correct default exists for a relative encoding); optional for the
    wide layout, where the state is already absolute.
    """
    import numpy as np

    if params.compact_carry:
        if round_idx is None:
            raise ValueError(
                "node_snapshot of a compact_carry state needs round_idx "
                "(the cursor its relative encodings decode against); "
                "pass the number of rounds executed so far"
            )
        state = _carry_decode(state, round_idx)
    status = np.asarray(state.status[node_id])
    inc = np.asarray(state.inc[node_id])
    deadline = np.asarray(state.suspect_deadline[node_id])
    subjects = np.asarray(world.subject_ids)
    not_self = subjects != node_id

    def ids_with(code):
        return subjects[(status == code) & not_self].tolist()

    snapshot = {
        "node_id": int(node_id),
        "incarnation": int(np.asarray(state.self_inc)[node_id]),
        "alive_members": ids_with(records.ALIVE),
        "suspected_members": ids_with(records.SUSPECT),
        "dead_tombstones": ids_with(records.DEAD),
        "unknown_members": ids_with(records.ABSENT),
        "pending_suspicion_timers": {
            int(s): int(d)
            for s, d in zip(subjects, deadline)
            if d != INT32_MAX
        },
        "record_incarnations": {
            int(s): int(i)
            for s, i, st in zip(subjects, inc, status)
            if st != records.ABSENT
        },
    }
    if params.epoch_bits:
        # Guard arm only: the naive-reuse arm (epoch_guard=False) has
        # no lane, so the field is OMITTED there rather than reported
        # as a misleading empty dict.
        epochs = np.asarray(state.epoch[node_id])
        snapshot["record_epochs"] = {
            int(s): int(e)
            for s, e, st in zip(subjects, epochs, status)
            if st != records.ABSENT
        }
    return snapshot


def _wide_timer_fields(state: SwimState, params: SwimParams, cursor):
    """(suspect_deadline, spread_until) decoded to ABSOLUTE rounds at
    ``cursor`` — the two carry fields the health registry reads
    (telemetry/metrics.observe_tick's suspicion lifetimes,
    sample_gauges' piggyback occupancy), layout-neutral: the wide carry
    passes through, the compact carry decodes its relative int16/int8
    encodings exactly like ``_carry_decode`` (without materializing the
    full wide state when only these two lanes are needed)."""
    if not params.compact_carry:
        return state.suspect_deadline, state.spread_until
    dl = state.suspect_deadline.astype(jnp.int32)
    dl = jnp.where(dl == _DEADLINE_NONE16, INT32_MAX, cursor + dl)
    return dl, cursor + state.spread_until.astype(jnp.int32)


@partial(jax.jit, static_argnames=("params", "n_rounds", "spec"),
         donate_argnames=("state", "metrics_state"))
def run_metered(base_key, params: SwimParams, world: SwimWorld,
                n_rounds: int, spec=None,
                state: Optional[SwimState] = None, start_round: int = 0,
                knobs: Optional[Knobs] = None, shift_key=None,
                metrics_state=None):
    """``run`` with the always-on health-metrics registry carried
    through the scan (telemetry/metrics.py).

    Each tick folds its health signals — FD probe outcomes
    (models/fd.probe_outcome_updates), gossip/wire counters, suspicion
    onset/refute/fire transitions and the suspicion-lifetime histogram
    — into one fixed-shape registry pytree
    (``telemetry.metrics.MetricsState``); gauges (queue depths,
    piggyback occupancy, wire saturation) are sampled once from the
    final carry.  ``spec`` (static) declares the registry; ``None`` =
    the default protocol-health spec.  Protocol state and the returned
    per-round metrics are bit-identical to ``run`` on the same
    arguments — the registry only observes.

    Returns ``(final_state, metrics_state, metrics)``.
    ``metrics_state`` resumes a registry across windows
    (``telemetry.metrics.stream_metered_run`` is the windowed-flush
    driver); like ``state`` it is DONATED — don't reuse either after
    the call.  Rounds fuse per ``params.rounds_per_step`` exactly like
    ``run``.

    Thin alias over the composed plane runner
    (models/compose.composed_scan with a single
    ``telemetry.metrics.MetricsPlane``); the scan body lives there.
    """
    from scalecube_cluster_tpu.models import compose
    from scalecube_cluster_tpu.telemetry import metrics as telemetry_metrics

    if spec is None:
        spec = telemetry_metrics.MetricsSpec.default()
    plane = telemetry_metrics.MetricsPlane(spec,
                                           metrics_state=metrics_state)
    final_state, results, metrics = compose.composed_scan(
        base_key, params, world, n_rounds, planes=(plane,), state=state,
        start_round=start_round, knobs=knobs, shift_key=shift_key,
    )
    return final_state, results["metrics"], metrics


def _fused_scan(tick, carry, n_rounds: int, start_round, k: int,
                fused_body=None):
    """Scan ``tick`` over ``n_rounds`` rounds, K ticks per scan step.

    ``tick(carry, round_idx) -> (carry, metrics)``.  The fused body
    unrolls K ticks and stacks their per-round metric rows, so the
    scan's output buffers (and its carry layout fix-ups) are touched
    once per K rounds instead of every round; the stacked
    [steps, K, ...] traces reshape back to [rounds, ...] in row-major
    (= round) order.  A trailing ``n_rounds % K`` remainder runs
    through an unfused tail scan on the same ``tick``, so the result is
    bit-identical to ``k == 1`` for any (n_rounds, K) pair — every
    tick's draws depend only on (base_key, round_idx), never on scan
    position (SwimParams.rounds_per_step docstring).

    ``fused_body(carry, rounds_k) -> (carry, [K, ...]-stacked metrics)``
    overrides the default K-times-``tick`` body — the hook run_traced
    uses to amortize per-step work (one event-record scatter per step
    instead of per round) without changing per-round semantics; it MUST
    stay bit-identical to K sequential ``tick`` applications.
    """
    rounds = jnp.arange(n_rounds, dtype=jnp.int32) + start_round
    steps, rem = divmod(n_rounds, k)
    if k == 1 or steps == 0:
        return jax.lax.scan(tick, carry, rounds)

    if fused_body is None:
        def fused_body(c, rounds_k):
            ms = []
            for j in range(k):
                c, m = tick(c, rounds_k[j])
                ms.append(m)
            return c, jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *ms)

    carry, head = jax.lax.scan(
        fused_body, carry, rounds[:steps * k].reshape(steps, k)
    )
    head = jax.tree_util.tree_map(
        lambda x: x.reshape((steps * k,) + x.shape[2:]), head
    )
    if rem == 0:
        return carry, head
    carry, tail = jax.lax.scan(tick, carry, rounds[steps * k:])
    metrics = jax.tree_util.tree_map(
        lambda a, b: jnp.concatenate([a, b], axis=0), head, tail
    )
    return carry, metrics


@partial(jax.jit, static_argnames=("params", "n_rounds"),
         donate_argnames=("state",))
def run(base_key, params: SwimParams, world: SwimWorld, n_rounds: int,
        state: Optional[SwimState] = None, start_round: int = 0,
        knobs: Optional[Knobs] = None, shift_key=None):
    """Scan the SWIM tick over ``n_rounds`` rounds from ``start_round``.

    Returns (final_state, metrics-dict of [n_rounds, ...] traces).
    ``start_round``/``state`` support checkpoint-resume: re-enter the scan
    at round r with a restored carry (SURVEY.md §5.4).  ``shift_key``:
    optional separate key for the shift-channel draws (swim_tick
    docstring — the shared-shift batching hook for vmapped sweeps).

    ``params.rounds_per_step`` fuses K ticks per scan step (bit-identical
    outputs — _fused_scan docstring).  The ``state`` argument is DONATED:
    the carry's HBM buffers are reused for the result instead of
    double-buffering the membership matrices, so never reuse a state
    object after passing it here — current XLA donates on CPU too, and
    the input buffers really are gone.  Need the previous carry?  Take
    a host snapshot first (``jax.device_get(state)``).

    Thin alias over the composed plane runner
    (models/compose.composed_scan with an empty plane stack); the scan
    body lives there.
    """
    from scalecube_cluster_tpu.models import compose

    final_state, _, metrics = compose.composed_scan(
        base_key, params, world, n_rounds, planes=(), state=state,
        start_round=start_round, knobs=knobs, shift_key=shift_key,
    )
    return final_state, metrics


@partial(jax.jit, static_argnames=("params", "n_rounds", "trace_capacity"),
         donate_argnames=("state", "telemetry"))
def run_traced(base_key, params: SwimParams, world: SwimWorld, n_rounds: int,
               trace_capacity: int = telemetry_trace.DEFAULT_CAPACITY,
               state: Optional[SwimState] = None, start_round: int = 0,
               knobs: Optional[Knobs] = None, shift_key=None,
               telemetry: Optional["telemetry_trace.TelemetryState"] = None):
    """``run`` with the membership event trace carried through the scan.

    The round step additionally derives each cell's net status
    transition (telemetry/trace.derive_event_codes — the dense analog of
    the reference's listener emissions, MembershipProtocolImpl.java:
    543-588), compacts the events into the jit-carried fixed-capacity
    buffer (overflow counted, never silent), and advances the
    first-suspect/first-removed round matrices the in-jit latency
    histograms reduce over (telemetry/trace.latency_histograms).

    Returns (final_state, telemetry_state, metrics).  ``telemetry``
    resumes an existing trace across chunked/checkpointed scans (pass
    the previous chunk's result).  Single-device (like ``run``).

    Rounds fuse per ``params.rounds_per_step`` exactly like ``run`` (the
    trace lanes stay per-round — recording order is round order in both
    layouts), and ``state``/``telemetry`` are DONATED like ``run``'s
    carry — don't reuse either after the call.  For long traced runs,
    ``telemetry.sink.stream_traced_run`` drives this in segments with
    the device→host trace offload overlapped against the next segment's
    compute.

    Thin alias over the composed plane runner
    (models/compose.composed_scan with a single
    ``telemetry.trace.TracePlane`` — its fused-step hook batches the
    event record exactly like the pre-compose body); the scan body
    lives there.
    """
    from scalecube_cluster_tpu.models import compose

    plane = telemetry_trace.TracePlane(capacity=trace_capacity,
                                       telemetry=telemetry)
    final_state, results, metrics = compose.composed_scan(
        base_key, params, world, n_rounds, planes=(plane,), state=state,
        start_round=start_round, knobs=knobs, shift_key=shift_key,
    )
    return final_state, results["trace"], metrics
