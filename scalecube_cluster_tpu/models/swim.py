"""The full SWIM tick: failure detection + gossip + suspicion + SYNC on TPU.

This is the flagship model: the reference's three protocol components —
FailureDetectorImpl (random probe + ping-req), GossipProtocolImpl
(infection-style dissemination) and MembershipProtocolImpl (merge rule,
suspicion timeouts, incarnation self-refutation, SYNC anti-entropy) — lifted
into ONE pure state-transition function over dense arrays, scanned over
protocol rounds with ``jax.lax.scan``.  The lift is faithful because the
reference already runs each node's whole stack single-threaded on one
scheduler (SURVEY.md §1): a node's behavior in a period IS a pure function
of (state, inbound messages, RNG).

State layout — the subject-view matrix
--------------------------------------
``[N, K]`` arrays where row i = observer node, column k = *tracked subject*
(``subject_ids[k]`` is the subject's node index):

  - **full-view mode** (K == N, subjects = everyone): exact dense SWIM,
    every node tracks every node — the reference semantics, O(N²) state,
    practical to ~16k members/chip.
  - **focal mode** (K << N): only K focal subjects' records are tracked
    through the full protocol machinery; the other N-K members are alive
    background that probes, relays gossip and syncs.  State is O(N·K), so
    1M members × 10k rounds fits one chip — this is what produces the
    dissemination / first-false-positive curves at the BASELINE.md scale
    (the reference itself never ran above N=50, SURVEY.md §6).

Time quantization: the gossip period is the base round
(config.ClusterConfig.to_sim); pings fire every ``ping_every`` rounds,
SYNC every ``sync_every``.  Sub-round timing (pingTimeout vs pingInterval,
exponential link delays) is resolved in closed form inside the FD phase by
sampling per-hop delays and comparing sums against the millisecond budgets
— the phased collapse of the 3-hop ping-req flow (SURVEY.md §7 hard parts).

Documented deviations from the reference (all statistical-regime-neutral):
  - fanout targets drawn with replacement (ops/prng.py docstring);
  - FD probe targets drawn uniformly per period instead of round-robin over
    a shuffled pass (FailureDetectorImpl.java:338-347); detection-time
    distributions at large N are indistinguishable, and the SWIM paper
    itself analyzes the uniform variant;
  - the SYNC exchange is push-only per round (the syncAck pull is replaced
    by the partner's own future random pushes — symmetric in distribution);
    an FD ALIVE-verdict on a suspected member pushes the suspect record to
    the member itself (MembershipProtocolImpl.java:379-391's SYNC), whose
    self-refutation then travels back by gossip;
  - gossip per-gossip "infected" sets are not tracked (models/gossip.py).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from scalecube_cluster_tpu import records, swim_math
from scalecube_cluster_tpu.ops import delivery, prng

INT32_MAX = jnp.iinfo(jnp.int32).max


# --------------------------------------------------------------------------
# Static parameters
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SwimParams:
    """Compile-time shape/schedule knobs of the SWIM tick.

    Round-quantized from ClusterConfig via :meth:`from_config`
    (config.ClusterConfig.to_sim describes the quantization rule).
    Millisecond knobs that resolve *within* a round (ping_timeout_ms,
    mean_delay_ms) stay in ms and are compared against sampled hop delays.
    """

    n_members: int
    n_subjects: int
    fanout: int
    periods_to_spread: int
    ping_every: int
    sync_every: int
    suspicion_rounds: int
    ping_req_members: int
    # Sub-round timing (ms), resolved in closed form in the FD phase.
    ping_timeout_ms: float = 500.0
    ping_interval_ms: float = 1000.0
    mean_delay_ms: float = 0.0
    loss_probability: float = 0.0
    # True: FD probes uniformly among *known* subjects (exact reference
    # behavior, full-view mode); False: uniformly over the whole cluster
    # (focal mode, where most members aren't tracked subjects).
    ping_known_only: bool = True
    # Per-subject metric columns (disable for K too large to trace).
    per_subject_metrics: bool = True

    @staticmethod
    def from_config(config, n_members: int, n_subjects: Optional[int] = None,
                    loss_probability: float = 0.0, mean_delay_ms: float = 0.0,
                    **overrides) -> "SwimParams":
        sim = config.to_sim(n_members)
        k = n_members if n_subjects is None else n_subjects
        kwargs = dict(
            n_members=n_members,
            n_subjects=k,
            fanout=sim.gossip_fanout,
            periods_to_spread=sim.periods_to_spread,
            ping_every=sim.ping_every,
            sync_every=sim.sync_every,
            suspicion_rounds=sim.suspicion_rounds,
            ping_req_members=sim.ping_req_members,
            ping_timeout_ms=float(config.ping_timeout),
            ping_interval_ms=float(config.ping_interval),
            mean_delay_ms=mean_delay_ms,
            loss_probability=loss_probability,
            ping_known_only=(k == n_members),
        )
        kwargs.update(overrides)
        return SwimParams(**kwargs)

    @property
    def full_view(self) -> bool:
        return self.n_subjects == self.n_members


# --------------------------------------------------------------------------
# World model: ground truth + fault injection (the NetworkEmulator analog)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class SwimWorld:
    """Ground-truth node liveness + network fault schedule (dynamic arrays).

    The vectorization of the reference's NetworkEmulator
    (transport/NetworkEmulator.java:21-273) plus process-level faults the
    reference injects by stopping transports (MembershipProtocolTest
    partition/restart scenarios, SURVEY.md §4):

      - ``down_from``/``down_until`` [N] int32: node i is crashed during
        rounds [down_from, down_until) — it neither sends, receives, nor
        updates state (frozen, like a stopped JVM); on revival it resumes
        with its old identity and refutes its own death via gossip.
      - ``partition_of`` [P, N] int8: rolling-partition schedule; at round
        r, phase (r // partition_phase_rounds) % P is active, and messages
        cross partition boundaries only if ids match.  A single all-zeros
        phase means no partition (the default).
      - ``subject_ids`` [K] int32 / ``slot_of_node`` [N] int32: the focal
        subject mapping (slot -1 = node is not a tracked subject).
    """

    down_from: jnp.ndarray
    down_until: jnp.ndarray
    partition_of: jnp.ndarray
    partition_phase_rounds: jnp.ndarray  # int32 scalar
    subject_ids: jnp.ndarray
    slot_of_node: jnp.ndarray

    @staticmethod
    def healthy(params: SwimParams,
                subject_ids: Optional[jnp.ndarray] = None) -> "SwimWorld":
        n, k = params.n_members, params.n_subjects
        if subject_ids is None:
            subject_ids = jnp.arange(k, dtype=jnp.int32)
        slot_of_node = (
            jnp.full((n,), -1, dtype=jnp.int32)
            .at[subject_ids]
            .set(jnp.arange(k, dtype=jnp.int32))
        )
        return SwimWorld(
            down_from=jnp.full((n,), INT32_MAX, dtype=jnp.int32),
            down_until=jnp.full((n,), INT32_MAX, dtype=jnp.int32),
            partition_of=jnp.zeros((1, n), dtype=jnp.int8),
            partition_phase_rounds=jnp.int32(1),
            subject_ids=subject_ids,
            slot_of_node=slot_of_node,
        )

    def with_crash(self, node, at_round: int, until_round: int = INT32_MAX):
        """Crash ``node`` (scalar or array) during [at_round, until_round)."""
        node = jnp.atleast_1d(jnp.asarray(node, dtype=jnp.int32))
        return dataclasses.replace(
            self,
            down_from=self.down_from.at[node].set(at_round),
            down_until=self.down_until.at[node].set(until_round),
        )

    def with_partition_schedule(self, partition_of, phase_rounds: int):
        partition_of = jnp.asarray(partition_of, dtype=jnp.int8)
        if partition_of.ndim == 1:
            partition_of = partition_of[None, :]
        return dataclasses.replace(
            self,
            partition_of=partition_of,
            partition_phase_rounds=jnp.int32(phase_rounds),
        )

    def alive_at(self, round_idx):
        """[N] bool ground-truth liveness at a round."""
        return ~((self.down_from <= round_idx) & (round_idx < self.down_until))

    def partition_at(self, round_idx):
        """[N] partition id at a round (rolling schedule)."""
        phase = (round_idx // self.partition_phase_rounds) % self.partition_of.shape[0]
        return jax.lax.dynamic_index_in_dim(
            self.partition_of, phase, axis=0, keepdims=False
        )


jax.tree_util.register_dataclass(
    SwimWorld,
    data_fields=[
        "down_from", "down_until", "partition_of", "partition_phase_rounds",
        "subject_ids", "slot_of_node",
    ],
    meta_fields=[],
)


# --------------------------------------------------------------------------
# Scan carry
# --------------------------------------------------------------------------


@dataclasses.dataclass
class SwimState:
    """Scan carry: the distributed membership state, one row per observer.

    ``status``/``inc`` [N, K]: observer's record of each subject — the dense
    form of ``Map<id, MembershipRecord>`` (MembershipProtocolImpl.java:82).
    A stored DEAD is the deleted-record tombstone that keeps spreading its
    death notice (ops/delivery.merge_inbox docstring).

    ``spread_until``    [N, K] int32: gossip retransmission window for the
                        current record (GossipState.infectionPeriod analog).
    ``suspect_deadline`` [N, K] int32: round at which a SUSPECT entry is
                        declared DEAD (suspicionTimeoutTasks analog,
                        MembershipProtocolImpl.java:96,597-606); INT32_MAX
                        when no timer is pending.
    ``self_inc``        [N] int32: own incarnation (bumped by refutation,
                        MembershipProtocolImpl.java:488-509).
    """

    status: jnp.ndarray
    inc: jnp.ndarray
    spread_until: jnp.ndarray
    suspect_deadline: jnp.ndarray
    self_inc: jnp.ndarray


jax.tree_util.register_dataclass(
    SwimState,
    data_fields=["status", "inc", "spread_until", "suspect_deadline", "self_inc"],
    meta_fields=[],
)


def initial_state(params: SwimParams, world: SwimWorld,
                  warm: bool = True) -> SwimState:
    """Warm start: everyone knows every subject ALIVE at incarnation 0.

    (The post-join steady state; seed-join growth is exercised separately
    by starting rows ABSENT.)  A node's record about *itself* is pinned
    ALIVE at its own incarnation.
    """
    n, k = params.n_members, params.n_subjects
    fill = records.ALIVE if warm else records.ABSENT
    status = jnp.full((n, k), fill, dtype=jnp.int8)
    is_self = world.subject_ids[None, :] == jnp.arange(n, dtype=jnp.int32)[:, None]
    status = jnp.where(is_self, records.ALIVE, status)
    return SwimState(
        status=status,
        inc=jnp.zeros((n, k), dtype=jnp.int32),
        spread_until=jnp.zeros((n, k), dtype=jnp.int32),
        suspect_deadline=jnp.full((n, k), INT32_MAX, dtype=jnp.int32),
        self_inc=jnp.zeros((n,), dtype=jnp.int32),
    )


# --------------------------------------------------------------------------
# The tick
# --------------------------------------------------------------------------


def _hop_ok(key, loss_probability, mean_delay_ms, budget_ms, n_hops, shape):
    """P2P multi-hop success: every hop delivered AND total delay <= budget.

    Vectorizes NetworkLinkSettings.evaluateLoss/evaluateDelay
    (transport/NetworkLinkSettings.java:54-74) over ``n_hops`` chained hops
    with a shared millisecond budget (the reference's Reactor
    ``.timeout(duration)``, FailureDetectorImpl.java:152).
    """
    keys = jax.random.split(key, n_hops * 2)
    ok = jnp.ones(shape, dtype=jnp.bool_)
    total_delay = jnp.zeros(shape, dtype=jnp.float32)
    for h in range(n_hops):
        ok &= ~prng.bernoulli_mask(keys[2 * h], loss_probability, shape)
        total_delay += prng.exponential_delay(keys[2 * h + 1], mean_delay_ms, shape)
    return ok & (total_delay <= budget_ms)


def swim_tick(state: SwimState, round_idx, base_key, params: SwimParams,
              world: SwimWorld, offset=0, axis_name: Optional[str] = None):
    """One protocol round.  Pure: (state, r, key) -> (state', metrics).

    Phases (matching the reference's periodic loops, SURVEY.md §3.2-3.4):
      1. FD probe (every ping_every rounds): pick target, direct ping with
         ping_timeout, else ping-req via k proxies — collapsed in closed
         form over the loss/delay model; SUSPECT verdicts merge locally,
         ALIVE-on-suspected pushes the record to the subject (SYNC analog).
      2. Gossip send: every node pushes its hot records to fanout targets.
      3. SYNC (every sync_every rounds): push the full row to one random
         member (anti-entropy, MembershipProtocolImpl.java:439-454).
      4. Merge all inboxes through the is_overrides lattice; self-records
         refute (incarnation bump); suspicion timers set/cancel/fire.

    Sharding: ``state`` rows may be a contiguous slice of the global member
    axis (``offset`` = first global row).  Senders scatter into a
    global-height inbox contribution; under ``shard_map`` the contributions
    combine with one ``lax.pmax`` over ``axis_name`` — the ICI collective
    that replaces the reference's point-to-point TCP (SURVEY.md §5.8) —
    and each device keeps its own row slice.  With ``axis_name=None`` and
    ``offset=0`` this is the single-device path unchanged.
    """
    n, k = params.n_members, params.n_subjects
    n_local = state.status.shape[0]
    # Fold both the round and the shard offset so draws are independent
    # across rounds AND across devices (ops/prng.py module docstring).
    key = prng.round_key(prng.round_key(base_key, round_idx), offset)
    (k_ping_t, k_ping_net, k_proxy, k_proxy_net, k_gossip_t, k_gossip_drop,
     k_sync_t, k_sync_drop) = jax.random.split(key, 8)

    def combine_max(buf):
        """Cross-device inbox combine + own-row slice."""
        if axis_name is not None:
            buf = jax.lax.pmax(buf, axis_name)
        if n_local == n and axis_name is None:
            return buf
        return jax.lax.dynamic_slice_in_dim(buf, offset, n_local, axis=0)

    def global_sum(x):
        return jax.lax.psum(x, axis_name) if axis_name is not None else x

    alive = world.alive_at(round_idx)                       # [N] ground truth
    part = world.partition_at(round_idx)                    # [N]
    node_ids = jnp.arange(n_local, dtype=jnp.int32) + offset    # global ids
    alive_here = alive[node_ids]                            # [n_local]
    is_self = world.subject_ids[None, :] == node_ids[:, None]   # [n_local, K]

    # Row i's record about itself is pinned (a node always believes itself
    # ALIVE at self_inc — MembershipProtocolImpl drops self-updates and
    # refutes instead, :488-509).
    status = jnp.where(is_self, records.ALIVE, state.status)
    inc = jnp.where(is_self, state.self_inc[:, None], state.inc)

    def same_partition(a_ids, b_ids):
        return part[a_ids] == part[b_ids]

    # ---- Phase 1: failure detector probe --------------------------------
    fd_round = (round_idx % params.ping_every) == 0

    if params.ping_known_only:
        # Uniform among known live-record subjects (FailureDetectorImpl
        # pingMembers list, :48-49) — exact in full-view mode.
        eligible = (~is_self) & (
            (status == records.ALIVE) | (status == records.SUSPECT)
        )
        slot, has_target = prng.choose_eligible(k_ping_t, eligible)
        ping_target = world.subject_ids[slot]               # [n_local] node ids
    else:
        # Focal mode: probe the whole cluster uniformly; only probes that
        # land on tracked subjects affect tracked state.
        ping_target = prng.targets_excluding_self(
            k_ping_t, n_local, n, 1, sender_offset=offset
        )[:, 0]
        slot = world.slot_of_node[ping_target]              # -1 = untracked
        has_target = slot >= 0
        eligible_t = (
            jnp.take_along_axis(status, jnp.maximum(slot, 0)[:, None], 1)[:, 0]
        )
        has_target &= (eligible_t == records.ALIVE) | (eligible_t == records.SUSPECT)

    t = ping_target
    # Direct ping: 2 hops within ping_timeout (FailureDetectorImpl.java:128-176).
    direct_ok = (
        _hop_ok(k_ping_net, params.loss_probability, params.mean_delay_ms,
                params.ping_timeout_ms, 2, (n_local,))
        & alive[t] & same_partition(node_ids, t)
    )
    # Ping-req through R proxies: 4 hops within (ping_interval - ping_timeout)
    # (:178-213; transit relay :258-315).
    r_proxies = params.ping_req_members
    proxies = prng.targets_excluding_self(
        k_proxy, n_local, n, r_proxies, sender_offset=offset
    )
    proxy_ok = (
        _hop_ok(k_proxy_net, params.loss_probability, params.mean_delay_ms,
                params.ping_interval_ms - params.ping_timeout_ms, 4,
                (n_local, r_proxies))
        & alive[proxies] & alive[t][:, None]
        & same_partition(node_ids[:, None], proxies)
        & same_partition(proxies, t[:, None])
        & (proxies != t[:, None])
    )
    ack_ok = direct_ok | jnp.any(proxy_ok, axis=1)
    probe_active = fd_round & has_target & alive_here       # [n_local]
    verdict_suspect = probe_active & ~ack_ok
    verdict_alive = probe_active & ack_ok

    # SUSPECT verdict -> local record (SUSPECT, entry inc) for the target
    # slot (onFailureDetectorEvent, MembershipProtocolImpl.java:392-397).
    slot_safe = jnp.maximum(slot, 0)
    fd_slot_onehot = (
        jnp.arange(k, dtype=jnp.int32)[None, :] == slot_safe[:, None]
    )
    fd_suspect_key = delivery.pack_record(
        jnp.int8(records.SUSPECT),
        jnp.take_along_axis(inc, slot_safe[:, None], 1)[:, 0],
    )
    fd_inbox = jnp.where(
        fd_slot_onehot & verdict_suspect[:, None],
        fd_suspect_key[:, None],
        delivery.NO_MESSAGE,
    )

    # ALIVE verdict on a suspected entry -> push the suspect record to the
    # member itself so it can refute (the reference sends SYNC there,
    # :379-391; the refutation travels back via gossip).
    entry_t_status = jnp.take_along_axis(status, slot_safe[:, None], 1)[:, 0]
    push_refute = verdict_alive & (entry_t_status == records.SUSPECT)

    # ---- Phase 2 + 3: gossip and SYNC sends ------------------------------
    # Hot records: changed within the spread window; DEAD tombstones
    # transmit their death notice (GossipProtocolImpl.java:239-250).
    hot = (status != records.ABSENT) & (round_idx < state.spread_until)
    record_keys = delivery.pack_record(status, inc)          # [n_local, K]
    gossip_keys = jnp.where(hot, record_keys, delivery.NO_MESSAGE)

    gossip_targets = prng.targets_excluding_self(
        k_gossip_t, n_local, n, params.fanout, sender_offset=offset
    )
    send_ok = alive_here[:, None] & alive[gossip_targets] \
        & same_partition(node_ids[:, None], gossip_targets)
    gossip_drop = (
        prng.bernoulli_mask(k_gossip_drop, params.loss_probability,
                            (n_local, params.fanout))
        | ~send_ok
    )

    # SYNC: full-row push to one random member (doSync,
    # MembershipProtocolImpl.java:298-314) — tombstones masked out (the
    # reference table holds no DEAD records, so SYNC never carries them).
    sync_round = (round_idx % params.sync_every) == 0
    sync_keys = jnp.where(status == records.DEAD, delivery.NO_MESSAGE, record_keys)
    sync_target = prng.targets_excluding_self(
        k_sync_t, n_local, n, 1, sender_offset=offset
    )
    # FD's alive-on-suspected push reuses the sync channel, aimed at the
    # suspected member itself.
    sync_target = jnp.where(push_refute[:, None], t[:, None], sync_target)
    do_sync = (sync_round & alive_here) | push_refute
    sync_ok = (
        alive[sync_target[:, 0]]
        & same_partition(node_ids, sync_target[:, 0])
        & ~prng.bernoulli_mask(k_sync_drop, params.loss_probability, (n_local,))
    )
    sync_drop = (~(do_sync & sync_ok))[:, None]

    # Accumulate all send channels into one global-height contribution,
    # then a single cross-device combine (one pmax per round).
    inbox_buf = jnp.maximum(
        delivery.scatter_max(gossip_keys, gossip_targets, gossip_drop, n),
        delivery.scatter_max(sync_keys, sync_target, sync_drop, n),
    )
    alive_flags = (gossip_keys >= 0) & (status == records.ALIVE)
    sync_alive_flags = (sync_keys >= 0) & (status == records.ALIVE)
    alive_buf = (
        delivery.scatter_or(alive_flags, gossip_targets, gossip_drop, n)
        | delivery.scatter_or(sync_alive_flags, sync_target, sync_drop, n)
    )
    inbox = combine_max(inbox_buf)
    inbox_alive = combine_max(alive_buf.astype(jnp.int8)).astype(jnp.bool_)

    # FD local verdicts fold into the same inbox (observer-local, no comm).
    inbox = jnp.maximum(inbox, fd_inbox)

    # ---- Phase 4: merge + timers ----------------------------------------
    new_status, new_inc, changed = delivery.merge_inbox(
        status, inc, inbox, inbox_alive
    )

    # Self-refutation (updateMembership about-self branch, :488-509): if the
    # inbound winner about ME overrides my ALIVE@self_inc record, bump to
    # max(inc)+1 and gossip the refutation (spread reset via `changed`).
    win_status, win_inc = delivery.unpack_record(inbox)
    self_overridden = is_self & records.is_overrides_array(
        win_status, win_inc, records.ALIVE, state.self_inc[:, None]
    )
    refuted = jnp.any(self_overridden, axis=1)
    bumped_inc = jnp.maximum(
        state.self_inc,
        jnp.max(jnp.where(self_overridden, win_inc, 0), axis=1),
    ) + 1
    new_self_inc = jnp.where(refuted & alive_here, bumped_inc, state.self_inc)
    new_status = jnp.where(is_self, records.ALIVE, new_status)
    new_inc = jnp.where(is_self, new_self_inc[:, None], new_inc)
    changed = jnp.where(is_self, self_overridden & alive_here[:, None], changed)

    # Suspicion timers (scheduleSuspicionTimeoutTask / cancel,
    # MembershipProtocolImpl.java:518-523,590-606).  ``computeIfAbsent``
    # semantics: an accepted SUSPECT update does NOT reset a pending timer;
    # any accepted non-SUSPECT update cancels it.
    no_timer = state.suspect_deadline == INT32_MAX
    start_timer = changed & (new_status == records.SUSPECT) & no_timer
    cancel_timer = changed & (new_status != records.SUSPECT)
    deadline = jnp.where(
        start_timer,
        round_idx + params.suspicion_rounds,
        jnp.where(cancel_timer, INT32_MAX, state.suspect_deadline),
    )
    # Timer fires -> DEAD at the same incarnation (onSuspicionTimeout,
    # :608-618); the tombstone spreads its death notice.
    fired = (new_status == records.SUSPECT) & (round_idx >= deadline)
    new_status = jnp.where(fired, records.DEAD, new_status)
    deadline = jnp.where(fired, INT32_MAX, deadline)
    changed = changed | fired

    # Crashed nodes are frozen (a stopped JVM): no state updates at all.
    frozen = ~alive_here[:, None]
    new_status = jnp.where(frozen, status, new_status)
    new_inc = jnp.where(frozen, inc, new_inc)
    deadline = jnp.where(frozen, state.suspect_deadline, deadline)
    changed = changed & ~frozen

    spread_until = jnp.where(
        changed, round_idx + 1 + params.periods_to_spread, state.spread_until
    )

    new_state = SwimState(
        status=new_status.astype(jnp.int8),
        inc=new_inc.astype(jnp.int32),
        spread_until=spread_until.astype(jnp.int32),
        suspect_deadline=deadline.astype(jnp.int32),
        self_inc=new_self_inc.astype(jnp.int32),
    )

    # ---- Metrics (the per-round observability tensors, SURVEY.md §5.1) ---
    observer_alive = alive_here[:, None]
    subject_alive = alive[world.subject_ids][None, :]
    counts = {}
    for name, code in (("alive", records.ALIVE), ("suspect", records.SUSPECT),
                       ("dead", records.DEAD), ("absent", records.ABSENT)):
        mask = (new_status == code) & observer_alive & ~is_self
        counts[name] = global_sum(
            jnp.sum(mask, axis=0, dtype=jnp.int32)
            if params.per_subject_metrics
            else jnp.sum(mask, dtype=jnp.int32)
        )
    # False positive: a live observer holds SUSPECT/DEAD about a live subject.
    fp_mask = (
        ((new_status == records.SUSPECT) | (new_status == records.DEAD))
        & observer_alive & subject_alive & ~is_self
    )
    metrics = dict(
        counts,
        false_positives=global_sum(
            jnp.sum(fp_mask, axis=0, dtype=jnp.int32)
            if params.per_subject_metrics
            else jnp.sum(fp_mask, dtype=jnp.int32)
        ),
        messages_gossip=global_sum(jnp.sum(
            jnp.any(hot, axis=1)[:, None] & ~gossip_drop, dtype=jnp.int32
        )),
        messages_ping=global_sum(jnp.sum(probe_active, dtype=jnp.int32)),
        refutations=global_sum(jnp.sum(refuted & alive_here, dtype=jnp.int32)),
    )
    return new_state, metrics


@partial(jax.jit, static_argnames=("params", "n_rounds"))
def run(base_key, params: SwimParams, world: SwimWorld, n_rounds: int,
        state: Optional[SwimState] = None, start_round: int = 0):
    """Scan the SWIM tick over ``n_rounds`` rounds from ``start_round``.

    Returns (final_state, metrics-dict of [n_rounds, ...] traces).
    ``start_round``/``state`` support checkpoint-resume: re-enter the scan
    at round r with a restored carry (SURVEY.md §5.4).
    """
    if state is None:
        state = initial_state(params, world)

    def body(carry, round_idx):
        return swim_tick(carry, round_idx, base_key, params, world)

    rounds = jnp.arange(n_rounds, dtype=jnp.int32) + start_round
    return jax.lax.scan(body, state, rounds)
