"""The provenance plane: per-belief CHANNEL ATTRIBUTION, in-jit.

The trace plane (telemetry/trace.py) records THAT a belief changed;
the metrics plane (telemetry/metrics.py) records HOW OFTEN.  Neither
records *via which channel* the evidence arrived — so a false-positive
death cannot be traced back to the faulty link that planted it.  This
plane closes that gap: for every (observer, subject) status transition
it names the winning channel —

  CH_FD_DIRECT        the observer's own failure-detector verdict: a
                      direct-probe timeout (SUSPECT) or the suspicion
                      timer firing (DEAD) — first-hand evidence
  CH_PINGREQ_PROXY    the FD verdict reached THROUGH proxies: the
                      direct probe failed and k ping-req proxies were
                      launched before the verdict (Lifeguard's
                      indirect-probe stage)
  CH_GOSSIP           a piggybacked membership record on the gossip
                      fanout — the infection-style channel
  CH_SYNC             a SYNC family exchange: periodic anti-entropy,
                      a refutation push, or the joiner<->seed round
                      trip (the join path IS a SYNC exchange)
  CH_SELF_REFUTATION  the observer is the subject and bumped its own
                      incarnation to refute a suspicion about itself
  CH_JOIN_REBIRTH     the subject was ADMITTED into the slot this very
                      round (open-world JOIN); later observers that
                      learn of the admission through the wire attribute
                      to the carrying channel, not to the admission

by comparing the round's folded winner key against the per-channel
folded maxima the tick bodies expose when ``SwimParams.provenance`` is
on (models/swim.py: scatter, shift, k_block, and both pipelined
halves expose ``dict(fd=, gossip=, sync=, ping_req=)`` into the shared
``RoundCtx``).  The exposure is strictly ADDITIVE — the combined inbox
dataflow is textually untouched, so the off-switch is bit-identical
and the on-switch is state-identical (tests/test_provenance.py pins
both).

The attribution cascade is TOTAL: every transitioned cell gets exactly
one channel (the bench gate checks the fractions sum to 1.0).
Priority, most-specific first: join-rebirth, then timer-fired removals
(a DEAD transition whose wire winner is not DEAD came from the local
suspicion timer — FD), then the FD key when it ties the winner (split
direct vs ping-req-proxy by the per-row launch flag), then SYNC on a
winner tie (SYNC beats GOSSIP: the exchange is the more specific
evidence when both delivered the identical key), then GOSSIP, with FD
as the residual fallback (a transition none of the wire maxima explain
is first-hand by elimination — e.g. the merge funnel's own in-tick
edges).

Records land in a fixed-capacity overflow-counted buffer — the
record_events_batch idiom from telemetry/trace.py: one cumsum + one
scatter per round, nothing silently truncated — journaled host-side as
the ``provenance`` record kind (telemetry/sink.py) and mined by the
blame engine (telemetry/query.py: infection paths, channel-mix SLOs,
``python -m scalecube_cluster_tpu.telemetry explain``).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from scalecube_cluster_tpu import records
from scalecube_cluster_tpu.ops import delivery
from scalecube_cluster_tpu.telemetry import trace as ttrace
from scalecube_cluster_tpu.telemetry.events import TraceEventType

# Channel codes, in cascade-priority order (decode_attributions and the
# blame engine name them through CHANNEL_NAMES; tests pin the values).
CH_FD_DIRECT = 0
CH_PINGREQ_PROXY = 1
CH_GOSSIP = 2
CH_SYNC = 3
CH_SELF_REFUTATION = 4
CH_JOIN_REBIRTH = 5

CHANNEL_NAMES = ("fd_direct", "pingreq_proxy", "gossip", "sync",
                 "self_refutation", "join_rebirth")

# (observer, subject, epoch, transition, channel, round) per record.
_N_LANES = 6

# Same sizing logic as the event trace: a transition emits at most one
# record per (observer, subject) cell per round, so the crash-scenario
# envelope matches the trace plane's; 65536 x 6 lanes x 4 B = 1.5 MB.
DEFAULT_CAPACITY = 1 << 16


# --------------------------------------------------------------------------
# Carried state
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ProvenanceState:
    """Fixed-capacity attribution buffer (module docstring).

    ``lanes[i] = (observer, subject, epoch, transition, channel,
    round)`` for i < ``count``, in (round, observer-major cell) order;
    ``dropped`` counts records lost to overflow — the decoded buffer is
    an exact prefix of the attribution stream, never a silent sample.
    """

    lanes: jnp.ndarray      # [capacity, 6] int32
    count: jnp.ndarray      # int32 scalar: records written (<= capacity)
    dropped: jnp.ndarray    # int32 scalar: records lost to overflow

    @property
    def capacity(self) -> int:
        return self.lanes.shape[0]

    @staticmethod
    def empty(capacity: int = DEFAULT_CAPACITY) -> "ProvenanceState":
        return ProvenanceState(
            lanes=jnp.full((capacity, _N_LANES), -1, dtype=jnp.int32),
            count=jnp.int32(0),
            dropped=jnp.int32(0),
        )


jax.tree_util.register_dataclass(
    ProvenanceState, data_fields=["lanes", "count", "dropped"],
    meta_fields=[],
)


# --------------------------------------------------------------------------
# The attribution cascade (pure, unit-testable)
# --------------------------------------------------------------------------


def attribute_channels(params, prov, codes, join_now):
    """[n_local, K] int8 channel code per cell (module-docstring cascade).

    ``prov`` is the tick's exposure dict (``fd``/``gossip``/``sync``
    [n_local, K] wire keys, ``ping_req`` [n_local] bool); ``codes`` the
    round's transition codes (0 = no event — those cells' channel
    values are meaningless and masked out by the recorder); ``join_now``
    [n_local, K] bool marks cells whose subject is ADMITTED this round.

    The cascade is a where-chain from least to most specific, so the
    most specific test wins — total by construction (the FD fallback is
    the chain's base), which is exactly the "every transition gets one
    channel" bench gate.
    """
    fd = prov["fd"]
    gossip = prov["gossip"]
    sync = prov["sync"]
    winner = jnp.maximum(fd, jnp.maximum(sync, gossip))
    w_status, _ = delivery.unpack_record(
        winner, fmt=params.wire_format, epoch_bits=params.epoch_bits)

    chan = jnp.full(codes.shape, jnp.int8(CH_FD_DIRECT), dtype=jnp.int8)
    gossip_wins = (gossip >= 0) & (gossip == winner)
    chan = jnp.where(gossip_wins, jnp.int8(CH_GOSSIP), chan)
    # SYNC beats GOSSIP on a key tie (both channels delivered the
    # identical record): the exchange is the direct conversation.
    sync_wins = (sync >= 0) & (sync == winner)
    chan = jnp.where(sync_wins, jnp.int8(CH_SYNC), chan)
    # The FD verdict beats both when it ties the winner: first-hand
    # evidence outranks relays carrying the same record.
    fd_wins = (fd >= 0) & (fd == winner)
    if params.ping_req_members > 0:
        # The launch flag fires on any failed direct probe; only with
        # proxies configured does it mean the verdict went THROUGH them.
        fd_code = jnp.where(prov["ping_req"][:, None],
                            jnp.int8(CH_PINGREQ_PROXY),
                            jnp.int8(CH_FD_DIRECT))
    else:
        fd_code = jnp.int8(CH_FD_DIRECT)
    chan = jnp.where(fd_wins, fd_code, chan)
    # A removal no wire key explains is the local suspicion timer
    # firing — the FD's second-stage verdict, not a relay.
    timer_fired = (codes == jnp.int8(TraceEventType.REMOVED + 1)) \
        & (w_status != records.DEAD)
    chan = jnp.where(timer_fired, jnp.int8(CH_FD_DIRECT), chan)
    chan = jnp.where(join_now, jnp.int8(CH_JOIN_REBIRTH), chan)
    return chan


def round_channel_records(rc):
    """(codes, channels, epochs) of one tick's attributed transitions.

    ``codes`` [n_local, K] int8 (0 = none, else TraceEventType + 1 —
    the trace plane's exact derivation, so both planes agree on what
    transitioned); ``channels`` int8 channel per coded cell; ``epochs``
    int32 identity epoch of the cell AFTER the tick (0 with the
    open-world plane off).  Self-refutations — the observer bumping its
    own incarnation — overlay the (pinned, code-0) self cell with an
    ALIVE_REFUTED @ CH_SELF_REFUTATION record.
    """
    prev_epoch = rc.prev.epoch if rc.params.epoch_bits else None
    codes, _ = ttrace.round_transition_codes(
        rc.round_idx, rc.prev.status, rc.prev.inc, rc.new, rc.world,
        observer_offset=rc.offset, prev_epoch=prev_epoch,
    )
    n_local = rc.prev.status.shape[0]
    node_ids = jnp.arange(n_local, dtype=jnp.int32) + rc.offset
    subject_ids = jnp.asarray(rc.world.subject_ids, jnp.int32)
    join_now = (rc.world.join_at[subject_ids] == rc.round_idx)[None, :]
    channels = attribute_channels(rc.params, rc.provenance, codes,
                                  join_now)

    # Self-refutation: the tick pins self cells, so the suspicion the
    # observer refuted lives only in the self_inc bump — surface it as
    # its own record on the (code-0) self cell.
    refuted = jnp.asarray(rc.new.self_inc, jnp.int32) \
        > jnp.asarray(rc.prev.self_inc, jnp.int32)
    is_self = subject_ids[None, :] == node_ids[:, None]
    self_refute = is_self & refuted[:, None] & (codes == 0)
    codes = jnp.where(
        self_refute, jnp.int8(TraceEventType.ALIVE_REFUTED + 1), codes)
    channels = jnp.where(self_refute, jnp.int8(CH_SELF_REFUTATION),
                         channels)

    if rc.params.epoch_bits:
        epochs = jnp.asarray(rc.new.epoch, jnp.int32)
    else:
        epochs = jnp.zeros(codes.shape, dtype=jnp.int32)
    return codes, channels, epochs


#: Gather-compact window of the fast record path: a round with at most
#: this many attributed cells writes ONE contiguous [window, 6] block
#: (searchsorted + gather + dynamic_update_slice) instead of a sparse
#: [N*K, 6] scatter — the XLA CPU scatter is a row-wise scalar loop and
#: was the whole measured provenance overhead (bench.py --blame).
#: Bursts beyond the window, and rounds near the buffer's capacity,
#: take the exact scatter path instead, so semantics never change.
COMPACT_WINDOW = 256


def record_attributions(pv: ProvenanceState, round_idx, codes, channels,
                        epochs, subject_ids,
                        observer_offset: int = 0) -> ProvenanceState:
    """Compact one round's attributed cells into the buffer — the
    telemetry/trace.record_events_batch idiom (cumsum slot assignment,
    exact overflow count), under a ``lax.cond`` that skips silent
    rounds entirely.

    Two record paths, bit-identical in what they append (same rows,
    same flat order, same count/dropped accounting):

    - FAST (the common case): when the round's burst fits
      :data:`COMPACT_WINDOW` and the buffer has a full window of
      headroom, the changed cells are gather-compacted into one
      ``[window, 6]`` block and written with a single contiguous
      ``dynamic_update_slice`` at ``count`` — no sparse scatter.
    - EXACT: bigger bursts and the buffer's last window fall back to
      the ``mode="drop"`` scatter, which handles overflow precisely.
    """
    n, k = codes.shape
    cap = pv.capacity
    flat_code = codes.reshape(-1)
    has = flat_code > 0
    observer = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32)[:, None] + observer_offset, (n, k)
    ).reshape(-1)
    subject = jnp.broadcast_to(
        jnp.asarray(subject_ids, jnp.int32)[None, :], (n, k)
    ).reshape(-1)
    flat_chan = channels.reshape(-1)
    flat_epoch = epochs.reshape(-1)
    flat_round = jnp.broadcast_to(
        jnp.asarray(round_idx, jnp.int32), (n * k,))

    window = min(cap, COMPACT_WINDOW, n * k)
    c = jnp.cumsum(has.astype(jnp.int32))
    total = c[-1]

    def fast(p: ProvenanceState) -> ProvenanceState:
        # m-th changed cell = first flat index with cumsum >= m (the
        # cumsum increments exactly at changed cells), so a vectorized
        # searchsorted recovers the compacted source order.
        src = jnp.searchsorted(
            c, jnp.arange(1, window + 1, dtype=jnp.int32))
        src = jnp.minimum(src, n * k - 1)
        valid = jnp.arange(window, dtype=jnp.int32) < total
        block = jnp.stack([
            observer[src],
            subject[src],
            flat_epoch[src],
            flat_code[src].astype(jnp.int32) - 1,
            flat_chan[src].astype(jnp.int32),
            flat_round[src],
        ], axis=1)
        offs = jnp.minimum(p.count, cap - window)  # == p.count here
        existing = jax.lax.dynamic_slice(
            p.lanes, (offs, jnp.int32(0)), (window, _N_LANES))
        block = jnp.where(valid[:, None], block, existing)
        lanes = jax.lax.dynamic_update_slice(
            p.lanes, block, (offs, jnp.int32(0)))
        # total <= window and count + window <= cap: no overflow here.
        return ProvenanceState(lanes=lanes, count=p.count + total,
                               dropped=p.dropped)

    def exact(p: ProvenanceState) -> ProvenanceState:
        slot = p.count + c - 1
        idx = jnp.where(has & (slot < cap), slot, cap)  # cap = OOB -> drop
        rows = jnp.stack([
            observer,
            subject,
            flat_epoch,
            flat_code.astype(jnp.int32) - 1,
            flat_chan.astype(jnp.int32),
            flat_round,
        ], axis=1)
        lanes = p.lanes.at[idx].set(rows, mode="drop")
        new_count = jnp.minimum(p.count + total, cap)
        new_dropped = p.dropped + total - (new_count - p.count)
        return ProvenanceState(lanes=lanes, count=new_count,
                               dropped=new_dropped)

    def record(p: ProvenanceState) -> ProvenanceState:
        use_fast = (total <= window) & (p.count + window <= cap)
        return jax.lax.cond(use_fast, fast, exact, p)

    return jax.lax.cond(jnp.any(has), record, lambda p: p, pv)


def observe_round(pv: ProvenanceState, rc) -> ProvenanceState:
    """One round's provenance update: derive + attribute + record.

    The WHOLE derivation rides a ``lax.cond`` on the trace plane's
    event predicate (telemetry/trace.observe_round_codes: any status
    change, a scheduled leave, an epoch flip) widened with the
    self-incarnation bump — the one transition the provenance plane
    records that moves no status bit.  Event-free rounds — most of a
    healthy run — reduce to four cheap reductions, which is what keeps
    the armed stack inside the overhead gate (bench.py --blame)."""
    if rc.provenance is None:
        raise ValueError(
            "the provenance plane needs the tick's per-channel exposure: "
            "set SwimParams.provenance=True (the knob arms the maxima "
            "the attribution cascade reads)"
        )
    n_local = rc.prev.status.shape[0]
    node_ids = jnp.arange(n_local, dtype=jnp.int32) + rc.offset
    pred = rc.any_status_change | jnp.any(
        rc.world.leave_at[node_ids] == rc.round_idx)
    if rc.params.epoch_bits:
        pred = pred | jnp.any(
            jnp.asarray(rc.prev.epoch) != jnp.asarray(rc.new.epoch))
    pred = pred | jnp.any(
        jnp.asarray(rc.new.self_inc, jnp.int32)
        > jnp.asarray(rc.prev.self_inc, jnp.int32))

    def active(p: ProvenanceState) -> ProvenanceState:
        codes, channels, epochs = round_channel_records(rc)
        return record_attributions(p, rc.round_idx, codes, channels,
                                   epochs, rc.world.subject_ids,
                                   observer_offset=rc.offset)

    return jax.lax.cond(pred, active, lambda p: p, pv)


# --------------------------------------------------------------------------
# The compose() plane
# --------------------------------------------------------------------------


class ProvenancePlane:
    """Channel attribution as a composed-runner plane
    (models/compose.py): carry slice = :class:`ProvenanceState`,
    per-round hook = :func:`observe_round` reading the shared round
    context's ``provenance`` exposure.  No fused pair — the plane folds
    once per tick inside a fused body (the exposure is per-round by
    construction); the batched driver reaches it through
    ``BatchRoundCtx.per_row_fold``.

    ``state`` resumes an existing buffer across chunked scans.
    """

    name = "provenance"

    def __init__(self, capacity: int = DEFAULT_CAPACITY, state=None):
        self.capacity = capacity
        self.state = state

    def init(self, params, world):
        if not params.provenance:
            raise ValueError(
                "ProvenancePlane requires SwimParams.provenance=True: "
                "with the knob off the tick bodies compile the "
                "per-channel exposure out and there is nothing to "
                "attribute"
            )
        if self.state is not None:
            return self.state
        return ProvenanceState.empty(self.capacity)

    def on_round(self, rc, pv):
        return observe_round(pv, rc)

    def finalize(self, fc, pv):
        return pv


# --------------------------------------------------------------------------
# Host-side decoding
# --------------------------------------------------------------------------


def decode_attributions(pv: ProvenanceState) -> list:
    """Device buffer -> plain-dict rows (host side), the exact recorded
    prefix in (round, observer-major cell) order.  ``transition`` is
    the TraceEventType name, ``channel`` the CHANNEL_NAMES entry —
    the same spelling the journal record and the blame engine use."""
    lanes = np.asarray(pv.lanes)
    count = int(pv.count)
    out = []
    for i in range(count):
        obs, subj, epoch, code, chan, rnd = (int(v) for v in lanes[i])
        out.append(dict(
            observer=obs, subject=subj, epoch=epoch,
            transition=TraceEventType(code).name,
            channel=CHANNEL_NAMES[chan], round=rnd,
        ))
    return out


def attributions_payload(pv: ProvenanceState) -> dict:
    """The journal payload of the ``provenance`` record kind
    (telemetry/sink.py): decoded rows + exact buffer accounting."""
    return dict(
        rows=decode_attributions(pv),
        recorded=int(pv.count),
        dropped=int(pv.dropped),
        capacity=int(pv.capacity),
    )
