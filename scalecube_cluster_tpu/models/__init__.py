"""The dense TPU tick models.

  - ``swim``    the flagship full-protocol tick (FD + gossip + suspicion
                + SYNC), two delivery modes, fault injection, delay rings
  - ``gossip``  infection-only dissemination (GossipProtocolImpl analog)
  - ``fd``      failure detection in isolation (FailureDetectorTest's
                stubbed-membership setup; BASELINE config 3)
"""
