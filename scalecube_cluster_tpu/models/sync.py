"""SYNC anti-entropy plane: periodic full-table exchange for partition heal.

The reference's signature non-paper extension is MembershipProtocolImpl's
periodic SYNC (doSync -> onSync -> SYNC_ACK, MembershipProtocolImpl.java:
298-331,346-367): every ``syncInterval`` each member exchanges its FULL
membership table with one peer drawn from seeds ∪ live members, and both
sides merge by the incarnation-precedence rules.  Infection-style
piggyback gossip (Das et al., 2002) only carries *recent* updates — two
halves healed after a long partition can disagree forever about events
that aged out of the spread window (the gossip payload mask in
``models/swim._send_components``).  Anti-entropy epidemic repair
(Demers et al., 1987) closes exactly that gap: the full-state exchange
re-seeds the stale disagreements into the table merge, whose accepted
records re-enter the hot gossip window and disseminate epidemically —
so a healed partition re-converges within roughly one sync interval
plus one dissemination bound.

This module is the device-side form of that plane, composed into the
SWIM tick (``SwimParams.sync_interval`` rounds; 0 — the default — is
OFF and compiles the plane out entirely, leaving every run shape
bit-identical to the plane-less tick).

Exchange topology — the paired-offset deviation (documented)
------------------------------------------------------------
The reference's doSync draws one peer per member from seeds ∪ live
candidates and completes a request/reply round trip.  A per-member
random peer with a reply is a gather across the member axis — hostile
to the sharded row layout (the reply's source rows live on other
devices).  Instead the plane draws ONE shared ring offset ``s`` per
exchange round (from the round key all devices agree on, like shift
mode's channel shifts) and every live member sends its full syncable
table to BOTH ``(i + s) mod N`` and ``(i - s) mod N``.  The unordered
pair ``{i, i + s}`` therefore exchanges tables in full duplex — member
``i``'s send on the ``+s`` channel is the SYNC, its partner's send on
the ``-s`` channel is the SYNC_ACK — and both directions are plain
shifted/scattered dense flows, so the exchange rides the existing
delivery machinery in every mode (scatter, shift, blocked) and the
sharded twins, including the pipelined double-buffer (the contribution
folds into the same global-height inbox buffer the regular channels
pmax).  Per-member peer choice is uniform over offsets, which is the
statistical regime of the reference's uniform candidate draw; the
seed-gated contact rule (known-live ∪ seeds) still applies when seeds
are configured, matching doSync's candidate set.

Payload and merge
-----------------
The payload is the sender's full table row — status + incarnation
lanes packed as wire keys — masked by the same ``syncable`` rule as the
in-tick SYNC channel (table-DEAD rows are never transmitted: the
reference's table holds no DEAD records).  Delivery is subject to
ground-truth liveness, partition walls, and per-link loss exactly like
every other channel; it is same-round even under ``max_delay_rounds``
(``sync_timeout`` >> link delays in the reference regime — the
``_seed_anti_entropy`` precedent).  The receiver merges through the
ordinary inbox max-fold + ``ops/delivery.merge_inbox`` gate, so the
incarnation-precedence rules are the table's own: in particular a
stored DEAD tombstone gates like ABSENT and REOPENS for an arriving
ALIVE record — which is precisely how a healed half re-admits the
members it declared dead during the partition (the dense analog of the
reference's remove-then-re-add, MembershipProtocolTest.
testNetworkPartitionThenRecovery).

Convergence measurement
-----------------------
``divergent_cells`` / ``divergence_probe`` quantify table agreement:
a subject column is DIVERGENT while two live observers hold different
(status, incarnation) records about it.  ``chaos/monitor.py`` raises
``POST_HEAL_DIVERGENCE`` when divergence persists past the scenario's
post-heal agreement window; ``bench.py --sync`` measures
``sync_rounds_to_converge`` — rounds from the heal until the first
divergence-free table — for the plane against the gossip-only control
(which provably never converges: stale tombstones are neither hot for
gossip nor eligible FD targets, so nothing ever repairs them).

The quiesced-heal precondition (measured, not assumed)
------------------------------------------------------
The bounded re-convergence claim holds for partitions whose fault
effects went COLD before the heal: every cross-partition suspicion
matured to a tombstone and the tombstones' gossip windows expired
inside the split.  There the post-heal dynamics are monotone — ALIVE
records reopen tombstone cells through the merge gate, the reopened
records disseminate, and nothing re-arms the dead notices — and
convergence lands within one exchange plus one dissemination bound.
A heal arriving MID-SUSPICION (split shorter than detection +
suspicion timeout + spread expiry) instead releases freshly-hot
tombstones into the healed cluster, and the protocol's own merge
precedence (a DEAD record overrides ANY live incarnation,
records.is_overrides rule 3, while a stored tombstone reopens for any
ALIVE) sustains a DEAD/ALIVE reinfection ping-pong that no amount of
anti-entropy bounds — the subject burns incarnations refuting a
death notice that keeps re-arming.  That regime is a faithful property
of the reference's merge rules, not of this plane (the reference's
partition-recovery test heals a quiesced split too); the scenario
compiler therefore only PROMISES post-heal agreement
(``chaos/monitor.POST_HEAL_DIVERGENCE``) when the split length clears
``chaos/scenarios.quiesce_bound``, and ``bench.py --sync`` measures
the quiesced-heal scenario.

``SwimParams.dead_suppress_rounds`` (default 0 = the reference
behavior above) BOUNDS the mid-suspicion regime: for that many rounds
after a tombstone is stored the cell holds (no reopen), so the death
notice's retransmission windows expire against closed cells and the
eventual reopens meet a cold network — the oscillation terminates
within one window sized past the suspicion + spread tail
(tests/test_dead_suppression.py pins both regimes).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from scalecube_cluster_tpu import records

# Fold constants for the plane's PRNG streams — disjoint from every
# existing fold (0x5317 shift channels, 29 seed anti-entropy, 7/11/13
# delay bins), so enabling the plane never perturbs the base tick's
# draws (the sync_interval=0 bit-identity contract).
_OFFSET_FOLD = 0x53CA
_DROP_FOLD = 41

# This module's row in the composed-runner plane inventory
# (models/compose.plane_registry): an IN-TICK plane — compiled into
# ``swim_tick`` by its knob, no extra carry lane (the exchange rides
# the protocol's own status/inc lanes and wire buffers).  A plain dict
# (no compose import: swim imports this module, compose imports swim).
PLANE = dict(
    name="sync", kind="in-tick", knobs=("sync_interval", "sync_every"),
    lanes=(),
    doc="anti-entropy full-table exchange for partition heal "
        "(sync_interval > 0 arms it; sync_every is the reference's "
        "per-round push channel)",
)


def due(round_idx, sync_interval: int):
    """Is ``round_idx`` an anti-entropy exchange round?

    Static ``sync_interval`` (a SwimParams field); callers gate the
    whole phase out when it is 0, so the dynamic predicate only exists
    in programs that carry the plane.  Fires at round 0 too — on a warm
    converged table the exchange is a semantic no-op (every delivered
    key equals the stored key, and the merge gate is strict), so the
    phase's cadence needs no special-casing at the origin.
    """
    return (round_idx % jnp.int32(sync_interval)) == 0


def partner_offset(channel_key, n_members: int):
    """The round's shared exchange offset ``s`` in [1, n_members - 1].

    Drawn from a dedicated fold of the round's CHANNEL key (the
    un-device-folded stream every shard agrees on — models/swim.
    _round_context's ``k_shifts``), so all devices pair the same rows.
    ``s = n/2`` degenerates the two directions onto one partner; the
    inbox max-fold dedups the double delivery, so the edge costs
    nothing and needs no exclusion.
    """
    return jax.random.randint(
        jax.random.fold_in(channel_key, _OFFSET_FOLD), (), 1, n_members,
        dtype=jnp.int32,
    )


def drop_key(k_sync_drop):
    """The per-device key sourcing the exchange's two in-flight loss
    draws (one per direction, folded 0/1 by the caller)."""
    return jax.random.fold_in(k_sync_drop, _DROP_FOLD)


def exchange_targets(node_ids, s, n_members: int):
    """[n_local, 2] global partner ids: column 0 = ``(i + s) mod N``
    (the SYNC direction), column 1 = ``(i - s) mod N`` (the partner's
    reply direction)."""
    n = jnp.int32(n_members)
    fwd = (node_ids + s) % n
    bwd = (node_ids - s) % n          # jnp mod: non-negative for n > 0
    return jnp.stack([fwd, bwd], axis=1)


def sent_count(ae_due, alive_here):
    """``messages_anti_entropy`` for one round: exchange messages
    issued by live members (2 per member on exchange rounds).

    The send-ATTEMPT convention, counted before partition walls, wire
    loss, AND the seed-contact gate — deliberately, so the counter
    means exactly the same thing in scatter and shift modes (the shift
    tick evaluates the contact gate at the receiver; counting gated
    attempts at the sender there would cost two extra unshift
    exchanges per round on the hot path).  Per-link delivered/lost
    attribution — including contact-gate suppression — is the
    ``link_counters`` substrate's job, exactly as for the gossip
    channels."""
    return 2 * jnp.sum(ae_due & alive_here, dtype=jnp.int32)


# --------------------------------------------------------------------------
# Table-agreement measurement (the convergence observable)
# --------------------------------------------------------------------------


def divergent_cells(status, inc, alive_rows):
    """Cells where a live observer disagrees with the column consensus.

    ``status``/``inc`` are WIDE [N, K] table lanes, ``alive_rows`` [N]
    ground-truth observer liveness.  A column AGREES when every live
    observer holds the same (status, incarnation) record about it; the
    per-cell mask marks live observers whose packed record differs from
    the column's maximum packed record — empty iff the live tables
    agree exactly (the packed key is injective in (status, inc) below
    the wire saturation cap, records.merge_key docstring).

    Returns ``(cell_mask [N, K] bool, divergent_cols [K] bool)``.
    Frozen (crashed/left) rows are excluded: their stale tables are
    unreachable state, not live disagreement.
    """
    key = records.merge_key(status, jnp.asarray(inc, jnp.int32))
    live = jnp.asarray(alive_rows, jnp.bool_)[:, None]
    fill = jnp.iinfo(jnp.int32).min
    col_max = jnp.max(jnp.where(live, key, fill), axis=0)
    cell_mask = live & (key != col_max[None, :])
    return cell_mask, jnp.any(cell_mask, axis=0)


@partial(jax.jit, static_argnames=("params",))
def divergence_probe(state, params, world, n_rounds):
    """Divergent-column count of a carry encoded at cursor ``n_rounds``
    (the number of rounds executed so far) — the host-side convergence
    probe ``bench.py --sync`` polls between run segments.

    Layout-neutral: compact/int16 carries decode first (the same rule
    the monitor uses).  ``n_rounds`` is a DYNAMIC argument — the bench's
    probe loop calls this with a new cursor every few rounds, and a
    static cursor would recompile the [N, K] program per probe.
    Returns an int32 scalar.
    """
    from scalecube_cluster_tpu.models import swim

    cursor = jnp.asarray(n_rounds, jnp.int32)
    if params.compact_carry:
        state = swim._carry_decode(state, cursor)
    _, cols = divergent_cells(state.status, state.inc,
                              world.alive_at(cursor))
    return jnp.sum(cols, dtype=jnp.int32)
