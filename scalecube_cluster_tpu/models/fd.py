"""Failure-detector-only model: the SWIM probe in isolation.

The reference tests its FD component with membership stubbed out
(FailureDetectorTest.java:414-428 fakes the peer list as a pre-seeded
event stream) — BASELINE config 3 is exactly that setup at scale: "10k
members, FailureDetectorImpl ping/ping-req under 5% packet loss".

On the dense tick the same isolation is a *configuration*, not a fork:
the full swim tick (models/swim.py) with the gossip channel masked off
(Knobs.fanout = 0) and SYNC pushed past the horizon.  What remains per
round is the probe phase — direct ping within ping_timeout, ping-req via
k proxies within the remaining interval — and the local SUSPECT/ALIVE
verdict stream, with no dissemination between observers.  Suspicion
timeouts still fire locally, mirroring the FD's per-period verdicts
feeding a mute membership.

This module packages that configuration so "FD-only" runs are one call,
with the same delivery modes, link faults, and world schedules as the
full model.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from scalecube_cluster_tpu.models import swim


def fd_only_knobs(params: swim.SwimParams) -> swim.Knobs:
    """Knobs that silence gossip + SYNC, leaving only the probe phase.

    ``sync_every=0`` is the never-sync sentinel (models/swim.py gates the
    sync round on ``sync_every > 0``; a huge modulo would still fire at
    round 0).  It also disables the FD's alive-on-suspected refute push —
    that push is a SYNC issued by *membership*
    (MembershipProtocolImpl.java:379-391), which this isolation stubs out,
    so verdicts stay strictly observer-local.

    Caveat: with the Lifeguard plane on (``SwimParams.lhm_max > 0``) the
    buddy-system refute push rides the FD ACK PATH itself
    (models/lifeguard.py) and is therefore NOT silenced by
    ``sync_every=0`` — an FD isolation that must stay verdict-local
    should keep ``lhm_max = 0``.
    """
    return dataclasses.replace(
        swim.Knobs.from_params(params),
        sync_every=jnp.int32(0),
        fanout=jnp.int32(0),
    )


def effective_probe_budgets(params: swim.SwimParams, lhm,
                            ping_timeout_ms=None):
    """Per-member FD budgets under the Lifeguard health plane
    (models/lifeguard.py): ``(ping_budget_ms, ping_req_budget_ms)``,
    each the base budget scaled by the member's Local Health Multiplier
    — Lifeguard's LHA Probe timeout scaling (a member that suspects its
    own slowness gives its peers more time to answer before issuing a
    SUSPECT verdict).

    ``ping_budget_ms`` [n] scales ``ping_timeout_ms`` (the direct-ping
    round trip's budget); ``ping_req_budget_ms`` [n] scales the
    remaining-interval budget of the k-proxy fan-out.  With ``lhm == 1``
    both equal the base values exactly (the healthy-member no-op the
    plane's bit-identity tests pin); they never drop below base
    (lhm >= 1 by clamp).

    ``ping_timeout_ms`` overrides the static base timeout with a traced
    knob value (swim.Knobs.ping_timeout_ms, clamped to the interval at
    the call site); None = ``params.ping_timeout_ms``.  The interval
    itself stays static — the knob splits it, never grows it.
    """
    m = jnp.asarray(lhm, jnp.float32)
    pt = (params.ping_timeout_ms if ping_timeout_ms is None
          else ping_timeout_ms)
    return (pt * m, (params.ping_interval_ms - pt) * m)


def probe_outcome_updates(tick_metrics: dict) -> dict:
    """FD probe-outcome counters for the health registry
    (telemetry/metrics.py) from one tick's metrics row.

    Maps the probe phase's wire-level counter families onto the
    registry's health-lane names — the FailureDetector half of the
    Lifeguard-style health plane: probe volume (``fd_probes_sent``, the
    reference's per-period PING count, FailureDetectorImpl.java:148),
    indirect-probe escalation (``fd_ping_req_sent``, the k-proxy
    fan-out that fires exactly when a direct ping failed — its rate IS
    the local-saturation/loss signal), and tracked-subject verdict
    volume (``fd_tracked_verdicts``, the stream that drives suspicion
    state).  Pure renaming on purpose: the counters are computed inside
    the tick where the probes are issued; this hook just owns which of
    them constitute FD health.
    """
    out = {}
    for reg_name, key in (("fd_probes_sent", "messages_ping_sent"),
                          ("fd_ping_req_sent", "messages_ping_req_sent"),
                          ("fd_tracked_verdicts", "messages_ping")):
        if key in tick_metrics:
            out[reg_name] = jnp.sum(
                jnp.asarray(tick_metrics[key]), dtype=jnp.int32)
    return out


def run(base_key, params: swim.SwimParams, world: swim.SwimWorld,
        n_rounds: int, state: Optional[swim.SwimState] = None,
        start_round: int = 0):
    """swim.run with gossip/SYNC silenced (see module docstring).

    Returns (final_state, metrics); ``suspect``/``alive`` traces are the
    per-period FailureDetectorEvent stream aggregated over observers
    (FailureDetectorImpl.java:363-366).
    """
    return swim.run(base_key, params, world, n_rounds, state=state,
                    start_round=start_round, knobs=fd_only_knobs(params))
