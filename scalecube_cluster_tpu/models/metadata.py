"""Metadata KV plane: versioned per-member config propagation.

The reference's ``MetadataStoreImpl`` attaches a small KV map to every
member and disseminates *versions* of it alongside membership: an
updated member bumps its incarnation, peers notice the new record and
re-fetch the map (MetadataStoreImpl.java; the oracle facade
``oracle/metadata.py`` keeps those pull semantics for parity tests).
The dense model cannot afford per-pair RPC fetches, so this plane ships
the *payload itself* infection-style — the SWIM-paper dissemination
substrate carrying config instead of liveness — with the SYNC
anti-entropy full-table exchange (models/sync.py) guaranteeing
convergence through partition heal exactly as it does for membership.

Lanes and the packed word
-------------------------
``SwimParams.metadata_keys`` (M; 0 = the default = the plane compiles
out) sizes a fixed-shape per-member KV lane:

  ``md``        [n_local, K, M] int32 — observer i's belief about
                subject k's M metadata cells, one packed word each;
  ``md_spread`` [n_local, K]    int32 — the absolute round until which
                row (i, k) is hot for piggyback gossip (the membership
                ``spread_until`` rule applied per metadata row).

Each cell is ONE packed int32 word (sign bit clear, so the wire's
max-fold and the scatter fill value behave exactly like record keys)::

    word = (epoch & 0x7F) << 24 | version << 10 | value
    word == 0  <=>  unset

``value`` is a 10-bit application payload (0..1023 — a config enum /
shard-map generation, not a string store), ``version`` a 14-bit
per-(slot, epoch) write counter saturating at 16383, ``epoch`` the low
7 bits of the PR-10 identity epoch.  A version is meaningful only per
(slot, epoch): the merge gate drops words whose epoch bits disagree
with the receiver's current identity belief for that slot, and zeroes
stale local cells on a belief change — a reused slot starts from an
empty map at version 0, never inheriting the previous occupant's
config (the identity-epoch rule that makes LWW sound under churn).

Last-writer-wins by construction
--------------------------------
Within one (slot, epoch) the packed word is monotone in (version,
value), so the merge is a plain ``jnp.maximum`` — associative and
commutative, which is what lets the payload ride every existing
delivery substrate unchanged: the scatter max-fold, the shift
channels' per-message delivery, the pipelined double-buffer's deferred
pmax, and the sharded combines.  Ties (same version, different value)
deterministically prefer the larger value — a documented
determinization of concurrent same-version writes; the owner is the
only writer in this model (pushes land at the owner's own row), so
ties do not occur on the write path.

Dissemination — hot rows on gossip, full table on anti-entropy
--------------------------------------------------------------
Hot rows (``round < md_spread``) piggyback the gossip channels and the
SYNC/refute channel, masked per sender exactly like hot membership
records.  The FULL table rides only the anti-entropy paired exchange
(``sync_interval > 0``) — which is the A/B story ``bench.py --rollout``
measures: with the exchange off, a push that quiesced inside a
partition is no longer hot at heal time and the stale half stays
divergent forever (the membership tombstone argument of
models/sync.py, verbatim, applied to config).

No new PRNG draws, no new channels: the plane reuses the round's
existing targets and drop masks, so ``metadata_keys=0`` bit-identity
is structural — there is nothing to perturb.  Delivery is same-round
only under ``max_delay_rounds`` (the anti-entropy precedent; config
convergence is measured in rounds, not sub-round latency).

Deviations, documented: values are small ints, not strings (fixed
shape; the oracle parity map is int-valued str()s); propagation is
push-payload, not pull-on-version (the reference's fetch RPC has no
dense analog — convergence semantics, not wire timing, are the pinned
contract); ``k_block`` (the >10M capacity path) excludes the plane —
an [N, N, M] metadata table is itself infeasible at that scale
(SwimParams.__post_init__ validates).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# Packed-word layout (see module docstring).  31 bits used; the sign
# bit stays clear so packed words order like non-negative ints under
# the wire's max-fold and the scatter fill (-1) stays strictly below
# every real word.
MD_VALUE_BITS = 10
MD_VERSION_BITS = 14
MD_EPOCH_BITS = 7
MD_VALUE_MAX = (1 << MD_VALUE_BITS) - 1
MD_VERSION_MAX = (1 << MD_VERSION_BITS) - 1
MD_EPOCH_MASK = (1 << MD_EPOCH_BITS) - 1

# This module's row in the composed-runner plane inventory
# (models/compose.plane_registry): an IN-TICK plane — compiled into
# ``swim_tick`` by its knob — with two carry lanes.  A plain dict (no
# compose import: swim imports this module, compose imports swim).
PLANE = dict(
    name="metadata", kind="in-tick", knobs=("metadata_keys",),
    lanes=("md", "md_spread"),
    doc="per-member versioned KV (config) lane: LWW merge per "
        "(slot, epoch) identity, hot rows on piggyback gossip, full "
        "table on the anti-entropy exchange (metadata_keys > 0 arms "
        "it)",
)


def pack_word(epoch, version, value):
    """Pack (epoch, version, value) int32 lanes into one md word.

    Callers clamp ``version``/``value`` to their field widths; ``epoch``
    is masked to its low bits here (identity epochs grow without
    bound, the word only needs enough to disambiguate a slot's recent
    occupants — the wire-key epoch-bits argument).
    """
    ep = jnp.asarray(epoch, jnp.int32) & MD_EPOCH_MASK
    return ((ep << (MD_VERSION_BITS + MD_VALUE_BITS))
            | (jnp.asarray(version, jnp.int32) << MD_VALUE_BITS)
            | jnp.asarray(value, jnp.int32))


def word_epoch(word):
    return (jnp.asarray(word, jnp.int32)
            >> (MD_VERSION_BITS + MD_VALUE_BITS)) & MD_EPOCH_MASK


def word_version(word):
    return (jnp.asarray(word, jnp.int32) >> MD_VALUE_BITS) & MD_VERSION_MAX


def word_value(word):
    return jnp.asarray(word, jnp.int32) & MD_VALUE_MAX


def initial_lanes(params, n_local: int):
    """The plane's carry slice for ``initial_state``: empty tables.

    Off (``metadata_keys == 0``): zero-size lanes — zero bytes, zero
    compute, and every lane op below is statically gated out (the
    ``initial_epoch`` zero-size pattern).
    """
    m = params.metadata_keys
    if m == 0:
        return dict(md=jnp.zeros((n_local, 0, 0), dtype=jnp.int32),
                    md_spread=jnp.zeros((n_local, 0), dtype=jnp.int32))
    k = params.n_subjects
    return dict(md=jnp.zeros((n_local, k, m), dtype=jnp.int32),
                md_spread=jnp.zeros((n_local, k), dtype=jnp.int32))


def inject_pushes(md, md_spread, round_idx, params, world, node_ids,
                  own_epoch, alive_here):
    """Apply the world's scheduled config pushes landing this round.

    A push is an OWNER-LOCAL write (the reference's updateMetadata runs
    on the member itself): at ``md_push_at[p]`` node ``md_push_node[p]``
    writes ``md_push_value[p]`` into its own row's cell
    ``md_push_key[p]`` at version ``stored + 1`` (saturating) under its
    current identity epoch, and opens the row's gossip window.  The
    schedule length P is static and small, so the loop unrolls.  A
    crashed member cannot push config — ``alive_here`` gates the write
    like the user-gossip spread() injection (the oracle's stopped
    member runs nothing).

    Pure in (md, md_spread, round_idx): the pipelined send/recv halves
    re-derive the identical injection from the same carried state, the
    same way the self-pin does.
    """
    n_push = world.md_push_at.shape[0]
    if n_push == 0 or params.metadata_keys == 0:
        return md, md_spread
    k = params.n_subjects
    m = params.metadata_keys
    own_col = (jnp.arange(k, dtype=jnp.int32)[None, :]
               == node_ids[:, None])                        # [n_local, K]
    own_ep = (jnp.asarray(own_epoch, jnp.int32) if own_epoch is not None
              else jnp.zeros(node_ids.shape, jnp.int32))
    for p in range(n_push):
        here = ((node_ids == world.md_push_node[p])
                & (round_idx == world.md_push_at[p])
                & alive_here)                               # [n_local]
        key_onehot = (jnp.arange(m, dtype=jnp.int32)
                      == world.md_push_key[p])              # [M]
        cell = (here[:, None, None] & own_col[:, :, None]
                & key_onehot[None, None, :])                # [n_local,K,M]
        new_ver = jnp.minimum(word_version(md) + 1, MD_VERSION_MAX)
        new_word = pack_word(own_ep[:, None, None], new_ver,
                             world.md_push_value[p])
        md = jnp.where(cell, new_word, md)
        md_spread = jnp.where(
            here[:, None] & own_col,
            round_idx + 1 + params.periods_to_spread, md_spread,
        )
    return md, md_spread


def hot_payload(md, md_spread, round_idx):
    """[n_local, K*M] flattened gossip payload: hot rows only.

    Sender-side mask exactly like hot membership records; sender
    liveness/partition/loss gating is the delivering channel's own
    mask, shared with the membership payload (no new draws).
    """
    n_local, k, m = md.shape
    hot = (round_idx < md_spread)[:, :, None]
    return jnp.where(hot, md, 0).reshape(n_local, k * m)


def full_payload(md):
    """[n_local, K*M] flattened anti-entropy payload: the full table."""
    n_local, k, m = md.shape
    return md.reshape(n_local, k * m)


def merge(md, md_spread, arrivals_flat, round_idx, params, is_self,
          epoch_belief, frozen_rows):
    """Fold one round's delivered metadata words into the carry.

    ``arrivals_flat`` [n_local, K*M] is the max-folded delivery buffer
    (scatter fill -1 clamps to the unset word).  Gates, in order:

      1. *identity*: a word whose epoch bits disagree with the
         receiver's POST-MERGE identity belief for the slot is dropped,
         and stale local cells are zeroed on a belief change (versions
         are per (slot, epoch); a reused slot starts empty);
      2. *self-pin*: a member never accepts external words about its
         OWN cells — it is the sole authority for its map (the
         reference's metadata lives on the owner);
      3. *LWW*: ``jnp.maximum`` — the packed word is monotone in
         (version, value) within one epoch.

    Strictly-improved rows open a gossip window; frozen (crashed/left)
    rows keep their old lanes like every other carry field.  Returns
    ``(md, md_spread)``.
    """
    n_local, k, m = md.shape
    arr = jnp.maximum(arrivals_flat.reshape(n_local, k, m), 0)
    if params.epoch_bits and epoch_belief is not None:
        belief = jnp.asarray(epoch_belief, jnp.int32) & MD_EPOCH_MASK
        arr = jnp.where(
            (arr != 0) & (word_epoch(arr) == belief[:, :, None]), arr, 0
        )
        md = jnp.where(
            (md != 0) & (word_epoch(md) != belief[:, :, None]), 0, md
        )
    arr = jnp.where(is_self[:, :, None], 0, arr)
    new_md = jnp.maximum(md, arr)
    improved = jnp.any(new_md != md, axis=2)                # [n_local, K]
    new_spread = jnp.where(
        improved, round_idx + 1 + params.periods_to_spread, md_spread
    )
    fz = frozen_rows[:, None]
    new_md = jnp.where(fz[:, :, None], md, new_md)
    new_spread = jnp.where(fz, md_spread, new_spread)
    return new_md, new_spread


def owner_words(md, node_ids, n_members: int, offset=0, axis_name=None):
    """[N, M] ground-truth table: each owner's words about itself.

    The owner's own row is the authority (pushes land there; the
    self-pin keeps it so).  Sharded: each device contributes its local
    diagonal block and one pmax assembles the full table.
    """
    # Full view: column j is node j, so each row's own column index IS
    # its global node id.
    diag = jnp.take_along_axis(md, node_ids[:, None, None], axis=1)[:, 0, :]
    buf = jnp.zeros((n_members, md.shape[2]), dtype=jnp.int32)
    buf = jax.lax.dynamic_update_slice(buf, diag, (offset, 0))
    if axis_name is not None:
        buf = jax.lax.pmax(buf, axis_name)
    return buf


def divergent_count(md, node_ids, alive, alive_here, n_members: int,
                    offset=0, axis_name=None):
    """int32 scalar: (live observer, live owner, key) cells where the
    observer's word differs from the owner's own word — 0 iff every
    live member agrees with every live owner's map (the convergence
    observable; the ``metadata_divergent`` metric).  Globally reduced
    (one psum) when ``axis_name`` is set.
    """
    owners = owner_words(md, node_ids, n_members, offset=offset,
                         axis_name=axis_name)
    owner_live = jnp.asarray(alive, jnp.bool_)              # [N]
    cell = (md != owners[None, :, :]) \
        & alive_here[:, None, None] & owner_live[None, :, None]
    count = jnp.sum(cell, dtype=jnp.int32)
    if axis_name is not None:
        count = jax.lax.psum(count, axis_name)
    return count


# --------------------------------------------------------------------------
# Host-side convergence probes (the bench poll loop)
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("params",))
def divergence_probe(state, params, world, n_rounds):
    """Divergent-cell count of a finished carry at cursor ``n_rounds``
    — the probe ``bench.py --rollout`` polls between run segments
    (the sync-plane divergence_probe pattern: dynamic cursor, no
    recompile per poll).  Single-device full view.
    """
    cursor = jnp.asarray(n_rounds, jnp.int32)
    n = params.n_members
    node_ids = jnp.arange(n, dtype=jnp.int32)
    alive = world.alive_at(cursor)
    return divergent_count(state.md, node_ids, alive, alive, n)


@partial(jax.jit, static_argnames=("params",))
def member_converged(state, params, world, n_rounds):
    """[N] bool: live members whose FULL metadata view agrees with
    every live owner's own words — the per-member observable behind
    ``metadata_convergence_p99`` (the p99 is over members' first
    converged poll, measured by the bench's segment loop).  A dead
    observer reports converged (it is not a member of the SLO
    population).
    """
    cursor = jnp.asarray(n_rounds, jnp.int32)
    n = params.n_members
    node_ids = jnp.arange(n, dtype=jnp.int32)
    alive = world.alive_at(cursor)
    owners = owner_words(state.md, node_ids, n)
    mismatch = (state.md != owners[None, :, :]) & alive[None, :, None]
    return ~(jnp.any(mismatch, axis=(1, 2)) & alive)
