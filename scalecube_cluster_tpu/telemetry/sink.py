"""Host-side telemetry sinks: JSONL run manifests + TensorBoard export.

The reference's runs are observed through SLF4J logs and JMX monitors;
the dense runs' equivalent durable surface is one JSONL file per run:

    line 1: {"kind": "manifest", run id, schema, config digest, device
             info, caller metadata}
    then:   {"kind": "counters", ...}   per-chunk digested counter rows
            {"kind": "histogram", ...}  named bucket histograms
            {"kind": "events", ...}     batches of typed trace events
            {"kind": "curve", ...}      per-round series (downsampled)
            {"kind": "summary", ...}    closing totals

The always-on health registry (telemetry/metrics.py) flushes per
window through :meth:`TelemetrySink.write_metrics_window`:
``{"kind": "metrics_window", round_start, round_end, counters, gauges,
histograms}`` — ``round_end`` makes the record resumable through
:func:`covered_upto`, the same journal-cursor dedup the resilient
supervisor's segments use.  Chaos campaigns (chaos/campaign.py) reuse
the same pipeline with two
more kinds via :meth:`TelemetrySink.write_record`:
``{"kind": "chaos_scenario", ...}`` — one verdict row per scenario
(green flag, per-invariant-code violation counts + first rounds,
first-violation evidence lanes, counter digests, the one-line repro) —
and ``{"kind": "chaos_verdict", ...}``, the campaign summary.

Everything is line-delimited JSON so a run is greppable, appendable and
stream-parseable; :func:`read_records` / :func:`read_events` round-trip
it (pinned by tests/test_telemetry_sink.py).

Sink directory resolution: explicit argument, else the
``SCALECUBE_TPU_TELEMETRY_DIR`` env var, else the caller's default
(bench.py uses ``artifacts/telemetry``).  The TensorBoard exporter
follows the repo's existing profiling convention: it activates only
when ``SCALECUBE_TPU_PROFILE_DIR`` is set (utils/runlog.profiled uses
the same gate) and degrades to a no-op if no TensorBoard writer package
is importable — never a hard dependency.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from scalecube_cluster_tpu.telemetry.events import (
    MembershipTraceEvent,
    TraceEventType,
)

SCHEMA_VERSION = 1
TELEMETRY_DIR_ENV = "SCALECUBE_TPU_TELEMETRY_DIR"
PROFILE_DIR_ENV = "SCALECUBE_TPU_PROFILE_DIR"
# Segment length (in protocol rounds) of the overlapped trace offload
# (stream_traced_run); override with this env var.
TRACE_SEGMENT_ENV = "SCALECUBE_TPU_TRACE_SEGMENT_ROUNDS"
DEFAULT_SEGMENT_ROUNDS = 256

# Counter names digested into a counters row (the same families
# utils/runlog.log_metrics_summary prints; per-subject [rounds, K]
# traces sum over subjects).
_COUNTER_NAMES = (
    "messages_gossip", "messages_ping", "messages_ping_sent",
    "messages_ping_req_sent", "refutations", "false_positives",
    "false_suspicion_onsets", "false_suspect_rounds", "stale_view_rounds",
)


def new_run_id(prefix: str = "run") -> str:
    stamp = time.strftime("%Y%m%d-%H%M%S")
    return f"{prefix}-{stamp}-{os.urandom(3).hex()}"


def config_digest(params) -> str:
    """Stable 12-hex digest of a run configuration.

    Accepts a dataclass (SwimParams, ClusterConfig, ...) or a plain
    dict; same knobs -> same digest across processes, so manifests from
    different runs of one configuration are groupable.
    """
    if dataclasses.is_dataclass(params) and not isinstance(params, type):
        obj = dataclasses.asdict(params)
    elif isinstance(params, dict):
        obj = params
    else:
        obj = {"repr": repr(params)}
    blob = json.dumps(obj, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def device_info() -> dict:
    """Backend + device census, robust to an uninitializable backend."""
    try:
        import jax

        devs = jax.devices()
        return {
            "backend": jax.default_backend(),
            "device_count": len(devs),
            "device_kind": devs[0].device_kind if devs else None,
        }
    except Exception as e:  # noqa: BLE001 — telemetry must not kill a run
        return {"backend": "unavailable", "error": f"{type(e).__name__}: {e}"}


# Keys counters_row has already warned about (warn ONCE per key per
# process — a non-numeric lane repeats every flush window, and one
# warning per window would bury the signal it exists to raise).
_WARNED_NON_NUMERIC: set = set()


def counters_row(metrics: dict, round_offset: int = 0,
                 label: Optional[str] = None) -> dict:
    """Digest one chunk of per-round metric traces into a counters row.

    Same input contract as runlog.log_metrics_summary: a dict of
    [n_rounds, ...] traces from models/swim.run.  Scalar-trace counters
    are summed over the chunk; per-subject traces sum over subjects too.
    An empty metrics dict produces an empty (but valid) row.

    A counter lane whose values are NOT summable numbers (an object
    array, strings, a malformed registry flush) is skipped from the row
    — but never silently: the first time each key fails it warns, so a
    registry/driver schema drift can't quietly lose a lane forever.
    """
    row: dict = {"label": label, "round_offset": round_offset}
    n_rounds = 0
    for v in metrics.values():
        n_rounds = int(np.asarray(v).shape[0])
        break
    row["n_rounds"] = n_rounds
    for name in _COUNTER_NAMES:
        if name in metrics:
            try:
                v = np.asarray(metrics[name])
                if not (np.issubdtype(v.dtype, np.number)
                        or np.issubdtype(v.dtype, np.bool_)):
                    raise TypeError(f"non-numeric dtype {v.dtype}")
                row[name] = int(v.sum())
            except (TypeError, ValueError) as e:
                if name not in _WARNED_NON_NUMERIC:
                    _WARNED_NON_NUMERIC.add(name)
                    import warnings

                    warnings.warn(
                        f"counters_row: dropping non-numeric metric "
                        f"{name!r} ({e}) — this lane will be missing "
                        f"from counter rows (warned once per key)",
                        stacklevel=2,
                    )
    return row


class TelemetrySink:
    """One JSONL run manifest under a sink directory (module docstring).

    Every record is flushed as it is written, so a SIGKILL loses at most
    the one line being emitted — and :func:`read_records` skips that
    torn trailing line instead of refusing the whole file.

    ``path`` pins the sink to an exact file instead of deriving one
    from (out_dir, run_id); with ``append=True`` an existing file is
    extended rather than truncated — the resilient-runner journal shape
    (resilience/supervisor.py), where a relaunched process must
    continue the SAME file with no holes and no duplicate rounds
    (:func:`covered_upto` is the dedup cursor).
    """

    def __init__(self, out_dir: Optional[str] = None,
                 run_id: Optional[str] = None, prefix: str = "run",
                 path: Optional[str] = None, append: bool = False):
        if path is not None:
            self.path = path
            stem = os.path.splitext(os.path.basename(path))[0]
            self.run_id = run_id or stem
            directory = os.path.dirname(os.path.abspath(path)) or "."
        else:
            if out_dir is None:
                raise ValueError("TelemetrySink needs out_dir or path")
            self.run_id = run_id or new_run_id(prefix)
            directory = out_dir
            self.path = os.path.join(out_dir, f"{self.run_id}.jsonl")
        os.makedirs(directory, exist_ok=True)
        if append:
            self._heal_torn_tail(self.path)
        self._f = open(self.path, "a" if append else "w")
        self._closed = False

    @staticmethod
    def _heal_torn_tail(path: str) -> None:
        """Truncate an unterminated final line before appending.

        A record is durable iff its line is newline-terminated (writes
        are flushed per record); a file ending mid-line means the
        previous writer was killed mid-write.  Appending after it would
        fuse the torn fragment with the next record into one corrupt
        INTERIOR line — which read_records correctly refuses — so the
        fragment is dropped at reopen instead: it was never durable.
        """
        if not os.path.exists(path) or os.path.getsize(path) == 0:
            return
        with open(path, "rb+") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(-1, os.SEEK_END)
            if f.read(1) == b"\n":
                return
            # Bounded backward scan for the last newline (journals at
            # scale run to GBs — never slurped; only the torn tail is
            # ever in memory, one chunk at a time).
            keep, pos, chunk = 0, size, 1 << 16
            while pos > 0:
                start = max(0, pos - chunk)
                f.seek(start)
                idx = f.read(pos - start).rfind(b"\n")
                if idx != -1:
                    keep = start + idx + 1
                    break
                pos = start
            import warnings

            warnings.warn(
                f"{path}: dropping {size - keep}-byte torn trailing "
                f"record before appending (writer killed mid-line)",
                stacklevel=3,
            )
            f.truncate(keep)

    @staticmethod
    def from_env(default_dir: Optional[str] = None,
                 prefix: str = "run") -> Optional["TelemetrySink"]:
        """Sink in $SCALECUBE_TPU_TELEMETRY_DIR, else ``default_dir``,
        else None (telemetry off)."""
        out_dir = os.environ.get(TELEMETRY_DIR_ENV) or default_dir
        if not out_dir:
            return None
        return TelemetrySink(out_dir, prefix=prefix)

    # -- record writers ----------------------------------------------------

    def _write(self, kind: str, payload: dict) -> None:
        rec = {"kind": kind, "run_id": self.run_id}
        rec.update(payload)
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def write_manifest(self, params=None, **extra) -> None:
        self._write("manifest", {
            "schema_version": SCHEMA_VERSION,
            "wall_time": time.time(),
            "config_digest": config_digest(params) if params is not None
            else None,
            "device": device_info(),
            **extra,
        })

    def write_counters(self, metrics: dict, round_offset: int = 0,
                       label: Optional[str] = None) -> None:
        self._write("counters", counters_row(metrics, round_offset, label))

    def write_events(self, events: Iterable[MembershipTraceEvent],
                     dropped: int = 0, batch: int = 1000) -> None:
        """Event batches (chunked so single lines stay parseable-sized);
        ``dropped`` reports the trace buffer's overflow count so a
        truncated trace is never mistaken for a complete one."""
        events = list(events)
        for i in range(0, len(events), batch):
            self._write("events", {
                "offset": i,
                "events": [e.to_json() for e in events[i:i + batch]],
            })
        self._write("events_footer",
                    {"recorded": len(events), "dropped": int(dropped)})

    def write_histogram(self, name: str, edges: Sequence[int],
                        counts: Sequence[int], **meta) -> None:
        self._write("histogram", {
            "name": name,
            "edges": np.asarray(edges).tolist(),
            "counts": np.asarray(counts).tolist(),
            **meta,
        })

    def write_curve(self, name: str, values, round_offset: int = 0,
                    max_points: int = 2048, **meta) -> None:
        """A per-round series (e.g. fraction-informed-by-round),
        stride-downsampled to ``max_points``."""
        v = np.asarray(values)
        stride = max(1, int(np.ceil(v.shape[0] / max_points)))
        idx = list(range(0, v.shape[0], stride))
        # Always keep the terminal sample (a dissemination curve's
        # converged value) even when the stride would skip it.
        if idx and idx[-1] != v.shape[0] - 1:
            idx.append(v.shape[0] - 1)
        self._write("curve", {
            "name": name,
            "round_offset": round_offset,
            "stride": stride,
            "values": v[idx].tolist(),
            **meta,
        })

    def write_summary(self, **fields) -> None:
        self._write("summary", fields)

    def write_metrics_window(self, window: dict) -> None:
        """One health-metrics flush window (telemetry/metrics.py):
        ``{"round_start", "round_end", "counters", "gauges",
        "histograms"}``.  ``round_end`` makes the record resumable
        through the journal cursor — ``covered_upto(path,
        kind="metrics_window")`` is the dedup cursor a relaunched
        metered run consults, exactly the resilient supervisor's
        segment semantics."""
        for key in ("round_start", "round_end"):
            if key not in window:
                raise ValueError(
                    f"metrics_window record needs {key!r} (the journal "
                    f"cursor dedups on round_end)")
        self._write("metrics_window", dict(window))

    def write_provenance(self, payload: dict, batch: int = 2000) -> None:
        """Channel-attribution rows (models/provenance.py's
        ``attributions_payload``) as ``provenance`` records, chunked so
        single lines stay parseable-sized.  EVERY chunk carries the
        buffer accounting (``recorded``/``dropped``/``capacity`` — they
        are totals, idempotent across chunks), so a reader holding any
        one chunk knows whether the stream is complete; a truncated
        attribution stream is never mistaken for a whole one (the
        write_events ``dropped`` discipline)."""
        rows = list(payload.get("rows", []))
        acct = {k: int(payload[k])
                for k in ("recorded", "dropped", "capacity")
                if k in payload}
        if not rows:
            self._write("provenance", {"offset": 0, "rows": [], **acct})
            return
        for i in range(0, len(rows), batch):
            self._write("provenance", {
                "offset": i,
                "rows": rows[i:i + batch],
                **acct,
            })

    def write_record(self, kind: str, payload: dict) -> None:
        """Generic typed row for schema extensions that don't warrant a
        dedicated writer (the chaos verdict rows — module docstring).
        ``payload`` must be JSON-serializable."""
        self._write(kind, payload)

    def close(self) -> None:
        if not self._closed:
            self._f.close()
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# --------------------------------------------------------------------------
# Readers (the round-trip half of the contract)
# --------------------------------------------------------------------------


def iter_records(path: str, kind: Optional[str] = None):
    """Stream the records of a JSONL manifest one at a time.

    A record is durable iff its line is NEWLINE-TERMINATED: the writer
    emits ``json + "\\n"`` per record and flushes, so a SIGKILL landing
    mid-write leaves at most one unterminated trailing line.  That line
    is skipped with a warning — EVEN IF it happens to parse (the kill
    can land between the payload bytes and the newline; counting such a
    record would disagree with the byte-identical truncation
    ``TelemetrySink._heal_torn_tail`` applies at reopen, and a resumed
    writer would then dedup against a record that no longer exists).
    An unparseable newline-terminated line still raises: the per-record
    write discipline cannot produce one, so it is real corruption, not
    a torn write.

    Generator on purpose: journals at scale run to GBs of event
    batches, and consumers that fold over them (covered_upto's running
    max) must not hold every record resident the way
    :func:`read_records`'s list does.
    """
    # One-byte tail probe: is the final line newline-terminated?
    with open(path, "rb") as fb:
        fb.seek(0, os.SEEK_END)
        size = fb.tell()
        terminated = True
        if size:
            fb.seek(-1, os.SEEK_END)
            terminated = fb.read(1) == b"\n"

    def parse(lineno: int, line: str, is_final_payload: bool):
        line = line.strip()
        if is_final_payload and not terminated:
            import warnings

            warnings.warn(
                f"{path}: skipping torn trailing record ({len(line)} "
                f"bytes, no newline) — the writer was killed mid-line",
                stacklevel=4,
            )
            return None
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"{path}: unparseable newline-terminated record at "
                f"line {lineno} — interior corruption, not a torn tail"
            ) from e
        if kind is None or rec.get("kind") == kind:
            return rec
        return None

    # Streamed with one payload-line of lookahead: a line is processed
    # once a later payload line proves it is not the file's last.
    with open(path) as f:
        pending = None
        for i, raw in enumerate(f):
            if not raw.strip():
                continue
            if pending is not None:
                rec = parse(pending[0], pending[1], False)
                if rec is not None:
                    yield rec
            pending = (i + 1, raw)
        if pending is not None:
            rec = parse(pending[0], pending[1], True)
            if rec is not None:
                yield rec


def read_records(path: str, kind: Optional[str] = None) -> List[dict]:
    """All (or one ``kind`` of) records in a JSONL manifest, as a list
    (:func:`iter_records` has the durability/torn-tail contract)."""
    return list(iter_records(path, kind=kind))


def covered_upto(path: str, kind: str = "segment") -> int:
    """The journal's round cursor: max ``round_end`` over well-formed
    ``kind`` records, 0 for a missing/empty journal.  Torn trailing
    lines don't count (iter_records skips them) — exactly the
    resume-dedup semantics the resilient supervisor needs: a segment
    whose ``round_end`` <= this cursor is already durably journaled.
    Streams: each record is dropped after its round_end is folded in.
    """
    if not os.path.exists(path):
        return 0
    ends = (int(r["round_end"]) for r in iter_records(path, kind=kind)
            if "round_end" in r)
    return max(ends, default=0)


class JournalFollower:
    """Incremental reader of a journal another process may still be
    writing: a byte-offset cursor over NEWLINE-TERMINATED lines.

    :func:`iter_records`'s durability rule, applied live: a record is
    durable iff its line is newline-terminated, so each :meth:`poll`
    consumes bytes only up to the LAST newline currently in the file —
    an in-progress (or torn) trailing fragment is simply left for the
    next poll, which is the streaming equivalent of iter_records'
    torn-tail skip.  Consumed bytes are NEVER re-read (the cursor only
    advances, and always lands just after a newline), so tailing a
    long-running journal — or rebasing ``covered_upto`` across
    supervisor relaunch segments — costs one scan of the new bytes, not
    a fresh parse from byte 0 (pinned by tests/test_alarms.py).

    A terminated-but-unparseable line still raises ``ValueError``
    (interior corruption, iter_records' rule).  The file SHRINKING
    below the cursor also raises: ``_heal_torn_tail`` can only ever
    truncate an unterminated fragment this follower never consumed, so
    a shorter-than-cursor file means the journal was rewritten
    out-of-band and every downstream dedup cursor is void.

    Per-kind ``round_end`` maxima fold incrementally as lines are
    consumed — :meth:`covered_upto` is :func:`covered_upto` rebased on
    the cursor.
    """

    def __init__(self, path: str, kind: Optional[str] = None):
        self.path = path
        self.kind = kind
        self.offset = 0
        self._covered: Dict[str, int] = {}

    def covered_upto(self, kind: str = "segment") -> int:
        """Max ``round_end`` over ``kind`` records consumed SO FAR
        (module-level :func:`covered_upto` semantics, incremental)."""
        return self._covered.get(kind, 0)

    def poll(self) -> List[dict]:
        """Consume every newly-durable record; [] when nothing new
        (including a missing file — the writer may not have started)."""
        if not os.path.exists(self.path):
            return []
        with open(self.path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size < self.offset:
                raise ValueError(
                    f"{self.path}: journal shrank below the follower "
                    f"cursor ({size} < {self.offset}) — rewritten "
                    f"out-of-band; the consumed-record cursor is void")
            if size == self.offset:
                return []
            f.seek(self.offset)
            chunk = f.read(size - self.offset)
        nl = chunk.rfind(b"\n")
        if nl < 0:
            return []            # only an unterminated fragment so far
        lineno_base = self.offset   # byte position, for error messages
        self.offset += nl + 1
        out: List[dict] = []
        for raw in chunk[:nl + 1].split(b"\n"):
            if not raw.strip():
                continue
            try:
                rec = json.loads(raw.decode("utf-8"))
            except (json.JSONDecodeError, UnicodeDecodeError) as e:
                raise ValueError(
                    f"{self.path}: unparseable newline-terminated "
                    f"record after byte {lineno_base} — interior "
                    f"corruption, not a torn tail") from e
            k = rec.get("kind")
            if "round_end" in rec and k is not None:
                self._covered[k] = max(self._covered.get(k, 0),
                                       int(rec["round_end"]))
            if self.kind is None or k == self.kind:
                out.append(rec)
        return out


def follow_records(path: str, kind: Optional[str] = None) -> JournalFollower:
    """A :class:`JournalFollower` over ``path`` — the live-tail reader
    (``telemetry watch``) and the supervisor's scan-once resume cursor."""
    return JournalFollower(path, kind=kind)


def read_events(path: str) -> List[MembershipTraceEvent]:
    events: List[MembershipTraceEvent] = []
    for rec in read_records(path, kind="events"):
        events.extend(
            MembershipTraceEvent.from_json(e) for e in rec["events"]
        )
    return events


def read_provenance(path: str) -> Tuple[List[dict], dict]:
    """The journal's channel-attribution stream: (rows, accounting).

    Rows concatenate across ``provenance`` chunks in offset order (the
    writer emits them in order; the sort makes a merged journal safe);
    accounting is the LAST chunk's recorded/dropped/capacity totals
    (idempotent across chunks — write_provenance's contract)."""
    chunks = read_records(path, kind="provenance")
    chunks.sort(key=lambda r: int(r.get("offset", 0)))
    rows: List[dict] = []
    acct: dict = {}
    for rec in chunks:
        rows.extend(rec.get("rows", []))
        for k in ("recorded", "dropped", "capacity"):
            if k in rec:
                acct[k] = int(rec[k])
    return rows, acct


def fraction_informed_curve(dead_counts, n_live_observers: int):
    """[rounds] fraction of live observers holding the death notice —
    the dissemination curve, from the tick's per-round ``dead`` counts
    for one subject column."""
    v = np.asarray(dead_counts, dtype=np.float64)
    return v / max(1, int(n_live_observers))


# --------------------------------------------------------------------------
# Overlapped trace offload: the segmented traced-run driver
# --------------------------------------------------------------------------


@dataclasses.dataclass
class TracedRunResult:
    """What :func:`stream_traced_run` hands back, host-side.

    ``events`` is the decoded stream in round order (empty when
    ``decode=False``); ``recorded``/``dropped`` total the per-segment
    buffers.  ``telemetry`` carries the final first-suspect /
    first-removed matrices (feed it to
    ``telemetry.trace.latency_histograms``); its trace buffer is a
    placeholder — the event stream lives in ``events``.  ``metrics`` is
    the concatenated [n_rounds, ...] trace dict as numpy arrays.
    """

    events: List[MembershipTraceEvent]
    recorded: int
    dropped: int
    capacity: int
    segment_rounds: int
    n_segments: int
    metrics: dict
    telemetry: object


def stream_traced_run(base_key, params, world, n_rounds: int, *,
                      state=None, knobs=None, shift_key=None,
                      start_round: int = 0,
                      segment_rounds: Optional[int] = None,
                      trace_capacity: Optional[int] = None,
                      decode: bool = True):
    """Drive ``models/swim.run_traced`` in segments with the trace
    offload overlapped against the next segment's compute.

    A monolithic traced run fetches its whole event buffer in one
    blocking ``device_get`` at the end; this driver instead scans
    ``segment_rounds``-round segments and, thanks to JAX's async
    dispatch, ENQUEUES segment k+1 before fetching segment k's trace
    slab + metric rows — the device chews on the next segment while the
    host drains the previous one, so the device→host copy costs no
    device time (the ISSUE-2 overlapped-offload shape; segment length
    from ``SCALECUBE_TPU_TRACE_SEGMENT_ROUNDS``, default
    ``DEFAULT_SEGMENT_ROUNDS``).

    Each segment gets a FRESH event buffer of ``trace_capacity`` while
    the first-suspect/first-removed matrices thread through (they are
    donated segment-to-segment along with the carry —
    swim.run_traced's donation contract).  With zero drops the
    concatenated stream is exactly the monolithic run's; under
    overflow, drops are counted per segment (a segmented run can only
    drop FEWER events than one shared buffer, never more, and the
    count is still exact).

    Returns ``(final_state, TracedRunResult)``.  ``decode=False`` skips
    building host-side event objects (the offload still happens) — use
    it when timing, where python-object construction would pollute the
    measurement.
    """
    import jax

    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.telemetry import trace as ttrace

    if segment_rounds is None:
        env = os.environ.get(TRACE_SEGMENT_ENV)
        segment_rounds = int(env) if env else DEFAULT_SEGMENT_ROUNDS
    segment_rounds = max(1, segment_rounds)
    cap = trace_capacity or ttrace.DEFAULT_CAPACITY

    if state is None:
        state = swim.initial_state(params, world)
    tel0 = ttrace.TelemetryState.init(
        params.n_members, params.n_subjects, capacity=1
    )
    fs, fr = tel0.first_suspect, tel0.first_removed

    pending = None          # (trace pytree, metrics) of the previous segment
    slabs, metric_parts = [], []

    def harvest(p):
        # ONE transfer per segment: a per-leaf device_get (separate
        # syncs per array) measurably dominates small-segment offload.
        (lanes, count, seg_dropped), metrics = jax.device_get(p)
        slabs.append((np.asarray(lanes), int(count), int(seg_dropped)))
        metric_parts.append(metrics)

    r, n_segments = 0, 0
    while r < n_rounds:
        step = min(segment_rounds, n_rounds - r)
        tel_in = ttrace.TelemetryState.resume(fs, fr, capacity=cap)
        state, tel_out, metrics = swim.run_traced(
            base_key, params, world, step, trace_capacity=cap,
            state=state, start_round=start_round + r, knobs=knobs,
            shift_key=shift_key, telemetry=tel_in,
        )
        # tel_in (including fs/fr) is donated into the call just made;
        # tel_out's buffers are fresh outputs — safe to read any time.
        fs, fr = tel_out.first_suspect, tel_out.first_removed
        r += step
        n_segments += 1
        if pending is not None:     # overlapped: next segment is enqueued
            harvest(pending)
        pending = ((tel_out.trace.lanes, tel_out.trace.count,
                    tel_out.trace.dropped), metrics)
    if pending is not None:
        harvest(pending)

    events: List[MembershipTraceEvent] = []
    recorded = dropped = 0
    for lanes, count, seg_dropped in slabs:
        recorded += count
        dropped += seg_dropped
        if decode:
            events.extend(
                MembershipTraceEvent(
                    round=int(lanes[i, 0]),
                    observer=int(lanes[i, 1]),
                    subject=int(lanes[i, 2]),
                    event_type=TraceEventType(int(lanes[i, 3])),
                    incarnation=int(lanes[i, 4]),
                )
                for i in range(count)
            )
    metrics_np = {}
    if metric_parts:
        metrics_np = {
            name: np.concatenate(
                [np.asarray(p[name]) for p in metric_parts], axis=0
            )
            for name in metric_parts[0]
        }
    final_tel = ttrace.TelemetryState(
        trace=ttrace.EventTrace.empty(1), first_suspect=fs,
        first_removed=fr,
    )
    return state, TracedRunResult(
        events=events, recorded=recorded, dropped=dropped, capacity=cap,
        segment_rounds=segment_rounds, n_segments=n_segments,
        metrics=metrics_np, telemetry=final_tel,
    )


# --------------------------------------------------------------------------
# TensorBoard export (gated; never a hard dependency)
# --------------------------------------------------------------------------


def _summary_writer(logdir: str):
    try:
        from tensorboardX import SummaryWriter
    except Exception:  # noqa: BLE001 — optional dependency
        return None
    return SummaryWriter(logdir=logdir)


def export_tensorboard(logdir: str, run_id: str,
                       scalars: Optional[Dict[str, Sequence]] = None,
                       histograms: Optional[dict] = None,
                       max_points: int = 1024) -> Optional[str]:
    """Write scalar traces + bucket histograms as TensorBoard summaries.

    ``scalars``: name -> per-round series (downsampled to max_points).
    ``histograms``: name -> (edges, counts) bucket pairs.  Returns the
    event-file directory, or None when no writer package is available.
    """
    path = os.path.join(logdir, run_id)
    w = _summary_writer(path)
    if w is None:
        return None
    try:
        for name, series in (scalars or {}).items():
            v = np.asarray(series)
            if v.ndim > 1:
                v = v.sum(axis=tuple(range(1, v.ndim)))
            stride = max(1, int(np.ceil(v.shape[0] / max_points)))
            for step in range(0, v.shape[0], stride):
                w.add_scalar(name, float(v[step]), global_step=step)
        for name, (edges, counts) in (histograms or {}).items():
            e = np.asarray(edges, dtype=np.float64)
            c = np.asarray(counts, dtype=np.float64)
            if c.sum() <= 0:
                continue
            # Bucket i covers [e[i], e[i+1]); the open last bucket gets a
            # synthetic right edge so TB has a finite limit.
            limits = np.append(e[1:], e[-1] * 2 + 1)
            mids = (limits + e) / 2.0
            w.add_histogram_raw(
                name,
                min=float(e[0]), max=float(limits[-1]),
                num=int(c.sum()),
                sum=float((mids * c).sum()),
                sum_squares=float((mids * mids * c).sum()),
                bucket_limits=limits.tolist(),
                bucket_counts=c.tolist(),
                global_step=0,
            )
    finally:
        w.close()
    return path


def maybe_export_tensorboard(run_id: str,
                             scalars: Optional[Dict[str, Sequence]] = None,
                             histograms: Optional[dict] = None,
                             log=None) -> Optional[str]:
    """TensorBoard export gated behind SCALECUBE_TPU_PROFILE_DIR (the
    repo's existing profiling-surface convention — runlog.profiled)."""
    logdir = os.environ.get(PROFILE_DIR_ENV)
    if not logdir:
        return None
    path = export_tensorboard(logdir, run_id, scalars, histograms)
    if log is not None:
        if path:
            log.info("tensorboard telemetry written to %s", path)
        else:
            log.info("tensorboard export skipped (no writer package)")
    return path
