"""Always-on protocol health metrics: the in-jit registry.

The event trace (telemetry/trace.py) answers *what happened*; production
SWIM also needs an always-on NUMERIC health plane — probe outcomes,
suspicion lifetimes, piggyback occupancy, wire saturation — the signals
Lifeguard (Dadgar et al., 2018) argues a deployed SWIM must export to be
operable.  This module is that plane for the dense tick:

  - :class:`MetricsSpec` — the fixed registry declaration (counter,
    gauge and bucketed-histogram names, histogram edges), a frozen
    hashable dataclass passed as a STATIC jit argument: the registry's
    shape never depends on data, so the carried state is one
    fixed-shape pytree.
  - :class:`MetricsState` — the carried values: ``[C]`` int32 counters,
    ``[G]`` float32 gauges, one ``[B]`` int32 count vector per
    histogram.  ``models/swim.run_metered`` threads it through the scan
    as a DONATED carry, exactly like the trace buffer.
  - pure update ops — :func:`inc` / :func:`inc_many` (counters),
    :func:`set_gauge`, :func:`observe` (bucketize + scatter-add, gated
    on any-sample so silent rounds cost one reduction) — all usable
    inside jit.

Instrumentation lives where the signals originate: FD probe-outcome
counter mapping in ``models/fd.py``, gossip piggyback occupancy in
``models/gossip.py``, wire saturation in ``ops/delivery.py``, suspicion
queue/lifetime derivation here from the carry fields ``models/swim.py``
exposes, chaos violation counts from ``chaos/monitor.py``'s
run shape.  :func:`observe_tick` is the one per-round entry the run
shapes call.

Cost: per round, a handful of scalar counter adds (XLA fuses them into
the scan body) plus ONE [N, K] status-compare reduction gating the
suspicion-transition block (the telemetry/trace.py emptiness-gate
pattern) — steady-state rounds pay the gate only.  Gauges are sampled
once per run/window from the FINAL carry (a gauge is by definition
last-value, so per-round sampling would be dead work).  The bench pins
the metered/unmetered ratio on the smoke path
(``bench.py --metrics``; artifacts/metrics_smoke.json).

Multichip: under the row-sharded mesh (parallel/mesh.shard_run_metered)
each device accumulates a LOCAL registry; tick-level counters that are
already psum-global inside ``swim_tick`` are added on the lead device
only (``lead`` weight), and the whole registry is psum-combined once
across the mesh via ``parallel/compat.psum_tree`` before offload —
counters and histogram counts are additive, gauges are assembled from
already-global numerators.

Windowed flush: :func:`stream_metered_run` drives ``run_metered`` in
windows and writes one ``metrics_window`` JSONL record per window
(``TelemetrySink.write_metrics_window``); records carry
``round_start``/``round_end`` so the PR-4 journal cursor
(``sink.covered_upto(path, kind="metrics_window")``) dedups resumed
runs exactly like the resilient supervisor's segments.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scalecube_cluster_tpu import records

INT32_MAX = jnp.iinfo(jnp.int32).max

# Suspicion lifetimes span refutations (a few probe cycles) through the
# full suspicion timeout; the geometric grid matches the latency
# histogram convention (telemetry/trace.DEFAULT_LATENCY_EDGES).
DEFAULT_SUSPICION_EDGES = (0, 1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64,
                           96, 128)

# The default protocol-health registry.  Counters are WINDOW totals
# (int32; the windowed flush resets them, so a counter's headroom is
# per-window, not per-run), gauges are last-sampled values, histograms
# are bucketed counts with the declared edges (bucket i covers
# [edges[i], edges[i+1]), last bucket open).
DEFAULT_COUNTERS = (
    "fd_probes_sent",            # PINGs issued by live members
    "fd_ping_req_sent",          # PING_REQ fan-out messages
    "fd_tracked_verdicts",       # probe verdicts on tracked subjects
    "gossip_messages",           # wire gossip messages sent
    "refutations",               # self-refutation incarnation bumps
    "suspicions_started",        # cells newly turned SUSPECT
    "suspicions_refuted",        # SUSPECT resolved back to ALIVE
    "suspicions_fired",          # SUSPECT matured to DEAD
    "false_suspicion_onsets",    # new SUSPECT about a live subject
    "false_positive_rounds",     # observer-rounds holding FP views
    "live_observer_rounds",      # sum of live members over rounds
    "chaos_violations",          # invariant-monitor trips (monitored)
    "joins_admitted",            # open-world JOINs fired (ground-truth
                                 # admissions, SwimWorld.join_at; 0
                                 # when the plane is off)
)
DEFAULT_GAUGES = (
    "live_members",              # ground-truth live count
    "suspect_entries",           # suspicion queue depth (live observers)
    "dead_entries",              # tombstones held by live observers
    "gossip_piggyback_occupancy",  # hot records / live tracked records
    "wire_saturation",           # gossip messages / send-slot capacity
    "lhm",                       # mean Lifeguard health multiplier over
                                 # live members (models/lifeguard.py;
                                 # 0 = plane off, 1 = all healthy)
    "free_slots",                # slots with no live occupant — the
                                 # open-world admission capacity
                                 # (n_members - live_members)
)
DEFAULT_HISTOGRAMS = (
    ("suspicion_lifetime_rounds", DEFAULT_SUSPICION_EDGES),
)


@dataclasses.dataclass(frozen=True)
class MetricsSpec:
    """The fixed registry declaration (module docstring).

    Frozen + tuples only, so instances hash — the spec is a STATIC jit
    argument; changing the declared metrics recompiles, updating their
    values never does.
    """

    counters: Tuple[str, ...] = DEFAULT_COUNTERS
    gauges: Tuple[str, ...] = DEFAULT_GAUGES
    histograms: Tuple[Tuple[str, Tuple[int, ...]], ...] = DEFAULT_HISTOGRAMS

    def __post_init__(self):
        for kind in ("counters", "gauges"):
            names = getattr(self, kind)
            if len(set(names)) != len(names):
                raise ValueError(f"duplicate {kind} names: {names}")
        hnames = tuple(n for n, _ in self.histograms)
        if len(set(hnames)) != len(hnames):
            raise ValueError(f"duplicate histogram names: {hnames}")
        for name, edges in self.histograms:
            if len(edges) < 2 or list(edges) != sorted(set(edges)):
                raise ValueError(
                    f"histogram {name!r} needs >= 2 strictly increasing "
                    f"edges (got {edges})")

    @staticmethod
    def default() -> "MetricsSpec":
        return MetricsSpec()

    def counter_index(self, name: str) -> int:
        return self.counters.index(name)

    def gauge_index(self, name: str) -> int:
        return self.gauges.index(name)

    def histogram_edges(self, name: str) -> Tuple[int, ...]:
        for n, edges in self.histograms:
            if n == name:
                return edges
        raise KeyError(f"histogram {name!r} not in spec")


@dataclasses.dataclass
class MetricsState:
    """The carried registry values (one donated pytree).

    ``counters`` [C] int32 / ``gauges`` [G] float32 in spec order;
    ``hists`` maps histogram name -> [B] int32 bucket counts.
    """

    counters: jnp.ndarray
    gauges: jnp.ndarray
    hists: Dict[str, jnp.ndarray]

    @staticmethod
    def init(spec: MetricsSpec) -> "MetricsState":
        return MetricsState(
            counters=jnp.zeros((len(spec.counters),), dtype=jnp.int32),
            gauges=jnp.zeros((len(spec.gauges),), dtype=jnp.float32),
            hists={name: jnp.zeros((len(edges),), dtype=jnp.int32)
                   for name, edges in spec.histograms},
        )


jax.tree_util.register_dataclass(
    MetricsState, data_fields=["counters", "gauges", "hists"],
    meta_fields=[],
)


# --------------------------------------------------------------------------
# Pure update ops (jit-safe)
# --------------------------------------------------------------------------


def inc(ms: MetricsState, spec: MetricsSpec, name: str,
        value) -> MetricsState:
    """counter[name] += value (value: scalar, any int dtype)."""
    idx = spec.counter_index(name)
    return dataclasses.replace(
        ms, counters=ms.counters.at[idx].add(
            jnp.asarray(value, jnp.int32)),
    )


def inc_many(ms: MetricsState, spec: MetricsSpec,
             updates: Dict[str, jnp.ndarray]) -> MetricsState:
    """Batch counter adds: one delta vector, one tensor add.  Unknown
    names raise at trace time (a registry mismatch is a bug, not data)."""
    if not updates:
        return ms
    delta = jnp.zeros_like(ms.counters)
    for name, value in updates.items():
        delta = delta.at[spec.counter_index(name)].add(
            jnp.asarray(value, jnp.int32))
    return dataclasses.replace(ms, counters=ms.counters + delta)


def set_gauge(ms: MetricsState, spec: MetricsSpec, name: str,
              value) -> MetricsState:
    """gauge[name] = value (last write wins — gauges are samples)."""
    idx = spec.gauge_index(name)
    return dataclasses.replace(
        ms, gauges=ms.gauges.at[idx].set(jnp.asarray(value, jnp.float32)),
    )


def observe(ms: MetricsState, spec: MetricsSpec, name: str, values,
            mask) -> MetricsState:
    """Bucketize ``values`` where ``mask`` and add to histogram counts.

    ``values``/``mask`` broadcast to a common shape; the whole pass runs
    under a ``lax.cond`` on ``any(mask)`` — the identity when the round
    observed nothing (the telemetry/trace.py emptiness-gate pattern), so
    silent rounds pay one reduction instead of a searchsorted + scatter.
    """
    edges = jnp.asarray(spec.histogram_edges(name), jnp.int32)
    b = edges.shape[0]
    values = jnp.asarray(values, jnp.int32)
    mask = jnp.asarray(mask, jnp.bool_)
    values, mask = jnp.broadcast_arrays(values, mask)

    def add(h):
        bucket = jnp.clip(
            jnp.searchsorted(edges, values, side="right") - 1, 0, b - 1
        ).reshape(-1)
        return h.at[bucket].add(mask.reshape(-1).astype(jnp.int32))

    hists = dict(ms.hists)
    hists[name] = jax.lax.cond(jnp.any(mask), add, lambda h: h,
                               ms.hists[name])
    return dataclasses.replace(ms, hists=hists)


def reset_window(ms: MetricsState) -> MetricsState:
    """Zero the additive lanes (counters, histograms) for the next flush
    window; gauges carry (they are last-value samples, not totals)."""
    return MetricsState(
        counters=jnp.zeros_like(ms.counters),
        gauges=ms.gauges,
        hists={k: jnp.zeros_like(v) for k, v in ms.hists.items()},
    )


# --------------------------------------------------------------------------
# The per-round observation (called inside the scan body)
# --------------------------------------------------------------------------


def observe_tick(ms: MetricsState, spec: MetricsSpec, params, kn,
                 round_idx, prev_status, prev_deadline, new_status,
                 tick_metrics, world, lead=None, alive_now=None,
                 any_status_change=None) -> MetricsState:
    """Fold one tick's health signals into the registry.

    ``prev_status``/``prev_deadline`` are the carry fields BEFORE the
    tick in their WIDE decoding (absolute deadline rounds),
    ``new_status`` after; ``tick_metrics`` is the tick's per-round
    metrics dict (already psum-global under sharding).  ``lead`` is the
    sharded-dedup weight for global quantities — 1 on the lead device,
    0 elsewhere, None (=1) on a single device — so the end-of-run
    registry psum (:func:`aggregate_across_devices`) counts them once.

    ``alive_now`` / ``any_status_change``: precomputed
    ``world.alive_at(round_idx)`` and ``any(prev != new)`` from the
    composed runner's shared round context (models/compose.RoundCtx) —
    the same values this function would derive itself, handed in so a
    multi-plane stack pays each reduction once; None recomputes them
    (identical bits either way).

    Counter adds are a fused delta-vector add; the suspicion-transition
    block (onset/refute/fire counters + the lifetime histogram, the
    only [N, K] work beyond one compare-reduce) runs under a
    ``lax.cond`` and is skipped on steady-state rounds.
    """
    from scalecube_cluster_tpu.models import fd as fd_model

    lead_w = jnp.int32(1) if lead is None else jnp.asarray(lead, jnp.int32)

    def total(x):
        return jnp.sum(jnp.asarray(x), dtype=jnp.int32)

    # Global per-tick counters (lead-weighted under sharding).
    updates = {}
    for name, value in fd_model.probe_outcome_updates(tick_metrics).items():
        if name in spec.counters:
            updates[name] = jnp.asarray(value, jnp.int32) * lead_w
    for name, key in (("gossip_messages", "messages_gossip"),
                      ("refutations", "refutations"),
                      ("false_suspicion_onsets", "false_suspicion_onsets"),
                      ("false_positive_rounds", "false_positives")):
        if name in spec.counters and key in tick_metrics:
            updates[name] = total(tick_metrics[key]) * lead_w
    if "live_observer_rounds" in spec.counters:
        alive = (world.alive_at(round_idx) if alive_now is None
                 else alive_now)
        updates["live_observer_rounds"] = (
            jnp.sum(alive, dtype=jnp.int32) * lead_w
        )
    if (getattr(params, "open_world", False)
            and "joins_admitted" in spec.counters):
        # Ground-truth admissions this round (the world join schedule —
        # the tick executes exactly these; gated on the plane so a
        # plane-off registry never even traces the reduction).
        updates["joins_admitted"] = (
            jnp.sum(world.join_at == jnp.asarray(round_idx, jnp.int32),
                    dtype=jnp.int32) * lead_w
        )
    ms = inc_many(ms, spec, updates)

    # Suspicion-transition block: local-state derivation (NOT
    # lead-weighted — rows are per-device under sharding), gated on any
    # status change at all (every transition below implies one).
    track = tuple(n for n in ("suspicions_started", "suspicions_refuted",
                              "suspicions_fired") if n in spec.counters)
    has_hist = any(n == "suspicion_lifetime_rounds"
                   for n, _ in spec.histograms)
    if not track and not has_hist:
        return ms

    def active(m):
        started = ((new_status == records.SUSPECT)
                   & (prev_status != records.SUSPECT))
        resolved = ((prev_status == records.SUSPECT)
                    & (new_status != records.SUSPECT))
        upd = {}
        if "suspicions_started" in track:
            upd["suspicions_started"] = total(started)
        if "suspicions_refuted" in track:
            upd["suspicions_refuted"] = total(
                resolved & (new_status == records.ALIVE))
        if "suspicions_fired" in track:
            upd["suspicions_fired"] = total(
                resolved & (new_status == records.DEAD))
        m = inc_many(m, spec, upd)
        if has_hist:
            # The timer was armed at onset as onset + suspicion_rounds
            # (models/swim._merge_and_timers), so the deadline encodes
            # the onset round exactly; lifetime = resolution - onset.
            # Guard the no-timer sentinel (the TIMER_BOUND invariant
            # says it can't co-occur with SUSPECT, but a garbage
            # lifetime must not reach the buckets if it ever did).
            had_timer = resolved & (prev_deadline != INT32_MAX)
            lifetime = round_idx - (prev_deadline - kn.suspicion_rounds)
            if getattr(params, "lhm_max", 0) > 0:
                # Lifeguard LHA Suspicion stretches armed deadlines past
                # the base schedule (models/lifeguard.py), so the
                # deadline-derived onset is late for health-extended
                # timers: the recovered lifetime is measured against the
                # BASE schedule (exact for healthy observers, an
                # underestimate by the health extension otherwise) and
                # clamped at 0 so a stretched timer can't go negative
                # into the buckets.
                lifetime = jnp.maximum(lifetime, 0)
            m = observe(m, spec, "suspicion_lifetime_rounds", lifetime,
                        had_timer)
        return m

    changed = (jnp.any(prev_status != new_status)
               if any_status_change is None else any_status_change)
    return jax.lax.cond(changed, active, lambda m: m, ms)


def sample_gauges(ms: MetricsState, spec: MetricsSpec, params, kn,
                  status, spread_until_wide, alive_here, round_idx,
                  world, last_tick_metrics=None,
                  axis_name=None, lhm=None) -> MetricsState:
    """Sample every gauge from the FINAL carry of a run/window.

    ``status``/``spread_until_wide`` are the (possibly local-row) carry
    fields decoded wide at cursor ``round_idx`` (the round the state
    would run next); ``alive_here`` the matching ground-truth liveness
    rows.  Under sharding, local numerators are psum'd over
    ``axis_name`` (parallel/compat.psum_tree) so the stored gauge
    values are global on every device.

    ``lhm``: the carry's Lifeguard health lane ([local rows] int32,
    models/lifeguard.py) — when given (plane on), the ``lhm`` gauge
    samples the mean multiplier over live members; None / plane off
    leaves the gauge at its 0 init (a plane-off run reads 0, an
    all-healthy plane-on run reads 1).
    """
    from scalecube_cluster_tpu.parallel import compat

    obs_alive = alive_here[:, None]
    live = jnp.sum(world.alive_at(round_idx), dtype=jnp.int32)  # global

    suspect, dead, hot = compat.psum_tree((
        jnp.sum((status == records.SUSPECT) & obs_alive, dtype=jnp.int32),
        jnp.sum((status == records.DEAD) & obs_alive, dtype=jnp.int32),
        jnp.sum(_hot_records(status, spread_until_wide, round_idx)
                & obs_alive, dtype=jnp.int32),
    ), axis_name)

    from scalecube_cluster_tpu.models import gossip as gossip_model
    from scalecube_cluster_tpu.ops import delivery as delivery_ops

    values = {
        "live_members": live,
        "suspect_entries": suspect,
        "dead_entries": dead,
        "free_slots": jnp.int32(params.n_members) - live,
        "gossip_piggyback_occupancy": gossip_model.piggyback_occupancy(
            hot, live * params.n_subjects),
    }
    if last_tick_metrics is not None and "messages_gossip" in last_tick_metrics:
        values["wire_saturation"] = delivery_ops.wire_saturation(
            jnp.sum(jnp.asarray(last_tick_metrics["messages_gossip"]),
                    dtype=jnp.int32),
            live, kn.fanout,
        )
    if lhm is not None and lhm.shape[0]:
        lhm_sum = compat.psum_tree(
            jnp.sum(jnp.where(alive_here, lhm, 0), dtype=jnp.int32),
            axis_name,
        )
        values["lhm"] = (lhm_sum.astype(jnp.float32)
                         / jnp.maximum(live, 1).astype(jnp.float32))
    for name, value in values.items():
        if name in spec.gauges:
            ms = set_gauge(ms, spec, name, value)
    return ms


def _hot_records(status, spread_until_wide, round_idx):
    """The gossip piggyback mask: records still inside their
    retransmission window (models/swim._send_components' ``hot``,
    evaluated at the NEXT round the state would run)."""
    return (status != records.ABSENT) & (round_idx < spread_until_wide)


def aggregate_across_devices(ms: MetricsState,
                             axis_name: Optional[str]) -> MetricsState:
    """Combine per-device registries into the global one (sharded runs).

    Counters and histogram counts are additive — one psum over the mesh
    (parallel/compat.psum_tree).  Gauges are NOT summed: they were
    assembled from already-global numerators (:func:`sample_gauges`),
    so every device holds the same value already.
    """
    from scalecube_cluster_tpu.parallel import compat

    if axis_name is None:
        return ms
    return dataclasses.replace(
        ms,
        counters=compat.psum_tree(ms.counters, axis_name),
        hists=compat.psum_tree(ms.hists, axis_name),
    )


# --------------------------------------------------------------------------
# The compose() plane
# --------------------------------------------------------------------------


class MetricsPlane:
    """The health-metrics registry as a composed-runner plane
    (models/compose.py): carry slice = :class:`MetricsState`, per-round
    hook = :func:`observe_tick` over the shared round context,
    finalizer = the end-of-run :func:`sample_gauges` (+ the cross-mesh
    registry psum under sharding) — exactly the pre-compose
    ``run_metered`` / ``shard_run_metered`` / monitored-metered folds.

    ``chaos_from`` names an earlier plane in the stack (the invariant
    monitor) whose per-round ``code_counts`` delta feeds the
    ``chaos_violations`` counter — the monitored-metered shape; None
    leaves the counter untouched.  ``metrics_state`` resumes a registry
    across windows (the ``run_metered(metrics_state=...)`` argument).
    """

    name = "metrics"

    def __init__(self, spec: MetricsSpec, metrics_state=None,
                 chaos_from: Optional[str] = None):
        self.spec = spec
        self.metrics_state = metrics_state
        self.chaos_from = chaos_from

    def init(self, params, world):
        if self.metrics_state is not None:
            return self.metrics_state
        return MetricsState.init(self.spec)

    def on_round(self, rc, ms):
        ms = observe_tick(
            ms, self.spec, rc.params, rc.kn, rc.round_idx,
            rc.prev.status, rc.prev_deadline_wide, rc.new.status,
            rc.metrics, rc.world, lead=rc.lead, alive_now=rc.alive_now,
            any_status_change=rc.any_status_change,
        )
        if (self.chaos_from is not None
                and "chaos_violations" in self.spec.counters):
            before = rc.plane_before(self.chaos_from)
            after = rc.plane_after(self.chaos_from)
            ms = inc(ms, self.spec, "chaos_violations",
                     jnp.sum(after.code_counts - before.code_counts,
                             dtype=jnp.int32))
        return ms

    def finalize(self, fc, ms):
        ms = sample_gauges(
            ms, self.spec, fc.params, fc.kn, fc.final_state.status,
            fc.spread_until_wide, fc.alive_here, fc.end_round, fc.world,
            last_tick_metrics=fc.last_tick_metrics,
            axis_name=fc.axis_name,
            lhm=fc.final_state.lhm if fc.params.lhm_max > 0 else None,
        )
        return aggregate_across_devices(ms, fc.axis_name)


# --------------------------------------------------------------------------
# Host-side decode + the windowed flush driver
# --------------------------------------------------------------------------


def to_json(ms: MetricsState, spec: MetricsSpec) -> dict:
    """Device registry -> the JSONL-ready ``metrics_window`` payload.

    Counters are int32 WINDOW totals (module docstring); a negative
    lane means the window outgrew the int32 headroom and wrapped
    in-device — the value is garbage, so warn (the fix is a shorter
    flush window, not a wider dtype: int32 keeps the carry cheap on
    accelerators).
    """
    counters = np.asarray(ms.counters)
    gauges = np.asarray(ms.gauges)
    if (counters < 0).any():
        import warnings

        wrapped = [n for i, n in enumerate(spec.counters) if counters[i] < 0]
        warnings.warn(
            f"metrics window counters wrapped int32 (negative totals): "
            f"{wrapped} — shorten the flush window (stream_metered_run "
            f"window_rounds) to keep per-window totals under 2**31",
            stacklevel=2,
        )
    return {
        "counters": {n: int(counters[i])
                     for i, n in enumerate(spec.counters)},
        "gauges": {n: round(float(gauges[i]), 6)
                   for i, n in enumerate(spec.gauges)},
        "histograms": {
            name: {"edges": list(edges),
                   "counts": np.asarray(ms.hists[name]).tolist()}
            for name, edges in spec.histograms
        },
    }


def stream_metered_run(base_key, params, world, n_rounds: int, *,
                       sink=None, window_rounds: int = 64,
                       spec: Optional[MetricsSpec] = None,
                       state=None, knobs=None, shift_key=None,
                       start_round: int = 0, skip_covered: bool = True,
                       alarm_specs=None):
    """Drive ``models/swim.run_metered`` in flush windows.

    After each ``window_rounds``-round window the registry is fetched,
    written as one ``metrics_window`` record (when ``sink`` is given)
    and reset (gauges carry).  Records carry ``round_start`` /
    ``round_end``, so an append-mode journal sink dedups a resumed run
    through the PR-4 cursor: windows whose ``round_end`` is already
    covered are recomputed (the carry must advance) but not re-written
    (``skip_covered``) — no duplicate rows after any kill/relaunch
    sequence, the resilient supervisor's segment semantics.

    ``alarm_specs`` (a sequence of ``telemetry.alarms.AlarmSpec``;
    needs ``sink``) evaluates each flush window through a live
    :class:`~scalecube_cluster_tpu.telemetry.alarms.AlarmEngine` and
    journals every state change as an ``alarm_transition`` record.  The
    same ONE startup scan that finds the metrics cursor replays any
    existing rows through the engine and dedups already-durable
    transitions, so alarms inherit the exactly-once resume guarantee
    (telemetry/alarms.py module docstring); windows the cursor skips
    were already replayed and are not re-observed.

    Returns ``(final_state, window_rows)`` where ``window_rows`` is the
    host-side list of every window payload (including skipped-write
    ones), each ``{"round_start", "round_end", "counters", "gauges",
    "histograms"}``.
    """
    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.telemetry import sink as tsink

    spec = spec or MetricsSpec.default()
    window_rounds = max(1, int(window_rounds))
    engine = existing = None
    if alarm_specs:
        from scalecube_cluster_tpu.telemetry import alarms as talarms

        if sink is None:
            raise ValueError(
                "alarm_specs needs a sink: transitions are journal "
                "records (telemetry/alarms.py)")
        engine = talarms.AlarmEngine(alarm_specs,
                                     kinds=("metrics_window",))
    covered = 0
    if sink is not None and (skip_covered or engine is not None):
        # One scan serves both cursors: the metrics-window dedup AND
        # the alarm replay (satellite rule: a long journal is parsed
        # once, not once per consumer).
        follower = tsink.follow_records(sink.path)
        records = follower.poll()
        if skip_covered:
            covered = follower.covered_upto(kind="metrics_window")
        if engine is not None:
            replayed, existing = talarms.replay_journal(engine, records)
            talarms.write_transitions(sink, replayed, existing)

    ms = MetricsState.init(spec)
    if state is None:
        state = swim.initial_state(params, world)
    rows: List[dict] = []
    r = 0
    while r < n_rounds:
        step = min(window_rounds, n_rounds - r)
        state, ms, _ = swim.run_metered(
            base_key, params, world, step, spec=spec, state=state,
            start_round=start_round + r, knobs=knobs, shift_key=shift_key,
            metrics_state=ms,
        )
        w_start, w_end = start_round + r, start_round + r + step
        row = {"round_start": w_start, "round_end": w_end,
               **to_json(jax.device_get(ms), spec)}
        rows.append(row)
        if sink is not None and w_end > covered:
            sink.write_metrics_window(row)
            if engine is not None:
                talarms.write_transitions(
                    sink, engine.observe({"kind": "metrics_window", **row}),
                    existing)
        ms = reset_window(ms)
        r += step
    return state, rows
