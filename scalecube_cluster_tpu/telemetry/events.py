"""The typed membership event schema shared by the oracle and the tick.

The reference's observable protocol surface is ``MembershipEvent``
(membership/MembershipEvent.java:1-123: ADDED/REMOVED/UPDATED per
observer) plus the internal transitions its tests reach into
(suspicion, refutation).  The dense tick can't call a listener per
event, so both layers speak ONE numeric schema instead:

    (round, observer, subject, event_type, incarnation)

  - ``round``       protocol round of the transition (the tick's scan
                    cursor; the oracle quantizes ``sim.now`` by the
                    gossip interval — the same base-round mapping as
                    config.ClusterConfig.to_sim).
  - ``observer``    node index whose membership table transitioned (the
                    reference's "local member" of the listener).
  - ``subject``     node index the record is about.
  - ``event_type``  :class:`TraceEventType` — the five table transitions
                    that cover the reference's event surface.
  - ``incarnation`` incarnation of the accepted record.

Event types vs the reference surface:

  - ``ADDED``          null/tombstone entry accepted an ALIVE record
                       (MembershipProtocolImpl.java:553-570; re-adding a
                       restarted member is the delete-then-re-add path,
                       :512-516).
  - ``SUSPECTED``      entry turned SUSPECT (FD verdict or gossip,
                       :392-397) — the transition the suspicion timer
                       starts from.
  - ``ALIVE_REFUTED``  a SUSPECT entry was overridden by a
                       higher-incarnation ALIVE (the refutation
                       arriving, :488-509).
  - ``REMOVED``        entry accepted DEAD (suspicion timeout, leave
                       notice, or gossiped tombstone; the reference
                       emits MembershipEvent.REMOVED here, :543-552).
  - ``LEAVING``        the observer announced its own graceful leave
                       (leaveCluster's DEAD@inc+1 self-gossip,
                       :197-206); observer == subject.

Timing caveat for cross-layer diffs: rounds are stochastic (probe draws,
gossip spread), so exact-match comparisons should be made on the
timing-free :meth:`MembershipTraceEvent.key` = (observer, subject, type,
incarnation) — see :func:`event_key_set`.  Per-round transition
collapse: the tick emits the NET transition of a (observer, subject)
cell per round, so an ABSENT->SUSPECT round (possible when the ALIVE
gate opener and a SUSPECT winner arrive together) is one SUSPECTED
event where the oracle's serialized merges would emit ADDED then
SUSPECTED.  Warm-state scenarios (the parity tests) never hit this.

This module is pure Python (no jax) so the event-driven oracle can
import it without touching the accelerator stack.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Iterable, List, Optional, Sequence, Set, Tuple


class TraceEventType(enum.IntEnum):
    """Membership-table transition kinds (int codes are the wire/lane
    values — stable, do not renumber).

    ``JOINED`` is the open-world admission lane
    (models/swim.SwimParams.open_world): the cell's stored identity
    EPOCH advanced to a live record — a NEW member entered a recycled
    slot — where plain ``ADDED`` stays the same-identity (re-)add
    (cold-start discovery, tombstone reopen after partition heal,
    crash-revival).  The reference's listener emits ADDED for both
    (it has real per-identity member ids); consumers diffing against
    the oracle union the two types (chaos/campaign.cross_validate_churn).
    """

    ADDED = 0
    SUSPECTED = 1
    ALIVE_REFUTED = 2
    REMOVED = 3
    LEAVING = 4
    JOINED = 5


@dataclasses.dataclass(frozen=True, order=True)
class MembershipTraceEvent:
    """One observed membership-table transition (module docstring)."""

    round: int
    observer: int
    subject: int
    event_type: TraceEventType
    incarnation: int

    def key(self) -> Tuple[int, int, int, int]:
        """Timing-free identity for cross-layer diffs: (observer,
        subject, type, incarnation)."""
        return (self.observer, self.subject, int(self.event_type),
                self.incarnation)

    def to_json(self) -> dict:
        return {
            "round": self.round,
            "observer": self.observer,
            "subject": self.subject,
            "event_type": self.event_type.name,
            "incarnation": self.incarnation,
        }

    @staticmethod
    def from_json(obj: dict) -> "MembershipTraceEvent":
        return MembershipTraceEvent(
            round=int(obj["round"]),
            observer=int(obj["observer"]),
            subject=int(obj["subject"]),
            event_type=TraceEventType[obj["event_type"]],
            incarnation=int(obj["incarnation"]),
        )


def event_key_set(
    events: Iterable[MembershipTraceEvent],
    types: Optional[Sequence[TraceEventType]] = None,
    subjects: Optional[Sequence[int]] = None,
    observers: Optional[Sequence[int]] = None,
    min_round: Optional[int] = None,
) -> Set[Tuple[int, int, int, int]]:
    """Timing-free key set of a filtered event stream — the diffable form.

    Two layers running the same scenario agree on WHICH transitions
    happened (the key set) even though the rounds they happen in are
    stochastic; ``set_a == set_b`` is the parity assertion
    (tests/test_telemetry_trace.py).
    """
    types_s = None if types is None else {TraceEventType(t) for t in types}
    subj_s = None if subjects is None else set(subjects)
    obs_s = None if observers is None else set(observers)
    out = set()
    for e in events:
        if types_s is not None and e.event_type not in types_s:
            continue
        if subj_s is not None and e.subject not in subj_s:
            continue
        if obs_s is not None and e.observer not in obs_s:
            continue
        if min_round is not None and e.round < min_round:
            continue
        out.add(e.key())
    return out


def diff_event_streams(a, b, **filters):
    """(only_in_a, only_in_b) timing-free key sets — the two sides of a
    model-vs-oracle trace diff.  Empty/empty means parity."""
    ka, kb = event_key_set(a, **filters), event_key_set(b, **filters)
    return ka - kb, kb - ka


class OracleTraceCollector:
    """Collects the oracle's trace stream into the shared numeric schema.

    The oracle emits (event_type, subject Member, incarnation) per
    observer through ``MembershipProtocol.listen_trace``; this adapter
    maps members to integer node indices and quantizes virtual time to
    protocol rounds (``sim.now // round_ms`` — the same base-round rule
    as ClusterConfig.to_sim), producing the exact record layout the
    tick's decoded trace yields (telemetry/trace.decode_events).
    """

    def __init__(self, sim, round_ms: int,
                 index_of: Callable[[object], int]):
        self.sim = sim
        self.round_ms = round_ms
        self.index_of = index_of
        self.events: List[MembershipTraceEvent] = []

    def watch(self, cluster, observer_index: Optional[int] = None) -> None:
        """Subscribe to one oracle cluster's trace stream."""
        obs = (self.index_of(cluster.member())
               if observer_index is None else observer_index)

        def on_trace(event_type, member, incarnation):
            self.events.append(MembershipTraceEvent(
                round=int(self.sim.now // self.round_ms),
                observer=obs,
                subject=self.index_of(member),
                event_type=TraceEventType(event_type),
                incarnation=int(incarnation),
            ))

        cluster.listen_trace(on_trace)
