"""The jit-carried membership event trace (the dense listener analog).

The reference observes its protocol through per-event listener calls
(MembershipProtocolImpl._emit, :543-588).  Inside a ``lax.scan`` no host
call can run per event, so the trace is a fixed-capacity device buffer
carried through the scan:

  - :class:`EventTrace` — ``lanes [capacity, 5]`` int32:
    (round, observer, subject, event_type, incarnation) per recorded
    event, plus ``count`` (events written) and ``dropped`` (events that
    arrived after the buffer filled).  Overflow is ALWAYS counted —
    the decoded trace is an exact prefix of the event stream and
    ``dropped`` says precisely how many events are missing; nothing is
    silently truncated.
  - :class:`TelemetryState` — the trace plus per-(observer, subject)
    ``first_suspect`` / ``first_removed`` round matrices, the inputs of
    the in-jit detection/removal latency histograms
    (:func:`latency_histograms` — no per-round host round trips).

Event detection is transition-based: :func:`derive_event_codes` compares
the carry's (status, incarnation) before and after one ``swim_tick``
(models/swim.py) and emits the NET transition per cell — the same five
types the oracle's merge funnel emits through ``listen_trace``
(telemetry/events.py has the schema + the per-round collapse caveat).
A crashed observer's rows are frozen by the tick, so a stopped node
emits nothing — exactly a stopped JVM.

Cost: recording flattens one ``[N, K]`` int8 code matrix per round —
one fused elementwise pass to derive the net-transition codes, a cumsum
to assign slots, ONE scatter into the lane buffer (no per-event-type
passes), and one fused count/overflow bookkeeping update.  It is OFF
unless requested (``models/swim.run_traced``); the untraced hot path is
untouched.  For long runs, ``telemetry/sink.stream_traced_run``
overlaps the device→host offload of each segment's trace slab with the
next segment's compute, so traced throughput tracks untraced
(bench.py's ``traced_overhead_ratio``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scalecube_cluster_tpu import records
from scalecube_cluster_tpu.telemetry.events import (
    MembershipTraceEvent,
    TraceEventType,
)

INT32_MAX = jnp.iinfo(jnp.int32).max

# Default event-buffer capacity: comfortably above the 2·N SUSPECTED +
# REMOVED events of a crash scenario at the telemetry-scenario scales
# (bench.py caps its traced scenario well below this), small enough
# (65536 × 5 lanes × 4 B = 1.3 MB) to be free next to any carry.
DEFAULT_CAPACITY = 1 << 16

# Latency histogram bucket edges, in protocol rounds.  Bucket i covers
# [edges[i], edges[i+1]); the last bucket is open-ended.  Roughly
# geometric: detection latencies cluster at a few probe cycles, removal
# adds the suspicion timeout, so the range spans both regimes.
DEFAULT_LATENCY_EDGES = (0, 1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64,
                         96, 128, 192, 256, 384, 512)

_N_LANES = 5  # (round, observer, subject, event_type, incarnation)


# --------------------------------------------------------------------------
# Carried state
# --------------------------------------------------------------------------


@dataclasses.dataclass
class EventTrace:
    """Fixed-capacity event buffer (module docstring).

    ``lanes[i] = (round, observer, subject, event_type, incarnation)``
    for i < ``count``, in (round, observer-major cell) order — the
    deterministic serialization of each round's transitions.
    """

    lanes: jnp.ndarray      # [capacity, 5] int32
    count: jnp.ndarray      # int32 scalar: events recorded (<= capacity)
    dropped: jnp.ndarray    # int32 scalar: events lost to overflow

    @property
    def capacity(self) -> int:
        return self.lanes.shape[0]

    @staticmethod
    def empty(capacity: int = DEFAULT_CAPACITY) -> "EventTrace":
        return EventTrace(
            lanes=jnp.full((capacity, _N_LANES), -1, dtype=jnp.int32),
            count=jnp.int32(0),
            dropped=jnp.int32(0),
        )


jax.tree_util.register_dataclass(
    EventTrace, data_fields=["lanes", "count", "dropped"], meta_fields=[]
)


@dataclasses.dataclass
class TelemetryState:
    """Scan-carried telemetry: the event buffer + first-transition rounds.

    ``first_suspect``/``first_removed`` [N, K] int32: the first round
    observer i turned subject-slot k SUSPECT / DEAD (INT32_MAX = never)
    — the per-observer detection/removal samples the latency histograms
    reduce over.
    """

    trace: EventTrace
    first_suspect: jnp.ndarray
    first_removed: jnp.ndarray

    @staticmethod
    def init(n_members: int, n_subjects: int,
             capacity: int = DEFAULT_CAPACITY) -> "TelemetryState":
        def full():
            # Two SEPARATE buffers: run_traced donates its telemetry
            # argument, and donating one aliased array through two tree
            # leaves is an XLA error ("donate the same buffer twice").
            return jnp.full((n_members, n_subjects), INT32_MAX,
                            dtype=jnp.int32)

        return TelemetryState(
            trace=EventTrace.empty(capacity),
            first_suspect=full(),
            first_removed=full(),
        )

    @staticmethod
    def resume(first_suspect, first_removed,
               capacity: int = DEFAULT_CAPACITY) -> "TelemetryState":
        """Segment-resume shape: a FRESH event buffer with the
        first-transition matrices carried over — what every segmented
        traced driver hands run_traced per segment (sink
        .stream_traced_run's overlapped offload, the resilient
        supervisor's checkpoint restore).  The matrices are converted
        on the way in, so host numpy from a checkpoint is fine."""
        return TelemetryState(
            trace=EventTrace.empty(capacity),
            first_suspect=jnp.asarray(first_suspect),
            first_removed=jnp.asarray(first_removed),
        )


jax.tree_util.register_dataclass(
    TelemetryState,
    data_fields=["trace", "first_suspect", "first_removed"],
    meta_fields=[],
)


# --------------------------------------------------------------------------
# Per-round recording (called inside the scan body)
# --------------------------------------------------------------------------


def derive_event_codes(prev_status, prev_inc, new_status, new_inc,
                       is_self, leaving_now, self_inc,
                       prev_epoch=None, new_epoch=None):
    """(codes, incarnations) of this round's net cell transitions.

    ``codes`` [N, K] int8: 0 = no event, else TraceEventType + 1.  The
    (prev, new) status pair determines at most one transition per cell
    (events.py maps each to its reference merge-funnel line):

      ABSENT/DEAD -> ALIVE   ADDED        (tombstone re-add included —
                                           delete-then-re-add, :512-516)
      !SUSPECT    -> SUSPECT SUSPECTED
      SUSPECT     -> ALIVE   ALIVE_REFUTED
      !DEAD       -> DEAD    REMOVED

    Self cells are pinned by the tick (never transition); the one self
    event is LEAVING, injected from the world's leave schedule with the
    announced incarnation self_inc + 1 (leaveCluster's DEAD@inc+1).

    ``prev_epoch``/``new_epoch`` (the open-world identity lane,
    models/swim.SwimState.epoch — None when the plane is off): a cell
    whose stored EPOCH ADVANCED to a live record is a JOIN admission —
    it codes ``JOINED``, disambiguating a NEW identity entering a
    recycled slot from a same-identity re-add (which stays ``ADDED``).
    The admission wins over every status-derived code for the cell
    (e.g. a stale-ALIVE cell admitting the new identity is a JOINED,
    not a silent ALIVE->ALIVE), keeping the one-event-per-cell
    partition exact.

    The transition masks are mutually exclusive by construction
    (they partition on the NEW status: ALIVE splits on the previous
    status, SUSPECT and DEAD each gate on not-already-there), so the
    code matrix is ONE weighted sum of disjoint masks — a single fused
    elementwise pass over the [N, K] pair, not a per-type select chain.
    This is the traced tick's whole per-round overhead next to the
    untraced path, so it stays one pass.
    """
    prev = prev_status
    new = new_status
    added = ((prev == records.ABSENT) | (prev == records.DEAD)) \
        & (new == records.ALIVE)
    suspected = (new == records.SUSPECT) & (prev != records.SUSPECT)
    refuted = (prev == records.SUSPECT) & (new == records.ALIVE)
    removed = (new == records.DEAD) & (prev != records.DEAD)

    joined = None
    if prev_epoch is not None and jnp.asarray(prev_epoch).size:
        joined = (
            (jnp.asarray(new_epoch, jnp.int32)
             > jnp.asarray(prev_epoch, jnp.int32))
            & ((new == records.ALIVE) | (new == records.SUSPECT))
        )
        not_joined = ~joined
        added &= not_joined
        suspected &= not_joined
        refuted &= not_joined
        removed &= not_joined

    code = (
        added.astype(jnp.int8) * jnp.int8(TraceEventType.ADDED + 1)
        + suspected.astype(jnp.int8) * jnp.int8(TraceEventType.SUSPECTED + 1)
        + refuted.astype(jnp.int8)
        * jnp.int8(TraceEventType.ALIVE_REFUTED + 1)
        + removed.astype(jnp.int8) * jnp.int8(TraceEventType.REMOVED + 1)
    )
    if joined is not None:
        code = code + joined.astype(jnp.int8) * jnp.int8(
            TraceEventType.JOINED + 1)
    code = jnp.where(is_self, jnp.int8(0), code)
    code = jnp.where(leaving_now, jnp.int8(TraceEventType.LEAVING + 1), code)

    inc = jnp.asarray(new_inc, jnp.int32)
    inc = jnp.where(leaving_now,
                    jnp.asarray(self_inc, jnp.int32)[:, None] + 1, inc)
    return code, inc


def record_events(trace: EventTrace, round_idx, codes, incarnations,
                  subject_ids, observer_offset: int = 0) -> EventTrace:
    """Compact this round's coded cells into the event buffer
    (single-round form of :func:`record_events_batch`)."""
    return record_events_batch(
        trace, jnp.asarray(round_idx, jnp.int32)[None],
        codes[None], incarnations[None], subject_ids,
        observer_offset=observer_offset,
    )


def record_events_batch(trace: EventTrace, round_ids, codes, incarnations,
                        subject_ids, observer_offset: int = 0) -> EventTrace:
    """Compact a BATCH of rounds' coded cells into the event buffer.

    ``round_ids`` [R], ``codes``/``incarnations`` [R, N, K]: the stacked
    per-round transition codes of one fused scan step
    (models/swim.run_traced with rounds_per_step > 1).  Flattening is
    round-major then row-major — exactly the order R sequential
    single-round records would produce — so the resulting (lanes, count,
    dropped) are bit-identical to the per-round path while paying the
    cumsum + scatter ONCE per step.  The whole record runs under a
    ``lax.cond`` and is skipped exactly when the batch holds no events
    (the identity on the buffer), so silent steady-state steps cost one
    reduction, not a scatter.
    """
    r, n, k = codes.shape
    cap = trace.capacity
    flat_code = codes.reshape(-1)
    has = flat_code > 0
    flat_round = jnp.broadcast_to(
        jnp.asarray(round_ids, jnp.int32)[:, None, None], (r, n, k)
    ).reshape(-1)
    flat_inc = incarnations.reshape(-1)
    observer = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32)[None, :, None] + observer_offset,
        (r, n, k),
    ).reshape(-1)
    subject = jnp.broadcast_to(
        jnp.asarray(subject_ids, jnp.int32)[None, None, :], (r, n, k)
    ).reshape(-1)

    def record(tr: EventTrace) -> EventTrace:
        slot = tr.count + jnp.cumsum(has.astype(jnp.int32)) - 1
        idx = jnp.where(has & (slot < cap), slot, cap)  # cap = OOB -> drop
        rows = jnp.stack([
            flat_round,
            observer,
            subject,
            flat_code.astype(jnp.int32) - 1,
            flat_inc,
        ], axis=1)
        lanes = tr.lanes.at[idx].set(rows, mode="drop")
        total = jnp.sum(has, dtype=jnp.int32)
        new_count = jnp.minimum(tr.count + total, cap)
        new_dropped = tr.dropped + total - (new_count - tr.count)
        return EventTrace(lanes=lanes, count=new_count, dropped=new_dropped)

    return jax.lax.cond(jnp.any(has), record, lambda tr: tr, trace)


def round_transition_codes(round_idx, prev_status, prev_inc, new_state,
                           world, observer_offset: int = 0,
                           prev_epoch=None):
    """(codes, ev_inc) of one tick's net transitions (the derive half of
    :func:`observe_round` — split out so the fused scan can batch the
    record half across rounds_per_step ticks).  ``prev_epoch``: the
    carry's identity-epoch lane BEFORE the tick (open-world plane; the
    new lane rides in ``new_state.epoch``) — None disables the JOINED
    disambiguation."""
    n = prev_status.shape[0]
    node_ids = jnp.arange(n, dtype=jnp.int32) + observer_offset
    is_self = jnp.asarray(world.subject_ids, jnp.int32)[None, :] \
        == node_ids[:, None]
    leaving_now = (world.leave_at[node_ids] == round_idx)[:, None] & is_self
    return derive_event_codes(
        prev_status, prev_inc, new_state.status, new_state.inc,
        is_self, leaving_now, new_state.self_inc,
        prev_epoch=prev_epoch,
        new_epoch=None if prev_epoch is None else new_state.epoch,
    )


def update_first_rounds(tel: TelemetryState, codes,
                        round_idx) -> TelemetryState:
    """Advance the first-suspect/first-removed matrices for one round's
    codes (trace buffer untouched — pair with record_events[_batch])."""
    suspected = codes == jnp.int8(TraceEventType.SUSPECTED + 1)
    removed = codes == jnp.int8(TraceEventType.REMOVED + 1)
    first_suspect = jnp.where(
        suspected & (tel.first_suspect == INT32_MAX), round_idx,
        tel.first_suspect,
    )
    first_removed = jnp.where(
        removed & (tel.first_removed == INT32_MAX), round_idx,
        tel.first_removed,
    )
    return TelemetryState(trace=tel.trace, first_suspect=first_suspect,
                          first_removed=first_removed)


def observe_round_codes(tel: TelemetryState, round_idx, prev_status,
                        prev_inc, new_state, world,
                        observer_offset: int = 0, prev_epoch=None,
                        any_status_change=None):
    """(tel', codes, ev_inc) for one tick, with the WHOLE derivation +
    first-round update gated on a two-reduction predicate.

    Every event type requires a status transition (incarnation-only
    changes emit nothing) except LEAVING, which fires off the world's
    leave schedule, and JOINED, which requires an epoch-lane change —
    so ``any(status changed) | any(leaving now) [| any(epoch changed)]``
    is an exact emptiness test, and steady-state rounds (the
    overwhelming majority) cost one [N, K] compare + one [N] compare
    instead of the full derivation.  The silent branch returns all-zero
    codes, which every consumer (record scatter, first-round updates)
    treats as the identity — bit-identical to the ungated path.

    ``any_status_change``: the precomputed ``any(prev != new)`` scalar
    from the composed runner's shared round context
    (models/compose.RoundCtx) — the same value this function would
    derive itself, handed in so a multi-plane stack pays the reduction
    once; None recomputes it (the single-plane path, identical bits).
    """
    n = prev_status.shape[0]
    node_ids = jnp.arange(n, dtype=jnp.int32) + observer_offset
    changed = (jnp.any(prev_status != new_state.status)
               if any_status_change is None else any_status_change)
    pred = changed | jnp.any(world.leave_at[node_ids] == round_idx)
    if prev_epoch is not None and jnp.asarray(prev_epoch).size:
        pred = pred | jnp.any(
            jnp.asarray(prev_epoch) != jnp.asarray(new_state.epoch))

    def active(t):
        codes, ev_inc = round_transition_codes(
            round_idx, prev_status, prev_inc, new_state, world,
            observer_offset, prev_epoch=prev_epoch,
        )
        return update_first_rounds(t, codes, round_idx), codes, ev_inc

    def silent(t):
        return (t, jnp.zeros(prev_status.shape, dtype=jnp.int8),
                jnp.zeros(prev_status.shape, dtype=jnp.int32))

    return jax.lax.cond(pred, active, silent, tel)


def observe_round(tel: TelemetryState, round_idx, prev_status, prev_inc,
                  new_state, world, observer_offset: int = 0,
                  prev_epoch=None, any_status_change=None
                  ) -> TelemetryState:
    """One round's telemetry update: derive transitions, record them,
    advance the first-suspect/first-removed matrices.

    ``prev_status``/``prev_inc`` are the carry fields BEFORE the tick,
    ``new_state`` the SwimState after; both in their stored layout (the
    int16 compact-carry incarnation upcasts losslessly below its
    saturation point).  Called from models/swim.run_traced inside the
    scan body (the fused body batches the record half per scan step —
    record_events_batch).  Event-free rounds reduce to two cheap
    predicates (observe_round_codes + record's own cond).
    """
    tel, codes, ev_inc = observe_round_codes(
        tel, round_idx, prev_status, prev_inc, new_state, world,
        observer_offset, prev_epoch=prev_epoch,
        any_status_change=any_status_change,
    )
    trace = record_events(tel.trace, round_idx, codes, ev_inc,
                          world.subject_ids, observer_offset)
    return TelemetryState(trace=trace, first_suspect=tel.first_suspect,
                          first_removed=tel.first_removed)


# --------------------------------------------------------------------------
# The compose() plane
# --------------------------------------------------------------------------


class TracePlane:
    """The membership event trace as a composed-runner plane
    (models/compose.py): carry slice = :class:`TelemetryState`,
    per-round hook = :func:`observe_round` reading the shared round
    context, fused-step hook = ONE :func:`record_events_batch` scatter
    per scan step (exactly the pre-compose ``run_traced`` fused body).

    ``telemetry`` resumes an existing state across chunked scans (the
    ``run_traced(telemetry=...)`` argument threads through here).
    """

    name = "trace"
    fused = True

    def __init__(self, capacity: int = DEFAULT_CAPACITY, telemetry=None,
                 observer_offset: int = 0):
        self.capacity = capacity
        self.telemetry = telemetry
        self.observer_offset = observer_offset

    def init(self, params, world):
        if self.telemetry is not None:
            return self.telemetry
        return TelemetryState.init(params.n_members, params.n_subjects,
                                   self.capacity)

    def _prev_epoch(self, rc):
        return rc.prev.epoch if rc.params.epoch_bits else None

    def on_round(self, rc, tel):
        return observe_round(
            tel, rc.round_idx, rc.prev.status, rc.prev.inc, rc.new,
            rc.world, observer_offset=self.observer_offset,
            prev_epoch=self._prev_epoch(rc),
            any_status_change=rc.any_status_change,
        )

    def on_round_fused(self, rc, tel):
        tel, codes, ev_inc = observe_round_codes(
            tel, rc.round_idx, rc.prev.status, rc.prev.inc, rc.new,
            rc.world, self.observer_offset,
            prev_epoch=self._prev_epoch(rc),
            any_status_change=rc.any_status_change,
        )
        return tel, (codes, ev_inc)

    def on_step(self, rounds_k, tel, stacked, world):
        codes, ev_inc = stacked
        trace = record_events_batch(tel.trace, rounds_k, codes, ev_inc,
                                    world.subject_ids,
                                    self.observer_offset)
        return TelemetryState(trace=trace, first_suspect=tel.first_suspect,
                              first_removed=tel.first_removed)

    def on_round_batch(self, rc, tel):
        """The batched fold (models/compose.composed_batch_scan): one
        ``lax.cond`` on the BATCH-LEVEL emptiness predicate — any row's
        status change, scheduled leave, or epoch advance — wrapping the
        vmapped per-row :func:`observe_round`.  A globally-silent round
        (the steady-state majority across the whole batch) costs the
        predicate reductions only; when any row has events, silent rows
        ride the active branch with all-zero codes, which the record
        scatter and first-round updates treat as the identity — so
        every row stays bit-identical to its sequential run.
        """
        node_ids = jnp.arange(rc.params.n_members, dtype=jnp.int32) \
            + self.observer_offset
        pred = rc.any_status_change | jnp.any(
            rc.world.leave_at[:, node_ids] == rc.round_idx)
        prev_epoch = self._prev_epoch(rc)
        if prev_epoch is not None and jnp.asarray(prev_epoch).size:
            pred = pred | jnp.any(
                jnp.asarray(prev_epoch) != jnp.asarray(rc.new.epoch))

        def active(t):
            def row(tel_r, prev, new, world):
                return observe_round(
                    tel_r, rc.round_idx, prev.status, prev.inc, new,
                    world, observer_offset=self.observer_offset,
                    prev_epoch=(prev.epoch if rc.params.epoch_bits
                                else None),
                )
            return jax.vmap(row)(t, rc.prev, rc.new, rc.world)

        return jax.lax.cond(pred, active, lambda t: t, tel)

    def finalize(self, fc, tel):
        return tel


# --------------------------------------------------------------------------
# In-jit derived metrics
# --------------------------------------------------------------------------


def _bucketize(values, edges):
    e = jnp.asarray(edges, jnp.int32)
    idx = jnp.searchsorted(e, values, side="right") - 1
    return jnp.clip(idx, 0, len(edges) - 1)


def latency_histograms(tel: TelemetryState, world,
                       edges: Sequence[int] = DEFAULT_LATENCY_EDGES,
                       ref_rounds=None) -> dict:
    """Detection/removal latency histograms per subject, on device.

    Latency of observer i for subject slot k = first transition round
    minus the subject's fault round (``ref_rounds`` [K]; default: the
    earlier of the subject's crash and leave rounds from the world
    schedule).  Subjects with no scheduled fault (or transitions that
    precede it — false positives) are excluded; ``*_undetected`` counts
    observers that never transitioned for a faulted subject.

    Returns {"edges": [B], "detection": [K, B], "removal": [K, B],
    "detection_undetected": [K], "removal_undetected": [K]} of device
    arrays — pure jnp, callable under jit (no host round trips).
    """
    subject_ids = jnp.asarray(world.subject_ids, jnp.int32)
    if ref_rounds is None:
        ref_rounds = jnp.minimum(world.down_from[subject_ids],
                                 world.leave_at[subject_ids])
    ref = jnp.asarray(ref_rounds, jnp.int32)
    n = tel.first_suspect.shape[0]
    k = subject_ids.shape[0]
    b = len(edges)
    is_self = subject_ids[None, :] == jnp.arange(n, dtype=jnp.int32)[:, None]
    faulted = (ref != INT32_MAX)[None, :]

    out = {"edges": jnp.asarray(edges, jnp.int32)}
    for name, first in (("detection", tel.first_suspect),
                        ("removal", tel.first_removed)):
        lat = first - ref[None, :]
        valid = (first != INT32_MAX) & faulted & (lat >= 0) & ~is_self
        bucket = _bucketize(lat, edges)
        flat = jnp.where(
            valid,
            jnp.arange(k, dtype=jnp.int32)[None, :] * b + bucket,
            k * b,
        ).reshape(-1)
        counts = jnp.zeros((k * b,), jnp.int32).at[flat].add(
            1, mode="drop"
        ).reshape(k, b)
        out[name] = counts
        out[name + "_undetected"] = jnp.sum(
            (first == INT32_MAX) & faulted & ~is_self, axis=0,
            dtype=jnp.int32,
        )
    return out


# --------------------------------------------------------------------------
# Host-side decoding
# --------------------------------------------------------------------------


def decode_events(trace_or_tel) -> list:
    """Device buffer -> typed ``MembershipTraceEvent`` list (host side).

    Accepts an :class:`EventTrace` or a :class:`TelemetryState`.  The
    result is the exact recorded prefix of the event stream, in
    (round, observer-major cell) order; ``trace.dropped`` says how many
    later events the capacity cut off.
    """
    trace = getattr(trace_or_tel, "trace", trace_or_tel)
    lanes = np.asarray(trace.lanes)
    count = int(trace.count)
    return [
        MembershipTraceEvent(
            round=int(lanes[i, 0]),
            observer=int(lanes[i, 1]),
            subject=int(lanes[i, 2]),
            event_type=TraceEventType(int(lanes[i, 3])),
            incarnation=int(lanes[i, 4]),
        )
        for i in range(count)
    ]


def histograms_to_json(hists: dict) -> dict:
    """Device histogram dict -> plain-python JSONL-ready form."""
    out = {}
    for name, v in hists.items():
        out[name] = np.asarray(v).tolist()
    return out
