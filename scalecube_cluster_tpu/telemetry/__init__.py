"""On-device membership event trace + telemetry pipeline.

The reference exposes its protocol life through observable surfaces —
``MembershipProtocol.listen()`` emits a typed event stream, SLF4J logs
per-period counters, and JMX MBeans answer point queries (SURVEY.md
§5.1).  A jit'd 10k-round scan can't call a listener per event; this
package is the dense-equivalent observability stack:

  - ``events``  the typed event schema (``MembershipTraceEvent``) shared
                by BOTH layers: the oracle emits it from its merge funnel
                (``MembershipProtocol.listen_trace``) and the TPU tick's
                decoded trace produces the same records, so model-vs-
                oracle event streams are directly diffable — observability
                doubling as a correctness surface.  Pure Python, no jax.
  - ``trace``   the jit side: a fixed-capacity event buffer carried
                through ``jax.lax.scan`` (int32 lanes, overflow counted,
                never silently truncated), per-(observer, subject)
                first-suspect/first-removal round tracking, and in-jit
                detection/removal latency histograms.
  - ``sink``    host sinks: a JSONL run manifest (run id, config digest,
                device info, counter rows, histograms, event batches,
                windowed health-metrics flushes) and a TensorBoard
                exporter gated behind ``SCALECUBE_TPU_PROFILE_DIR``.
  - ``metrics`` the always-on numeric health plane: a fixed-shape
                in-jit counter/gauge/histogram registry carried through
                the scan (``models/swim.run_metered``), psum-combined
                across a device mesh, flushed per window as
                ``metrics_window`` records.
  - ``query``   the cross-run half: load/merge manifests, compute the
                health SLOs (false-positive observer-rate, latency
                percentiles, dissemination rounds), ``diff`` two runs,
                ``regress`` along a BENCH trajectory — all behind the
                ``python -m scalecube_cluster_tpu.telemetry`` CLI.
"""

from scalecube_cluster_tpu.telemetry import events, sink, trace
from scalecube_cluster_tpu.telemetry import metrics, query  # noqa: E402
from scalecube_cluster_tpu.telemetry.events import (
    MembershipTraceEvent,
    OracleTraceCollector,
    TraceEventType,
    event_key_set,
)

__all__ = [
    "events",
    "metrics",
    "query",
    "sink",
    "trace",
    "MembershipTraceEvent",
    "OracleTraceCollector",
    "TraceEventType",
    "event_key_set",
]
