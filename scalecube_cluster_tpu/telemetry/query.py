"""Cross-run health queries: load/merge manifests, SLOs, diff, regress.

The host half of the always-on health plane (telemetry/metrics.py).
JSONL run manifests accumulate ``metrics_window`` rows (windowed
registry flushes), ``histogram`` rows (detection/removal latency
buckets) and counter rows; this module folds them into one
:class:`HealthReport` per run, computes the protocol's quantitative
SLOs — the paper's headline guarantees as numbers —

  - ``false_positive_observer_rate``: false-suspicion onsets per live
    observer-round (the bounded-false-positive guarantee),
  - ``detection_latency_p50/p99`` and ``removal_latency_p50/p99``
    rounds (expected-detection-time, from the latency histograms),
  - ``suspicion_lifetime_p50/p99`` rounds (Lifeguard's timeout-health
    signal, from the registry histogram),
  - ``dissemination_rounds`` (the O(log n) spread, from the
    fraction-informed curve when present),

and compares runs: :func:`diff_reports` for two manifests,
:func:`regress` for the BENCH_*.json + MULTICHIP_*.json trajectories
with a noise band (single-chip and multichip per-chip throughput gate
as independent series; legacy MULTICHIP stubs skip as provenance) —
the regression gate ``python -m scalecube_cluster_tpu.telemetry
regress`` runs in CI (tests/test_metrics_query.py pins it against the
committed BENCH_r01..r05 series).

Percentiles from buckets: counts in bucket i cover
``[edges[i], edges[i+1])`` (last bucket open); the percentile
interpolates linearly inside its bucket and clamps to the last edge
for the open tail — a LOWER bound there (real latencies in the open
bucket are >= the reported value), so declare edges past the tail
you care about.
"""

from __future__ import annotations

import dataclasses
import glob as globlib
import json
import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

from scalecube_cluster_tpu.telemetry import sink as tsink

THROUGHPUT_METRIC = "swim_member_rounds_per_sec_per_chip"
DEFAULT_NOISE_BAND = 0.10
# Dissemination is integer-quantized (rounds); allow the quantization
# step on top of the relative band before calling it a regression.
DISSEMINATION_SLACK_ROUNDS = 1
# The provenance plane's absolute overhead ceiling (ISSUE 20): the
# composed stack with per-channel attribution may cost at most 10% over
# the same stack without the plane, measured interleaved on one host.
PROVENANCE_OVERHEAD_LIMIT = 1.10


# --------------------------------------------------------------------------
# Loading + merging one run's manifest
# --------------------------------------------------------------------------


@dataclasses.dataclass
class HealthReport:
    """One run's folded health state.

    ``counters``/``gauges``: merged over every ``metrics_window`` row
    (counters sum — they are window totals; gauges take the LAST
    window's sample).  ``histograms``: bucket counts summed per name,
    both the registry's windows and standalone ``histogram`` records
    (detection/removal latency).  ``windows`` keeps the raw per-window
    rows for time-resolved rendering.
    """

    path: str
    run_id: Optional[str]
    counters: Dict[str, int]
    gauges: Dict[str, float]
    histograms: Dict[str, Tuple[List[int], List[int]]]  # name -> (edges, counts)
    windows: List[dict]
    curves: Dict[str, dict]
    summary: dict
    # Channel-attribution rows (``provenance`` records, PR 20) — empty
    # for journals written before the plane existed (old journals stay
    # valid; the blame engine just has nothing to mine).
    provenance: List[dict] = dataclasses.field(default_factory=list)

    @property
    def rounds_covered(self) -> int:
        return max((int(w["round_end"]) for w in self.windows), default=0)


def _merge_hist(store: Dict[str, Tuple[List[int], List[int]]], name: str,
                edges: Sequence[int], counts: Sequence[int]) -> None:
    edges, counts = list(edges), [int(c) for c in counts]
    if name not in store:
        store[name] = (edges, counts)
        return
    old_edges, old_counts = store[name]
    if old_edges != edges:
        raise ValueError(
            f"histogram {name!r}: incompatible edges across records "
            f"({old_edges} vs {edges})")
    store[name] = (old_edges,
                   [a + b for a, b in zip(old_counts, counts)])


def load_report(path: str) -> HealthReport:
    """Fold one JSONL manifest into a :class:`HealthReport`."""
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Tuple[List[int], List[int]]] = {}
    windows: List[dict] = []
    curves: Dict[str, dict] = {}
    summary: dict = {}
    provenance: List[dict] = []
    run_id = None
    for rec in tsink.iter_records(path):
        run_id = run_id or rec.get("run_id")
        kind = rec.get("kind")
        if kind == "metrics_window":
            windows.append(rec)
            for k, v in rec.get("counters", {}).items():
                counters[k] = counters.get(k, 0) + int(v)
            for k, v in rec.get("gauges", {}).items():
                gauges[k] = float(v)          # last window wins
            for name, h in rec.get("histograms", {}).items():
                _merge_hist(hists, name, h["edges"], h["counts"])
        elif kind == "histogram":
            _merge_hist(hists, rec["name"], rec["edges"], rec["counts"])
        elif kind == "curve":
            curves[rec["name"]] = rec
        elif kind == "summary":
            summary.update({k: v for k, v in rec.items()
                            if k not in ("kind", "run_id")})
        elif kind == "events_footer":
            # The trace buffer's overflow accounting (sink.write_events'
            # footer): fold it into a counter lane so a truncated event
            # stream surfaces in every report/regress path instead of
            # living only in the raw journal (drops are additive across
            # segments — each footer closes one segment's buffer).
            counters["trace_dropped_total"] = (
                counters.get("trace_dropped_total", 0)
                + int(rec.get("dropped", 0)))
        elif kind == "provenance":
            provenance.append(rec)
            # Accounting totals are idempotent across chunks
            # (sink.write_provenance) — last one wins.
            if "dropped" in rec:
                counters["provenance_dropped_total"] = int(rec["dropped"])
    provenance.sort(key=lambda r: int(r.get("offset", 0)))
    rows = [row for rec in provenance for row in rec.get("rows", [])]
    return HealthReport(path=path, run_id=run_id, counters=counters,
                        gauges=gauges, histograms=hists, windows=windows,
                        curves=curves, summary=summary, provenance=rows)


def merge_reports(reports: Sequence[HealthReport]) -> HealthReport:
    """Fold several runs' reports into one (counters/histograms sum,
    gauges take the last run's samples) — the cross-run aggregate the
    CLI ``report`` command prints for multiple manifests."""
    out = HealthReport(path=",".join(r.path for r in reports),
                       run_id=None, counters={}, gauges={}, histograms={},
                       windows=[], curves={}, summary={})
    for r in reports:
        for k, v in r.counters.items():
            out.counters[k] = out.counters.get(k, 0) + v
        out.gauges.update(r.gauges)
        for name, (edges, counts) in r.histograms.items():
            _merge_hist(out.histograms, name, edges, counts)
        out.windows.extend(r.windows)
        out.curves.update(r.curves)
        out.summary.update(r.summary)
        out.provenance.extend(r.provenance)
    return out


# --------------------------------------------------------------------------
# SLOs
# --------------------------------------------------------------------------


def percentile_from_histogram(edges: Sequence[int], counts: Sequence[int],
                              q: float) -> Optional[float]:
    """q-th percentile (q in [0, 1]) from bucketed counts.

    Linear interpolation within the bucket; the open last bucket clamps
    to its lower edge (conservative: real latencies there are >= it).
    None when the histogram is empty.
    """
    total = sum(counts)
    if total == 0:
        return None
    target = q * total
    if target == 0:  # p0 = the smallest observed bucket's lower edge
        return float(edges[next(i for i, c in enumerate(counts) if c)])
    cum = 0.0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= target:
            lo = edges[i]
            hi = edges[i + 1] if i + 1 < len(edges) else edges[i]
            frac = (target - cum) / c
            return float(lo + (hi - lo) * frac)
        cum += c
    return float(edges[-1])


def dissemination_rounds_from_curve(curve: dict) -> Optional[int]:
    """First round the fraction-informed curve reaches 1.0 (upper bound
    under downsampling: the stride makes this at most one stride late,
    never early), relative to the curve's round offset."""
    values = curve.get("values") or []
    stride = int(curve.get("stride", 1))
    for i, v in enumerate(values):
        if v >= 1.0:
            return i * stride
    return None


def compute_slos(report: HealthReport) -> dict:
    """The protocol health SLOs of one (merged) report — module
    docstring.  Missing inputs yield None, never a crash: a partial
    manifest still reports what it can."""
    c, g = report.counters, report.gauges
    slos: dict = {}

    onsets = c.get("false_suspicion_onsets")
    obs_rounds = c.get("live_observer_rounds")
    slos["false_positive_observer_rate"] = (
        (onsets / obs_rounds) if onsets is not None and obs_rounds
        else None)

    for name, key in (("detection_latency", "detection_latency_rounds"),
                      ("removal_latency", "removal_latency_rounds"),
                      ("suspicion_lifetime", "suspicion_lifetime_rounds")):
        h = report.histograms.get(key)
        for q, tag in ((0.5, "p50"), (0.99, "p99")):
            slos[f"{name}_{tag}"] = (
                percentile_from_histogram(h[0], h[1], q) if h else None)

    curve = report.curves.get("fraction_informed")
    slos["dissemination_rounds"] = (
        dissemination_rounds_from_curve(curve) if curve else None)

    # SYNC anti-entropy plane: rounds from the partition heal to the
    # first divergence-free membership table (bench.py --sync writes
    # this into the run's summary row; models/sync.py defines the
    # divergence observable).
    slos["sync_rounds_to_converge"] = report.summary.get(
        "sync_rounds_to_converge")

    # Metadata KV plane: p99 of per-push convergence latency (rounds
    # from a config push — or the end of the disruption that covered it
    # — to every live table holding the word; bench.py --rollout writes
    # this into the run's summary row, models/metadata.py defines the
    # divergence observable).
    slos["metadata_convergence_p99"] = report.summary.get(
        "metadata_convergence_p99")

    slos["chaos_violations"] = c.get("chaos_violations")
    slos["suspect_entries"] = g.get("suspect_entries")
    slos["wire_saturation"] = g.get("wire_saturation")
    slos["gossip_piggyback_occupancy"] = g.get("gossip_piggyback_occupancy")
    slos["rounds_covered"] = report.rounds_covered or None

    # Trace-buffer overflow, surfaced as a first-class lane (an
    # events_footer journals it; a report that never shows it invites
    # mistaking a truncated trace for a complete one).  None when the
    # journal carries no event stream at all.
    slos["trace_dropped_total"] = c.get("trace_dropped_total")

    # Provenance plane (PR 20): channel-mix SLOs over the journaled
    # attribution rows — absent (not None-padded) for journals without
    # the plane, so pre-plane reports render unchanged.
    if report.provenance:
        slos.update(provenance_slos(report.provenance))
        slos["provenance_dropped_total"] = c.get(
            "provenance_dropped_total", 0)
    return slos


# --------------------------------------------------------------------------
# The blame engine: infection paths, channel-mix SLOs, explain
# --------------------------------------------------------------------------

# Channels that are FIRST-HAND evidence (the observer's own failure
# detector, direct or through its ping-req proxies) — everything else
# relays somebody else's verdict (models/provenance.CHANNEL_NAMES).
FIRST_HAND_CHANNELS = ("fd_direct", "pingreq_proxy")

# The transitions that constitute "believing the subject is failing" —
# what infection paths and blame reports trace by default.
SUSPICION_TRANSITIONS = ("SUSPECTED", "REMOVED")


def _percentile(values: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of a raw sample list (None when empty)."""
    if not values:
        return None
    v = sorted(values)
    idx = min(len(v) - 1, max(0, math.ceil(q * len(v)) - 1))
    return float(v[idx])


def infection_paths(rows: Sequence[dict], subject: int,
                    transitions: Sequence[str] = SUSPICION_TRANSITIONS
                    ) -> Dict[int, dict]:
    """Per-observer infection path for one subject: observer ->
    ``{"first_round", "first_channel", "first_transition",
    "channels": {channel: first round seen via it}}``.

    ``rows`` is the journaled attribution stream
    (HealthReport.provenance); ``transitions`` restricts which belief
    the path traces (default: the suspicion funnel — SUSPECTED and
    REMOVED).  The first-informed round per observer per channel is
    exactly the "who told you, and how" reconstruction the module
    docstring promises.
    """
    paths: Dict[int, dict] = {}
    for r in rows:
        if int(r.get("subject", -1)) != subject:
            continue
        if r.get("transition") not in transitions:
            continue
        obs, ch, rnd = int(r["observer"]), r["channel"], int(r["round"])
        entry = paths.setdefault(obs, {
            "first_round": None, "first_channel": None,
            "first_transition": None, "channels": {},
        })
        if ch not in entry["channels"] or rnd < entry["channels"][ch]:
            entry["channels"][ch] = rnd
        if entry["first_round"] is None or rnd < entry["first_round"]:
            entry["first_round"] = rnd
            entry["first_channel"] = ch
            entry["first_transition"] = r["transition"]
    return paths


def channel_mix(rows: Sequence[dict]) -> Dict[str, float]:
    """Fraction of attributed transitions per channel ({} when empty).
    The attribution cascade is total, so the fractions sum to exactly
    1.0 — the bench gate recomputes the sum from here."""
    counts: Dict[str, int] = {}
    for r in rows:
        counts[r["channel"]] = counts.get(r["channel"], 0) + 1
    total = sum(counts.values())
    if not total:
        return {}
    return {ch: c / total for ch, c in sorted(counts.items())}


def provenance_slos(rows: Sequence[dict]) -> dict:
    """Channel-mix SLOs over the attribution stream:

      - ``removal_via_sync_fraction``: of all REMOVED transitions, the
        fraction whose winning channel was the SYNC family — how much
        of the death notice's spread leaned on anti-entropy instead of
        the infection-style gossip path;
      - ``dissemination_hops_p99``: p99 over all (subject, transition)
        groups of (observer's first-informed round − the group's
        earliest first-informed round) — the relay depth of the
        epidemic, measured in rounds behind the first carrier.
    """
    out: dict = {"channel_mix": channel_mix(rows)}
    removed = [r for r in rows if r.get("transition") == "REMOVED"]
    out["removal_via_sync_fraction"] = (
        sum(1 for r in removed if r["channel"] == "sync") / len(removed)
        if removed else None)
    first: Dict[tuple, int] = {}
    for r in rows:
        k = (int(r["subject"]), r["transition"])
        rnd = int(r["round"])
        if k not in first or rnd < first[k]:
            first[k] = rnd
    lags = [int(r["round"]) - first[(int(r["subject"]), r["transition"])]
            for r in rows]
    out["dissemination_hops_p99"] = _percentile(lags, 0.99)
    return out


def blame_report(rows: Sequence[dict], subject: int) -> dict:
    """Who planted the belief that ``subject`` failed, and how it spread.

    Mines the attribution stream for the subject's suspicion funnel
    (SUSPECTED/REMOVED):

      - ``origin_observer``/``origin_round``/``origin_channel``: the
        EARLIEST first-hand sighting (fd_direct / pingreq_proxy — the
        observer whose own failure detector started the rumor; for a
        false positive under an asymmetric faulty link this names the
        observer on the broken side);
      - ``first_carrier_channel``: the channel of the earliest sighting
        at any OTHER observer — how the rumor first left the origin;
      - ``refuted``: whether the subject's suspicion was later refuted
        (an ALIVE_REFUTED/ADDED row for the subject, or the subject's
        own self-refutation) — True is the false-positive signature;
      - ``observers_informed``/``onset_round``/``last_round``: spread
        extent.

    ``verdict`` is "no_suspicion_recorded" when the stream holds no
    suspicion rows for the subject (nothing to blame).
    """
    sight = sorted(
        (r for r in rows
         if int(r.get("subject", -1)) == subject
         and r.get("transition") in SUSPICION_TRANSITIONS),
        key=lambda r: int(r["round"]))
    if not sight:
        return {"subject": subject, "verdict": "no_suspicion_recorded"}
    onset = sight[0]
    first_hand = [r for r in sight
                  if r["channel"] in FIRST_HAND_CHANNELS]
    origin = first_hand[0] if first_hand else onset
    carriers = [r for r in sight
                if int(r["observer"]) != int(origin["observer"])]
    refuted = any(
        int(r.get("subject", -1)) == subject
        and r.get("transition") in ("ALIVE_REFUTED", "ADDED")
        and int(r["round"]) >= int(onset["round"])
        for r in rows)
    return {
        "subject": subject,
        "verdict": "refuted_false_positive" if refuted else "suspected",
        "onset_round": int(onset["round"]),
        "origin_observer": int(origin["observer"]),
        "origin_round": int(origin["round"]),
        "origin_channel": origin["channel"],
        "origin_first_hand": bool(first_hand),
        "first_carrier_channel": (carriers[0]["channel"] if carriers
                                  else None),
        "observers_informed": len({int(r["observer"]) for r in sight}),
        "last_round": int(sight[-1]["round"]),
        "refuted": refuted,
    }


def explain_belief(rows: Sequence[dict], observer: int, subject: int,
                   round_idx: Optional[int] = None) -> dict:
    """Answer "why did ``observer`` believe this about ``subject``"
    from the attribution stream alone — the ``telemetry explain``
    subcommand's engine.

    Returns every recorded (observer, subject) attribution in round
    order plus ``answer``: the row in force at ``round_idx`` (the last
    transition at or before it; the latest transition when ``round_idx``
    is None).  ``context`` carries the subject's blame report and this
    observer's infection path, so one query shows the full chain:
    what the observer believed, via which channel, and who started it.
    """
    events = sorted(
        (r for r in rows
         if int(r.get("observer", -1)) == observer
         and int(r.get("subject", -1)) == subject),
        key=lambda r: int(r["round"]))
    answer = None
    if round_idx is None:
        answer = events[-1] if events else None
    else:
        at_or_before = [r for r in events
                        if int(r["round"]) <= round_idx]
        answer = at_or_before[-1] if at_or_before else None
    return {
        "observer": observer,
        "subject": subject,
        "round": round_idx,
        "events": events,
        "answer": answer,
        "context": {
            "blame": blame_report(rows, subject),
            "infection_path": infection_paths(rows, subject).get(observer),
        },
    }


# --------------------------------------------------------------------------
# diff
# --------------------------------------------------------------------------


def diff_reports(a: HealthReport, b: HealthReport) -> List[dict]:
    """Per-SLO and per-counter comparison rows for two runs.

    Each row: {"metric", "a", "b", "delta", "rel"} (rel None when a is
    0/None).  Ordering: SLOs first, then counters, then gauges — the
    stable rendering contract the CLI table prints.
    """
    rows: List[dict] = []

    def add(name, va, vb):
        delta = (vb - va) if (va is not None and vb is not None) else None
        rel = (delta / va) if (delta is not None and va) else None
        rows.append({"metric": name, "a": va, "b": vb, "delta": delta,
                     "rel": rel})

    sa, sb = compute_slos(a), compute_slos(b)
    for name in sa:
        add(f"slo/{name}", sa[name], sb.get(name))
    for name in sorted(set(a.counters) | set(b.counters)):
        add(f"counter/{name}", a.counters.get(name), b.counters.get(name))
    for name in sorted(set(a.gauges) | set(b.gauges)):
        add(f"gauge/{name}", a.gauges.get(name), b.gauges.get(name))
    return rows


def format_table(rows: List[dict], headers: Sequence[str]) -> str:
    """Fixed-width text table (no dependencies; right-aligned numbers)."""
    def fmt(v):
        if v is None:
            return "-"
        if isinstance(v, float):
            return f"{v:.6g}"
        return str(v)

    table = [[fmt(r.get(h)) for h in headers] for r in rows]
    widths = [max(len(h), *(len(row[i]) for row in table)) if table
              else len(h) for i, h in enumerate(headers)]
    out = ["  ".join(h.ljust(widths[i]) for i, h in enumerate(headers))]
    out.append("  ".join("-" * w for w in widths))
    for row in table:
        out.append("  ".join(row[i].rjust(widths[i]) if i else
                             row[i].ljust(widths[i])
                             for i in range(len(headers))))
    return "\n".join(out)


# --------------------------------------------------------------------------
# regress: the BENCH_*.json trajectory gate
# --------------------------------------------------------------------------


def load_bench_payload(path: str) -> Tuple[Optional[dict], Optional[str]]:
    """One BENCH/MULTICHIP artifact's measurement payload as
    ``(payload, skip_note)``.

    ``payload`` is None — with the reason in ``skip_note`` — when the
    round recorded a failure (rc != 0 / parsed null) or is a legacy
    stub with no measurement fields (the MULTICHIP_r01..r05
    ``{"rc":0,"ok":true}`` era): both are kept in the committed
    trajectory as provenance and skipped, never failed."""
    with open(path) as f:
        doc = json.load(f)
    if "parsed" in doc or "rc" in doc:
        if doc.get("rc") not in (0, None):
            return None, "failed run (skipped)"
        payload = doc.get("parsed")
        stub_note = "legacy stub round — no measurement payload (skipped)"
    else:
        payload = doc
        stub_note = "no measurement fields (skipped)"
    if not isinstance(payload, dict) or payload.get("value") is None:
        if not (isinstance(payload, dict)
                and ("traced_overhead_ratio" in payload
                     or "metrics_overhead_ratio" in payload
                     or "pipelined_speedup_ratio" in payload
                     or "sync_rounds_to_converge" in payload
                     or "metadata_convergence_p99" in payload
                     or "fp_ratio" in payload
                     or "no_resurrection_violations" in payload
                     or "vmap_speedup_ratio" in payload
                     or "fused_serial_speedup_ratio" in payload
                     or "compose_speedup_ratio" in payload
                     or "findings_total" in payload
                     or "alarm_detection_lag_windows" in payload
                     or "batch_speedup_ratio" in payload
                     or "rounds_survived" in payload
                     or "blame_origin_correct" in payload)):
            return None, stub_note
    return payload, None


def regress(paths: Sequence[str],
            band: float = DEFAULT_NOISE_BAND) -> Tuple[bool, List[dict]]:
    """Walk a BENCH_*.json / MULTICHIP_*.json trajectory (sorted by
    filename = round order); the LATEST measurement of each tracked
    metric must not regress beyond the noise band against the best
    prior value.  Artifacts group into series by their ``metric``
    field, so the single-chip and multichip per-chip trajectories gate
    independently in one walk.

    Checks:
      - throughput (``value`` of each headline metric — including the
        multichip per-chip rate): latest must be >= best_prior *
        (1 - band).  Rounds marked ``"smoke": true`` are excluded from
        this comparison (recorded as skipped rows): a smoke window's
        absolute rate depends on whatever host/load ran it, so only
        real bench rounds form the throughput trajectory — smoke
        rounds still contribute their machine-independent ratio
        checks below;
      - ``dissemination_rounds``: latest must be <= best_prior *
        (1 + band) + 1 quantization round;
      - overhead ratios (``traced_overhead_ratio``,
        ``metrics_overhead_ratio``): latest must be <= 1 + band
        (absolute — 1.0 means the observability plane is free);
      - ``pipelined_speedup_ratio`` (multichip pipelined/serial rate):
        latest must be >= 1 - band — the delivery pipeline must never
        cost throughput;
      - SYNC heal artifacts (``sync_rounds_to_converge`` present):
        the latest must have ``converged`` true with
        ``post_heal_divergence`` 0 (and the gossip-only control still
        diverging, when recorded) — absolute gates — and the
        convergence-time series stays <= best_prior * (1 + band) + 1
        quantization round;
      - Config-rollout artifacts (``rollout_converged`` present,
        bench.py --rollout): absolute gates — the staged rollout
        converged every stage within its deadline with no rollback,
        the gossip-only control (metadata on, SYNC off) still
        divergent, ``metadata_convergence_p99`` within the scenario's
        convergence bound, and zero monitor violations — plus the
        banded non-smoke p99 series (smoke rows under the sync-heal
        fallback rule);
      - Lifeguard A/B artifacts (``fp_ratio`` +
        ``detection_p99_delta_rounds`` present, bench.py --lifeguard):
        absolute gates — ``fp_ratio`` (plane-on FP observer rate over
        its own control) <= 0.5 and the crash-detection latency P99
        delta <= +1 round;
      - Fuzz-campaign artifacts (``vmap_speedup_ratio`` + ``coverage``
        present, bench.py --fuzz): absolute gates — the healthy
        mega-campaign green, the weakened coverage arm found > 0
        planted violations with the healthy arm at 0 on the same
        slice, and (full rounds only) ``vmap_speedup_ratio`` >= 1 —
        plus the banded non-smoke ``scenario_throughput`` series;
      - Composed-runner artifacts (``compose_speedup_ratio`` present,
        bench.py --compose): absolute gates — the full instrumented
        stack's one-scan route at least matches the alias-by-alias
        route (ratio >= 1.0), its overhead vs bare stays within the
        band of head-style's, the compile-count arm is strictly
        reduced, and the alias parity probe was green;
      - swimlint artifacts (``findings_total`` present,
        ``python -m scalecube_cluster_tpu.analysis check``): absolute
        gates — ``findings_total`` == 0 (unsuppressed static-analysis
        findings are never noise) and the artifact self-reports ok;
      - Alarm-drill artifacts (``alarm_detection_lag_windows`` present,
        bench.py --alarms): absolute gates — the breach arm's planted
        SLO breach fired (>= 1 firing transition) within one metrics
        window of onset, resolved after the heal, and the healthy arm
        fired ZERO alarms;
      - Autotuner artifacts (``batch_speedup_ratio`` + ``profiles``
        present, bench.py --tune): absolute gates — the traced-knob
        grid sweep (one compile per shape bucket) at least matches the
        static recompile-per-config sweep (ratio >= 1.0), >= 2
        named tuned profiles shipped, each Pareto-non-dominated by the
        reference default over the recorded objectives (dominance
        recomputed from the payload) and fuzz-oracle green on
        held-out seeds;
      - Soak artifacts (``rounds_survived`` + ``drift`` present,
        bench.py --soak): absolute gates — zero monitor violations
        across the whole lifetime, the compose program's compile cache
        FLAT after segment 1 (runtime recompile drift), host RSS
        bounded, the seeded mid-soak SIGKILL/relaunch drill
        byte-identical to the uninterrupted run (journal AND state
        digest), and the live alarm engine quiet.  Smoke soaks are
        provenance unless the walk holds only smoke rounds (the
        sync-heal fallback rule);
      - Blame-drill artifacts (``blame_origin_correct`` present,
        bench.py --blame): ABSOLUTE gates — the blame report named the
        planted faulty-link origin, every recorded transition carried
        exactly one channel (attribution fractions sum to 1.0 with
        zero provenance-buffer drops AND zero trace drops — the
        committed full-provenance artifact must be complete), the
        off-switch stayed bit-identical (states + metrics),
        ``provenance_overhead_ratio`` <= 1.10 (absolute — the plane
        must stay near-free next to the same composed stack without
        it), and the ``telemetry explain`` probe resolved its seeded
        (observer, subject) query with the correct channel and round.
        Smoke drills are provenance unless the walk holds only smoke
        rounds (the sync-heal fallback rule: `--blame --smoke`'s
        in-bench check of its own fresh artifact still bites).

    Returns (ok, check rows); each row {"check", "latest", "reference",
    "threshold", "ok", "source"}.  Unreadable/failed artifacts — and
    the legacy MULTICHIP stub rounds that carry no throughput fields —
    are reported as skipped rows (ok=None): provenance, not a
    regression.
    """
    rows: List[dict] = []
    series: Dict[str, List[Tuple[str, dict]]] = {}
    # Round order is carried by the FILENAME (BENCH_r01 < BENCH_r02...):
    # sort on basenames so an artifact passed by absolute path (the
    # bench gates the one it just wrote, often under a tmp dir) still
    # lands at its round position instead of wherever its directory
    # happens to sort — '/tmp/...' < 'MULTICHIP_r06.json' would have
    # made the stale committed round the "latest" one.
    for path in sorted(paths, key=lambda p: (os.path.basename(p), p)):
        try:
            payload, skip_note = load_bench_payload(path)
        except (OSError, json.JSONDecodeError) as e:
            rows.append({"check": "load", "source": os.path.basename(path),
                         "ok": None,
                         "note": f"unreadable: {type(e).__name__}: {e}"})
            continue
        if payload is None:
            rows.append({"check": "load", "source": os.path.basename(path),
                         "ok": None, "note": skip_note})
            continue
        metric = payload.get("metric", "unknown")
        series.setdefault(metric, []).append((path, payload))

    ok = True

    def check(name, source, latest, reference, threshold, passed):
        nonlocal ok
        ok = ok and passed
        rows.append({"check": name, "source": os.path.basename(source),
                     "latest": latest, "reference": reference,
                     "threshold": threshold, "ok": passed})

    for metric, entries in sorted(series.items()):
        values = [(p, pl["value"]) for p, pl in entries
                  if isinstance(pl.get("value"), (int, float))
                  and not pl.get("smoke")]
        for p, pl in entries:
            if isinstance(pl.get("value"), (int, float)) and pl.get("smoke"):
                rows.append({
                    "check": f"throughput/{metric}",
                    "source": os.path.basename(p), "ok": None,
                    "note": "smoke round — host-dependent rate, not a "
                            "trajectory datum (ratio checks still apply)",
                })
        if len(values) >= 2:
            *prior, (last_path, last) = values
            best = max(v for _, v in prior)
            check(f"throughput/{metric}", last_path, last,
                  best, best * (1.0 - band), last >= best * (1.0 - band))
        dis = [(p, pl["dissemination_rounds"]) for p, pl in entries
               if isinstance(pl.get("dissemination_rounds"), (int, float))
               and pl["dissemination_rounds"] > 0]
        if len(dis) >= 2:
            *prior, (last_path, last) = dis
            best = min(v for _, v in prior)
            limit = best * (1.0 + band) + DISSEMINATION_SLACK_ROUNDS
            check("slo/dissemination_rounds", last_path, last, best,
                  limit, last <= limit)
        for ratio_key in ("traced_overhead_ratio", "metrics_overhead_ratio"):
            ratios = [(p, pl[ratio_key]) for p, pl in entries
                      if isinstance(pl.get(ratio_key), (int, float))]
            if ratios:
                last_path, last = ratios[-1]
                limit = 1.0 + band
                check(f"slo/{ratio_key}", last_path, last, 1.0, limit,
                      last <= limit and math.isfinite(last))
        # The delivery pipeline's floor: pipelined must not run slower
        # than the serial combine beyond noise (ratio = pipelined/serial,
        # >= 1 means the overlap pays).
        speedups = [(p, pl["pipelined_speedup_ratio"]) for p, pl in entries
                    if isinstance(pl.get("pipelined_speedup_ratio"),
                                  (int, float))]
        if speedups:
            last_path, last = speedups[-1]
            floor = 1.0 - band
            check("slo/pipelined_speedup_ratio", last_path, last, 1.0,
                  floor, last >= floor and math.isfinite(last))
        # SYNC anti-entropy heal artifacts (bench.py --sync): the latest
        # round's headline claims gate ABSOLUTELY — the plane must have
        # converged with zero post-heal divergence while the gossip-only
        # control demonstrably did not — and the convergence-time series
        # gates within the band (smaller is better; +1 quantization
        # round, like dissemination).  Smoke heal artifacts are
        # provenance, not trajectory data (their tiny N converges on a
        # different scale), UNLESS the walk holds only smoke rounds —
        # then they gate themselves, so `--sync --smoke`'s in-bench
        # check of its own fresh artifact still bites.
        heals_all = [(p, pl) for p, pl in entries
                     if "sync_rounds_to_converge" in pl]
        heals = [(p, pl) for p, pl in heals_all
                 if not pl.get("smoke")] or heals_all
        if heals is not heals_all:
            for p, pl in heals_all:
                if pl.get("smoke"):
                    rows.append({
                        "check": "slo/sync_heal", "source":
                        os.path.basename(p), "ok": None,
                        "note": "smoke heal round — different scale, "
                                "not a trajectory datum",
                    })
        if heals:
            last_path, last = heals[-1]
            converged = bool(last.get("converged"))
            check("slo/sync_heal_converged", last_path, converged, True,
                  True, converged)
            phd = last.get("post_heal_divergence")
            check("slo/post_heal_divergence", last_path, phd, 0, 0,
                  phd == 0)
            if "gossip_only_converged" in last:
                check("slo/gossip_only_diverges", last_path,
                      last["gossip_only_converged"], False, False,
                      last["gossip_only_converged"] is False)
            # Absolute contract: convergence landed inside the
            # scenario's promised window.
            rounds_c = last.get("sync_rounds_to_converge")
            window = last.get("window_rounds")
            if isinstance(rounds_c, (int, float)) and isinstance(
                    window, (int, float)):
                check("slo/sync_converge_within_window", last_path,
                      rounds_c, window, window, rounds_c <= window)
        conv = [(p, pl) for p, pl in heals
                if isinstance(pl.get("sync_rounds_to_converge"),
                              (int, float))]
        if len(conv) >= 2:
            *prior, (last_path, last) = conv
            best = min(pl["sync_rounds_to_converge"] for _, pl in prior)
            # Floor the reference at one exchange interval: where the
            # heal round lands relative to the exchange cadence is phase
            # luck, so a prior run converging on the very first probe
            # must not turn the band into a knife edge.
            floor = last.get("sync_interval") or 0
            limit = (max(best, floor) * (1.0 + band)
                     + DISSEMINATION_SLACK_ROUNDS)
            check("slo/sync_rounds_to_converge", last_path,
                  last["sync_rounds_to_converge"], best, limit,
                  last["sync_rounds_to_converge"] <= limit)
        # Config-rollout artifacts (bench.py --rollout): the staged
        # rollout's headline claims gate ABSOLUTELY — every stage
        # converged within its deadline with no rollback, the
        # gossip-only control (metadata on, SYNC off) demonstrably did
        # NOT converge through the partition, the per-push convergence
        # p99 landed inside the scenario's promised bound
        # (chaos/scenarios.metadata_convergence_bound, recorded in the
        # payload), and the monitored composite ran violation-free.
        # Smoke rollout artifacts are provenance unless the walk holds
        # only smoke rounds (the sync-heal fallback rule: `--rollout
        # --smoke`'s in-bench check of its own fresh artifact bites).
        ro_all = [(p, pl) for p, pl in entries
                  if "rollout_converged" in pl]
        ro = [(p, pl) for p, pl in ro_all
              if not pl.get("smoke")] or ro_all
        if ro is not ro_all:
            for p, pl in ro_all:
                if pl.get("smoke"):
                    rows.append({
                        "check": "slo/config_rollout", "source":
                        os.path.basename(p), "ok": None,
                        "note": "smoke rollout round — different scale, "
                                "not a trajectory datum",
                    })
        if ro:
            last_path, last = ro[-1]
            converged = bool(last.get("rollout_converged"))
            check("slo/rollout_converged", last_path, converged, True,
                  True, converged)
            rb = last.get("rolled_back")
            check("slo/rollout_not_rolled_back", last_path, rb, False,
                  False, rb is False)
            if "control_converged" in last:
                check("slo/rollout_control_diverges", last_path,
                      last["control_converged"], False, False,
                      last["control_converged"] is False)
            p99 = last.get("metadata_convergence_p99")
            bound = last.get("convergence_deadline_rounds")
            if isinstance(p99, (int, float)) and isinstance(
                    bound, (int, float)):
                check("slo/metadata_convergence_p99_within_bound",
                      last_path, p99, bound, bound, p99 <= bound)
            mv = last.get("monitor_violations")
            check("slo/rollout_monitor_violations", last_path, mv, 0, 0,
                  mv == 0)
        ro_conv = [(p, pl) for p, pl in ro
                   if isinstance(pl.get("metadata_convergence_p99"),
                                 (int, float))]
        if len(ro_conv) >= 2:
            *prior, (last_path, last) = ro_conv
            best = min(pl["metadata_convergence_p99"] for _, pl in prior)
            # Same phase-luck floor as the sync series: one exchange
            # interval.
            floor = last.get("sync_interval") or 0
            limit = (max(best, floor) * (1.0 + band)
                     + DISSEMINATION_SLACK_ROUNDS)
            check("slo/metadata_convergence_p99", last_path,
                  last["metadata_convergence_p99"], best, limit,
                  last["metadata_convergence_p99"] <= limit)
        # Lifeguard A/B artifacts (bench.py --lifeguard): the headline
        # adaptivity claims gate ABSOLUTELY — the plane must at least
        # halve the false-positive observer rate of its own control
        # while keeping crash-detection latency P99 within one round —
        # so the committed win cannot silently rot.  Smoke artifacts
        # are provenance unless the walk holds only smoke rounds (the
        # sync-heal rule: `--lifeguard --smoke`'s in-bench check of its
        # own fresh artifact still bites).
        lg_all = [(p, pl) for p, pl in entries
                  if "fp_ratio" in pl
                  and "detection_p99_delta_rounds" in pl]
        lg = [(p, pl) for p, pl in lg_all
              if not pl.get("smoke")] or lg_all
        if lg is not lg_all:
            for p, pl in lg_all:
                if pl.get("smoke"):
                    rows.append({
                        "check": "slo/lifeguard_fp", "source":
                        os.path.basename(p), "ok": None,
                        "note": "smoke lifeguard round — different "
                                "scale, not a trajectory datum",
                    })
        if lg:
            last_path, last = lg[-1]
            ratio = last.get("fp_ratio")
            if not isinstance(ratio, (int, float)):
                # bench.py records fp_ratio: null when the CONTROL arm
                # produced zero false-suspicion onsets — there was
                # nothing to improve, so the run demonstrates neither a
                # win nor a rot: provenance, not a regression.
                rows.append({
                    "check": "slo/lifeguard_fp", "source":
                    os.path.basename(last_path), "ok": None,
                    "note": "no FP signal (control recorded zero "
                            "onsets) — nothing to gate",
                })
            else:
                check("slo/lifeguard_fp_improvement", last_path, ratio,
                      0.5, 0.5, math.isfinite(ratio) and ratio <= 0.5)
                delta = last.get("detection_p99_delta_rounds")
                check("slo/lifeguard_detection_parity", last_path,
                      delta, 0.0, DISSEMINATION_SLACK_ROUNDS,
                      isinstance(delta, (int, float))
                      and math.isfinite(delta)
                      and delta <= DISSEMINATION_SLACK_ROUNDS)
        # Open-world churn A/B artifacts (bench.py --churn): ABSOLUTE
        # gates — the epoch guard must hold ZERO resurrection and
        # join-completeness violations with join propagation inside the
        # scenario's dissemination bound, the storm must actually GROW
        # the cluster, and the naive control arm must DEMONSTRATE the
        # resurrection failure (a control that stops failing means the
        # A/B stopped measuring the hazard).  Smoke artifacts are
        # provenance unless the walk holds only smoke rounds (the
        # sync-heal rule).
        ch_all = [(p, pl) for p, pl in entries
                  if "no_resurrection_violations" in pl
                  and "join_propagation_p99_rounds" in pl]
        ch = [(p, pl) for p, pl in ch_all
              if not pl.get("smoke")] or ch_all
        if ch is not ch_all:
            for p, pl in ch_all:
                if pl.get("smoke"):
                    rows.append({
                        "check": "slo/churn_growth", "source":
                        os.path.basename(p), "ok": None,
                        "note": "smoke churn round — different scale, "
                                "not a trajectory datum",
                    })
        if ch:
            last_path, last = ch[-1]
            check("slo/churn_no_resurrection", last_path,
                  last.get("no_resurrection_violations"), 0, 0,
                  last.get("no_resurrection_violations") == 0)
            check("slo/churn_join_completeness", last_path,
                  last.get("join_completeness_violations"), 0, 0,
                  last.get("join_completeness_violations") == 0)
            naive = last.get("naive_no_resurrection_violations")
            check("slo/churn_naive_demonstrates_failure", last_path,
                  naive, "> 0", 1,
                  isinstance(naive, (int, float)) and naive > 0)
            p99 = last.get("join_propagation_p99_rounds")
            jbound = last.get("join_propagation_bound_rounds")
            if isinstance(p99, (int, float)) and isinstance(
                    jbound, (int, float)):
                check("slo/churn_join_propagation_within_bound",
                      last_path, p99, jbound, jbound, p99 <= jbound)
            else:
                rows.append({
                    "check": "slo/churn_join_propagation_within_bound",
                    "source": os.path.basename(last_path), "ok": None,
                    "note": "no join-propagation samples recorded — "
                            "nothing to gate",
                })
            growth = last.get("net_growth_members")
            check("slo/churn_net_positive_growth", last_path, growth,
                  "> 0", 1,
                  isinstance(growth, (int, float)) and growth > 0)
        # Vmapped fuzz-campaign artifacts (bench.py --fuzz): the chaos
        # mega-fuzzer's speed AND quality gates.  ABSOLUTE — the
        # healthy mega-campaign is green, the deliberately-weakened
        # coverage arm FOUND its planted violations (> 0) while the
        # healthy arm found none on the same slice, and (full rounds
        # only) the vmapped batch beats the sequential dispatch loop:
        # ``vmap_speedup_ratio`` >= 1.  The speedup floor skips smoke
        # rounds as provenance — a mini smoke batch is mostly singleton
        # buckets, where there is no batch axis to amortize dispatch
        # over, so its ratio hovers at ~1 by construction and gating it
        # would be a coin flip; the quality gates keep the sync-heal
        # fallback rule (smoke rounds gate themselves when the walk
        # holds nothing else).  BANDED (non-smoke rounds only —
        # scenarios/sec is host-dependent, the throughput rule): the
        # ``scenario_throughput`` series is smaller-is-worse and must
        # not shrink beyond the noise band.
        fz_all = [(p, pl) for p, pl in entries
                  if "vmap_speedup_ratio" in pl and "coverage" in pl]
        fz = [(p, pl) for p, pl in fz_all
              if not pl.get("smoke")] or fz_all
        if fz is not fz_all:
            for p, pl in fz_all:
                if pl.get("smoke"):
                    rows.append({
                        "check": "slo/fuzz_campaign", "source":
                        os.path.basename(p), "ok": None,
                        "note": "smoke fuzz round — different scale, "
                                "not a trajectory datum (quality gates "
                                "still apply when nothing else walks)",
                    })
        if fz:
            last_path, last = fz[-1]
            speedup = last.get("vmap_speedup_ratio")
            if last.get("smoke"):
                rows.append({
                    "check": "slo/fuzz_vmap_speedup",
                    "source": os.path.basename(last_path), "ok": None,
                    "note": "smoke round — singleton-bucket mini "
                            "batches have no batch axis to amortize "
                            "dispatch over; the floor gates full "
                            "rounds",
                })
            else:
                check("slo/fuzz_vmap_speedup", last_path, speedup, 1.0,
                      1.0,
                      isinstance(speedup, (int, float))
                      and math.isfinite(speedup) and speedup >= 1.0)
            check("slo/fuzz_campaign_green", last_path,
                  last.get("green"), True, True,
                  last.get("green") is True)
            cov = last.get("coverage") or {}
            planted = cov.get("weakened_violations")
            check("slo/fuzz_coverage_finds_planted", last_path, planted,
                  "> 0", 1,
                  isinstance(planted, (int, float)) and planted > 0)
            healthy = cov.get("healthy_violations")
            check("slo/fuzz_coverage_healthy_clean", last_path, healthy,
                  0, 0, healthy == 0)
        st = [(p, pl["scenario_throughput"]) for p, pl in fz_all
              if isinstance(pl.get("scenario_throughput"), (int, float))
              and not pl.get("smoke")]
        if len(st) >= 2:
            *prior, (last_path, last) = st
            best = max(v for _, v in prior)
            check("slo/fuzz_scenario_throughput", last_path, last, best,
                  best * (1.0 - band), last >= best * (1.0 - band))
        # Fused-wire artifacts (bench.py --wire): the single-buffer
        # scatter wire's committed win.  ABSOLUTE gates on the latest
        # round — fused throughput >= the two-buffer HEAD path on BOTH
        # the serial and pipelined runs (ratio >= 1.0, a floor: the
        # collective halving must never cost throughput), the modeled
        # bytes/slot and collectives/round pinned exactly (4-vs-5 B,
        # 1-vs-2 combines — arithmetic, not host-dependent), the HLO
        # instruction counts matching the model when the text parse
        # was available (null = provenance), and shift-mode accounting
        # untouched.  Smoke artifacts are provenance unless the walk
        # holds only smoke rounds (the sync-heal rule: `--wire
        # --smoke`'s in-bench check of its own fresh artifact still
        # bites).
        wr_all = [(p, pl) for p, pl in entries
                  if "fused_serial_speedup_ratio" in pl
                  and "fused_pipelined_speedup_ratio" in pl]
        wr = [(p, pl) for p, pl in wr_all
              if not pl.get("smoke")] or wr_all
        if wr is not wr_all:
            for p, pl in wr_all:
                if pl.get("smoke"):
                    rows.append({
                        "check": "slo/wire_fused", "source":
                        os.path.basename(p), "ok": None,
                        "note": "smoke wire round — host-dependent "
                                "rates, not a trajectory datum",
                    })
        if wr:
            last_path, last = wr[-1]
            for ratio_key in ("fused_serial_speedup_ratio",
                              "fused_pipelined_speedup_ratio"):
                ratio = last.get(ratio_key)
                check(f"slo/{ratio_key}", last_path, ratio, 1.0, 1.0,
                      isinstance(ratio, (int, float))
                      and math.isfinite(ratio) and ratio >= 1.0)
            bps = last.get("wire_bytes_per_slot") or {}
            check("slo/wire_fused_bytes_per_slot", last_path,
                  bps.get("fused"), 4, 4, bps.get("fused") == 4)
            check("slo/wire_legacy_bytes_per_slot", last_path,
                  bps.get("legacy"), 5, 5, bps.get("legacy") == 5)
            cpr = last.get("wire_collectives_per_round") or {}
            check("slo/wire_fused_collectives_per_round", last_path,
                  cpr.get("fused"), 1, 1, cpr.get("fused") == 1)
            check("slo/wire_legacy_collectives_per_round", last_path,
                  cpr.get("legacy"), 2, 2, cpr.get("legacy") == 2)
            hlo = last.get("hlo_full_height_collectives")
            if isinstance(hlo, dict):
                check("slo/wire_hlo_fused_single_collective", last_path,
                      hlo.get("fused"), 1, 1, hlo.get("fused") == 1)
                check("slo/wire_hlo_legacy_collective_pair", last_path,
                      hlo.get("legacy"), 2, 2, hlo.get("legacy") == 2)
            else:
                rows.append({
                    "check": "slo/wire_hlo_fused_single_collective",
                    "source": os.path.basename(last_path), "ok": None,
                    "note": "no compiled-HLO collective count recorded "
                            "— nothing to gate",
                })
            check("slo/wire_shift_accounting_unchanged", last_path,
                  last.get("shift_accounting_unchanged"), True, True,
                  last.get("shift_accounting_unchanged") is True)
            parity = last.get("pipelined_serial_parity") or {}
            check("slo/wire_pipelined_serial_parity", last_path,
                  parity, True, True,
                  parity.get("fused") is True
                  and parity.get("legacy") is True)
        # Composed-runner artifacts (bench.py --compose): the full
        # instrumented stack through ONE scan must never lose to the
        # pre-compose alias-by-alias route.  ABSOLUTE gates on the
        # latest round — ``compose_speedup_ratio`` (head-style seconds
        # over composed seconds) >= 1.0 floor, the composed stack's
        # instrumentation overhead no worse than head-style's beyond
        # the band (both ratios share one host window, so the
        # comparison is machine-independent), and the compile-count
        # arm STRICTLY reduced (programs_composed < programs_head_
        # style — one program per layout where the aliases pay three).
        # The ratio gates apply to smoke rounds too (interleaved
        # same-host ratios, the metrics_overhead_ratio convention);
        # only the absolute rates are host-dependent provenance.
        cp = [(p, pl) for p, pl in entries
              if "compose_speedup_ratio" in pl]
        if cp:
            last_path, last = cp[-1]
            ratio = last.get("compose_speedup_ratio")
            check("slo/compose_speedup_ratio", last_path, ratio, 1.0,
                  1.0, isinstance(ratio, (int, float))
                  and math.isfinite(ratio) and ratio >= 1.0)
            fso = last.get("full_stack_overhead_ratio")
            hso = last.get("head_style_overhead_ratio")
            limit = (hso * (1.0 + band)
                     if isinstance(hso, (int, float)) else None)
            check("slo/compose_full_stack_overhead", last_path, fso,
                  hso, limit,
                  isinstance(fso, (int, float))
                  and isinstance(hso, (int, float))
                  and math.isfinite(fso) and fso <= limit)
            comp = last.get("compile") or {}
            ph = comp.get("programs_head_style")
            pc = comp.get("programs_composed")
            check("slo/compose_compile_count_reduced", last_path, pc,
                  ph, "strictly fewer",
                  isinstance(ph, (int, float))
                  and isinstance(pc, (int, float)) and 0 < pc < ph)
            par = last.get("parity") or {}
            check("slo/compose_alias_parity", last_path, par, True,
                  True, bool(par) and all(v is True
                                          for v in par.values()))
        # swimlint artifacts (python -m scalecube_cluster_tpu.analysis
        # check): ABSOLUTE — the committed static-analysis round must
        # be finding-free and self-reported ok.  findings_total counts
        # UNSUPPRESSED findings only (baselined asymmetries don't gate:
        # they carry a committed justification), so findings > 0 means
        # either a plane stopped reaching a run shape or a compile
        # audit went red — never noise, always a gate.
        sa = [(p, pl) for p, pl in entries
              if "findings_total" in pl]
        if sa:
            last_path, last = sa[-1]
            total = last.get("findings_total")
            check("slo/static_analysis_clean", last_path, total, 0, 0,
                  total == 0)
            check("slo/static_analysis_ok", last_path,
                  last.get("ok"), True, True, last.get("ok") is True)
        # Alarm-drill artifacts (bench.py --alarms): the live SLO alarm
        # engine's measured detection claim.  ABSOLUTE gates on the
        # latest round — the weakened-knobs breach arm FIRED (>= 1
        # firing transition) with ``alarm_detection_lag_windows`` <= 1
        # (the breach is caught within one metrics window of onset),
        # the alarm RESOLVED after the fault healed, and the healthy
        # arm — same world, same compiled program — fired ZERO alarms.
        # Smoke drills are provenance unless the walk holds only smoke
        # rounds (the sync-heal fallback rule: `--alarms --smoke`'s
        # in-bench check of its own fresh artifact still bites).
        al_all = [(p, pl) for p, pl in entries
                  if "alarm_detection_lag_windows" in pl
                  and "healthy_transitions" in pl]
        al = [(p, pl) for p, pl in al_all
              if not pl.get("smoke")] or al_all
        if al is not al_all:
            for p, pl in al_all:
                if pl.get("smoke"):
                    rows.append({
                        "check": "slo/alarm_drill", "source":
                        os.path.basename(p), "ok": None,
                        "note": "smoke alarm drill — different scale, "
                                "not a trajectory datum",
                    })
        if al:
            last_path, last = al[-1]
            fired = last.get("breach_fired")
            check("slo/alarm_breach_fired", last_path, fired, ">= 1",
                  1, isinstance(fired, (int, float)) and fired >= 1)
            lag = last.get("alarm_detection_lag_windows")
            check("slo/alarm_detection_lag", last_path, lag, 1.0, 1.0,
                  isinstance(lag, (int, float)) and math.isfinite(lag)
                  and lag <= 1.0)
            check("slo/alarm_resolved_after_heal", last_path,
                  last.get("breach_resolved"), True, True,
                  last.get("breach_resolved") is True)
            quiet = last.get("healthy_transitions")
            check("slo/alarm_healthy_quiet", last_path, quiet, 0, 0,
                  quiet == 0)
        # Autotuner artifacts (bench.py --tune): ABSOLUTE gates on the
        # latest round — the traced-knob grid sweep at least matches
        # the static recompile-per-config counterfactual
        # (``batch_speedup_ratio`` >= 1.0), at least
        # two named tuned profiles shipped, every profile
        # Pareto-non-dominated by the reference default over the
        # recorded objectives (dominance RECOMPUTED here from the
        # payload's SLO rows, not trusted from the writer's flag) and
        # fuzz-oracle green on its held-out seeds.  Smoke sweeps are
        # provenance unless the walk holds only smoke rounds (the
        # sync-heal fallback rule: `--tune --smoke`'s in-bench check
        # of its own fresh artifact still bites).
        tn_all = [(p, pl) for p, pl in entries
                  if "batch_speedup_ratio" in pl and "profiles" in pl]
        tn = [(p, pl) for p, pl in tn_all
              if not pl.get("smoke")] or tn_all
        if tn is not tn_all:
            for p, pl in tn_all:
                if pl.get("smoke"):
                    rows.append({
                        "check": "slo/tune_pareto", "source":
                        os.path.basename(p), "ok": None,
                        "note": "smoke tune sweep — different scale, "
                                "not a trajectory datum",
                    })
        if tn:
            last_path, last = tn[-1]
            ratio = last.get("batch_speedup_ratio")
            check("slo/tune_batch_speedup", last_path, ratio, 1.0, 1.0,
                  isinstance(ratio, (int, float)) and ratio >= 1.0)
            profs = last.get("profiles") or {}
            check("slo/tune_profiles_shipped", last_path,
                  sorted(profs), ">= 2 named profiles", 2,
                  len(profs) >= 2)
            objs = last.get("objectives") or []
            ref = last.get("reference_slos") or {}
            nondom = {}
            for name, prof in sorted(profs.items()):
                slos = prof.get("slos") or {}
                complete = bool(objs) and all(
                    isinstance(ref.get(o), (int, float))
                    and isinstance(slos.get(o), (int, float))
                    for o in objs)
                ref_dominates = complete and all(
                    ref[o] <= slos[o] for o in objs) and any(
                    ref[o] < slos[o] for o in objs)
                nondom[name] = complete and not ref_dominates
            check("slo/tune_profiles_nondominated", last_path, nondom,
                  True, True, bool(nondom) and all(nondom.values()))
            fuzz = {name: prof.get("fuzz_green")
                    for name, prof in sorted(profs.items())}
            check("slo/tune_profiles_fuzz_green", last_path, fuzz,
                  True, True,
                  bool(fuzz) and all(v is True for v in fuzz.values()))
        # Soak artifacts (bench.py --soak): the production soak's drift
        # invariants.  ABSOLUTE gates on the latest round — every one
        # of these is a "never" claim, not a trajectory: a single
        # monitor violation, one recompile after segment 1, or one
        # byte of journal divergence under SIGKILL is a regression at
        # any scale.  Smoke soaks are provenance unless the walk holds
        # only smoke rounds (the sync-heal fallback rule: `--soak
        # --smoke`'s in-bench check of its own fresh artifact still
        # bites).
        sk_all = [(p, pl) for p, pl in entries
                  if "rounds_survived" in pl and "drift" in pl]
        sk = [(p, pl) for p, pl in sk_all
              if not pl.get("smoke")] or sk_all
        if sk is not sk_all:
            for p, pl in sk_all:
                if pl.get("smoke"):
                    rows.append({
                        "check": "slo/soak", "source":
                        os.path.basename(p), "ok": None,
                        "note": "smoke soak — different scale, "
                                "not a trajectory datum",
                    })
        if sk:
            last_path, last = sk[-1]
            drift = last.get("drift") or {}
            viol = drift.get("violations")
            check("slo/soak_violations", last_path, viol, 0, 0,
                  viol == 0)
            sizes = drift.get("cache_sizes")
            check("slo/soak_compile_flat", last_path, sizes,
                  "one program, every segment", True,
                  drift.get("compile_flat") is True
                  and isinstance(sizes, list) and len(sizes) >= 1)
            check("slo/soak_rss_bounded", last_path,
                  drift.get("rss_growth_mb"),
                  "bounded growth", True,
                  drift.get("rss_bounded") is True)
            drill = last.get("kill_drill") or {}
            check("slo/soak_kill_exactly_once", last_path,
                  {k: drill.get(k) for k in
                   ("ok", "journal_match", "state_match")},
                  True, True,
                  drill.get("ok") is True
                  and drill.get("journal_match") is True
                  and drill.get("state_match") is True)
            alarms = last.get("alarms") or {}
            check("slo/soak_alarms_quiet", last_path,
                  alarms.get("transitions"), 0, 0,
                  alarms.get("quiet") is True
                  and alarms.get("transitions") == 0)
        # Blame-drill artifacts (bench.py --blame): the provenance
        # plane's measured attribution claims, gated ABSOLUTELY on the
        # latest round (docstring bullet).  Smoke drills are provenance
        # unless the walk holds only smoke rounds (the sync-heal
        # fallback rule).
        bl_all = [(p, pl) for p, pl in entries
                  if "blame_origin_correct" in pl]
        bl = [(p, pl) for p, pl in bl_all
              if not pl.get("smoke")] or bl_all
        if bl is not bl_all:
            for p, pl in bl_all:
                if pl.get("smoke"):
                    rows.append({
                        "check": "slo/blame_drill", "source":
                        os.path.basename(p), "ok": None,
                        "note": "smoke blame drill — different scale, "
                                "not a trajectory datum",
                    })
        if bl:
            last_path, last = bl[-1]
            check("slo/blame_origin_correct", last_path,
                  last.get("blame_origin_correct"), True, True,
                  last.get("blame_origin_correct") is True)
            attr = last.get("attribution") or {}
            frac = attr.get("total_fraction")
            check("slo/provenance_attribution_total", last_path, frac,
                  1.0, 1.0,
                  isinstance(frac, (int, float))
                  and math.isfinite(frac) and abs(frac - 1.0) < 1e-9)
            check("slo/provenance_dropped", last_path,
                  attr.get("dropped"), 0, 0, attr.get("dropped") == 0)
            check("slo/trace_dropped_total", last_path,
                  last.get("trace_dropped_total"), 0, 0,
                  last.get("trace_dropped_total") == 0)
            check("slo/provenance_off_switch_identical", last_path,
                  last.get("off_switch_identical"), True, True,
                  last.get("off_switch_identical") is True)
            ratio = last.get("provenance_overhead_ratio")
            check("slo/provenance_overhead_ratio", last_path, ratio,
                  1.0, PROVENANCE_OVERHEAD_LIMIT,
                  isinstance(ratio, (int, float))
                  and math.isfinite(ratio)
                  and ratio <= PROVENANCE_OVERHEAD_LIMIT)
            ex = last.get("explain_check") or {}
            check("slo/provenance_explain_resolved", last_path,
                  {k: ex.get(k) for k in
                   ("resolved", "channel_correct", "round_correct")},
                  True, True,
                  ex.get("resolved") is True
                  and ex.get("channel_correct") is True
                  and ex.get("round_correct") is True)
    return ok, rows


def expand_paths(patterns: Sequence[str]) -> List[str]:
    """Globs + literal paths -> sorted unique file list."""
    out: List[str] = []
    for pat in patterns:
        matches = sorted(globlib.glob(pat))
        out.extend(matches if matches else [pat])
    seen, uniq = set(), []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq
