"""The telemetry CLI: ``python -m scalecube_cluster_tpu.telemetry``.

Five subcommands over the JSONL manifests and BENCH artifacts
(telemetry/query.py, telemetry/alarms.py):

  report   <manifest.jsonl> [...]   fold manifests, print the health
                                    SLO table (``--json`` for machines,
                                    ``--windows`` for the per-window
                                    time series)
  diff     <a.jsonl> <b.jsonl>      per-SLO/counter/gauge comparison
                                    of two runs
  watch    <journal.jsonl>          live-tail a journal another process
                                    is writing (sink.follow_records —
                                    never re-reads consumed bytes) and
                                    render a refreshing alarm/SLO
                                    table; exits when the run's
                                    ``summary`` record lands (or after
                                    ``--max-seconds``); ``--json``
                                    emits one line per consumed window
                                    / transition for machines
  explain  <journal.jsonl>          answer "why did observer i believe
           --observer i --subject j  this about subject j" from the
           [--round r]               journal's provenance records alone
                                    (telemetry/query.explain_belief):
                                    the belief in force, its winning
                                    channel + round, the subject's
                                    blame report and this observer's
                                    infection path
  regress  [paths/globs ...]        walk the BENCH_*.json +
                                    MULTICHIP_*.json trajectories
                                    (the default globs) and exit 1 on
                                    throughput or SLO regressions
                                    beyond ``--band``; legacy stub
                                    rounds skip as provenance

Exit codes: 0 ok, 1 regression detected (regress), 2 usage/input error
— stable for CI gating (tests/test_metrics_query.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional, Sequence

from scalecube_cluster_tpu.telemetry import query


def _cmd_report(args) -> int:
    reports = [query.load_report(p) for p in args.manifests]
    merged = (query.merge_reports(reports) if len(reports) > 1
              else reports[0])
    slos = query.compute_slos(merged)
    if args.json:
        print(json.dumps({
            "manifests": [r.path for r in reports],
            "slos": slos,
            "counters": merged.counters,
            "gauges": merged.gauges,
            "windows": merged.windows if args.windows else len(merged.windows),
        }))
        return 0
    rows = [{"metric": k, "value": v} for k, v in slos.items()]
    print(f"# health report: {', '.join(r.path for r in reports)}")
    print(query.format_table(rows, ["metric", "value"]))
    if merged.counters:
        print("\n# counters (summed over windows)")
        print(query.format_table(
            [{"metric": k, "value": v}
             for k, v in sorted(merged.counters.items())],
            ["metric", "value"]))
    if args.windows and merged.windows:
        print("\n# per-window")
        wrows = [{
            "window": f"[{w['round_start']}, {w['round_end']})",
            "fp_onsets": w.get("counters", {}).get("false_suspicion_onsets"),
            "suspect": w.get("gauges", {}).get("suspect_entries"),
            "occupancy": w.get("gauges", {}).get(
                "gossip_piggyback_occupancy"),
            "saturation": w.get("gauges", {}).get("wire_saturation"),
        } for w in merged.windows]
        print(query.format_table(
            wrows, ["window", "fp_onsets", "suspect", "occupancy",
                    "saturation"]))
    return 0


def _cmd_diff(args) -> int:
    a = query.load_report(args.a)
    b = query.load_report(args.b)
    rows = query.diff_reports(a, b)
    if args.json:
        print(json.dumps({"a": a.path, "b": b.path, "rows": rows}))
        return 0
    print(f"# diff: a={a.path}  b={b.path}")
    print(query.format_table(rows, ["metric", "a", "b", "delta", "rel"]))
    return 0


def _cmd_watch(args) -> int:
    """Tail a live journal and render the alarm/SLO table.

    Read-only: the watcher runs its OWN alarm engine over the tailed
    ``metrics_window``/``segment`` rows (it never writes to a journal
    it does not own) and shows journaled ``alarm_transition`` rows —
    written by the run itself — as provenance.  The follower consumes
    each durable line exactly once, so across the whole session every
    window is seen once and only once (tests/test_alarms.py pins this
    against a live writer subprocess).
    """
    from scalecube_cluster_tpu.telemetry import alarms as talarms
    from scalecube_cluster_tpu.telemetry import sink as tsink

    threshold = (args.threshold if args.threshold is not None
                 else talarms.DEFAULT_FP_THRESHOLD)
    specs = talarms.default_specs(threshold=threshold,
                                  for_windows=args.for_windows,
                                  clear_windows=args.clear_windows)
    engine = talarms.AlarmEngine(specs)
    follower = tsink.follow_records(args.journal)
    deadline = (time.time() + args.max_seconds
                if args.max_seconds is not None else None)
    windows = transitions_seen = journal_transitions = 0
    segments = rounds_covered = 0
    unknown_kinds: dict = {}
    done = False
    while True:
        fresh = follower.poll()
        new_rows = []
        boundaries = []
        for rec in fresh:
            kind = rec.get("kind")
            if kind in talarms.WINDOW_KINDS:
                windows += 1
                # Segment rows are checkpoint boundaries, not just
                # windows: count them so a multi-segment soak tail is
                # distinguishable from a single run (segment index +
                # cumulative rounds surface in the live table).
                if kind == "segment":
                    segments += 1
                    rounds_covered = max(rounds_covered,
                                         int(rec.get("round_end", 0)))
                    boundaries.append(
                        (segments, rec.get("round_start"),
                         rec.get("round_end")))
                caused = engine.observe(rec)
                transitions_seen += len(caused)
                if args.json:
                    row = {
                        "kind": "window", "source": kind,
                        "round_start": rec.get("round_start"),
                        "round_end": rec.get("round_end"),
                        "transitions": caused,
                    }
                    if kind == "segment":
                        row["segment"] = segments
                        row["rounds_cumulative"] = rounds_covered
                    print(json.dumps(row), flush=True)
                else:
                    new_rows.append(rec)
            elif kind == talarms.TRANSITION_KIND:
                journal_transitions += 1
                if args.json:
                    print(json.dumps({"kind": "journal_transition",
                                      **{k: v for k, v in rec.items()
                                         if k != "kind"}}), flush=True)
            elif kind == "summary":
                done = True
            elif kind not in ("manifest",):
                # A record kind this watcher doesn't render (a journal
                # written by a newer schema — e.g. ``provenance`` rows
                # landing on an old reader): count it per kind so new
                # kinds degrade LOUDLY, never silently.
                kind = kind or "<missing>"
                first_sight = kind not in unknown_kinds
                unknown_kinds[kind] = unknown_kinds.get(kind, 0) + 1
                if first_sight and args.json:
                    print(json.dumps({
                        "kind": "unknown_record_kind",
                        "record_kind": kind,
                        "note": "journal kind this watcher does not "
                                "render — counted in watch_summary",
                    }), flush=True)
        if fresh and not args.json:
            header = f"\n# watch {args.journal}: {windows} window(s)"
            if segments:
                header += (f", segment {segments} · "
                           f"{rounds_covered} round(s)")
            print(header + f", cursor at byte {follower.offset}")
            for seg, start, end in boundaries:
                print(f"# segment {seg} boundary: rounds "
                      f"[{start}, {end}) · {end} cumulative")
            print(query.format_table(
                engine.state_rows(),
                ["alarm", "state", "value", "threshold", "comparator",
                 "fired", "resolved"]))
            if journal_transitions:
                print(f"({journal_transitions} alarm_transition row(s) "
                      f"journaled by the run itself)")
            if unknown_kinds:
                print("(unrendered record kinds: "
                      + ", ".join(f"{k}×{c}" for k, c
                                  in sorted(unknown_kinds.items()))
                      + ")")
            sys.stdout.flush()
        if done or (deadline is not None and time.time() >= deadline):
            break
        time.sleep(args.interval)
    digest = {
        "kind": "watch_summary", "journal": args.journal,
        "windows": windows, "engine_transitions": transitions_seen,
        "journal_transitions": journal_transitions,
        "segments": segments, "rounds_covered": rounds_covered,
        "unknown_kinds": unknown_kinds,
        "run_ended": done,
        "alarms": engine.state_rows(),
    }
    if args.json:
        print(json.dumps(digest), flush=True)
    else:
        print(f"# watch done: run {'ended' if done else 'still live'}, "
              f"{windows} window(s), {transitions_seen} transition(s)")
    return 0


def _cmd_explain(args) -> int:
    """Answer "why did i believe j was dead" from the journal alone."""
    report = query.load_report(args.journal)
    if not report.provenance:
        print(f"error: {args.journal} holds no provenance records — "
              f"run with SwimParams.provenance=True and journal the "
              f"plane (sink.write_provenance)", file=sys.stderr)
        return 2
    result = query.explain_belief(report.provenance, args.observer,
                                  args.subject, round_idx=args.round)
    if args.json:
        print(json.dumps(result))
        return 0
    obs, subj = args.observer, args.subject
    when = f" at round {args.round}" if args.round is not None else ""
    print(f"# explain: observer {obs} about subject {subj}{when} "
          f"({args.journal})")
    ans = result["answer"]
    if ans is None:
        print(f"observer {obs} recorded no transition for subject "
              f"{subj}{when} — no belief to explain")
    else:
        print(f"observer {obs} believed {ans['transition']} at round "
              f"{ans['round']} via {ans['channel']} "
              f"(epoch {ans['epoch']})")
    if result["events"]:
        print("\n# full (observer, subject) attribution history")
        print(query.format_table(
            result["events"],
            ["round", "transition", "channel", "epoch"]))
    blame = result["context"]["blame"]
    print(f"\n# blame report for subject {subj}")
    print(query.format_table(
        [{"field": k, "value": v} for k, v in blame.items()],
        ["field", "value"]))
    path = result["context"]["infection_path"]
    if path:
        print(f"\n# observer {obs}'s infection path for subject {subj}: "
              f"first informed round {path['first_round']} via "
              f"{path['first_channel']} ({path['first_transition']}); "
              f"per-channel first rounds: "
              + ", ".join(f"{c}@{r}" for c, r
                          in sorted(path["channels"].items())))
    return 0


def _cmd_regress(args) -> int:
    paths = query.expand_paths(
        args.paths
        or ["BENCH_*.json", "MULTICHIP_*.json",
            os.path.join("artifacts", "sync_heal*.json"),
            os.path.join("artifacts", "lifeguard_fp*.json"),
            os.path.join("artifacts", "churn_growth*.json"),
            os.path.join("artifacts", "fuzz_campaign*.json"),
            os.path.join("artifacts", "wire_fused*.json"),
            os.path.join("artifacts", "compose_perf*.json"),
            os.path.join("artifacts", "static_analysis*.json"),
            os.path.join("artifacts", "alarm_drill*.json"),
            os.path.join("artifacts", "tune_pareto*.json"),
            os.path.join("artifacts", "soak_report*.json"),
            os.path.join("artifacts", "config_rollout*.json"),
            os.path.join("artifacts", "provenance_blame*.json")])
    readable = [p for p in paths if os.path.exists(p)]
    if not readable:
        print("regress: no artifacts matched", file=sys.stderr)
        return 2
    ok, rows = query.regress(readable, band=args.band)
    if args.json:
        print(json.dumps({"ok": ok, "band": args.band, "checks": rows}))
    else:
        print(f"# regress over {len(readable)} artifacts "
              f"(noise band {args.band:.0%})")
        print(query.format_table(
            rows, ["check", "source", "latest", "reference", "threshold",
                   "ok", "note"]))
        print("PASS" if ok else "REGRESSION")
    return 0 if ok else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scalecube_cluster_tpu.telemetry",
        description=__doc__.splitlines()[0],
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("report", help="health SLO report of manifest(s)")
    p.add_argument("manifests", nargs="+")
    p.add_argument("--json", action="store_true")
    p.add_argument("--windows", action="store_true",
                   help="include the per-window time series")
    p.set_defaults(fn=_cmd_report)

    p = sub.add_parser("diff", help="compare two run manifests")
    p.add_argument("a")
    p.add_argument("b")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_diff)

    p = sub.add_parser(
        "watch",
        help="live-tail a journal: refreshing alarm/SLO table "
             "(exits on the run's summary record)")
    p.add_argument("journal")
    p.add_argument("--interval", type=float, default=0.5,
                   help="poll interval, seconds (default 0.5)")
    p.add_argument("--max-seconds", type=float, default=None,
                   help="stop after this many seconds even if the run "
                        "is still live (default: wait for the summary "
                        "record)")
    p.add_argument("--threshold", type=float, default=None,
                   help="false_positive_observer_rate breach threshold "
                        "(default: telemetry.alarms"
                        ".DEFAULT_FP_THRESHOLD)")
    p.add_argument("--for-windows", type=int, default=1,
                   help="consecutive breached windows before firing")
    p.add_argument("--clear-windows", type=int, default=1,
                   help="consecutive clear windows before resolving")
    p.add_argument("--json", action="store_true",
                   help="one JSON line per consumed window/transition "
                        "+ a closing watch_summary line")
    p.set_defaults(fn=_cmd_watch)

    p = sub.add_parser(
        "explain",
        help="why did observer i believe this about subject j — from "
             "the journal's provenance records alone")
    p.add_argument("journal")
    p.add_argument("--observer", type=int, required=True,
                   help="observer node id (who held the belief)")
    p.add_argument("--subject", type=int, required=True,
                   help="subject node id (whom the belief was about)")
    p.add_argument("--round", type=int, default=None,
                   help="explain the belief in force at this round "
                        "(default: the latest recorded transition)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_explain)

    p = sub.add_parser(
        "regress",
        help="fail on regressions along the BENCH/MULTICHIP trajectories")
    p.add_argument("paths", nargs="*",
                   help="artifact files/globs (default: BENCH_*.json "
                        "MULTICHIP_*.json artifacts/sync_heal*.json "
                        "artifacts/lifeguard_fp*.json "
                        "artifacts/churn_growth*.json "
                        "artifacts/fuzz_campaign*.json "
                        "artifacts/wire_fused*.json "
                        "artifacts/compose_perf*.json "
                        "artifacts/static_analysis*.json "
                        "artifacts/alarm_drill*.json "
                        "artifacts/tune_pareto*.json "
                        "artifacts/soak_report*.json "
                        "artifacts/config_rollout*.json "
                        "artifacts/provenance_blame*.json)")
    p.add_argument("--band", type=float, default=query.DEFAULT_NOISE_BAND,
                   help="relative noise band (default 0.10)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_regress)

    args = parser.parse_args(argv)
    try:
        return args.fn(args)
    except (OSError, ValueError, KeyError) as e:
        # KeyError: a malformed manifest record (e.g. a histogram row a
        # foreign writer truncated) — input error (2), not regression (1).
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
