"""The telemetry CLI: ``python -m scalecube_cluster_tpu.telemetry``.

Three subcommands over the JSONL manifests and BENCH artifacts
(telemetry/query.py):

  report   <manifest.jsonl> [...]   fold manifests, print the health
                                    SLO table (``--json`` for machines,
                                    ``--windows`` for the per-window
                                    time series)
  diff     <a.jsonl> <b.jsonl>      per-SLO/counter/gauge comparison
                                    of two runs
  regress  [paths/globs ...]        walk the BENCH_*.json +
                                    MULTICHIP_*.json trajectories
                                    (the default globs) and exit 1 on
                                    throughput or SLO regressions
                                    beyond ``--band``; legacy stub
                                    rounds skip as provenance

Exit codes: 0 ok, 1 regression detected (regress), 2 usage/input error
— stable for CI gating (tests/test_metrics_query.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from scalecube_cluster_tpu.telemetry import query


def _cmd_report(args) -> int:
    reports = [query.load_report(p) for p in args.manifests]
    merged = (query.merge_reports(reports) if len(reports) > 1
              else reports[0])
    slos = query.compute_slos(merged)
    if args.json:
        print(json.dumps({
            "manifests": [r.path for r in reports],
            "slos": slos,
            "counters": merged.counters,
            "gauges": merged.gauges,
            "windows": merged.windows if args.windows else len(merged.windows),
        }))
        return 0
    rows = [{"metric": k, "value": v} for k, v in slos.items()]
    print(f"# health report: {', '.join(r.path for r in reports)}")
    print(query.format_table(rows, ["metric", "value"]))
    if merged.counters:
        print("\n# counters (summed over windows)")
        print(query.format_table(
            [{"metric": k, "value": v}
             for k, v in sorted(merged.counters.items())],
            ["metric", "value"]))
    if args.windows and merged.windows:
        print("\n# per-window")
        wrows = [{
            "window": f"[{w['round_start']}, {w['round_end']})",
            "fp_onsets": w.get("counters", {}).get("false_suspicion_onsets"),
            "suspect": w.get("gauges", {}).get("suspect_entries"),
            "occupancy": w.get("gauges", {}).get(
                "gossip_piggyback_occupancy"),
            "saturation": w.get("gauges", {}).get("wire_saturation"),
        } for w in merged.windows]
        print(query.format_table(
            wrows, ["window", "fp_onsets", "suspect", "occupancy",
                    "saturation"]))
    return 0


def _cmd_diff(args) -> int:
    a = query.load_report(args.a)
    b = query.load_report(args.b)
    rows = query.diff_reports(a, b)
    if args.json:
        print(json.dumps({"a": a.path, "b": b.path, "rows": rows}))
        return 0
    print(f"# diff: a={a.path}  b={b.path}")
    print(query.format_table(rows, ["metric", "a", "b", "delta", "rel"]))
    return 0


def _cmd_regress(args) -> int:
    paths = query.expand_paths(
        args.paths
        or ["BENCH_*.json", "MULTICHIP_*.json",
            os.path.join("artifacts", "sync_heal*.json"),
            os.path.join("artifacts", "lifeguard_fp*.json"),
            os.path.join("artifacts", "churn_growth*.json"),
            os.path.join("artifacts", "fuzz_campaign*.json"),
            os.path.join("artifacts", "wire_fused*.json"),
            os.path.join("artifacts", "compose_perf*.json"),
            os.path.join("artifacts", "static_analysis*.json")])
    readable = [p for p in paths if os.path.exists(p)]
    if not readable:
        print("regress: no artifacts matched", file=sys.stderr)
        return 2
    ok, rows = query.regress(readable, band=args.band)
    if args.json:
        print(json.dumps({"ok": ok, "band": args.band, "checks": rows}))
    else:
        print(f"# regress over {len(readable)} artifacts "
              f"(noise band {args.band:.0%})")
        print(query.format_table(
            rows, ["check", "source", "latest", "reference", "threshold",
                   "ok", "note"]))
        print("PASS" if ok else "REGRESSION")
    return 0 if ok else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scalecube_cluster_tpu.telemetry",
        description=__doc__.splitlines()[0],
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("report", help="health SLO report of manifest(s)")
    p.add_argument("manifests", nargs="+")
    p.add_argument("--json", action="store_true")
    p.add_argument("--windows", action="store_true",
                   help="include the per-window time series")
    p.set_defaults(fn=_cmd_report)

    p = sub.add_parser("diff", help="compare two run manifests")
    p.add_argument("a")
    p.add_argument("b")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_diff)

    p = sub.add_parser(
        "regress",
        help="fail on regressions along the BENCH/MULTICHIP trajectories")
    p.add_argument("paths", nargs="*",
                   help="artifact files/globs (default: BENCH_*.json "
                        "MULTICHIP_*.json artifacts/sync_heal*.json "
                        "artifacts/lifeguard_fp*.json "
                        "artifacts/churn_growth*.json "
                        "artifacts/fuzz_campaign*.json "
                        "artifacts/wire_fused*.json "
                        "artifacts/compose_perf*.json "
                        "artifacts/static_analysis*.json)")
    p.add_argument("--band", type=float, default=query.DEFAULT_NOISE_BAND,
                   help="relative noise band (default 0.10)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_regress)

    args = parser.parse_args(argv)
    try:
        return args.fn(args)
    except (OSError, ValueError, KeyError) as e:
        # KeyError: a malformed manifest record (e.g. a histogram row a
        # foreign writer truncated) — input error (2), not regression (1).
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
