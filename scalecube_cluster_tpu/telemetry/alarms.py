"""Live SLO alarms: streaming breach detection over a running journal.

Everything in telemetry/query.py is post-hoc — SLOs and regress
verdicts computed from COMMITTED artifacts after a run has ended.  This
module is the live half (ROADMAP item 5's missing piece): a declarative
:class:`AlarmSpec` registry evaluated INCREMENTALLY over the streaming
``metrics_window`` / supervisor ``segment`` rows a running cluster
already emits, each alarm a pending→firing→resolved state machine with
debounce and clear-side hysteresis.

Every state change is written back to the journal as an
``alarm_transition`` record (via ``TelemetrySink.write_record``), so a
run's alarm history is durable, greppable and diffable like every other
record kind — and RESUMABLE: transitions are a pure deterministic
function of the window-row sequence (the runs themselves are
bit-reproducible), so a relaunched process replays the journal's rows
through a fresh engine, reconstructs exactly the transitions the dead
process would have written, and skips the ones already durable
(:func:`replay_journal` + :func:`write_transitions` — the per-
``round_end`` count dedup).  The exactly-once journal guarantee the
resilient supervisor gives segments extends to alarms with no new
machinery on the write path.

Record shape::

    {"kind": "alarm_transition", "alarm": <spec name>,
     "from": "ok|pending|firing", "to": "pending|firing|resolved|ok",
     "round_start": int, "round_end": int,   # the triggering window
     "value": float, "threshold": float, "comparator": str,
     "streak": int}

``round_end`` makes the record a first-class citizen of the journal
cursor: ``sink.covered_upto(path, kind="alarm_transition")`` works, and
the dedup above is keyed on it.  Consumers: the resilience supervisor
(segment-boundary evaluation), ``telemetry.metrics.stream_metered_run``
(per-flush-window evaluation), and the live ``watch`` CLI
(``python -m scalecube_cluster_tpu.telemetry watch`` — tails a foreign
journal via ``sink.follow_records`` and renders the table read-only).

Pinned by tests/test_alarms.py.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: Record kinds an engine evaluates as "one window of counters".
WINDOW_KINDS = ("metrics_window", "segment")

#: The journal record kind every transition is written as.
TRANSITION_KIND = "alarm_transition"

#: Alarm states.  ``resolved`` is a TRANSITION, not a resting state —
#: after a resolve the alarm is back at ``ok`` and can fire again.
OK, PENDING, FIRING = "ok", "pending", "firing"
RESOLVED = "resolved"

_COMPARATORS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}

#: Default breach threshold for the false-positive observer-rate alarm
#: (onsets per live observer-round per window, summed over all
#: suspected targets — under an asymmetric loss pulse a single healthy
#: observer cycles onset->refute->re-onset against MANY quadrant
#: members at once, so pulse-window rates exceed 1).  Calibrated by
#: the full bench.py --alarms drill (n=48, pulse_loss=0.6, seeds
#: 7/11/23): the healthy arm's worst pulse window stays <= 1.35 while
#: the weakened-knobs breach arm's (chaos.alarm_breach_knobs) never
#: drops under 1.70 during the pulse — 1.5 splits the gap with >= 10%
#: margin on both sides; both arms are exactly 0 outside the pulse
#: (artifacts/alarm_drill.json records the measured margins).  The
#: smoke drill geometry (n=24) runs lower rates and overrides this via
#: its own preset (bench.py SMOKE_ALARM_THRESHOLD).
DEFAULT_FP_THRESHOLD = 1.5


@dataclasses.dataclass(frozen=True)
class AlarmSpec:
    """One declarative alarm over a windowed counter ratio.

    ``numerator`` is a counter lane name; ``denominator`` is a counter
    lane, the literal ``"rounds"`` (the window's round count — the
    per-round-rate fallback for record kinds that don't carry the SLO's
    denominator lane, e.g. supervisor ``segment`` counter rows), or
    None for a raw windowed sum.  The value compared against
    ``threshold`` is ``sum(numerator) / sum(denominator)`` over the
    last ``window`` rows (a SLIDING window in metrics windows, not
    rounds).

    ``for_windows`` is the firing debounce: the alarm goes ``pending``
    on the first breached evaluation and ``firing`` only after that
    many CONSECUTIVE breaches (``for_windows <= 1`` fires immediately).
    ``clear_windows`` is the resolve-side hysteresis: a firing alarm
    resolves only after that many consecutive clear evaluations — a
    single healthy window inside an incident must not flap the alarm.

    A window whose denominator sums to zero (or whose lanes are absent
    from the record entirely) is NOT an evaluation: streaks and state
    are untouched — absence of signal is not health.
    """

    name: str
    numerator: str
    denominator: Optional[str] = None
    comparator: str = ">"
    threshold: float = 0.0
    window: int = 1
    for_windows: int = 1
    clear_windows: int = 1

    def __post_init__(self):
        if self.comparator not in _COMPARATORS:
            raise ValueError(
                f"alarm {self.name!r}: comparator {self.comparator!r} "
                f"not in {sorted(_COMPARATORS)}")
        for field in ("window", "for_windows", "clear_windows"):
            if getattr(self, field) < 1:
                raise ValueError(
                    f"alarm {self.name!r}: {field} must be >= 1 "
                    f"(got {getattr(self, field)})")

    def breached(self, value: float) -> bool:
        return _COMPARATORS[self.comparator](value, self.threshold)


def default_specs(threshold: float = DEFAULT_FP_THRESHOLD,
                  for_windows: int = 1,
                  clear_windows: int = 1) -> Tuple[AlarmSpec, ...]:
    """The default registry: the paper's headline bounded-false-positive
    guarantee as a live alarm — false-suspicion onsets per live
    observer-round (the PR-5 ``false_positive_observer_rate`` SLO),
    evaluated per flush window."""
    return (AlarmSpec(
        name="false_positive_observer_rate",
        numerator="false_suspicion_onsets",
        denominator="live_observer_rounds",
        comparator=">", threshold=threshold,
        window=1, for_windows=for_windows, clear_windows=clear_windows,
    ),)


def _window_counters(rec: dict) -> Tuple[dict, int]:
    """(counter dict, rounds) of one window-ish record.

    Both ``metrics_window`` rows and supervisor ``segment`` rows nest
    their lanes under ``counters`` (the registry flush vs. the
    counters_row digest) and carry ``round_start``/``round_end``; the
    round span is the ``"rounds"`` denominator.
    """
    counters = rec.get("counters") or {}
    rounds = int(rec.get("round_end", 0)) - int(rec.get("round_start", 0))
    return counters, max(rounds, 0)


@dataclasses.dataclass
class _AlarmState:
    state: str = OK
    breach_streak: int = 0
    clear_streak: int = 0
    last_value: Optional[float] = None
    fired: int = 0                 # lifetime count of firing transitions
    resolved: int = 0


class AlarmEngine:
    """Incremental evaluator: feed journal records, get transitions.

    Deterministic by construction — state is a pure fold over the
    window-row sequence, specs are evaluated in registry order and each
    spec changes state at most once per row, so the transition list for
    any row prefix is reproducible across processes.  That determinism
    is what makes the replay/dedup resume protocol exactly-once
    (module docstring).

    The engine never writes; callers pair :meth:`observe` with
    :func:`write_transitions` (or just read the states for rendering —
    the ``watch`` CLI's read-only mode).
    """

    def __init__(self, specs: Sequence[AlarmSpec],
                 kinds: Sequence[str] = WINDOW_KINDS):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate alarm names: {names}")
        self.specs = tuple(specs)
        self.kinds = tuple(kinds)
        self._states: Dict[str, _AlarmState] = {
            s.name: _AlarmState() for s in specs}
        self._history: Dict[str, collections.deque] = {
            s.name: collections.deque(maxlen=s.window) for s in specs}
        self.windows_seen = 0

    # -- state access (the watch table) ------------------------------------

    def state_rows(self) -> List[dict]:
        """One render-ready row per alarm (the watch table's shape)."""
        return [{
            "alarm": s.name,
            "state": st.state,
            "value": st.last_value,
            "threshold": s.threshold,
            "comparator": s.comparator,
            "fired": st.fired,
            "resolved": st.resolved,
        } for s in self.specs for st in (self._states[s.name],)]

    def state_of(self, name: str) -> str:
        return self._states[name].state

    # -- evaluation --------------------------------------------------------

    def observe(self, rec: dict) -> List[dict]:
        """Feed one journal record; returns the (possibly empty) list
        of transition payloads it caused, in deterministic spec order.
        Non-window kinds are ignored, so a whole record stream can be
        piped through unsorted."""
        if rec.get("kind") not in self.kinds:
            return []
        counters, rounds = _window_counters(rec)
        self.windows_seen += 1
        out: List[dict] = []
        for spec in self.specs:
            t = self._observe_one(spec, counters, rounds, rec)
            if t is not None:
                out.append(t)
        return out

    def _observe_one(self, spec: AlarmSpec, counters: dict, rounds: int,
                     rec: dict) -> Optional[dict]:
        if spec.numerator not in counters:
            return None                      # lane absent: no evaluation
        num = float(counters[spec.numerator])
        if spec.denominator == "rounds":
            den: Optional[float] = float(rounds)
        elif spec.denominator is not None:
            if spec.denominator not in counters:
                return None
            den = float(counters[spec.denominator])
        else:
            den = None
        hist = self._history[spec.name]
        hist.append((num, den))
        num_sum = sum(n for n, _ in hist)
        if den is None:
            value = num_sum
        else:
            den_sum = sum(d for _, d in hist)
            if den_sum <= 0:
                return None                  # zero denominator: no signal
            value = num_sum / den_sum
        st = self._states[spec.name]
        st.last_value = value
        return self._step(spec, st, spec.breached(value), value, rec)

    def _step(self, spec: AlarmSpec, st: _AlarmState, breached: bool,
              value: float, rec: dict) -> Optional[dict]:
        prev = st.state
        to: Optional[str] = None
        if breached:
            st.clear_streak = 0
            st.breach_streak += 1
            if prev in (OK,) and st.breach_streak >= spec.for_windows:
                st.state, to = FIRING, FIRING
                st.fired += 1
            elif prev == OK:
                st.state, to = PENDING, PENDING
            elif prev == PENDING and st.breach_streak >= spec.for_windows:
                st.state, to = FIRING, FIRING
                st.fired += 1
        else:
            st.breach_streak = 0
            if prev == PENDING:
                # Breach gone before the debounce matured: the pending
                # alarm cancels back to ok — recorded (it is a state
                # change an operator watching the table saw happen).
                st.state, to = OK, OK
            elif prev == FIRING:
                st.clear_streak += 1
                if st.clear_streak >= spec.clear_windows:
                    st.state, to = OK, RESOLVED
                    st.resolved += 1
        if to is None:
            return None
        return {
            "alarm": spec.name,
            "from": prev,
            "to": to,
            "round_start": int(rec.get("round_start", 0)),
            "round_end": int(rec.get("round_end", 0)),
            "value": round(float(value), 8),
            "threshold": spec.threshold,
            "comparator": spec.comparator,
            "streak": (st.breach_streak if breached else st.clear_streak),
        }


# --------------------------------------------------------------------------
# Resume: replay + exactly-once dedup
# --------------------------------------------------------------------------


def replay_journal(engine: AlarmEngine, records: Iterable[dict],
                   ) -> Tuple[List[dict], "collections.Counter"]:
    """Rebuild ``engine`` from an existing record stream (journal
    order), returning ``(transitions, existing)``:

    - ``transitions``: everything the engine would have emitted for the
      replayed rows — a superset of what the dead process durably wrote
      when it was killed mid-transition;
    - ``existing``: a per-``round_end`` count of ``alarm_transition``
      records already durable in the stream.

    Feed both to :func:`write_transitions`: the count dedup writes
    exactly the missing tail (transition emission order per window is
    deterministic — :class:`AlarmEngine` docstring), extending the
    journal's exactly-once guarantee to alarms across any kill/relaunch
    sequence.  One scan, no re-parsing: pass a
    :class:`~scalecube_cluster_tpu.telemetry.sink.JournalFollower`'s
    ``poll()`` output (or ``iter_records``) — the same pass that feeds
    the supervisor's ``covered_upto`` rebase.
    """
    transitions: List[dict] = []
    existing: collections.Counter = collections.Counter()
    for rec in records:
        if rec.get("kind") == TRANSITION_KIND:
            existing[int(rec.get("round_end", 0))] += 1
        else:
            transitions.extend(engine.observe(rec))
    return transitions, existing


def write_transitions(sink, transitions: Sequence[dict],
                      existing: Optional["collections.Counter"] = None,
                      ) -> List[dict]:
    """Write ``transitions`` through ``sink`` as ``alarm_transition``
    records, skipping the first ``existing[round_end]`` transitions of
    each ``round_end`` (already durable — the replay dedup).  Returns
    the records actually written.  Mutates ``existing`` (counts are
    consumed), so one counter threads through replay + the live loop.
    """
    written: List[dict] = []
    for t in transitions:
        if existing is not None:
            end = int(t.get("round_end", 0))
            if existing[end] > 0:
                existing[end] -= 1
                continue
        sink.write_record(TRANSITION_KIND, dict(t))
        written.append(t)
    return written
