"""Cluster configuration with LAN/WAN/LOCAL presets.

Mirror of the reference's immutable builder config
(cluster/src/main/java/io/scalecube/cluster/ClusterConfig.java:24-419),
redesigned as a frozen dataclass (the idiomatic Python analog of the Java
builder; use ``dataclasses.replace`` / ``ClusterConfig.replace`` instead of
builder chaining).  One object implements all three protocol config
interfaces, exactly like the reference's
``ClusterConfig implements FailureDetectorConfig, GossipConfig,
MembershipConfig``.

For the TPU simulation the millisecond knobs are quantized to discrete
protocol *rounds* via :meth:`ClusterConfig.to_sim`, with the gossip
interval as the base tick (SURVEY.md §7 design mapping).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from scalecube_cluster_tpu import swim_math

# Default settings for LAN cluster (ClusterConfig.java:26-36).
DEFAULT_SYNC_GROUP = "default"
DEFAULT_SYNC_INTERVAL = 30_000
DEFAULT_SYNC_TIMEOUT = 3_000
DEFAULT_SUSPICION_MULT = 5
DEFAULT_PING_INTERVAL = 1_000
DEFAULT_PING_TIMEOUT = 500
DEFAULT_PING_REQ_MEMBERS = 3
DEFAULT_GOSSIP_INTERVAL = 200
DEFAULT_GOSSIP_FANOUT = 3
DEFAULT_GOSSIP_REPEAT_MULT = 3
DEFAULT_METADATA_TIMEOUT = 3_000

# Transport defaults (transport/TransportConfig.java:5-9).
DEFAULT_PORT = 0
DEFAULT_CONNECT_TIMEOUT = 3_000
DEFAULT_MAX_FRAME_LENGTH = 2 * 1024 * 1024


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """All protocol knobs for one cluster member (or one simulated cluster).

    Field-for-field parity with ClusterConfig.java:64-81 (times in ms).
    """

    seed_members: Tuple[str, ...] = ()
    metadata: Tuple[Tuple[str, str], ...] = ()

    # MembershipConfig (membership/MembershipConfig.java:7-26)
    sync_interval: int = DEFAULT_SYNC_INTERVAL
    sync_timeout: int = DEFAULT_SYNC_TIMEOUT
    sync_group: str = DEFAULT_SYNC_GROUP
    suspicion_mult: int = DEFAULT_SUSPICION_MULT

    # FailureDetectorConfig (fdetector/FailureDetectorConfig.java:3-10)
    ping_interval: int = DEFAULT_PING_INTERVAL
    ping_timeout: int = DEFAULT_PING_TIMEOUT
    ping_req_members: int = DEFAULT_PING_REQ_MEMBERS

    # GossipConfig (gossip/GossipConfig.java:3-10)
    gossip_interval: int = DEFAULT_GOSSIP_INTERVAL
    gossip_fanout: int = DEFAULT_GOSSIP_FANOUT
    gossip_repeat_mult: int = DEFAULT_GOSSIP_REPEAT_MULT

    metadata_timeout: int = DEFAULT_METADATA_TIMEOUT

    # TransportConfig (transport/TransportConfig.java:3-126)
    port: int = DEFAULT_PORT
    connect_timeout: int = DEFAULT_CONNECT_TIMEOUT
    max_frame_length: int = DEFAULT_MAX_FRAME_LENGTH
    member_host: Optional[str] = None
    member_port: Optional[int] = None

    def __post_init__(self) -> None:
        # Validation mirrors ClusterConfig.Builder.build() (ClusterConfig.java:412-415).
        if self.ping_timeout >= self.ping_interval:
            raise ValueError(
                f"ping_timeout ({self.ping_timeout}) must be smaller than "
                f"ping_interval ({self.ping_interval})"
            )

    # -- presets -----------------------------------------------------------

    @staticmethod
    def default() -> "ClusterConfig":
        """LAN defaults (ClusterConfig.java:107-114)."""
        return ClusterConfig()

    default_lan = default

    @staticmethod
    def default_wan() -> "ClusterConfig":
        """WAN overrides (ClusterConfig.java:116-126)."""
        return ClusterConfig(
            suspicion_mult=6,
            sync_interval=60_000,
            ping_timeout=3_000,
            ping_interval=5_000,
            gossip_fanout=4,
            connect_timeout=10_000,
        )

    @staticmethod
    def default_local() -> "ClusterConfig":
        """Loopback overrides (ClusterConfig.java:128-140)."""
        return ClusterConfig(
            suspicion_mult=3,
            sync_interval=15_000,
            ping_timeout=200,
            ping_interval=1_000,
            gossip_repeat_mult=2,
            ping_req_members=1,
            gossip_interval=100,
            connect_timeout=1_000,
        )

    def replace(self, **kwargs) -> "ClusterConfig":
        return dataclasses.replace(self, **kwargs)

    def metadata_dict(self) -> Dict[str, str]:
        return dict(self.metadata)

    # -- round quantization for the TPU tick -------------------------------

    def to_sim(self, cluster_size: int) -> "SimParams":
        """Quantize millisecond knobs to protocol rounds for the dense tick.

        The gossip interval is the base round (the shortest periodic loop in
        the reference, GossipProtocolImpl.java:105-112); ping and sync
        intervals become multiples of it, and the suspicion timeout becomes a
        round count via the analytic model (ClusterMath.java:123-125).
        """
        base = self.gossip_interval

        def rounds(ms: int) -> int:
            return max(1, int(round(ms / base)))

        return SimParams(
            cluster_size=cluster_size,
            ping_every=rounds(self.ping_interval),
            sync_every=rounds(self.sync_interval),
            suspicion_rounds=rounds(
                swim_math.suspicion_timeout(self.suspicion_mult, cluster_size, self.ping_interval)
            ),
            ping_req_members=self.ping_req_members,
            gossip_fanout=self.gossip_fanout,
            gossip_repeat_mult=self.gossip_repeat_mult,
            periods_to_spread=swim_math.gossip_periods_to_spread(
                self.gossip_repeat_mult, cluster_size
            ),
            periods_to_sweep=swim_math.gossip_periods_to_sweep(
                self.gossip_repeat_mult, cluster_size
            ),
        )


@dataclasses.dataclass(frozen=True)
class SimParams:
    """Static (compile-time) parameters of the dense TPU tick.

    Everything here is a Python int baked into the jitted program — no
    dynamic shapes (SURVEY.md §7; XLA requires static control flow).
    """

    cluster_size: int
    ping_every: int
    sync_every: int
    suspicion_rounds: int
    ping_req_members: int
    gossip_fanout: int
    gossip_repeat_mult: int
    periods_to_spread: int
    periods_to_sweep: int
