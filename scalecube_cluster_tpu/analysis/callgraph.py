"""Mention-graph over the package source: the reachability substrate
of swimlint's cross-cutting rules.

The plane-threading matrix (analysis/rules.py) needs "which
``SwimParams`` knobs does the code reachable from ``shard_run_metered``
consult?" — a question about the *source*, not the runtime: a knob the
sharded path never reads is a plane that silently doesn't exist there,
which is exactly the hazard ROADMAP item 1 describes (one plane ==
~28 hand-edited files with nothing but review discipline checking
coverage).

So this module builds a deliberately *over-approximate* static call
graph:

  - nodes are top-level functions and class methods (nested closures —
    the ``tick``/``body`` lambdas every run shape wraps around
    ``lax.scan`` — are inlined into their parent, which is what makes
    ``lax.scan(tick, ...)`` reachability free);
  - an edge exists when a function MENTIONS another: a ``Name`` load
    resolving through the module's import/def table, an attribute on a
    resolved module alias (``swim.run_metered``), a class attribute
    (``SwimParams.from_config``), or — the over-approximation — an
    attribute whose bare name matches a known method/property
    (``params.wire_format`` edges into the property body, so the
    fields the property consults count as consulted).

Over-approximation is the safe direction for a *completeness* rule:
a spurious edge can at worst hide a missing-threading finding behind an
unrelated same-named method, while a missed edge would fabricate one.
Two deliberate precision guards keep the cones meaningful:

  - annotations are NOT mentions (every signature says ``SwimParams``;
    following them would pull ``__post_init__`` — which consults every
    field for validation — into every cone and blind the matrix);
  - a bare class-name mention edges only into ``__init__``, never the
    whole method set (constructors run; validators and classmethods
    don't, unless actually referenced).

Everything operates on a *root directory* of ``.py`` files, so the
mutation tests can point the same engine at a copied, deliberately
broken tree (tests/test_analysis_rules.py).
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import Dict, Iterable, List, Optional, Set, Tuple


@dataclasses.dataclass
class FunctionInfo:
    """One graph node: a top-level function or a class method."""

    qualname: str            # "models/swim.py::run" / "...::SwimParams.wire_format"
    name: str                # bare name ("run" / "wire_format")
    rel: str                 # module path relative to the root
    node: ast.AST            # FunctionDef / AsyncFunctionDef
    cls: Optional[str] = None


@dataclasses.dataclass
class ModuleInfo:
    rel: str                 # "models/swim.py"
    path: pathlib.Path
    tree: ast.Module
    # name -> ("func", qualname) | ("class", class name) | ("module", rel)
    symbols: Dict[str, Tuple[str, str]] = dataclasses.field(
        default_factory=dict)
    # import aliases that resolve OUTSIDE the package ("np" -> "numpy")
    extern: Dict[str, str] = dataclasses.field(default_factory=dict)


class PackageGraph:
    """All modules under ``root`` plus the mention graph between their
    functions.  ``root`` is the package directory itself (the directory
    holding ``models/``, ``ops/``, ...)."""

    def __init__(self, root):
        self.root = pathlib.Path(root).resolve()
        if not self.root.is_dir():
            raise FileNotFoundError(f"analysis root is not a directory: "
                                    f"{self.root}")
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        # bare method/function name -> qualnames (the over-approx index)
        self.by_name: Dict[str, List[str]] = {}
        # class name -> {method name -> qualname}
        self.classes: Dict[str, Dict[str, str]] = {}
        self._edges: Dict[str, Set[str]] = {}
        self._load()
        self._resolve_imports()
        self._build_edges()

    # -- loading -----------------------------------------------------------

    def _load(self):
        paths = sorted(self.root.rglob("*.py"))
        if not paths:
            raise FileNotFoundError(f"no .py files under {self.root}")
        for path in paths:
            rel = str(path.relative_to(self.root))
            try:
                tree = ast.parse(path.read_text())
            except SyntaxError as e:
                raise SyntaxError(f"{rel}: {e}") from e
            mod = ModuleInfo(rel=rel, path=path, tree=tree)
            self.modules[rel] = mod
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._add_function(mod, node, cls=None)
                elif isinstance(node, ast.ClassDef):
                    mod.symbols[node.name] = ("class", node.name)
                    methods = self.classes.setdefault(node.name, {})
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            q = self._add_function(mod, item, cls=node.name)
                            methods[item.name] = q

    def _add_function(self, mod: ModuleInfo, node, cls: Optional[str]) -> str:
        qual = (f"{mod.rel}::{cls}.{node.name}" if cls
                else f"{mod.rel}::{node.name}")
        info = FunctionInfo(qualname=qual, name=node.name, rel=mod.rel,
                            node=node, cls=cls)
        self.functions[qual] = info
        self.by_name.setdefault(node.name, []).append(qual)
        if cls is None:
            mod.symbols[node.name] = ("func", qual)
        return qual

    # -- import resolution -------------------------------------------------

    def _module_for(self, dotted_parts: List[str]) -> Optional[str]:
        """Resolve a dotted module path to a rel path under the root by
        suffix matching (so ``scalecube_cluster_tpu.models.swim`` and a
        copied tree's ``anything.models.swim`` both land on
        ``models/swim.py``)."""
        for start in range(len(dotted_parts)):
            tail = dotted_parts[start:]
            as_file = "/".join(tail) + ".py"
            as_pkg = "/".join(tail + ["__init__.py"])
            if as_file in self.modules:
                return as_file
            if as_pkg in self.modules:
                return as_pkg
        return None

    def _resolve_imports(self):
        for mod in self.modules.values():
            base_parts = mod.rel.split("/")[:-1]
            # every Import/ImportFrom in the file, including the lazy
            # in-function ones run_metered-style bodies use
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        parts = alias.name.split(".")
                        local = alias.asname or parts[0]
                        target = self._module_for(parts)
                        if target is not None:
                            mod.symbols[local] = ("module", target)
                        else:
                            mod.extern[local] = alias.name
                elif isinstance(node, ast.ImportFrom):
                    if node.level:  # relative import
                        up = base_parts[: len(base_parts) - (node.level - 1)]
                        parts = up + (node.module.split(".")
                                      if node.module else [])
                    else:
                        parts = (node.module or "").split(".")
                    src = self._module_for(parts)
                    for alias in node.names:
                        local = alias.asname or alias.name
                        sub = self._module_for(parts + [alias.name])
                        if sub is not None:
                            mod.symbols[local] = ("module", sub)
                        elif src is not None:
                            sym = self.modules[src].symbols.get(alias.name)
                            if sym is not None:
                                mod.symbols[local] = sym
                        elif parts and parts[0]:
                            mod.extern[local] = ".".join(parts
                                                         + [alias.name])

    # -- mention edges -----------------------------------------------------

    def _mention_nodes(self, fn_node) -> Iterable[ast.AST]:
        """Walk a function body skipping annotations (see module
        docstring: annotations are types, not data flow)."""
        skip = set()
        for node in ast.walk(fn_node):
            if isinstance(node, ast.arg) and node.annotation is not None:
                skip.add(id(node.annotation))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.returns is not None:
                skip.add(id(node.returns))
            elif isinstance(node, ast.AnnAssign):
                skip.add(id(node.annotation))
        stack = list(ast.iter_child_nodes(fn_node))
        while stack:
            node = stack.pop()
            if id(node) in skip:
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def module_alias(self, mod: ModuleInfo, expr) -> Optional[str]:
        """rel path if ``expr`` is a Name bound to a package module."""
        if isinstance(expr, ast.Name):
            sym = mod.symbols.get(expr.id)
            if sym is not None and sym[0] == "module":
                return sym[1]
        return None

    def extern_root(self, mod: ModuleInfo, expr) -> Optional[str]:
        """Dotted name of the external module ``expr`` is rooted at
        (``np.random`` -> "numpy"), else None."""
        while isinstance(expr, ast.Attribute):
            expr = expr.value
        if isinstance(expr, ast.Name):
            return mod.extern.get(expr.id)
        return None

    def _edge_targets(self, mod: ModuleInfo, node) -> List[str]:
        out: List[str] = []
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            sym = mod.symbols.get(node.id)
            if sym is not None:
                if sym[0] == "func":
                    out.append(sym[1])
                elif sym[0] == "class":
                    init = self.classes.get(sym[1], {}).get("__init__")
                    if init:
                        out.append(init)
        elif isinstance(node, ast.Attribute):
            target_mod = self.module_alias(mod, node.value)
            if target_mod is not None:
                sym = self.modules[target_mod].symbols.get(node.attr)
                if sym is not None and sym[0] == "func":
                    out.append(sym[1])
                elif sym is not None and sym[0] == "class":
                    init = self.classes.get(sym[1], {}).get("__init__")
                    if init:
                        out.append(init)
            elif (isinstance(node.value, ast.Name)
                  and node.value.id in self.classes):
                q = self.classes[node.value.id].get(node.attr)
                if q:
                    out.append(q)
            elif self.extern_root(mod, node.value) is None:
                # the over-approximate leg: attribute name matching any
                # known method/property (``params.wire_format``,
                # ``eng.deliver``) — see module docstring
                out.extend(self.by_name.get(node.attr, ()))
        return out

    def _build_edges(self):
        for qual, info in self.functions.items():
            mod = self.modules[info.rel]
            edges: Set[str] = set()
            for node in self._mention_nodes(info.node):
                for tgt in self._edge_targets(mod, node):
                    if tgt != qual:
                        edges.add(tgt)
            self._edges[qual] = edges

    # -- queries -----------------------------------------------------------

    def find(self, rel: str, name: str) -> Optional[str]:
        qual = f"{rel}::{name}"
        return qual if qual in self.functions else None

    def cone(self, roots: Iterable[str]) -> Set[str]:
        """All functions reachable from ``roots`` (roots included)."""
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.functions]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            stack.extend(self._edges.get(q, ()))
        return seen

    def consult_sites(self, qual: str,
                      fields: Set[str]) -> List[Tuple[str, str, int]]:
        """(field, rel, line) for every ``<expr>.<field>`` read in the
        function whose base is not a module alias — an attribute with a
        knob's name on a non-module object is a consultation of that
        knob (``params.sync_interval``, ``kn.suspicion_rounds``,
        ``self.compact_carry`` inside a property)."""
        info = self.functions[qual]
        mod = self.modules[info.rel]
        sites: List[Tuple[str, str, int]] = []
        for node in self._mention_nodes(info.node):
            if (isinstance(node, ast.Attribute) and node.attr in fields
                    and isinstance(node.ctx, ast.Load)
                    and self.module_alias(mod, node.value) is None
                    and self.extern_root(mod, node.value) is None):
                sites.append((node.attr, info.rel, node.lineno))
        return sites

    def dataclass_fields(self, rel: str, cls: str) -> List[str]:
        """Annotated field names of a (data)class, in declaration order
        — the statically-extracted knob list the matrix rows come
        from."""
        mod = self.modules.get(rel)
        if mod is None:
            raise KeyError(f"no module {rel!r} under {self.root}")
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef) and node.name == cls:
                return [item.target.id for item in node.body
                        if isinstance(item, ast.AnnAssign)
                        and isinstance(item.target, ast.Name)]
        raise KeyError(f"no class {cls!r} in {rel}")
