"""swimlint: project-native static analysis.

The reference implementation gets cross-cutting guarantees from the JVM
type system; the JAX reproduction threads every protocol plane by hand
through three tick bodies, two pipelined halves, and seven run entry
points (ROADMAP item 1's "28 files per plane").  This package machine-
checks that family of invariants:

  - :mod:`.callgraph` — mention-graph reachability over the source;
  - :mod:`.rules` — the plane-threading completeness matrix,
    trace-safety, donation-safety, and the magic-literal owning-table
    audit;
  - :mod:`.compile_audit` — jaxpr-level checks on every run entry
    point (zero host callbacks, compact carry lanes stay narrow, no
    recompile on a second same-shape call);
  - :mod:`.engine` — the driver + per-finding baseline contract;
  - ``python -m scalecube_cluster_tpu.analysis`` — the CLI
    (``report``/``check``, exit 0/1/2; see :mod:`.__main__`).

The ``check`` artifact (``artifacts/static_analysis.json``) is the
machine-readable knob x run-shape map the ROADMAP item-1 compose()
refactor must preserve, and ``telemetry regress`` gates on its
``findings_total == 0``.
"""

from scalecube_cluster_tpu.analysis.engine import (  # noqa: F401
    AnalysisResult, BaselineError, load_baseline, run_analysis,
)
from scalecube_cluster_tpu.analysis.rules import (  # noqa: F401
    ENTRY_POINTS, TICK_BODIES, Finding, LiteralFamily,
    default_literal_families,
)
