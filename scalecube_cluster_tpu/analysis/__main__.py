"""The swimlint CLI: ``python -m scalecube_cluster_tpu.analysis``.

Two subcommands over the project-native static analysis
(analysis/engine.py):

  report  print the plane-threading matrix summary and every finding
          (suppressed ones included), write the artifact; exit 0
          unless the input is unusable
  check   the CI gate: exit 1 on any unsuppressed finding, 0 clean

Both write ``artifacts/static_analysis.json`` (override with
``--artifact``; ``--artifact ''`` skips) — the machine-readable map of
knob x run-shape threading the compose() refactor consumes, and the
artifact ``telemetry regress`` walks with an absolute findings==0 gate.

Exit codes: 0 clean, 1 findings (check only), 2 usage/input error
(bad root, malformed baseline) — stable for CI
(tests/test_analysis_cli.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from scalecube_cluster_tpu.analysis import engine

DEFAULT_ARTIFACT = os.path.join("artifacts", "static_analysis.json")


def _write_artifact(artifact: dict, path: str) -> None:
    if not path:
        return
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")


def _print_summary(result: engine.AnalysisResult, verbose: bool) -> None:
    n_entries = len(engine.ENTRY_POINTS)
    uniform = sum(
        1 for f in result.fields
        if len([e for e, cols in result.matrix["entries"][f].items()
                if cols]) in (0, n_entries)
    )
    print(f"# swimlint @ {result.root}")
    print(f"rules: {', '.join(result.rules_ran)}")
    print(f"plane matrix: {len(result.fields)} SwimParams knobs x "
          f"{n_entries} run shapes + {len(engine.TICK_BODIES)} tick "
          f"bodies + batch driver ({uniform}/{len(result.fields)} "
          f"knobs uniformly threaded)")
    if result.suppressed:
        print(f"suppressed (baselined): {len(result.suppressed)}")
        if verbose:
            for f in result.suppressed:
                print(f"  ~ {f.id}: {f.justification}")
    if result.findings:
        print(f"FINDINGS: {len(result.findings)}")
        for f in result.findings:
            anchor = f"{f.path}:{f.line}" if f.line else f.path
            print(f"  ! [{f.rule}] {anchor}: {f.message}")
    else:
        print("findings: none")


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scalecube_cluster_tpu.analysis",
        description=__doc__.splitlines()[0],
    )
    sub = parser.add_subparsers(dest="command", required=True)
    for name, help_ in (("report", "print matrix + findings, exit 0"),
                        ("check", "CI gate: exit 1 on findings")):
        p = sub.add_parser(name, help=help_)
        p.add_argument("--root", default=None,
                       help="package tree to analyze (default: the "
                            "installed scalecube_cluster_tpu)")
        p.add_argument("--baseline", default=None,
                       help="suppression file (default: the package's "
                            "analysis/baseline.json for the installed "
                            "root, none for a foreign --root tree)")
        p.add_argument("--artifact", default=None,
                       help=f"artifact path (default {DEFAULT_ARTIFACT} "
                            f"when analyzing the installed package, no "
                            f"artifact for a foreign --root tree — the "
                            f"committed artifact must never be clobbered "
                            f"by a mutation-debug run; '' skips writing)")
        p.add_argument("--no-compile", action="store_true",
                       help="AST rules only — skip the trace/recompile/"
                            "dtype audits")
        p.add_argument("--json", action="store_true",
                       help="print the artifact JSON instead of the "
                            "summary")
        p.add_argument("-v", "--verbose", action="store_true")
        p.set_defaults(mode=name)

    args = parser.parse_args(argv)
    try:
        result = engine.run_analysis(
            root=args.root, baseline=args.baseline,
            compile_audit=False if args.no_compile else None,
        )
    except (engine.BaselineError, FileNotFoundError, SyntaxError,
            ValueError, KeyError) as e:
        # KeyError: a parseable --root tree that is not this package
        # (no models/swim.py / no SwimParams) — input error, exit 2
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    artifact = result.to_artifact()
    artifact_path = args.artifact
    if artifact_path is None:
        # the DEFAULT path is the COMMITTED artifact: only a full run
        # on the installed tree may write it — a foreign --root tree or
        # an AST-only --no-compile pass would clobber the committed
        # compile-audit blocks (tests/test_analysis_cli.py pins both)
        full_run = (result.root == engine.default_root()
                    and not args.no_compile)
        artifact_path = DEFAULT_ARTIFACT if full_run else ""
    try:
        _write_artifact(artifact, artifact_path)
    except OSError as e:
        print(f"error: cannot write artifact {artifact_path}: {e}",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(artifact, sort_keys=True))
    else:
        _print_summary(result, args.verbose)
    if args.mode == "check" and not result.ok:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
