"""Compile-time audits: trace every run entry point at tiny N and
check the class of perf regression no unit test sees.

Three audits per entry point (the seven ENTRY_POINTS of
analysis/rules.py), all on one tiny compact-carry scatter config:

``host-callbacks``
    The traced program must contain ZERO host callback primitives
    (``pure_callback``/``io_callback``/``debug_callback``/...): one
    stray ``jax.debug.print`` or host hook in the scan body turns every
    round into a device->host round trip and silently serializes the
    hot loop.

``carry-dtype``
    With ``compact_carry=True`` the scan carry's int16/int8 lanes must
    STAY int16/int8: the audit counts narrow-lane avals in the traced
    scan carry and fails if any lane widened (and if any carry aval is
    int64/float64 at all).  A widening here is the capacity regression
    the compact layout exists to prevent — it doubles the [N, K] carry
    bytes without failing a single numeric test.

``recompile``
    A second call with identical shapes/statics must be a compile-cache
    HIT (the jitted entry's miss counter does not move).  An unhashable
    static, a fresh non-``eq`` params object per call, or an
    accidentally-dynamic Python value in the signature shows up as a
    recompile — in production that is a multi-second stall every
    checkpoint segment.

The audits run the REAL installed package (they import and trace it),
so the engine only schedules them when the analysis root is the
installed package tree; AST-only runs on copies (the mutation tests)
skip them with a note in the artifact.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from scalecube_cluster_tpu.analysis.rules import ENTRY_POINTS, Finding

TINY_N = 8
TINY_ROUNDS = 3

_CALLBACK_MARKERS = ("callback", "outside_call", "infeed", "outfeed")


def _tiny_setup():
    import jax

    from scalecube_cluster_tpu import config
    from scalecube_cluster_tpu.models import swim

    cfg = config.ClusterConfig.default().replace(
        gossip_interval=100, ping_interval=200, ping_timeout=100,
        sync_interval=1_000, suspicion_mult=3,
    )
    # compact carry: the layout whose narrow lanes the dtype audit pins;
    # scatter delivery so the sharded/pipelined entries run the same
    # config (k_block/shift variants are covered by the AST matrix).
    params = swim.SwimParams.from_config(cfg, n_members=TINY_N,
                                         compact_carry=True)
    world = swim.SwimWorld.healthy(params)
    key = jax.random.PRNGKey(0)
    return params, world, key


def _drivers(params, world, key):
    """name -> (jitted entry object, zero-arg call thunk).  Thunks pass
    identical arguments every call, so the second invocation must be a
    cache hit."""
    from scalecube_cluster_tpu.chaos import monitor
    from scalecube_cluster_tpu.models import swim
    from scalecube_cluster_tpu.parallel import compat
    from scalecube_cluster_tpu.parallel import mesh as pmesh

    spec = monitor.MonitorSpec.passive(params)
    n = TINY_ROUNDS
    if compat.HAS_SHARD_MAP:
        mesh = pmesh.make_mesh(1)
        sharded = {
            "shard_run": (
                pmesh.shard_run,
                lambda: pmesh.shard_run(key, params, world, n, mesh)),
            "shard_run_metered": (
                pmesh.shard_run_metered,
                lambda: pmesh.shard_run_metered(key, params, world, n,
                                                mesh)),
        }
    else:
        # legacy JAX without shard_map: the sharded suites all skip
        # (parallel/compat.py) — the audit records the same skip
        # instead of a false red
        sharded = {"shard_run": compat.SKIP_REASON,
                   "shard_run_metered": compat.SKIP_REASON}
    return {
        "run": (swim.run,
                lambda: swim.run(key, params, world, n)),
        "run_traced": (swim.run_traced,
                       lambda: swim.run_traced(key, params, world, n)),
        "run_metered": (swim.run_metered,
                        lambda: swim.run_metered(key, params, world, n)),
        "run_monitored": (
            monitor.run_monitored,
            lambda: monitor.run_monitored(key, params, world, spec, n)),
        "run_monitored_metered": (
            monitor.run_monitored_metered,
            lambda: monitor.run_monitored_metered(key, params, world,
                                                  spec, n)),
        **sharded,
    }


def _iter_eqns(jaxpr):
    """Every eqn in a jaxpr, recursing through pjit/scan/cond/shard_map
    sub-jaxprs carried in eqn params."""
    stack = [jaxpr]
    seen = set()
    while stack:
        jx = stack.pop()
        if id(jx) in seen:
            continue
        seen.add(id(jx))
        for eqn in jx.eqns:
            yield eqn
            for val in eqn.params.values():
                stack.extend(_sub_jaxprs(val))


def _sub_jaxprs(val):
    out = []
    if hasattr(val, "jaxpr") and hasattr(val.jaxpr, "eqns"):
        out.append(val.jaxpr)          # ClosedJaxpr
    elif hasattr(val, "eqns"):
        out.append(val)                # raw Jaxpr
    elif isinstance(val, (list, tuple)):
        for item in val:
            out.extend(_sub_jaxprs(item))
    return out


def _scan_carry_avals(jaxpr):
    """[(aval, ...)] for each scan eqn's carry block."""
    carries = []
    for eqn in _iter_eqns(jaxpr):
        if eqn.primitive.name != "scan":
            continue
        nc = eqn.params.get("num_consts", 0)
        ncar = eqn.params.get("num_carry", 0)
        if ncar:
            carries.append([v.aval for v in
                            eqn.invars[nc:nc + ncar]])
    return carries


def _narrow_counts(tree) -> Tuple[int, int]:
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    n16 = sum(1 for leaf in leaves if leaf.dtype == jnp.int16)
    n8 = sum(1 for leaf in leaves if leaf.dtype == jnp.int8)
    return n16, n8


def run_compile_audit(entries: Optional[Sequence[str]] = None
                      ) -> Tuple[dict, List[Finding]]:
    """Returns ``(report, findings)``; ``report`` is the per-entry
    artifact block, ``findings`` is empty when all audits pass."""
    import jax
    import jax.numpy as jnp

    from scalecube_cluster_tpu.models import swim

    params, world, key = _tiny_setup()
    drivers = _drivers(params, world, key)
    names = list(entries) if entries is not None else list(ENTRY_POINTS)
    unknown = sorted(set(names) - set(drivers))
    if unknown:
        raise ValueError(f"unknown compile-audit entries: {unknown}")

    exp16, exp8 = _narrow_counts(swim.initial_state(params, world))
    report: Dict[str, dict] = {}
    findings: List[Finding] = []

    def fail(entry, check, message):
        findings.append(Finding(
            rule="compile-audit",
            id=f"compile-audit:{entry}:{check}",
            path=ENTRY_POINTS[entry][0], line=0,
            message=f"[{entry}] {message}",
        ))

    for entry in names:
        if isinstance(drivers[entry], str):
            # environment cannot run this entry at all (e.g. no
            # shard_map): a skip, not a red — matches the test suites
            report[entry] = {"ok": True, "skipped": drivers[entry]}
            continue
        jitted, thunk = drivers[entry]
        row: dict = {}
        report[entry] = row
        try:
            jaxpr = jax.make_jaxpr(lambda: thunk())()

            callbacks = sorted({eqn.primitive.name
                                for eqn in _iter_eqns(jaxpr.jaxpr)
                                if any(m in eqn.primitive.name
                                       for m in _CALLBACK_MARKERS)})
            row["host_callbacks"] = callbacks
            if callbacks:
                fail(entry, "host-callbacks",
                     f"host callback primitives in the traced program: "
                     f"{callbacks} — every round pays a device->host "
                     f"round trip")

            carries = _scan_carry_avals(jaxpr.jaxpr)
            wide = sorted({str(a.dtype) for c in carries for a in c
                           if str(a.dtype) in ("int64", "float64")})
            best16 = max((sum(1 for a in c if a.dtype == jnp.int16)
                          for c in carries), default=0)
            best8 = max((sum(1 for a in c if a.dtype == jnp.int8)
                         for c in carries), default=0)
            row["scan_carry"] = {
                "scans": len(carries),
                "int16_lanes": best16, "int16_expected": exp16,
                "int8_lanes": best8, "int8_expected": exp8,
                "wide_dtypes": wide,
            }
            # distinct check slugs per failure mode: the finding id is
            # the baseline key, so two different defects must never
            # share one id (engine._collapse_duplicate_ids would merge
            # them into a flapping ':x2')
            if not carries:
                fail(entry, "carry-scan-missing",
                     "no scan with a carry found in the traced program "
                     "— the hot loop moved; update the audit")
            else:
                if wide:
                    fail(entry, "carry-dtype-wide",
                         f"64-bit dtypes in the scan carry: {wide}")
                if best16 < exp16 or best8 < exp8:
                    fail(entry, "carry-dtype-narrowed-lanes-lost",
                         f"compact int16/int8 lanes widened in the scan "
                         f"carry: {best16}/{exp16} int16 and "
                         f"{best8}/{exp8} int8 lanes survive — the "
                         f"compact layout is paying wide-carry HBM")

            if hasattr(jitted, "_cache_size"):
                before = jitted._cache_size()
                jax.block_until_ready(thunk())
                after_first = jitted._cache_size()
                jax.block_until_ready(thunk())
                after_second = jitted._cache_size()
                row["recompile"] = {
                    "first_call_misses": after_first - before,
                    "second_call_misses": after_second - after_first,
                }
                if after_second != after_first:
                    fail(entry, "recompile",
                         f"second same-shape call recompiled "
                         f"({after_second - after_first} new cache "
                         f"entries) — a static argument is not "
                         f"hash-stable")
            else:  # pragma: no cover - older/newer jax without the API
                row["recompile"] = {"skipped": "no _cache_size API"}
            row["ok"] = not any(f.id.startswith(f"compile-audit:{entry}:")
                                for f in findings)
        except Exception as e:  # noqa: BLE001 - audit must report, not die
            row["ok"] = False
            row["error"] = f"{type(e).__name__}: {e}"
            fail(entry, "error",
                 f"audit raised {type(e).__name__}: {e}")
    return report, findings
