"""swimlint's driver: run the rules, apply the baseline, build the
artifact.

The baseline file (``analysis/baseline.json`` next to this module by
default) is the ONLY suppression mechanism: a JSON list of
``{"id", "justification"}`` rows, one per finding that is *intended*
(a scatter-only wire knob has no shift-body threading site — that is
the design, and the justification says so in one line).  The contract
(tests/test_analysis_cli.py):

  - a suppression with an empty/missing justification is an INPUT
    error (exit 2) — zero unexplained suppressions can be committed;
  - a suppression whose finding no longer exists is itself a finding
    (``baseline:stale:...``) when its rule ran — a fixed asymmetry must
    leave the baseline, or the file silently grows dead weight that
    would mask a regression under the same id;
  - suppressed findings stay in the artifact (``suppressed: true``)
    so the matrix map stays complete for the compose() refactor.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Dict, List, Optional

from scalecube_cluster_tpu.analysis import rules as rules_mod
from scalecube_cluster_tpu.analysis.callgraph import PackageGraph
from scalecube_cluster_tpu.analysis.rules import (
    ENTRY_POINTS, MATRIX_SITE_CAP, TICK_BODIES, Finding,
)

SCHEMA = "swimlint/1"


class BaselineError(ValueError):
    """Malformed baseline file — an input error (CLI exit 2), never a
    findings exit (1)."""


def default_root() -> pathlib.Path:
    """The installed package directory (the tree ``check`` audits)."""
    return pathlib.Path(__file__).resolve().parents[1]


def default_baseline_path() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path) -> Dict[str, str]:
    """id -> justification.  Missing file = empty baseline."""
    path = pathlib.Path(path)
    if not path.exists():
        return {}
    try:
        doc = json.loads(path.read_text())
    except json.JSONDecodeError as e:
        raise BaselineError(f"baseline {path}: not valid JSON: {e}") from e
    rows = doc.get("suppressions") if isinstance(doc, dict) else None
    if not isinstance(rows, list):
        raise BaselineError(
            f"baseline {path}: expected {{'suppressions': [...]}}"
        )
    out: Dict[str, str] = {}
    for i, row in enumerate(rows):
        if not isinstance(row, dict) or not row.get("id"):
            raise BaselineError(f"baseline {path}: row {i} has no 'id'")
        just = row.get("justification")
        if not isinstance(just, str) or not just.strip():
            raise BaselineError(
                f"baseline {path}: suppression {row['id']!r} has no "
                f"justification — zero unexplained suppressions "
                f"(analysis/engine.py docstring)"
            )
        if row["id"] in out:
            raise BaselineError(
                f"baseline {path}: duplicate suppression {row['id']!r}"
            )
        out[row["id"]] = just.strip()
    return out


def _collapse_duplicate_ids(findings: List[Finding]) -> List[Finding]:
    """One finding per id; k > 1 same-id occurrences collapse into one
    whose id gains an ``:x<k>`` suffix.  This is what keeps a baseline
    suppression from silently absorbing FUTURE occurrences: a second
    hand-copied literal in the same file changes the id (``...:x2``),
    so the committed suppression goes stale (its own finding) and the
    new occurrence surfaces unsuppressed."""
    groups: Dict[str, List[Finding]] = {}
    for f in findings:
        groups.setdefault(f.id, []).append(f)
    out: List[Finding] = []
    for fid, group in groups.items():
        if len(group) == 1:
            out.append(group[0])
            continue
        first = group[0]
        lines = sorted({g.line for g in group if g.line})
        first.id = f"{fid}:x{len(group)}"
        first.message += (f" [{len(group)} occurrences"
                          + (f": lines {', '.join(map(str, lines))}"
                             if lines else "") + "]")
        out.append(first)
    return out


@dataclasses.dataclass
class AnalysisResult:
    root: pathlib.Path
    fields: List[str]
    matrix: dict
    findings: List[Finding]          # unsuppressed
    suppressed: List[Finding]
    compile_report: dict
    rules_ran: List[str]

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_artifact(self) -> dict:
        def cell(sites: List[str]) -> dict:
            return {"count": len(sites), "sites": sites[:MATRIX_SITE_CAP]}

        matrix = {
            group: {f: {col: cell(sites) for col, sites in cols.items()}
                    for f, cols in per_field.items()}
            for group, per_field in self.matrix.items()
        }
        return {
            "schema": SCHEMA,
            "metric": "static_analysis",
            "generated_by": "python -m scalecube_cluster_tpu.analysis",
            "root": self.root.name,
            "rules": self.rules_ran,
            "fields": self.fields,
            "entry_points": list(ENTRY_POINTS),
            "tick_bodies": list(TICK_BODIES),
            "matrix": matrix,
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [f.to_json() for f in self.suppressed],
            "findings_total": len(self.findings),
            "suppressed_total": len(self.suppressed),
            "compile_audit": self.compile_report,
            "ok": self.ok,
        }


def run_analysis(root=None, baseline=None,
                 compile_audit: Optional[bool] = None) -> AnalysisResult:
    """Run every rule over ``root`` and fold in the baseline.

    ``compile_audit=None`` auto-selects: the audits trace the IMPORTED
    package, so they only run when ``root`` is the installed tree;
    ``True`` insists (raises on a foreign root), ``False`` skips.
    """
    root = pathlib.Path(root).resolve() if root is not None \
        else default_root()
    if baseline is not None:
        baseline_map = load_baseline(baseline)
    elif root == default_root():
        baseline_map = load_baseline(default_baseline_path())
    else:
        # a foreign root (a mutated copy, a fixture tree) has its own
        # asymmetries: the installed package's suppressions would all
        # read as stale there — default to no baseline instead
        baseline_map = {}

    graph = PackageGraph(root)
    matrix, findings = rules_mod.plane_matrix(graph)
    findings += rules_mod.thin_entries(graph)
    findings += rules_mod.trace_safety(graph)
    findings += rules_mod.donation_safety(graph)
    findings += rules_mod.magic_literals(graph)
    rules_ran = ["plane-matrix", "thin-entry", "trace-safety",
                 "donation-safety", "magic-literal"]

    is_installed_tree = root == default_root()
    if compile_audit is True and not is_installed_tree:
        raise ValueError(
            f"compile audit traces the imported package; root {root} is "
            f"not the installed tree {default_root()}"
        )
    do_compile = (compile_audit if compile_audit is not None
                  else is_installed_tree)
    if do_compile:
        from scalecube_cluster_tpu.analysis.compile_audit import (
            run_compile_audit,
        )

        # always the full seven-entry audit: a partial audit would make
        # the stale-baseline check lie about unaudited entries
        compile_report, compile_findings = run_compile_audit()
        findings += compile_findings
        rules_ran.append("compile-audit")
    else:
        compile_report = {
            "skipped": ("foreign analysis root — AST rules only"
                        if not is_installed_tree else "disabled"),
        }

    findings = _collapse_duplicate_ids(findings)

    # Fold the baseline: split suppressed findings out, then flag
    # baseline rows whose finding no longer exists (only for rules that
    # actually ran — a --no-compile run must not call compile-audit
    # suppressions stale).
    seen_ids = {f.id for f in findings}
    live, suppressed = [], []
    for f in findings:
        if f.id in baseline_map:
            f.suppressed = True
            f.justification = baseline_map[f.id]
            suppressed.append(f)
        else:
            live.append(f)
    for bid, just in sorted(baseline_map.items()):
        rule = bid.split(":", 1)[0]
        if bid not in seen_ids and rule in rules_ran:
            live.append(Finding(
                rule="baseline", id=f"baseline:stale:{bid}",
                path="analysis/baseline.json", line=0,
                message=(
                    f"baseline suppresses {bid!r} but the finding no "
                    f"longer exists — remove the row (justification "
                    f"was: {just})"
                ),
            ))

    fields = graph.dataclass_fields(rules_mod.PARAMS_MODULE,
                                    rules_mod.PARAMS_CLASS)
    return AnalysisResult(
        root=root, fields=fields, matrix=matrix, findings=live,
        suppressed=suppressed, compile_report=compile_report,
        rules_ran=rules_ran,
    )
