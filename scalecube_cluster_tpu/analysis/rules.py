"""swimlint's AST rules.

Four rule families over the :class:`~.callgraph.PackageGraph`:

``plane-matrix``
    The headline rule.  Rows = every ``SwimParams`` field, statically
    extracted from the dataclass (no hand-maintained knob list to rot);
    columns = the seven run entry points and the four tick-body
    variants.  A cell holds the consultation sites (``params.<knob>``
    reads) reachable from that column's root cone.  A knob consulted in
    SOME run shapes but not others is exactly the "28 files per plane"
    hazard ROADMAP item 1 warns about — a plane that silently does not
    exist on one path — and fails ``check``.  Within the tick-body
    group: the three whole-tick bodies (scatter / shift / k_block) must
    agree with each other, and the pipelined send/recv pair — which IS
    the scatter tick split in half — must consult at least everything
    the scatter body does.  Intended asymmetries (a scatter-only wire
    knob, a shift-only capacity knob) are not bugs; they live in the
    baseline file with a one-line justification each, so a NEW
    asymmetry still fires.

``trace-safety``
    Host nondeterminism and host-sync coercions in the device modules
    (``models/``, ``ops/``, ``chaos/monitor.py``, ``parallel/mesh.py``):
    ``time.time``/``random``/``np.random``/``datetime.now`` anywhere in
    those modules, and ``.item()``/``.tolist()``/``float(jnp...)``-style
    forced synchronization inside the *device cone* — the functions
    reachable from the seven entry points, i.e. code that runs under
    trace where such a call is either a tracer error waiting for the
    right branch or a silent per-round host round-trip.

``donation-safety``
    A buffer passed through a ``donate_argnums``/``donate_argnames``
    jit boundary is gone — XLA reuses its memory for the output (and
    current XLA donates on CPU too: models/swim.run docstring).  The
    rule finds call sites of donating functions and flags reads of a
    donated argument that follow the call in SOURCE order (up to and
    including a rebind line's RHS).  Source order is the documented
    approximation: a loop-carried read textually ABOVE the donating
    call (iteration 2 reading iteration 1's donated buffer) is not
    flagged — rebind-per-iteration, the repo-wide donation idiom, is
    what the rule enforces on the lines it can see.

``magic-literal``
    The generalized PR-13 constant audit: each constant family (wire
    saturation points, carry dtype bounds, identity-epoch widths,
    monitor invariant codes) has ONE owning table; an evaluated literal
    from a family appearing in code outside its owning files is a
    hand-copied constant waiting to drift.  Token-level, like the
    tests/test_wire_constants.py grep-proof this rule absorbed —
    comments and docstrings may cite the numbers (documentation is not
    a clamp site).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from scalecube_cluster_tpu.analysis.callgraph import PackageGraph


@dataclasses.dataclass
class Finding:
    rule: str
    id: str            # stable across unrelated edits (no line numbers)
    path: str          # module path relative to the analysis root
    line: int          # best-effort anchor for humans (0 = whole file)
    message: str
    suppressed: bool = False
    justification: Optional[str] = None

    def to_json(self) -> dict:
        d = {"rule": self.rule, "id": self.id, "path": self.path,
             "line": self.line, "message": self.message}
        if self.suppressed:
            d["suppressed"] = True
            d["justification"] = self.justification
        return d


# --------------------------------------------------------------------------
# Roots: the seven run entry points and the tick-body variants
# --------------------------------------------------------------------------

PARAMS_MODULE = "models/swim.py"
PARAMS_CLASS = "SwimParams"

ENTRY_POINTS: Dict[str, Tuple[str, str]] = {
    "run": ("models/swim.py", "run"),
    "run_traced": ("models/swim.py", "run_traced"),
    "run_metered": ("models/swim.py", "run_metered"),
    "run_monitored": ("chaos/monitor.py", "run_monitored"),
    "run_monitored_metered": ("chaos/monitor.py", "run_monitored_metered"),
    "shard_run": ("parallel/mesh.py", "shard_run"),
    "shard_run_metered": ("parallel/mesh.py", "shard_run_metered"),
}

# The three sibling whole-tick bodies swim_tick dispatches between, and
# the pipelined half-tick pair (= the scatter tick split at the
# send/recv boundary, parallel/mesh._pipelined_rounds).
TICK_BODIES: Dict[str, Sequence[Tuple[str, str]]] = {
    "scatter": (("models/swim.py", "_tick_scatter"),),
    "shift": (("models/swim.py", "_tick_shift"),),
    "k_block": (("models/swim.py", "_tick_shift_blocked"),),
    "pipelined": (("models/swim.py", "swim_tick_send"),
                  ("models/swim.py", "swim_tick_recv")),
}

# Whole-tick bodies compared against each other for completeness;
# "pipelined" is handled as a superset check against "scatter".
WHOLE_TICK_BODIES = ("scatter", "shift", "k_block")

# The composed plane runner's scan drivers (models/compose.py): every
# entry point is a thin alias over one of these, so every knob that
# reaches ANY run shape must be consultable from their cones — a knob
# threaded around compose() instead of through it is the hand-threading
# regression the refactor exists to end.
COMPOSE_ROOTS: Sequence[Tuple[str, str]] = (
    ("models/compose.py", "composed_scan"),
    ("models/compose.py", "composed_shard_scan"),
)
COMPOSE_MODULE = "models/compose.py"

# The batched scan driver (PR 17): one more run shape, one more matrix
# column — a knob consulted by ANY run shape but unreachable from
# composed_batch_scan is a plane the batch axis silently ignores (the
# tune sweep would report identical SLOs for every setting of it).
BATCH_ROOTS: Sequence[Tuple[str, str]] = (
    ("models/compose.py", "composed_batch_scan"),
)

# Batch entry points: thin aliases over composed_batch_scan, held to
# the same thin-entry rule as the seven plain entries (and counted
# into the trace-safety device cone).
BATCH_ENTRY_POINTS: Dict[str, Tuple[str, str]] = {
    "run_monitored_batch": ("chaos/monitor.py", "run_monitored_batch"),
}

# Supervised entry points (PR 18): long-lived drivers that must reach
# the compose scan ONLY through the resilient supervisor — they
# assemble a workload and delegate to resilience/supervisor.py
# (run_resilient owns the segment loop, journal, and checkpoint
# discipline), and may touch neither a scan/tick internal nor a
# models/compose.py driver directly: a soak that bypassed the
# supervisor would lose the exactly-once journal contract its drift
# invariants are defined over.
SUPERVISOR_MODULE = "resilience/supervisor.py"
SUPERVISED_ENTRY_POINTS: Dict[str, Tuple[str, str]] = {
    "run_soak": ("soak/driver.py", "run_soak"),
}

# Scan/tick internals a THIN alias entry point must never touch
# directly — tick-body logic lives in compose.py and the plane
# modules, entries only assemble a plane stack and delegate
# (the thin-entry rule).
TICK_INTERNALS: Sequence[Tuple[str, str]] = (
    ("models/swim.py", "swim_tick"),
    ("models/swim.py", "swim_tick_send"),
    ("models/swim.py", "swim_tick_recv"),
    ("models/swim.py", "_fused_scan"),
    ("models/swim.py", "_tick_scatter"),
    ("models/swim.py", "_tick_shift"),
    ("models/swim.py", "_tick_shift_blocked"),
    ("models/compose.py", "_pipelined_rounds"),
    ("telemetry/trace.py", "observe_round"),
    ("telemetry/trace.py", "observe_round_codes"),
    ("telemetry/metrics.py", "observe_tick"),
    ("models/provenance.py", "observe_round"),
    ("chaos/monitor.py", "check_round"),
)

DEVICE_MODULES_PREFIXES = ("models/", "ops/")
DEVICE_MODULES_FILES = ("chaos/monitor.py", "parallel/mesh.py")

MATRIX_SITE_CAP = 8  # sites listed per artifact cell (count is exact)


def _is_device_module(rel: str) -> bool:
    return (rel.startswith(DEVICE_MODULES_PREFIXES)
            or rel in DEVICE_MODULES_FILES)


def _resolve_roots(graph: PackageGraph, roots: Iterable[Tuple[str, str]],
                   strict: bool = True) -> List[str]:
    out = []
    for rel, name in roots:
        qual = graph.find(rel, name)
        if qual is None:
            if strict:
                raise ValueError(
                    f"plane-matrix root {rel}::{name} not found under "
                    f"{graph.root} — the seven-entry-point contract "
                    f"moved; update analysis/rules.py "
                    f"ENTRY_POINTS/TICK_BODIES"
                )
            continue
        out.append(qual)
    return out


# --------------------------------------------------------------------------
# Rule 1: plane-threading completeness matrix
# --------------------------------------------------------------------------

def _column_sites(graph: PackageGraph, roots: List[str],
                  fields: Set[str]) -> Dict[str, List[Tuple[str, int]]]:
    sites: Dict[str, List[Tuple[str, int]]] = {}
    for qual in sorted(graph.cone(roots)):
        for field, rel, line in graph.consult_sites(qual, fields):
            sites.setdefault(field, []).append((rel, line))
    for field in sites:
        sites[field] = sorted(set(sites[field]))
    return sites


def plane_matrix(graph: PackageGraph):
    """Returns ``(matrix, findings)``.

    ``matrix`` = {"entries": {field: {entry: [sites]}},
    "bodies": {field: {body: [sites]}}} with sites as "rel:line"
    strings — the machine-readable map of what the compose() refactor
    must preserve (emitted into artifacts/static_analysis.json).
    """
    fields = graph.dataclass_fields(PARAMS_MODULE, PARAMS_CLASS)
    fset = set(fields)

    entry_cols = {name: _column_sites(graph, _resolve_roots(graph, [spec]),
                                      fset)
                  for name, spec in ENTRY_POINTS.items()}
    body_cols = {name: _column_sites(graph, _resolve_roots(graph, specs),
                                     fset)
                 for name, specs in TICK_BODIES.items()}
    compose_col = _column_sites(
        graph, _resolve_roots(graph, COMPOSE_ROOTS), fset)
    batch_col = _column_sites(
        graph, _resolve_roots(graph, BATCH_ROOTS), fset)

    matrix = {
        "entries": {f: {e: [f"{r}:{ln}" for r, ln in entry_cols[e].get(f, [])]
                        for e in ENTRY_POINTS}
                    for f in fields},
        "bodies": {f: {b: [f"{r}:{ln}" for r, ln in body_cols[b].get(f, [])]
                       for b in TICK_BODIES}
                   for f in fields},
        "compose": {f: {"compose": [f"{r}:{ln}"
                                    for r, ln in compose_col.get(f, [])]}
                    for f in fields},
        "batch": {f: {"batch": [f"{r}:{ln}"
                                for r, ln in batch_col.get(f, [])]}
                  for f in fields},
    }

    findings: List[Finding] = []
    for f in fields:
        reached = {e for e in ENTRY_POINTS if entry_cols[e].get(f)}
        # Every knob any run shape consults must be reachable from the
        # composed scan drivers — the seven entries are thin aliases,
        # so a consult that exists only outside compose's cone is a
        # plane threaded around the runner, not through it.
        if reached and not compose_col.get(f):
            findings.append(Finding(
                rule="plane-matrix",
                id=f"plane-matrix:{f}:compose",
                path=COMPOSE_ROOTS[0][0], line=0,
                message=(
                    f"SwimParams.{f} is consulted on the "
                    f"{'/'.join(sorted(reached))} run shape(s) but "
                    f"nothing reachable from the composed scan drivers "
                    f"({'/'.join(n for _, n in COMPOSE_ROOTS)}) reads "
                    f"it — the plane bypasses compose()"
                ),
            ))
        # ... and from the batched driver too: the batch axis runs the
        # same tick, so a knob any run shape consults that is
        # unreachable from composed_batch_scan is a plane the (knobs ×
        # scenarios) sweep cannot observe.
        if reached and not batch_col.get(f):
            findings.append(Finding(
                rule="plane-matrix",
                id=f"plane-matrix:{f}:batch",
                path=BATCH_ROOTS[0][0], line=0,
                message=(
                    f"SwimParams.{f} is consulted on the "
                    f"{'/'.join(sorted(reached))} run shape(s) but "
                    f"nothing reachable from the batched scan driver "
                    f"({'/'.join(n for _, n in BATCH_ROOTS)}) reads "
                    f"it — the batch axis bypasses the plane"
                ),
            ))
        if reached and reached != set(ENTRY_POINTS):
            for e in sorted(set(ENTRY_POINTS) - reached):
                where = sorted(reached)
                findings.append(Finding(
                    rule="plane-matrix",
                    id=f"plane-matrix:{f}:entry:{e}",
                    path=ENTRY_POINTS[e][0], line=0,
                    message=(
                        f"SwimParams.{f} is consulted on the "
                        f"{'/'.join(where)} run shape(s) but nothing "
                        f"reachable from {e} reads it — the plane does "
                        f"not exist on that path"
                    ),
                ))
        body_reached = {b for b in WHOLE_TICK_BODIES if body_cols[b].get(f)}
        if body_reached and body_reached != set(WHOLE_TICK_BODIES):
            for b in sorted(set(WHOLE_TICK_BODIES) - body_reached):
                findings.append(Finding(
                    rule="plane-matrix",
                    id=f"plane-matrix:{f}:body:{b}",
                    path=TICK_BODIES[b][0][0], line=0,
                    message=(
                        f"SwimParams.{f} is consulted in the "
                        f"{'/'.join(sorted(body_reached))} tick body(ies) "
                        f"but not in the {b} body's cone — a plane "
                        f"threaded through some delivery modes only"
                    ),
                ))
        # The pipelined halves ARE the scatter tick split in two: every
        # knob the scatter body consults must survive the split.
        if body_cols["scatter"].get(f) and not body_cols["pipelined"].get(f):
            findings.append(Finding(
                rule="plane-matrix",
                id=f"plane-matrix:{f}:body:pipelined",
                path=TICK_BODIES["pipelined"][0][0], line=0,
                message=(
                    f"SwimParams.{f} is consulted in the scatter tick "
                    f"body but not in the pipelined send/recv halves — "
                    f"the knob was lost in the half-tick split"
                ),
            ))
    return matrix, findings


# --------------------------------------------------------------------------
# Rule 1b: thin-entry — no tick-body logic outside compose/plane modules
# --------------------------------------------------------------------------

def thin_entries(graph: PackageGraph) -> List[Finding]:
    """Each of the seven run entry points — and each batch entry
    (``BATCH_ENTRY_POINTS``) — must be a THIN alias: it assembles a
    plane stack and delegates to a models/compose.py scan
    driver, and neither its own body nor a same-module plain-function
    helper it directly calls (the ``shard_run`` -> shard_map plumbing
    shape) may mention a scan/tick internal (``TICK_INTERNALS``) —
    tick-body logic lives in compose.py and the plane modules only.

    Supervised entries (``SUPERVISED_ENTRY_POINTS`` — the soak
    driver) invert the delegation target: they must reach
    resilience/supervisor.py (which owns the compose delegation), and
    a DIRECT edge into models/compose.py or a tick internal is itself
    the finding — the supervisor's journal/checkpoint discipline is
    not optional for a long-lived run.

    Lenient on missing roots (fixture trees may define a subset — the
    plane matrix is the strict guardian of the seven-entry contract).
    """
    internals = {q for rel, name in TICK_INTERNALS
                 if (q := graph.find(rel, name)) is not None}
    findings: List[Finding] = []
    for entry, (rel, name) in {**ENTRY_POINTS,
                               **BATCH_ENTRY_POINTS}.items():
        qual = graph.find(rel, name)
        if qual is None:
            continue
        frontier = [qual]
        for tgt in sorted(graph._edges.get(qual, ())):
            info = graph.functions.get(tgt)
            if (info is not None and info.rel == rel and info.cls is None
                    and tgt not in internals):
                frontier.append(tgt)
        touches_compose = False
        emitted = set()  # one finding per (entry, internal) defect,
        #                  even when entry AND helper both reach it
        for q in frontier:
            for tgt in sorted(graph._edges.get(q, ())):
                info = graph.functions.get(tgt)
                if info is None:
                    continue
                if info.rel == COMPOSE_MODULE \
                        and tgt not in internals:
                    touches_compose = True
                if tgt in internals:
                    fid = f"thin-entry:{entry}:{info.name}"
                    if fid in emitted:
                        continue
                    emitted.add(fid)
                    findings.append(Finding(
                        rule="thin-entry",
                        id=fid,
                        path=rel,
                        line=graph.functions[qual].node.lineno,
                        message=(
                            f"entry point {entry} reaches the scan/tick "
                            f"internal {info.rel}::{info.name} directly "
                            f"(via {graph.functions[q].name}) — tick-"
                            f"body logic belongs in models/compose.py "
                            f"or a plane module; entries are thin "
                            f"aliases"
                        ),
                    ))
        if not touches_compose:
            findings.append(Finding(
                rule="thin-entry",
                id=f"thin-entry:{entry}:no-compose-delegation",
                path=rel, line=graph.functions[qual].node.lineno,
                message=(
                    f"entry point {entry} never delegates to a "
                    f"models/compose.py scan driver — every run shape "
                    f"is a thin alias over the composed runner"
                ),
            ))
    for entry, (rel, name) in SUPERVISED_ENTRY_POINTS.items():
        qual = graph.find(rel, name)
        if qual is None:
            continue
        frontier = [qual]
        for tgt in sorted(graph._edges.get(qual, ())):
            info = graph.functions.get(tgt)
            if (info is not None and info.rel == rel and info.cls is None
                    and tgt not in internals):
                frontier.append(tgt)
        touches_supervisor = False
        emitted = set()
        for q in frontier:
            for tgt in sorted(graph._edges.get(q, ())):
                info = graph.functions.get(tgt)
                if info is None:
                    continue
                if info.rel == SUPERVISOR_MODULE:
                    touches_supervisor = True
                if tgt in internals or info.rel == COMPOSE_MODULE:
                    fid = f"thin-entry:{entry}:{info.name}"
                    if fid in emitted:
                        continue
                    emitted.add(fid)
                    findings.append(Finding(
                        rule="thin-entry",
                        id=fid,
                        path=rel,
                        line=graph.functions[qual].node.lineno,
                        message=(
                            f"supervised entry {entry} reaches "
                            f"{info.rel}::{info.name} directly (via "
                            f"{graph.functions[q].name}) — a "
                            f"long-lived driver delegates to the "
                            f"resilient supervisor, never to the scan "
                            f"or tick layer itself"
                        ),
                    ))
        if not touches_supervisor:
            findings.append(Finding(
                rule="thin-entry",
                id=f"thin-entry:{entry}:no-supervisor-delegation",
                path=rel, line=graph.functions[qual].node.lineno,
                message=(
                    f"supervised entry {entry} never delegates to "
                    f"resilience/supervisor.py — the segment loop, "
                    f"journal, and checkpoint discipline live there"
                ),
            ))
    return findings


# --------------------------------------------------------------------------
# Rule 2: trace-safety
# --------------------------------------------------------------------------

# Dotted external prefixes that mean host nondeterminism (a fresh value
# per trace, frozen into the compiled program — or a tracer error).
BANNED_EXTERN = (
    "random.", "numpy.random", "time.time", "time.time_ns",
    "time.perf_counter", "time.monotonic", "datetime.datetime.now",
    "datetime.datetime.utcnow", "secrets.", "uuid.uuid",
)

# Method calls that force device->host synchronization when the
# receiver is traced; only meaningful inside the device cone.
HOST_SYNC_METHODS = ("item", "tolist")
REDUCTION_METHODS = {"sum", "mean", "max", "min", "any", "all"}


def _dotted(graph: PackageGraph, mod, expr) -> Optional[str]:
    """Fully-dotted name of an Attribute/Name chain rooted at an
    external import alias, else None."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    root = mod.extern.get(expr.id)
    if root is None:
        return None
    return ".".join([root] + list(reversed(parts)))


def _mentions_traced_reduction(graph: PackageGraph, mod, node) -> bool:
    """True when the expression contains a jnp-rooted call or an
    array-reduction method call — the classic ``float(jnp.sum(x))`` /
    ``int(x.max())`` host-sync shapes."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = sub.func
            dotted = _dotted(graph, mod, fn)
            if dotted is not None and dotted.startswith(
                    ("jax.numpy", "jnp")):
                return True
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in REDUCTION_METHODS):
                return True
        elif isinstance(sub, ast.Attribute):
            dotted = _dotted(graph, mod, sub)
            if dotted is not None and dotted.startswith("jax.numpy"):
                return True
    return False


def trace_safety(graph: PackageGraph) -> List[Finding]:
    findings: List[Finding] = []
    # lenient: fixture trees (tests) may define only a subset of the
    # entry points — the plane matrix is the strict guardian of the
    # seven-entry contract
    entry_roots = _resolve_roots(
        graph,
        list(ENTRY_POINTS.values()) + list(BATCH_ENTRY_POINTS.values()),
        strict=False)
    device_cone = graph.cone(entry_roots)

    for qual, info in sorted(graph.functions.items()):
        if not _is_device_module(info.rel):
            continue
        mod = graph.modules[info.rel]
        in_cone = qual in device_cone
        for node in graph._mention_nodes(info.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(graph, mod, node.func)
            if dotted is not None and dotted.startswith(BANNED_EXTERN):
                findings.append(Finding(
                    rule="trace-safety",
                    id=f"trace-safety:{info.rel}:{info.name}:{dotted}",
                    path=info.rel, line=node.lineno,
                    message=(
                        f"{dotted}() in device module function "
                        f"{info.name} — host nondeterminism is frozen "
                        f"into the trace (draw through ops/prng.py "
                        f"instead)"
                    ),
                ))
                continue
            if not in_cone:
                continue  # host-side helper in a device module
            fn = node.func
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in HOST_SYNC_METHODS
                    and not node.args and not node.keywords):
                findings.append(Finding(
                    rule="trace-safety",
                    id=f"trace-safety:{info.rel}:{info.name}:.{fn.attr}",
                    path=info.rel, line=node.lineno,
                    message=(
                        f".{fn.attr}() inside {info.name}, which is "
                        f"reachable from the run entry points — a "
                        f"device->host sync (tracer error under jit)"
                    ),
                ))
            elif (isinstance(fn, ast.Name)
                  and fn.id in ("float", "int", "bool")
                  and fn.id not in mod.symbols
                  and node.args
                  and _mentions_traced_reduction(graph, mod,
                                                 node.args[0])):
                findings.append(Finding(
                    rule="trace-safety",
                    id=(f"trace-safety:{info.rel}:{info.name}:"
                        f"{fn.id}-coercion"),
                    path=info.rel, line=node.lineno,
                    message=(
                        f"{fn.id}() over an array reduction inside "
                        f"{info.name} (device cone) — host-sync "
                        f"coercion of a traced value"
                    ),
                ))
            elif (isinstance(fn, ast.Name) and fn.id == "print"
                  and "print" not in mod.symbols):
                findings.append(Finding(
                    rule="trace-safety",
                    id=f"trace-safety:{info.rel}:{info.name}:print",
                    path=info.rel, line=node.lineno,
                    message=(
                        f"print() inside {info.name} (device cone) — "
                        f"runs at trace time, not per round; use "
                        f"telemetry lanes or jax.debug off the hot path"
                    ),
                ))
    return findings


# --------------------------------------------------------------------------
# Rule 3: donation-safety
# --------------------------------------------------------------------------

def _donated_params(graph: PackageGraph
                    ) -> Dict[str, Tuple[List[str], Set[str]]]:
    """function QUALNAME -> (positional parameter names, donated
    parameter names), harvested from ``@partial(jax.jit, ...,
    donate_argnames/donate_argnums=...)`` decorators on package
    functions.  Keyed by qualname, not bare name: the package has
    several same-named ``run`` functions and only swim's donates —
    call sites resolve through the symbol table before matching."""
    donating: Dict[str, Tuple[List[str], Set[str]]] = {}
    for info in graph.functions.values():
        node = info.node
        arg_names = [a.arg for a in (node.args.posonlyargs
                                     + node.args.args)]
        for dec in node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            names: Set[str] = set()
            for kw in dec.keywords:
                if kw.arg == "donate_argnames":
                    names.update(
                        elt.value for elt in ast.walk(kw.value)
                        if isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str))
                elif kw.arg == "donate_argnums":
                    for elt in ast.walk(kw.value):
                        if (isinstance(elt, ast.Constant)
                                and isinstance(elt.value, int)
                                and not isinstance(elt.value, bool)
                                and elt.value < len(arg_names)):
                            names.add(arg_names[elt.value])
            if names:
                donating[info.qualname] = (arg_names, names)
    return donating


def donation_safety(graph: PackageGraph) -> List[Finding]:
    donating = _donated_params(graph)
    if not donating:
        return []
    findings: List[Finding] = []

    for qual, info in sorted(graph.functions.items()):
        mod = graph.modules[info.rel]
        # (donated var name, callee, call first line, call end position)
        donated: List[Tuple[str, str, int, Tuple[int, int]]] = []
        stores: Dict[str, List[int]] = {}
        loads: Dict[str, List[Tuple[int, int]]] = {}
        for node in graph._mention_nodes(info.node):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Store):
                    stores.setdefault(node.id, []).append(node.lineno)
                elif isinstance(node.ctx, ast.Load):
                    loads.setdefault(node.id, []).append(
                        (node.lineno, node.col_offset))
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Name):
                # `state += 1` READS the old buffer before storing:
                # the target is a load at its own position too (the
                # Store ctx above only sees the write half)
                loads.setdefault(node.target.id, []).append(
                    (node.target.lineno, node.target.col_offset))
            if not isinstance(node, ast.Call):
                continue
            # resolve the callee to a QUALNAME through the symbol
            # table (bare-name matching would confuse swim.run with
            # the non-donating fd.run/gossip.run)
            callee_qual = None
            if isinstance(node.func, ast.Name):
                sym = mod.symbols.get(node.func.id)
                if sym is not None and sym[0] == "func":
                    callee_qual = sym[1]
            elif isinstance(node.func, ast.Attribute):
                target_mod = graph.module_alias(mod, node.func.value)
                if target_mod is not None:
                    sym = graph.modules[target_mod].symbols.get(
                        node.func.attr)
                    if sym is not None and sym[0] == "func":
                        callee_qual = sym[1]
            if callee_qual not in donating or callee_qual == qual:
                continue
            callee = callee_qual.split("::", 1)[1]
            # loads inside the call expression (including the donated
            # argument itself) are part of the donation, not a
            # read-after — the window opens at the call's end POSITION
            # (line + column, so a read on the call's own closing line
            # still counts)
            call_end = (getattr(node, "end_lineno", node.lineno),
                        getattr(node, "end_col_offset", 1 << 30))
            param_names, donated_set = donating[callee_qual]
            args_bound: List[Tuple[str, ast.AST]] = [
                (param_names[i], a) for i, a in enumerate(node.args)
                if i < len(param_names)]
            args_bound += [(kw.arg, kw.value) for kw in node.keywords
                           if kw.arg is not None]
            for pname, val in args_bound:
                if pname in donated_set and isinstance(val, ast.Name):
                    donated.append((val.id, callee, node.lineno,
                                    call_end))
        for var, callee, call_line, call_end in donated:
            kills = [ln for ln in stores.get(var, []) if ln >= call_line]
            horizon = min(kills) if kills else float("inf")
            # loads BEYOND the rebind line read the new value; loads ON
            # the rebind line's RHS (pos[0] == horizon) execute before
            # the store and still read the donated buffer — flag them
            bad = [pos for pos in loads.get(var, [])
                   if pos > call_end and pos[0] <= horizon]
            if bad:
                bad.sort()
                findings.append(Finding(
                    rule="donation-safety",
                    id=f"donation-safety:{info.rel}:{info.name}:{var}",
                    path=info.rel, line=bad[0][0],
                    message=(
                        f"{info.name} passes `{var}` into {callee} "
                        f"(donated argument, line {call_line}) and reads "
                        f"it again at line {bad[0][0]} — the buffer was "
                        f"reused for the output; snapshot with "
                        f"jax.device_get first or rebind the name"
                    ),
                ))
    return findings


# --------------------------------------------------------------------------
# Rule 4: magic-literal families
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class LiteralFamily:
    name: str
    values: frozenset            # evaluated ints that must not reappear
    allowed: frozenset           # rel paths allowed to spell them out
    description: str


def default_literal_families() -> List[LiteralFamily]:
    """The owning-table contract, with values computed FROM the tables
    (import-time, never hand-copied here either)."""
    from scalecube_cluster_tpu.ops import delivery

    sat = set()
    for fmt in delivery.WIRE_FORMATS.values():
        sat.add(fmt.inc_sat(0))
        sat.add(fmt.inc_sat(fmt.epoch_bits))
    # int16 carry ceiling family, DERIVED (this file is scanned too:
    # spelling the bound out here would be its own finding)
    i16max = (1 << 15) - 1
    return [
        LiteralFamily(
            name="wire-saturation",
            values=frozenset(sat),
            allowed=frozenset({"ops/delivery.py", "records.py"}),
            description=(
                "incarnation saturation points of every wire-format "
                "rung x epoch width (ops/delivery.WIRE_FORMATS; derive "
                "via models/swim._wire_inc_sat)"
            ),
        ),
        LiteralFamily(
            name="carry-bound",
            values=frozenset({i16max, i16max - 1, i16max - 2}),
            allowed=frozenset({"models/swim.py"}),
            description=(
                "int16 compact-carry deadline bounds (models/swim.py "
                "owns the carry encoding and its validators)"
            ),
        ),
    ]


def magic_literals(graph: PackageGraph,
                   families: Optional[Sequence[LiteralFamily]] = None
                   ) -> List[Finding]:
    """Token-level family scan plus (on a full default run only) the
    symbolic monitor-code / epoch-width shape checks.  Passing an
    explicit ``families`` list narrows the rule to exactly those
    families — the tests/test_wire_constants.py contract."""
    symbolic = families is None
    if families is None:
        families = default_literal_families()
    findings: List[Finding] = []
    for rel in sorted(graph.modules):
        mod = graph.modules[rel]
        toks = list(tokenize.generate_tokens(
            io.StringIO(mod.path.read_text()).readline))
        for fam in families:
            if rel in fam.allowed:
                continue
            for tok in toks:
                if tok.type != tokenize.NUMBER:
                    continue
                try:
                    value = int(tok.string, 0)
                except ValueError:
                    continue
                if value in fam.values:
                    findings.append(Finding(
                        rule="magic-literal",
                        id=f"magic-literal:{fam.name}:{rel}:{value}",
                        path=rel, line=tok.start[0],
                        message=(
                            f"literal {value} ({fam.name}) outside its "
                            f"owning table "
                            f"({'/'.join(sorted(fam.allowed))}): "
                            f"{tok.line.strip()}"
                        ),
                    ))
    if not symbolic:
        return findings
    # Symbolic sub-checks: monitor codes and epoch widths are small
    # integers (can't be token-banned), so ban the *shapes* that
    # hard-code them instead.
    for rel in sorted(graph.modules):
        mod = graph.modules[rel]
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Compare) and rel != "chaos/monitor.py"
                    and isinstance(node.left, ast.Attribute)
                    and node.left.attr == "code"
                    and any(isinstance(c, ast.Constant)
                            and isinstance(c.value, int)
                            and not isinstance(c.value, bool)
                            for c in node.comparators)):
                findings.append(Finding(
                    rule="magic-literal",
                    id=f"magic-literal:monitor-code:{rel}",
                    path=rel, line=node.lineno,
                    message=(
                        "comparison of `.code` against a bare int — "
                        "use chaos/monitor.InvariantCode names"
                    ),
                ))
            elif (isinstance(node, ast.Call)
                  and rel not in ("ops/delivery.py",)
                  and any(kw.arg == "epoch_bits"
                          and isinstance(kw.value, ast.Constant)
                          and isinstance(kw.value.value, int)
                          and kw.value.value != 0
                          for kw in getattr(node, "keywords", []))):
                findings.append(Finding(
                    rule="magic-literal",
                    id=f"magic-literal:epoch-width:{rel}",
                    path=rel, line=node.lineno,
                    message=(
                        "literal epoch_bits= width outside "
                        "ops/delivery.py — widths come from "
                        "WireFormat.epoch_bits"
                    ),
                ))
    return findings
