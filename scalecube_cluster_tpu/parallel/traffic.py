"""Per-round collective-traffic model for the sharded SWIM tick.

SURVEY.md §5.8 promises an accounting of what the two delivery modes move
over ICI when the member rows are sharded across ``D`` devices
(``parallel/mesh.py``); this module is that accounting as executable
formulas, pinned to the actual tick by ``tests/test_traffic.py`` at two
levels: trace-time exchange counters, and the COMPILED program — the
lowered HLO of ``shard_run`` on the 8-device mesh is parsed and its
collective-permute / all-reduce counts and operand bytes asserted equal
to these formulas.

Shift mode (ops/shift.ShiftEngine)
----------------------------------
Every sharded ``deliver`` moves the device's whole local block twice
(rotations by ``d`` and ``d+1`` blocks — ShiftEngine docstring), i.e.
``2 * n_local * row_bytes`` sent per device per exchange, neighbor-to-
neighbor.  The tick performs, per round:

  - ``fanout + 2`` payload channels (gossip channels, SYNC, refute push),
    each delivering the packed-key buffer (``4K`` B/row) and the packed
    int8 transmit-mask buffer (``K`` B/row);
  - per gossip channel, the [N] hot-sender flags for message counting
    (1 B/row), and 2 deliveries of the [N] refuting-sender flags;
  - full-view contact gating adds one status delivery (``K`` B/row) per
    payload channel (models/swim._tick_shift ``gate_contacts``).

Per-device ICI bytes therefore scale as **O(n_local * K)** — they *shrink*
as devices are added at fixed N, so the shift path weak-scales: doubling
D halves both the per-device compute and the per-device ICI traffic.

Scatter mode (ops/delivery + lax.pmax)
--------------------------------------
Under the FUSED single-buffer wire (SwimParams.fused_wire, the default)
the inbox combine is ONE ``pmax`` over the full-height [N, K] packed-key
contribution buffer per round (per delay bin) — the ALIVE flag rides the
key word's own bits, so no flag buffer crosses ICI: 4 B/slot on the
wide wire vs the legacy two-buffer path's 5 (int32 key + int8 flag, 2
collectives per round; ``fused_wire=False`` keeps that path as the
bench.py --wire baseline, and each extra delay bin adds one more
collective per buffer).  A ring all-reduce sends ``2 * (D-1)/D * size``
per device, i.e. per-device ICI bytes are **O(N * K) — constant in D**.
Scatter mode is the validation path; at scale the shift path's
advantage grows linearly in D.

Pipelined scatter (parallel/mesh._pipelined_rounds)
---------------------------------------------------
The default sharded scatter path double-buffers the contribution: round
r's combine pair is carried into round r+1's scan body and combined
there, next to r+1's state-independent draw compute.  Per-round
collective COUNT and BYTES are identical to the serial path (the same
two buffers cross ICI once per round); what changes is placement — the
compiled program holds the per-round pair in the loop body (operand: the
carried buffer) plus one epilogue pair for the final round, so the
non-tuple full-height all-reduce instruction count doubles
(``pipelined_scatter_hlo_collectives``) while the per-round wire cost
(``scatter_ici_bytes_per_device_round``) is unchanged.  The payoff is
scheduling: XLA may now start the transfer under the next round's
compute instead of stalling the scan body on it.

DCN note: block rotations are neighbor exchanges on the device ring, so
on a multi-slice mesh only the rotations that cross a slice boundary pay
DCN — 2 boundary crossings per exchange regardless of D, giving per-device
DCN bytes ~ ``(2/D)`` of the ICI figure.  The scatter pmax is a full
all-reduce and pays DCN proportional to its whole buffer.  The crossover
is therefore immediate: for any D >= 2 the shift path moves less per
device, and the gap grows as D (matching the reference seam it replaces —
per-message TCP in TransportImpl.java:257-269 scales per-node traffic
with cluster-wide message volume, not cluster size).
"""

from __future__ import annotations

INT32 = 4
INT16 = 2
INT8 = 1


def _key_bytes(params) -> int:
    """Wire bytes per packed record key — the active WireFormat's word
    width (ops/delivery.WIRE_FORMATS): 2 for wire16 (``compact_carry``
    or ``int16_wire``, halving every key exchange's ICI bytes — the
    sharded full-view capacity layout is also the cheaper one to scale
    out), 4 for the wide and wire24 rungs (wire24 spends the int32
    word's idle bits on incarnation headroom instead of narrower
    lanes).
    """
    return params.wire_format.word_bytes


def shift_exchanges_per_round(params, gate_contacts: bool = False):
    """Sharded block exchanges (ShiftEngine.deliver calls) per tick.

    Returns a dict of exchange-name -> row_bytes; the exchange count is
    its length.  Pinned to models/swim._tick_shift by tests/test_traffic.py
    (trace-time call counts AND the compiled HLO's collective operands).

    The SYNC anti-entropy plane (``params.sync_interval > 0``) adds two
    payload channels — the ``±s`` paired full-table exchange
    (models/sync.py) — that execute every round with their delivery
    masked off non-exchange rounds (the same no-``cond`` discipline as
    the FD probe), so the per-round exchange count and wire bytes grow
    by exactly two (keys + txmask, plus the status gate when contacts
    are seed-gated).
    """
    k = params.n_subjects
    kb = _key_bytes(params)
    ae = 2 if params.sync_interval > 0 else 0
    channels = params.fanout + 2 + ae   # gossip + SYNC + refute (+ plane)
    exchanges = {}
    for c in range(channels):
        exchanges[f"keys[{c}]"] = k * kb
        exchanges[f"txmask[{c}]"] = k * INT8
    for c in range(params.fanout):          # gossip message counting
        exchanges[f"hot_any[{c}]"] = INT8
    exchanges["refuting_senders@fd"] = INT8      # h_pushers at fd_shift
    exchanges["refuting_senders@sync"] = INT8    # h_pushers at sync_shift
    if gate_contacts:
        for c in range(channels - 1):       # refute push skips the gate
            exchanges[f"status_gate[{c}]"] = k * INT8
    return exchanges


def shift_ici_bytes_per_device_round(params, n_devices: int,
                                     gate_contacts: bool = False) -> int:
    """Bytes each device sends over ICI per round, shift mode.

    2 block rotations of [n_local, ...] per exchange (ShiftEngine
    docstring; rotation distance 0 still counted — upper bound).
    """
    n_local = params.n_members // n_devices
    per_row = sum(shift_exchanges_per_round(params, gate_contacts).values())
    return 2 * n_local * per_row


def scatter_collectives_per_round(params) -> int:
    """Full-height pmax combines per tick, scatter mode.

    FUSED wire (default): ONE combined key buffer per delay bin — the
    ALIVE flags ride the key bits (models/swim._scatter_channel_bufs).
    Legacy two-buffer wire (``fused_wire=False``): the key buffer plus
    the int8 ALIVE-flag buffer per bin."""
    bins = params.max_delay_rounds + 1 if params.max_delay_rounds > 0 else 1
    return (1 if params.fused_wire else 2) * bins


def scatter_wire_bytes_per_slot(params) -> int:
    """Wire bytes ONE (receiver, subject) inbox slot costs per round in
    the scatter combine: the packed-key word, plus the int8 ALIVE flag
    on the legacy two-buffer wire — the 4-vs-5 B/slot headline of the
    fused wire (wide rung; 2 vs 3 on wire16, 4 on wire24 whose word
    already carries the widened key)."""
    return _key_bytes(params) + (0 if params.fused_wire else INT8)


def pipelined_scatter_hlo_collectives(params) -> int:
    """Full-height combine instructions in the compiled PIPELINED
    scatter program: the per-round combines ride the scan body
    (combining the PREVIOUS round's carried contribution — ONE
    instruction under the fused wire, the key + flag pair on the
    legacy two-buffer wire) and the final round's combines run in the
    loop epilogue — so the instruction count doubles while per-round
    collectives (``scatter_collectives_per_round``) and per-round ICI
    bytes are unchanged.  Pipelining moves the combine, it does not
    add traffic."""
    return 2 * scatter_collectives_per_round(params)


def scatter_ici_bytes_per_device_round(params, n_devices: int) -> int:
    """Bytes each device sends over ICI per round, scatter mode: ring
    all-reduce cost 2*(D-1)/D * buffer over the [N, K] combined key
    buffer (plus the int8 flag buffer on the legacy two-buffer wire —
    ``scatter_wire_bytes_per_slot``).

    The anti-entropy plane adds NO scatter-mode ICI traffic: its two
    exchange channels scatter into the SAME full-height contribution
    buffers the regular channels pmax (models/swim._scatter_channel_bufs),
    so collective count and operand bytes are unchanged — pinned by
    tests/test_traffic.py's sync-plane HLO test.
    """
    n, k = params.n_members, params.n_subjects
    bins = params.max_delay_rounds + 1 if params.max_delay_rounds > 0 else 1
    buffer_bytes = n * k * scatter_wire_bytes_per_slot(params) * bins
    return int(2 * (n_devices - 1) / n_devices * buffer_bytes)


# --------------------------------------------------------------------------
# SYNC anti-entropy plane: full-table bytes per interval vs piggyback
# --------------------------------------------------------------------------


def sync_exchange_bytes_per_member(params) -> int:
    """Wire bytes ONE member sends per anti-entropy exchange round
    (``sync_interval`` cadence, models/sync.py): its full syncable
    table row — K packed record keys — to each of the two paired
    partners.  The per-interval cost of the repair plane; amortized
    per round it is this / sync_interval."""
    return 2 * params.n_subjects * _key_bytes(params)


def piggyback_bytes_per_member_round(params) -> int:
    """Upper-bound wire bytes one member's piggyback gossip moves per
    round: ``fanout`` targets x the K-record payload (hot-masked in
    practice, so the real figure is occupancy x this — the
    ``gossip_piggyback_occupancy`` gauge).  The comparison figure for
    the anti-entropy plane's amortized cost: with the default
    ``sync_interval`` orders of magnitude above 1, the repair plane's
    per-round bytes are a small fraction of the piggyback budget
    (``bench.py --sync`` reports both)."""
    return params.fanout * params.n_subjects * _key_bytes(params)
